"""Benchmark: flagship train-step throughput on the attached TPU chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "steps/sec/chip", "vs_baseline": N}

Baseline note (BASELINE.md): the reference publishes no numbers; the
driver's north star is >=3x the fork's 8xA100 NCCL steps/sec, chip-
normalized, on the QT-Opt grasping Q-fn — a number that must be
self-measured and is unmeasurable here (no A100s, no network). Until a
driver-measured GPU figure exists, vs_baseline is computed against the
documented estimate below.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

# Estimated per-chip steps/sec of the fork's TF1 + tf.distribute(NCCL)
# 8xA100 baseline on the QT-Opt Q-function (472x472 conv tower, batch
# 32/GPU): conv-heavy TF1 graphs on A100 typically sustain ~10-20
# steps/sec/GPU at this size; we take the optimistic end as the bar.
BASELINE_STEPS_PER_SEC_PER_CHIP = 20.0
WARMUP_LOOPS = 2
MEASURE_LOOPS = 3
# Steps fused per dispatch via Trainer.train_steps (lax.scan) — the same
# in-device loop TPUEstimator ran under TPUConfig(iterations_per_loop),
# and how train_eval_model(iterations_per_loop=K) trains for real.
# Throughput plateaus around K=60 on the v5e chip (measured 175 → 200 →
# 220 steps/s at K=1/20/60); the K-deep stacked batch (~5 GB at batch
# 32 float32) fits comfortably in 16 GB HBM.
# Roofline (measured 2026-07-30 via compiled.cost_analysis): 95 GF and
# 4.03 GB of HBM traffic per step → at ~4.8 ms/step the chip moves
# ~840 GB/s, saturating v5e HBM bandwidth (~819 GB/s spec) at ~10% MXU.
# The big 472×472 conv tower is bandwidth-bound (BN train-mode stats
# force extra activation passes XLA can't fuse away), so steps/sec here
# is at the hardware ceiling for this architecture; further gains would
# require semantic changes (smaller activations, norm-free tower).
ITERATIONS_PER_LOOP = 60


def main() -> None:
  from __graft_entry__ import _example_batch, _flagship_model
  from tensor2robot_tpu import modes
  from tensor2robot_tpu.parallel import mesh as mesh_lib
  from tensor2robot_tpu.specs import tensorspec_utils as ts
  from tensor2robot_tpu.train.trainer import Trainer

  model, _ = _flagship_model()
  try:
    batch_size = model.benchmark_batch_size  # flagship models override
  except AttributeError:
    batch_size = 32
  n_chips = jax.device_count()
  mesh = mesh_lib.create_mesh()
  trainer = Trainer(model, mesh=mesh, seed=0)
  state = trainer.create_train_state(batch_size=batch_size)

  features = _example_batch(model, batch_size, modes.TRAIN)
  label_spec = model.get_label_specification(modes.TRAIN)
  labels = jax.tree_util.tree_map(
      lambda s: jnp.zeros((batch_size,) + s.shape, s.dtype),
      ts.flatten_spec_structure(label_spec),
      is_leaf=lambda x: isinstance(x, ts.ExtendedTensorSpec))
  if not list(labels.keys()):
    labels = None
  features, labels = trainer.shard_batch((features, labels))

  k = ITERATIONS_PER_LOOP
  stacked_sharding = mesh_lib.stacked_batch_sharding(mesh, "data")

  def stack(tree):
    if tree is None:
      return None
    return jax.device_put(
        jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (k,) + x.shape), tree),
        stacked_sharding)

  stacked_features, stacked_labels = stack(features), stack(labels)

  for _ in range(WARMUP_LOOPS):
    state, metrics = trainer.train_steps(
        state, stacked_features, stacked_labels)
  float(metrics["loss"])  # host readback: block_until_ready is not a
  # reliable sync through remote-tunnel backends, an actual value is.

  start = time.perf_counter()
  for _ in range(MEASURE_LOOPS):
    state, metrics = trainer.train_steps(
        state, stacked_features, stacked_labels)
  float(metrics["loss"])  # forces the whole measured chain
  elapsed = time.perf_counter() - start

  steps_per_sec_per_chip = MEASURE_LOOPS * k / elapsed / n_chips
  print(json.dumps({
      "metric": f"{type(model).__name__} train steps/sec/chip "
                f"(batch {batch_size})",
      "value": round(steps_per_sec_per_chip, 3),
      "unit": "steps/sec/chip",
      "vs_baseline": round(
          steps_per_sec_per_chip / BASELINE_STEPS_PER_SEC_PER_CHIP, 3),
  }))


if __name__ == "__main__":
  main()

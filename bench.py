"""Benchmark: flagship train throughput + roofline + input pipeline.

Driver contract (VERDICT r2 Weak #2: the contract keys must survive a
tail-capture that truncates from the FRONT): stdout carries ONE COMPACT
JSON line (< ~1 KB) with metric / value / unit / vs_baseline plus a few
scalars; the full evidence trail (roofline, baseline derivation,
microbenchmarks, variants, input-pipeline study) is written to the
committed side file named by the "detail" key (BENCH_DETAIL_r03.json).

Headline operating point (stated, per VERDICT r2 #3): QT-Opt grasping
Q-function, per-chip batch 128, uint8 wire format (model option
`uint8_images=True` — identical conv math, 4× less batch wire traffic),
60 scanned steps per dispatch. The metric is per-IMAGE throughput so
operating points with different batch sizes compare against the same
derived A100 bar: the bar is a compute roofline × efficiency, which is
batch-independent per image. The reference-parity batch-32 float32 line
(comparable with BENCH_r01/r02) is also measured and emitted.

Methodology notes (full numbers in the detail artifact):
  - Per-call dispatch overhead through this container's remote-tunnel
    TPU is ~50-100 ms (measured; real TPU hosts: sub-ms). Naive
    timings INCLUDE it (the honest measured number on this box);
    steady-state per-step marginals (two scan lengths, differenced)
    are emitted alongside with the methodology named.
  - XLA cost_analysis on a scan-of-K executable reports the body once,
    so flops ARE per-step; bytes-accessed is inflated by stacked-batch
    slice accounting and is never used for bandwidth claims.
  - An isolated-conv microbench (same delta method) anchors the MFU
    ceiling story: the 64-channel tower convs reach 36-90% MFU in
    isolation, the 3-input-channel parity stem ~3% — the gap between
    end-to-end MFU and peak is the workload's lane structure, not
    scheduling loss.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

DETAIL_FILE = "BENCH_DETAIL_r03.json"

WARMUP_LOOPS = 2
MEASURE_LOOPS = 3
# Steps fused per dispatch via Trainer.train_steps (lax.scan) — the same
# in-device loop TPUEstimator ran under TPUConfig(iterations_per_loop).
ITERATIONS_PER_LOOP = 60

# Chip peaks for mfu, keyed by substrings of device_kind.
# v5e ("TPU v5 lite"): 197 TFLOP/s bf16 (public spec).
_CHIP_PEAKS = {
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6": 918e12,
}

# --- the derived A100 baseline -------------------------------------------
# BASELINE.json's north star: beat the fork's 8xA100 tf.distribute+NCCL
# throughput per chip by >=3x. That fork number is unmeasurable here (no
# A100s, no network), so the bar is DERIVED from the measured parity
# FLOPs/image (XLA cost analysis, cross-checked analytically), favorably
# to the A100 — full rationale in the detail artifact's
# baseline.assumptions. The fork would run the PARITY model (float32,
# batch at its choosing), so the bar is per-image and batch-independent:
#   a100_img_per_sec(tier) = A100_FP32_FLOPS * tier / flops_per_image
# vs_baseline uses the CONSERVATIVE fork_estimate tier (0.5 = isolated
# cuDNN fp32 convs at <=50% of peak with zero other overhead).
A100_FP32_FLOPS = 19.5e12
FORK_FP32_CONV_EFFICIENCY = 0.5
FORK_TYPICAL_E2E_EFFICIENCY = 0.25
# Analytic parity-model FLOPs (batch 32): used ONLY if cost_analysis
# fails (ADVICE r2: never emit vs_baseline null — fall back loudly).
ANALYTIC_PARITY_FLOPS_B32 = 96.4e9

_BASELINE_ASSUMPTIONS = (
    "fp32 TF1 fork (no mixed-precision hooks in the reference API; "
    "TF32 would lift the raw ceiling ~8x but those convs are then "
    "bandwidth/launch-bound at 64-channel shapes); A100 19.5 fp32 "
    "TFLOP/s; isolated cuDNN fp32 convs <= ~50% of peak "
    "(fork_estimate tier); end-to-end TF1 training historically 25-35% "
    "of the isolated-conv roofline (fork_typical tier). The bar is "
    "per-image: flops_per_image from the measured PARITY model (the "
    "architecture the fork would run); uint8 wire changes transport, "
    "not conv math. HBM-side bound intentionally not derived (XLA "
    "bytes-accessed inflated by stacked-batch slice accounting; "
    "omitting it only favors the A100).")


def _chip_peak(device_kind: str):
  kind = device_kind.lower()
  for key, peak in _CHIP_PEAKS.items():
    if key in kind:
      return peak
  return None


def _cost_analysis_flops(compiled):
  """Per-step flops from the K-step executable (body counted once —
  see module docstring); 0.0 on failure."""
  try:
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
      ca = ca[0]
    return float(ca.get("flops", 0.0))
  except Exception:
    return 0.0


def _zeros_batch(model, batch_size, mode):
  from __graft_entry__ import _example_batch
  from tensor2robot_tpu.specs import tensorspec_utils as ts

  features = _example_batch(model, batch_size, mode)
  label_spec = model.get_label_specification(mode)
  labels = jax.tree_util.tree_map(
      lambda s: jnp.zeros((batch_size,) + s.shape, s.dtype),
      ts.flatten_spec_structure(label_spec),
      is_leaf=lambda x: isinstance(x, ts.ExtendedTensorSpec))
  if not list(labels.keys()):
    labels = None
  return features, labels


class _TrainBench:
  """One compiled K-scanned train-step executable + its measurements."""

  def __init__(self, model, batch_size: int, k: int):
    from tensor2robot_tpu import modes
    from tensor2robot_tpu.parallel import mesh as mesh_lib
    from tensor2robot_tpu.train.trainer import Trainer

    self.batch_size, self.k = batch_size, k
    mesh = mesh_lib.create_mesh()
    self._trainer = Trainer(model, mesh=mesh, seed=0)
    self._state = self._trainer.create_train_state(batch_size=batch_size)
    features, labels = _zeros_batch(model, batch_size, modes.TRAIN)
    features, labels = self._trainer.shard_batch((features, labels))
    sharding = mesh_lib.stacked_batch_sharding(mesh, "data")

    def stack(tree):
      if tree is None:
        return None
      return jax.device_put(
          jax.tree_util.tree_map(
              lambda x: jnp.broadcast_to(x[None], (k,) + x.shape), tree),
          sharding)

    self._batch = (stack(features), stack(labels))
    self._compiled = self._trainer.aot_train_steps(self._state, *self._batch)
    self.flops_per_step = _cost_analysis_flops(self._compiled)

  def measure(self, warmup: int, measure: int):
    """Naive steps/sec/chip (includes per-call dispatch overhead)."""
    n_chips = jax.device_count()
    state, metrics = self._state, None
    for _ in range(warmup):
      state, metrics = self._compiled(state, *self._batch)
    if metrics is not None:
      float(metrics["loss"])  # host readback: the only reliable sync
    start = time.perf_counter()
    for _ in range(measure):
      state, metrics = self._compiled(state, *self._batch)
    float(metrics["loss"])
    elapsed = time.perf_counter() - start
    self._state = state
    return round(measure * self.k / elapsed / n_chips, 3)


def _measure_config(model, batch_size, k, warmup=WARMUP_LOOPS,
                    measure=MEASURE_LOOPS):
  bench = _TrainBench(model, batch_size, k)
  sps = bench.measure(warmup, measure)
  return sps, bench.flops_per_step, bench


def _steady_state(model, batch_size, k_small, k_big, calls=2,
                  big_bench=None):
  """(ms_per_step_marginal, per_call_overhead_ms) via two scan lengths.

  The difference between a k_big call and a k_small call contains no
  dispatch overhead — it is (k_big - k_small) pure steps. `big_bench`
  reuses an already-compiled k_big executable (an AOT compile costs
  tens of seconds on this box)."""
  per_call = {}
  for k in (k_small, k_big):
    if k == k_big and big_bench is not None:
      bench = big_bench
    else:
      bench = _TrainBench(model, batch_size, k)
    bench.measure(1, 1)  # warm
    best = None
    for _ in range(calls):
      start = time.perf_counter()
      bench.measure(0, 1)
      el = time.perf_counter() - start
      best = el if best is None else min(best, el)
    per_call[k] = best
  marginal = (per_call[k_big] - per_call[k_small]) / (k_big - k_small)
  overhead = per_call[k_small] - k_small * marginal
  return marginal * 1e3, max(overhead, 0.0) * 1e3


def _microbench_convs():
  """Isolated conv achieved-TFLOP/s at the flagship's shapes (delta
  method between two scan lengths — immune to dispatch overhead).
  Anchors the 'where the MFU goes' story (VERDICT r2 #3b)."""
  from jax import lax

  peak = _chip_peak(jax.devices()[0].device_kind) or 0
  key = jax.random.key(0)

  def marginal_us(make_fn, x, l1=30, l2=150, calls=3):
    times = {}
    for length in (l1, l2):
      fn = make_fn(length)
      out = fn(x)
      jax.block_until_ready(out)
      best = None
      for _ in range(calls):
        start = time.perf_counter()
        jax.block_until_ready(fn(x))
        el = time.perf_counter() - start
        best = el if best is None else min(best, el)
      times[length] = best
    return (times[l2] - times[l1]) / (l2 - l1) * 1e6

  def conv_chain(b, hw, c):
    w = jax.random.normal(key, (3, 3, c, c), jnp.bfloat16) * 0.04
    x = jax.random.normal(key, (b, hw, hw, c), jnp.bfloat16)

    def make(length):
      def step(y, _):
        return lax.conv_general_dilated(
            y, w, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC")), None
      return jax.jit(lambda x: lax.scan(step, x, None, length=length)[0])
    flops = 2 * b * hw * hw * 9 * c * c
    return make, x, flops

  def stem_chain(b):
    w = jax.random.normal(key, (6, 6, 3, 64), jnp.bfloat16) * 0.04
    x = jax.random.normal(key, (b, 472, 472, 3), jnp.bfloat16)

    def make(length):
      def step(y, _):
        out = lax.conv_general_dilated(
            y, w, (4, 4), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y * (1 + 1e-4 * jnp.mean(out).astype(y.dtype)), None
      return jax.jit(lambda x: lax.scan(step, x, None, length=length)[0])
    flops = 2 * b * 118 * 118 * 36 * 3 * 64
    return make, x, flops

  table = {}
  for name, (make, x, flops) in {
      "tower_3x3_64ch_59sq_b32": conv_chain(32, 59, 64),
      "tower_3x3_64ch_59sq_b128": conv_chain(128, 59, 64),
      "tower_3x3_128ch_59sq_b32": conv_chain(32, 59, 128),
      "parity_stem_6x6s4_472sq_b32": stem_chain(32),
  }.items():
    us = marginal_us(make, x)
    entry = {"us_per_op": round(us), "achieved_tflops": round(
        flops / (us * 1e-6) / 1e12, 1)}
    if peak:
      entry["mfu"] = round(flops / (us * 1e-6) / peak, 3)
    table[name] = entry
  table["note"] = (
      "delta method (two scan lengths) — per-op marginal cost, no "
      "dispatch overhead. Read the measured MFU from the fields above "
      "(they are re-measured every run and vary run-to-run on the "
      "shared tunnel chip); the stable pattern is that the 64-channel "
      "tower convs sit far above the 3-input-channel parity stem, and "
      "128 input channels approach the MXU roofline — the end-to-end "
      "MFU ceiling is the parity architecture's lane structure (Cin=3 "
      "stem, Cout=64 tower), not scheduling loss.")
  return table


def _make_jpeg_dataset(path: str, num_records: int, image_size: int) -> None:
  """tf.Examples with real JPEG camera-like images (gradients + random
  blocks: realistic compressibility), float32 actions, scalar targets."""
  from tensor2robot_tpu.data.example_proto import encode_example
  from tensor2robot_tpu.data.tfrecord import TFRecordWriter
  from tensor2robot_tpu.utils.image import encode_jpeg

  rng = np.random.default_rng(0)
  yy, xx = np.mgrid[0:image_size, 0:image_size]
  base = ((xx + yy) * (255.0 / (2 * image_size))).astype(np.uint8)
  with TFRecordWriter(path) as writer:
    for i in range(num_records):
      img = np.stack([np.roll(base, 31 * i, axis=1)] * 3, axis=-1).copy()
      for _ in range(8):
        y, x = rng.integers(0, image_size - 32, size=2)
        img[y:y + 32, x:x + 32] = rng.integers(0, 255, (32, 32, 3))
      writer.write(encode_example({
          "image": [encode_jpeg(img, quality=85)],
          "action": rng.standard_normal(4).astype(np.float32),
          "target_q": np.asarray([rng.random()], np.float32),
      }))


def _make_raw_uint8_dataset(path: str, num_records: int,
                            image_size: int) -> None:
  """tf.Examples with RAW uint8 image bytes (no JPEG): the
  `wire_format="raw"` + `uint8_images=True` pipeline — zero decode."""
  from tensor2robot_tpu.data.example_proto import encode_example
  from tensor2robot_tpu.data.tfrecord import TFRecordWriter

  rng = np.random.default_rng(0)
  with TFRecordWriter(path) as writer:
    for _ in range(num_records):
      img = rng.integers(0, 255, (image_size, image_size, 3), np.uint8)
      writer.write(encode_example({
          "image": [img.tobytes()],
          "action": rng.standard_normal(4).astype(np.float32),
          "target_q": np.asarray([rng.random()], np.float32),
      }))


def _record_fed_steps_per_sec(model, path, batch_size, n_steps=14):
  """Record-fed single-step training (the real train_eval feed: reader
  threads → parse → preprocess → double-buffered device prefetch).

  Returns (cold_rate, steady_rate, state, trainer): cold = n_steps /
  total from a cold pipeline (fill cost included — this number scales
  with n_steps on a fill-dominated box, so it is NOT comparable across
  protocol changes); steady = 1 / mean(per-step time over the last
  third), after the prefetch buffers have drained to the pipeline's
  true sustained rate (protocol-stable — use this for ratios)."""
  from tensor2robot_tpu import modes
  from tensor2robot_tpu.data.default_input_generator import (
      DefaultRecordInputGenerator)
  from tensor2robot_tpu.data.prefetch import prefetch_to_device
  from tensor2robot_tpu.parallel import mesh as mesh_lib
  from tensor2robot_tpu.train.trainer import Trainer

  mesh = mesh_lib.create_mesh()
  trainer = Trainer(model, mesh=mesh, seed=0)
  state = trainer.create_train_state(batch_size=batch_size)
  gen = DefaultRecordInputGenerator(
      file_patterns=path, batch_size=batch_size, seed=0,
      num_pipeline_threads=max(1, os.cpu_count() or 1))
  gen.set_specification_from_model(model, modes.TRAIN)

  def fresh_batches():
    return prefetch_to_device(
        gen.create_dataset_fn(modes.TRAIN)(),
        sharding=trainer.batch_sharding)

  batches = fresh_batches()
  features, labels = next(batches)
  state, metrics = trainer.train_step(state, features, labels)  # compile
  float(metrics["loss"])
  # Fresh pipeline for the measurement: the tens-of-seconds compile let
  # every buffer fill; draining them would measure train-step speed,
  # not sustained throughput. Cold start is the honest side.
  batches.close()
  batches = fresh_batches()
  step_times = []
  start = time.perf_counter()
  for _ in range(n_steps):
    t0 = time.perf_counter()
    features, labels = next(batches)
    state, metrics = trainer.train_step(state, features, labels)
    float(metrics["loss"])  # sync per step so step_times are real
    step_times.append(time.perf_counter() - t0)
  elapsed = time.perf_counter() - start
  batches.close()
  tail = step_times[-max(n_steps // 3, 3):]
  steady = 1.0 / (sum(tail) / len(tail))
  return n_steps / elapsed, steady, state, trainer


def _bench_input_pipeline(batch_size: int, synthetic_headline_sps: float):
  """records/sec (native on/off), record-fed training for the JPEG and
  the raw-uint8 wire (VERDICT r2 #5), H2D bandwidth, and the per-core
  decode context. This host has os.cpu_count() core(s); JPEG decode and
  parse scale ~linearly with host cores."""
  import tempfile

  from tensor2robot_tpu import modes
  from tensor2robot_tpu.data.default_input_generator import (
      DefaultRecordInputGenerator)
  from tensor2robot_tpu.research.qtopt.t2r_models import QTOptGraspingModel

  num_records = 384
  model = QTOptGraspingModel()
  image_size = model._in_image_size
  out = {"host_cpu_cores": os.cpu_count(), "record_batch_size": batch_size}

  with tempfile.TemporaryDirectory() as tmp:
    jpeg_path = os.path.join(tmp, "bench.tfrecord")
    _make_jpeg_dataset(jpeg_path, num_records, image_size)
    out["jpeg_bytes_per_record"] = round(
        os.path.getsize(jpeg_path) / num_records)

    def records_per_sec(disable_native: bool) -> float:
      from tensor2robot_tpu.data import native
      env_key = "T2R_DISABLE_NATIVE"
      prev = os.environ.get(env_key)
      os.environ[env_key] = "1" if disable_native else "0"
      native.reset_cache()
      try:
        gen = DefaultRecordInputGenerator(
            file_patterns=jpeg_path, batch_size=batch_size, seed=0,
            num_pipeline_threads=max(1, os.cpu_count() or 1))
        gen.set_specification_from_model(model, modes.TRAIN)
        it = gen.create_dataset_fn(modes.TRAIN)()
        next(it)  # warm: thread spin-up + first parse
        n_batches = 10
        start = time.perf_counter()
        for _ in range(n_batches):
          next(it)
        elapsed = time.perf_counter() - start
        it.close()
        return n_batches * batch_size / elapsed
      finally:
        if prev is None:
          os.environ.pop(env_key, None)
        else:
          os.environ[env_key] = prev
        native.reset_cache()

    native_rps = records_per_sec(disable_native=False)
    python_rps = records_per_sec(disable_native=True)
    out["jpeg_records_per_sec_native"] = round(native_rps, 1)
    out["jpeg_records_per_sec_python"] = round(python_rps, 1)
    out["native_speedup"] = round(native_rps / max(python_rps, 1e-9), 2)
    out["native_note"] = (
        "native = C++ TFRecord framing + CRC32C + whole-batch parse + "
        "libjpeg decode; python = pure-Python CRC + per-record parse + "
        "PIL. Decode-only, the native path measures ~2x PIL "
        "(1827 vs 879 472^2-decodes/sec, 2026-07-31); the rest of the "
        "gap is CRC and parse.")

    # Sustained record-fed training, JPEG/float32 wire (native pinned
    # on — an inherited T2R_DISABLE_NATIVE=1 would silently measure the
    # Python path while the JSON attributes it to native).
    from tensor2robot_tpu.data import native as native_mod
    prev_disable = os.environ.get("T2R_DISABLE_NATIVE")
    os.environ["T2R_DISABLE_NATIVE"] = "0"
    native_mod.reset_cache()
    record_fed, record_fed_steady, state, trainer = (
        _record_fed_steps_per_sec(model, jpeg_path, batch_size))
    out["record_fed_jpeg_cold_steps_per_sec"] = round(record_fed, 2)
    out["record_fed_jpeg_steady_steps_per_sec"] = round(
        record_fed_steady, 2)

    # Raw-uint8 wire (VERDICT r2 #5): no JPEG decode, 4x less H2D than
    # float32 — the two mitigations visible despite this container's
    # 1-core host and tunnel H2D.
    raw_path = os.path.join(tmp, "bench_raw.tfrecord")
    _make_raw_uint8_dataset(raw_path, num_records, image_size)
    raw_model = QTOptGraspingModel(uint8_images=True, wire_format="raw")
    record_fed_raw, record_fed_raw_steady, _, _ = (
        _record_fed_steps_per_sec(raw_model, raw_path, batch_size))
    out["record_fed_uint8_steps_per_sec"] = round(record_fed_raw, 2)
    out["record_fed_uint8_steady_steps_per_sec"] = round(
        record_fed_raw_steady, 2)
    # Ratio on the STEADY figures: the cold rates are dominated by the
    # one-time pipeline fill and scale with the protocol's n_steps
    # (review r3) — only the sustained rates compare wire formats.
    out["uint8_vs_jpeg_record_fed_steady"] = round(
        record_fed_raw_steady / max(record_fed_steady, 1e-9), 2)

    # Synthetic-fed at the SAME single-step dispatch (the K-scanned
    # headline amortizes dispatch; the record-fed loop cannot).
    sfeat, slab = _zeros_batch(model, batch_size, modes.TRAIN)
    sfeat, slab = trainer.shard_batch((sfeat, slab))
    state, metrics = trainer.train_step(state, sfeat, slab)
    float(metrics["loss"])
    n_steps = 10
    start = time.perf_counter()
    for _ in range(n_steps):
      state, metrics = trainer.train_step(state, sfeat, slab)
    float(metrics["loss"])
    elapsed = time.perf_counter() - start
    synthetic_k1 = n_steps / elapsed
    out["synthetic_steps_per_sec_k1"] = round(synthetic_k1, 2)
    out["record_fed_uint8_fraction_of_k1"] = round(
        record_fed_raw / synthetic_k1, 3)

    if prev_disable is None:
      os.environ.pop("T2R_DISABLE_NATIVE", None)
    else:
      os.environ["T2R_DISABLE_NATIVE"] = prev_disable
    native_mod.reset_cache()

    # H2D bandwidth of one float32 feature batch (remote-tunnel path).
    one_batch = np.zeros((batch_size, image_size, image_size, 3),
                         np.float32)
    jax.block_until_ready(jax.device_put(one_batch))  # warm path
    start = time.perf_counter()
    jax.block_until_ready(jax.device_put(one_batch))
    h2d = one_batch.nbytes / (time.perf_counter() - start)
    out["h2d_gbps"] = round(h2d / 1e9, 3)
    out["note"] = (
        "record-fed throughput on this box is bounded by container "
        "artifacts, not pipeline design: a 1-core host (decode+parse "
        "scale ~linearly with cores; feeding "
        f"~{round(synthetic_headline_sps)} img/sec needs "
        f"~{round(synthetic_headline_sps / max(native_rps, 1))} cores "
        "at the measured per-core JPEG rate — real TPU hosts have "
        f"~100+) and a {h2d / 1e9:.2f} GB/s tunnel H2D (real hosts: "
        "tens of GB/s). The raw-uint8 wire removes decode entirely and "
        "cuts wire bytes 4x vs float32 — its measured multiple over "
        "the JPEG/float path above is the design margin this box can "
        "demonstrate.")
  return out


def main() -> None:
  from tensor2robot_tpu.research.qtopt.t2r_models import QTOptGraspingModel

  parity_batch = QTOptGraspingModel.benchmark_batch_size  # 32
  k = ITERATIONS_PER_LOOP
  device_kind = jax.devices()[0].device_kind
  peak = _chip_peak(device_kind)

  # --- reference-parity line (comparable with BENCH_r01/r02) ----------
  parity_sps, parity_flops, parity_bench = _measure_config(
      QTOptGraspingModel(), parity_batch, k)
  flops_source = "xla_cost_analysis"
  if not parity_flops:
    # ADVICE r2: a cost-analysis failure must not null the contract
    # keys — fall back to the documented analytic count (a batch-32
    # figure, scaled: conv FLOPs are linear in batch), loudly.
    parity_flops = ANALYTIC_PARITY_FLOPS_B32 * parity_batch / 32
    flops_source = "analytic_fallback(cost_analysis failed)"
  flops_per_image = parity_flops / parity_batch

  # --- steady state (dispatch overhead removed, methodology named) ----
  # Runs immediately after the parity measurement so the k=60
  # executable is reused, then ALL parity device buffers are dropped
  # before the batch-128 allocations (the 16 GB HBM cannot hold both
  # stacked batches at once).
  parity_marginal_ms, overhead_ms = _steady_state(
      QTOptGraspingModel(), parity_batch, 20, k, big_bench=parity_bench)
  del parity_bench

  # --- headline operating point (stated): batch 128, uint8 wire ------
  headline_batch = 128
  headline_model = QTOptGraspingModel(uint8_images=True)
  headline_sps, headline_flops, _ = _measure_config(
      headline_model, headline_batch, k)
  headline_img_s = headline_sps * headline_batch

  # --- derived per-image A100 bar -------------------------------------
  ideal_img_s = A100_FP32_FLOPS / flops_per_image
  fork_estimate_img_s = ideal_img_s * FORK_FP32_CONV_EFFICIENCY
  fork_typical_img_s = ideal_img_s * FORK_TYPICAL_E2E_EFFICIENCY
  vs_baseline = round(headline_img_s / fork_estimate_img_s, 2)

  # --- variants --------------------------------------------------------
  variants = {}
  v_f32_128, _, _ = _measure_config(QTOptGraspingModel(), 128, 15,
                                    warmup=1, measure=2)
  variants["float32_wire_b128_k15"] = {
      "steps_per_sec_per_chip": v_f32_128,
      "images_per_sec_per_chip": round(v_f32_128 * 128),
      "note": "float32 wire caps k at 15 (stacked batch is 4x larger); "
              "the uint8 headline's margin over this line is wire "
              "traffic + dispatch amortization, same conv math"}
  v_s2d, _, _ = _measure_config(
      QTOptGraspingModel(uint8_images=True, stem="space_to_depth"),
      headline_batch, k, warmup=1, measure=2)
  variants["s2d_folded_stem_b128_uint8"] = {
      "steps_per_sec_per_chip": v_s2d,
      "images_per_sec_per_chip": round(v_s2d * headline_batch),
      "note": "folded space-to-depth stem (ops/stem_conv.py): isolated "
              "stem fwd+grad_w 1269us vs 1701us parity, but e2e-neutral "
              "at this operating point — recorded honestly"}

  microbench = _microbench_convs()

  input_pipeline = _bench_input_pipeline(parity_batch, headline_img_s)

  mfu = None
  if peak and headline_flops:
    # headline flops from its own executable (uint8 variant's math).
    mfu = round(headline_flops * headline_sps / peak, 4)
  parity_mfu = None
  if peak and parity_flops:
    parity_mfu = round(parity_flops * parity_sps / peak, 4)
    parity_steady_mfu = round(
        parity_flops / (parity_marginal_ms * 1e-3) / peak, 4)
  else:
    parity_steady_mfu = None

  detail = {
      "round": 3,
      "device_kind": device_kind,
      "iterations_per_loop": k,
      "headline": {
          "operating_point": f"batch {headline_batch}, uint8 wire, "
                             f"k={k}, parity architecture (BatchNorm, "
                             "6x6 conv stem)",
          "images_per_sec_per_chip": round(headline_img_s),
          "steps_per_sec_per_chip": headline_sps,
          "mfu": mfu,
          "flops_per_step": round(headline_flops),
      },
      "parity_b32": {
          "steps_per_sec_per_chip": parity_sps,
          "images_per_sec_per_chip": round(parity_sps * parity_batch),
          "mfu_naive": parity_mfu,
          "steady_state_ms_per_step": round(parity_marginal_ms, 2),
          "steady_state_steps_per_sec": round(1e3 / parity_marginal_ms, 1),
          "mfu_steady": parity_steady_mfu,
          "per_call_dispatch_overhead_ms": round(overhead_ms, 1),
          "flops_per_step": round(parity_flops),
          "flops_source": flops_source,
          "vs_baseline_steps_basis": round(
              parity_sps / (fork_estimate_img_s / parity_batch), 2),
      },
      "baseline": {
          "kind": "derived-a100-fp32-compute-roofline, per-image",
          "flops_per_image": round(flops_per_image),
          "a100_ideal_bound_img_per_sec": round(ideal_img_s),
          "a100_fork_estimate_img_per_sec": round(fork_estimate_img_s),
          "a100_fork_typical_img_per_sec": round(fork_typical_img_s),
          "assumptions": _BASELINE_ASSUMPTIONS,
      },
      "vs_a100_ideal_bound": round(headline_img_s / ideal_img_s, 2),
      "vs_fork_typical": round(headline_img_s / fork_typical_img_s, 2),
      "conv_microbench": microbench,
      "variants": variants,
      "input_pipeline": input_pipeline,
  }
  with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         DETAIL_FILE), "w") as f:
    json.dump(detail, f, indent=2)

  print(json.dumps({
      "metric": "QTOptGraspingModel train images/sec/chip "
                f"(batch {headline_batch}, uint8 wire, k={k})",
      "value": round(headline_img_s),
      "unit": "images/sec/chip",
      "vs_baseline": vs_baseline,
      "vs_baseline_tier": "a100_fork_estimate (conservative x0.5)",
      "parity_b32_steps_per_sec": parity_sps,
      "mfu": mfu,
      "flops_per_image": round(flops_per_image),
      "record_fed_uint8_steps_per_sec": input_pipeline.get(
          "record_fed_uint8_steps_per_sec"),
      "device_kind": device_kind,
      "detail": DETAIL_FILE,
  }))


if __name__ == "__main__":
  main()

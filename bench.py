"""Benchmark: flagship train-step throughput + roofline + input pipeline.

Prints ONE JSON line. Driver contract keys: metric / value / unit /
vs_baseline. Everything else is the evidence trail:

  - roofline: flops_per_step, hbm_bytes_per_step, achieved_gbps, mfu,
    mbu — measured via the compiled executable's cost_analysis(), not
    hand-derived comments.
  - baseline: the A100 bar DERIVED from the same measured numbers with
    every assumption stated (see _derive_baseline), replacing round 1's
    invented 20 steps/sec constant.
  - variants: the reference-parity BatchNorm line (the headline) plus
    the TPU-first GroupNorm tower and uint8-wire-format variants that
    document the headroom beyond parity.
  - input_pipeline: records/sec and JPEG decodes/sec through
    DefaultRecordInputGenerator (native on/off) and sustained
    record-fed training vs synthetic-fed (SURVEY.md §7 hard part 3).
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

WARMUP_LOOPS = 2
MEASURE_LOOPS = 3
# Steps fused per dispatch via Trainer.train_steps (lax.scan) — the same
# in-device loop TPUEstimator ran under TPUConfig(iterations_per_loop),
# and how train_eval_model(iterations_per_loop=K) trains for real.
# Throughput plateaus around K=60 on the v5e chip (measured 175 → 200 →
# 220 steps/s at K=1/20/60); the K-deep stacked batch (~5 GB at batch
# 32 float32) fits comfortably in 16 GB HBM.
ITERATIONS_PER_LOOP = 60

# Chip peaks for mfu/mbu, keyed by substrings of device_kind.
# v5e ("TPU v5 lite"): 197 TFLOP/s bf16, 819 GB/s HBM (public spec).
_CHIP_PEAKS = {
    "v5 lite": (197e12, 819e9),
    "v5e": (197e12, 819e9),
    "v5p": (459e12, 2765e9),
    "v4": (275e12, 1228e9),
    "v6": (918e12, 1640e9),
}

# --- the derived A100 baseline -------------------------------------------
# BASELINE.json's north star: beat the fork's 8xA100 tf.distribute+NCCL
# steps/sec/chip by >=3x. That fork number is unmeasurable here (no
# A100s, no network), so the bar is DERIVED from this run's MEASURED
# FLOPs/step (XLA cost analysis, cross-checked analytically;
# dtype/implementation-independent), favorably to the A100:
#   1. The fork runs fp32 (TF1 default; the reference API surface has
#      no mixed-precision hooks — SURVEY.md §2): 19.5 TFLOP/s on A100
#      CUDA cores. If the fork used the NVIDIA TF1 fork's TF32 default
#      the compute ceiling rises ~8x, but cuDNN TF32 convs at these
#      shapes (64-channel 3x3) are then firmly bandwidth/launch-bound —
#      the fp32 figure remains the defensible per-chip anchor; the
#      raw ceiling is emitted so a reader can substitute assumptions.
#   2. ideal_bound = A100 fp32 compute roofline for the measured
#      FLOPs/step: a STRICT upper bound on a fp32 A100 implementation
#      (100%-of-peak convolutions, zero memory/NCCL/input/dispatch
#      overhead). An HBM-side bound is NOT derivable here — XLA's
#      bytes-accessed metric is inflated by stacked-batch slice
#      accounting (see _cost_analysis) — which only makes ideal_bound
#      MORE generous to the A100.
#   3. fork_estimate = ideal_bound x 0.5: cuDNN fp32 convs at these
#      shapes sustain at most ~50% of peak in isolation (the
#      fork-favorable end; the per-op TF1 graph executor, BN stats
#      passes, and NCCL sync push real numbers lower).
#   4. fork_typical = ideal_bound x 0.25: end-to-end TF1 training
#      (input pipeline + Python dispatch + NCCL) historically sustains
#      25-35% of the isolated-conv roofline; 0.25 is the midpoint-low.
# vs_baseline uses the CONSERVATIVE fork_estimate (so the headline
# ratio is a lower-bound style claim); vs_a100_ideal_bound and
# vs_fork_typical are also emitted.
A100_FP32_FLOPS = 19.5e12
FORK_FP32_CONV_EFFICIENCY = 0.5
FORK_TYPICAL_E2E_EFFICIENCY = 0.25


def _chip_peaks(device_kind: str):
  kind = device_kind.lower()
  for key, peaks in _CHIP_PEAKS.items():
    if key in kind:
      return peaks
  return None, None


def _cost_analysis(compiled, k: int):
  """(flops_per_step, xla_bytes_accessed) from the K-step executable.

  XLA's cost analysis counts a while-loop (lax.scan) body ONCE — trip
  count is not folded in — and this executable is exactly K identical
  step bodies plus a negligible epilogue, so the reported flops ARE the
  per-step figure (verified against an analytic conv-FLOPs count: ~100
  GF/step for the 472² tower at batch 32 vs 96.4 GF reported; round 1's
  BENCH divided by K and under-reported 60x).

  "bytes accessed" is returned raw but is NOT usable as an HBM-traffic
  proxy for this program: slice ops over the K-stacked 5 GB input
  buffer are billed the full operand size, so the figure (12.3 GB
  "per step") exceeds what 819 GB/s HBM could move in a 4.8 ms step by
  3x. It is emitted only as an upper bound with this caveat attached;
  no mbu/achieved-bandwidth claims are derived from it."""
  del k  # see docstring: body-once semantics make flops per-step
  try:
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
      ca = ca[0]
    return (float(ca.get("flops", 0.0)),
            float(ca.get("bytes accessed", 0.0)))
  except Exception:
    return 0.0, 0.0


def _derive_baseline(flops_per_step: float):
  if not flops_per_step:
    return None
  ideal = A100_FP32_FLOPS / flops_per_step
  return {
      "kind": "derived-a100-fp32-compute-roofline",
      "a100_ideal_bound_steps_per_sec": round(ideal, 1),
      "a100_fork_estimate_steps_per_sec": round(
          ideal * FORK_FP32_CONV_EFFICIENCY, 1),
      "a100_fork_typical_steps_per_sec": round(
          ideal * FORK_TYPICAL_E2E_EFFICIENCY, 1),
      "assumptions": (
          "fp32 TF1 fork (no mixed-precision hooks in the reference "
          "API; TF32 would lift the raw ceiling ~8x but those convs "
          "are then bandwidth/launch-bound at these 64-channel "
          "shapes); A100 19.5 fp32 TFLOP/s; isolated cuDNN fp32 convs "
          "<= ~50% of peak (fork_estimate); end-to-end TF1 training "
          "historically 25-35% of the isolated-conv roofline "
          "(fork_typical). HBM-side bound intentionally not derived: "
          "XLA bytes-accessed is inflated by stacked-batch slice "
          "accounting, and omitting it only favors the A100."),
      "limit": "compute",
  }


def _zeros_batch(model, batch_size, mode):
  from __graft_entry__ import _example_batch
  from tensor2robot_tpu.specs import tensorspec_utils as ts

  features = _example_batch(model, batch_size, mode)
  label_spec = model.get_label_specification(mode)
  labels = jax.tree_util.tree_map(
      lambda s: jnp.zeros((batch_size,) + s.shape, s.dtype),
      ts.flatten_spec_structure(label_spec),
      is_leaf=lambda x: isinstance(x, ts.ExtendedTensorSpec))
  if not list(labels.keys()):
    labels = None
  return features, labels


def _measure_model(model, batch_size: int, k: int, warmup: int,
                   measure: int):
  """Steps/sec/chip + roofline for one model via the K-scanned step."""
  from tensor2robot_tpu import modes
  from tensor2robot_tpu.parallel import mesh as mesh_lib
  from tensor2robot_tpu.train.trainer import Trainer

  n_chips = jax.device_count()
  mesh = mesh_lib.create_mesh()
  trainer = Trainer(model, mesh=mesh, seed=0)
  state = trainer.create_train_state(batch_size=batch_size)
  features, labels = _zeros_batch(model, batch_size, modes.TRAIN)
  features, labels = trainer.shard_batch((features, labels))

  stacked_sharding = mesh_lib.stacked_batch_sharding(mesh, "data")

  def stack(tree):
    if tree is None:
      return None
    return jax.device_put(
        jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (k,) + x.shape), tree),
        stacked_sharding)

  stacked_features, stacked_labels = stack(features), stack(labels)
  compiled = trainer.aot_train_steps(state, stacked_features, stacked_labels)
  flops_per_step, hbm_bytes_per_step = _cost_analysis(compiled, k)

  for _ in range(warmup):
    state, metrics = compiled(state, stacked_features, stacked_labels)
  float(metrics["loss"])  # host readback: block_until_ready is not a
  # reliable sync through remote-tunnel backends, an actual value is.

  start = time.perf_counter()
  for _ in range(measure):
    state, metrics = compiled(state, stacked_features, stacked_labels)
  float(metrics["loss"])  # forces the whole measured chain
  elapsed = time.perf_counter() - start

  steps_per_sec = measure * k / elapsed / n_chips
  sec_per_step = 1.0 / steps_per_sec
  peak_flops, _ = _chip_peaks(jax.devices()[0].device_kind)
  roofline = {
      "flops_per_step": round(flops_per_step),
      "xla_bytes_accessed_per_step_upper_bound": round(
          hbm_bytes_per_step),
      "bytes_caveat": "slice ops over the K-stacked input are billed "
                      "full operand size; not a real-traffic figure "
                      "(see bench.py _cost_analysis)",
  }
  if flops_per_step:
    roofline["achieved_tflops"] = round(
        flops_per_step / sec_per_step / 1e12, 2)
    if peak_flops:
      roofline["mfu"] = round(flops_per_step / sec_per_step / peak_flops, 4)
  return round(steps_per_sec, 3), roofline


def _make_jpeg_dataset(path: str, num_records: int, image_size: int) -> None:
  """Writes `num_records` tf.Examples with real JPEG-encoded camera-like
  images (gradients + random blocks: realistic compressibility), float32
  actions, and scalar Bellman targets — the flagship's wire format."""
  from tensor2robot_tpu.data.example_proto import encode_example
  from tensor2robot_tpu.data.tfrecord import TFRecordWriter
  from tensor2robot_tpu.utils.image import encode_jpeg

  rng = np.random.default_rng(0)
  yy, xx = np.mgrid[0:image_size, 0:image_size]
  base = ((xx + yy) * (255.0 / (2 * image_size))).astype(np.uint8)
  with TFRecordWriter(path) as writer:
    for i in range(num_records):
      img = np.stack([np.roll(base, 31 * i, axis=1)] * 3, axis=-1).copy()
      # A few random blocks so JPEG size/decode cost is image-dependent.
      for _ in range(8):
        y, x = rng.integers(0, image_size - 32, size=2)
        img[y:y + 32, x:x + 32] = rng.integers(0, 255, (32, 32, 3))
      writer.write(encode_example({
          "image": [encode_jpeg(img, quality=85)],
          "action": rng.standard_normal(4).astype(np.float32),
          "target_q": np.asarray([rng.random()], np.float32),
      }))


def _bench_input_pipeline(model, batch_size: int,
                          synthetic_steps_per_sec: float):
  """records/sec + decodes/sec (native on/off) and record-fed training.

  NOTE this host exposes os.cpu_count() CPU cores (1 in the bench
  container); JPEG decode throughput scales ~linearly with host cores,
  so the records/sec here is a per-core figure, not a host ceiling.
  """
  import tempfile

  from tensor2robot_tpu import modes
  from tensor2robot_tpu.data.default_input_generator import (
      DefaultRecordInputGenerator)
  from tensor2robot_tpu.data.prefetch import prefetch_to_device
  from tensor2robot_tpu.parallel import mesh as mesh_lib
  from tensor2robot_tpu.train.trainer import Trainer

  num_records = 512
  image_size = model._in_image_size
  out = {"host_cpu_cores": os.cpu_count(), "record_batch_size": batch_size}

  with tempfile.TemporaryDirectory() as tmp:
    path = os.path.join(tmp, "bench.tfrecord")
    _make_jpeg_dataset(path, num_records, image_size)
    out["jpeg_bytes_per_record"] = round(
        os.path.getsize(path) / num_records)

    def records_per_sec(disable_native: bool) -> float:
      from tensor2robot_tpu.data import native
      env_key = "T2R_DISABLE_NATIVE"
      prev = os.environ.get(env_key)
      os.environ[env_key] = "1" if disable_native else "0"
      native.reset_cache()
      try:
        gen = DefaultRecordInputGenerator(
            file_patterns=path, batch_size=batch_size, seed=0,
            num_pipeline_threads=max(1, os.cpu_count() or 1))
        gen.set_specification_from_model(model, modes.TRAIN)
        it = gen.create_dataset_fn(modes.TRAIN)()
        next(it)  # warm: thread spin-up + first parse
        n_batches = 12
        start = time.perf_counter()
        for _ in range(n_batches):
          next(it)
        elapsed = time.perf_counter() - start
        it.close()
        return n_batches * batch_size / elapsed
      finally:
        if prev is None:
          os.environ.pop(env_key, None)
        else:
          os.environ[env_key] = prev
        native.reset_cache()  # downstream consumers re-decide from env

    native_rps = records_per_sec(disable_native=False)
    python_rps = records_per_sec(disable_native=True)
    # One decoded JPEG per record in this schema.
    out["jpeg_records_per_sec_native"] = round(native_rps, 1)
    out["jpeg_records_per_sec_python"] = round(python_rps, 1)
    out["native_speedup"] = round(native_rps / max(python_rps, 1e-9), 2)

    # Sustained record-fed training (native path — pinned, not ambient:
    # an inherited T2R_DISABLE_NATIVE=1 would silently measure the
    # Python decode path while the JSON attributes it to native),
    # single-step dispatch with double-buffered device prefetch — the
    # real train_eval feed.
    from tensor2robot_tpu.data import native as native_mod
    prev_disable = os.environ.get("T2R_DISABLE_NATIVE")
    os.environ["T2R_DISABLE_NATIVE"] = "0"
    native_mod.reset_cache()
    mesh = mesh_lib.create_mesh()
    trainer = Trainer(model, mesh=mesh, seed=0)
    state = trainer.create_train_state(batch_size=batch_size)
    gen = DefaultRecordInputGenerator(
        file_patterns=path, batch_size=batch_size, seed=0,
        num_pipeline_threads=max(1, os.cpu_count() or 1))
    gen.set_specification_from_model(model, modes.TRAIN)

    def fresh_batches():
      return prefetch_to_device(
          gen.create_dataset_fn(modes.TRAIN)(),
          sharding=trainer.batch_sharding)

    batches = fresh_batches()
    features, labels = next(batches)
    state, metrics = trainer.train_step(state, features, labels)  # compile
    float(metrics["loss"])
    # Fresh pipeline for the measurement: during the tens-of-seconds
    # compile above, the reader/parse threads filled every buffer
    # (prefetch_batches + device prefetch depth ≈ 6 ready batches), and
    # draining those would measure train-step speed, not sustained
    # record-fed throughput. Starting cold includes the fill cost —
    # the honest (slightly pessimistic) side.
    batches.close()
    batches = fresh_batches()
    n_steps = 10
    start = time.perf_counter()
    for _ in range(n_steps):
      features, labels = next(batches)
      state, metrics = trainer.train_step(state, features, labels)
    float(metrics["loss"])
    elapsed = time.perf_counter() - start
    batches.close()
    record_fed = n_steps / elapsed
    if prev_disable is None:
      os.environ.pop("T2R_DISABLE_NATIVE", None)
    else:
      os.environ["T2R_DISABLE_NATIVE"] = prev_disable
    native_mod.reset_cache()

    # The apples-to-apples bar: synthetic-fed at the SAME single-step
    # dispatch (the K=60 headline amortizes dispatch; the record-fed
    # loop cannot, so compare like with like, and report both).
    sfeat, slab = _zeros_batch(model, batch_size, modes.TRAIN)
    sfeat, slab = trainer.shard_batch((sfeat, slab))
    state, metrics = trainer.train_step(state, sfeat, slab)
    float(metrics["loss"])
    start = time.perf_counter()
    for _ in range(n_steps):
      state, metrics = trainer.train_step(state, sfeat, slab)
    float(metrics["loss"])
    elapsed = time.perf_counter() - start
    synthetic_k1 = n_steps / elapsed

    # Attribute the record-fed gap: host→device bandwidth of one
    # feature batch (on this box the chip hangs off a remote tunnel,
    # orders of magnitude below a real TPU host's PCIe/DMA path).
    one_batch = np.zeros((batch_size, image_size, image_size, 3),
                         np.float32)
    jax.block_until_ready(jax.device_put(one_batch))  # warm path
    start = time.perf_counter()
    jax.block_until_ready(jax.device_put(one_batch))
    h2d = one_batch.nbytes / (time.perf_counter() - start)
    out["h2d_gbps"] = round(h2d / 1e9, 3)

    out["record_fed_steps_per_sec"] = round(record_fed, 2)
    out["synthetic_steps_per_sec_k1"] = round(synthetic_k1, 2)
    out["record_fed_fraction_of_k1"] = round(record_fed / synthetic_k1, 3)
    out["record_fed_fraction_of_headline"] = round(
        record_fed / synthetic_steps_per_sec, 3)
    out["note"] = (
        "record-fed throughput on this box is bounded by two "
        "container artifacts, not the pipeline design: a 1-core host "
        "(JPEG decode scales ~linearly with cores; feeding "
        f"~{round(synthetic_steps_per_sec * batch_size)} images/sec "
        f"needs ~{round(synthetic_steps_per_sec * batch_size / max(native_rps, 1))} "
        "cores at the measured per-core rate — TPU hosts have ~100+) "
        f"and a remote-tunnel H2D path measured at {h2d / 1e9:.2f} GB/s "
        "(real hosts: tens of GB/s; the float32 wire batch alone is "
        f"{one_batch.nbytes / 1e6:.0f} MB/step — uint8_images=True "
        "cuts it 4x and removes the decode entirely)")
  return out


def main() -> None:
  from tensor2robot_tpu.research.qtopt.t2r_models import QTOptGraspingModel

  batch_size = QTOptGraspingModel.benchmark_batch_size
  k = ITERATIONS_PER_LOOP

  # Headline: the reference-parity workload (BatchNorm tower, float32
  # wire format) — comparable with BENCH_r01.
  value, roofline = _measure_model(
      QTOptGraspingModel(), batch_size, k, WARMUP_LOOPS, MEASURE_LOOPS)

  # space_to_depth stem not benched by default: measured 2026-07-30 at
  # 159 vs 189 steps/s against the parity stem (same warmup/measure
  # settings) — the 472² 6D transpose's HBM traffic and the 1.8x stem
  # FLOPs (192- vs 108-feature kernel) outweigh the MXU lane gain on a
  # stem that is ~18% of total FLOPs. Kept as a model option + test;
  # negative result recorded in DESIGN.md §8.
  variants = {}
  for name, kwargs in (
      ("groupnorm_tower", {"norm": "group"}),
      ("uint8_wire", {"uint8_images": True}),
  ):
    v, r = _measure_model(
        QTOptGraspingModel(**kwargs), batch_size, k, 1, 2)
    variants[name] = {"steps_per_sec_per_chip": v, **r}

  # Throughput headroom beyond the parity batch: per-chip batch 128
  # lifts MFU 10.4% → 16.1% (measured 2026-07-30) — larger spatial
  # tiles per conv dispatch. The headline stays batch 32 (the fork's
  # per-GPU batch, the comparable); this line documents the knob.
  # k=15, not the headline's 60: the K-stacked float32 input at batch
  # 128 is k × 85 MB — 60 × 342 MB ≈ 20 GB would blow the 16 GB HBM,
  # so dispatch amortization here differs from the headline (a second
  # variable in the comparison; the MFU figure is what transfers).
  v128, r128 = _measure_model(
      QTOptGraspingModel(), 128, 15, 1, 2)
  variants["batch128"] = {
      "steps_per_sec_per_chip": v128,
      "images_per_sec_per_chip": round(v128 * 128),
      "mfu": r128.get("mfu"),
  }

  baseline = _derive_baseline(roofline.get("flops_per_step", 0))
  if baseline:
    bar = baseline["a100_fork_estimate_steps_per_sec"]
    vs_baseline = round(value / bar, 3)
    vs_ideal = round(value / baseline["a100_ideal_bound_steps_per_sec"], 3)
    vs_typical = round(
        value / baseline["a100_fork_typical_steps_per_sec"], 3)
  else:
    vs_baseline = vs_ideal = vs_typical = None

  input_pipeline = _bench_input_pipeline(
      QTOptGraspingModel(), batch_size, value)

  print(json.dumps({
      "metric": f"QTOptGraspingModel train steps/sec/chip "
                f"(batch {batch_size})",
      "value": value,
      "unit": "steps/sec/chip",
      "vs_baseline": vs_baseline,
      "vs_a100_ideal_bound": vs_ideal,
      "vs_fork_typical": vs_typical,
      "device_kind": jax.devices()[0].device_kind,
      "iterations_per_loop": k,
      "roofline": roofline,
      "baseline": baseline,
      "variants": variants,
      "input_pipeline": input_pipeline,
  }))


if __name__ == "__main__":
  main()

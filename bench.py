"""Benchmark: flagship train throughput + roofline + input pipeline.

Driver contract (VERDICT r2 Weak #2: the contract keys must survive a
tail-capture that truncates from the FRONT): stdout carries ONE COMPACT
JSON line (< ~1 KB) with metric / value / unit / vs_baseline plus a few
scalars; the full evidence trail (roofline, baseline derivation,
microbenchmarks, step budget, variants, input-pipeline study) is written
to the committed side file named by the "detail" key.

Headline operating point (stated, per VERDICT r2 #3): QT-Opt grasping
Q-function, per-chip batch 128, uint8 wire format (model option
`uint8_images=True` — identical conv math, 4× less batch wire traffic),
60 scanned steps per dispatch. The metric is per-IMAGE throughput so
operating points with different batch sizes compare against the same
derived A100 bar: the bar is a compute roofline × efficiency, which is
batch-independent per image. The reference-parity batch-32 float32 line
(comparable with earlier rounds' artifacts) is also measured and emitted.

Methodology (numbers live in the detail artifact, never in prose —
VERDICT r3 #2):
  - Per-call dispatch overhead through this container's remote-tunnel
    TPU is large and variable (measured each run into
    `parity_b32.per_call_dispatch_overhead_ms`; real TPU hosts: sub-ms).
    Naive timings INCLUDE it; steady-state per-step marginals (two scan
    lengths, differenced) are emitted alongside with the methodology
    named, with spread over repeated rounds.
  - XLA cost_analysis on a scan-of-K executable reports the body once,
    so flops ARE per-step; bytes-accessed is inflated by stacked-batch
    slice accounting and is never used for bandwidth claims.
  - Every field that supports a claim carries {median, min, max, trials}
    measured THIS run (VERDICT r3 #1/#2: single-shot ratios on a
    contended 1-core host are noise; committed constants go stale).
  - The isolated-conv microbench anchors the MFU-ceiling story: read
    the relative pattern (64-/128-channel tower convs far above the
    3-input-channel parity stem) from this run's fields.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

ROUND = 20
DETAIL_FILE = f"BENCH_DETAIL_r{ROUND:02d}.json"

WARMUP_LOOPS = 2
MEASURE_LOOPS = 3
# The headline operating point's batch; used by BOTH the measurement in
# main() and the metric label in _METRIC_NAME so they cannot diverge.
HEADLINE_BATCH = 128
# Steps fused per dispatch via Trainer.train_steps (lax.scan) — the same
# in-device loop TPUEstimator ran under TPUConfig(iterations_per_loop).
ITERATIONS_PER_LOOP = 60

# Chip peaks for mfu, keyed by substrings of device_kind.
# v5e ("TPU v5 lite"): 197 TFLOP/s bf16 (public spec). Owned by the
# obs ledger since round 12 so every MFU estimate (headline, per-
# executable attribution) reads one table.
from tensor2robot_tpu.obs.ledger import CHIP_PEAKS as _CHIP_PEAKS

# --- the derived A100 baseline -------------------------------------------
# BASELINE.json's north star: beat the fork's 8xA100 tf.distribute+NCCL
# throughput per chip by >=3x. That fork number is unmeasurable here (no
# A100s, no network), so the bar is DERIVED from the measured parity
# FLOPs/image (XLA cost analysis, cross-checked analytically), favorably
# to the A100 — full rationale in the detail artifact's
# baseline.assumptions. The fork would run the PARITY model (float32,
# batch at its choosing), so the bar is per-image and batch-independent:
#   a100_img_per_sec(tier) = A100_FP32_FLOPS * tier / flops_per_image
# vs_baseline uses the CONSERVATIVE fork_estimate tier (0.5 = isolated
# cuDNN fp32 convs at <=50% of peak with zero other overhead).
A100_FP32_FLOPS = 19.5e12
FORK_FP32_CONV_EFFICIENCY = 0.5
FORK_TYPICAL_E2E_EFFICIENCY = 0.25
# Analytic parity-model FLOPs (batch 32): used ONLY if cost_analysis
# fails (ADVICE r2: never emit vs_baseline null — fall back loudly).
ANALYTIC_PARITY_FLOPS_B32 = 96.4e9

_BASELINE_ASSUMPTIONS = (
    "fp32 TF1 fork (no mixed-precision hooks in the reference API; "
    "TF32 would lift the raw ceiling ~8x but those convs are then "
    "bandwidth/launch-bound at 64-channel shapes); A100 19.5 fp32 "
    "TFLOP/s; isolated cuDNN fp32 convs <= ~50% of peak "
    "(fork_estimate tier); end-to-end TF1 training historically 25-35% "
    "of the isolated-conv roofline (fork_typical tier). The bar is "
    "per-image: flops_per_image from the measured PARITY model (the "
    "architecture the fork would run); uint8 wire changes transport, "
    "not conv math. HBM-side bound intentionally not derived (XLA "
    "bytes-accessed inflated by stacked-batch slice accounting; "
    "omitting it only favors the A100).")


def _spread(values, digits=3):
  """{median,min,max,trials} — the committed shape of every measured
  field a doc is allowed to cite (VERDICT r3 #2)."""
  vals = [float(v) for v in values]
  return {
      "median": round(statistics.median(vals), digits),
      "min": round(min(vals), digits),
      "max": round(max(vals), digits),
      "trials": len(vals),
  }


def _chip_peak(device_kind: str):
  kind = device_kind.lower()
  for key, peak in _CHIP_PEAKS.items():
    if key in kind:
      return peak
  return None


def _cost_analysis_flops(compiled):
  """Per-step flops from the K-step executable (body counted once —
  see module docstring); 0.0 on failure."""
  try:
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
      ca = ca[0]
    return float(ca.get("flops", 0.0))
  except Exception:
    return 0.0


def _zeros_batch(model, batch_size, mode):
  from __graft_entry__ import _example_batch
  from tensor2robot_tpu.specs import tensorspec_utils as ts

  features = _example_batch(model, batch_size, mode)
  label_spec = model.get_label_specification(mode)
  labels = jax.tree_util.tree_map(
      lambda s: jnp.zeros((batch_size,) + s.shape, s.dtype),
      ts.flatten_spec_structure(label_spec),
      is_leaf=lambda x: isinstance(x, ts.ExtendedTensorSpec))
  if not list(labels.keys()):
    labels = None
  return features, labels


class _TrainBench:
  """One compiled K-scanned train-step executable + its measurements."""

  def __init__(self, model, batch_size: int, k: int):
    from tensor2robot_tpu import modes
    from tensor2robot_tpu.parallel import mesh as mesh_lib
    from tensor2robot_tpu.train.trainer import Trainer

    self.batch_size, self.k = batch_size, k
    mesh = mesh_lib.create_mesh()
    self._trainer = Trainer(model, mesh=mesh, seed=0)
    self._state = self._trainer.create_train_state(batch_size=batch_size)
    features, labels = _zeros_batch(model, batch_size, modes.TRAIN)
    features, labels = self._trainer.shard_batch((features, labels))
    sharding = mesh_lib.stacked_batch_sharding(mesh, "data")

    def stack(tree):
      if tree is None:
        return None
      return jax.device_put(
          jax.tree_util.tree_map(
              lambda x: jnp.broadcast_to(x[None], (k,) + x.shape), tree),
          sharding)

    self._batch = (stack(features), stack(labels))
    self._compiled = self._trainer.aot_train_steps(self._state, *self._batch)
    self.flops_per_step = _cost_analysis_flops(self._compiled)

  def measure(self, warmup: int, measure: int):
    """Naive steps/sec/chip (includes per-call dispatch overhead)."""
    n_chips = jax.device_count()
    state, metrics = self._state, None
    for _ in range(warmup):
      state, metrics = self._compiled(state, *self._batch)
    if metrics is not None:
      float(metrics["loss"])  # host readback: the only reliable sync
    start = time.perf_counter()
    for _ in range(measure):
      state, metrics = self._compiled(state, *self._batch)
    float(metrics["loss"])
    elapsed = time.perf_counter() - start
    self._state = state
    return round(measure * self.k / elapsed / n_chips, 3)


def _measure_config(model, batch_size, k, warmup=WARMUP_LOOPS,
                    measure=MEASURE_LOOPS):
  bench = _TrainBench(model, batch_size, k)
  sps = bench.measure(warmup, measure)
  return sps, bench.flops_per_step, bench


def _steady_state(model, batch_size, k_small, k_big, rounds=5,
                  big_bench=None):
  """Per-step marginal cost via two scan lengths, with spread.

  The difference between a k_big call and a k_small call contains no
  dispatch overhead — it is (k_big - k_small) pure steps. Each round
  produces one independent marginal estimate; the spread over rounds is
  what makes the number citable on a contended host (VERDICT r3 #3:
  a single estimate with no spread anchors nothing). `big_bench`
  reuses an already-compiled k_big executable (an AOT compile costs
  tens of seconds on this box).

  Returns (marginal_ms_spread, overhead_ms) — overhead from the best
  (least-contended) round.
  """
  small_bench = _TrainBench(model, batch_size, k_small)
  bench_by_k = {k_small: small_bench,
                k_big: big_bench or _TrainBench(model, batch_size, k_big)}
  for bench in bench_by_k.values():
    bench.measure(1, 1)  # warm
  marginals, overheads = [], []
  for _ in range(rounds):
    per_call = {}
    for k, bench in bench_by_k.items():
      start = time.perf_counter()
      bench.measure(0, 1)
      per_call[k] = time.perf_counter() - start
    marginal = (per_call[k_big] - per_call[k_small]) / (k_big - k_small)
    if marginal > 0:
      marginals.append(marginal * 1e3)
      overheads.append(
          max(per_call[k_small] - k_small * marginal * 1e-3, 0.0) * 1e3)
  if not marginals:  # pathological contention: fall back to big-call rate
    start = time.perf_counter()
    bench_by_k[k_big].measure(0, 1)
    marginals = [(time.perf_counter() - start) / k_big * 1e3]
    overheads = [0.0]
  return _spread(marginals, 3), round(min(overheads), 1)


def _microbench_convs(reps=5):
  """Isolated conv achieved-TFLOP/s at the flagship's shapes (delta
  method between two scan lengths — immune to dispatch overhead), with
  {median,min,max,trials} per field over `reps` independent repetitions
  (VERDICT r3 #3: committed-vs-rerun values differed up to 2.4x with no
  way to tell noise from regression). Anchors the 'where the MFU goes'
  story."""
  from jax import lax

  peak = _chip_peak(jax.devices()[0].device_kind) or 0
  key = jax.random.key(0)

  def marginal_us_once(fns, x, l1, l2):
    times = {}
    for length, fn in fns.items():
      start = time.perf_counter()
      jax.block_until_ready(fn(x))
      times[length] = time.perf_counter() - start
    return (times[l2] - times[l1]) / (l2 - l1) * 1e6

  def conv_chain(b, hw, c):
    w = jax.random.normal(key, (3, 3, c, c), jnp.bfloat16) * 0.04
    x = jax.random.normal(key, (b, hw, hw, c), jnp.bfloat16)

    def make(length):
      def step(y, _):
        return lax.conv_general_dilated(
            y, w, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC")), None
      return jax.jit(lambda x: lax.scan(step, x, None, length=length)[0])
    flops = 2 * b * hw * hw * 9 * c * c
    return make, x, flops

  def stem_chain(b):
    w = jax.random.normal(key, (6, 6, 3, 64), jnp.bfloat16) * 0.04
    x = jax.random.normal(key, (b, 472, 472, 3), jnp.bfloat16)

    def make(length):
      def step(y, _):
        out = lax.conv_general_dilated(
            y, w, (4, 4), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y * (1 + 1e-4 * jnp.mean(out).astype(y.dtype)), None
      return jax.jit(lambda x: lax.scan(step, x, None, length=length)[0])
    flops = 2 * b * 118 * 118 * 36 * 3 * 64
    return make, x, flops

  l1, l2 = 30, 150
  table = {}
  for name, (make, x, flops) in {
      "tower_3x3_64ch_59sq_b32": conv_chain(32, 59, 64),
      "tower_3x3_64ch_59sq_b128": conv_chain(128, 59, 64),
      "tower_3x3_128ch_59sq_b32": conv_chain(32, 59, 128),
      "parity_stem_6x6s4_472sq_b32": stem_chain(32),
  }.items():
    fns = {length: make(length) for length in (l1, l2)}
    for fn in fns.values():
      jax.block_until_ready(fn(x))  # compile + warm
    us_samples = [marginal_us_once(fns, x, l1, l2) for _ in range(reps)]
    us_samples = [u for u in us_samples if u > 0] or us_samples
    entry = {
        "us_per_op": _spread(us_samples, 1),
        "achieved_tflops": _spread(
            [flops / (u * 1e-6) / 1e12 for u in us_samples], 1),
    }
    if peak:
      entry["mfu"] = _spread(
          [flops / (u * 1e-6) / peak for u in us_samples], 3)
    table[name] = entry
  table["note"] = (
      "delta method (two scan lengths) — per-op marginal cost, no "
      "dispatch overhead; every field is {median,min,max,trials} from "
      "this run. The stable pattern to read: the 64-channel tower "
      "convs sit far above the 3-input-channel parity stem, and 128 "
      "input channels approach the MXU roofline — the end-to-end MFU "
      "ceiling is the parity architecture's lane structure (Cin=3 "
      "stem, Cout=64 tower), not scheduling loss.")
  return table


# --- per-piece step budget (VERDICT r3 #3) --------------------------------


def _step_budget(anchor_ms_spread, reps=5):
  """Delta-method timings of the parity b32 train step's pieces.

  Each piece is the real Flax layer sequence at the real shapes/dtypes
  (bf16 compute, f32 params, train-mode BatchNorm), measured as
  forward+backward (jax.value_and_grad) via the same two-scan-length
  marginal as everything else; the scan carries the piece's params
  perturbed by 1e-30*grad so XLA cannot hoist the loop body, and
  gradients w.r.t. activations are folded into that perturbation so
  backward-through-input is computed, not dead-code-eliminated.

  The pieces partition the train step: stem (includes reading the
  (32,472,472,3) float32 batch slice, as the real scanned step does),
  pre-merge tower, action merge, post-merge tower, head+loss, optimizer
  update. Known exclusions, all sub-1%-scale: BatchNorm running-stat
  EMA axpys (64-float), metrics tree, step-counter bump. Boundary
  handoffs (the jnp.sum coupling loss per piece) read each piece's
  output once — in the fused step the consumer does that read, so the
  budget slightly double-counts boundaries, which only INFLATES the
  coverage fraction's honesty band, never hides a missing ms.
  """
  import flax.linen as nn
  import optax
  from jax import lax

  from tensor2robot_tpu.layers.vision_layers import normalize_image

  b = 32
  dtype = jnp.bfloat16
  key = jax.random.key(0)

  class Stem(nn.Module):
    # pool_kind "flax" = nn.max_pool (reduce-window; SelectAndScatter
    # backward) — the production default; "reshape" = ops/pool.py
    # formulation, measured here as a candidate swap.
    pool_kind: str = "flax"

    @nn.compact
    def __call__(self, x):
      from tensor2robot_tpu.ops.pool import max_pool_reshape
      x = normalize_image(x, dtype)
      x = nn.Conv(64, (6, 6), strides=(4, 4), dtype=dtype, name="stem")(x)
      x = nn.relu(nn.BatchNorm(
          use_running_average=False, dtype=dtype, name="stem_bn")(x))
      if self.pool_kind == "reshape":
        return max_pool_reshape(x)
      return nn.max_pool(x, (2, 2), strides=(2, 2))

  class PreTower(nn.Module):
    @nn.compact
    def __call__(self, x):
      for i in range(3):
        x = nn.relu(nn.BatchNorm(
            use_running_average=False, dtype=dtype, name=f"pre_bn{i}")(
                nn.Conv(64, (3, 3), dtype=dtype, name=f"pre_conv{i}")(x)))
      return x

  class ActionMerge(nn.Module):
    @nn.compact
    def __call__(self, x, action):
      emb = nn.relu(nn.Dense(64, dtype=dtype, name="action_fc1")(
          action.astype(dtype)))
      emb = nn.Dense(64, dtype=dtype, name="action_fc2")(emb)
      return nn.relu(x + emb[:, None, None, :])

  class PostTower(nn.Module):
    # conv_kind "direct" = nn.Conv strided SAME (production default);
    # "folded" = ops/strided_conv.py lanes-folded formulation — same
    # function, measured here as a candidate swap for the strided
    # backward shapes the r3 ablation flagged.
    conv_kind: str = "direct"

    @nn.compact
    def __call__(self, x):
      from tensor2robot_tpu.ops.strided_conv import strided3x3_same
      for i, stride in enumerate((2, 2, 2)):
        if self.conv_kind == "folded":
          assert stride == 2, "strided3x3_same hardcodes stride 2"
          c = x.shape[-1]
          kernel = self.param(f"post_conv{i}_kernel",
                              nn.initializers.lecun_normal(),
                              (3, 3, c, 64))
          bias = self.param(f"post_conv{i}_bias",
                            nn.initializers.zeros, (64,))
          x = strided3x3_same(x, kernel.astype(dtype)) + bias.astype(
              dtype)
        else:
          x = nn.Conv(64, (3, 3), strides=(stride, stride), dtype=dtype,
                      name=f"post_conv{i}")(x)
        x = nn.relu(nn.BatchNorm(
            use_running_average=False, dtype=dtype,
            name=f"post_bn{i}")(x))
      return x

  class HeadLoss(nn.Module):
    @nn.compact
    def __call__(self, x, target):
      x = jnp.mean(x, axis=(1, 2))
      x = nn.relu(nn.Dense(64, dtype=dtype, name="fc1")(x))
      logit = nn.Dense(1, dtype=jnp.float32, name="q_head")(x)[:, 0]
      return jnp.mean(optax.sigmoid_binary_cross_entropy(logit, target))

  def piece_ms(module, inputs, grad_argnums, scalar_output=False,
               l1=10, l2=50):
    """Marginal fwd+bwd ms/op of `module` applied to `inputs`.

    grad_argnums mirrors the real step's backward exactly: params
    (argnum 0) plus the ACTIVATION inputs flowing from earlier pieces —
    never leaf inputs (image, action, target), whose gradients the
    real train step does not compute."""
    variables = module.init(key, *inputs)
    params = variables["params"]
    stats = {k: v for k, v in variables.items() if k != "params"}

    def loss_fn(params, *xs):
      out = module.apply({"params": params, **stats}, *xs,
                         mutable=list(stats.keys()) or False)
      if stats:
        out = out[0]
      if not scalar_output:
        out = jnp.sum(out.astype(jnp.float32))
      return out

    grad_fn = jax.value_and_grad(loss_fn, argnums=grad_argnums)

    def make(length):
      def body(carry, _):
        params = carry
        _, grads = grad_fn(params, *inputs)
        g_params = grads[0]
        # Scalar coupling keeps the activation gradients alive.
        g_extra = sum(jnp.sum(g.astype(jnp.float32)) for g in grads[1:]) \
            if len(grads) > 1 else 0.0
        new_params = jax.tree_util.tree_map(
            lambda p, g: p + (1e-30 * (g.astype(p.dtype)
                                       + jnp.asarray(g_extra, p.dtype))),
            params, g_params)
        return new_params, None
      return jax.jit(
          lambda p: lax.scan(body, p, None, length=length)[0])

    fns = {length: make(length) for length in (l1, l2)}
    for fn in fns.values():
      jax.block_until_ready(fn(params))  # compile + warm
    samples = []
    for _ in range(reps):
      times = {}
      for length, fn in fns.items():
        start = time.perf_counter()
        jax.block_until_ready(fn(params))
        times[length] = time.perf_counter() - start
      samples.append((times[l2] - times[l1]) / (l2 - l1) * 1e3)
    return [s for s in samples if s > 0] or samples

  rng = np.random.default_rng(0)
  x_img = jnp.asarray(rng.random((b, 472, 472, 3)), jnp.float32)
  x_59 = jnp.asarray(rng.standard_normal((b, 59, 59, 64)), dtype)
  action = jnp.asarray(rng.standard_normal((b, 4)), jnp.float32)
  target = jnp.asarray(rng.random((b,)), jnp.float32)

  budget = {}
  budget["stem_incl_batch_read"] = _spread(
      piece_ms(Stem(), (x_img,), grad_argnums=(0,)), 3)
  # Candidate swap measured side by side (ops/pool.py): identical
  # function, reshape-max backward instead of SelectAndScatter.
  budget["stem_variant_reshape_pool"] = _spread(
      piece_ms(Stem(pool_kind="reshape"), (x_img,), grad_argnums=(0,)),
      3)
  budget["pre_tower_3x_conv3x3_59sq"] = _spread(
      piece_ms(PreTower(), (x_59,), grad_argnums=(0, 1)), 3)
  budget["action_merge_dense"] = _spread(
      piece_ms(ActionMerge(), (x_59, action), grad_argnums=(0, 1)), 3)
  budget["post_tower_3x_strided_conv"] = _spread(
      piece_ms(PostTower(), (x_59,), grad_argnums=(0, 1)), 3)
  budget["post_tower_variant_folded"] = _spread(
      piece_ms(PostTower(conv_kind="folded"), (x_59,),
               grad_argnums=(0, 1)), 3)
  budget["head_pool_fc_loss"] = _spread(
      piece_ms(HeadLoss(), (x_59, target), grad_argnums=(0, 1),
               scalar_output=True), 3)

  # Optimizer: the real model's param tree through the real optimizer.
  from tensor2robot_tpu.research.qtopt.t2r_models import QTOptGraspingModel
  model = QTOptGraspingModel()
  module = model.build_module()
  variables = module.init(key, {"image": x_img, "action": action},
                          "train")
  params = variables["params"]
  opt = model.create_optimizer()
  opt_state = opt.init(params)
  grads = jax.tree_util.tree_map(jnp.ones_like, params)

  def make_opt(length):
    def body(carry, _):
      params, opt_state = carry
      updates, new_opt_state = opt.update(grads, opt_state, params)
      return (optax.apply_updates(params, updates), new_opt_state), None
    return jax.jit(lambda c: lax.scan(body, c, None, length=length)[0])

  fns = {length: make_opt(length) for length in (10, 50)}
  carry = (params, opt_state)
  for fn in fns.values():
    jax.block_until_ready(fn(carry))
  opt_samples = []
  for _ in range(reps):
    times = {}
    for length, fn in fns.items():
      start = time.perf_counter()
      jax.block_until_ready(fn(carry))
      times[length] = time.perf_counter() - start
    opt_samples.append((times[50] - times[10]) / 40 * 1e3)
  budget["optimizer_update"] = _spread(
      [s for s in opt_samples if s > 0] or opt_samples, 3)

  pieces_total = sum(v["median"] for key, v in budget.items()
                     if "_variant" not in key)
  anchor = anchor_ms_spread["median"]
  budget["sum_of_pieces_ms"] = round(pieces_total, 3)
  budget["measured_full_step_ms"] = anchor_ms_spread
  budget["coverage_fraction"] = round(pieces_total / anchor, 3) \
      if anchor else None
  budget["note"] = (
      "fwd+bwd marginal ms per piece (delta method, spread over "
      f"{reps} reps); pieces partition the parity b32 train step. "
      "coverage_fraction = sum_of_pieces / measured_full_step — above "
      "1.0 means boundary reads double-counted plus XLA cross-piece "
      "fusion the isolated pieces can't enjoy; the per-piece SHARES "
      "are the decision-relevant signal. Pieces tagged intrinsic to "
      "the parity architecture: stem (Cin=3 lane structure), "
      "tower convs + BatchNorm (the reference's exact math).")
  return budget


# --- input pipeline --------------------------------------------------------


def _make_jpeg_dataset(path: str, num_records: int, image_size: int) -> None:
  """tf.Examples with real JPEG camera-like images (gradients + random
  blocks: realistic compressibility), float32 actions, scalar targets."""
  from tensor2robot_tpu.data.example_proto import encode_example
  from tensor2robot_tpu.data.tfrecord import TFRecordWriter
  from tensor2robot_tpu.utils.image import encode_jpeg

  rng = np.random.default_rng(0)
  yy, xx = np.mgrid[0:image_size, 0:image_size]
  base = ((xx + yy) * (255.0 / (2 * image_size))).astype(np.uint8)
  with TFRecordWriter(path) as writer:
    for i in range(num_records):
      img = np.stack([np.roll(base, 31 * i, axis=1)] * 3, axis=-1).copy()
      for _ in range(8):
        y, x = rng.integers(0, image_size - 32, size=2)
        img[y:y + 32, x:x + 32] = rng.integers(0, 255, (32, 32, 3))
      writer.write(encode_example({
          "image": [encode_jpeg(img, quality=85)],
          "action": rng.standard_normal(4).astype(np.float32),
          "target_q": np.asarray([rng.random()], np.float32),
      }))


def _make_raw_uint8_dataset(path: str, num_records: int,
                            image_size: int) -> None:
  """tf.Examples with RAW uint8 image bytes (no JPEG): the
  `wire_format="raw"` + `uint8_images=True` pipeline — zero decode."""
  from tensor2robot_tpu.data.example_proto import encode_example
  from tensor2robot_tpu.data.tfrecord import TFRecordWriter

  rng = np.random.default_rng(0)
  with TFRecordWriter(path) as writer:
    for _ in range(num_records):
      img = rng.integers(0, 255, (image_size, image_size, 3), np.uint8)
      writer.write(encode_example({
          "image": [img.tobytes()],
          "action": rng.standard_normal(4).astype(np.float32),
          "target_q": np.asarray([rng.random()], np.float32),
      }))


def _records_per_sec_trials(model, jpeg_path, batch_size, trials=5,
                            n_batches=8):
  """records/sec through the full pipeline, native vs python arms.

  Protocol (VERDICT r3 #1: one-shot fixed-order ratios did not survive
  the driver's own reruns): `trials` independent measurements per arm,
  arm order ALTERNATING between trials, fresh generator + thread pool
  per measurement, one warm batch before timing. Emits spread for both
  arms and for the per-trial-pair ratio."""
  from tensor2robot_tpu import modes
  from tensor2robot_tpu.data.default_input_generator import (
      DefaultRecordInputGenerator)

  def one(native_mode: str) -> float:
    gen = DefaultRecordInputGenerator(
        file_patterns=jpeg_path, batch_size=batch_size, seed=0,
        num_pipeline_threads=max(1, os.cpu_count() or 1),
        native_mode=native_mode)
    gen.set_specification_from_model(model, modes.TRAIN)
    it = gen.create_dataset_fn(modes.TRAIN)()
    next(it)  # warm: thread spin-up + first parse
    start = time.perf_counter()
    for _ in range(n_batches):
      next(it)
    elapsed = time.perf_counter() - start
    it.close()
    return n_batches * batch_size / elapsed

  rates = {"native": [], "python": []}
  for trial in range(trials):
    order = ("native", "python") if trial % 2 == 0 else ("python", "native")
    for arm in order:
      rates[arm].append(one(arm))
  ratios = [n / p for n, p in zip(rates["native"], rates["python"])]
  return {
      "jpeg_records_per_sec_native": _spread(rates["native"], 1),
      "jpeg_records_per_sec_python": _spread(rates["python"], 1),
      "native_speedup": _spread(ratios, 2),
  }


def _decode_only_trials(jpeg_blobs, trials=5, n_decodes=16):
  """Single-thread JPEG decode rate, native libjpeg vs PIL, interleaved
  trials — measured THIS run (replaces the r3 hardcoded prose constant,
  VERDICT r3 Weak #2)."""
  import io

  from PIL import Image

  from tensor2robot_tpu.data import native

  lib = native.get_native()
  if lib is None:
    return {"note": "native library unavailable; decode-only not measured"}
  blobs = (jpeg_blobs * ((n_decodes // len(jpeg_blobs)) + 1))[:n_decodes]

  def native_rate():
    start = time.perf_counter()
    for blob in blobs:
      lib.jpeg_decode(blob, channels=3)
    return n_decodes / (time.perf_counter() - start)

  def pil_rate():
    start = time.perf_counter()
    for blob in blobs:
      with Image.open(io.BytesIO(blob)) as img:
        if img.mode != "RGB":
          img = img.convert("RGB")
        np.asarray(img)
    return n_decodes / (time.perf_counter() - start)

  arms = {"native": native_rate, "pil": pil_rate}
  for fn in arms.values():
    fn()  # warm
  rates = {"native": [], "pil": []}
  for trial in range(trials):
    order = ("native", "pil") if trial % 2 == 0 else ("pil", "native")
    for arm in order:
      rates[arm].append(arms[arm]())
  return {
      "decodes_per_sec_native": _spread(rates["native"], 1),
      "decodes_per_sec_pil": _spread(rates["pil"], 1),
      "native_decode_speedup": _spread(
          [n / p for n, p in zip(rates["native"], rates["pil"])], 2),
  }


def _record_fed_rates(model, path, batch_size, trials=3, n_steps=12):
  """Record-fed single-step training (the real train_eval feed: reader
  threads → parse → preprocess → double-buffered device prefetch),
  with spread over fresh-pipeline trials.

  Per trial: cold rate = n_steps / total from a cold pipeline (fill
  cost included — scales with n_steps on a fill-dominated box, NOT
  comparable across protocol changes); steady rate = 1 / mean(per-step
  time over the last third), after the prefetch buffers drain to the
  pipeline's sustained rate (protocol-stable — use for ratios).

  Pipelines run native_mode='auto': the calibration decision each trial
  is recorded into the returned stats (the default-path evidence the
  artifact owes — VERDICT r3 #1c).

  Returns (stats_dict, state, trainer) — trainer/state reusable for a
  same-shape synthetic measurement without recompiling."""
  from tensor2robot_tpu import modes
  from tensor2robot_tpu.data.default_input_generator import (
      DefaultRecordInputGenerator)
  from tensor2robot_tpu.data.prefetch import prefetch_to_device
  from tensor2robot_tpu.parallel import mesh as mesh_lib
  from tensor2robot_tpu.train.trainer import Trainer

  mesh = mesh_lib.create_mesh()
  trainer = Trainer(model, mesh=mesh, seed=0)
  state = trainer.create_train_state(batch_size=batch_size)
  gen = DefaultRecordInputGenerator(
      file_patterns=path, batch_size=batch_size, seed=0,
      num_pipeline_threads=max(1, os.cpu_count() or 1),
      native_mode="auto")
  gen.set_specification_from_model(model, modes.TRAIN)

  def fresh_batches():
    return prefetch_to_device(
        gen.create_dataset_fn(modes.TRAIN)(),
        sharding=trainer.batch_sharding)

  # Compile once (outside all timed trials).
  batches = fresh_batches()
  features, labels = next(batches)
  state, metrics = trainer.train_step(state, features, labels)
  float(metrics["loss"])
  batches.close()

  cold, steady, calibrations = [], [], []
  for _ in range(trials):
    batches = fresh_batches()
    calibrations.append(
        gen.pipeline_stats.get("native_calibration", {}))
    step_times = []
    start = time.perf_counter()
    for _ in range(n_steps):
      t0 = time.perf_counter()
      features, labels = next(batches)
      state, metrics = trainer.train_step(state, features, labels)
      float(metrics["loss"])  # sync per step so step_times are real
      step_times.append(time.perf_counter() - t0)
    elapsed = time.perf_counter() - start
    batches.close()
    tail = step_times[-max(n_steps // 3, 3):]
    cold.append(n_steps / elapsed)
    steady.append(1.0 / (sum(tail) / len(tail)))
  stats = {
      "cold_steps_per_sec": _spread(cold, 2),
      "steady_steps_per_sec": _spread(steady, 2),
      "auto_calibration_per_trial": calibrations,
  }
  return stats, state, trainer


def _bench_input_pipeline(batch_size: int, synthetic_headline_sps: float):
  """records/sec (native/python arms, interleaved trials), decode-only
  rates, record-fed training for the JPEG and raw-uint8 wires, H2D
  bandwidth. Every claim-bearing field carries spread; the default data
  path is auto-calibrated per pipeline and the decisions are recorded."""
  import tempfile

  from tensor2robot_tpu import modes
  from tensor2robot_tpu.research.qtopt.t2r_models import QTOptGraspingModel

  num_records = 384
  model = QTOptGraspingModel()
  image_size = model._in_image_size
  out = {"host_cpu_cores": os.cpu_count(), "record_batch_size": batch_size}

  with tempfile.TemporaryDirectory() as tmp:
    jpeg_path = os.path.join(tmp, "bench.tfrecord")
    _make_jpeg_dataset(jpeg_path, num_records, image_size)
    out["jpeg_bytes_per_record"] = round(
        os.path.getsize(jpeg_path) / num_records)

    out.update(_records_per_sec_trials(model, jpeg_path, batch_size))
    out["native_note"] = (
        "native = C++ TFRecord framing + CRC32C + whole-batch parse + "
        "libjpeg decode; python = pure-Python CRC + per-record parse + "
        "PIL, both pinned via native_mode (no env toggling). Arms "
        "interleaved with alternating order, fresh pipeline per trial; "
        "read this run's decode_only fields for the decode-only split. "
        "The production default is native_mode='auto': each pipeline "
        "times one batch both ways at startup and pins its own winner "
        "(decisions recorded under record_fed_jpeg."
        "auto_calibration_per_trial).")

    from tensor2robot_tpu.data.tfrecord import read_tfrecords
    from tensor2robot_tpu.data.example_proto import decode_example
    some_records = []
    for record in read_tfrecords(jpeg_path):
      some_records.append(decode_example(record)["image"][0])
      if len(some_records) >= 8:
        break
    out["decode_only"] = _decode_only_trials(some_records)

    # Sustained record-fed training on both wire formats, auto-selected
    # data path, spread over fresh-pipeline trials.
    jpeg_stats, _, jpeg_trainer = _record_fed_rates(
        model, jpeg_path, batch_size)
    out["record_fed_jpeg"] = jpeg_stats

    raw_path = os.path.join(tmp, "bench_raw.tfrecord")
    _make_raw_uint8_dataset(raw_path, num_records, image_size)
    raw_model = QTOptGraspingModel(uint8_images=True, wire_format="raw")
    raw_stats, raw_state, raw_trainer = _record_fed_rates(
        raw_model, raw_path, batch_size)
    out["record_fed_uint8"] = raw_stats
    # Ratio on the STEADY medians: cold rates are dominated by one-time
    # pipeline fill and scale with the protocol's n_steps (review r3) —
    # only the sustained rates compare wire formats.
    out["uint8_vs_jpeg_record_fed_steady"] = round(
        raw_stats["steady_steps_per_sec"]["median"]
        / max(jpeg_stats["steady_steps_per_sec"]["median"], 1e-9), 2)

    # Synthetic-fed at the SAME single-step dispatch, same (uint8)
    # model, so the fraction below is like-for-like (ADVICE r3: the r3
    # key divided a uint8 cold rate by a float32-model synthetic rate —
    # mixed model AND mixed basis).
    sfeat, slab = _zeros_batch(raw_model, batch_size, modes.TRAIN)
    sfeat, slab = raw_trainer.shard_batch((sfeat, slab))
    state, metrics = raw_trainer.train_step(raw_state, sfeat, slab)
    float(metrics["loss"])
    n_steps = 10
    start = time.perf_counter()
    for _ in range(n_steps):
      state, metrics = raw_trainer.train_step(state, sfeat, slab)
    float(metrics["loss"])
    elapsed = time.perf_counter() - start
    synthetic_k1_uint8 = n_steps / elapsed
    out["synthetic_steps_per_sec_k1_uint8_model"] = round(
        synthetic_k1_uint8, 2)
    out["record_fed_uint8_steady_fraction_of_k1"] = round(
        raw_stats["steady_steps_per_sec"]["median"] / synthetic_k1_uint8,
        3)

    # H2D bandwidth of one float32 feature batch (remote-tunnel path).
    one_batch = np.zeros((batch_size, image_size, image_size, 3),
                         np.float32)
    jax.block_until_ready(jax.device_put(one_batch))  # warm path
    start = time.perf_counter()
    jax.block_until_ready(jax.device_put(one_batch))
    h2d = one_batch.nbytes / (time.perf_counter() - start)
    out["h2d_gbps"] = round(h2d / 1e9, 3)
    native_median = out["jpeg_records_per_sec_native"]["median"]
    out["note"] = (
        "record-fed throughput on this box is bounded by container "
        "artifacts, not pipeline design: a "
        f"{os.cpu_count()}-core host (decode+parse scale ~linearly "
        "with cores; feeding "
        f"~{round(synthetic_headline_sps)} img/sec needs "
        f"~{round(synthetic_headline_sps / max(native_median, 1))} "
        "cores at this run's per-core JPEG rate — real TPU hosts have "
        f"~100+) and a {h2d / 1e9:.2f} GB/s tunnel H2D (real hosts: "
        "tens of GB/s). The raw-uint8 wire removes decode entirely and "
        "cuts wire bytes 4x vs float32 — its measured steady multiple "
        "over the JPEG/float path (uint8_vs_jpeg_record_fed_steady) is "
        "the design margin this box can demonstrate.")
  return out


def _bench_serving_compact(trials=3, control_steps=10, image_size=None):
  """Compact fused-CEM serving measurement for the bench detail.

  VERDICT r5 Weak #4 / Next #3: the serving control rate lived only in
  bin/bench_serving, which the driver never runs — so a driver-only
  chip window refreshed throughput but left the serving number stale
  another round. This measures the single-robot closed loop (CEMPolicy:
  one fused control step per frame — sample, score, elite-refit — 64
  samples x 3 iterations) for both wire formats, with the
  {median,min,max,trials} spread shape every citable field carries.
  The fleet sweep (micro-batching, bucket ladder, p50/p99) remains
  bin/bench_serving's job; this block is the driver-path sentinel.

  `image_size` shrinks the model so the chipless orchestrator tests
  can exercise the block's shape contract on CPU.
  """
  from tensor2robot_tpu.predictors.checkpoint_predictor import (
      CheckpointPredictor)
  from tensor2robot_tpu.research.qtopt.cem import CEMPolicy
  from tensor2robot_tpu.research.qtopt.t2r_models import QTOptGraspingModel

  rng = np.random.default_rng(0)
  out = {}
  for uint8_images in (False, True):
    kwargs = {"uint8_images": uint8_images}
    if image_size:
      kwargs.update(image_size=image_size, in_image_size=image_size)
    model = QTOptGraspingModel(**kwargs)
    predictor = CheckpointPredictor(model)
    predictor.init_randomly()
    policy = CEMPolicy(predictor, action_size=4, num_samples=64,
                       num_elites=6, iterations=3, seed=0)
    size = model.get_feature_specification("train")["image"].shape[0]

    def make_frame():
      if uint8_images:
        return rng.integers(0, 255, (size, size, 3), np.uint8)
      return rng.random((size, size, 3)).astype(np.float32)

    # Fresh frames per step: the robot loop pays H2D for every camera
    # image; reusing one frame would hide exactly that cost.
    frames = [make_frame() for _ in range(control_steps)]
    jax.block_until_ready(policy(frames[0]))  # compile the control step
    rates = []
    for _ in range(max(1, trials)):
      start = time.perf_counter()
      for image in frames:
        jax.block_until_ready(policy(image))
      rates.append(control_steps / (time.perf_counter() - start))
    out["uint8" if uint8_images else "float32"] = {
        "closed_loop_hz": _spread(rates, 1),
        "closed_loop_ms": _spread([1e3 / r for r in rates], 2),
        "image_bytes": int(frames[0].nbytes),
    }
  out["note"] = (
      "single-robot fused CEM control step (64 samples x 3 "
      "iterations), closed loop on fresh frames, both wire formats; "
      "measured inside bench.py so every driver bench run refreshes "
      "serving evidence. The fleet micro-batching sweep stays in "
      "bin/bench_serving --fleet.")
  return out


def _bench_actor_compact():
  """Actor-throughput block for the bench detail (ISSUE 5).

  Same driver-refreshable rationale as the serving and learner blocks:
  the committed replay artifact (REPLAY_SMOKE_r0N.json) carries the
  chipless actor comparison, but a driver-only chip window should still
  re-measure the vector-vs-threaded acting ratio and the
  acting/learning overlap fraction on the real host+chip pair. Runs
  replay/actor_bench's collector-only comparison (one shared TinyQ
  predictor, same CEM hyperparameters, same total env count on both
  paths; the threaded scalar collectors ARE the measured fallback);
  every citable field carries the {median,min,max,trials} spread.
  """
  from tensor2robot_tpu.replay.actor_bench import measure_actor_throughput
  return measure_actor_throughput()


def _bench_anakin_compact():
  """Anakin-throughput block for the bench detail (ISSUE 6).

  Same driver-refreshable rationale as the serving/learner/actor
  blocks: the committed replay artifact (REPLAY_SMOKE_r0N.json)
  carries the chipless fused-vs-fleet comparison, but a driver-only
  chip window should re-measure the fused act->step->extend->learn
  executable against the numpy vector fleet on the real host+chip
  pair. Runs replay/anakin_bench's comparison (same TinyQ critic, same
  CEM hyperparameters, same env count on both paths; the headline
  ratio co-schedules the megastep learner with the fleet — the r08
  production shape — and the collect-only ratio rides along); every
  citable field carries the {median,min,max,trials} spread, and the
  block's `dtype` field is where the ROADMAP item 5 bf16 CEM tier
  lands its precision ablation.
  """
  from tensor2robot_tpu.replay.anakin_bench import (
      measure_anakin_throughput)
  return measure_anakin_throughput()


def _bench_anakin_multichip_compact():
  """Pod-scale Anakin scaling block for the bench detail (ISSUE 7).

  The committed chipless artifact (MULTICHIP_r06.json) carries the
  1/2/4/8 VIRTUAL-device ladder, where efficiency measures XLA
  partitioning overhead, not pod speedup (its `virtual_mesh` caveat).
  This block is the driver-refreshable real-chip counterpart: on a
  multi-chip window it re-runs the fused executable over every
  power-of-two mesh the hardware offers at a fixed global workload —
  per-device transitions/s plus scaling efficiency vs the 1-device
  run, with `probed_device_kind` naming the silicon. On a single chip
  the ladder honestly collapses to [1] (structure still asserted).
  """
  from tensor2robot_tpu.replay.anakin_multichip_bench import (
      measure_anakin_multichip)
  return measure_anakin_multichip()


def _bench_fleet_compact():
  """Fleet-serving block for the bench detail (ISSUE 10).

  Same driver-refreshable rationale as the serving block: the
  committed FLEET_r11.json carries the chipless 128-client protocol
  (8-virtual-device mesh), but a driver-only chip window should still
  re-measure the routed fleet — SLO classes under open-loop Poisson
  load, the deterministic overload burst, both rollout cycles, and the
  one-executable-per-bucket-PER-DEVICE ledger — on whatever devices
  the window offers (a single chip honestly collapses to 1 replica).
  Reduced clients/windows: this is the driver-path sentinel, the full
  sweep stays serving/fleet_bench's job. CPU-probe results never reach
  this block: the orchestrator's cpu_fallback guard (PR 1 convention)
  rejects a CPU claim before main() runs.
  """
  from tensor2robot_tpu.serving.fleet_bench import R11_CLASSES, measure_fleet
  return measure_fleet(
      classes=tuple((slo_class, max(4, clients // 4), hz)
                    for slo_class, clients, hz in R11_CLASSES),
      load_multipliers=(1.0,), duration_s=2.0, max_queue=32,
      rollout_cycle_s=5.0, rollout_mirror=1.0, rollout_canary=0.5,
      rollout_min_shadow=8, rollout_min_canary=4)


def _bench_obs_compact():
  """Observability block for the bench detail (ISSUE 11 + 12).

  The committed chipless artifact (OBS_r13.json) carries the full
  protocol on the 8-virtual-device mesh, where estimated_mfu is
  honestly null (no CPU peak model). This block is the
  driver-refreshable real-chip counterpart: a reduced run of the same
  phases (fused replay attribution, host-loop stage spans, routed
  serve window + injected breach, watchdog controls, the aggregator
  self-check whose hosts_merged/stall counts feed the round-13 compact
  keys) on the window's real devices, where the per-executable
  estimated-MFU column becomes a measured number against the chip's
  known peak. Same schema as the artifact.
  """
  from tensor2robot_tpu.obs.obs_bench import measure_obs
  return measure_obs(replay_steps=40, host_steps=12,
                     serve_duration_s=1.0)


def _bench_precision_compact():
  """Precision-tier block for the bench detail (ISSUE 13).

  The committed chipless artifact (PRECISION_r14.json) carries the
  full parity protocol — selected-action q-agreement across the bucket
  ladder on a trained critic, fused-loop TD bars per tier, the
  per-tier exactly-once ledger, and the bf16-tier rollout gate — where
  bf16 is CPU-emulated and the compact speedup is honestly null. This
  block is the driver-refreshable real-chip counterpart: a reduced run
  of the same phases on the window's devices, where
  `cem_bf16_speedup` becomes a measured MXU number (bf16 matmuls on
  the native path vs the f32 oracle executables) — the queued
  measurement ISSUE 13 lands when the pool returns.
  """
  from tensor2robot_tpu.replay.precision_bench import measure_precision
  return measure_precision(
      buckets=(1, 2, 4, 8), corpus_scenes=32, pretrain_steps=150,
      loop_steps=60, rollout_min_shadow=6, rollout_min_canary=3,
      rollout_cycle_s=60.0, enforce_bars=False)


def _bench_faults_compact():
  """Fault-tolerance block for the bench detail (ISSUE 14).

  The committed chipless artifact (FAULTS_r15.json) carries the full
  chaos protocol — scripted replica faults under paced traffic with
  the quarantine→probe→reinstate arc, degraded-mode shedding,
  dispatcher restart budgets, export-corruption rejection, and the
  learner's bit-exact crash-resume — where recovery LATENCY numbers
  carry the virtual-mesh caveat. This block is the driver-refreshable
  real-chip counterpart: a reduced run of the same phases on the
  window's devices, where post-quarantine p99 re-convergence becomes
  a measured chip number. The live kill-resume run is skipped here
  (minutes of loop time; the committed artifact carries it) — the
  deterministic bit-parity resume and every router/dispatcher/export
  phase run in full.
  """
  from tensor2robot_tpu.serving.fault_bench import (R15_CLASSES,
                                                    measure_faults)
  return measure_faults(
      classes=tuple((slo_class, max(2, clients // 2), hz)
                    for slo_class, clients, hz in R15_CLASSES),
      chaos_s=3.0, recovery_s=2.0, parity_steps=(15, 15),
      live_resume=False, enforce_bars=False)


def _bench_health_compact():
  """Training-health sentinel block for the bench detail (ISSUE 15).

  The committed chipless artifact (HEALTH_r16.json) carries the full
  protocol — the instrumented fused loop's ledger-stability A/B,
  every injected numeric corruption (nan_grads through anakin,
  value_scale through the host loop, corrupt_served_variables against
  a live router) detected within its rule's window, the fleet Q-drift
  aggregate rollup, and the zero-false-positive healthy controls —
  where detection LATENCY carries the virtual-mesh caveat. This block
  is the driver-refreshable real-chip counterpart: a reduced run of
  the same phases on the window's devices, where the in-program
  summary's cost and the detection latency become chip numbers.
  """
  from tensor2robot_tpu.obs.health_bench import measure_health
  return measure_health(
      ledger_mesh_axis=1, ledger_dispatches=2, nan_steps=40,
      nan_inject_at=10, scale_steps=30, scale_inject_at=15,
      fleet_requests=120, control_steps=15, enforce_bars=False)


def _bench_tpquant_compact():
  """TP + int8 block for the bench detail (ISSUE 16).

  The committed chipless artifact (TPQUANT_r17.json) carries the full
  protocol — the flagship conv tower through ONE fused anakin_step at
  tp=1/2/4/8 with rule-derived partition specs (leaf shardings and
  per-replica bytes asserted, tp=1 the bitwise oracle), the int8
  served-weights tier's q-oracle agreement + per-tier ledger + >= 3x
  served-bytes reduction, and the int8 promotion gate with an
  injected-breach auto-rollback — where every RATE carries the
  virtual-mesh caveat. This block is the driver-refreshable real-chip
  counterpart: a reduced ladder on the window's devices, where
  tp_scaling_efficiency becomes a measured chip number instead of the
  chipless null.
  """
  from tensor2robot_tpu.replay.tpquant_bench import measure_tpquant
  return measure_tpquant(
      tp_ladder=(1, 2, 4), ladder_steps=2, buckets=(1, 4),
      corpus_scenes=32, pretrain_steps=150, rollout_devices=None,
      rollout_min_shadow=6, rollout_min_canary=3,
      rollout_cycle_s=60.0, enforce_bars=False)


def _bench_flywheel_compact():
  """Data-flywheel block for the bench detail (ISSUE 18).

  The committed chipless artifact (FLYWHEEL_r18.json) carries the full
  protocol — the spec-validated ingest gate refusing malformed served
  episodes by field name, the closed serve→collect→train→redeploy loop
  (synthetic collectors retired at cutover, >= 2 live promote cycles
  mid-run, per-transition correlation ids reconciled against the
  router's logical-request counter, staleness/coverage/mix interlock
  green) and the stale-params control whose severed export path MUST
  breach — where improvement and cycle ORDERING are the chipless
  claims. This block is the driver-refreshable real-chip counterpart:
  a reduced loop on the window's devices, where serving and ingest
  THROUGHPUT become chip numbers instead of the chipless caveat.
  """
  from tensor2robot_tpu.flywheel.flywheel_bench import measure_flywheel
  return measure_flywheel(
      warm_steps=16, fleet_steps=30, export_every=15,
      control_fleet_steps=60, enforce_bars=False)


def _bench_multihost_compact():
  """Pod-scale bring-up block for the bench detail (ISSUE 19).

  The committed chipless artifact (MULTIHOST_r19.json) carries the full
  protocol — 2 REAL processes x 4 virtual CPU devices through the JAX
  coordination service running ONE anakin_step with exactly-once
  per-process compile ledgers, the seam-vs-r17-oracle single-process
  bit-parity pair, kill-one-process fused checkpoint resume with the
  post-resume stream parity bar, and the router-of-routers front door
  (1:1 ingress reconciliation, drift-rollup cross-host quarantine by
  name) — where throughput/scaling keys are null by the virtual-mesh
  honesty rule. This block is the driver-refreshable counterpart at
  reduced scale: the front-door phase runs on the window's devices
  (the per-class p99 headroom becomes a measured serving number), and
  the 2-process bring-up + kill-one-process resume re-run live in CPU
  worker subprocesses (the learner phases emulate controllers, so they
  measure structure on any host — a single-chip window cannot host two
  REAL controllers, which is why their throughput stays null).
  """
  import tempfile
  from tensor2robot_tpu.parallel.multihost_bench import (
      measure_frontdoor, measure_fused_resume, measure_mesh_bringup)
  with tempfile.TemporaryDirectory() as workdir:
    bringup = measure_mesh_bringup(
        os.path.join(workdir, "bringup"), seed=0, num_steps=10,
        checkpoint_dir=os.path.join(workdir, "ckpt"), enforce_bars=False)
    control = bringup.pop("control_workers")
    resume = measure_fused_resume(
        os.path.join(workdir, "resume"), seed=0, num_steps=10,
        control_workers=control, enforce_bars=False)
  frontdoor = measure_frontdoor(seed=0, requests=120, enforce_bars=False)
  return {
      "mesh_bringup": bringup,
      "fused_resume": resume,
      "frontdoor": frontdoor,
      "multihost_processes": (bringup.get("processes")
                              if all(bringup.get("bars", {}).values())
                              else None),
      "fused_resume_parity_ok": resume.get("fused_resume_parity_ok"),
      "frontdoor_p99_headroom": frontdoor.get("frontdoor_p99_headroom"),
  }


def _bench_sebulba_compact():
  """Sebulba decoupled tier for the bench detail (ISSUE 20).

  The committed chipless artifact (SEBULBA_r20.json) carries the full
  protocol — 2 REAL CEM actor processes streaming fixed-shape chunks
  through the spool transport + bounded TransitionQueue into the
  2-device sharded learner behind the double-buffered device_put
  prefetch seam, the serialized one-process oracle bit-parity pair
  (params AND megastep metric stream), and the kill-one-actor
  watchdog -> quarantine -> probe -> reinstate run with zero learner
  recompiles — where throughput keys are null by the virtual-mesh
  honesty rule. This block is the driver-refreshable counterpart at
  reduced scale: synthetic actors (numpy-only subprocesses, so the
  decoupled structure re-runs live on any host) with bars deferred to
  the compact sentinels. The learner itself needs two local devices
  to shard across; a single-chip window reports the skip honestly.
  """
  import tempfile
  from tensor2robot_tpu.parallel.sebulba_bench import (
      measure_actor_outage, measure_decoupled_overlap)
  if len(jax.devices()) < 2:
    return {"skipped": "sharded Sebulba learner needs >= 2 local "
                       "devices; committed artifact: SEBULBA_r20.json"}
  with tempfile.TemporaryDirectory() as workdir:
    overlap = measure_decoupled_overlap(
        os.path.join(workdir, "overlap"), seed=0, enforce_bars=False,
        synthetic=True, num_megasteps=3)
    outage = measure_actor_outage(
        os.path.join(workdir, "outage"), seed=0, enforce_bars=False)
  return {
      "decoupled_overlap": overlap,
      "actor_outage": outage,
      "sebulba_actor_processes": (
          2 if all(value is not False
                   for value in overlap.get("bars", {}).values())
          else None),
      "sebulba_oracle_bit_identical": overlap.get(
          "params_parity", {}).get("bit_identical"),
      "sebulba_outage_reinstated": (
          all(value is not False
              for value in outage.get("bars", {}).values()) or None),
      "sebulba_overlap_fraction": overlap.get(
          "overlap", {}).get("overlap_fraction"),
  }


def _bench_learner_compact():
  """Learner-throughput block for the bench detail (ISSUE 4).

  The device-resident megastep's claim — one donated executable per K
  optimizer steps instead of four dispatches + host replay work per
  step — is a DRIVER-refreshable measurement, same rationale as the
  serving block: the full loop artifact (REPLAY_SMOKE_r0N.json) is
  chipless and builder-committed, but a driver-only chip window should
  still re-measure the fused-vs-host learner ratio on the real chip.
  Runs replay/learner_bench's collector-free comparison (TinyQ critic,
  both paths at ONE batch shape, single-device mesh per-chip basis);
  every citable field carries the {median,min,max,trials} spread.
  """
  from tensor2robot_tpu.replay.learner_bench import (
      measure_learner_throughput)
  return measure_learner_throughput()


def main() -> None:
  from tensor2robot_tpu.research.qtopt.t2r_models import QTOptGraspingModel

  parity_batch = QTOptGraspingModel.benchmark_batch_size  # 32
  k = ITERATIONS_PER_LOOP
  device_kind = jax.devices()[0].device_kind
  peak = _chip_peak(device_kind)

  # --- reference-parity line (comparable with earlier rounds) ---------
  parity_sps, parity_flops, parity_bench = _measure_config(
      QTOptGraspingModel(), parity_batch, k)
  flops_source = "xla_cost_analysis"
  if not parity_flops:
    # ADVICE r2: a cost-analysis failure must not null the contract
    # keys — fall back to the documented analytic count (a batch-32
    # figure, scaled: conv FLOPs are linear in batch), loudly.
    parity_flops = ANALYTIC_PARITY_FLOPS_B32 * parity_batch / 32
    flops_source = "analytic_fallback(cost_analysis failed)"
  flops_per_image = parity_flops / parity_batch

  # --- steady state (dispatch overhead removed, methodology named) ----
  # Runs immediately after the parity measurement so the k=60
  # executable is reused, then ALL parity device buffers are dropped
  # before the batch-128 allocations (the 16 GB HBM cannot hold both
  # stacked batches at once).
  parity_marginal, overhead_ms = _steady_state(
      QTOptGraspingModel(), parity_batch, 20, k, big_bench=parity_bench)
  del parity_bench

  # --- per-piece budget of the parity step (VERDICT r3 #3) ------------
  # Evidence sections are individually fail-safe: the driver contract
  # line must print even if one section dies on a flaky tunnel — the
  # error is recorded in the artifact, never swallowed.
  try:
    step_budget = _step_budget(parity_marginal)
  except Exception as e:
    step_budget = {"error": f"{type(e).__name__}: {e}"}

  # --- headline operating point (stated): batch 128, uint8 wire ------
  headline_batch = HEADLINE_BATCH
  headline_model = QTOptGraspingModel(uint8_images=True)
  headline_sps, headline_flops, _ = _measure_config(
      headline_model, headline_batch, k)
  headline_img_s = headline_sps * headline_batch

  # --- derived per-image A100 bar -------------------------------------
  ideal_img_s = A100_FP32_FLOPS / flops_per_image
  fork_estimate_img_s = ideal_img_s * FORK_FP32_CONV_EFFICIENCY
  fork_typical_img_s = ideal_img_s * FORK_TYPICAL_E2E_EFFICIENCY
  vs_baseline = round(headline_img_s / fork_estimate_img_s, 2)

  # --- variants --------------------------------------------------------
  variants = {}
  try:
    v_f32_128, _, _ = _measure_config(QTOptGraspingModel(), 128, 15,
                                      warmup=1, measure=2)
    variants["float32_wire_b128_k15"] = {
        "steps_per_sec_per_chip": v_f32_128,
        "images_per_sec_per_chip": round(v_f32_128 * 128),
        "note": "float32 wire caps k at 15 (stacked batch is 4x "
                "larger); the uint8 headline's margin over this line "
                "is wire traffic + dispatch amortization, same conv "
                "math"}
    v_s2d, _, _ = _measure_config(
        QTOptGraspingModel(uint8_images=True, stem="space_to_depth"),
        headline_batch, k, warmup=1, measure=2)
    variants["s2d_folded_stem_b128_uint8"] = {
        "steps_per_sec_per_chip": v_s2d,
        "images_per_sec_per_chip": round(v_s2d * headline_batch),
        "note": "folded space-to-depth stem (ops/stem_conv.py): faster "
                "in stem isolation (see ops/stem_conv.py provenance "
                "notes) but e2e-neutral at this operating point — "
                "recorded honestly"}
    # impl="fast" (ops/pool.py reshape pool + ops/strided_conv.py
    # folded strided convs): same function and checkpoint layout as
    # parity — these variants answer, end to end, whether the budget's
    # piece-level candidates buy real step time.
    v_fast_b32, _, _ = _measure_config(
        QTOptGraspingModel(impl="fast"), parity_batch, k,
        warmup=1, measure=2)
    variants["parity_b32_fast_impl"] = {
        "steps_per_sec_per_chip": v_fast_b32,
        "vs_baseline_steps_basis": round(
            v_fast_b32 / (fork_estimate_img_s / parity_batch), 2),
        "note": "identical math to parity_b32 (impl='fast': reshape "
                "max pool + lanes-folded strided convs); compare "
                "steps_per_sec with parity_b32 to read the win"}
    v_fast_headline, _, _ = _measure_config(
        QTOptGraspingModel(uint8_images=True, impl="fast"),
        headline_batch, k, warmup=1, measure=2)
    variants["headline_fast_impl_b128_uint8"] = {
        "steps_per_sec_per_chip": v_fast_headline,
        "images_per_sec_per_chip": round(
            v_fast_headline * headline_batch),
        "note": "headline operating point with impl='fast'"}
  except Exception as e:
    variants["error"] = f"{type(e).__name__}: {e}"

  try:
    microbench = _microbench_convs()
  except Exception as e:
    microbench = {"error": f"{type(e).__name__}: {e}"}

  try:
    input_pipeline = _bench_input_pipeline(parity_batch, headline_img_s)
  except Exception as e:
    input_pipeline = {"error": f"{type(e).__name__}: {e}"}

  try:
    serving = _bench_serving_compact()
  except Exception as e:
    serving = {"error": f"{type(e).__name__}: {e}"}

  try:
    fleet = _bench_fleet_compact()
  except Exception as e:
    fleet = {"error": f"{type(e).__name__}: {e}"}

  try:
    learner = _bench_learner_compact()
  except Exception as e:
    learner = {"error": f"{type(e).__name__}: {e}"}

  try:
    actor = _bench_actor_compact()
  except Exception as e:
    actor = {"error": f"{type(e).__name__}: {e}"}

  try:
    anakin = _bench_anakin_compact()
  except Exception as e:
    anakin = {"error": f"{type(e).__name__}: {e}"}

  try:
    anakin_multichip = _bench_anakin_multichip_compact()
  except Exception as e:
    anakin_multichip = {"error": f"{type(e).__name__}: {e}"}

  try:
    obs = _bench_obs_compact()
  except Exception as e:
    obs = {"error": f"{type(e).__name__}: {e}"}

  try:
    precision = _bench_precision_compact()
  except Exception as e:
    precision = {"error": f"{type(e).__name__}: {e}"}

  try:
    faults = _bench_faults_compact()
  except Exception as e:
    faults = {"error": f"{type(e).__name__}: {e}"}

  try:
    health = _bench_health_compact()
  except Exception as e:
    health = {"error": f"{type(e).__name__}: {e}"}

  try:
    tpquant = _bench_tpquant_compact()
  except Exception as e:
    tpquant = {"error": f"{type(e).__name__}: {e}"}

  try:
    flywheel = _bench_flywheel_compact()
  except Exception as e:
    flywheel = {"error": f"{type(e).__name__}: {e}"}

  try:
    multihost = _bench_multihost_compact()
  except Exception as e:
    multihost = {"error": f"{type(e).__name__}: {e}"}

  try:
    sebulba = _bench_sebulba_compact()
  except Exception as e:
    sebulba = {"error": f"{type(e).__name__}: {e}"}

  mfu = None
  if peak and headline_flops:
    # headline flops from its own executable (uint8 variant's math).
    mfu = round(headline_flops * headline_sps / peak, 4)
  parity_mfu = None
  parity_steady_mfu = None
  if peak and parity_flops:
    parity_mfu = round(parity_flops * parity_sps / peak, 4)
    if parity_marginal["median"]:
      parity_steady_mfu = round(
          parity_flops / (parity_marginal["median"] * 1e-3) / peak, 4)

  detail = {
      "round": ROUND,
      "device_kind": device_kind,
      "iterations_per_loop": k,
      "headline": {
          "operating_point": f"batch {headline_batch}, uint8 wire, "
                             f"k={k}, parity architecture (BatchNorm, "
                             "6x6 conv stem)",
          "images_per_sec_per_chip": round(headline_img_s),
          "steps_per_sec_per_chip": headline_sps,
          "mfu": mfu,
          "flops_per_step": round(headline_flops),
      },
      "parity_b32": {
          "steps_per_sec_per_chip": parity_sps,
          "images_per_sec_per_chip": round(parity_sps * parity_batch),
          "mfu_naive": parity_mfu,
          "steady_state_ms_per_step": parity_marginal,
          "steady_state_steps_per_sec": round(
              1e3 / parity_marginal["median"], 1),
          "mfu_steady": parity_steady_mfu,
          "per_call_dispatch_overhead_ms": overhead_ms,
          "flops_per_step": round(parity_flops),
          "flops_source": flops_source,
          "vs_baseline_steps_basis": round(
              parity_sps / (fork_estimate_img_s / parity_batch), 2),
      },
      "step_budget_parity_b32": step_budget,
      "baseline": {
          "kind": "derived-a100-fp32-compute-roofline, per-image",
          "flops_per_image": round(flops_per_image),
          "a100_ideal_bound_img_per_sec": round(ideal_img_s),
          "a100_fork_estimate_img_per_sec": round(fork_estimate_img_s),
          "a100_fork_typical_img_per_sec": round(fork_typical_img_s),
          "assumptions": _BASELINE_ASSUMPTIONS,
      },
      "vs_a100_ideal_bound": round(headline_img_s / ideal_img_s, 2),
      "vs_fork_typical": round(headline_img_s / fork_typical_img_s, 2),
      "conv_microbench": microbench,
      "variants": variants,
      "input_pipeline": input_pipeline,
      "serving": serving,
      "fleet": fleet,
      "learner": learner,
      "actor": actor,
      "anakin": anakin,
      "anakin_multichip": anakin_multichip,
      "obs": obs,
      "precision": precision,
      "faults": faults,
      "health": health,
      "tpquant": tpquant,
      "flywheel": flywheel,
      "multihost": multihost,
      "sebulba": sebulba,
  }
  with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         DETAIL_FILE), "w") as f:
    json.dump(detail, f, indent=2)

  print(json.dumps({
      "metric": _METRIC_NAME,
      "value": round(headline_img_s),
      "unit": "images/sec/chip",
      "vs_baseline": vs_baseline,
      "vs_baseline_tier": "a100_fork_estimate (conservative x0.5)",
      "parity_b32_steps_per_sec": parity_sps,
      "mfu": mfu,
      "flops_per_image": round(flops_per_image),
      "record_fed_uint8_steps_per_sec": input_pipeline.get(
          "record_fed_uint8", {}).get(
              "cold_steps_per_sec", {}).get("median"),
      "learner_megastep_speedup": learner.get(
          "speedup", {}).get("median"),
      "actor_fleet_speedup": actor.get(
          "speedup", {}).get("median"),
      "anakin_env_steps_speedup": anakin.get(
          "speedup", {}).get("median"),
      # Fleet-serving sentinels (ISSUE 10): min per-class p99 headroom
      # at the block's top offered-load point, and the client count it
      # sustained with every class inside budget. Null-safe under
      # outage/error like every compact key.
      "fleet_p99_headroom": fleet.get("fleet_p99_headroom"),
      "fleet_clients_sustained": fleet.get("fleet_clients_sustained"),
      # A single-entry ladder (1-chip window) scores 1.0 against itself
      # by construction — publish null rather than fake linear scaling.
      "anakin_multichip_scaling_efficiency": (
          (anakin_multichip.get("scales") or [{}])[-1].get(
              "scaling_efficiency_vs_1dev")
          if len(anakin_multichip.get("scales") or []) > 1 else None),
      # Obs sentinel (ISSUE 11): the fused replay executable's measured
      # device-time share of its run window. Null-safe under error.
      "obs_anakin_step_share": next(
          (row.get("device_time_share")
           for row in (obs.get("replay", {}).get("attribution", {})
                       .get("executables") or [])
           if row.get("name") == "anakin_step"), None),
      # Fleet-obs sentinels (ISSUE 12): how many per-process streams
      # the obs block's aggregator pass merged, and how many watchdog
      # stalls its injected-stall control raised (exactly 1 when the
      # watchdog works: the injection fires, the healthy control stays
      # silent). Null-safe under outage/error like every compact key.
      "fleetobs_hosts_merged": obs.get("fleetobs", {}).get(
          "hosts_merged"),
      "watchdog_stalls": obs.get("watchdog", {}).get(
          "injected_stall", {}).get("events"),
      # Precision-tier sentinels (ISSUE 13): the bf16 tier's
      # selected-action q-agreement vs the f32 oracle (meaningful on
      # any backend — numerics, not timing) and its measured scoring
      # speedup (a CHIP claim: null on a virtual mesh by the block's
      # own honesty rule, measured on a real window). Null-safe under
      # outage/error like every compact key.
      "cem_bf16_action_agreement": precision.get(
          "cem_bf16_action_agreement"),
      "cem_bf16_speedup": precision.get("cem_bf16_speedup"),
      # Fault-tolerance sentinels (ISSUE 14): did the post-quarantine
      # clean window put every class's p99 back inside its budget, and
      # did the deterministic crash-resume reproduce the uninterrupted
      # run bit for bit. Null-safe under outage/error like every
      # compact key.
      "fault_recovery_p99_ok": faults.get("fault_recovery_p99_ok"),
      "learner_resume_parity": faults.get("learner_resume_parity"),
      # Health-sentinel sentinels (ISSUE 15): did every injected
      # numeric corruption kind get detected within its rule's window
      # (with the breach dumps schema-valid and correlated), and did
      # the fleet Q-drift guard both catch the corrupted replica and
      # stay silent on the healthy fleet. Null-safe under
      # outage/error like every compact key.
      "health_breach_detection_ok": health.get(
          "health_breach_detection_ok"),
      "fleet_q_drift_ok": health.get("fleet_q_drift_ok"),
      # TP + int8 sentinels (ISSUE 16): the flagship TP ladder's
      # measured scaling efficiency (a CHIP claim: null on a virtual
      # mesh by the block's own honesty rule, measured on a real
      # window), the int8 tier's selected-action q-agreement vs the
      # f32 oracle (numerics — meaningful on any backend), and the
      # flagship tree's int8 served-bytes reduction (structural).
      # Null-safe under outage/error like every compact key.
      "tp_scaling_efficiency": tpquant.get("tp_scaling_efficiency"),
      "int8_q_agreement": tpquant.get("int8_q_agreement"),
      "int8_param_bytes_reduction": tpquant.get(
          "int8_param_bytes_reduction"),
      # Data-flywheel sentinels (ISSUE 18): the closed loop's policy
      # improvement with synthetic collection retired at cutover (the
      # learner trained ONLY on what the fleet served — meaningful on
      # any backend: structure, not timing), and whether the ingested-
      # stream interlock held — the healthy run's staleness/coverage/
      # mix rules green AND the stale-params control breaching. Null-
      # safe under outage/error like every compact key.
      "flywheel_policy_improvement": flywheel.get(
          "flywheel_policy_improvement"),
      "flywheel_ingest_health_ok": flywheel.get(
          "flywheel_ingest_health_ok"),
      # Pod-scale sentinels (ISSUE 19): how many REAL controller
      # processes the block's live reduced bring-up spanned (null
      # unless every bring-up bar held), whether kill-one-process
      # fused resume reproduced the uninterrupted control bit for
      # bit, and the front door's min per-class p99 headroom (a
      # timing claim: null when quantitative-gated or errored).
      # Null-safe under outage/error like every compact key.
      "multihost_processes": multihost.get("multihost_processes"),
      "fused_resume_parity_ok": multihost.get("fused_resume_parity_ok"),
      "frontdoor_p99_headroom": multihost.get("frontdoor_p99_headroom"),
      # Sebulba decoupled-tier sentinels (ISSUE 20): how many REAL
      # actor processes fed the sharded learner with every structural
      # bar holding (null otherwise or when the window lacks two
      # devices), whether the live learner's params matched the
      # serialized one-process oracle bit for bit, whether the
      # kill-one-actor quarantine -> probe -> reinstate walk held
      # with zero recompiles, and the measured actor-busy/learner-wall
      # overlap fraction. Null-safe under skip/error like every
      # compact key.
      "sebulba_actor_processes": sebulba.get("sebulba_actor_processes"),
      "sebulba_oracle_bit_identical": sebulba.get(
          "sebulba_oracle_bit_identical"),
      "sebulba_outage_reinstated": sebulba.get(
          "sebulba_outage_reinstated"),
      "sebulba_overlap_fraction": sebulba.get("sebulba_overlap_fraction"),
      "device_kind": device_kind,
      "detail": DETAIL_FILE,
  }))


# --- driver-contract resilience (VERDICT r4 #1) --------------------------
# The axon pool exhibits TWO failure modes when no chip is free: an
# immediate UNAVAILABLE error from backend init, and a silent indefinite
# hang on the claim. Either one, hit in-process, breaks the ONE-JSON-LINE
# stdout contract (round 4's driver run: rc=1, parsed=null, raw
# traceback). So the default entry point is an ORCHESTRATOR that never
# touches the backend itself: it claims the chip in a bounded-timeout
# subprocess probe (retried — a successful probe exits immediately,
# returning the chip to the pool for the real run), then runs the
# measuring entry in a second bounded subprocess, and converts every
# failure — probe exhaustion, bench crash, bench hang, garbled output —
# into ONE structured, parseable JSON line on stdout with rc 0.

_METRIC_NAME = ("QTOptGraspingModel train images/sec/chip "
                f"(batch {HEADLINE_BATCH}, uint8 wire, "
                f"k={ITERATIONS_PER_LOOP})")

_PROBE_SNIPPET = "import jax; print(jax.devices()[0].device_kind)"


def _emit_error_line(error: str, **extra) -> None:
  """Failure-path stdout contract: one compact JSON line, never a
  traceback; value/vs_baseline explicitly null so the driver records a
  structured outage instead of an unparseable crash."""
  line = {
      "metric": _METRIC_NAME,
      "value": None,
      "unit": "images/sec/chip",
      "vs_baseline": None,
      "error": error,
  }
  line.update(extra)
  print(json.dumps(line))


def _probe_backend(timeout_s: float, attempts: int, sleep_s: float):
  """Claim the TPU in a killable subprocess; (device_kind|None, outcomes).

  Each attempt records "ok", "unavailable_error" (backend init raised),
  "hang_timeout" (the silent no-free-chip claim block, killed at the
  bound), or "cpu_fallback" (ADVICE r5: on a host with the axon plugin
  var unset, jax.devices() silently yields the CPU backend — a probe
  that accepted it would publish CPU numbers as the images/sec/chip
  headline). A CPU claim is rejected unless T2R_BENCH_ALLOW_CPU=1
  explicitly opts in. The probe snippet is env-overridable so the
  failure paths are testable on a box with no chip at all.
  """
  snippet = os.environ.get("T2R_BENCH_PROBE_SNIPPET", _PROBE_SNIPPET)
  allow_cpu = os.environ.get("T2R_BENCH_ALLOW_CPU") == "1"
  outcomes = []
  for attempt in range(attempts):
    try:
      res = subprocess.run(
          [sys.executable, "-c", snippet],
          capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
      outcomes.append("hang_timeout")
    else:
      if res.returncode == 0 and res.stdout.strip():
        kind = res.stdout.strip().splitlines()[-1]
        if kind.strip().lower() == "cpu" and not allow_cpu:
          # Deterministic (the plugin env var is unset, not the pool
          # flapping): retrying cannot change the answer, so don't
          # burn attempts*sleep before the error line.
          outcomes.append("cpu_fallback")
          return None, outcomes
        outcomes.append("ok")
        return kind, outcomes
      outcomes.append("unavailable_error")
    if attempt + 1 < attempts:
      time.sleep(sleep_s)
  return None, outcomes


def _extract_json_line(text: str):
  """Last stdout line that parses as a JSON object with the contract
  keys; compile logs or stray prints around it are tolerated."""
  for line in reversed(text.strip().splitlines()):
    line = line.strip()
    if not line.startswith("{"):
      continue
    try:
      obj = json.loads(line)
    except ValueError:
      continue
    if isinstance(obj, dict) and "metric" in obj and "value" in obj:
      return line
  return None


def _run_inner(timeout_s: float, attempts: int = 2,
               probed_device_kind: Optional[str] = None) -> None:
  """Run main() in a bounded subprocess and forward its contract line.

  CRASH-ONLY retry (one extra attempt after a short sleep): the probe
  succeeded moments earlier, so a crash is either deterministic (the
  retry fails identically; the error line carries BOTH attempts'
  diagnostics) or a transient pool flap (the sleep+retry rescues the
  round's only measurement). A timeout is never retried — the known
  hang mode blocks indefinitely, so a second attempt would only double
  the driver's wait for its contract line — and unparseable output is
  never retried (a formatting bug is deterministic; re-running a
  completed benchmark cannot fix it).

  `timeout_s` is a SHARED TOTAL budget across every attempt AND the
  inter-attempt sleep (ADVICE r5: per-attempt budgets made the worst
  case ~2x T2R_BENCH_INNER_TIMEOUT + sleep, undocumented): each attempt
  gets only the time remaining, and a retry whose budget the sleep
  exhausted is abandoned — so the contract line always appears within
  ~T2R_BENCH_INNER_TIMEOUT of the probe succeeding.

  The forwarded contract line (success or error) carries
  `probed_device_kind` so a driver can cross-check what chip the
  orchestrator claimed against what the inner run measured on.
  """
  snippet = os.environ.get("T2R_BENCH_INNER_SNIPPET")
  if snippet is not None:
    cmd = [sys.executable, "-c", snippet]
  else:
    cmd = [sys.executable, os.path.abspath(__file__)]
  env = dict(os.environ, T2R_BENCH_INNER="1")
  retry_sleep_s = float(os.environ.get("T2R_BENCH_RETRY_SLEEP") or 30)
  deadline = time.monotonic() + timeout_s
  crashes = []

  def _extra():
    extra = {"crashes": crashes} if crashes else {}
    if probed_device_kind is not None:
      extra["probed_device_kind"] = probed_device_kind
    return extra

  for attempt in range(max(1, attempts)):
    remaining = deadline - time.monotonic()
    if remaining <= 0:
      break
    try:
      res = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=remaining, env=env)
    except subprocess.TimeoutExpired:
      _emit_error_line("bench_timeout", timeout_s=timeout_s, **_extra())
      return
    if res.returncode != 0:
      tail = " | ".join(res.stderr.strip().splitlines()[-3:])[-400:]
      crashes.append({"returncode": res.returncode,
                      "stderr_tail": tail})
      if attempt + 1 < max(1, attempts):
        time.sleep(min(retry_sleep_s, max(0.0,
                                          deadline - time.monotonic())))
      continue
    line = _extract_json_line(res.stdout)
    if line is None:
      _emit_error_line("bench_output_unparseable", **_extra())
      return
    if probed_device_kind is not None:
      try:
        obj = json.loads(line)
        obj.setdefault("probed_device_kind", probed_device_kind)
        line = json.dumps(obj)
      except ValueError:
        pass  # _extract_json_line already vetted it; belt only
    print(line)
    return
  _emit_error_line("bench_failed", **dict(_extra(), crashes=crashes))


def _orchestrate() -> None:
  # Probe defaults bound the WORST-case (full outage) time to the error
  # line at ~6.5 min (2 x 180s + 20s): a healthy claim completes in
  # well under 180s, and an unknown driver-side timeout must see the
  # contract line, not a killed process. Longer chip-hunting loops
  # belong outside (they can re-run bench.py, which is idempotent).
  # After a successful probe, T2R_BENCH_INNER_TIMEOUT is the SHARED
  # total budget for the inner run including its crash-only retry and
  # sleep (_run_inner), so the end-to-end worst case to a contract line
  # is probe bound + one inner budget (~51.5 min at defaults), never 2x.
  probe_timeout = float(os.environ.get("T2R_BENCH_PROBE_TIMEOUT", "180"))
  attempts = int(os.environ.get("T2R_BENCH_PROBE_ATTEMPTS", "2"))
  sleep_s = float(os.environ.get("T2R_BENCH_PROBE_SLEEP", "20"))
  inner_timeout = float(
      os.environ.get("T2R_BENCH_INNER_TIMEOUT") or 45 * 60)
  kind, outcomes = _probe_backend(probe_timeout, attempts, sleep_s)
  if kind is None:
    _emit_error_line("tpu_pool_unavailable",
                     probe_attempts=outcomes,
                     probe_timeout_s=probe_timeout)
    return
  _run_inner(inner_timeout, probed_device_kind=kind)


if __name__ == "__main__":
  if os.environ.get("T2R_BENCH_INNER") == "1":
    main()
  else:
    _orchestrate()

#!/usr/bin/env bash
# Chip watcher: turn ANY TPU-pool window into the round's committed
# artifacts (VERDICT r5 Weak #1 / Next #1: the r5 watcher lived in /tmp
# and died with the container — this is the committed, durable form).
#
# Usage:  scripts/measure_round.sh [ROUND]        # default: bench.py's ROUND
#         nohup scripts/measure_round.sh >/dev/null 2>&1 &   # arm for the session
#
# Behavior:
#   - Polls the pool with a BOUNDED probe (timeout'd subprocess import of
#     jax; a CPU backend is rejected, mirroring bench.py's cpu_fallback
#     guard) every POLL_S seconds, up to MAX_HOURS.
#   - When a chip appears, runs the measurement stages in order. Each
#     stage is SKIPPED when its artifact already exists and is non-empty,
#     so a watcher restarted mid-round (or racing the driver) never
#     clobbers landed evidence and resumes where it left off.
#   - Every stage is bounded by its own timeout; a stage failure logs and
#     moves on (a flapping pool should not forfeit the other stages).
#   - Logs to the STABLE path /tmp/measure_round.log (append, stamped
#     with round + UTC time) so any session can `tail` the same file.
#
# Stages (artifact -> producer):
#   REPLAY_SMOKE_r0N.json        bin/run_qtopt_replay --smoke --anakin
#                                --mesh 8,1 (CHIPLESS backstop, runs
#                                before any chip appears; normally
#                                builder-committed and skipped — ISSUE
#                                4/5/6/7. Since r10 the smoke runs the
#                                SHARDED protocol: the fused loop over
#                                an 8-virtual-device dp mesh with
#                                ZeRO-1, mesh_shape/zero1 in the
#                                artifact. This IS the anakin-bench
#                                stage too: the anakin_throughput block
#                                carries the fused-vs-numpy-fleet env
#                                rate, host-blocked fraction, and CEM
#                                dtype)
#   MULTICHIP_r06.json           replay/anakin_multichip_bench --smoke
#                                (CHIPLESS backstop too — ISSUE 7: the
#                                fused executable at 1/2/4/8 virtual
#                                devices, fixed global workload;
#                                virtual_mesh caveat inside)
#   FLEET_r0N.json               serving/fleet_bench --smoke (CHIPLESS
#                                backstop too — ISSUE 10: SLO-class
#                                offered-load sweep at 128 clients on
#                                the 8-virtual-device mesh, overload
#                                burst, shadow/canary rollout cycles,
#                                per-device compile ledger; normally
#                                builder-committed and skipped)
#   OBS_r0N.json                 obs/obs_bench --smoke (CHIPLESS
#                                backstop too — ISSUE 11: per-
#                                executable device-time attribution
#                                over the replay-smoke protocol, the
#                                Chrome-trace stage coverage, and the
#                                injected-SLO-breach flight-recorder
#                                dump; since r13 also the watchdog
#                                controls and the aggregator self-
#                                check; normally builder-committed
#                                and skipped)
#   FLEETOBS_r0N.json            bin/obs_aggregate --smoke (CHIPLESS
#                                backstop too — ISSUE 12: >= 2 real
#                                subprocess serve loops on 8-virtual-
#                                device meshes against one shared
#                                logdir, merged into one fleet view
#                                with correlation-linked request
#                                timelines, the cross-host SLO rollup,
#                                and the watchdog stall/negative
#                                controls; normally builder-committed
#                                and skipped)
#   PRECISION_r0N.json           replay/precision_bench --smoke
#                                (CHIPLESS backstop too — ISSUE 13:
#                                bf16-vs-f32 selected-action
#                                q-agreement across the bucket ladder
#                                on a trained critic, fused-loop TD
#                                bars per tier, the per-tier
#                                exactly-once compile ledger, and the
#                                bf16-tier shadow/canary promotion
#                                gate with an injected-breach
#                                auto-rollback; bf16 is CPU-emulated
#                                chipless, so the speedup key is null
#                                — real-chip rates land via bench.py's
#                                precision block; normally
#                                builder-committed and skipped)
#   BENCH_DETAIL_r0N.json        bench.py (orchestrator; also emits the
#                                compact line, saved to BENCH_builder_r0N.json)
#   SERVING_r0N.json             bin/bench_serving single-robot + --fleet lines
#   CAPABILITY_r0N_fast.jsonl    bin/run_capability_checks --scale fast
#                                (+ vrgripper seed-offsets 1,2 for spread —
#                                VERDICT r5 #5)
#   CAPABILITY_r0N_full.jsonl    bin/run_capability_checks --scale full
#   TPU_TESTS_r0N.log            pytest tests/ --tpu (the on-chip lane)
#
# After a successful sweep, flip the matching docs/ARTIFACTS.md rows to
# `committed` and commit the artifacts (the round-start orphan sweep
# catches any the session forgot).

set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"

ROUND="${1:-$(sed -n 's/^ROUND = \([0-9]\+\)$/\1/p' bench.py)}"
RTAG=$(printf 'r%02d' "$ROUND")
LOG="${MEASURE_LOG:-/tmp/measure_round.log}"
POLL_S="${MEASURE_POLL_S:-600}"
PROBE_TIMEOUT_S="${MEASURE_PROBE_TIMEOUT_S:-150}"
MAX_HOURS="${MEASURE_MAX_HOURS:-12}"

log() { echo "[$(date -u +%FT%TZ) $RTAG] $*" >>"$LOG"; }

probe_chip() {
  # Bounded probe; a silent no-free-chip claim hangs and is killed.
  kind=$(timeout -k 5 "$PROBE_TIMEOUT_S" python -c \
    'import jax; print(jax.devices()[0].device_kind)' 2>/dev/null \
    | tail -n 1)
  [ -n "$kind" ] && [ "$(echo "$kind" | tr '[:upper:]' '[:lower:]')" != cpu ]
}

run_stage() {
  # run_stage <artifact> <timeout_s> <cmd...>: skip if landed, bound, log.
  # The command must write to $STAGE_TMP; it is moved onto the artifact
  # only on success, so a mid-stage failure/timeout can never leave a
  # truncated or partial artifact that a restarted watcher would treat
  # as landed and skip forever.
  artifact="$1"; bound="$2"; shift 2
  if [ -s "$artifact" ]; then
    log "skip $artifact (exists)"
    return 0
  fi
  STAGE_TMP="${artifact}.tmp"
  export STAGE_TMP
  rm -f "$STAGE_TMP"
  log "start $artifact: $*"
  if timeout -k 30 "$bound" "$@" >>"$LOG" 2>&1 && [ -s "$STAGE_TMP" ]; then
    mv "$STAGE_TMP" "$artifact"
    log "done $artifact"
  else
    rc=$?
    rm -f "$STAGE_TMP"
    log "FAILED $artifact (rc=$rc) — continuing with remaining stages"
    return 1
  fi
}

log "watcher armed (poll ${POLL_S}s, probe bound ${PROBE_TIMEOUT_S}s, max ${MAX_HOURS}h)"

# Chipless backstop BEFORE the chip loop: the replay smoke needs no
# chip (the CLI pins JAX_PLATFORMS=cpu), so a round whose builder
# forgot to commit it still gets the artifact. run_stage's tmp→mv is
# what makes the pickup atomic: a killed run never leaves a truncated
# artifact that later watchers would skip as landed (ISSUE 4). The
# skip check runs FIRST (the normal, builder-committed case must not
# wait on anything), and the pytest defer — the smoke's learner-
# throughput block is a timing measurement, same contention rule as
# the probe — is BOUNDED so a test-heavy session can never stall the
# watcher past its MAX_HOURS contract.
deadline=$(( $(date +%s) + MAX_HOURS * 3600 ))
if [ -s "REPLAY_SMOKE_${RTAG}.json" ]; then
  log "skip REPLAY_SMOKE_${RTAG}.json (exists)"
else
  while pgrep -f "python -m pytest" >/dev/null 2>&1 \
      && [ "$(date +%s)" -lt "$deadline" ]; do
    log "deferring replay-smoke backstop: pytest is running"
    sleep 60
  done
  run_stage "REPLAY_SMOKE_${RTAG}.json" 1800 sh -c '
    python -m tensor2robot_tpu.bin.run_qtopt_replay --smoke \
      --anakin --mesh 8,1 --out "$STAGE_TMP"'
fi
# Second chipless backstop (ISSUE 7): the pod-scale scaling ladder on
# the 8-virtual-device CPU mesh. Same tmp→mv atomicity and pytest
# deferral rules as the replay smoke (it is a timing measurement).
if [ -s "MULTICHIP_r06.json" ]; then
  log "skip MULTICHIP_r06.json (exists)"
else
  while pgrep -f "python -m pytest" >/dev/null 2>&1 \
      && [ "$(date +%s)" -lt "$deadline" ]; do
    log "deferring multichip backstop: pytest is running"
    sleep 60
  done
  run_stage "MULTICHIP_r06.json" 1800 sh -c '
    python -m tensor2robot_tpu.replay.anakin_multichip_bench --smoke \
      --out "$STAGE_TMP"'
fi
# Third chipless backstop (ISSUE 10): the fleet-serving protocol —
# SLO-class offered-load sweep + deterministic overload burst + both
# rollout cycles on the 8-virtual-device mesh, 128 clients. Normally
# builder-committed and skipped; same tmp→mv atomicity and pytest
# deferral rules (its per-class p99 bars are timing measurements).
if [ -s "FLEET_${RTAG}.json" ]; then
  log "skip FLEET_${RTAG}.json (exists)"
else
  while pgrep -f "python -m pytest" >/dev/null 2>&1 \
      && [ "$(date +%s)" -lt "$deadline" ]; do
    log "deferring fleet backstop: pytest is running"
    sleep 60
  done
  run_stage "FLEET_${RTAG}.json" 1800 sh -c '
    python -m tensor2robot_tpu.serving.fleet_bench --smoke \
      --out "$STAGE_TMP"'
fi
# Fourth chipless backstop (ISSUE 11): the observability protocol —
# attribution over the replay smoke, stage-span trace, injected-breach
# flight-recorder dump. Same tmp→mv atomicity and pytest deferral
# rules (its attribution shares are timing measurements).
if [ -s "OBS_${RTAG}.json" ]; then
  log "skip OBS_${RTAG}.json (exists)"
else
  while pgrep -f "python -m pytest" >/dev/null 2>&1 \
      && [ "$(date +%s)" -lt "$deadline" ]; do
    log "deferring obs backstop: pytest is running"
    sleep 60
  done
  run_stage "OBS_${RTAG}.json" 1800 sh -c '
    python -m tensor2robot_tpu.obs.obs_bench --smoke \
      --out "$STAGE_TMP"'
fi
# Fifth chipless backstop (ISSUE 12): the fleet-observability merge —
# >= 2 real subprocess loops against one shared logdir, aggregated
# into the FLEETOBS view (correlation timelines, SLO rollup, watchdog
# controls). Same tmp→mv atomicity and pytest deferral rules (worker
# step rates and stall deadlines are timing measurements).
if [ -s "FLEETOBS_${RTAG}.json" ]; then
  log "skip FLEETOBS_${RTAG}.json (exists)"
else
  while pgrep -f "python -m pytest" >/dev/null 2>&1 \
      && [ "$(date +%s)" -lt "$deadline" ]; do
    log "deferring fleetobs backstop: pytest is running"
    sleep 60
  done
  run_stage "FLEETOBS_${RTAG}.json" 1800 sh -c '
    python -m tensor2robot_tpu.bin.obs_aggregate --smoke \
      --out "$STAGE_TMP"'
fi
# Sixth chipless backstop (ISSUE 13): the precision-tier protocol —
# bf16-vs-f32 parity bars, per-tier ledger, and the bf16-tier rollout
# gate. Same tmp→mv atomicity and pytest deferral rules (its scoring
# rates and rollout latency bars are timing measurements).
if [ -s "PRECISION_${RTAG}.json" ]; then
  log "skip PRECISION_${RTAG}.json (exists)"
else
  while pgrep -f "python -m pytest" >/dev/null 2>&1 \
      && [ "$(date +%s)" -lt "$deadline" ]; do
    log "deferring precision backstop: pytest is running"
    sleep 60
  done
  run_stage "PRECISION_${RTAG}.json" 3000 sh -c '
    python -m tensor2robot_tpu.replay.precision_bench --smoke \
      --out "$STAGE_TMP"'
fi
# Seventh chipless backstop (ISSUE 14): the chaos protocol — scripted
# deterministic faults under paced traffic (quarantine/probe/reinstate,
# degraded shedding, dispatcher restarts, export-corruption rejection,
# learner crash-resume with the bit-parity bar). Same tmp→mv atomicity
# and pytest deferral rules (its p99-recovery bars are timing
# measurements).
if [ -s "FAULTS_${RTAG}.json" ]; then
  log "skip FAULTS_${RTAG}.json (exists)"
else
  while pgrep -f "python -m pytest" >/dev/null 2>&1 \
      && [ "$(date +%s)" -lt "$deadline" ]; do
    log "deferring faults backstop: pytest is running"
    sleep 60
  done
  run_stage "FAULTS_${RTAG}.json" 3000 sh -c '
    python -m tensor2robot_tpu.serving.fault_bench --smoke \
      --out "$STAGE_TMP"'
fi
# Eighth chipless backstop (ISSUE 15): the health-sentinel protocol —
# injected numeric corruption (nan_grads through the fused loop,
# value_scale through the host loop, a corrupted serving replica)
# detected by the in-program summaries / drift rules / fleet Q-drift
# guard, zero breaches on the healthy controls, the instrumented
# ledger bit-stable. Its committed artifact carries the round's
# compact sentinel keys (health_breach_detection_ok /
# fleet_q_drift_ok) so the bench trajectory accumulates chiplessly
# while the pool outage holds. Same tmp→mv atomicity and pytest
# deferral rules (its host-blocked bar is a timing measurement).
if [ -s "HEALTH_${RTAG}.json" ]; then
  log "skip HEALTH_${RTAG}.json (exists)"
else
  while pgrep -f "python -m pytest" >/dev/null 2>&1 \
      && [ "$(date +%s)" -lt "$deadline" ]; do
    log "deferring health backstop: pytest is running"
    sleep 60
  done
  run_stage "HEALTH_${RTAG}.json" 3000 sh -c '
    python -m tensor2robot_tpu.obs.health_bench --smoke \
      --out "$STAGE_TMP"'
fi
# Ninth chipless backstop (ISSUE 16): the TP + int8 protocol — the
# flagship critic through ONE fused anakin_step at tp=1/2/4/8 with
# rule-derived partition specs (sharding structure and per-replica
# bytes asserted; tp=1 the bitwise oracle), the int8 served-weights
# tier's q-oracle agreement / per-tier ledger / served-bytes
# reduction, and the int8 promotion gate with an injected-breach
# auto-rollback. Same tmp→mv atomicity and pytest deferral rules (its
# ladder step rates are timing measurements; flagship compiles are
# CPU-heavy).
if [ -s "TPQUANT_${RTAG}.json" ]; then
  log "skip TPQUANT_${RTAG}.json (exists)"
else
  while pgrep -f "python -m pytest" >/dev/null 2>&1 \
      && [ "$(date +%s)" -lt "$deadline" ]; do
    log "deferring tpquant backstop: pytest is running"
    sleep 60
  done
  run_stage "TPQUANT_${RTAG}.json" 3000 sh -c '
    python -m tensor2robot_tpu.replay.tpquant_bench --smoke \
      --out "$STAGE_TMP"'
fi
# Tenth chipless backstop (ISSUE 18): the data-flywheel protocol — the
# spec-validated ingest gate (malformed served episodes refused with
# the field named), the closed serve→collect→train→redeploy loop with
# synthetic collectors retired at cutover and >= 2 live promote cycles
# mid-run, per-transition correlation ids reconciled against the
# router's logical-request counter, the staleness/coverage/mix
# interlock green, and the stale-params control whose severed export
# path must breach. Same tmp→mv atomicity and pytest deferral rules
# (its promote cycles and client pacing are wall-clock sensitive).
if [ -s "FLYWHEEL_${RTAG}.json" ]; then
  log "skip FLYWHEEL_${RTAG}.json (exists)"
else
  while pgrep -f "python -m pytest" >/dev/null 2>&1 \
      && [ "$(date +%s)" -lt "$deadline" ]; do
    log "deferring flywheel backstop: pytest is running"
    sleep 60
  done
  run_stage "FLYWHEEL_${RTAG}.json" 3000 sh -c '
    python -m tensor2robot_tpu.bin.bench_flywheel --smoke \
      --out "$STAGE_TMP"'
fi
# Eleventh chipless backstop (ISSUE 19): the pod bring-up protocol —
# one anakin_step lowered across 2 REAL processes x 4 virtual CPU
# devices over the JAX coordination service (exactly-once per-process
# compile ledgers, tp rules + ZeRO-1 composed on the cross-process
# mesh), the seam-vs-r17-oracle single-process bit-parity pair, the
# kill-one-process fused checkpoint resume parity proof, and the
# router-of-routers front door with cross-host quarantine by name.
# Throughput/scaling keys are null by the virtual-mesh honesty rule.
# Pytest deferral matters doubly here: the phases spawn real worker
# processes on a small host, and the front-door p99 bars are timing
# asserts.
if [ -s "MULTIHOST_${RTAG}.json" ]; then
  log "skip MULTIHOST_${RTAG}.json (exists)"
else
  while pgrep -f "python -m pytest" >/dev/null 2>&1 \
      && [ "$(date +%s)" -lt "$deadline" ]; do
    log "deferring multihost backstop: pytest is running"
    sleep 60
  done
  run_stage "MULTIHOST_${RTAG}.json" 3000 sh -c '
    python -m tensor2robot_tpu.bin.bench_multihost --smoke \
      --out "$STAGE_TMP"'
fi
# Twelfth chipless backstop (ISSUE 20): the Sebulba decoupled tier —
# 2 REAL CEM actor processes streaming fixed-shape chunks through the
# spool transport + bounded TransitionQueue into the 2-device sharded
# learner behind the double-buffered device_put prefetch seam, the
# serialized one-process oracle bit-parity pair (params AND megastep
# metric stream), and the kill-one-actor watchdog -> quarantine ->
# probe -> reinstate run with zero learner recompiles. Throughput keys
# are null by the virtual-mesh honesty rule. Pytest deferral matters:
# the run spawns real actor subprocesses on a small host and the
# watchdog deadlines are wall-clock.
if [ -s "SEBULBA_${RTAG}.json" ]; then
  log "skip SEBULBA_${RTAG}.json (exists)"
else
  while pgrep -f "python -m pytest" >/dev/null 2>&1 \
      && [ "$(date +%s)" -lt "$deadline" ]; do
    log "deferring sebulba backstop: pytest is running"
    sleep 60
  done
  run_stage "SEBULBA_${RTAG}.json" 3000 sh -c '
    python -m tensor2robot_tpu.bin.bench_sebulba --smoke \
      --out "$STAGE_TMP"'
fi
while [ "$(date +%s)" -lt "$deadline" ]; do
  # Never perturb a live test run: the probe's jax import is real CPU
  # on a small host, and the serving smoke's amortization bar is a
  # TIMING assert — a probe landing mid-suite is exactly the kind of
  # contention that flakes it (observed r6). Defer until pytest exits.
  if pgrep -f "python -m pytest" >/dev/null 2>&1; then
    log "deferring probe: pytest is running"
    sleep 60
    continue
  fi
  if probe_chip; then
    log "chip available — starting measurement sweep"
    # bench.py orchestrates its own probe/retry and writes the detail
    # file itself; its compact contract line is the staged artifact
    # here (the detail file lands beside it from the same run). A
    # structured OUTAGE line (rc 0 by design) must NOT land as the
    # stage artifact — that would mark the stage done and skip every
    # later chip window — so the stage only succeeds on a real
    # measurement (non-null value, no error key).
    run_stage "BENCH_builder_${RTAG}.json" 3600 sh -c '
      python bench.py > "$STAGE_TMP" &&
      python - "$STAGE_TMP" <<PYEOF
import json, sys
obj = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
sys.exit(1 if obj.get("error") or obj.get("value") is None else 0)
PYEOF'
    run_stage "SERVING_${RTAG}.json" 1800 sh -c '
      python -m tensor2robot_tpu.bin.bench_serving >  "$STAGE_TMP" &&
      python -m tensor2robot_tpu.bin.bench_serving --fleet >> "$STAGE_TMP"'
    run_stage "CAPABILITY_${RTAG}_fast.jsonl" 5400 sh -c '
      python -m tensor2robot_tpu.bin.run_capability_checks --scale fast \
        > "$STAGE_TMP" &&
      for off in 1 2; do
        python -m tensor2robot_tpu.bin.run_capability_checks --scale fast \
          --checks vrgripper --seed-offset $off >> "$STAGE_TMP" || exit 1;
      done'
    run_stage "CAPABILITY_${RTAG}_full.jsonl" 10800 \
      sh -c 'python -m tensor2robot_tpu.bin.run_capability_checks --scale full \
        > "$STAGE_TMP"'
    # Test failures still produce the (valuable) log — only a hang/kill
    # discards the partial tmp and leaves the stage retryable.
    run_stage "TPU_TESTS_${RTAG}.log" 3600 \
      sh -c 'python -m pytest tests/ --tpu -q > "$STAGE_TMP" 2>&1; true'
    log "sweep complete — flip docs/ARTIFACTS.md rows to committed and commit"
    exit 0
  fi
  log "pool unavailable; sleeping ${POLL_S}s"
  sleep "$POLL_S"
done
log "watcher expired after ${MAX_HOURS}h with no chip window"
exit 1

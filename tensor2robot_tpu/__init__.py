"""tensor2robot_tpu — a TPU-native (JAX/XLA/pjit/Pallas) robot-learning framework.

A ground-up rebuild of the capabilities of ``sharmasecureservices/tensor2robot``
(a TF1/Estimator-era robot-learning harness), re-designed TPU-first:

- a typed tensor-spec system (``tensor2robot_tpu.specs``) that drives data
  parsing, preprocessing, device feeding, export signatures, and on-robot
  inference from a single model definition;
- a portable model abstraction (``tensor2robot_tpu.models``) built on Flax,
  with regression / classification / critic base classes;
- synchronous data-parallel (and model-parallel-capable) training over a
  ``jax.sharding.Mesh`` (``tensor2robot_tpu.parallel``,
  ``tensor2robot_tpu.train``) — XLA collectives over ICI/DCN replace the
  reference's CrossShardOptimizer / NCCL all-reduce;
- async checkpointing (Orbax), EMA parameter averaging, and hot-reloadable
  export (jax2tf SavedModel so existing robot serving is unchanged, plus a
  pure-JAX predictor path);
- MAML-style meta-learning as a model transformer
  (``tensor2robot_tpu.meta_learning``);
- research workloads: pose_env reaching, QT-Opt grasping Q-function (+ CEM),
  Grasp2Vec, VRGripper BC (``tensor2robot_tpu.research``).

Reference parity map: SURVEY.md §2 (component inventory). The reference mount
was empty during the survey (SURVEY.md §0); reference citations in docstrings
are of the form ``<file> §<symbol>`` against the upstream
``google-research/tensor2robot`` layout reconstructed there.
"""

__version__ = "0.1.0"

"""Chaos benchmark CLI: the bin/ face of serving/fault_bench.

    # The committed FAULTS_r15 protocol (chipless: the CLI bootstraps an
    # 8-virtual-device CPU mesh and re-execs itself; acceptance bars
    # are ENFORCED at generation time):
    python -m tensor2robot_tpu.bin.bench_faults --smoke --out FAULTS_r15.json

    # Reduced tier-1 lane (2 devices, short windows, same structure):
    python -m tensor2robot_tpu.bin.bench_faults --ci

Everything — the scripted fault schedule under paced traffic, the
quarantine→probe→reinstate arc, degraded-mode shedding, dispatcher
restart budgets, export-corruption rejection, and the learner's
bit-exact crash-resume — lives in serving/fault_bench.py; this wrapper
exists so the chaos protocol is discoverable next to bench_fleet in
the bin/ surface every other measured artifact is produced from.
"""

from tensor2robot_tpu.serving.fault_bench import main

if __name__ == "__main__":
  main()

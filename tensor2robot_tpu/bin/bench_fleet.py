"""Fleet-serving benchmark CLI: the bin/ face of serving/fleet_bench.

    # The committed FLEET_r11 protocol (chipless: the CLI bootstraps an
    # 8-virtual-device CPU mesh and re-execs itself):
    python -m tensor2robot_tpu.bin.bench_fleet --smoke --out FLEET_r11.json

    # Reduced tier-1 lane (2 devices, short windows, same structure):
    python -m tensor2robot_tpu.bin.bench_fleet --ci

Everything — the offered-load sweep across SLO classes, the overload
burst, the shadow/canary rollout cycles, the per-device compile ledger
— lives in serving/fleet_bench.py; this wrapper exists so the fleet
protocol is discoverable next to bench_serving (the single-replica
oracle's sweep) in the bin/ surface every other measured artifact is
produced from.
"""

from tensor2robot_tpu.serving.fleet_bench import main

if __name__ == "__main__":
  main()

"""Data-flywheel benchmark CLI: the bin/ face of flywheel/flywheel_bench.

    # The committed FLYWHEEL_r18 protocol (chipless: the CLI bootstraps
    # an 8-virtual-device CPU mesh and re-execs itself; acceptance bars
    # are ENFORCED at generation time):
    python -m tensor2robot_tpu.bin.bench_flywheel --smoke --out FLYWHEEL_r18.json

    # Reduced tier-1 lane (2 devices, short phases, same structure):
    python -m tensor2robot_tpu.bin.bench_flywheel --ci

Everything — the spec-validated ingest gate (malformed served episodes
refused with the field named), the closed serve→collect→train→redeploy
loop with synthetic collectors retired at cutover and ≥ 2 live promote
cycles mid-run, per-transition correlation-id traceability reconciled
against the router's logical-request counter, the staleness/coverage/
mix interlock, and the stale-params control whose severed export path
must breach — lives in flywheel/flywheel_bench.py; this wrapper exists
so the flywheel protocol is discoverable next to bench_fleet in the
bin/ surface every other measured artifact is produced from.
"""

from tensor2robot_tpu.flywheel.flywheel_bench import main

if __name__ == "__main__":
  main()

"""Health-sentinel benchmark CLI: the bin/ face of obs/health_bench.

    # The committed HEALTH_r16 protocol (chipless: the CLI bootstraps an
    # 8-virtual-device CPU mesh and re-execs itself; acceptance bars
    # are ENFORCED at generation time):
    python -m tensor2robot_tpu.bin.bench_health --smoke --out HEALTH_r16.json

    # Reduced tier-1 lane (2 devices, short windows, same structure):
    python -m tensor2robot_tpu.bin.bench_health --ci

Everything — the ledger-stability A/B of the instrumented fused loop,
the injected numeric corruptions (nan_grads through anakin,
value_scale through the host loop, corrupt_served_variables against a
live router) with their detection bars, the fleet Q-drift aggregate
rollup, and the zero-false-positive healthy controls — lives in
obs/health_bench.py; this wrapper exists so the sentinel protocol is
discoverable next to bench_faults in the bin/ surface every other
measured artifact is produced from.
"""

from tensor2robot_tpu.obs.health_bench import main

if __name__ == "__main__":
  main()

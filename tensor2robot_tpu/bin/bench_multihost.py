"""Pod-scale bring-up CLI: the bin/ face of parallel/multihost_bench.

    # The committed MULTIHOST_r19 protocol (chipless: 2 REAL processes
    # x 4 virtual CPU devices each over the JAX coordination service;
    # acceptance bars are ENFORCED at generation time):
    python -m tensor2robot_tpu.bin.bench_multihost --smoke --out MULTIHOST_r19.json

    # Reduced tier-1 lane (front-door phase only, bars deferred):
    python -m tensor2robot_tpu.bin.bench_multihost --ci

Everything — the 2-process anakin_step bring-up with exactly-once
per-process compile ledgers, the seam-vs-r17-oracle single-process
bit-parity pair, the kill-one-process fused checkpoint resume with the
post-resume stream parity bar, and the router-of-routers front door
(ingress-stamped deadlines across the hop, 1:1 request reconciliation,
drift-rollup cross-host quarantine by name) — lives in
parallel/multihost_bench.py; this wrapper exists so the pod protocol is
discoverable next to bench_fleet in the bin/ surface every other
measured artifact is produced from.
"""

from tensor2robot_tpu.parallel.multihost_bench import main

if __name__ == "__main__":
  main()

"""Sebulba decoupled-tier CLI: the bin/ face of parallel/sebulba_bench.

    # The committed SEBULBA_r20 protocol (chipless: 2 REAL CEM actor
    # processes + 1 sharded learner process on virtual CPU devices;
    # acceptance bars are ENFORCED at generation time):
    python -m tensor2robot_tpu.bin.bench_sebulba --smoke --out SEBULBA_r20.json

    # Reduced tier-1 lane (synthetic actors, bars deferred):
    python -m tensor2robot_tpu.bin.bench_sebulba --ci

Everything — the 2-actor-process spool transport with bounded ack
backpressure, the double-buffered device_put ingest seam feeding the
sharded ring's exactly-once device_extend, the serialized one-process
oracle whose params must match the live learner BIT for bit, and the
kill-one-actor watchdog -> quarantine -> probe -> reinstate protocol
with zero learner recompiles — lives in parallel/sebulba_bench.py (the
machinery itself in parallel/sebulba.py); this wrapper exists so the
decoupled tier is discoverable next to bench_multihost in the bin/
surface every other measured artifact is produced from.
"""

from tensor2robot_tpu.parallel.sebulba_bench import main

if __name__ == "__main__":
  main()

"""Serving benchmark: QT-Opt CEM control, single-robot and fleet modes.

Single-robot mode (default; the classic `SERVING_r*` fields): per
control step, CEMPolicy ships one camera image to the device, runs all
CEM iterations (sample → score → elite refit) inside one compiled
program, and returns one action. Latency is weight-independent, so a
randomly initialized Q-function measures the same control rate a
trained one serves at.

    python -m tensor2robot_tpu.bin.bench_serving

Fleet mode (`--fleet`; the fleet fields of the `SERVING_r*` schema):
N synthetic clients drive the serving/ stack — deadline micro-batcher
→ bucket ladder → ONE batched CEM executable per bucket — either
closed-loop (each client blocks on its action) or at a target offered
load (`--target-hz`). Emits aggregate images/sec, per-request p50/p99
latency, batch occupancy, padding waste, and the compiled-executable
ledger. `--fleet --smoke` swaps in the millisecond-scale
serving.smoke.TinyQPredictor and runs on CPU: the tier-1 lane that
exercises the whole serving path on every PR, no TPU pool required.

Both modes print ONE JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time

import numpy as np


def bench_policy(uint8_images: bool, control_steps: int = 30) -> dict:
  import jax

  from tensor2robot_tpu.predictors.checkpoint_predictor import (
      CheckpointPredictor)
  from tensor2robot_tpu.research.qtopt.cem import CEMPolicy
  from tensor2robot_tpu.research.qtopt.t2r_models import QTOptGraspingModel

  model = QTOptGraspingModel(uint8_images=uint8_images)
  predictor = CheckpointPredictor(model)
  predictor.init_randomly()
  policy = CEMPolicy(predictor, action_size=4, num_samples=64,
                     num_elites=6, iterations=3, seed=0)
  size = model.get_feature_specification("train")["image"].shape[0]
  rng = np.random.default_rng(0)

  def make_image():
    if uint8_images:
      return rng.integers(0, 255, (size, size, 3), np.uint8)
    return rng.random((size, size, 3)).astype(np.float32)

  # closed_loop: block on every action before the next frame — the
  # rate a real robot loop gets (it needs action N before frame N+1).
  # pipelined: block only at the end — async dispatch overlaps host
  # transfer with device compute, an offline-throughput ceiling, NOT a
  # control rate. Both on fresh frames (distinct camera image per
  # step, paying host→device transfer each time).
  frames = [make_image() for _ in range(control_steps)]
  jax.block_until_ready(policy(frames[0]))  # compile the control step

  out = {}
  start = time.perf_counter()
  for image in frames:
    jax.block_until_ready(policy(image))
  elapsed = time.perf_counter() - start
  out["closed_loop_hz"] = round(control_steps / elapsed, 1)
  out["closed_loop_ms"] = round(1e3 * elapsed / control_steps, 2)

  start = time.perf_counter()
  for image in frames:
    action = policy(image)
  jax.block_until_ready(action)
  elapsed = time.perf_counter() - start
  out["pipelined_hz"] = round(control_steps / elapsed, 1)

  out["image_wire_format"] = "uint8" if uint8_images else "float32"
  out["image_size"] = int(size)
  out["image_bytes"] = int(frames[0].nbytes)
  return out


# --- fleet mode ------------------------------------------------------------


def _cem_kwargs(smoke: bool) -> dict:
  """CEM config shared by the fleet policy AND the single-client
  baseline (the amortization ratio must compare like with like). The
  smoke lane shrinks it: per-client CEM compute scales linearly with
  batch on any backend, so a small config keeps per-flush DISPATCH —
  the cost micro-batching actually amortizes — dominant on CPU, which
  is the property the smoke asserts."""
  if smoke:
    return dict(action_size=4, num_samples=32, num_elites=4,
                iterations=2, seed=0)
  return dict(action_size=4, num_samples=64, num_elites=6,
              iterations=3, seed=0)


def _make_fleet_policy(smoke: bool, uint8_images: bool):
  """(predictor, policy, make_image) for the fleet sweep."""
  from tensor2robot_tpu.serving.policy import CEMFleetPolicy

  if smoke:
    from tensor2robot_tpu.serving.smoke import TinyQPredictor
    predictor = TinyQPredictor()
    make_image = predictor.make_image
  else:
    from tensor2robot_tpu.predictors.checkpoint_predictor import (
        CheckpointPredictor)
    from tensor2robot_tpu.research.qtopt.t2r_models import (
        QTOptGraspingModel)
    model = QTOptGraspingModel(uint8_images=uint8_images)
    predictor = CheckpointPredictor(model)
    predictor.init_randomly()
    size = model.get_feature_specification("train")["image"].shape[0]
    rng = np.random.default_rng(0)

    def make_image(seed: int):
      del seed
      if uint8_images:
        return rng.integers(0, 255, (size, size, 3), np.uint8)
      return rng.random((size, size, 3)).astype(np.float32)

  policy = CEMFleetPolicy(predictor, **_cem_kwargs(smoke))
  return predictor, policy, make_image


def _run_clients(server, n_clients: int, frames: int, make_image,
                 target_hz: float) -> float:
  """Drives n closed-loop (or paced open-loop) clients; returns seconds."""
  errors = []

  def closed_loop(client: int):
    image = make_image(client)
    try:
      for _ in range(frames):
        server.act(image)
    except Exception as e:  # surface, don't hang the join
      errors.append(e)

  def open_loop(client: int):
    image = make_image(client)
    period = 1.0 / target_hz
    futures = []
    next_at = time.perf_counter()
    try:
      for _ in range(frames):
        delay = next_at - time.perf_counter()
        if delay > 0:
          time.sleep(delay)
        futures.append(server.submit(image))
        next_at += period
      for future in futures:
        future.result()
    except Exception as e:
      errors.append(e)

  run = open_loop if target_hz > 0 else closed_loop
  threads = [threading.Thread(target=run, args=(i,), daemon=True)
             for i in range(n_clients)]
  start = time.perf_counter()
  for thread in threads:
    thread.start()
  for thread in threads:
    thread.join()
  elapsed = time.perf_counter() - start
  if errors:
    raise errors[0]
  return elapsed


def bench_fleet(smoke: bool, clients: list, frames: int,
                deadline_ms: float, target_hz: float,
                uint8_images: bool = True, repeats: int = 3) -> dict:
  import statistics

  from tensor2robot_tpu.serving.server import FleetServer
  from tensor2robot_tpu.serving.stats import ServingStats

  predictor, policy, make_image = _make_fleet_policy(smoke, uint8_images)
  ladder = policy.ladder

  # Precompile the whole ladder up front (server warmup): measured
  # sweep points then assert zero mid-flight compiles — the bounded-
  # executables property the ladder exists for.
  for bucket in ladder.sizes:
    policy([make_image(i) for i in range(bucket)])

  # Single-client closed loop through the single-robot path (CEMPolicy:
  # one fused control step per frame, no batching) — the amortization
  # baseline the fleet numbers are read against. Median over `repeats`
  # trials: a contended host's one-off stall must not set the baseline.
  from tensor2robot_tpu.research.qtopt.cem import CEMPolicy
  import jax
  single_policy = CEMPolicy(predictor, **_cem_kwargs(smoke))
  image = make_image(0)
  jax.block_until_ready(single_policy(image))
  single_rates = []
  for _ in range(max(1, repeats)):
    start = time.perf_counter()
    for _ in range(frames):
      jax.block_until_ready(single_policy(image))
    single_rates.append(frames / (time.perf_counter() - start))
  single_hz = statistics.median(single_rates)

  sweep = []
  for n in clients:
    stats = ServingStats()
    server = FleetServer(policy, max_batch=min(n, ladder.max_batch),
                         deadline_ms=deadline_ms, stats=stats)
    rates = []
    with server:
      # One throwaway round primes the batcher threads.
      [f.result() for f in [server.submit(make_image(i))
                            for i in range(n)]]
      for _ in range(max(1, repeats)):
        elapsed = _run_clients(server, n, frames, make_image, target_hz)
        rates.append(n * frames / elapsed)
    snap = server.snapshot()
    point = {
        "clients": n,
        "offered_hz_per_client": target_hz if target_hz > 0
        else "closed_loop",
        "aggregate_images_per_sec": round(statistics.median(rates), 1),
        "aggregate_trials": [round(r, 1) for r in rates],
        "latency_p50_ms": snap.get("latency_p50_ms"),
        "latency_p99_ms": snap.get("latency_p99_ms"),
        "batch_occupancy": snap.get("batch_occupancy"),
        "padding_waste": snap.get("padding_waste"),
        "mean_batch_size": snap.get("mean_batch_size"),
        "flushes": snap.get("flushes"),
        "deadline_flushes": snap.get("deadline_flushes"),
    }
    sweep.append(point)

  top = sweep[-1]
  cem_kwargs = _cem_kwargs(smoke)
  return {
      "mode": "smoke" if smoke else "full",
      "cem": {k: cem_kwargs[k]
              for k in ("num_samples", "num_elites", "iterations")},
      "bucket_ladder": list(ladder.sizes),
      "compile_counts": {str(k): v
                         for k, v in sorted(policy.compile_counts.items())},
      "deadline_ms": deadline_ms,
      "frames_per_client": frames,
      "repeats": max(1, repeats),
      "single_client_closed_loop_hz": round(single_hz, 1),
      "single_client_trials_hz": [round(r, 1) for r in single_rates],
      "fleet_sweep": sweep,
      "amortization_at_max_clients": round(
          top["aggregate_images_per_sec"] / single_hz, 2),
  }


def _parse_args(argv=None):
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument("--fleet", action="store_true",
                      help="multi-client micro-batching sweep")
  parser.add_argument("--smoke", action="store_true",
                      help="CPU smoke: TinyQPredictor, runs chipless "
                           "(tier-1 CI lane)")
  parser.add_argument("--clients", default="1,2,4,8,16",
                      help="comma-separated concurrent-client sweep")
  parser.add_argument("--frames", type=int, default=0,
                      help="frames per client (0 = mode default)")
  parser.add_argument("--deadline-ms", type=float, default=5.0,
                      help="micro-batcher deadline budget")
  parser.add_argument("--target-hz", type=float, default=0.0,
                      help="offered load per client; 0 = closed loop")
  parser.add_argument("--repeats", type=int, default=3,
                      help="measurement trials per point (median wins)")
  parser.add_argument("--float32", action="store_true",
                      help="fleet full mode: float32 wire instead of "
                           "uint8")
  args = parser.parse_args(argv)
  if args.smoke and not args.fleet:
    # --smoke pins JAX to CPU; letting it combine with the single-robot
    # default would grind the 472x472 model on CPU and emit a normal-
    # looking classic serving line measured on the wrong backend.
    parser.error("--smoke is a fleet-mode lane; pass --fleet --smoke")
  return args


def main(argv=None) -> None:
  args = _parse_args(argv)
  if args.smoke:
    # Chipless lane: must pick the CPU backend, and only can before
    # JAX initializes (imports below are deliberately lazy).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
  import jax

  if args.fleet:
    clients = [int(c) for c in args.clients.split(",") if c]
    frames = args.frames or (60 if args.smoke else 30)
    fleet = bench_fleet(args.smoke, clients, frames, args.deadline_ms,
                        args.target_hz,
                        uint8_images=not args.float32,
                        repeats=args.repeats)
    print(json.dumps({
        "metric": "QT-Opt fleet serving: deadline micro-batch + "
                  "bucketed CEM",
        "device_kind": jax.devices()[0].device_kind,
        **fleet,
        "reference_note": "the reference ran robot fleets at 10-30 Hz "
                          "through one batched session.run per CEM "
                          "iteration (SURVEY.md §3.3)",
    }))
    return

  results = [bench_policy(uint8_images=False),
             bench_policy(uint8_images=True)]
  print(json.dumps({
      "metric": "QT-Opt fused CEM control rate (64 samples x 3 iters)",
      "device_kind": jax.devices()[0].device_kind,
      "results": results,
      "reference_note": "the reference's robot fleets ran 10-30 Hz "
                        "with a batched session.run per CEM iteration "
                        "(SURVEY.md §3.3)",
  }))


if __name__ == "__main__":
  main()

"""Serving-latency benchmark: the QT-Opt CEM control loop on the chip.

Measures the fused on-device control step (README "Current benchmark"
serving claims; committed artifact `SERVING_r*.json`): per control
step, CEMPolicy ships one camera image to the device, runs all CEM
iterations (sample → score → elite refit) inside one compiled program,
and returns one action. Latency is weight-independent, so a randomly
initialized Q-function measures the same control rate a trained one
serves at.

    python -m tensor2robot_tpu.bin.bench_serving

Prints one JSON line: control-step Hz / ms for the float32 and uint8
wire formats at the flagship 472x472 camera size.
"""

from __future__ import annotations

import json
import time

import numpy as np


def bench_policy(uint8_images: bool, control_steps: int = 30) -> dict:
  import jax

  from tensor2robot_tpu.predictors.checkpoint_predictor import (
      CheckpointPredictor)
  from tensor2robot_tpu.research.qtopt.cem import CEMPolicy
  from tensor2robot_tpu.research.qtopt.t2r_models import QTOptGraspingModel

  model = QTOptGraspingModel(uint8_images=uint8_images)
  predictor = CheckpointPredictor(model)
  predictor.init_randomly()
  policy = CEMPolicy(predictor, action_size=4, num_samples=64,
                     num_elites=6, iterations=3, seed=0)
  size = model.get_feature_specification("train")["image"].shape[0]
  rng = np.random.default_rng(0)

  def make_image():
    if uint8_images:
      return rng.integers(0, 255, (size, size, 3), np.uint8)
    return rng.random((size, size, 3)).astype(np.float32)

  # closed_loop: block on every action before the next frame — the
  # rate a real robot loop gets (it needs action N before frame N+1).
  # pipelined: block only at the end — async dispatch overlaps host
  # transfer with device compute, an offline-throughput ceiling, NOT a
  # control rate. Both on fresh frames (distinct camera image per
  # step, paying host→device transfer each time).
  frames = [make_image() for _ in range(control_steps)]
  jax.block_until_ready(policy(frames[0]))  # compile the control step

  out = {}
  start = time.perf_counter()
  for image in frames:
    jax.block_until_ready(policy(image))
  elapsed = time.perf_counter() - start
  out["closed_loop_hz"] = round(control_steps / elapsed, 1)
  out["closed_loop_ms"] = round(1e3 * elapsed / control_steps, 2)

  start = time.perf_counter()
  for image in frames:
    action = policy(image)
  jax.block_until_ready(action)
  elapsed = time.perf_counter() - start
  out["pipelined_hz"] = round(control_steps / elapsed, 1)

  out["image_wire_format"] = "uint8" if uint8_images else "float32"
  out["image_size"] = int(size)
  out["image_bytes"] = int(frames[0].nbytes)
  return out


def main() -> None:
  import jax

  results = [bench_policy(uint8_images=False),
             bench_policy(uint8_images=True)]
  print(json.dumps({
      "metric": "QT-Opt fused CEM control rate (64 samples x 3 iters)",
      "device_kind": jax.devices()[0].device_kind,
      "results": results,
      "reference_note": "the reference's robot fleets ran 10-30 Hz "
                        "with a batched session.run per CEM iteration "
                        "(SURVEY.md §3.3)",
  }))


if __name__ == "__main__":
  main()

"""Fleet observability aggregation CLI: the bin/ face of obs/aggregate.

    # merge one fleet logdir (N processes' metrics.jsonl / registry
    # snapshots / Chrome traces / flightrec dumps) into one view:
    python -m tensor2robot_tpu.bin.obs_aggregate --logdir DIR --out FLEET.json

    # the committed FLEETOBS_r13 protocol (chipless: spawns >= 2 real
    # subprocess serve loops on 8-virtual-device CPU meshes against one
    # shared logdir, runs the watchdog positive/negative controls,
    # merges, self-checks):
    python -m tensor2robot_tpu.bin.obs_aggregate --smoke --out FLEETOBS_r13.json

    # reduced tier-1 lane (same structure, shorter windows):
    python -m tensor2robot_tpu.bin.obs_aggregate --ci

Everything — stream discovery, reservoir-union percentile merging, the
host-prefixed merged trace with cross-process request flows, the SLO
rollup and its consistency check, straggler detection against the
fleet median — lives in obs/aggregate.py; this wrapper exists so the
fleet merge is discoverable next to the other artifact producers in
the bin/ surface.
"""

from tensor2robot_tpu.obs.aggregate import main

if __name__ == "__main__":
  main()

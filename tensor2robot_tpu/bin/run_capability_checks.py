"""Reproduces the per-family capability checks from README/DESIGN.

One command per model family (or all), each running the REAL pipeline —
data generation → record parsing → training → export → serving — and
printing one JSON line with the measured outcome:

    python -m tensor2robot_tpu.bin.run_t2r_trainer  # normal training
    python -m tensor2robot_tpu.bin.run_capability_checks \
        --checks pose_env,qtopt,grasp2vec,vrgripper,maml \
        --scale fast

`--scale full` matches the README numbers (minutes per check on a
chip); `fast` shrinks images/steps for a quicker signal (still real
training, looser expectations). Exit code is non-zero if any check
misses its expectation, so this doubles as an acceptance test on real
hardware.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np


# (fast, full) per-check knobs.
_SCALES = {
    "pose_env": {"fast": dict(episodes=1000, steps=800, image=64),
                 "full": dict(episodes=2000, steps=1500, image=64)},
    "qtopt": {"fast": dict(grasps=3000, steps=1200, image=64),
              "full": dict(grasps=8000, steps=2500, image=128)},
    "grasp2vec": {"fast": dict(triplets=2048, steps=600, image=64),
                  "full": dict(triplets=8192, steps=1500, image=64)},
    "vrgripper": {"fast": dict(demos=2000, steps=800, image=64),
                  "full": dict(demos=4000, steps=1500, image=64)},
    "maml": {"fast": dict(steps=800, image=64),
             "full": dict(steps=2000, image=64)},
}
# Expectation per (check, scale), set just-under-measured (10-15%
# slack) from the committed r2 runs on cluttered scenes
# (CAPABILITY_r02_full.jsonl / CAPABILITY_r02_fast.jsonl, one v5e,
# 2026-07-30): pose_env 0.765 fast / 0.925 full (tight 0.05 gate),
# qtopt 0.47/0.85 (random 0.05), grasp2vec 0.453/0.734 (chance 0.016).
# vrgripper: recalibrated r3 — the r3 pose_env occluder randomization
# hardened its training scenes (measured r3: 0.75 fast / 0.925 full vs
# 0.86/0.95 at r2), so the bars moved to keep the 10-15% slack
# (CAPABILITY_r03_*.jsonl, 2026-07-31). maml: recalibrated r3 (VERDICT r2 #6 — the old
# gate was saturated at 1.0): noisy-demonstrations regime (sigma=0.22
# condition labels) scored at half the object radius measured 0.879
# fast / 0.922 full (one v5e, 2026-07-31), so the gate now sits in the
# sensitive region with the usual 10-15% slack; a secondary
# adapted-vs-unadapted margin assertion (>=0.5 at the object radius)
# still catches the historical total-collapse failure mode.
_EXPECT = {
    ("pose_env", "fast"): 0.65, ("pose_env", "full"): 0.80,
    ("qtopt", "fast"): 0.40, ("qtopt", "full"): 0.72,
    ("grasp2vec", "fast"): 0.38, ("grasp2vec", "full"): 0.62,
    ("vrgripper", "fast"): 0.65, ("vrgripper", "full"): 0.80,
    ("maml", "fast"): 0.75, ("maml", "full"): 0.80,
}


def _train_and_restore_predictor(model, record_path, steps, run_dir):
  """Shared record-pipeline half: train -> native export -> predictor."""
  from tensor2robot_tpu.data.default_input_generator import (
      DefaultRecordInputGenerator)
  from tensor2robot_tpu.export.native_export_generator import (
      NativeExportGenerator)
  from tensor2robot_tpu.predictors.exported_model_predictor import (
      ExportedModelPredictor)
  from tensor2robot_tpu.train.train_eval import train_eval_model

  train_eval_model(
      model,
      input_generator_train=DefaultRecordInputGenerator(
          file_patterns=record_path, batch_size=64, seed=1),
      max_train_steps=steps, iterations_per_loop=50,
      model_dir=run_dir, export_generator=NativeExportGenerator(),
      log_every_steps=max(100, steps))
  predictor = ExportedModelPredictor(
      export_root=os.path.join(run_dir, "export", "latest"))
  if not predictor.restore(timeout_s=10.0):
    raise RuntimeError(
        f"No export appeared under {run_dir}/export/latest")
  return predictor


def check_pose_env(scale: str, workdir: str) -> dict:
  import optax

  from tensor2robot_tpu.research.pose_env import pose_env
  from tensor2robot_tpu.research.pose_env.eval_policy import evaluate_policy
  from tensor2robot_tpu.research.pose_env.pose_env_models import (
      PoseEnvRegressionModel)

  knobs = _SCALES["pose_env"][scale]
  rec = os.path.join(workdir, "pose.tfrecord")
  pose_env.write_tfrecords(rec, num_episodes=knobs["episodes"], seed=0,
                           image_size=knobs["image"])
  model = PoseEnvRegressionModel(image_size=knobs["image"],
                                 optimizer_fn=lambda: optax.adam(1e-3))
  predictor = _train_and_restore_predictor(
      model, rec, knobs["steps"], os.path.join(workdir, "pose_run"))
  # Gate on a TIGHT reach threshold: at the env default (0.10) the
  # check saturates at 1.0 even with scene clutter (measured r2 full),
  # so a 2x quality regression would still "pass". 0.05 is inside the
  # rasterized target disc radius — still a legitimate "reach success",
  # but sensitive to localization error. The 0.10 figure comes from the
  # SAME 200 rollouts (extra_thresholds re-buckets the distances).
  result = evaluate_policy(predictor, num_episodes=200, seed=1234,
                           image_size=knobs["image"],
                           success_threshold=0.05,
                           extra_thresholds=(0.10,))
  # Key derived the same way evaluate_policy builds it (f"{t:g}") so a
  # 0.10-vs-0.1 formatting drift cannot KeyError.
  return {"success_rate": result["success_rate"],
          "success_rate_at_0p10": result[f"success_rate_at_{0.10:g}"],
          "mean_reward": result["mean_reward"],
          "metric": "reach success within 0.05"}


def check_qtopt(scale: str, workdir: str) -> dict:
  import optax

  from tensor2robot_tpu.research.qtopt import synthetic_grasping as sg
  from tensor2robot_tpu.research.qtopt.cem import CEMPolicy
  from tensor2robot_tpu.research.qtopt.t2r_models import QTOptGraspingModel

  knobs = _SCALES["qtopt"][scale]
  rec = os.path.join(workdir, "grasps.tfrecord")
  sg.write_tfrecords(rec, num_examples=knobs["grasps"],
                     image_size=knobs["image"], seed=0)
  model = QTOptGraspingModel(image_size=knobs["image"],
                             in_image_size=knobs["image"],
                             optimizer_fn=lambda: optax.adam(1e-3))
  predictor = _train_and_restore_predictor(
      model, rec, knobs["steps"], os.path.join(workdir, "qtopt_run"))
  policy = CEMPolicy(predictor, action_size=4, num_samples=128,
                     num_elites=10, iterations=4, seed=7)
  cem = sg.evaluate_grasp_policy(policy, num_scenes=200, seed=5555,
                                 image_size=knobs["image"])
  rng = np.random.default_rng(0)
  rand = sg.evaluate_grasp_policy(
      lambda im: rng.uniform(-1, 1, 4), num_scenes=200, seed=5555,
      image_size=knobs["image"])
  return {"success_rate": cem["success_rate"],
          "random_success_rate": rand["success_rate"]}


def check_grasp2vec(scale: str, workdir: str) -> dict:
  import optax

  from tensor2robot_tpu.research.grasp2vec import synthetic_scenes as ss
  from tensor2robot_tpu.research.grasp2vec.grasp2vec_model import (
      Grasp2VecModel)
  from tensor2robot_tpu.specs import tensorspec_utils as ts
  from tensor2robot_tpu.train.trainer import Trainer

  knobs = _SCALES["grasp2vec"][scale]
  model = Grasp2VecModel(image_size=knobs["image"], depth=18,
                         norm="group",
                         optimizer_fn=lambda: optax.adam(1e-3))
  trainer = Trainer(model, seed=0)
  batch = 64
  state = trainer.create_train_state(batch_size=batch)
  data = ss.sample_triplets(knobs["triplets"], image_size=knobs["image"],
                            seed=0)
  rng = np.random.default_rng(1)
  for _ in range(knobs["steps"]):
    idx = rng.choice(knobs["triplets"], batch, replace=False)
    feats = ts.TensorSpecStruct(ss.as_model_batch(data, idx))
    sharded, _ = trainer.shard_batch((feats, None))
    state, _ = trainer.train_step(state, sharded, None)
  heldout = ss.sample_triplets(64, image_size=knobs["image"], seed=777)
  feats = ts.TensorSpecStruct(ss.as_model_batch(heldout, np.arange(64)))
  sharded, _ = trainer.shard_batch((feats, None))
  metrics = trainer.eval_step(state, sharded, None)
  return {"success_rate": float(metrics["retrieval_accuracy"]),
          "metric": "held-out 64-way retrieval accuracy"}


def check_vrgripper(scale: str, workdir: str, seed_offset: int = 0) -> dict:
  import jax
  import optax

  from tensor2robot_tpu.research.pose_env import pose_env
  from tensor2robot_tpu.research.pose_env.eval_policy import evaluate_policy
  from tensor2robot_tpu.research.vrgripper.vrgripper_env_models import (
      VRGripperRegressionModel)
  from tensor2robot_tpu.specs import tensorspec_utils as ts
  from tensor2robot_tpu.train.trainer import Trainer

  knobs = _SCALES["vrgripper"][scale]
  model = VRGripperRegressionModel(image_size=knobs["image"],
                                   action_size=2, gripper_pose_size=4,
                                   optimizer_fn=lambda: optax.adam(1e-3))
  # seed_offset varies TRAINING randomness (init, demos, batch order)
  # for seed-spread measurement (VERDICT r3 #8); the eval episodes stay
  # fixed so runs are comparable.
  trainer = Trainer(model, seed=seed_offset)
  batch = 64
  state = trainer.create_train_state(batch_size=batch)
  images, targets = pose_env.collect_episodes(
      knobs["demos"], seed=seed_offset, image_size=knobs["image"])
  rng = np.random.default_rng(1 + seed_offset)
  proprio = rng.normal(0, 1, (knobs["demos"], 4)).astype(np.float32)
  for _ in range(knobs["steps"]):
    idx = rng.choice(knobs["demos"], batch, replace=False)
    feats = ts.TensorSpecStruct({
        "image": images[idx].astype(np.float32) / 255.0,
        "gripper_pose": proprio[idx]})
    labels = ts.TensorSpecStruct({"action": targets[idx]})
    sharded_f, sharded_l = trainer.shard_batch((feats, labels))
    state, _ = trainer.train_step(state, sharded_f, sharded_l)

  from tensor2robot_tpu.export import export_utils
  variables = export_utils.fetch_variables_to_host(
      state.variables(use_ema=True))
  predict = jax.jit(model.predict_fn)
  zero_proprio = np.zeros((1, 4), np.float32)

  def policy(features):
    feats = ts.TensorSpecStruct({"image": features["image"],
                                 "gripper_pose": zero_proprio})
    return predict(variables, feats)

  result = evaluate_policy(policy, num_episodes=200, seed=4321,
                           image_size=knobs["image"])
  return {"success_rate": result["success_rate"]}


def check_maml(scale: str, workdir: str) -> dict:
  import jax
  import jax.numpy as jnp
  import optax

  from tensor2robot_tpu.research.pose_env import meta_reaching as mr
  from tensor2robot_tpu.research.pose_env.pose_env_maml_models import (
      pose_env_maml_model)
  from tensor2robot_tpu.train.trainer import Trainer

  knobs = _SCALES["maml"][scale]
  k_c = k_i = 4
  # Noisy demonstrations (meta_reaching.sample_meta_batch docstring):
  # condition labels jittered at BOTH train and eval by sigma = the
  # object radius (0.22; objects are >=0.48 apart). Measured r3
  # calibration path: with clean labels OR sigma=0.10 the check
  # saturates at 1.0 — the position comes from vision, label noise
  # only matters once it can flip which object the condition evidence
  # points at. At sigma=0.22 a fraction of tasks carry genuinely
  # misleading demonstrations, so success measures how well the
  # adapted policy integrates K noisy examples — a graded signal.
  noise = 0.22

  def build(num_inner_steps):
    return pose_env_maml_model(
        num_inner_steps=num_inner_steps, inner_lr=0.05,
        num_condition_samples=k_c, num_inference_samples=k_i,
        image_size=knobs["image"],
        optimizer_fn=lambda: optax.adam(1e-3))

  model = build(3)
  trainer = Trainer(model, seed=0)
  state = trainer.create_train_state()
  for step in range(knobs["steps"]):
    meta, _ = mr.sample_meta_batch(8, k_c, k_i, image_size=knobs["image"],
                                   seed=100_000 + step,
                                   condition_label_noise=noise)
    feats = trainer.shard_batch(jax.tree_util.tree_map(jnp.asarray, meta))
    state, _ = trainer.train_step(state, feats, None)
  meta, info = mr.sample_meta_batch(64, k_c, k_i,
                                    image_size=knobs["image"], seed=9999,
                                    condition_label_noise=noise)
  feats = jax.tree_util.tree_map(jnp.asarray, meta)
  variables = jax.device_get(state.variables())

  def predictions(m_eval):
    out, _ = m_eval.inference_network_fn(variables, feats, "eval")
    return np.asarray(out["inference_output"], np.float32)

  # Gate on a TIGHT reach radius (same design as the pose_env check):
  # at the full object radius (0.22) adapted success saturates — so the
  # gate would only catch the total-collapse failure mode. Half the
  # object radius under the sigma=0.22 condition noise above lands the
  # measured figure in the sensitive region (see _EXPECT), so subtler
  # adaptation-quality regressions move the gated number. The 0.22
  # figure (same predictions, re-bucketed) and the adapted-vs-unadapted
  # margin are also emitted; the margin is asserted as a secondary
  # check.
  tight = mr.OBJECT_RADIUS / 2
  adapted_preds = predictions(model)  # one adaptation+forward pass,
  # scored at both radii (the full inference over 64 tasks is the
  # expensive part, not the bucketing).
  adapted = mr.reach_success(adapted_preds, info, radius=tight)
  adapted_full = mr.reach_success(adapted_preds, info,
                                  radius=mr.OBJECT_RADIUS)
  unadapted = mr.reach_success(predictions(build(0)), info,
                               radius=mr.OBJECT_RADIUS)
  margin_ok = (adapted_full["success_rate"]
               >= unadapted["success_rate"] + 0.5)
  return {"success_rate": (adapted["success_rate"] if margin_ok
                           else 0.0),
          "success_rate_at_object_radius": adapted_full["success_rate"],
          "unadapted_success_rate": unadapted["success_rate"],
          "adapted_vs_unadapted_margin_ok": margin_ok,
          "metric": f"query reach within {tight:g} (half object "
                    "radius), gated on adapted-unadapted margin"}


_CHECKS = {
    "pose_env": check_pose_env,
    "qtopt": check_qtopt,
    "grasp2vec": check_grasp2vec,
    "vrgripper": check_vrgripper,
    "maml": check_maml,
}


def main(argv=None) -> int:
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument("--checks", default="all",
                      help="comma list of %s or 'all'" % sorted(_CHECKS))
  parser.add_argument("--scale", choices=("fast", "full"), default="fast")
  parser.add_argument("--workdir", default=None,
                      help="scratch dir (default: a TemporaryDirectory)")
  parser.add_argument("--seed-offset", type=int, default=0,
                      help="offsets TRAINING seeds in checks that "
                           "support it (currently vrgripper) for "
                           "seed-spread measurement; eval episodes "
                           "stay fixed")
  args = parser.parse_args(argv)
  names = (sorted(_CHECKS) if args.checks == "all"
           else [n.strip() for n in args.checks.split(",")])
  unknown = [n for n in names if n not in _CHECKS]
  if unknown:
    parser.error(f"Unknown checks {unknown}; have {sorted(_CHECKS)}")

  failures = 0
  with tempfile.TemporaryDirectory() as default_dir:
    workdir_root = args.workdir or default_dir
    for name in names:
      start = time.time()
      # Per-(check, scale) scratch dir, cleared first: train_eval_model
      # is resume-aware, so reusing a populated run dir would train 0
      # steps (or crash on shape mismatch across scales).
      workdir = os.path.join(workdir_root, f"{name}_{args.scale}")
      if os.path.isdir(workdir):
        import shutil
        shutil.rmtree(workdir)
      os.makedirs(workdir)
      record = {"check": name, "scale": args.scale}
      if args.seed_offset:
        record["seed_offset"] = args.seed_offset
      try:
        import inspect
        check_fn = _CHECKS[name]
        kwargs = {}
        if "seed_offset" in inspect.signature(check_fn).parameters:
          kwargs["seed_offset"] = args.seed_offset
        elif args.seed_offset:
          record["seed_offset_ignored"] = True
        result = check_fn(args.scale, workdir, **kwargs)
        expect = _EXPECT[(name, args.scale)]
        passed = bool(result["success_rate"] >= expect)
        record.update(
            {k: (round(float(v), 4) if isinstance(v, (int, float))
                 else v)
             for k, v in result.items()})
        record["expected_at_least"] = expect
      except Exception as e:  # isolate: one crashing family must not
        passed = False        # silence the remaining checks' report.
        record["error"] = f"{type(e).__name__}: {e}"
      failures += not passed
      record["passed"] = passed
      record["seconds"] = round(time.time() - start, 1)
      print(json.dumps(record), flush=True)
  return 1 if failures else 0


if __name__ == "__main__":
  sys.exit(main())

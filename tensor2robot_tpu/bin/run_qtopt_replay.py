"""Run the closed QT-Opt loop: collect → replay → Bellman-label → train.

The continuous-learning entry the reference never shipped in-repo
(its collectors/replay/Bellman fleet ran off-repo — SURVEY.md §2),
driving tensor2robot_tpu/replay end to end: CEMFleetPolicy collectors
on synthetic grasping, a sharded prioritized ring buffer, CEM-maximized
Bellman targets against a lagged target net, and the Trainer's AOT
train step — with the compiled-program ledger in the output.

    python -m tensor2robot_tpu.bin.run_qtopt_replay --smoke
    python -m tensor2robot_tpu.bin.run_qtopt_replay --smoke --device-resident
    python -m tensor2robot_tpu.bin.run_qtopt_replay --smoke \
        --device-resident --vector-actors
    python -m tensor2robot_tpu.bin.run_qtopt_replay --smoke --anakin

`--device-resident` (ISSUE 4) keeps replay state on device and fuses
K = megastep_inner sample→CEM-label→train→reprioritize iterations into
ONE donated megastep executable (replay/device_buffer.py); the default
is the PR 2 host-path loop, kept as the fallback. With
`--device-resident` the output additionally carries a
`learner_throughput` block (train steps/s, transitions/s, host-blocked
fraction, device-vs-host speedup at the same batch shape — the
replay/learner_bench.py comparison; skip with `--no-learner-bench`).

`--vector-actors` (ISSUE 5) replaces the threaded scalar collectors
with the vectorized actor fleet (replay/actor.py): every env steps in
lockstep through ONE fused CEM bucket executable, feeding the queue in
fixed fleet-size chunks, overlapped with the learner. Collection
semantics (retry budget, exploration mix, scene-seed stream) are
unchanged; the threaded path stays the default and the measured
fallback. The output additionally carries an `actor_throughput` block
(env steps/s, transitions/s, vector-vs-threaded speedup at the same
policy and env count, and the acting/learning overlap fraction — the
replay/actor_bench.py comparison; skip with `--no-actor-bench`).

`--anakin` (ISSUE 6) fuses the WHOLE loop: the JAX-native grasping env
(research/qtopt/jax_grasping.py), CEM acting, fixed-chunk replay
extend, and the learner inner body compile into ONE donated executable
(replay/anakin.py) scanning `anakin_inner` control steps per dispatch
— no collector threads, no queue, zero host work in the steady state.
The output carries an `anakin_throughput` block (fused vs numpy-fleet
env steps/s at the same env count and policy — both in their full
production shape, the collect-only baseline alongside — plus the
host-blocked fraction and the CEM scoring `dtype`; skip with
`--no-anakin-bench`). The vector-actor and threaded paths stay the
measured fallbacks.

`--mesh DP[,TP]` (ISSUE 7) runs the loop over an explicit dp×tp device
mesh instead of the single-process default. With `--anakin` this is
the pod-scale configuration: per-shard env fleets, the replay ring
capacity-sharded per device, the fused learn body data-parallel with
gradient all-reduce, and ZeRO-1 weight-update sharding applied inside
the scan — still exactly ONE `anakin_step` executable. In `--smoke`
mode a DP*TP > 1 mesh bootstraps DP*TP virtual CPU devices by
re-exec'ing with the canonical CPU-mesh environment (the
tests/conftest.py idiom); on a chip it meshes the first DP*TP real
devices. The r10 smoke protocol is `--smoke --anakin --mesh 8,1`; the
single-device `--anakin` run stays the unchanged semantics oracle.

Prints ONE JSON line (the repo's bench/driver contract): initial/final
eval Bellman residual, the reduction fraction, replay health counters,
and `compile_counts` (every value must be 1 — fixed-shape sampling
never recompiles; on the device path that includes exactly one
megastep executable, with vector actors exactly one acting executable
per bucket, and with --anakin exactly one fused anakin_step
executable). `--smoke` is the chipless CI scale (tier-1 asserts a
>= 30% residual reduction on it); the default scale is the same loop
with a bigger buffer/budget for on-chip runs. `--out` additionally
writes the same JSON to a file (the committed smoke artifact,
REPLAY_SMOKE_r09.json for this round).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def parse_profile(spec):
  """'START,END' -> (start, end) optimizer-step window; None passthrough."""
  if not spec:
    return None
  parts = spec.split(",")
  if len(parts) != 2:
    raise ValueError(f"--profile takes START,END steps, got {spec!r}")
  try:
    start, end = int(parts[0]), int(parts[1])
  except ValueError:
    raise ValueError(f"--profile takes integers, got {spec!r}")
  if start < 0 or end <= start:
    raise ValueError(
        f"--profile needs 0 <= START < END, got {spec!r}")
  return start, end


def parse_mesh(spec: str):
  """'8' or '4,2' -> (dp, tp). '0' keeps the mode default mesh."""
  parts = spec.split(",")
  if len(parts) > 2:
    raise ValueError(f"--mesh takes DP or DP,TP, got {spec!r}")
  try:
    dp = int(parts[0])
    tp = int(parts[1]) if len(parts) == 2 else 1
  except ValueError:
    raise ValueError(f"--mesh takes integers, got {spec!r}")
  if dp < 0 or tp < 1:
    raise ValueError(
        f"--mesh takes DP >= 1 (or 0 for the mode default) and "
        f"TP >= 1, got {spec!r}")
  if dp == 0 and tp != 1:
    # dp=0 keeps the mode-default mesh, which would silently discard
    # the requested TP degree — refuse instead.
    raise ValueError(
        f"--mesh 0,{tp} mixes the keep-default sentinel with an "
        "explicit TP degree; name DP explicitly (e.g. "
        f"--mesh 1,{tp}).")
  return dp, tp


def build_config(smoke: bool, seed: int, device_resident: bool = False,
                 vector_actors: bool = False, anakin: bool = False,
                 mesh=(0, 1), profile_window=None, precision: str = "f32"):
  from tensor2robot_tpu.replay.loop import ReplayLoopConfig
  dp, tp = mesh
  if smoke:
    # The sharded smoke keeps the r09 scale but rounds the env fleet,
    # sample batch, and ring capacity up to multiples of the data axis
    # (all three must shard over it, and the smoke CLI exposes no knob
    # to fix them by hand); power-of-two dp <= 8 keeps the exact
    # 4-env / batch-32 / capacity-512 oracle shapes.
    up = lambda v: -(-v // dp) * dp if (anakin and dp > 1) else v
    return ReplayLoopConfig(seed=seed, device_resident=device_resident,
                            vector_actors=vector_actors, anakin=anakin,
                            envs_per_collector=up(4), batch_size=up(32),
                            capacity=up(512), mesh_dp=dp, mesh_tp=tp,
                            profile_window=profile_window,
                            precision=precision)
  return ReplayLoopConfig(
      image_size=64, batch_size=32, capacity=50_000, min_fill=2_000,
      num_buffer_shards=4, num_collectors=4, envs_per_collector=8,
      queue_capacity=10_000, cem_num_samples=64, cem_num_elites=6,
      cem_iterations=3, refresh_every=200, eval_every=500,
      eval_batches=8, log_every=50, learning_rate=1e-4, seed=seed,
      device_resident=device_resident, megastep_inner=50,
      ingest_chunk=256, vector_actors=vector_actors, anakin=anakin,
      anakin_inner=200, anakin_bank_scenes=4096, mesh_dp=dp, mesh_tp=tp,
      profile_window=profile_window, precision=precision)


def run(steps: int, smoke: bool, logdir: str, seed: int,
        device_resident: bool = False, learner_bench: bool = True,
        vector_actors: bool = False, actor_bench: bool = True,
        anakin: bool = False, anakin_bench: bool = True,
        mesh=(0, 1), profile_window=None, precision: str = "f32") -> dict:
  from tensor2robot_tpu.replay.loop import ReplayTrainLoop
  config = build_config(smoke, seed, device_resident, vector_actors,
                        anakin, mesh=mesh, profile_window=profile_window,
                        precision=precision)
  model = None  # default: the flagship QTOptGraspingModel
  if smoke:
    # CI-scale critic (replay/smoke.py): the flagship's conv tower
    # cannot learn to discriminate within a smoke budget, so it would
    # prove the plumbing but not the learning claim.
    import optax
    from tensor2robot_tpu.replay.smoke import TinyQCriticModel
    model = TinyQCriticModel(
        image_size=config.image_size, action_size=config.action_size,
        optimizer_fn=lambda: optax.adam(config.learning_rate))
  loop = ReplayTrainLoop(config, logdir, model=model)
  results = loop.run(steps)
  if device_resident and learner_bench:
    # The ISSUE 4 acceptance block: device-vs-host learner throughput
    # at the same batch shape (collector-free; replay/learner_bench).
    from tensor2robot_tpu.replay.learner_bench import (
        measure_learner_throughput)
    results["learner_throughput"] = measure_learner_throughput(
        batch_size=config.batch_size,
        image_size=config.image_size if smoke else 16,
        action_size=config.action_size,
        inner_steps=config.megastep_inner if smoke else 10,
        steps_per_trial=3 * (config.megastep_inner if smoke else 10),
        cem_num_samples=config.cem_num_samples,
        cem_num_elites=config.cem_num_elites,
        cem_iterations=config.cem_iterations,
        gamma=config.gamma, seed=seed)
  if vector_actors and actor_bench:
    # The ISSUE 5 acceptance block: vector-vs-threaded actor throughput
    # at the same policy and env count, plus the acting/learning
    # overlap fraction (collector-free ratio; replay/actor_bench).
    from tensor2robot_tpu.replay.actor_bench import (
        measure_actor_throughput)
    results["actor_throughput"] = measure_actor_throughput(
        image_size=config.image_size if smoke else 16,
        action_size=config.action_size,
        max_attempts=config.max_attempts,
        grasp_radius=config.grasp_radius,
        exploration_epsilon=config.exploration_epsilon,
        scripted_fraction=config.scripted_fraction,
        cem_num_samples=config.cem_num_samples,
        cem_num_elites=config.cem_num_elites,
        cem_iterations=config.cem_iterations,
        batch_size=config.batch_size, gamma=config.gamma, seed=seed)
  if anakin and anakin_bench:
    # The ISSUE 6 acceptance block: fused-anakin vs numpy-vector-fleet
    # env throughput at the same env count and policy, plus the fused
    # loop's host-blocked fraction (replay/anakin_bench).
    from tensor2robot_tpu.replay.anakin_bench import (
        measure_anakin_throughput)
    results["anakin_throughput"] = measure_anakin_throughput(
        image_size=config.image_size if smoke else 16,
        action_size=config.action_size,
        max_attempts=config.max_attempts,
        grasp_radius=config.grasp_radius,
        exploration_epsilon=config.exploration_epsilon,
        scripted_fraction=config.scripted_fraction,
        cem_num_samples=config.cem_num_samples,
        cem_num_elites=config.cem_num_elites,
        cem_iterations=config.cem_iterations,
        train_every=config.anakin_train_every,
        batch_size=config.batch_size, gamma=config.gamma, seed=seed)
  results["mode"] = "smoke" if smoke else "full"
  results["metric"] = ("QT-Opt off-policy replay loop: eval Bellman "
                       "residual reduction")
  return results


def main(argv=None) -> None:
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument("--steps", type=int, default=0,
                      help="optimizer steps (0 = mode default)")
  parser.add_argument("--smoke", action="store_true",
                      help="chipless CI scale on the CPU backend")
  parser.add_argument("--device-resident", action="store_true",
                      help="device-resident replay + fused megastep "
                           "learner (numpy host path is the default)")
  parser.add_argument("--no-learner-bench", action="store_true",
                      help="skip the learner_throughput comparison "
                           "block on --device-resident runs")
  parser.add_argument("--vector-actors", action="store_true",
                      help="vectorized actor fleet: batched env "
                           "stepping through one fused CEM bucket "
                           "executable (threaded scalar collectors "
                           "are the default fallback)")
  parser.add_argument("--no-actor-bench", action="store_true",
                      help="skip the actor_throughput comparison "
                           "block on --vector-actors runs")
  parser.add_argument("--anakin", action="store_true",
                      help="fully fused Anakin loop: JAX-native env + "
                           "acting + replay extend + learner in ONE "
                           "donated executable (replay/anakin.py); "
                           "the vector-actor and threaded paths stay "
                           "the measured fallbacks")
  parser.add_argument("--no-anakin-bench", action="store_true",
                      help="skip the anakin_throughput comparison "
                           "block on --anakin runs")
  parser.add_argument("--mesh", default="0",
                      help="DP or DP,TP device mesh for the loop "
                           "(default: the mode's single-mesh default; "
                           "with --anakin this is the pod-scale "
                           "sharded configuration — ISSUE 7)")
  parser.add_argument("--precision", default="f32",
                      choices=("f32", "bf16"),
                      help="CEM Q-scoring tier (ISSUE 13): f32 = the "
                           "unchanged oracle (bit-identical lowering); "
                           "bf16 = low-precision scoring matmuls for "
                           "acting, Bellman labeling, and the "
                           "collectors' CEM policy — gradients, "
                           "optimizer state, TD priorities, and the "
                           "eval-vs-Q* metric stay f32")
  parser.add_argument("--profile", default=None,
                      help="START,END optimizer-step window for a "
                           "jax.profiler device-trace capture into "
                           "<logdir>/profile (the train ProfilerHook's "
                           "windowed capture, now on every replay "
                           "path; the window snaps outward to the "
                           "loop's dispatch boundaries, and the "
                           "guarded start_trace prevents a double "
                           "capture when another window is active)")
  parser.add_argument("--logdir", default=None,
                      help="metric_writer logdir (default: a tempdir)")
  parser.add_argument("--seed", type=int, default=0)
  parser.add_argument("--out", default=None,
                      help="also write the JSON line to this file")
  args = parser.parse_args(argv)
  mesh = parse_mesh(args.mesh)
  profile_window = parse_profile(args.profile)
  if args.smoke:
    n_devices = mesh[0] * mesh[1]
    if n_devices > 1:
      # A multi-device smoke needs the virtual CPU mesh configured
      # BEFORE JAX initializes (and the axon plugin var cleared — it
      # overrides platform selection in-process): re-exec with the
      # canonical environment, the tests/conftest.py idiom.
      # is_cpu_mesh_env is the loop guard: the re-exec'd process
      # passes it and falls through.
      from tensor2robot_tpu.utils.cpu_mesh_env import (cpu_mesh_env,
                                                       is_cpu_mesh_env)
      if not is_cpu_mesh_env(n_devices):
        if argv is not None:
          raise RuntimeError(
              "a multi-device --smoke mesh needs the virtual CPU mesh "
              "set up before JAX initializes; call main() with "
              "argv=None (the CLI re-execs itself) or pre-set "
              "cpu_mesh_env in the parent.")
        os.execve(sys.executable,
                  [sys.executable, "-m",
                   "tensor2robot_tpu.bin.run_qtopt_replay",
                   *sys.argv[1:]],
                  cpu_mesh_env(n_devices))
    # Chipless lane: pin the CPU backend before JAX initializes
    # (mirrors bench_serving --smoke; imports above are lazy for this).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
  steps = args.steps or (300 if args.smoke else 10_000)
  logdir = args.logdir or tempfile.mkdtemp(prefix="qtopt_replay_")
  results = run(steps, args.smoke, logdir, args.seed,
                device_resident=args.device_resident,
                learner_bench=not args.no_learner_bench,
                vector_actors=args.vector_actors,
                actor_bench=not args.no_actor_bench,
                anakin=args.anakin,
                anakin_bench=not args.no_anakin_bench,
                mesh=mesh, profile_window=profile_window,
                precision=args.precision)
  line = json.dumps(results)
  if args.out:
    with open(args.out, "w") as f:
      f.write(line + "\n")
  print(line)


if __name__ == "__main__":
  main()

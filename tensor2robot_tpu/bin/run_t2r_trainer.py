"""CLI trainer: config files + binding overrides → train_eval_model.

Reference parity: bin/run_t2r_trainer.py (SURVEY.md §3.1): the canonical
entry point —

    python -m tensor2robot_tpu.bin.run_t2r_trainer \
        --config research/pose_env/configs/train.cfg \
        --binding 'train_eval_model.max_train_steps = 100' \
        --model_dir /tmp/run1

Everything else (model, input generators, export, hooks) is injected via
the config system, exactly the reference's --gin_configs/--gin_bindings
two-level UX.
"""

from __future__ import annotations

import argparse
import importlib
import logging
import sys

from tensor2robot_tpu import config as t2r_config
from tensor2robot_tpu.train.train_eval import (
    continuous_eval_model,
    train_eval_model,
)


def main(argv=None) -> int:
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument("--config", action="append", default=[],
                      help="Config file path (repeatable; applied in order)")
  parser.add_argument("--binding", action="append", default=[],
                      help="Override binding, e.g. 'f.param = 1'"
                           " (repeatable; applied after files)")
  parser.add_argument("--model_dir", default=None,
                      help="Shortcut for train_eval_model.model_dir")
  parser.add_argument("--import_module", action="append", default=[],
                      help="Extra modules to import so their configurables "
                           "register (repeatable)")
  parser.add_argument("--mode", choices=("train_and_eval",
                                         "continuous_eval"),
                      default="train_and_eval",
                      help="train_and_eval runs train_eval_model; "
                           "continuous_eval runs the separate-job "
                           "evaluator polling model_dir's checkpoints "
                           "(configure continuous_eval_model.* bindings)")
  args = parser.parse_args(argv)

  logging.basicConfig(
      level=logging.INFO,
      format="%(asctime)s %(levelname)s %(name)s: %(message)s")

  # Standard components + research-model modules register on import.
  importlib.import_module("tensor2robot_tpu.config.registrations")
  for module in args.import_module:
    importlib.import_module(module)

  t2r_config.parse_config_files_and_bindings(args.config, args.binding)
  if args.model_dir:
    target = ("continuous_eval_model.model_dir"
              if args.mode == "continuous_eval"
              else "train_eval_model.model_dir")
    t2r_config.bind(target, args.model_dir)

  if args.mode == "continuous_eval":
    results = continuous_eval_model()
    logging.info("Evaluated %d checkpoints: %s", len(results),
                 sorted(results))
    return 0
  result = train_eval_model()
  logging.info("Final train metrics: %s", result.train_metrics)
  logging.info("Final eval metrics: %s", result.eval_metrics)
  return 0


if __name__ == "__main__":
  sys.exit(main())

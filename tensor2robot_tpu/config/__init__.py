"""t2r_config: dependency injection for run definitions.

Reference parity: SURVEY.md §5.6 — the reference is gin-config end-to-end
(two-level UX: .gin config files + --gin_bindings overrides, with the
operative config dumped to model_dir for reproducibility). gin is not in
this image, so this is a small native implementation of the same UX:
`@configurable` callables, `name.param = value` bindings with `@ref`,
`@ref()` and `%macro` values, file+override parsing, operative-config dump.
"""

from tensor2robot_tpu.config.config import (
    bind,
    clear_config,
    configurable,
    get_configurable,
    operative_config_str,
    parse_config,
    parse_config_files_and_bindings,
    query_binding,
)

__all__ = [
    "bind",
    "clear_config",
    "configurable",
    "get_configurable",
    "operative_config_str",
    "parse_config",
    "parse_config_files_and_bindings",
    "query_binding",
]

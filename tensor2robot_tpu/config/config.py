"""Minimal gin-style configuration system (own design, no gin dependency).

Syntax accepted in config files / binding strings:

    # comment
    train_eval_model.max_train_steps = 2000        # literal
    train_eval_model.model = @MockT2RModel()       # configured instance
    train_eval_model.export_generator = @NativeExportGenerator  # reference
    BATCH_SIZE = 64                                # macro (no dot)
    DefaultRecordInputGenerator.batch_size = %BATCH_SIZE
    nested.value = {"lr": 1e-4, "opt": @adam}      # refs inside literals

Semantics:
  - `@name` resolves to the registered configurable; `@name()` calls it
    (with its own bindings applied) at injection time.
  - Bindings fill *unsupplied* keyword arguments at call time; explicit
    call-site arguments always win.
  - `operative_config_str()` reports every binding actually consumed —
    the reference's operative_config.gin reproducibility artifact.
"""

from __future__ import annotations

import ast
import functools
import inspect
import re
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

_lock = threading.RLock()
_REGISTRY: Dict[str, Callable] = {}
_BINDINGS: Dict[str, Any] = {}          # "fn.param" -> raw parsed value
_MACROS: Dict[str, Any] = {}            # "NAME" -> raw parsed value
_OPERATIVE: Dict[str, Any] = {}         # bindings actually used


class _Ref:
  """Deferred reference to a configurable: @name or @name()."""

  def __init__(self, name: str, call: bool):
    self.name = name
    self.call = call

  def resolve(self) -> Any:
    target = get_configurable(self.name)
    return target() if self.call else target

  def __repr__(self):
    return f"@{self.name}" + ("()" if self.call else "")


class _Macro:
  """Deferred macro value: %NAME."""

  def __init__(self, name: str):
    self.name = name

  def resolve(self) -> Any:
    with _lock:
      if self.name not in _MACROS:
        raise ValueError(f"Undefined macro %{self.name}")
      return _resolve(_MACROS[self.name])

  def __repr__(self):
    return f"%{self.name}"


def _resolve(value: Any) -> Any:
  """Recursively resolves _Ref/_Macro placeholders inside parsed values."""
  if isinstance(value, (_Ref, _Macro)):
    return value.resolve()
  if isinstance(value, list):
    return [_resolve(v) for v in value]
  if isinstance(value, tuple):
    return tuple(_resolve(v) for v in value)
  if isinstance(value, dict):
    return {k: _resolve(v) for k, v in value.items()}
  return value


# --- registration ----------------------------------------------------------


def configurable(fn_or_name: Any = None, *, name: Optional[str] = None):
  """Registers a function/class; fills unsupplied kwargs from bindings.

  Usable bare (`@configurable`) or with a name
  (`@configurable(name="alias")`). Classes are registered with their
  __init__ wrapped.
  """
  def _register(target: Callable, reg_name: str):
    with _lock:
      existing = _REGISTRY.get(reg_name)
      if existing is not None:
        if existing is target:  # idempotent re-registration
          return existing
        raise ValueError(f"Configurable {reg_name!r} already registered.")

    if inspect.isclass(target):
      orig_init = target.__init__

      @functools.wraps(orig_init)
      def init_wrapper(self, *args, **kwargs):
        merged = _merge_bindings(reg_name, orig_init, args, kwargs,
                                 skip_self=True)
        orig_init(self, *args, **merged)

      target.__init__ = init_wrapper
      wrapped = target
    else:
      @functools.wraps(target)
      def wrapper(*args, **kwargs):
        merged = _merge_bindings(reg_name, target, args, kwargs)
        return target(*args, **merged)

      wrapped = wrapper
    with _lock:
      _REGISTRY[reg_name] = wrapped
    return wrapped

  if fn_or_name is None:
    return lambda target: _register(target, name or target.__name__)
  if isinstance(fn_or_name, str):
    return lambda target: _register(target, fn_or_name)
  return _register(fn_or_name, name or fn_or_name.__name__)


def _merge_bindings(reg_name: str, target: Callable, args, kwargs,
                    skip_self: bool = False) -> Dict[str, Any]:
  """kwargs + bindings for params not supplied positionally or by name."""
  try:
    sig = inspect.signature(target)
  except (TypeError, ValueError):
    return dict(kwargs)
  params = list(sig.parameters.values())
  if skip_self:
    params = params[1:]
  positional_names = {
      p.name for p in params[:len(args)]
      if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)}
  merged = dict(kwargs)
  with _lock:
    relevant = {key: v for key, v in _BINDINGS.items()
                if key.startswith(reg_name + ".")}
  has_var_kw = any(p.kind == p.VAR_KEYWORD for p in params)
  valid_names = {p.name for p in params}
  for key, raw in relevant.items():
    param = key[len(reg_name) + 1:]
    if param in merged or param in positional_names:
      continue
    if param not in valid_names and not has_var_kw:
      raise ValueError(
          f"Binding {key!r} names unknown parameter {param!r} of "
          f"{reg_name} (has: {sorted(valid_names)})")
    value = _resolve(raw)
    merged[param] = value
    with _lock:
      _OPERATIVE[key] = raw
  return merged


def get_configurable(name: str) -> Callable:
  with _lock:
    if name not in _REGISTRY:
      raise ValueError(
          f"Unknown configurable {name!r}; registered: "
          f"{sorted(_REGISTRY)}")
    return _REGISTRY[name]


# --- parsing ---------------------------------------------------------------

_TOKEN_RE = re.compile(r"(@[A-Za-z_][\w.]*(?:\(\))?|%[A-Za-z_][\w]*)")
_SENTINEL = "\x00t2r\x00"


def _scan_outside_strings(text: str):
  """Yields (index, char) for every char outside quoted string literals."""
  quote = None
  escaped = False
  for i, c in enumerate(text):
    if escaped:
      escaped = False
      continue
    if c == "\\":
      escaped = True
      continue
    if quote is not None:
      if c == quote:
        quote = None
      continue
    if c in "\"'":
      quote = c
      continue
    yield i, c


def _strip_comment(line: str) -> str:
  """Removes a trailing # comment, ignoring # inside string literals."""
  for i, c in _scan_outside_strings(line):
    if c == "#":
      return line[:i]
  return line


def _has_open_brackets(text: str) -> bool:
  """True if (), [], {} are unbalanced outside string literals."""
  depth = 0
  for _, c in _scan_outside_strings(text):
    if c in "([{":
      depth += 1
    elif c in ")]}":
      depth -= 1
  return depth > 0


def _quote_tokens(text: str) -> str:
  """Wraps @ref / %macro tokens in sentinel strings, skipping tokens that
  appear inside quoted string literals (e.g. emails, gs:// paths)."""
  starts = {i for i, c in _scan_outside_strings(text) if c in "@%"}
  out = []
  pos = 0
  for match in _TOKEN_RE.finditer(text):
    if match.start() not in starts:
      continue
    out.append(text[pos:match.start()])
    out.append(repr(_SENTINEL + match.group(1)))
    pos = match.end()
  out.append(text[pos:])
  return "".join(out)


def _parse_value(text: str) -> Any:
  """Parses a rhs: python literal with @ref / %macro tokens allowed."""
  text = text.strip()
  quoted = _quote_tokens(text)
  try:
    value = ast.literal_eval(quoted)
  except (ValueError, SyntaxError) as e:
    raise ValueError(f"Cannot parse config value: {text!r}") from e

  def _decode(v: Any) -> Any:
    if isinstance(v, str) and v.startswith(_SENTINEL):
      token = v[len(_SENTINEL):]
      if token.startswith("@"):
        call = token.endswith("()")
        return _Ref(token[1:-2] if call else token[1:], call)
      return _Macro(token[1:])
    if isinstance(v, list):
      return [_decode(x) for x in v]
    if isinstance(v, tuple):
      return tuple(_decode(x) for x in v)
    if isinstance(v, dict):
      return {k: _decode(x) for k, x in v.items()}
    return v

  return _decode(value)


def parse_config(lines: str) -> None:
  """Parses newline-separated binding statements."""
  # Join continuation lines (unbalanced brackets).
  pending = ""
  for raw_line in lines.splitlines():
    line = _strip_comment(raw_line).rstrip()
    if not line.strip():
      continue
    pending = (pending + " " + line).strip() if pending else line.strip()
    if _has_open_brackets(pending):
      continue
    statement, pending = pending, ""
    if "=" not in statement:
      raise ValueError(f"Malformed config line: {statement!r}")
    target, _, rhs = statement.partition("=")
    target = target.strip()
    value = _parse_value(rhs)
    bind(target, value)
  if pending:
    raise ValueError(f"Unterminated config statement: {pending!r}")


def bind(target: str, value: Any) -> None:
  """Binds `fn.param` (or macro NAME) to a value programmatically."""
  with _lock:
    if "." in target:
      _BINDINGS[target] = value
    else:
      _MACROS[target] = value


def query_binding(target: str) -> Any:
  with _lock:
    if "." in target:
      return _resolve(_BINDINGS[target])
    return _resolve(_MACROS[target])


def parse_config_files_and_bindings(
    config_files: Optional[Sequence[str]] = None,
    bindings: Optional[Sequence[str]] = None,
) -> None:
  """The reference CLI contract: files first, then override bindings."""
  for path in config_files or ():
    with open(path) as f:
      parse_config(f.read())
  for statement in bindings or ():
    parse_config(statement)


def operative_config_str() -> str:
  """Bindings actually consumed so far (reference: operative_config.gin)."""
  with _lock:
    macro_lines = [f"{k} = {v!r}" for k, v in sorted(_MACROS.items())]
    lines = [f"{k} = {v!r}" for k, v in sorted(_OPERATIVE.items())]
  return "\n".join(macro_lines + lines) + "\n"


def clear_config() -> None:
  """Clears bindings/macros/operative log (tests). Registry survives."""
  with _lock:
    _BINDINGS.clear()
    _MACROS.clear()
    _OPERATIVE.clear()

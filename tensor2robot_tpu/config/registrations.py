"""Registers the framework's standard components as configurables.

Imported by the CLI (and anyone using config files) so `@Name` references
resolve without per-module imports — the analogue of the reference's
modules importing gin at definition time.
"""

from tensor2robot_tpu.config import configurable

from tensor2robot_tpu.data.default_input_generator import (
    DefaultRandomInputGenerator,
    DefaultRecordInputGenerator,
    FractionalRecordInputGenerator,
    WeightedRecordInputGenerator,
)
from tensor2robot_tpu.export.native_export_generator import (
    NativeExportGenerator,
)
from tensor2robot_tpu.export.savedmodel_export_generator import (
    SavedModelExportGenerator,
)
from tensor2robot_tpu.export import exporters  # noqa: F401 (registers
# LatestExporter / BestExporter / create_default_exporters_fn)
from tensor2robot_tpu.hooks.async_export_hook import AsyncExportHookBuilder
from tensor2robot_tpu.utils import global_step_functions  # noqa: F401
from tensor2robot_tpu.utils import optimizers  # noqa: F401 (registers)
from tensor2robot_tpu.utils.mocks import MockT2RModel
from tensor2robot_tpu.utils.profiling import ProfilerHookBuilder

for _cls in (
    DefaultRandomInputGenerator,
    DefaultRecordInputGenerator,
    FractionalRecordInputGenerator,
    WeightedRecordInputGenerator,
    NativeExportGenerator,
    SavedModelExportGenerator,
    AsyncExportHookBuilder,
    MockT2RModel,
    ProfilerHookBuilder,
):
  configurable(_cls)

"""Data ingestion: record IO, example parsing, input generators, prefetch.

Reference parity: input_generators/ + the TF C++ RecordInput/parse_example
kernels the reference leaned on (SURVEY.md §2 "Input generators", §2 native
components table).
"""

from tensor2robot_tpu.data import example_proto
from tensor2robot_tpu.data import tfrecord
from tensor2robot_tpu.data.abstract_input_generator import (
    AbstractInputGenerator,
)
from tensor2robot_tpu.data.default_input_generator import (
    DefaultRandomInputGenerator,
    DefaultRecordInputGenerator,
    FractionalRecordInputGenerator,
    WeightedRecordInputGenerator,
)
from tensor2robot_tpu.data.parser import ExampleParser
from tensor2robot_tpu.data.prefetch import prefetch_to_device

__all__ = [
    "AbstractInputGenerator",
    "DefaultRandomInputGenerator",
    "DefaultRecordInputGenerator",
    "ExampleParser",
    "FractionalRecordInputGenerator",
    "WeightedRecordInputGenerator",
    "example_proto",
    "prefetch_to_device",
    "tfrecord",
]

// Native data-path kernels: CRC32C, TFRecord framing, JPEG decode.
//
// Reference parity: the reference's input pipeline got TFRecord framing,
// example parsing, and image decode from TensorFlow's C++ kernels
// (SURVEY.md §2 native-components table). This is the rebuild's native
// equivalent for the host-side hot loops, exposed as extern "C" and
// loaded from Python via ctypes (no pybind11 in the image).
//
// Build: python -m tensor2robot_tpu.data.build_native
//   (g++ -O3 -shared -fPIC native_data.cc -o libt2rnative.so -ljpeg)

#include <cstddef>
#include <cstdint>
#include <cstring>

#include <algorithm>
#include <atomic>
#include <csetjmp>
#include <cstdio>
#include <thread>
#include <vector>

extern "C" {
#include <jpeglib.h>
}

namespace {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli), table-driven, with TFRecord's masking.
// ---------------------------------------------------------------------------

// Table built at load time: ctypes calls drop the GIL, so lazy init
// with a plain flag would be a data race across loader threads.
struct CrcTable {
  uint32_t t[256];
  CrcTable() {
    const uint32_t poly = 0x82F63B78u;  // reflected Castagnoli
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t crc = i;
      for (int k = 0; k < 8; ++k) {
        crc = (crc & 1) ? (crc >> 1) ^ poly : crc >> 1;
      }
      t[i] = crc;
    }
  }
};
const CrcTable g_crc{};

uint32_t crc32c(const uint8_t* data, size_t len) {
  uint32_t crc = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; ++i) {
    crc = g_crc.t[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  }
  return crc ^ 0xFFFFFFFFu;
}

uint32_t masked_crc32c(const uint8_t* data, size_t len) {
  uint32_t crc = crc32c(data, len);
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

uint32_t read_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;  // little-endian hosts only (x86/ARM)
}

uint64_t read_u64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

}  // namespace

extern "C" {

uint32_t t2r_masked_crc32c(const uint8_t* data, uint64_t len) {
  return masked_crc32c(data, static_cast<size_t>(len));
}

// Indexes a whole TFRecord file buffer. Writes up to max_records
// (offset, length) pairs describing each record's payload. Returns the
// number of records found, or a negative error:
//   -1 truncated header/payload, -2 length-CRC mismatch,
//   -3 data-CRC mismatch, -4 more than max_records records.
int64_t t2r_tfrecord_index(const uint8_t* buf, uint64_t buf_len,
                           uint64_t* offsets, uint64_t* lengths,
                           uint64_t max_records, int32_t verify_crc) {
  uint64_t pos = 0;
  int64_t n = 0;
  while (pos < buf_len) {
    if (pos + 12 > buf_len) return -1;
    uint64_t rec_len = read_u64(buf + pos);
    if (verify_crc) {
      if (read_u32(buf + pos + 8) != masked_crc32c(buf + pos, 8)) return -2;
    }
    uint64_t data_start = pos + 12;
    // No-overflow bounds check: a corrupt length field must not wrap.
    uint64_t remaining = buf_len - data_start;
    if (remaining < 4 || rec_len > remaining - 4) return -1;
    if (verify_crc) {
      if (read_u32(buf + data_start + rec_len) !=
          masked_crc32c(buf + data_start, rec_len)) return -3;
    }
    if (static_cast<uint64_t>(n) >= max_records) return -4;
    offsets[n] = data_start;
    lengths[n] = rec_len;
    ++n;
    pos = data_start + rec_len + 4;
  }
  return n;
}

// ---------------------------------------------------------------------------
// JPEG decode via libjpeg.
// ---------------------------------------------------------------------------

struct T2rJpegError {
  jpeg_error_mgr mgr;
  jmp_buf jump;
};

void t2r_jpeg_error_exit(j_common_ptr cinfo) {
  T2rJpegError* err = reinterpret_cast<T2rJpegError*>(cinfo->err);
  longjmp(err->jump, 1);
}

// Reads image dimensions: returns 0 on success.
int32_t t2r_jpeg_info(const uint8_t* data, uint64_t len,
                      int32_t* width, int32_t* height,
                      int32_t* channels) {
  jpeg_decompress_struct cinfo;
  T2rJpegError jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = t2r_jpeg_error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  *width = cinfo.image_width;
  *height = cinfo.image_height;
  *channels = cinfo.num_components;
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Decodes into caller-allocated out (H*W*channels bytes). channels
// must be 1 or 3; libjpeg converts colorspace. Returns 0 on success.
int32_t t2r_jpeg_decode(const uint8_t* data, uint64_t len,
                        uint8_t* out, int32_t channels) {
  jpeg_decompress_struct cinfo;
  T2rJpegError jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = t2r_jpeg_error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = (channels == 1) ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const size_t row_stride =
      static_cast<size_t>(cinfo.output_width) * cinfo.output_components;
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out + row_stride * cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Decodes n JPEGs concurrently into one contiguous (n, h, w, channels)
// uint8 buffer. Every image must decode to exactly (h, w) — per-image
// status codes: 0 ok, -1 decode error, -2 dimension mismatch,
// -3 corrupt-but-recoverable data (libjpeg only warns on e.g.
// truncated entropy data and pads with gray; training data should not
// silently include such frames). Output slots are zeroed on any
// failure: an abort or recovery may already have written partial rows.
// Spawns min(num_threads, n)
// worker threads (libjpeg decompress objects are per-call, so decodes
// are independent); the caller holds no locks — from Python the ctypes
// call runs with the GIL released, so one call decodes a whole batch in
// parallel regardless of Python threading. Returns the failure count.
int32_t t2r_jpeg_decode_batch(const uint8_t* const* datas,
                              const uint64_t* lens, uint8_t* out,
                              int32_t expected_h, int32_t expected_w,
                              int32_t channels, int32_t n,
                              int32_t num_threads, int32_t* statuses) {
  if (n <= 0) return 0;
  const size_t image_bytes = static_cast<size_t>(expected_h) *
                             expected_w * channels;
  std::atomic<int32_t> next{0};
  std::atomic<int32_t> failures{0};

  auto worker = [&]() {
    for (;;) {
      const int32_t i = next.fetch_add(1);
      if (i >= n) return;
      uint8_t* dst = out + image_bytes * i;
      jpeg_decompress_struct cinfo;
      T2rJpegError jerr;
      cinfo.err = jpeg_std_error(&jerr.mgr);
      jerr.mgr.error_exit = t2r_jpeg_error_exit;
      if (setjmp(jerr.jump)) {
        jpeg_destroy_decompress(&cinfo);
        // A mid-stream abort may have written partial rows; the
        // contract is "failed slot is zeroed".
        std::memset(dst, 0, image_bytes);
        statuses[i] = -1;
        failures.fetch_add(1);
        continue;
      }
      jpeg_create_decompress(&cinfo);
      jpeg_mem_src(&cinfo, const_cast<uint8_t*>(datas[i]),
                   static_cast<unsigned long>(lens[i]));
      jpeg_read_header(&cinfo, TRUE);
      if (static_cast<int32_t>(cinfo.image_height) != expected_h ||
          static_cast<int32_t>(cinfo.image_width) != expected_w) {
        jpeg_destroy_decompress(&cinfo);
        // No rows were written, but the caller passes an uninitialized
        // output buffer (np.empty — zeroing 21 MB per 472² batch costs
        // ~6% of the 1-core pipeline), so the zeroed-slot contract is
        // enforced here for every failure path.
        std::memset(dst, 0, image_bytes);
        statuses[i] = -2;
        failures.fetch_add(1);
        continue;
      }
      cinfo.out_color_space = (channels == 1) ? JCS_GRAYSCALE : JCS_RGB;
      jpeg_start_decompress(&cinfo);
      const size_t row_stride =
          static_cast<size_t>(cinfo.output_width) * cinfo.output_components;
      while (cinfo.output_scanline < cinfo.output_height) {
        uint8_t* row = dst + row_stride * cinfo.output_scanline;
        jpeg_read_scanlines(&cinfo, &row, 1);
      }
      jpeg_finish_decompress(&cinfo);
      const bool corrupt = jerr.mgr.num_warnings > 0;
      jpeg_destroy_decompress(&cinfo);
      if (corrupt) {
        std::memset(dst, 0, image_bytes);
        statuses[i] = -3;
        failures.fetch_add(1);
        continue;
      }
      statuses[i] = 0;
    }
  };

  const int32_t threads =
      std::max(1, std::min(num_threads, n));
  if (threads == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(threads);
    for (int32_t t = 0; t < threads; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return failures.load();
}

// ---------------------------------------------------------------------------
// tf.Example wire-format parsing (schema in data/example_proto.py).
// The reference's input pipeline got this from TF's C++ parse_example
// kernels; this is the rebuild's native equivalent for the per-record
// hot loop. Proto semantics honored: unknown fields skipped, packed and
// unpacked repeated scalars both accepted, last map entry / last oneof
// field wins.
// ---------------------------------------------------------------------------

static bool pb_varint(const uint8_t** p, const uint8_t* end, uint64_t* v) {
  uint64_t result = 0;
  int shift = 0;
  while (*p < end && shift < 64) {
    const uint8_t b = *(*p)++;
    result |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *v = result;
      return true;
    }
    shift += 7;
  }
  return false;
}

static bool pb_skip(const uint8_t** p, const uint8_t* end, uint32_t wire) {
  uint64_t v;
  switch (wire) {
    case 0:
      return pb_varint(p, end, &v);
    case 1:
      if (end - *p < 8) return false;
      *p += 8;
      return true;
    case 2:
      if (!pb_varint(p, end, &v) ||
          static_cast<uint64_t>(end - *p) < v) return false;
      *p += v;
      return true;
    case 5:
      if (end - *p < 4) return false;
      *p += 4;
      return true;
    default:
      return false;
  }
}

// Locates the Feature submessage for `key` in a serialized Example.
// Returns 1 found (*feat/*feat_len set; last map entry wins), 0 not
// found, -4 malformed.
static int find_feature(const uint8_t* buf, uint64_t len,
                        const uint8_t* key, int32_t key_len,
                        const uint8_t** feat, uint64_t* feat_len) {
  const uint8_t* p = buf;
  const uint8_t* end = buf + len;
  bool found = false;
  while (p < end) {
    uint64_t tag;
    if (!pb_varint(&p, end, &tag)) return -4;
    if ((tag >> 3) == 1 && (tag & 7) == 2) {  // Example.features
      uint64_t flen;
      if (!pb_varint(&p, end, &flen) ||
          static_cast<uint64_t>(end - p) < flen) return -4;
      const uint8_t* fp = p;
      const uint8_t* fend = p + flen;
      p = fend;
      while (fp < fend) {  // Features.feature map entries
        uint64_t etag;
        if (!pb_varint(&fp, fend, &etag)) return -4;
        if ((etag >> 3) == 1 && (etag & 7) == 2) {
          uint64_t elen;
          if (!pb_varint(&fp, fend, &elen) ||
              static_cast<uint64_t>(fend - fp) < elen) return -4;
          const uint8_t* ep = fp;
          const uint8_t* eend = fp + elen;
          fp = eend;
          const uint8_t* k = nullptr;
          uint64_t klen = 0;
          const uint8_t* v = nullptr;
          uint64_t vlen = 0;
          while (ep < eend) {  // map entry: 1=key, 2=value
            uint64_t t2;
            if (!pb_varint(&ep, eend, &t2)) return -4;
            const uint32_t f2 = t2 >> 3, w2 = t2 & 7;
            if ((f2 == 1 || f2 == 2) && w2 == 2) {
              uint64_t l;
              if (!pb_varint(&ep, eend, &l) ||
                  static_cast<uint64_t>(eend - ep) < l) return -4;
              if (f2 == 1) {
                k = ep;
                klen = l;
              } else {
                v = ep;
                vlen = l;
              }
              ep += l;
            } else if (!pb_skip(&ep, eend, w2)) {
              return -4;
            }
          }
          if (k != nullptr && klen == static_cast<uint64_t>(key_len) &&
              std::memcmp(k, key, key_len) == 0) {
            *feat = v;
            *feat_len = vlen;
            found = true;  // keep scanning: last entry wins
          }
        } else if (!pb_skip(&fp, fend, etag & 7)) {
          return -4;
        }
      }
    } else if (!pb_skip(&p, end, tag & 7)) {
      return -4;
    }
  }
  return found ? 1 : 0;
}

// Extracts the set oneof list from a Feature: kind 1=BytesList,
// 2=FloatList, 3=Int64List. First kind field wins — matching the
// Python codec (example_proto.py §_decode_feature), which the fast
// path must stay bit-identical to. Returns 1 found, 0 empty feature,
// -4 malformed.
static int feature_list(const uint8_t* feat, uint64_t flen, int32_t* kind,
                        const uint8_t** list, uint64_t* list_len) {
  const uint8_t* p = feat;
  const uint8_t* end = feat + flen;
  while (p < end) {
    uint64_t tag;
    if (!pb_varint(&p, end, &tag)) return -4;
    const uint32_t field = tag >> 3, wire = tag & 7;
    if (field >= 1 && field <= 3 && wire == 2) {
      uint64_t l;
      if (!pb_varint(&p, end, &l) ||
          static_cast<uint64_t>(end - p) < l) return -4;
      *kind = static_cast<int32_t>(field);
      *list = p;
      *list_len = l;
      return 1;
    }
    if (!pb_skip(&p, end, wire)) return -4;
  }
  return 0;
}

// Parses FloatList content into out (exactly `cap` elements expected).
// Returns element count, -3 on overflow, -4 malformed.
static int64_t parse_floats(const uint8_t* list, uint64_t len, float* out,
                            int64_t cap) {
  const uint8_t* p = list;
  const uint8_t* end = list + len;
  int64_t n = 0;
  while (p < end) {
    uint64_t tag;
    if (!pb_varint(&p, end, &tag)) return -4;
    const uint32_t field = tag >> 3, wire = tag & 7;
    if (field == 1 && wire == 2) {  // packed
      uint64_t l;
      if (!pb_varint(&p, end, &l) ||
          static_cast<uint64_t>(end - p) < l) return -4;
      // Trailing bytes beyond a multiple of 4 are ignored, matching the
      // Python codec's size // 4 (example_proto.py §_decode_float_list).
      const int64_t cnt = static_cast<int64_t>(l / 4);
      if (n + cnt > cap) return -3;
      std::memcpy(out + n, p, cnt * 4);
      n += cnt;
      p += l;
    } else if (field == 1 && wire == 5) {  // unpacked
      if (end - p < 4) return -4;
      if (n + 1 > cap) return -3;
      std::memcpy(out + n, p, 4);
      n += 1;
      p += 4;
    } else if (!pb_skip(&p, end, wire)) {
      return -4;
    }
  }
  return n;
}

// Parses Int64List content into out. Same contract as parse_floats.
static int64_t parse_int64s(const uint8_t* list, uint64_t len, int64_t* out,
                            int64_t cap) {
  const uint8_t* p = list;
  const uint8_t* end = list + len;
  int64_t n = 0;
  while (p < end) {
    uint64_t tag;
    if (!pb_varint(&p, end, &tag)) return -4;
    const uint32_t field = tag >> 3, wire = tag & 7;
    if (field == 1 && wire == 2) {  // packed varints
      uint64_t l;
      if (!pb_varint(&p, end, &l) ||
          static_cast<uint64_t>(end - p) < l) return -4;
      const uint8_t* vp = p;
      const uint8_t* vend = p + l;
      p = vend;
      while (vp < vend) {
        uint64_t v;
        if (!pb_varint(&vp, vend, &v)) return -4;
        if (n + 1 > cap) return -3;
        out[n++] = static_cast<int64_t>(v);  // two's complement
      }
    } else if (field == 1 && wire == 0) {  // unpacked
      uint64_t v;
      if (!pb_varint(&p, end, &v)) return -4;
      if (n + 1 > cap) return -3;
      out[n++] = static_cast<int64_t>(v);
    } else if (!pb_skip(&p, end, wire)) {
      return -4;
    }
  }
  return n;
}

// Returns the FIRST bytes value's span; count of values via *count.
// Returns 0 ok, -4 malformed.
static int32_t parse_bytes_first(const uint8_t* list, uint64_t len,
                                 const uint8_t** ptr, uint64_t* blen,
                                 int64_t* count) {
  const uint8_t* p = list;
  const uint8_t* end = list + len;
  *count = 0;
  while (p < end) {
    uint64_t tag;
    if (!pb_varint(&p, end, &tag)) return -4;
    if ((tag >> 3) == 1 && (tag & 7) == 2) {
      uint64_t l;
      if (!pb_varint(&p, end, &l) ||
          static_cast<uint64_t>(end - p) < l) return -4;
      if (*count == 0) {
        *ptr = p;
        *blen = l;
      }
      ++(*count);
      p += l;
    } else if (!pb_skip(&p, end, tag & 7)) {
      return -4;
    }
  }
  return 0;
}

// Parses one dense numeric feature across a batch of records straight
// into a contiguous output array ((batch, elems), float32 for kind 2 /
// int64 for kind 3). Returns 0 ok; on failure sets *err_index to the
// offending record and returns: -1 feature missing, -2 kind mismatch
// (or empty feature), -3 element-count mismatch, -4 malformed proto.
int32_t t2r_example_batch_dense(const uint8_t* const* bufs,
                                const uint64_t* lens, int32_t batch,
                                const uint8_t* key, int32_t key_len,
                                int32_t kind, int64_t elems, void* out,
                                int64_t* err_index) {
  if (kind != 2 && kind != 3) return -2;
  for (int32_t b = 0; b < batch; ++b) {
    *err_index = b;
    const uint8_t* feat = nullptr;
    uint64_t flen = 0;
    int rc = find_feature(bufs[b], lens[b], key, key_len, &feat, &flen);
    if (rc < 0) return -4;
    if (rc == 0) return -1;
    int32_t fk = 0;
    const uint8_t* list = nullptr;
    uint64_t list_len = 0;
    rc = feature_list(feat, flen, &fk, &list, &list_len);
    if (rc < 0) return -4;
    if (rc == 0 || fk != kind) return -2;
    int64_t n;
    if (kind == 2) {
      n = parse_floats(list, list_len,
                       static_cast<float*>(out) + b * elems, elems);
    } else {
      n = parse_int64s(list, list_len,
                       static_cast<int64_t*>(out) + b * elems, elems);
    }
    if (n == -4) return -4;
    if (n < 0 || n != elems) return -3;
  }
  *err_index = -1;
  return 0;
}

// Extracts one bytes feature (first value) per record, zero-copy:
// ptrs[i]/out_lens[i] point INTO bufs[i]. Returns 0 ok; errors as in
// t2r_example_batch_dense.
int32_t t2r_example_batch_bytes(const uint8_t* const* bufs,
                                const uint64_t* lens, int32_t batch,
                                const uint8_t* key, int32_t key_len,
                                const uint8_t** ptrs, uint64_t* out_lens,
                                int64_t* err_index) {
  for (int32_t b = 0; b < batch; ++b) {
    *err_index = b;
    const uint8_t* feat = nullptr;
    uint64_t flen = 0;
    int rc = find_feature(bufs[b], lens[b], key, key_len, &feat, &flen);
    if (rc < 0) return -4;
    if (rc == 0) return -1;
    int32_t fk = 0;
    const uint8_t* list = nullptr;
    uint64_t list_len = 0;
    rc = feature_list(feat, flen, &fk, &list, &list_len);
    if (rc < 0) return -4;
    if (rc == 0 || fk != 1) return -2;
    int64_t count = 0;
    if (parse_bytes_first(list, list_len, &ptrs[b], &out_lens[b],
                          &count) != 0) return -4;
    if (count < 1) return -3;
  }
  *err_index = -1;
  return 0;
}

}  // extern "C"

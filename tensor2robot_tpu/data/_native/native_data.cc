// Native data-path kernels: CRC32C, TFRecord framing, JPEG decode.
//
// Reference parity: the reference's input pipeline got TFRecord framing,
// example parsing, and image decode from TensorFlow's C++ kernels
// (SURVEY.md §2 native-components table). This is the rebuild's native
// equivalent for the host-side hot loops, exposed as extern "C" and
// loaded from Python via ctypes (no pybind11 in the image).
//
// Build: python -m tensor2robot_tpu.data.build_native
//   (g++ -O3 -shared -fPIC native_data.cc -o libt2rnative.so -ljpeg)

#include <cstddef>
#include <cstdint>
#include <cstring>

#include <algorithm>
#include <atomic>
#include <csetjmp>
#include <cstdio>
#include <thread>
#include <vector>

extern "C" {
#include <jpeglib.h>
}

namespace {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli), table-driven, with TFRecord's masking.
// ---------------------------------------------------------------------------

// Table built at load time: ctypes calls drop the GIL, so lazy init
// with a plain flag would be a data race across loader threads.
struct CrcTable {
  uint32_t t[256];
  CrcTable() {
    const uint32_t poly = 0x82F63B78u;  // reflected Castagnoli
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t crc = i;
      for (int k = 0; k < 8; ++k) {
        crc = (crc & 1) ? (crc >> 1) ^ poly : crc >> 1;
      }
      t[i] = crc;
    }
  }
};
const CrcTable g_crc{};

uint32_t crc32c(const uint8_t* data, size_t len) {
  uint32_t crc = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; ++i) {
    crc = g_crc.t[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  }
  return crc ^ 0xFFFFFFFFu;
}

uint32_t masked_crc32c(const uint8_t* data, size_t len) {
  uint32_t crc = crc32c(data, len);
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

uint32_t read_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;  // little-endian hosts only (x86/ARM)
}

uint64_t read_u64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

}  // namespace

extern "C" {

uint32_t t2r_masked_crc32c(const uint8_t* data, uint64_t len) {
  return masked_crc32c(data, static_cast<size_t>(len));
}

// Indexes a whole TFRecord file buffer. Writes up to max_records
// (offset, length) pairs describing each record's payload. Returns the
// number of records found, or a negative error:
//   -1 truncated header/payload, -2 length-CRC mismatch,
//   -3 data-CRC mismatch, -4 more than max_records records.
int64_t t2r_tfrecord_index(const uint8_t* buf, uint64_t buf_len,
                           uint64_t* offsets, uint64_t* lengths,
                           uint64_t max_records, int32_t verify_crc) {
  uint64_t pos = 0;
  int64_t n = 0;
  while (pos < buf_len) {
    if (pos + 12 > buf_len) return -1;
    uint64_t rec_len = read_u64(buf + pos);
    if (verify_crc) {
      if (read_u32(buf + pos + 8) != masked_crc32c(buf + pos, 8)) return -2;
    }
    uint64_t data_start = pos + 12;
    // No-overflow bounds check: a corrupt length field must not wrap.
    uint64_t remaining = buf_len - data_start;
    if (remaining < 4 || rec_len > remaining - 4) return -1;
    if (verify_crc) {
      if (read_u32(buf + data_start + rec_len) !=
          masked_crc32c(buf + data_start, rec_len)) return -3;
    }
    if (static_cast<uint64_t>(n) >= max_records) return -4;
    offsets[n] = data_start;
    lengths[n] = rec_len;
    ++n;
    pos = data_start + rec_len + 4;
  }
  return n;
}

// ---------------------------------------------------------------------------
// JPEG decode via libjpeg.
// ---------------------------------------------------------------------------

struct T2rJpegError {
  jpeg_error_mgr mgr;
  jmp_buf jump;
};

void t2r_jpeg_error_exit(j_common_ptr cinfo) {
  T2rJpegError* err = reinterpret_cast<T2rJpegError*>(cinfo->err);
  longjmp(err->jump, 1);
}

// Reads image dimensions: returns 0 on success.
int32_t t2r_jpeg_info(const uint8_t* data, uint64_t len,
                      int32_t* width, int32_t* height,
                      int32_t* channels) {
  jpeg_decompress_struct cinfo;
  T2rJpegError jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = t2r_jpeg_error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  *width = cinfo.image_width;
  *height = cinfo.image_height;
  *channels = cinfo.num_components;
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Decodes into caller-allocated out (H*W*channels bytes). channels
// must be 1 or 3; libjpeg converts colorspace. Returns 0 on success.
int32_t t2r_jpeg_decode(const uint8_t* data, uint64_t len,
                        uint8_t* out, int32_t channels) {
  jpeg_decompress_struct cinfo;
  T2rJpegError jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = t2r_jpeg_error_exit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = (channels == 1) ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const size_t row_stride =
      static_cast<size_t>(cinfo.output_width) * cinfo.output_components;
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out + row_stride * cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Decodes n JPEGs concurrently into one contiguous (n, h, w, channels)
// uint8 buffer. Every image must decode to exactly (h, w) — per-image
// status codes: 0 ok, -1 decode error, -2 dimension mismatch,
// -3 corrupt-but-recoverable data (libjpeg only warns on e.g.
// truncated entropy data and pads with gray; training data should not
// silently include such frames). Output slots are zeroed on any
// failure: an abort or recovery may already have written partial rows.
// Spawns min(num_threads, n)
// worker threads (libjpeg decompress objects are per-call, so decodes
// are independent); the caller holds no locks — from Python the ctypes
// call runs with the GIL released, so one call decodes a whole batch in
// parallel regardless of Python threading. Returns the failure count.
int32_t t2r_jpeg_decode_batch(const uint8_t* const* datas,
                              const uint64_t* lens, uint8_t* out,
                              int32_t expected_h, int32_t expected_w,
                              int32_t channels, int32_t n,
                              int32_t num_threads, int32_t* statuses) {
  if (n <= 0) return 0;
  const size_t image_bytes = static_cast<size_t>(expected_h) *
                             expected_w * channels;
  std::atomic<int32_t> next{0};
  std::atomic<int32_t> failures{0};

  auto worker = [&]() {
    for (;;) {
      const int32_t i = next.fetch_add(1);
      if (i >= n) return;
      uint8_t* dst = out + image_bytes * i;
      jpeg_decompress_struct cinfo;
      T2rJpegError jerr;
      cinfo.err = jpeg_std_error(&jerr.mgr);
      jerr.mgr.error_exit = t2r_jpeg_error_exit;
      if (setjmp(jerr.jump)) {
        jpeg_destroy_decompress(&cinfo);
        // A mid-stream abort may have written partial rows; the
        // contract is "failed slot is zeroed".
        std::memset(dst, 0, image_bytes);
        statuses[i] = -1;
        failures.fetch_add(1);
        continue;
      }
      jpeg_create_decompress(&cinfo);
      jpeg_mem_src(&cinfo, const_cast<uint8_t*>(datas[i]),
                   static_cast<unsigned long>(lens[i]));
      jpeg_read_header(&cinfo, TRUE);
      if (static_cast<int32_t>(cinfo.image_height) != expected_h ||
          static_cast<int32_t>(cinfo.image_width) != expected_w) {
        jpeg_destroy_decompress(&cinfo);
        statuses[i] = -2;
        failures.fetch_add(1);
        continue;
      }
      cinfo.out_color_space = (channels == 1) ? JCS_GRAYSCALE : JCS_RGB;
      jpeg_start_decompress(&cinfo);
      const size_t row_stride =
          static_cast<size_t>(cinfo.output_width) * cinfo.output_components;
      while (cinfo.output_scanline < cinfo.output_height) {
        uint8_t* row = dst + row_stride * cinfo.output_scanline;
        jpeg_read_scanlines(&cinfo, &row, 1);
      }
      jpeg_finish_decompress(&cinfo);
      const bool corrupt = jerr.mgr.num_warnings > 0;
      jpeg_destroy_decompress(&cinfo);
      if (corrupt) {
        std::memset(dst, 0, image_bytes);
        statuses[i] = -3;
        failures.fetch_add(1);
        continue;
      }
      statuses[i] = 0;
    }
  };

  const int32_t threads =
      std::max(1, std::min(num_threads, n));
  if (threads == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(threads);
    for (int32_t t = 0; t < threads; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return failures.load();
}

}  // extern "C"

"""Abstract input generator — builds host-side batch iterators from specs.

Reference parity: input_generators/abstract_input_generator.py
§AbstractInputGenerator (SURVEY.md §2). Where the reference produced an
Estimator ``input_fn`` returning a tf.data graph, the rebuild produces a
plain Python factory of numpy batch iterators: parsing/decode/preprocess run
host-side, and ``data.prefetch_to_device`` overlaps the H2D transfer with
compute under whatever sharding the trainer passes.

Per-host data sharding (the TPUEstimator per-host input_fn equivalent) is a
first-class constructor arg: ``shard_index/num_shards`` partition files (or
the random stream) so each host feeds only its slice of the global batch.
"""

from __future__ import annotations

import abc
from typing import Callable, Iterator, Optional, Tuple

from tensor2robot_tpu import modes
from tensor2robot_tpu.modes import EVAL, PREDICT, TRAIN  # noqa: F401 (re-export)
from tensor2robot_tpu.specs import tensorspec_utils as ts

# A batch is (features, labels) — both flat TensorSpecStructs of numpy
# arrays with a leading (per-host) batch dim.
Batch = Tuple[ts.TensorSpecStruct, ts.TensorSpecStruct]


class AbstractInputGenerator(abc.ABC):
  """Builds per-host batch iterators conforming to a model's specs."""

  def __init__(
      self,
      batch_size: int = 32,
      shard_index: int = 0,
      num_shards: int = 1,
  ):
    if batch_size <= 0:
      raise ValueError(f"batch_size must be positive, got {batch_size}")
    if not 0 <= shard_index < num_shards:
      raise ValueError(
          f"shard_index {shard_index} out of range for {num_shards} shards")
    self._batch_size = batch_size
    self._shard_index = shard_index
    self._num_shards = num_shards
    self._feature_spec: Optional[ts.TensorSpecStruct] = None
    self._label_spec: Optional[ts.TensorSpecStruct] = None
    self._preprocess_fn: Optional[Callable[..., Batch]] = None
    self._wired_mode: Optional[str] = None

  # --- spec wiring (reference §set_specification_from_model) --------------

  def set_specification_from_model(self, model, mode: str) -> None:
    """Pulls in/out specs + preprocessor from a T2R model.

    The input pipeline parses what the model's *preprocessor* consumes
    (its in-specs) and emits what the model consumes (the preprocessor's
    out-specs), exactly as in the reference's input_fn wiring
    (SURVEY.md §3.1).
    """
    preprocessor = model.preprocessor
    self.set_specification(
        feature_spec=preprocessor.get_in_feature_specification(mode),
        label_spec=preprocessor.get_in_label_specification(mode),
    )
    self._preprocess_fn = lambda features, labels: preprocessor.preprocess(
        features, labels, mode)
    self._wired_mode = mode

  def set_specification(
      self,
      feature_spec: ts.SpecStructure,
      label_spec: Optional[ts.SpecStructure] = None,
  ) -> None:
    ts.assert_valid_spec_structure(feature_spec)
    self._feature_spec = ts.flatten_spec_structure(feature_spec)
    if label_spec is not None:
      ts.assert_valid_spec_structure(label_spec)
      self._label_spec = ts.flatten_spec_structure(label_spec)
    else:
      self._label_spec = ts.TensorSpecStruct()

  @property
  def batch_size(self) -> int:
    """Per-host batch size (global batch = batch_size × num_hosts)."""
    return self._batch_size

  @property
  def feature_spec(self) -> ts.TensorSpecStruct:
    self._assert_specs_set()
    return self._feature_spec

  @property
  def label_spec(self) -> ts.TensorSpecStruct:
    self._assert_specs_set()
    return self._label_spec

  def _assert_specs_set(self) -> None:
    if self._feature_spec is None:
      raise ValueError(
          "Input generator has no specs; call set_specification_from_model "
          "or set_specification first.")

  # --- pipeline construction ----------------------------------------------

  def create_dataset_fn(self, mode: str) -> Callable[[], Iterator[Batch]]:
    """Returns a factory of fresh batch iterators for `mode`.

    The factory (not a shared iterator) is returned so train and
    continuous-eval can each restart their streams — the analogue of the
    reference's create_dataset_input_fn returning an input_fn.
    """
    modes.validate_mode(mode)
    self._assert_specs_set()
    if self._preprocess_fn is not None and mode != self._wired_mode:
      raise ValueError(
          f"Input generator was wired for mode {self._wired_mode!r} (its "
          f"preprocess closure is mode-bound) but asked to produce "
          f"{mode!r}; call set_specification_from_model(model, {mode!r}) "
          "first.")

    def factory() -> Iterator[Batch]:
      iterator = self._create_iterator(mode)
      if self._preprocess_fn is None:
        return iterator
      return (self._preprocess_fn(f, l) for f, l in iterator)

    return factory

  @abc.abstractmethod
  def _create_iterator(self, mode: str) -> Iterator[Batch]:
    """Yields raw (pre-preprocessor) spec-conformant batches."""

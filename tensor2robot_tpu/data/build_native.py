"""Builds the native data-path library (g++, links libjpeg).

Usage: python -m tensor2robot_tpu.data.build_native
The library is optional: every consumer falls back to the pure-Python
implementations when it is absent or fails to build.
"""

from __future__ import annotations

import os
import subprocess
import sys

_THIS_DIR = os.path.dirname(os.path.abspath(__file__))
SOURCE = os.path.join(_THIS_DIR, "_native", "native_data.cc")
LIBRARY = os.path.join(_THIS_DIR, "_native", "libt2rnative.so")


def build(verbose: bool = True) -> str:
  """Compiles the shared library; returns its path."""
  cmd = [
      "g++", "-O3", "-march=native", "-shared", "-fPIC", "-pthread",
      SOURCE, "-o", LIBRARY, "-ljpeg",
  ]
  result = subprocess.run(cmd, capture_output=True, text=True)
  if result.returncode != 0:
    raise RuntimeError(
        f"native build failed:\n{result.stderr[-2000:]}")
  if verbose:
    print(f"Built {LIBRARY}")
  return LIBRARY


def main() -> int:
  build()
  return 0


if __name__ == "__main__":
  sys.exit(main())

"""Builds the native data-path library (g++, links libjpeg).

Usage: python -m tensor2robot_tpu.data.build_native
The library is optional: every consumer falls back to the pure-Python
implementations when it is absent or fails to build.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sys

_THIS_DIR = os.path.dirname(os.path.abspath(__file__))
SOURCE = os.path.join(_THIS_DIR, "_native", "native_data.cc")
LIBRARY = os.path.join(_THIS_DIR, "_native", "libt2rnative.so")
# Sidecar recording the sha256 of the source the .so was built from.
# Staleness is decided by content hash, NOT mtime ordering: a copied or
# touched .so artifact can carry an mtime newer than an updated source
# while holding pre-update code (ADVICE r3) — with the old mtime rule it
# would be trusted and could violate newer ABI contracts (e.g. return
# uninitialized memory for failure modes the update started zeroing).
HASH_SIDECAR = LIBRARY + ".srchash"


def source_hash() -> str:
  with open(SOURCE, "rb") as f:
    return hashlib.sha256(f.read()).hexdigest()


def library_is_current() -> bool:
  """True iff the built .so exists and matches the current source."""
  if not os.path.exists(LIBRARY):
    return False
  try:
    with open(HASH_SIDECAR) as f:
      recorded = f.read().strip()
  except OSError:
    return False  # no provenance record → rebuild
  return recorded == source_hash()


def build(verbose: bool = True) -> str:
  """Compiles the shared library; returns its path."""
  cmd = [
      "g++", "-O3", "-march=native", "-shared", "-fPIC", "-pthread",
      SOURCE, "-o", LIBRARY, "-ljpeg",
  ]
  result = subprocess.run(cmd, capture_output=True, text=True)
  if result.returncode != 0:
    raise RuntimeError(
        f"native build failed:\n{result.stderr[-2000:]}")
  with open(HASH_SIDECAR, "w") as f:
    f.write(source_hash() + "\n")
  if verbose:
    print(f"Built {LIBRARY}")
  return LIBRARY


def main() -> int:
  build()
  return 0


if __name__ == "__main__":
  sys.exit(main())

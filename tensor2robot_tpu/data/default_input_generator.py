"""Default input generators: random (mock stack) and TFRecord-backed.

Reference parity: input_generators/default_input_generator.py
§DefaultRecordInputGenerator, §DefaultRandomInputGenerator,
§FractionalRecordInputGenerator, §WeightedRecordInputGenerator
(SURVEY.md §2).
"""

from __future__ import annotations

import concurrent.futures
import itertools
import logging
import queue
import sys
import threading
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from tensor2robot_tpu.data import tfrecord
from tensor2robot_tpu.data.abstract_input_generator import (
    TRAIN,
    AbstractInputGenerator,
    Batch,
)
from tensor2robot_tpu.data.parser import ExampleParser
from tensor2robot_tpu.specs import tensorspec_utils as ts

_log = logging.getLogger(__name__)

_NATIVE_MODES = ("auto", "native", "python")


def _apply_native_mode(
    parser: ExampleParser,
    record_stream: Iterator[bytes],
    batch_size: int,
    native_mode: str,
) -> "tuple[Iterator[bytes], Dict]":
  """Pins or calibrates the parser's native path; returns the (possibly
  re-chained) record stream and a stats dict for `pipeline_stats`.

  "auto" peels one batch of records off the stream, times parse_batch
  both ways on it (interleaved — parser.calibrate_native), pins the
  winner, and chains the peeled records back so nothing is dropped or
  reordered. The one-batch cost (6 parses) is noise next to the jit
  compile every training run pays; the payoff is that the pipeline
  never runs a path that measures slower on the host it actually
  landed on (VERDICT r3 Weak #1: the native/python ratio is
  host-dependent — 1.39x on a quiet box, 0.56x on a contended one).
  """
  if native_mode not in _NATIVE_MODES:
    raise ValueError(
        f"native_mode must be one of {_NATIVE_MODES}, got {native_mode!r}")
  if native_mode != "auto":
    parser.set_native_enabled(native_mode == "native")
    return record_stream, {"native_calibration": {
        "decision": native_mode, "reason": "pinned by native_mode"}}
  head = list(itertools.islice(record_stream, batch_size))
  if len(head) < batch_size:
    # Not even one full batch (tiny eval set): nothing to measure, and
    # drop_remainder means these records produce no batch anyway.
    stats = {"decision": "native-if-available",
             "reason": "dataset smaller than one batch; not calibrated"}
  else:
    stats = parser.calibrate_native(head)
    _log.info("input pipeline native calibration: %s", stats)
  return itertools.chain(iter(head), record_stream), {
      "native_calibration": stats}


def _pipelined_parse(
    record_stream: Iterator[bytes],
    parser: ExampleParser,
    batch_size: int,
    num_threads: int,
    prefetch_batches: int,
) -> Iterator[Batch]:
  """Reader thread + parse pool → ordered, bounded stream of parsed batches.

  Shutdown contract: abandoning the returned iterator (close/GC) stops the
  reader thread and parse pool promptly — every blocking put uses a timeout
  loop against the stop event, so no thread can leak blocked on a full
  queue (the reference got this lifecycle from tf.data's C++ runtime).
  """
  stop = threading.Event()
  sentinel = object()
  # Bounded queue of *futures* preserves batch order while the pool parses
  # up to num_threads batches concurrently.
  futures: "queue.Queue" = queue.Queue(maxsize=max(1, prefetch_batches))
  pool = concurrent.futures.ThreadPoolExecutor(
      max_workers=max(1, num_threads), thread_name_prefix="t2r-parse")

  def put_checked(item) -> bool:
    while not stop.is_set():
      try:
        futures.put(item, timeout=0.1)
        return True
      except queue.Full:
        continue
    return False

  def reader() -> None:
    try:
      while not stop.is_set():
        records = list(itertools.islice(record_stream, batch_size))
        if len(records) < batch_size:
          # drop_remainder semantics: static shapes only (XLA contract).
          break
        if not put_checked(pool.submit(parser.parse_batch, records)):
          return
    except Exception as e:  # reader-side errors surface to the consumer
      put_checked(e)
      return
    put_checked(sentinel)

  thread = threading.Thread(target=reader, daemon=True, name="t2r-reader")
  thread.start()

  # Bound at definition time: during late interpreter shutdown, module
  # globals (`sys` included) may already be cleared when the finalizer
  # below runs, and the guard itself must not throw.
  is_finalizing = sys.is_finalizing

  def iterator() -> Iterator[Batch]:
    try:
      while True:
        item = futures.get()
        if item is sentinel:
          return
        if isinstance(item, Exception):
          raise item
        yield item.result()  # re-raises parse errors with traceback
    finally:
      stop.set()
      # When an ABANDONED iterator is finalized at interpreter exit, do
      # NOT touch the queue or the pool: finalization kills daemon
      # threads at their next GIL acquisition, so the reader can die
      # holding the futures-queue mutex or (inside pool.submit) the
      # executor's _shutdown_lock — and get_nowait()/pool.shutdown()
      # here would futex-wait on a poisoned lock forever, wedging the
      # exiting process (observed: main thread stuck in
      # ThreadPoolExecutor.shutdown under the native parser). The
      # threads cannot outlive the process; stop.set() is enough.
      if not is_finalizing():
        # Unblock a reader stuck between put attempts and let the pool
        # die promptly on ordinary mid-run abandonment. Both drains are
        # best-effort (except Exception: a racing reader may refill the
        # queue between get_nowait calls).
        try:
          while True:
            futures.get_nowait()
        except Exception:
          pass
        try:
          pool.shutdown(wait=False, cancel_futures=True)
        except Exception:
          pass

  return iterator()


class DefaultRandomInputGenerator(AbstractInputGenerator):
  """Spec-conformant random batches — the test/smoke workhorse.

  Reference: §DefaultRandomInputGenerator. Together with the mock model it
  lets the *real* train loop run a few steps with no data files and no
  accelerator (SURVEY.md §4 "the reference's core testing idea").
  """

  def __init__(self, seed: int = 0, **kwargs):
    super().__init__(**kwargs)
    self._seed = seed

  def _create_iterator(self, mode: str) -> Iterator[Batch]:
    # Different hosts draw different streams (per-host data sharding).
    rng = np.random.default_rng(self._seed + 7919 * self._shard_index)
    while True:
      features = ts.make_random_batch(
          self.feature_spec, self._batch_size, rng=rng,
          include_optional=False)
      labels = ts.make_random_batch(
          self.label_spec, self._batch_size, rng=rng,
          include_optional=False)
      yield features, labels


class DefaultRecordInputGenerator(AbstractInputGenerator):
  """TFRecord-backed batches: read → parse → decode → batch, host-side.

  Reference: §DefaultRecordInputGenerator (tf.data parallel-interleave +
  parse_example + decode). The rebuild runs the pipeline on host Python
  threads with a bounded batch queue; the C++ native reader (data/native)
  drops in underneath for throughput. Files are sharded round-robin across
  hosts before shuffling (the per-host input_fn contract).

  Args:
    file_patterns: comma-separated glob patterns of TFRecord files.
    shuffle_buffer_size: record-level shuffle window (train mode only).
    num_pipeline_threads: background parse/decode threads.
    prefetch_batches: bounded queue depth between parser and consumer.
    native_mode: "auto" (default — time one batch through the C++ and
      the pure-Python parser at startup, pin the winner for this
      pipeline, record the choice in `pipeline_stats`), "native"
      (prefer C++ whenever the library loads), or "python" (pure
      Python end to end). Both paths are bit-exact-tested equal
      (tests/test_native.py), so the choice is purely a speed policy;
      T2R_DISABLE_NATIVE=1 still force-disables native globally.
  """

  def __init__(
      self,
      file_patterns: str,
      shuffle_buffer_size: int = 1024,
      num_pipeline_threads: int = 4,
      prefetch_batches: int = 4,
      seed: int = 0,
      native_mode: str = "auto",
      **kwargs,
  ):
    super().__init__(**kwargs)
    if native_mode not in _NATIVE_MODES:
      raise ValueError(
          f"native_mode must be one of {_NATIVE_MODES}, got {native_mode!r}")
    self._file_patterns = file_patterns
    self._shuffle_buffer_size = shuffle_buffer_size
    self._num_pipeline_threads = max(1, num_pipeline_threads)
    self._prefetch_batches = max(1, prefetch_batches)
    self._seed = seed
    self._native_mode = native_mode
    # Stats of the most recently created pipeline (calibration outcome).
    self.pipeline_stats: Dict = {}

  def _shard_files(self) -> List[str]:
    files = tfrecord.list_files(self._file_patterns)
    shard = files[self._shard_index::self._num_shards]
    if not shard:
      raise ValueError(
          f"Host shard {self._shard_index}/{self._num_shards} got no files "
          f"out of {len(files)}; need at least one file per host.")
    return shard

  def _record_stream(self, mode: str) -> Iterator[bytes]:
    """Infinite (train) or single-pass (eval) stream of raw records."""
    files = self._shard_files()
    rng = np.random.default_rng(self._seed + 7919 * self._shard_index)
    epoch = itertools.count()
    for _ in (epoch if mode == TRAIN else range(1)):
      order = list(files)
      if mode == TRAIN:
        rng.shuffle(order)
      if mode == TRAIN and self._shuffle_buffer_size > 1:
        buffer: List[bytes] = []
        for path in order:
          for record in tfrecord.read_tfrecords(path):
            buffer.append(record)
            if len(buffer) >= self._shuffle_buffer_size:
              idx = rng.integers(len(buffer))
              buffer[idx], buffer[-1] = buffer[-1], buffer[idx]
              yield buffer.pop()
        rng.shuffle(buffer)
        yield from buffer
      else:
        for path in order:
          yield from tfrecord.read_tfrecords(path)

  def _create_iterator(self, mode: str) -> Iterator[Batch]:
    parser = ExampleParser(self.feature_spec, self.label_spec)
    stream, stats = _apply_native_mode(
        parser, self._record_stream(mode), self._batch_size,
        self._native_mode)
    self.pipeline_stats = stats
    return _pipelined_parse(
        record_stream=stream,
        parser=parser,
        batch_size=self._batch_size,
        num_threads=self._num_pipeline_threads,
        prefetch_batches=self._prefetch_batches,
    )


class FractionalRecordInputGenerator(DefaultRecordInputGenerator):
  """Trains on the first `file_fraction` of the (sorted) file list.

  Reference: §FractionalRecordInputGenerator — data-efficiency ablations.
  """

  def __init__(self, file_patterns: str, file_fraction: float = 1.0,
               **kwargs):
    if not 0.0 < file_fraction <= 1.0:
      raise ValueError(f"file_fraction must be in (0, 1], got {file_fraction}")
    super().__init__(file_patterns, **kwargs)
    self._file_fraction = file_fraction

  def _shard_files(self) -> List[str]:
    files = tfrecord.list_files(self._file_patterns)
    keep = max(1, int(round(self._file_fraction * len(files))))
    files = files[:keep]
    shard = files[self._shard_index::self._num_shards]
    if not shard:
      raise ValueError(
          f"Host shard {self._shard_index}/{self._num_shards} got no files "
          f"after fraction {self._file_fraction} of {len(files)}.")
    return shard


class WeightedRecordInputGenerator(AbstractInputGenerator):
  """Samples each batch element from one of several datasets by weight.

  Reference: §WeightedRecordInputGenerator — multi-dataset mixing (e.g.
  real robot data + sim data at a tuned ratio).
  """

  def __init__(
      self,
      file_patterns: Sequence[str],
      weights: Optional[Sequence[float]] = None,
      seed: int = 0,
      native_mode: str = "auto",
      **kwargs,
  ):
    super().__init__(**kwargs)
    if native_mode not in _NATIVE_MODES:
      raise ValueError(
          f"native_mode must be one of {_NATIVE_MODES}, got {native_mode!r}")
    self._native_mode = native_mode
    self.pipeline_stats: Dict = {}
    if weights is None:
      weights = [1.0] * len(file_patterns)
    if len(weights) != len(file_patterns):
      raise ValueError(
          f"{len(file_patterns)} datasets but {len(weights)} weights")
    total = float(sum(weights))
    if total <= 0:
      raise ValueError("weights must sum to a positive value")
    self._probs = [w / total for w in weights]
    self._seed = seed
    self._sources = [
        DefaultRecordInputGenerator(
            fp, seed=seed + i, batch_size=self._batch_size,
            shard_index=self._shard_index, num_shards=self._num_shards)
        for i, fp in enumerate(file_patterns)
    ]

  def set_specification(self, feature_spec, label_spec=None) -> None:
    super().set_specification(feature_spec, label_spec)
    for source in self._sources:
      source.set_specification(feature_spec, label_spec)

  def _create_iterator(self, mode: str) -> Iterator[Batch]:
    rng = np.random.default_rng(self._seed + 7919 * self._shard_index)
    # Per-element mixing: draw each record's source by weight, so every
    # batch is a weight-proportioned mixture (reference semantics — batch
    # statistics match the target ratio, unlike per-batch source picking).
    streams = [s._record_stream(mode) for s in self._sources]

    def mixed_records() -> Iterator[bytes]:
      live = list(range(len(streams)))
      while live:
        probs = np.array([self._probs[i] for i in live])
        choice = live[int(rng.choice(len(live), p=probs / probs.sum()))]
        try:
          yield next(streams[choice])
        except StopIteration:
          live.remove(choice)

    parser = ExampleParser(self.feature_spec, self.label_spec)
    stream, stats = _apply_native_mode(
        parser, mixed_records(), self._batch_size, self._native_mode)
    self.pipeline_stats = stats
    return _pipelined_parse(
        record_stream=stream,
        parser=parser,
        batch_size=self._batch_size,
        num_threads=self._sources[0]._num_pipeline_threads,
        prefetch_batches=self._sources[0]._prefetch_batches,
    )

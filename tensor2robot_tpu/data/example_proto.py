"""tf.Example wire-format codec with zero TensorFlow/protobuf dependency.

The reference parses serialized ``tf.Example`` protos with TF's C++
``parse_example`` kernels (SURVEY.md §2 native-components table). Here the
wire format is implemented directly — ``tf.Example`` is a tiny, frozen proto
schema, and hand-rolling it keeps the data path dependency-free and gives the
C++ fast-path reader (data/native) a bit-exact Python reference to test
against.

Schema (proto3, from tensorflow/core/example/{example,feature}.proto):

    message BytesList { repeated bytes value = 1; }
    message FloatList { repeated float value = 1 [packed]; }
    message Int64List { repeated int64 value = 1 [packed]; }
    message Feature { oneof kind {
        BytesList bytes_list = 1; FloatList float_list = 2;
        Int64List int64_list = 3; } }
    message Features { map<string, Feature> feature = 1; }
    message Example { Features features = 1; }

The decoder accepts both packed and unpacked repeated scalars and unknown
fields (skipped), as any conformant proto parser must.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Tuple, Union

FeatureValue = Union[List[bytes], List[float], List[int]]

_WIRETYPE_VARINT = 0
_WIRETYPE_64BIT = 1
_WIRETYPE_LEN = 2
_WIRETYPE_32BIT = 5


# ---------------------------------------------------------------------------
# Low-level wire helpers
# ---------------------------------------------------------------------------


def _write_varint(out: bytearray, value: int) -> None:
  if value < 0:
    value &= (1 << 64) - 1  # two's-complement 64-bit, proto int64 style
  while True:
    byte = value & 0x7F
    value >>= 7
    if value:
      out.append(byte | 0x80)
    else:
      out.append(byte)
      return


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
  result = 0
  shift = 0
  while True:
    if pos >= len(buf):
      raise ValueError("Truncated varint")
    byte = buf[pos]
    pos += 1
    result |= (byte & 0x7F) << shift
    if not byte & 0x80:
      return result, pos
    shift += 7
    if shift >= 70:
      raise ValueError("Varint too long")


def _signed64(value: int) -> int:
  if value >= 1 << 63:
    value -= 1 << 64
  return value


def _write_tag(out: bytearray, field: int, wiretype: int) -> None:
  _write_varint(out, (field << 3) | wiretype)


def _write_len_delimited(out: bytearray, field: int, payload: bytes) -> None:
  _write_tag(out, field, _WIRETYPE_LEN)
  _write_varint(out, len(payload))
  out += payload


def _skip_field(buf: bytes, pos: int, wiretype: int) -> int:
  if wiretype == _WIRETYPE_VARINT:
    _, pos = _read_varint(buf, pos)
    return pos
  if wiretype == _WIRETYPE_64BIT:
    return pos + 8
  if wiretype == _WIRETYPE_LEN:
    size, pos = _read_varint(buf, pos)
    return pos + size
  if wiretype == _WIRETYPE_32BIT:
    return pos + 4
  raise ValueError(f"Unsupported wire type {wiretype}")


def _iter_fields(buf: bytes) -> Iterator[Tuple[int, int, bytes, int]]:
  """Yields (field_number, wiretype, buf, value_pos); caller decodes value."""
  pos = 0
  while pos < len(buf):
    tag, pos = _read_varint(buf, pos)
    yield tag >> 3, tag & 7, buf, pos
    pos = _skip_field(buf, pos, tag & 7)


# ---------------------------------------------------------------------------
# Encoding
# ---------------------------------------------------------------------------


def _encode_bytes_list(values: List[bytes]) -> bytes:
  out = bytearray()
  for v in values:
    if isinstance(v, str):
      v = v.encode("utf-8")
    _write_len_delimited(out, 1, bytes(v))
  return bytes(out)


def _encode_float_list(values: List[float]) -> bytes:
  out = bytearray()
  payload = struct.pack(f"<{len(values)}f", *values)
  _write_len_delimited(out, 1, payload)  # packed
  return bytes(out)


def _encode_int64_list(values: List[int]) -> bytes:
  packed = bytearray()
  for v in values:
    _write_varint(packed, int(v))
  out = bytearray()
  _write_len_delimited(out, 1, bytes(packed))  # packed
  return bytes(out)


def encode_example(features: Dict[str, FeatureValue]) -> bytes:
  """Serializes a {name: list-of-bytes|float|int} dict as a tf.Example.

  The kind of each feature is inferred from its first element — numpy
  scalars included (np.float32 is not a Python float; missing it would
  silently truncate floats to int64). Empty lists encode as empty
  Int64Lists, matching TF's convention of an empty feature.
  """
  import numpy as _np

  features_payload = bytearray()
  for name, values in features.items():
    values = list(values)
    first = values[0] if values else None
    if isinstance(first, (bytes, str)):
      kind_field, kind_payload = 1, _encode_bytes_list(values)
    elif isinstance(first, (float, _np.floating)):
      kind_field, kind_payload = 2, _encode_float_list(
          [float(v) for v in values])
    elif first is None or isinstance(first, (int, _np.integer)):
      kind_field, kind_payload = 3, _encode_int64_list(
          [int(v) for v in values])
    else:
      raise TypeError(
          f"Feature {name!r}: cannot infer kind from {type(first).__name__};"
          " expected bytes/str, float, or int values.")
    feature_msg = bytearray()
    _write_len_delimited(feature_msg, kind_field, kind_payload)
    entry = bytearray()
    _write_len_delimited(entry, 1, name.encode("utf-8"))  # map key
    _write_len_delimited(entry, 2, bytes(feature_msg))  # map value
    _write_len_delimited(features_payload, 1, bytes(entry))
  example = bytearray()
  _write_len_delimited(example, 1, bytes(features_payload))
  return bytes(example)


# ---------------------------------------------------------------------------
# Decoding
# ---------------------------------------------------------------------------


def _decode_bytes_list(buf: bytes) -> List[bytes]:
  out: List[bytes] = []
  for field, wiretype, data, pos in _iter_fields(buf):
    if field == 1 and wiretype == _WIRETYPE_LEN:
      size, pos = _read_varint(data, pos)
      out.append(data[pos:pos + size])
  return out


def _decode_float_list(buf: bytes) -> List[float]:
  out: List[float] = []
  for field, wiretype, data, pos in _iter_fields(buf):
    if field != 1:
      continue
    if wiretype == _WIRETYPE_LEN:  # packed
      size, pos = _read_varint(data, pos)
      count = size // 4
      out.extend(struct.unpack_from(f"<{count}f", data, pos))
    elif wiretype == _WIRETYPE_32BIT:  # unpacked
      out.append(struct.unpack_from("<f", data, pos)[0])
  return out


def _decode_int64_list(buf: bytes) -> List[int]:
  out: List[int] = []
  for field, wiretype, data, pos in _iter_fields(buf):
    if field != 1:
      continue
    if wiretype == _WIRETYPE_LEN:  # packed
      size, pos = _read_varint(data, pos)
      end = pos + size
      while pos < end:
        value, pos = _read_varint(data, pos)
        out.append(_signed64(value))
    elif wiretype == _WIRETYPE_VARINT:  # unpacked
      value, _ = _read_varint(data, pos)
      out.append(_signed64(value))
  return out


def _decode_feature(buf: bytes) -> FeatureValue:
  for field, wiretype, data, pos in _iter_fields(buf):
    if wiretype != _WIRETYPE_LEN:
      continue
    size, pos = _read_varint(data, pos)
    payload = data[pos:pos + size]
    if field == 1:
      return _decode_bytes_list(payload)
    if field == 2:
      return _decode_float_list(payload)
    if field == 3:
      return _decode_int64_list(payload)
  return []


def decode_example(serialized: bytes) -> Dict[str, FeatureValue]:
  """Parses a serialized tf.Example into {name: list of bytes|float|int}."""
  features: Dict[str, FeatureValue] = {}
  for field, wiretype, data, pos in _iter_fields(serialized):
    if field != 1 or wiretype != _WIRETYPE_LEN:
      continue  # unknown field — skip
    size, pos = _read_varint(data, pos)
    features_buf = data[pos:pos + size]
    for f2, w2, d2, p2 in _iter_fields(features_buf):
      if f2 != 1 or w2 != _WIRETYPE_LEN:
        continue
      entry_size, p2 = _read_varint(d2, p2)
      entry = d2[p2:p2 + entry_size]
      name = None
      value: FeatureValue = []
      for f3, w3, d3, p3 in _iter_fields(entry):
        if w3 != _WIRETYPE_LEN:
          continue
        s3, p3 = _read_varint(d3, p3)
        payload = d3[p3:p3 + s3]
        if f3 == 1:
          name = payload.decode("utf-8")
        elif f3 == 2:
          value = _decode_feature(payload)
      if name is not None:
        features[name] = value
  return features

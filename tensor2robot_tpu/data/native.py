"""ctypes loader for the native data-path library, with auto-build.

Consumers call `get_native()`; None means "use the pure-Python path"
(missing compiler, missing libjpeg, or build failure — all non-fatal).
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from typing import Optional, Tuple

import numpy as np

_log = logging.getLogger(__name__)
_lock = threading.Lock()
_native: Optional["NativeData"] = None
_load_attempted = False

# Set T2R_DISABLE_NATIVE=1 to force the pure-Python data path.
_DISABLE_ENV = "T2R_DISABLE_NATIVE"


class NativeData:
  """Typed wrappers over libt2rnative.so."""

  def __init__(self, lib: ctypes.CDLL):
    self._lib = lib
    lib.t2r_masked_crc32c.restype = ctypes.c_uint32
    lib.t2r_masked_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.t2r_tfrecord_index.restype = ctypes.c_int64
    lib.t2r_tfrecord_index.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64, ctypes.c_int32]
    lib.t2r_jpeg_info.restype = ctypes.c_int32
    lib.t2r_jpeg_info.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32)]
    lib.t2r_jpeg_decode.restype = ctypes.c_int32
    lib.t2r_jpeg_decode.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32]
    if hasattr(lib, "t2r_jpeg_decode_batch"):  # older .so may predate it
      lib.t2r_jpeg_decode_batch.restype = ctypes.c_int32
      lib.t2r_jpeg_decode_batch.argtypes = [
          ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint64),
          ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32, ctypes.c_int32,
          ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
          ctypes.POINTER(ctypes.c_int32)]
    if hasattr(lib, "t2r_example_batch_dense"):
      lib.t2r_example_batch_dense.restype = ctypes.c_int32
      lib.t2r_example_batch_dense.argtypes = [
          ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint64),
          ctypes.c_int32, ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32,
          ctypes.c_int64, ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]
      lib.t2r_example_batch_bytes.restype = ctypes.c_int32
      lib.t2r_example_batch_bytes.argtypes = [
          ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint64),
          ctypes.c_int32, ctypes.c_char_p, ctypes.c_int32,
          ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64),
          ctypes.POINTER(ctypes.c_int64)]

  def masked_crc32c(self, data: bytes) -> int:
    return self._lib.t2r_masked_crc32c(data, len(data))

  def tfrecord_index(self, buf: bytes, verify_crc: bool = True
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (offsets, lengths) of record payloads in `buf`.

    Whole-buffer indexing: memory is O(len(buf)). For large shards
    prefer the streaming tfrecord.read_tfrecords (which uses the native
    CRC but O(record) memory)."""
    # Worst-case record size 16 bytes (empty payload) → bound the index.
    max_records = max(len(buf) // 16, 1)
    offsets = (ctypes.c_uint64 * max_records)()
    lengths = (ctypes.c_uint64 * max_records)()
    n = self._lib.t2r_tfrecord_index(
        buf, len(buf), offsets, lengths, max_records, int(verify_crc))
    if n < 0:
      reasons = {-1: "truncated record", -2: "length CRC mismatch",
                 -3: "data CRC mismatch", -4: "index overflow"}
      raise ValueError(
          f"Corrupt TFRecord buffer: {reasons.get(n, n)}")
    # as_array derives shape from the ctypes array type (max_records);
    # slice down to the actual record count.
    return (np.ctypeslib.as_array(offsets)[:n].copy(),
            np.ctypeslib.as_array(lengths)[:n].copy())

  def jpeg_decode(self, data: bytes,
                  channels: Optional[int] = None) -> np.ndarray:
    """Decodes a JPEG to (H, W, C) uint8 (C = 1 or 3)."""
    w = ctypes.c_int32()
    h = ctypes.c_int32()
    c = ctypes.c_int32()
    if self._lib.t2r_jpeg_info(data, len(data),
                               ctypes.byref(w), ctypes.byref(h),
                               ctypes.byref(c)) != 0:
      raise ValueError("Invalid JPEG data")
    out_channels = channels or (1 if c.value == 1 else 3)
    if out_channels not in (1, 3):
      raise ValueError(f"channels must be 1 or 3, got {out_channels}")
    out = np.empty((h.value, w.value, out_channels), np.uint8)
    rc = self._lib.t2r_jpeg_decode(
        data, len(data),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), out_channels)
    if rc != 0:
      raise ValueError("JPEG decode failed")
    return out

  @property
  def has_batch_decode(self) -> bool:
    return hasattr(self._lib, "t2r_jpeg_decode_batch")

  def jpeg_decode_batch(
      self,
      images: "list[bytes]",
      height: int,
      width: int,
      channels: int = 3,
      num_threads: int = 0,
  ) -> Tuple[np.ndarray, np.ndarray]:
    """Decodes a batch concurrently in C++ (GIL released for the whole
    batch — one call saturates all cores regardless of Python threads).

    Every image must decode to exactly (height, width); failures leave
    their output slot zeroed.

    Returns:
      ((N, H, W, C) uint8 array, (N,) int32 statuses — 0 ok, -1 decode
      error, -2 dimension mismatch, -3 corrupt-but-recoverable data
      such as truncated entropy segments).
    """
    if channels not in (1, 3):
      raise ValueError(f"channels must be 1 or 3, got {channels}")
    n = len(images)
    # np.empty, not np.zeros: the memset of the (N, H, W, C) output is
    # measurable on a 1-core host (~6% of the whole pipeline at 472²,
    # 2026-07-31 profile). The zeroed-failed-slot contract is enforced
    # inside the C++ worker (every failure path memsets its slot), not
    # by pre-zeroing the whole batch.
    out = np.empty((n, height, width, channels), np.uint8)
    statuses = np.zeros((n,), np.int32)
    if n == 0:
      return out, statuses
    datas = (ctypes.c_char_p * n)(*images)
    lens = (ctypes.c_uint64 * n)(*(len(im) for im in images))
    if num_threads <= 0:
      num_threads = min(n, os.cpu_count() or 1)
    self._lib.t2r_jpeg_decode_batch(
        datas, lens,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        height, width, channels, n, num_threads,
        statuses.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out, statuses


  # --- tf.Example parsing ---------------------------------------------------

  @property
  def has_example_parse(self) -> bool:
    return hasattr(self._lib, "t2r_example_batch_dense")

  def example_batch_dense(self, records: "list[bytes]", name: str,
                          kind: int, elems: int) -> Optional[np.ndarray]:
    """Parses feature `name` from every record into a (N, elems) array
    (kind 2 → float32 FloatList, 3 → int64 Int64List), entirely in C++.

    Returns None when the records don't match the request (missing
    feature / different wire kind / count mismatch) — callers fall back
    to the Python codec, which produces the precise error if the data is
    genuinely wrong. Raises on malformed protos (corrupt data is never
    silently skipped).
    """
    n = len(records)
    dtype = np.float32 if kind == 2 else np.int64
    out = np.empty((n, elems), dtype)
    if n == 0:
      return out
    datas = (ctypes.c_char_p * n)(*records)
    lens = (ctypes.c_uint64 * n)(*(len(r) for r in records))
    err_index = ctypes.c_int64(-1)
    rc = self._lib.t2r_example_batch_dense(
        datas, lens, n, name.encode("utf-8"), len(name.encode("utf-8")),
        kind, elems, out.ctypes.data_as(ctypes.c_void_p),
        ctypes.byref(err_index))
    if rc == 0:
      return out
    if rc == -4:
      raise ValueError(
          f"Malformed tf.Example proto at record {err_index.value} "
          f"(feature {name!r})")
    return None

  def example_batch_bytes(self, records: "list[bytes]",
                          name: str) -> Optional["list[bytes]"]:
    """Extracts the (first) bytes value of feature `name` per record.

    Same None-fallback / raise-on-malformed contract as
    example_batch_dense.
    """
    n = len(records)
    if n == 0:
      return []
    datas = (ctypes.c_char_p * n)(*records)
    lens = (ctypes.c_uint64 * n)(*(len(r) for r in records))
    ptrs = (ctypes.c_void_p * n)()
    out_lens = (ctypes.c_uint64 * n)()
    err_index = ctypes.c_int64(-1)
    rc = self._lib.t2r_example_batch_bytes(
        datas, lens, n, name.encode("utf-8"), len(name.encode("utf-8")),
        ptrs, out_lens, ctypes.byref(err_index))
    if rc == 0:
      # Copy out while `records` (the backing buffers) are alive.
      return [ctypes.string_at(ptrs[i], out_lens[i]) for i in range(n)]
    if rc == -4:
      raise ValueError(
          f"Malformed tf.Example proto at record {err_index.value} "
          f"(feature {name!r})")
    return None


def reset_cache() -> None:
  """Forgets the cached load decision so the next get_native() re-reads
  T2R_DISABLE_NATIVE — for tests/benchmarks toggling the native path
  within one process."""
  global _native, _load_attempted
  with _lock:
    _native = None
    _load_attempted = False


def get_native(auto_build: bool = True) -> Optional[NativeData]:
  """The loaded native library, building it on first use; None if
  unavailable."""
  global _native, _load_attempted
  with _lock:
    if _native is not None or _load_attempted:
      return _native
    _load_attempted = True
    if os.environ.get(_DISABLE_ENV) == "1":
      return None
    from tensor2robot_tpu.data import build_native
    try:
      # Content-hash staleness (ADVICE r3): the .so is trusted only if
      # its recorded source sha256 matches the source on disk.
      if not build_native.library_is_current() and auto_build:
        build_native.build(verbose=False)
      _native = NativeData(ctypes.CDLL(build_native.LIBRARY))
    except Exception as e:  # missing toolchain/libjpeg → Python path
      _log.info("Native data path unavailable (%s); using pure Python.", e)
      _native = None
    return _native

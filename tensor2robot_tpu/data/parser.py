"""Spec-driven parsing of serialized tf.Examples into dense numpy batches.

The analogue of the reference's ``tf.parse_example`` + per-``data_format``
image decode inside ``DefaultRecordInputGenerator`` (SURVEY.md §3.1). All
parsing/decoding happens host-side; by the time arrays reach the device
boundary they are dense, statically shaped, and numeric — encoded strings
never cross infeed (the invariant the reference enforced with
``TPUPreprocessorWrapper``).
"""

from __future__ import annotations

import io
from typing import Dict, List, Mapping, Optional

import numpy as np

from tensor2robot_tpu.data import example_proto
from tensor2robot_tpu.specs import tensorspec_utils as ts


def decode_image(data: bytes, data_format: Optional[str] = None) -> np.ndarray:
  """Decodes an encoded image to an HWC uint8 array.

  JPEGs go through the native libjpeg kernel when available (the input
  pipeline's hot loop — SURVEY.md §3.1); PIL handles everything else and
  serves as the fallback.
  """
  if data_format is None or data_format == "jpeg":
    from tensor2robot_tpu.data import native
    lib = native.get_native()
    if lib is not None and data[:2] == b"\xff\xd8":  # JPEG SOI marker
      try:
        return lib.jpeg_decode(data)
      except ValueError:
        pass  # e.g. CMYK: libjpeg can't convert — PIL below can
  from PIL import Image  # host-side decode only; never on device

  with Image.open(io.BytesIO(data)) as img:
    arr = np.asarray(img)
  if arr.ndim == 2:
    arr = arr[:, :, None]
  return arr


class ExampleParser:
  """Parses serialized tf.Example records per a spec structure.

  Built once per input pipeline from the model's (feature, label) specs;
  returns flat TensorSpecStructs mirroring the spec hierarchy.
  """

  def __init__(
      self,
      feature_spec: ts.SpecStructure,
      label_spec: Optional[ts.SpecStructure] = None,
  ):
    self._feature_spec = ts.flatten_spec_structure(feature_spec)
    self._label_spec = (
        ts.flatten_spec_structure(label_spec) if label_spec is not None
        else ts.TensorSpecStruct())
    # Record-level schema covering features and labels (they read different
    # keys of the same Example). Parsing below is route-driven; `schema` is
    # the public contract consumed by the native (C++) fast-path reader and
    # building it also validates that no two specs claim one record feature
    # with conflicting parse rules.
    merged = ts.TensorSpecStruct()
    for key, spec in self._feature_spec.items():
      merged[f"features/{key}"] = spec
    for key, spec in self._label_spec.items():
      merged[f"labels/{key}"] = spec
    self.schema = ts.tensorspec_to_feature_dict(merged)
    # record feature name → list of (dest struct name, flat key, spec)
    self._routes: Dict[str, List] = {}
    for key, spec in self._feature_spec.items():
      name = spec.name or key.rsplit("/", 1)[-1]
      self._routes.setdefault(name, []).append(("features", key, spec))
    for key, spec in self._label_spec.items():
      name = spec.name or key.rsplit("/", 1)[-1]
      self._routes.setdefault(name, []).append(("labels", key, spec))

  def parse_single(self, serialized: bytes):
    """Parses one record → (features, labels) of unbatched numpy arrays."""
    raw = example_proto.decode_example(serialized)
    features = ts.TensorSpecStruct()
    labels = ts.TensorSpecStruct()
    for name, routes in self._routes.items():
      values = raw.get(name)
      for dest, key, spec in routes:
        out = features if dest == "features" else labels
        if values is None:
          if spec.is_optional:
            continue
          raise ValueError(
              f"Record is missing required feature {name!r} "
              f"(for spec {key!r}); present: {sorted(raw)}")
        out[key] = self._materialize(name, spec, values)
    return features, labels

  def _materialize(self, name: str, spec: ts.ExtendedTensorSpec,
                   values) -> np.ndarray:
    if ts.is_encoded_image_spec(spec):
      if not values or not isinstance(values[0], bytes):
        raise ValueError(f"Feature {name!r}: expected encoded image bytes")
      img = decode_image(values[0], spec.data_format)
      if img.shape != spec.shape:
        raise ValueError(
            f"Feature {name!r}: decoded image shape {img.shape} != spec "
            f"shape {spec.shape}")
      return img.astype(spec.dtype, copy=False)
    if values and isinstance(values[0], bytes):
      # Raw-bytes numeric feature: TF convention of tensors serialized as a
      # single bytes value via .tobytes().
      arr = np.frombuffer(values[0], dtype=spec.dtype)
      target = spec.shape
      return arr.reshape(target)
    arr = np.asarray(values)
    if spec.is_sequence or spec.varlen_default_value is not None:
      # Varlen feature: flat value list → (time, *inner) padded/clipped to
      # spec.shape along time.
      if not spec.shape:
        raise ValueError(
            f"Feature {name!r}: sequence specs need a (time, ...) shape")
      inner = spec.shape[1:]
      inner_size = int(np.prod(inner)) if inner else 1
      if arr.size % inner_size:
        raise ValueError(
            f"Feature {name!r}: {arr.size} values not divisible by inner "
            f"shape {inner}")
      arr = arr.reshape((-1,) + inner)
      pad = spec.varlen_default_value
      arr = ts.pad_or_clip_array(
          arr, spec.shape[0], axis=0,
          pad_value=0.0 if pad is None else pad)
      return arr.astype(spec.dtype, copy=False)
    expected = int(np.prod(spec.shape)) if spec.shape else 1
    if arr.size != expected:
      raise ValueError(
          f"Feature {name!r}: got {arr.size} values, spec {spec.shape} "
          f"needs {expected}")
    return arr.reshape(spec.shape).astype(spec.dtype, copy=False)

  def parse_batch(self, serialized_records: List[bytes]):
    """Parses and stacks records → batched (features, labels)."""
    parsed = [self.parse_single(r) for r in serialized_records]
    features = _stack_structs([p[0] for p in parsed])
    labels = _stack_structs([p[1] for p in parsed])
    return features, labels


def _stack_structs(structs: List[ts.TensorSpecStruct]) -> ts.TensorSpecStruct:
  out = ts.TensorSpecStruct()
  if not structs:
    return out
  # Union of keys across records: optional features present in only part of
  # a batch cannot be stacked into a dense array — fail with the remedy
  # rather than crashing or silently dropping (order-dependent) data.
  keys = list(structs[0])
  key_set = set(keys)
  for s in structs[1:]:
    for key in s:
      if key not in key_set:
        key_set.add(key)
        keys.append(key)
  for key in keys:
    missing = sum(1 for s in structs if key not in s)
    if missing:
      raise ValueError(
          f"Optional feature {key!r} is present in only "
          f"{len(structs) - missing}/{len(structs)} records of a batch; "
          "optional features must be consistently present or absent within "
          "a dataset (or parsed with batch_size=1).")
    out[key] = np.stack([s[key] for s in structs])
  return out

"""Spec-driven parsing of serialized tf.Examples into dense numpy batches.

The analogue of the reference's ``tf.parse_example`` + per-``data_format``
image decode inside ``DefaultRecordInputGenerator`` (SURVEY.md §3.1). All
parsing/decoding happens host-side; by the time arrays reach the device
boundary they are dense, statically shaped, and numeric — encoded strings
never cross infeed (the invariant the reference enforced with
``TPUPreprocessorWrapper``).
"""

from __future__ import annotations

import io
import time
from typing import Dict, List, Mapping, Optional

import numpy as np

from tensor2robot_tpu.data import example_proto
from tensor2robot_tpu.specs import tensorspec_utils as ts

_UNSET = object()


def decode_image(data: bytes, data_format: Optional[str] = None,
                 channels: Optional[int] = None,
                 use_native: Optional[bool] = None) -> np.ndarray:
  """Decodes an encoded image to an HWC uint8 array.

  JPEGs go through the native libjpeg kernel when available (the input
  pipeline's hot loop — SURVEY.md §3.1); PIL handles everything else and
  serves as the fallback. `channels` (1 or 3) converts colorspace like
  TF's decode_jpeg(channels=N) — the conversion rule must be identical
  on the native and PIL paths so a dataset parses the same with or
  without the toolchain. `use_native=False` pins the PIL path (the
  parser threads its calibrated/pinned choice through here so "python
  path" means pure Python end to end, not a native-decode hybrid).
  """
  if (use_native is not False
      and (data_format is None or data_format == "jpeg")):
    from tensor2robot_tpu.data import native
    lib = native.get_native()
    if lib is not None and data[:2] == b"\xff\xd8":  # JPEG SOI marker
      try:
        return lib.jpeg_decode(data, channels=channels)
      except ValueError:
        pass  # e.g. CMYK: libjpeg can't convert — PIL below can
  from PIL import Image  # host-side decode only; never on device

  with Image.open(io.BytesIO(data)) as img:
    if channels == 1 and img.mode != "L":
      img = img.convert("L")
    elif channels == 3 and img.mode != "RGB":
      img = img.convert("RGB")
    arr = np.asarray(img)
  if arr.ndim == 2:
    arr = arr[:, :, None]
  return arr


class ExampleParser:
  """Parses serialized tf.Example records per a spec structure.

  Built once per input pipeline from the model's (feature, label) specs;
  returns flat TensorSpecStructs mirroring the spec hierarchy.
  """

  def __init__(
      self,
      feature_spec: ts.SpecStructure,
      label_spec: Optional[ts.SpecStructure] = None,
  ):
    self._feature_spec = ts.flatten_spec_structure(feature_spec)
    self._label_spec = (
        ts.flatten_spec_structure(label_spec) if label_spec is not None
        else ts.TensorSpecStruct())
    # Record-level schema covering features and labels (they read different
    # keys of the same Example). Parsing below is route-driven; `schema` is
    # the public contract consumed by the native (C++) fast-path reader and
    # building it also validates that no two specs claim one record feature
    # with conflicting parse rules.
    merged = ts.TensorSpecStruct()
    for key, spec in self._feature_spec.items():
      merged[f"features/{key}"] = spec
    for key, spec in self._label_spec.items():
      merged[f"labels/{key}"] = spec
    self.schema = ts.tensorspec_to_feature_dict(merged)
    # record feature name → list of (dest struct name, flat key, spec)
    self._routes: Dict[str, List] = {}
    for key, spec in self._feature_spec.items():
      name = spec.name or key.rsplit("/", 1)[-1]
      self._routes.setdefault(name, []).append(("features", key, spec))
    for key, spec in self._label_spec.items():
      name = spec.name or key.rsplit("/", 1)[-1]
      self._routes.setdefault(name, []).append(("labels", key, spec))
    self._native_plan_cache = _UNSET
    # None: prefer native when available (the default). False: pure
    # Python end to end. True: prefer native (explicit pin — still
    # falls back when the library is absent; correctness never depends
    # on the toolchain). Set directly or via calibrate_native().
    self._native_enabled: Optional[bool] = None

  def set_native_enabled(self, enabled: Optional[bool]) -> None:
    """Pins (True/False) or unpins (None) this parser's native path."""
    self._native_enabled = enabled

  # Calibration switches away from the unpinned default (native) only on
  # a clear win: on a contended 1-core host per-arm minima still jitter,
  # and a near-tie would flip the recorded decision on noise (VERDICT r4
  # Weak #4). With close arms the choice is immaterial anyway — a stable
  # decision beats a marginally-faster noisy one.
  CALIBRATION_HYSTERESIS = 0.15

  def calibrate_native(self, records: List[bytes], trials: int = 3) -> Dict:
    """Times parse_batch both ways on `records`; pins the faster path.

    The measurement interleaves arms in ABBA order (native, python,
    python, native, ...) and compares per-arm minima, so a one-shot
    ordering bias or a transient host stall cannot flip the decision
    the way a single fixed-order pair can (VERDICT r3 Weak #1: on a
    contended 1-core host, single-shot ratios swung 0.56x-1.39x
    between runs). Decision semantics: the incumbent is the unpinned
    default (native, when a plan exists); python is pinned only when
    its minimum beats native's by more than CALIBRATION_HYSTERESIS
    (relative margin on the incumbent's time). If timing raises
    mid-calibration the parser is left UNPINNED (None) and the error
    propagates — incomplete timings must not latch a possibly-crashing
    arm (ADVICE r4).

    Returns a stats dict recording the decision, reason, margin, and
    both arms' per-trial timings; callers surface it (the input
    generators expose it as `pipeline_stats["native_calibration"]`).
    """
    from tensor2robot_tpu.data import native
    lib = native.get_native()
    stats: Dict = {"trials": 0}
    if lib is None or not (lib.has_example_parse and lib.has_batch_decode):
      self._native_enabled = False
      stats.update(decision="python", reason="native library unavailable")
      return stats
    if self._native_plan is None:
      self._native_enabled = False
      stats.update(
          decision="python",
          reason="spec needs the python codec (optional/varlen/non-jpeg)")
      return stats
    times: Dict[str, List[float]] = {"native": [], "python": []}
    order = ("native", "python")
    try:
      for trial in range(max(1, trials)):
        for arm in (order if trial % 2 == 0 else order[::-1]):
          self._native_enabled = arm == "native"
          start = time.perf_counter()
          self.parse_batch(records)
          times[arm].append(time.perf_counter() - start)
    except BaseException:
      self._native_enabled = None
      raise
    best_native = min(times["native"])
    best_python = min(times["python"])
    python_margin = (best_native - best_python) / max(best_native, 1e-12)
    self._native_enabled = python_margin <= self.CALIBRATION_HYSTERESIS
    stats.update(
        decision="native" if self._native_enabled else "python",
        reason="calibrated",
        trials=max(1, trials),
        batch_records=len(records),
        native_batch_s=round(best_native, 5),
        python_batch_s=round(best_python, 5),
        native_times_s=[round(t, 5) for t in times["native"]],
        python_times_s=[round(t, 5) for t in times["python"]],
        python_margin=round(python_margin, 4),
        hysteresis=self.CALIBRATION_HYSTERESIS,
    )
    return stats

  def parse_single(self, serialized: bytes):
    """Parses one record → (features, labels) of unbatched numpy arrays."""
    raw = example_proto.decode_example(serialized)
    features = ts.TensorSpecStruct()
    labels = ts.TensorSpecStruct()
    for name, routes in self._routes.items():
      values = raw.get(name)
      for dest, key, spec in routes:
        out = features if dest == "features" else labels
        if values is None:
          if spec.is_optional:
            continue
          raise ValueError(
              f"Record is missing required feature {name!r} "
              f"(for spec {key!r}); present: {sorted(raw)}")
        out[key] = self._materialize(name, spec, values)
    return features, labels

  def _materialize(self, name: str, spec: ts.ExtendedTensorSpec,
                   values) -> np.ndarray:
    if ts.is_encoded_image_spec(spec):
      if not values or not isinstance(values[0], bytes):
        raise ValueError(f"Feature {name!r}: expected encoded image bytes")
      channels = (spec.shape[-1]
                  if len(spec.shape) == 3 and spec.shape[-1] in (1, 3)
                  else None)
      img = decode_image(values[0], spec.data_format, channels=channels,
                         use_native=self._native_enabled)
      if img.shape != spec.shape:
        raise ValueError(
            f"Feature {name!r}: decoded image shape {img.shape} != spec "
            f"shape {spec.shape}")
      return img.astype(spec.dtype, copy=False)
    if values and isinstance(values[0], bytes):
      # Raw-bytes numeric feature: TF convention of tensors serialized as a
      # single bytes value via .tobytes().
      arr = np.frombuffer(values[0], dtype=spec.dtype)
      target = spec.shape
      return arr.reshape(target)
    arr = np.asarray(values)
    if spec.is_sequence or spec.varlen_default_value is not None:
      # Varlen feature: flat value list → (time, *inner) padded/clipped to
      # spec.shape along time.
      if not spec.shape:
        raise ValueError(
            f"Feature {name!r}: sequence specs need a (time, ...) shape")
      inner = spec.shape[1:]
      inner_size = int(np.prod(inner)) if inner else 1
      if arr.size % inner_size:
        raise ValueError(
            f"Feature {name!r}: {arr.size} values not divisible by inner "
            f"shape {inner}")
      arr = arr.reshape((-1,) + inner)
      pad = spec.varlen_default_value
      arr = ts.pad_or_clip_array(
          arr, spec.shape[0], axis=0,
          pad_value=0.0 if pad is None else pad)
      return arr.astype(spec.dtype, copy=False)
    expected = int(np.prod(spec.shape)) if spec.shape else 1
    if arr.size != expected:
      raise ValueError(
          f"Feature {name!r}: got {arr.size} values, spec {spec.shape} "
          f"needs {expected}")
    return arr.reshape(spec.shape).astype(spec.dtype, copy=False)

  def parse_batch(self, serialized_records: List[bytes]):
    """Parses and stacks records → batched (features, labels).

    Fast path: when the native library is available and every route is
    dense (fixed-shape numeric or jpeg image, nothing optional/varlen),
    the whole batch parses in C++ — proto walking, value extraction,
    and thread-pooled jpeg decode — without constructing per-record
    Python objects (the reference's parse_example C++ kernels). Any
    mismatch between the plan and the actual records falls back to the
    per-record Python codec, which raises the precise error.
    """
    serialized_records = list(serialized_records)
    from tensor2robot_tpu.data import native
    lib = None if self._native_enabled is False else native.get_native()
    if (lib is not None and lib.has_example_parse
        and lib.has_batch_decode):
      result = self._parse_batch_native(serialized_records, lib)
      if result is not None:
        return result
    parsed = [self.parse_single(r) for r in serialized_records]
    features = _stack_structs([p[0] for p in parsed])
    labels = _stack_structs([p[1] for p in parsed])
    return features, labels

  @property
  def _native_plan(self):
    """Per-record-feature parse plan, or None if any route needs the
    Python codec (optional/varlen/sequence/unsupported dtype)."""
    if self._native_plan_cache is not _UNSET:
      return self._native_plan_cache
    plan = []
    for name, routes in self._routes.items():
      spec = routes[0][2]  # schema build validated cross-route agreement
      if any(s.is_optional for _, _, s in routes):
        plan = None
        break
      if ts.is_encoded_image_spec(spec):
        if (spec.data_format == "jpeg" and len(spec.shape) == 3
            and spec.shape[-1] in (1, 3)):
          plan.append(("jpeg", name, routes, spec))
          continue
        plan = None
        break
      if spec.is_sequence or spec.varlen_default_value is not None:
        plan = None
        break
      elems = int(np.prod(spec.shape)) if spec.shape else 1
      if np.issubdtype(spec.dtype, np.floating):
        plan.append(("float", name, routes, elems))
      elif np.issubdtype(spec.dtype, np.integer):
        plan.append(("int", name, routes, elems))
      else:
        plan = None
        break
    self._native_plan_cache = plan
    return plan

  def _parse_batch_native(self, records: List[bytes], lib):
    """C++ whole-batch parse; None → caller uses the Python path."""
    plan = self._native_plan
    if plan is None or not records:
      return None
    n = len(records)
    features = ts.TensorSpecStruct()
    labels = ts.TensorSpecStruct()
    for kind, name, routes, extra in plan:
      if kind == "jpeg":
        spec = extra
        blobs = lib.example_batch_bytes(records, name)
        if blobs is None:
          return None
        h, w, c = spec.shape
        images, statuses = lib.jpeg_decode_batch(blobs, h, w, c)
        if statuses.any():
          return None  # Python path raises the precise per-record error
        arr = images
      else:
        elems = extra
        proto_kind = 2 if kind == "float" else 3
        arr = lib.example_batch_dense(records, name, proto_kind, elems)
        if arr is None:
          # Raw-bytes tensor encoding (single bytes value = .tobytes()).
          blobs = lib.example_batch_bytes(records, name)
          if blobs is None:
            return None
          spec = routes[0][2]
          itemsize = np.dtype(spec.dtype).itemsize
          if any(len(b) != elems * itemsize for b in blobs):
            return None
          arr = np.stack(
              [np.frombuffer(b, dtype=spec.dtype) for b in blobs])
      for i, (dest, key, spec) in enumerate(routes):
        out = features if dest == "features" else labels
        shaped = arr.reshape((n,) + spec.shape)
        # Routes beyond the first get independent copies — the Python
        # path materializes per-route arrays, and aliased buffers would
        # let an in-place feature mutation corrupt its label twin.
        out[key] = shaped.astype(spec.dtype, copy=i > 0)
    return features, labels


def _stack_structs(structs: List[ts.TensorSpecStruct]) -> ts.TensorSpecStruct:
  out = ts.TensorSpecStruct()
  if not structs:
    return out
  # Union of keys across records: optional features present in only part of
  # a batch cannot be stacked into a dense array — fail with the remedy
  # rather than crashing or silently dropping (order-dependent) data.
  keys = list(structs[0])
  key_set = set(keys)
  for s in structs[1:]:
    for key in s:
      if key not in key_set:
        key_set.add(key)
        keys.append(key)
  for key in keys:
    missing = sum(1 for s in structs if key not in s)
    if missing:
      raise ValueError(
          f"Optional feature {key!r} is present in only "
          f"{len(structs) - missing}/{len(structs)} records of a batch; "
          "optional features must be consistently present or absent within "
          "a dataset (or parsed with batch_size=1).")
    out[key] = np.stack([s[key] for s in structs])
  return out

"""Double-buffered host→device prefetch under explicit shardings.

The TPU-native replacement for TPUEstimator's infeed queues (SURVEY.md §2
native-components table, "Host→device feeding"): while the device crunches
step N, the next host batch is already being transferred — `jax.device_put`
with a `NamedSharding` is asynchronous, so holding `depth` in-flight batches
overlaps H2D DMA with compute without any explicit infeed machinery.
"""

from __future__ import annotations

import collections
from typing import Any, Iterator, Optional

import jax


def prefetch_to_device(
    iterator: Iterator[Any],
    sharding: Optional[Any] = None,
    depth: int = 2,
) -> Iterator[Any]:
  """Yields batches moved to device, keeping `depth` transfers in flight.

  Args:
    iterator: host iterator of pytrees of numpy arrays (e.g. the
      (features, labels) tuples input generators yield).
    sharding: a `jax.sharding.Sharding` (or pytree of them matching the
      batch structure) describing how the global batch lays out over the
      mesh; None = default device placement.
    depth: number of batches resident on device. 2 = classic double
      buffering; more helps jittery input pipelines at the cost of HBM.
  """
  if depth < 1:
    raise ValueError(f"depth must be >= 1, got {depth}")

  def transfer(batch: Any) -> Any:
    if sharding is None:
      return jax.device_put(batch)
    return jax.device_put(batch, sharding)

  buffer: collections.deque = collections.deque()
  for batch in iterator:
    buffer.append(transfer(batch))
    if len(buffer) >= depth:
      yield buffer.popleft()
  while buffer:
    yield buffer.popleft()

"""Double-buffered host→device prefetch under explicit shardings.

The TPU-native replacement for TPUEstimator's infeed queues (SURVEY.md §2
native-components table, "Host→device feeding"): while the device crunches
step N, the next host batch is already being transferred — `jax.device_put`
with a `NamedSharding` is asynchronous, so holding `depth` in-flight batches
overlaps H2D DMA with compute without any explicit infeed machinery.

ISSUE 20 instruments the seam: in-flight depth and bytes flow through the
typed `obs/registry` writer (gauges ``<name>/depth`` and
``<name>/in_flight_bytes``, counter ``<name>/batches``), and a consumer
that must distinguish "stream ended" from "iterator bug" can opt into the
typed `PrefetchExhausted` instead of a bare `StopIteration` escaping a
generator frame (which Python would mangle into a RuntimeError anyway).
"""

from __future__ import annotations

import collections
from typing import Any, Iterator, Optional

import jax
import numpy as np


class PrefetchExhausted(Exception):
  """The upstream host iterator ended and every in-flight transfer has
  been yielded. Raised (instead of bare StopIteration) when the
  consumer passed ``exhaust_error=True`` — a learner loop catches THIS
  at its ingest seam rather than letting generator-protocol mechanics
  leak through as RuntimeError('generator raised StopIteration')."""

  def __init__(self, name: str, batches: int):
    super().__init__(
        f"prefetch stream {name!r} exhausted after {batches} batches")
    self.name = name
    self.batches = batches


def _host_nbytes(batch: Any) -> int:
  """Byte size of a host pytree BEFORE transfer (what H2D will move)."""
  return sum(np.asarray(leaf).nbytes
             for leaf in jax.tree_util.tree_leaves(batch))


def prefetch_to_device(
    iterator: Iterator[Any],
    sharding: Optional[Any] = None,
    depth: int = 2,
    registry: Optional[Any] = None,
    name: str = "prefetch",
    exhaust_error: bool = False,
) -> Iterator[Any]:
  """Yields batches moved to device, keeping `depth` transfers in flight.

  Args:
    iterator: host iterator of pytrees of numpy arrays (e.g. the
      (features, labels) tuples input generators yield).
    sharding: a `jax.sharding.Sharding` (or pytree of them matching the
      batch structure) describing how the global batch lays out over the
      mesh; None = default device placement.
    depth: number of batches resident on device. 2 = classic double
      buffering; more helps jittery input pipelines at the cost of HBM.
    registry: a `MetricRegistry`; defaults to the process registry.
      Gauges ``<name>/depth`` / ``<name>/in_flight_bytes`` track the
      buffer after every transition; counter ``<name>/batches`` counts
      yields.
    name: metric namespace for this stream.
    exhaust_error: when True, raise `PrefetchExhausted` after the final
      buffered batch instead of ending by StopIteration.
  """
  if depth < 1:
    raise ValueError(f"depth must be >= 1, got {depth}")
  if registry is None:
    from tensor2robot_tpu.obs.registry import get_registry
    registry = get_registry()
  depth_gauge = registry.gauge(f"{name}/depth")
  bytes_gauge = registry.gauge(f"{name}/in_flight_bytes")
  batches_counter = registry.counter(f"{name}/batches")

  def transfer(batch: Any) -> Any:
    if sharding is None:
      return jax.device_put(batch)
    return jax.device_put(batch, sharding)

  buffer: collections.deque = collections.deque()
  in_flight_bytes: collections.deque = collections.deque()
  yielded = 0

  def push(batch: Any) -> None:
    in_flight_bytes.append(_host_nbytes(batch))
    buffer.append(transfer(batch))
    depth_gauge.set(len(buffer))
    bytes_gauge.set(sum(in_flight_bytes))

  def pop() -> Any:
    in_flight_bytes.popleft()
    batch = buffer.popleft()
    depth_gauge.set(len(buffer))
    bytes_gauge.set(sum(in_flight_bytes))
    batches_counter.inc()
    return batch

  for batch in iterator:
    push(batch)
    if len(buffer) >= depth:
      yielded += 1
      yield pop()
  while buffer:
    yielded += 1
    yield pop()
  if exhaust_error:
    raise PrefetchExhausted(name, yielded)

"""TFRecord file framing (read/write) without TensorFlow.

The reference reads training data via TF's C++ RecordInput/TFRecordDataset
(SURVEY.md §2 native-components table). This module implements the on-disk
format directly so the framework owns its IO path:

    each record:  uint64 length (LE)
                  uint32 masked-crc32c(length bytes) (LE)
                  byte   data[length]
                  uint32 masked-crc32c(data) (LE)

CRC32C is the Castagnoli polynomial (0x1EDC6F41, reflected 0x82F63B78), with
TF's mask: ``((crc >> 15) | (crc << 17)) + 0xa282ead8 (mod 2^32)``.

This pure-Python implementation is the correctness reference; the C++
extension in data/native is the throughput path and must match it bit-exactly.
"""

from __future__ import annotations

import os
import struct
from typing import Iterable, Iterator, List, Optional

import numpy as np

# Table-driven CRC32C via numpy (vectorized table build; per-byte loop in
# Python is fine at test scale — the C++ reader owns the fast path).
_CRC_TABLE = None


def _crc_table() -> np.ndarray:
  global _CRC_TABLE
  if _CRC_TABLE is None:
    poly = np.uint32(0x82F63B78)
    table = np.arange(256, dtype=np.uint32)
    for _ in range(8):
      table = np.where(table & 1, (table >> 1) ^ poly, table >> 1)
    _CRC_TABLE = table
  return _CRC_TABLE


def crc32c(data: bytes) -> int:
  """CRC32C (Castagnoli) of `data`."""
  table = _crc_table()
  crc = np.uint32(0xFFFFFFFF)
  arr = np.frombuffer(data, dtype=np.uint8)
  for byte in arr:
    crc = table[(crc ^ byte) & np.uint32(0xFF)] ^ (crc >> np.uint32(8))
  return int(crc ^ np.uint32(0xFFFFFFFF))


def masked_crc32c(data: bytes) -> int:
  """TF's masked CRC (so CRCs of CRCs don't collide with data CRCs)."""
  crc = crc32c(data)
  rotated = ((crc >> 15) | (crc << 17)) & 0xFFFFFFFF
  return (rotated + 0xA282EAD8) & 0xFFFFFFFF


class TFRecordWriter:
  """Writes TFRecord files (data collection, test fixtures, converters)."""

  def __init__(self, path: str):
    self._file = open(path, "wb")

  def write(self, record: bytes) -> None:
    length_bytes = struct.pack("<Q", len(record))
    self._file.write(length_bytes)
    self._file.write(struct.pack("<I", masked_crc32c(length_bytes)))
    self._file.write(record)
    self._file.write(struct.pack("<I", masked_crc32c(record)))

  def flush(self) -> None:
    self._file.flush()

  def close(self) -> None:
    self._file.close()

  def __enter__(self) -> "TFRecordWriter":
    return self

  def __exit__(self, *exc) -> None:
    self.close()


def write_tfrecords(path: str, records: Iterable[bytes]) -> None:
  with TFRecordWriter(path) as writer:
    for record in records:
      writer.write(record)


def read_tfrecords(path: str, verify_crc: bool = True) -> Iterator[bytes]:
  """Yields records from one TFRecord file.

  CRC verification is on by default (corrupt robot-fleet data should fail
  loudly, not train silently). Uses the C++ framing/CRC kernel when the
  native library is available; pure Python otherwise.
  """
  # Streaming framing (O(record) memory even on multi-GB fleet shards)
  # with the CRC — the per-byte hot loop — done natively when available.
  from tensor2robot_tpu.data import native
  lib = native.get_native()
  crc = lib.masked_crc32c if lib is not None else masked_crc32c
  with open(path, "rb") as f:
    while True:
      header = f.read(12)
      if not header:
        return
      if len(header) < 12:
        raise ValueError(f"{path}: truncated record header")
      length, length_crc = struct.unpack("<QI", header)
      if verify_crc and crc(header[:8]) != length_crc:
        raise ValueError(f"{path}: corrupted record length (CRC mismatch)")
      data = f.read(length)
      if len(data) < length:
        raise ValueError(f"{path}: truncated record body")
      footer = f.read(4)
      if len(footer) < 4:
        raise ValueError(f"{path}: truncated record footer")
      (data_crc,) = struct.unpack("<I", footer)
      if verify_crc and crc(data) != data_crc:
        raise ValueError(f"{path}: corrupted record data (CRC mismatch)")
      yield data


def list_files(file_patterns: str | Iterable[str]) -> List[str]:
  """Expands comma-separated glob patterns to a sorted file list.

  Reference: input_generators file_patterns handling (comma-separated
  globs, e.g. '/data/train-*.tfrecord,/data/extra-*.tfrecord').
  """
  import glob as globlib

  if isinstance(file_patterns, str):
    patterns = [p for p in file_patterns.split(",") if p]
  else:
    patterns = list(file_patterns)
  files: List[str] = []
  for pattern in patterns:
    matches = sorted(globlib.glob(os.path.expanduser(pattern)))
    if not matches and os.path.exists(pattern):
      matches = [pattern]
    files.extend(matches)
  if not files:
    raise FileNotFoundError(
        f"No files matched file_patterns={file_patterns!r}")
  return files

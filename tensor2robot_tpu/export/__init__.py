"""Export: versioned serving artifacts from training state.

Reference parity: export_generators/ (SURVEY.md §2, §3.2). Two formats:
  - Native (flagship): jax.export StableHLO + orbax/npz params + spec
    assets — pure-JAX serving, compiles for cpu and tpu.
  - SavedModel (compatibility): jax2tf → tf.saved_model, preserving the
    reference's robot-side serving contract (SURVEY.md §3.3 boundary).
"""

from tensor2robot_tpu.export.export_utils import (
    SPEC_ASSET_NAME,
    latest_export_dir,
    list_export_versions,
    read_spec_assets,
    versioned_export_dir,
    write_spec_assets,
)
from tensor2robot_tpu.export.abstract_export_generator import (
    AbstractExportGenerator,
)
from tensor2robot_tpu.export.native_export_generator import (
    NativeExportGenerator,
)
from tensor2robot_tpu.export.exporters import (
    BestExporter,
    Exporter,
    LatestExporter,
    create_default_exporters_fn,
    run_exporters,
)

__all__ = [
    "AbstractExportGenerator",
    "BestExporter",
    "Exporter",
    "LatestExporter",
    "NativeExportGenerator",
    "create_default_exporters_fn",
    "run_exporters",
    "SPEC_ASSET_NAME",
    "latest_export_dir",
    "list_export_versions",
    "read_spec_assets",
    "versioned_export_dir",
    "write_spec_assets",
]

"""AbstractExportGenerator — spec-driven serving-artifact emission.

Reference parity: export_generators/abstract_export_generator.py
(SURVEY.md §2): build a serving signature from the model's feature specs
(labels stripped), emit a versioned artifact, embed spec assets. The
receiver-fn machinery is gone — a JAX serving fn is just predict_fn closed
over variables; what remains is the signature/versioning/asset contract.
"""

from __future__ import annotations

import abc
from typing import Any, Optional

from tensor2robot_tpu import modes
from tensor2robot_tpu.specs import tensorspec_utils as ts


class AbstractExportGenerator(abc.ABC):
  """Builds versioned serving artifacts for a model."""

  def __init__(self, export_root: Optional[str] = None):
    self._export_root = export_root
    self._model = None
    self._feature_spec: Optional[ts.TensorSpecStruct] = None

  @property
  def export_root(self) -> str:
    if self._export_root is None:
      raise ValueError("export_root not set.")
    return self._export_root

  @export_root.setter
  def export_root(self, value: str) -> None:
    self._export_root = value

  def set_specification_from_model(self, model) -> None:
    """Captures the serving signature: the model-ready (preprocessor-out)
    PREDICT feature specs, labels stripped."""
    self._model = model
    self._feature_spec = ts.flatten_spec_structure(
        model.preprocessor.get_out_feature_specification(modes.PREDICT))

  @property
  def feature_spec(self) -> ts.TensorSpecStruct:
    if self._feature_spec is None:
      raise ValueError(
          "Export generator has no specs; call "
          "set_specification_from_model first.")
    return self._feature_spec

  @abc.abstractmethod
  def export(self, variables: Any, global_step: int = 0) -> str:
    """Writes one new version under export_root; returns its final dir.

    Args:
      variables: the flax variables dict ({"params": ..., batch_stats...})
        to serve — callers pass EMA params when use_avg_model_params
        (TrainState.variables(use_ema=True)).
      global_step: the train step the variables were snapshotted at,
        recorded in the spec assets (0 = unknown).
    """

"""Versioned export directories + spec assets.

Reference parity: the trainer→robot boundary of SURVEY.md §3.3 — a
directory of timestamped versions on shared storage, written atomically
(robots poll concurrently), each embedding spec assets so predictors can
recover the input signature without the model's Python code
(export_generators/abstract_export_generator.py spec-asset embedding).
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional, Tuple

from tensor2robot_tpu.specs import tensorspec_utils as ts

SPEC_ASSET_NAME = "t2r_assets.json"
SPEC_ASSET_PB_NAME = "t2r_assets.pb"


def normalize_serving_outputs(outputs) -> dict:
  """The serving output contract: a flat {str: array} dict.

  Shared by every exporter and predictor so artifacts and in-process
  serving can never diverge on key naming.
  """
  if hasattr(outputs, "items"):
    return {str(k): v for k, v in outputs.items()}
  return {"inference_output": outputs}


def versioned_export_dir(export_root: str) -> Tuple[str, str]:
  """Returns (tmp_dir, final_dir) for a new monotonic version.

  Write into tmp_dir, then os.rename to final_dir — the atomic-publish
  protocol robots rely on (they never see partial exports).
  """
  os.makedirs(export_root, exist_ok=True)
  version = int(time.time())
  existing = list_export_versions(export_root)
  if existing and version <= existing[-1]:
    version = existing[-1] + 1
  final_dir = os.path.join(export_root, str(version))
  tmp_dir = os.path.join(export_root, f".tmp-{version}")
  return tmp_dir, final_dir


def publish(tmp_dir: str, final_dir: str) -> str:
  """Atomically publishes tmp_dir as final_dir (the rename robots
  watch for). A pre-existing final_dir is refused BY NAME (ISSUE 19):
  step-named export dirs (export_and_gc on a reused workdir) collide
  when a re-run reaches the same step, and the bare os.rename then
  dies with a bare OSError errno 39 (directory not empty) that names
  neither path — worse, on some platforms it could clobber the export
  a robot is mid-download on. Versioned dirs never hit this
  (versioned_export_dir allocates monotonically past survivors)."""
  if os.path.exists(final_dir):
    raise FileExistsError(
        f"export target already exists: {final_dir} (publishing "
        f"{tmp_dir}). A reused workdir re-reached an already-exported "
        "step — remove the stale export dir or point the run at a "
        "fresh workdir; refusing to clobber a published export.")
  os.rename(tmp_dir, final_dir)
  return final_dir


def list_export_versions(export_root: str) -> List[int]:
  """Sorted numeric version subdirs of export_root."""
  if not os.path.isdir(export_root):
    return []
  versions = []
  for name in os.listdir(export_root):
    if name.isdigit() and os.path.isdir(os.path.join(export_root, name)):
      versions.append(int(name))
  return sorted(versions)


def latest_export_dir(export_root: str) -> Optional[str]:
  versions = list_export_versions(export_root)
  if not versions:
    return None
  return os.path.join(export_root, str(versions[-1]))


def garbage_collect_exports(export_root: str, keep: int) -> List[str]:
  """Removes all but the newest `keep` versions (reference: version GC in
  the async export hook, SURVEY.md §3.4). keep <= 0 disables GC (never
  deletes the just-published version). Returns removed dirs."""
  import shutil
  if keep <= 0:
    return []
  removed = []
  versions = list_export_versions(export_root)
  for version in versions[:-keep]:
    path = os.path.join(export_root, str(version))
    shutil.rmtree(path, ignore_errors=True)
    removed.append(path)
  return removed


def resolve_export_root(generator, model_dir: Optional[str]) -> None:
  """Defaults a generator's export_root under model_dir (shared by the
  end-of-train export and the async export hook so they cannot drift)."""
  try:
    generator.export_root
  except ValueError:
    if not model_dir:
      raise ValueError(
          "Export generator has no export_root and no model_dir to "
          "default it under.")
    generator.export_root = os.path.join(model_dir, "export", "latest")


def fetch_is_collective(variables) -> bool:
  """True if fetch_variables_to_host(variables) involves a cross-process
  collective (some leaf is sharded across processes). When False, a
  non-primary host may skip a fetch whose result it would only discard
  — when True, every host MUST fetch together or the pod deadlocks."""
  import jax
  return any(
      hasattr(leaf, "is_fully_addressable")
      and not leaf.is_fully_addressable
      and not getattr(leaf, "is_fully_replicated", False)
      for leaf in jax.tree_util.tree_leaves(variables))


def fetch_variables_to_host(variables):
  """Device variables → host numpy, safely for ANY sharding.

  Replicated / single-host-sharded leaves are a plain device_get;
  leaves sharded across processes (TP on a multi-host mesh) are
  all-gathered first — device_get on a non-fully-addressable array
  raises. Every exporter path (end-of-train, eval exporters, the async
  hook) must fetch through this."""
  import jax
  import numpy as np

  def fetch(leaf):
    # Only genuinely cross-process-SHARDED leaves need the all-gather;
    # fully-replicated multi-host arrays (the pure-DP default) fetch
    # locally with a plain device_get (every process holds a full copy).
    if (hasattr(leaf, "is_fully_addressable")
        and not leaf.is_fully_addressable
        and not getattr(leaf, "is_fully_replicated", False)):
      from jax.experimental import multihost_utils
      return np.asarray(multihost_utils.process_allgather(leaf,
                                                          tiled=True))
    return jax.device_get(leaf)

  return jax.tree_util.tree_map(fetch, variables)


def export_and_gc(generator, variables, keep: int,
                  global_step: int = 0) -> Optional[str]:
  """One export + version GC (the publish step both export paths share).

  THE chief-worker gate for export artifacts: on multi-host, only the
  primary writes (N hosts publishing the same versioned directories
  would race each other and the GC); non-primary processes return
  None. Callers must still resolve/fetch `variables` on EVERY process
  before calling — fetch_variables_to_host is a cross-process
  collective for sharded params, and gating the fetch instead of the
  write deadlocks the pod."""
  from tensor2robot_tpu.parallel import distributed
  if not distributed.is_primary():
    return None
  export_dir = generator.export(variables, global_step=global_step)
  garbage_collect_exports(generator.export_root, keep=keep)
  return export_dir


def write_spec_assets(
    export_dir: str,
    feature_spec: ts.SpecStructure,
    label_spec: Optional[ts.SpecStructure] = None,
    extra: Optional[dict] = None,
    global_step: int = 0,
) -> str:
  """Writes the spec asset files predictors read the signature from.

  Two equivalent assets per export version: human-readable JSON and the
  language-neutral proto twin (proto/t2r.proto §T2RAssets — reference
  parity: proto-serialized spec assets alongside SavedModels).
  """
  payload = {
      "feature_spec": json.loads(ts.to_serialized(feature_spec)),
      "label_spec": (json.loads(ts.to_serialized(label_spec))
                     if label_spec is not None else None),
      "extra": extra or {},
      "global_step": int(global_step),
  }
  path = os.path.join(export_dir, SPEC_ASSET_NAME)
  with open(path, "w") as f:
    json.dump(payload, f, indent=2, sort_keys=True)
  from tensor2robot_tpu.proto import proto_utils
  assets = proto_utils.make_t2r_assets(
      feature_spec, label_spec, extra=extra, global_step=global_step)
  with open(os.path.join(export_dir, SPEC_ASSET_PB_NAME), "wb") as f:
    f.write(assets.SerializeToString())
  return path


def read_spec_assets(
    export_dir: str,
) -> Tuple[ts.TensorSpecStruct, Optional[ts.TensorSpecStruct], dict]:
  """Reads back (feature_spec, label_spec, extra).

  Prefers the JSON asset; falls back to the proto twin so artifacts
  written by non-Python exporters (proto only) still load.
  """
  path = os.path.join(export_dir, SPEC_ASSET_NAME)
  if not os.path.exists(path):
    from tensor2robot_tpu.proto import proto_utils, t2r_pb2
    pb_path = os.path.join(export_dir, SPEC_ASSET_PB_NAME)
    with open(pb_path, "rb") as f:
      assets = t2r_pb2.T2RAssets.FromString(f.read())
    return proto_utils.parse_t2r_assets(assets)
  with open(path) as f:
    payload = json.load(f)
  feature_spec = ts.from_serialized(json.dumps(payload["feature_spec"]))
  label_spec = (ts.from_serialized(json.dumps(payload["label_spec"]))
                if payload.get("label_spec") is not None else None)
  return feature_spec, label_spec, payload.get("extra", {})

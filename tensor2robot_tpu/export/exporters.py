"""Eval-driven exporters: latest / best-metric export policies.

Reference parity: tf.estimator's LatestExporter / BestExporter wired in
by utils/train_eval.py §create_exporters_fn (SURVEY.md §2 train/eval
orchestrator row, §3.2 call stack) — after each evaluation the
Estimator's EvalSpec exporters decide whether that checkpoint becomes a
serving artifact. Here an `Exporter` is driven by the train/eval loop
(and the continuous evaluator) with the evaluated variables and the
eval metrics; policies decide whether to publish a new export version.

Each exporter owns its own export generator instance and publishes to
`<model_dir>/export/<name>/<version>/`, the directory robots poll.
"""

from __future__ import annotations

import json
import logging
import math
import os
from typing import Callable, Dict, List, Optional, Sequence

from tensor2robot_tpu.config import configurable
from tensor2robot_tpu.export import export_utils

_log = logging.getLogger(__name__)


class Exporter:
  """Policy deciding when an eval result becomes a serving artifact."""

  def __init__(self, export_generator, name: str, keep: int = 5):
    self._generator = export_generator
    self.name = name
    self._keep = keep
    self._ready = False

  def begin(self, model, model_dir: str) -> None:
    """Binds the export root and the model's specs (idempotent)."""
    if self._ready:
      return
    try:
      self._generator.export_root
    except ValueError:
      if not model_dir:
        raise ValueError(
            f"Exporter {self.name!r} needs a model_dir to place its "
            "export root under.")
      self._generator.export_root = os.path.join(
          model_dir, "export", self.name)
    self._generator.set_specification_from_model(model)
    self._ready = True

  @property
  def export_root(self) -> str:
    return self._generator.export_root

  def after_eval(self, variables, global_step: int,
                 eval_metrics: Dict[str, float]) -> Optional[str]:
    """Maybe exports; returns the published dir or None.

    `variables` may be the variables pytree or a zero-arg callable
    returning it — the callable form lets callers defer the
    device→host transfer until a policy actually publishes."""
    raise NotImplementedError

  def _export(self, variables, global_step: int) -> Optional[str]:
    # Resolve the provider on EVERY process (the fetch inside is a
    # cross-process collective for sharded params); export_and_gc then
    # writes on the primary only and returns None elsewhere.
    if callable(variables):
      variables = variables()
    export_dir = export_utils.export_and_gc(
        self._generator, variables, keep=self._keep,
        global_step=global_step)
    if export_dir is not None:
      _log.info("Exporter %r published %s", self.name, export_dir)
    return export_dir


@configurable
class LatestExporter(Exporter):
  """Exports after every evaluation (tf.estimator.LatestExporter)."""

  def __init__(self, export_generator, name: str = "latest",
               keep: int = 5):
    super().__init__(export_generator, name=name, keep=keep)

  def after_eval(self, variables, global_step: int,
                 eval_metrics: Dict[str, float]) -> Optional[str]:
    return self._export(variables, global_step)


@configurable
class BestExporter(Exporter):
  """Exports only when the tracked eval metric improves
  (tf.estimator.BestExporter).

  The best value seen is persisted to `<export_root>/best_eval.json`, so
  a restarted eval job keeps comparing against the all-time best rather
  than re-exporting its first evaluation.
  """

  _STATE_FILE = "best_eval.json"

  def __init__(self, export_generator, name: str = "best",
               metric_key: str = "loss", higher_is_better: bool = False,
               keep: int = 5):
    super().__init__(export_generator, name=name, keep=keep)
    self._metric_key = metric_key
    self._higher_is_better = higher_is_better
    self._best: Optional[float] = None

  def begin(self, model, model_dir: str) -> None:
    first = not self._ready
    super().begin(model, model_dir)
    if first:
      path = os.path.join(self.export_root, self._STATE_FILE)
      if os.path.exists(path):
        try:
          with open(path) as f:
            self._best = float(json.load(f)["best"])
        except (ValueError, KeyError, TypeError):
          # A corrupt state file (e.g. truncated by a crash predating the
          # atomic write) must not brick the job; restart the comparison.
          _log.warning("Ignoring unreadable %s", path)

  def _improved(self, value: float) -> bool:
    if math.isnan(value):
      return False
    if self._best is None:
      return True
    return (value > self._best if self._higher_is_better
            else value < self._best)

  def after_eval(self, variables, global_step: int,
                 eval_metrics: Dict[str, float]) -> Optional[str]:
    if self._metric_key not in eval_metrics:
      raise KeyError(
          f"BestExporter {self.name!r} tracks {self._metric_key!r} but "
          f"eval produced {sorted(eval_metrics)}.")
    value = float(eval_metrics[self._metric_key])
    if not self._improved(value):
      return None
    export_dir = self._export(variables, global_step)
    # Policy state advances on every host (eval metrics are replicated,
    # so the decision stays host-consistent); the state FILE is the
    # primary's side effect, like the export itself (export_dir is None
    # on non-primary hosts).
    self._best = value
    if export_dir is None:
      return None
    os.makedirs(self.export_root, exist_ok=True)
    # Atomic tmp+rename (same protocol as export publishing): a crash
    # mid-write must never leave a truncated state file behind.
    path = os.path.join(self.export_root, self._STATE_FILE)
    tmp_path = path + ".tmp"
    with open(tmp_path, "w") as f:
      json.dump({"best": value, "metric": self._metric_key,
                 "global_step": int(global_step)}, f)
    os.replace(tmp_path, path)
    return export_dir


@configurable
def create_default_exporters_fn(
    export_generator_factory: Callable[[], object],
    best_metric_key: str = "loss",
    higher_is_better: bool = False,
    keep: int = 5,
) -> Callable[[object], List[Exporter]]:
  """Returns a create_exporters_fn making the reference's default pair:
  a LatestExporter plus a BestExporter on `best_metric_key`
  (utils/train_eval.py §create_exporters_fn default behaviour)."""

  def create_exporters_fn(model) -> List[Exporter]:
    del model  # exporters bind specs in begin()
    return [
        LatestExporter(export_generator_factory(), keep=keep),
        BestExporter(export_generator_factory(),
                     metric_key=best_metric_key,
                     higher_is_better=higher_is_better, keep=keep),
    ]

  return create_exporters_fn


def run_exporters(exporters: Sequence[Exporter], variables,
                  global_step: int,
                  eval_metrics: Dict[str, float]) -> Dict[str, str]:
  """Drives every exporter after one evaluation; returns {name: dir}
  for the ones that published. `variables` may be the pytree or a
  zero-arg callable (fetched at most once across all exporters)."""
  if callable(variables):
    provider, cache = variables, []

    def variables():  # noqa: F811 — memoized provider
      if not cache:
        cache.append(provider())
      return cache[0]

  published = {}
  for exporter in exporters:
    export_dir = exporter.after_eval(variables, global_step, eval_metrics)
    if export_dir is not None:
      published[exporter.name] = export_dir
  return published

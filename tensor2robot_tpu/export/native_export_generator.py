"""Native export: jax.export (StableHLO) + npz variables + spec assets.

The TPU-native serving format (replaces the reference's SavedModel for
pure-JAX consumers): the PREDICT computation is serialized as portable
StableHLO compiled-for {cpu, tpu}, so a robot-side process deserializes
and calls it with zero model Python code — the same decoupling as
SURVEY.md §3.3's SavedModel contract.

Artifact layout (one versioned dir):
    serving_fn.bin     jax.export.Exported.serialize() of
                       serve(variables, *features_in_key_order) -> {name: out}
    variables.npz      flat npz of the variables dict (export/variables_io.py;
                       numpy is the only robot-side dependency)
    t2r_assets.json    feature specs + feature key order + metadata
    t2r_assets.pb      proto twin of the JSON assets (proto/t2r.proto)

Batch dim is exported symbolically ("b") so serving batch size is free —
QT-Opt's CEM sweeps batch sizes at inference (SURVEY.md §3.3).
"""

from __future__ import annotations

import os
from typing import Any, Optional, Sequence

import jax
import jax.export  # the jax.export submodule is lazy: attribute access
# alone raises AttributeError in a process where nothing else has
# imported it (bare multi-host workers; the in-process test suite gets
# it transitively and never sees this).
import numpy as np

from tensor2robot_tpu.export import export_utils, variables_io
from tensor2robot_tpu.export.abstract_export_generator import (
    AbstractExportGenerator,
)

SERVING_FN_NAME = "serving_fn.bin"
VARIABLES_DIR = "variables"  # legacy orbax layout, still readable
VARIABLES_NPZ = "variables.npz"


class NativeExportGenerator(AbstractExportGenerator):
  """Emits the native StableHLO serving artifact."""

  def __init__(
      self,
      export_root: Optional[str] = None,
      platforms: Sequence[str] = ("cpu", "tpu"),
      polymorphic_batch: bool = True,
  ):
    super().__init__(export_root)
    self._platforms = tuple(platforms)
    self._polymorphic_batch = polymorphic_batch

  def export(self, variables: Any, global_step: int = 0) -> str:
    model = self._model
    feature_spec = self.feature_spec
    keys = list(feature_spec.keys())

    def serve(variables, *feature_arrays):
      features = type(feature_spec)(zip(keys, feature_arrays))
      # Plain dict out: stable across deserialization without custom
      # pytree registration on the consumer side.
      return export_utils.normalize_serving_outputs(
          model.predict_fn(variables, features))

    if self._polymorphic_batch:
      batch = jax.export.symbolic_shape("b")[0]
    else:
      batch = 1
    arg_shapes = [
        jax.ShapeDtypeStruct((batch,) + spec.shape, spec.dtype)
        for spec in feature_spec.values()
    ]
    variables = jax.device_get(variables)
    var_shapes = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
        variables)
    from tensor2robot_tpu.ops import dispatch
    with dispatch.xla_only():
      # Multi-platform artifacts lower every branch for every platform;
      # compiled Pallas calls cannot lower for the CPU target.
      exported = jax.export.export(
          jax.jit(serve), platforms=self._platforms)(var_shapes, *arg_shapes)

    tmp_dir, final_dir = export_utils.versioned_export_dir(self.export_root)
    os.makedirs(tmp_dir, exist_ok=True)
    with open(os.path.join(tmp_dir, SERVING_FN_NAME), "wb") as f:
      f.write(exported.serialize())
    # Variables as one flat npz (variables_io): numpy-only on the robot
    # side, and no checkpoint-library global state in this (possibly
    # worker) thread while the trainer checkpoints concurrently.
    variables_io.save_variables(
        os.path.join(tmp_dir, VARIABLES_NPZ), variables)
    export_utils.write_spec_assets(
        tmp_dir, feature_spec,
        extra={
            "format": "jax_export_stablehlo",
            "feature_keys": keys,
            "platforms": list(self._platforms),
        },
        global_step=global_step)
    return export_utils.publish(tmp_dir, final_dir)

"""SavedModel export via jax2tf — the reference's robot serving contract.

Reference parity: export_generators/default_export_generator.py
§DefaultExportGenerator (SURVEY.md §2, §3.2): versioned SavedModels with
spec assets, a numpy-feed signature, and a serialized-tf.Example
signature (parse_example built from the same specs). Robots running the
reference's ExportedSavedModelPredictor keep working unchanged — the
BASELINE north star.

TF is imported lazily: the core framework never needs it; only this
compatibility exporter does.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np

from tensor2robot_tpu.export import export_utils
from tensor2robot_tpu.export.abstract_export_generator import (
    AbstractExportGenerator,
)
from tensor2robot_tpu.specs import tensorspec_utils as ts


class SavedModelExportGenerator(AbstractExportGenerator):
  """Emits tf.saved_model versions from JAX variables via jax2tf."""

  def __init__(
      self,
      export_root: Optional[str] = None,
      platforms: Sequence[str] = ("cpu", "tpu"),
      with_tf_example_signature: bool = True,
  ):
    super().__init__(export_root)
    self._platforms = tuple(platforms)
    self._with_tf_example_signature = with_tf_example_signature

  def export(self, variables: Any, global_step: int = 0) -> str:
    import tensorflow as tf
    from jax.experimental import jax2tf

    from tensor2robot_tpu.ops import dispatch
    # Multi-platform serialization lowers all branches per platform;
    # Pallas calls can't lower for CPU (ops/dispatch.py).
    with dispatch.xla_only():
      return self._export(variables, global_step, tf, jax2tf)

  def _export(self, variables: Any, global_step: int, tf, jax2tf) -> str:
    model = self._model
    feature_spec = self.feature_spec
    keys = list(feature_spec.keys())
    variables = jax.device_get(variables)

    def serve(variables, *feature_arrays):
      features = type(feature_spec)(zip(keys, feature_arrays))
      return export_utils.normalize_serving_outputs(
          model.predict_fn(variables, features))

    tf_fn = jax2tf.convert(
        serve,
        polymorphic_shapes=[None] + ["(b, ...)"] * len(keys),
        native_serialization_platforms=self._platforms,
        with_gradient=False)

    module = tf.Module()
    # Weights as tf.Variables so the SavedModel is self-contained.
    module._variables = tf.nest.map_structure(
        lambda x: tf.Variable(np.asarray(x), trainable=False), variables)
    flat_module_vars = tf.nest.flatten(module._variables)
    var_struct = tf.nest.map_structure(lambda x: 0, variables)

    def _rebuild():
      return tf.nest.pack_sequence_as(var_struct, flat_module_vars)

    tensor_specs = [
        tf.TensorSpec((None,) + spec.shape, tf.as_dtype(np.dtype(spec.dtype)),
                      name=key)
        for key, spec in feature_spec.items()
    ]

    @tf.function(input_signature=tensor_specs)
    def serving_default(*feature_arrays):
      return tf_fn(_rebuild(), *feature_arrays)

    signatures = {"serving_default": serving_default}

    if self._with_tf_example_signature:
      parse_schema, raw_keys = self._tf_example_schema(tf, feature_spec)

      @tf.function(
          input_signature=[tf.TensorSpec([None], tf.string, name="input")])
      def serving_tf_example(serialized):
        parsed = tf.io.parse_example(serialized, parse_schema)
        arrays = []
        for key, spec in feature_spec.items():
          value = parsed[key]
          if ts.is_encoded_image_spec(spec):
            value = tf.map_fn(
                lambda s: tf.io.decode_image(
                    s, channels=spec.shape[-1], expand_animations=False),
                value, fn_output_signature=tf.uint8)
            value = tf.reshape(value, (-1,) + spec.shape)
          elif key in raw_keys:
            # Raw-bytes tensor convention (array.tobytes() as a single
            # bytes value — the same wire format data/parser.py accepts)
            # for dtypes tf.io.parse_example cannot parse directly.
            value = tf.io.decode_raw(
                value, tf.as_dtype(np.dtype(spec.dtype)))
            value = tf.reshape(value, (-1,) + spec.shape)
          arrays.append(value)
        return tf_fn(_rebuild(), *arrays)

      signatures["tf_example"] = serving_tf_example

    tmp_dir, final_dir = export_utils.versioned_export_dir(self.export_root)
    tf.saved_model.save(module, tmp_dir, signatures=signatures)
    export_utils.write_spec_assets(
        tmp_dir, feature_spec,
        extra={"format": "tf_saved_model", "feature_keys": keys,
               "platforms": list(self._platforms)},
        global_step=global_step)
    return export_utils.publish(tmp_dir, final_dir)

  @staticmethod
  def _tf_example_schema(tf, feature_spec: ts.TensorSpecStruct):
    """Specs → (tf.io parse schema, raw-bytes keys).

    Reference §tensorspec_to_feature_dict. Dtypes tf.io.parse_example
    cannot parse (anything outside float32/int64/string — e.g. the
    uint8 image wire format) are declared as raw-bytes string features
    and decode_raw'd in the serving fn.
    """
    parseable = {np.dtype(np.float32), np.dtype(np.int64)}
    schema = {}
    raw_keys = set()
    for key, spec in feature_spec.items():
      if ts.is_encoded_image_spec(spec):
        schema[key] = tf.io.FixedLenFeature([], tf.string)
      elif spec.varlen_default_value is not None:
        schema[key] = tf.io.FixedLenSequenceFeature(
            spec.shape[1:], tf.as_dtype(np.dtype(spec.dtype)),
            allow_missing=True,
            default_value=spec.varlen_default_value)
      elif np.dtype(spec.dtype) not in parseable:
        schema[key] = tf.io.FixedLenFeature([], tf.string)
        raw_keys.add(key)
      else:
        schema[key] = tf.io.FixedLenFeature(
            spec.shape, tf.as_dtype(np.dtype(spec.dtype)))
    return schema, raw_keys

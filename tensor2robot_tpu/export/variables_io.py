"""Self-contained variables artifact: one flat .npz, no checkpoint deps.

The native serving artifact stores model variables as a single npz file
(flat "/"-joined tree paths + an embedded JSON manifest) instead of a
training-checkpoint directory. Two reasons:

1. Robot-side consumers (predictors/) need only numpy to load a model —
   no orbax/tensorstore on the robot (the reference's equivalent
   decoupling: robots load SavedModels, never trainer checkpoints;
   SURVEY.md §3.3).
2. The async export hook writes from a worker thread while the trainer's
   orbax CheckpointManager may be mid-save on its own background thread
   (hooks/async_export_hook.py). Keeping the export path free of the
   checkpoint library's global state removes that thread-safety coupling.

Non-numpy-native dtypes (bfloat16 etc. from ml_dtypes) are stored as raw
byte views with the true dtype recorded in the manifest.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Mapping

import numpy as np

MANIFEST_KEY = "__t2r_manifest__"
_EMPTY_DICTS_KEY = "__empty_dicts__"
_RESERVED_KEYS = (MANIFEST_KEY, _EMPTY_DICTS_KEY)
_SEP = "/"


def _flatten(variables: Mapping[str, Any], prefix: str = "",
             out: Dict[str, np.ndarray] = None,
             empty: list = None) -> Dict[str, np.ndarray]:
  if out is None:
    out = {}
  if empty is None:
    empty = []
  if prefix and not variables:
    # Empty collections (e.g. a stateless model's batch_stats) must
    # survive the round trip: the serving fn was traced with the exact
    # variables pytree, so dropping them breaks the serve-time call.
    empty.append(prefix)
    return out
  for key, value in variables.items():
    if not isinstance(key, str):
      raise TypeError(f"Variable tree keys must be str, got {key!r}")
    if _SEP in key:
      raise ValueError(f"Variable name may not contain '{_SEP}': {key!r}")
    if key in _RESERVED_KEYS:
      raise ValueError(f"Variable name {key!r} is reserved")
    path = f"{prefix}{_SEP}{key}" if prefix else key
    if isinstance(value, Mapping):
      _flatten(value, path, out, empty)
    else:
      out[path] = np.asarray(value)
  return out


def _unflatten(flat: Mapping[str, np.ndarray],
               empty_dicts: list = ()) -> Dict[str, Any]:
  tree: Dict[str, Any] = {}
  for path in empty_dicts:
    node = tree
    for part in path.split(_SEP):
      node = node.setdefault(part, {})
  for path, value in flat.items():
    parts = path.split(_SEP)
    node = tree
    for part in parts[:-1]:
      node = node.setdefault(part, {})
    node[parts[-1]] = value
  return tree


def save_variables(path: str, variables: Mapping[str, Any]) -> None:
  """Writes a nested {str: array} tree to one npz file at `path`."""
  empty: list = []
  flat = _flatten(variables, empty=empty)
  manifest = {_EMPTY_DICTS_KEY: sorted(empty)}
  arrays = {}
  for key, value in flat.items():
    manifest[key] = {"dtype": value.dtype.name,
                     "shape": list(value.shape)}
    if value.dtype.kind == "V" or not value.dtype.isbuiltin:
      # ml_dtypes (bfloat16, float8_*) round-trip as byte views. Flatten
      # first: 0-d arrays reject itemsize-changing views, and the true
      # shape is restored from the manifest on load anyway.
      value = np.ascontiguousarray(value).reshape(-1).view(np.uint8)
    arrays[key] = value
  arrays[MANIFEST_KEY] = np.frombuffer(
      json.dumps(manifest, sort_keys=True).encode("utf-8"), dtype=np.uint8)
  with open(path, "wb") as f:
    np.savez(f, **arrays)


def load_variables(path: str) -> Dict[str, Any]:
  """Inverse of `save_variables`; returns nested dicts of numpy arrays."""
  with np.load(path) as data:
    manifest = json.loads(bytes(data[MANIFEST_KEY]).decode("utf-8"))
    empty_dicts = manifest.pop(_EMPTY_DICTS_KEY, [])
    flat = {}
    for key, meta in manifest.items():
      value = data[key]
      dtype = _lookup_dtype(meta["dtype"])
      if value.dtype != dtype:
        value = value.view(dtype).reshape(meta["shape"])
      flat[key] = value
  return _unflatten(flat, empty_dicts)


def _lookup_dtype(name: str) -> np.dtype:
  try:
    return np.dtype(name)
  except TypeError:
    import ml_dtypes
    return np.dtype(getattr(ml_dtypes, name))

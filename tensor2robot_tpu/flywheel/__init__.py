"""Fleet data flywheel: served traffic becomes the training stream.

ISSUE 18 — the serve→collect→train→redeploy cycle QT-Opt actually ran
(PAPER.md): the serving fleet's answered requests are captured at the
dispatch seam, closed against the env-dynamics oracle, validated
against the replay spec, and re-ingested as the learner's data — whose
exports then flow back through shadow→canary→promote to change the
very traffic they will later train on.

Layout:
  capture.py        EpisodeRecorder (the PolicyReplica._flush seam),
                    FlywheelIngest (the spec-validated re-ingest gate),
                    flywheel_rules (the poisoning-interlock HealthRules)
  loop.py           FleetClient (episode driver + outcome closer) and
                    FlywheelLoop (the closed cycle end to end)
  flywheel_bench.py the FLYWHEEL_r18 proof artifact
"""

from tensor2robot_tpu.flywheel.capture import (  # noqa: F401
    EpisodeRecorder,
    FlywheelIngest,
    IngestRejected,
    ServedRecord,
    flywheel_rules,
)
from tensor2robot_tpu.flywheel.loop import (  # noqa: F401
    FleetClient,
    FlywheelConfig,
    FlywheelLoop,
)

"""Episode capture at the serving seam + the spec-validated ingest gate.

Two halves of ISSUE 18's data path:

**EpisodeRecorder** hooks ``PolicyReplica._flush`` (the router passes it
down at construction): per served request it logs the scene image, the
CEM seed, the action the fleet ACTUALLY answered with (post-fault — the
seam is the truth, not the client's view), the serving params version
the dispatch ran under, and the request's correlation id (ISSUE 12; the
batcher binds the batch's ids in item order before calling the flush).
The flywheel's episode driver then waits on its request id to close the
transition against the env-dynamics oracle.

**FlywheelIngest** is the door back into the replay ring: a completed
episode re-enters ONLY through the same ``specs/tensorspec_utils``
validation the synthetic collectors' transitions pass (the spec system
types both sides by design). A malformed episode — shape/dtype drift, a
missing outcome stream, a transition without its correlation id — is
REFUSED with the offending field named: the gate raises
``IngestRejected``, counts it, and fires a ``flywheel_ingest_rejected``
flight-recorder dump; nothing is ever silently dropped. Accepted
episodes enqueue provenance-tagged ("served") and feed the staleness /
coverage / mix health metrics the sentinel rules watch.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from tensor2robot_tpu.obs import context as context_lib
from tensor2robot_tpu.obs import flight_recorder as flight_lib
from tensor2robot_tpu.obs import registry as registry_lib
from tensor2robot_tpu.obs.health import HealthRule
from tensor2robot_tpu.replay.ingest import (TRANSITION_KEYS,
                                            TransitionQueue,
                                            episode_to_transitions)
from tensor2robot_tpu.replay.ring_buffer import _validate_against_spec
from tensor2robot_tpu.specs import tensorspec_utils as ts


@dataclasses.dataclass
class ServedRecord:
  """What the serving seam knew about one answered request."""

  request_id: str
  image: np.ndarray
  seed: int
  action: np.ndarray
  params_version: Optional[int]
  device: str
  t_s: float


class EpisodeRecorder:
  """Thread-safe capture buffer keyed by request correlation id.

  ``record_served`` runs on replica dispatcher threads (inside
  ``_flush``, exception-isolated there); ``wait_for`` runs on the
  episode driver's thread and blocks until the request's record lands
  (a canary-phase live mirror can resolve after the client's own
  future). First capture per id wins: a router retry that re-flushes a
  request records a DUPLICATE (counted, not stored) — the first flush's
  action is the one whose answer the client received. Pending records
  are bounded FIFO (``max_pending``): an id nobody ever collects (a
  shed client, a crashed driver) is evicted oldest-first and counted.
  """

  def __init__(self, max_pending: int = 4096):
    if max_pending < 1:
      raise ValueError(f"max_pending must be >= 1, got {max_pending}")
    self._max_pending = max_pending
    self._records: "OrderedDict[str, ServedRecord]" = OrderedDict()
    self._cond = threading.Condition()
    self._epoch = time.perf_counter()
    self.captured = 0      # unique request ids recorded
    self.duplicates = 0    # repeat captures for an already-held id
    self.unattributed = 0  # batch items with no bound request id
    self.evicted = 0       # never-collected records shed by the bound
    self.collected = 0     # records handed to a waiter

  def record_served(self, items: Sequence, actions, device: str,
                    params_version: Optional[int] = None) -> int:
    """Captures one flushed batch; returns newly recorded count.

    ``items`` are the batcher's (image, seed) tuples in batch order;
    the bound ``request_ids`` context attr (comma-joined by the
    batcher, same order) attributes each item to its request.
    """
    joined = context_lib.context_attrs().get("request_ids") or ""
    ids = joined.split(",") if joined else []
    now = time.perf_counter() - self._epoch
    fresh = 0
    with self._cond:
      for i, (item, action) in enumerate(zip(items, actions)):
        request_id = ids[i] if i < len(ids) and ids[i] else None
        if request_id is None:
          self.unattributed += 1
          continue
        if request_id in self._records:
          self.duplicates += 1
          continue
        self._records[request_id] = ServedRecord(
            request_id=request_id,
            image=np.asarray(item[0]),
            seed=int(item[1]),
            action=np.array(action, np.float32, copy=True),
            params_version=(None if params_version is None
                            else int(params_version)),
            device=device,
            t_s=round(now, 6))
        self.captured += 1
        fresh += 1
      while len(self._records) > self._max_pending:
        self._records.popitem(last=False)
        self.evicted += 1
      if fresh:
        self._cond.notify_all()
    return fresh

  def wait_for(self, request_id: str,
               timeout: float = 5.0) -> Optional[ServedRecord]:
    """Pops the id's record, blocking up to ``timeout``; None on miss
    (a shed request never flushes, so its record never arrives)."""
    deadline = time.monotonic() + timeout
    with self._cond:
      while True:
        record = self._records.pop(request_id, None)
        if record is not None:
          self.collected += 1
          return record
        remaining = deadline - time.monotonic()
        if remaining <= 0:
          return None
        self._cond.wait(remaining)

  def pending(self) -> int:
    with self._cond:
      return len(self._records)

  def snapshot(self) -> Dict[str, int]:
    with self._cond:
      return {
          "captured": self.captured,
          "collected": self.collected,
          "duplicates": self.duplicates,
          "unattributed": self.unattributed,
          "evicted": self.evicted,
          "pending": len(self._records),
      }


class IngestRejected(ValueError):
  """A served episode refused at the ingest gate, offending field named."""

  def __init__(self, field: str, detail: str):
    self.field = field
    self.detail = detail
    super().__init__(
        f"served episode refused at ingest ({field}): {detail}")


class FlywheelIngest:
  """Spec-validated door from closed episodes back into the replay ring.

  Every accepted transition is traceable: the gate requires one
  correlation id and one serving-params version PER STEP, measures the
  params-version lag against the learner's current step (the staleness
  metric), and enqueues the validated batch provenance-tagged
  ("served") so the ring's mix ledger stays exact. Refusals raise
  ``IngestRejected`` with the field named — the caller decides what to
  do with the episode, but the gate never eats one silently.
  """

  def __init__(self, queue: TransitionQueue, transition_spec,
               learner_step_fn, monitor=None,
               registry: Optional[registry_lib.MetricRegistry] = None,
               flight_recorder=None, coverage_window: int = 32):
    self._queue = queue
    self._spec = ts.flatten_spec_structure(transition_spec)
    self._learner_step_fn = learner_step_fn
    self._monitor = monitor
    self._registry = registry or registry_lib.get_registry()
    self._recorder = flight_recorder or flight_lib.get_recorder()
    self._lock = threading.Lock()
    # Per-scene coverage over the most recent episodes: a fleet stuck
    # replaying one scene (a poisoned or looping client) collapses this
    # to 1 while every per-episode check still passes.
    self._scene_window: deque = deque(maxlen=coverage_window)
    self._request_ids: set = set()
    self._baseline_enqueued = 0
    self.episodes_ingested = 0
    self.transitions_ingested = 0
    self.rejected = 0
    self.max_staleness_lag = 0
    self.last_staleness_lag = 0

  def mark_cutover(self) -> None:
    """Snapshots the queue's enqueue counter as the mix baseline.

    The served-mix rule bounds the served share of what entered the
    queue SINCE CUTOVER — the warm-start phase legitimately enqueues
    thousands of synthetic rows, and folding them into the denominator
    forever would make the mix floor unreachable on a healthy run.
    After cutover the synthetic collectors are off, so anything
    diluting the post-cutover stream is exactly what the rule exists
    to catch."""
    with self._lock:
      self._baseline_enqueued = self._queue.stats()["enqueued"]

  def _reject(self, field: str, detail: str, scene_seed) -> None:
    with self._lock:
      self.rejected += 1
    self._registry.counter("flywheel/ingest_rejected").inc()
    self._recorder.trigger("flywheel_ingest_rejected", field=field,
                           detail=detail, scene_seed=int(scene_seed))
    raise IngestRejected(field, detail)

  def submit_episode(self, episode, *, scene_seed: int,
                     request_ids: Sequence[str],
                     params_versions: Sequence[Optional[int]],
                     provenance: str = "served") -> int:
    """Validates + enqueues one closed episode; returns transitions.

    Raises IngestRejected (field named) on: a step missing its
    correlation id or params version, episode streams disagreeing on
    length (the missing-outcome case: a served action whose reward/done
    never closed), or any spec key/shape/dtype mismatch.
    """
    actions = np.asarray(episode.get("actions", ()))
    steps = len(actions)
    request_ids = list(request_ids)
    params_versions = list(params_versions)
    if len(request_ids) != steps or any(not rid for rid in request_ids):
      self._reject(
          "request_ids",
          f"{len(request_ids)} correlation id(s) for {steps} step(s); "
          "every served transition must carry its originating "
          "request's id", scene_seed)
    if (len(params_versions) != steps
        or any(v is None for v in params_versions)):
      self._reject(
          "params_versions",
          f"{len(params_versions)} params version(s) for {steps} "
          "step(s); staleness lag needs the serving version per step",
          scene_seed)
    try:
      transitions = episode_to_transitions(episode)
    except (ValueError, KeyError) as e:
      self._reject("episode_streams", str(e), scene_seed)
    batch = {key: np.stack([t[key] for t in transitions])
             for key in TRANSITION_KEYS}
    try:
      batch = _validate_against_spec(self._spec, batch, batched=True)
    except ValueError as e:
      detail = str(e)
      field = next((key for key in self._spec
                    if detail.startswith(f"{key}:")), "spec_keys")
      self._reject(field, detail, scene_seed)

    self._queue.put_batch(batch, provenance=provenance)
    step = int(self._learner_step_fn())
    lag = step - min(int(v) for v in params_versions)
    with self._lock:
      self.episodes_ingested += 1
      self.transitions_ingested += steps
      self._request_ids.update(request_ids)
      self._scene_window.append(int(scene_seed))
      coverage = len(set(self._scene_window))
      served = self.transitions_ingested
      self.last_staleness_lag = lag
      self.max_staleness_lag = max(self.max_staleness_lag, lag)
    total = max(
        self._queue.stats()["enqueued"] - self._baseline_enqueued, 1)
    metrics = {
        "flywheel/staleness_lag": float(lag),
        "flywheel/scene_coverage": float(coverage),
        "flywheel/served_fraction": served / total,
    }
    for name, value in metrics.items():
      self._registry.gauge(name).set(value)
    if self._monitor is not None:
      # Cross-thread safe: HealthMonitor.observe is lock-guarded, and
      # the ingest tick is the right observation point — the interlock
      # must fire on what ENTERS the learner, not on a wall clock.
      self._monitor.observe(step, metrics)
    return steps

  def unique_request_ids(self) -> int:
    with self._lock:
      return len(self._request_ids)

  def snapshot(self) -> Dict[str, float]:
    with self._lock:
      return {
          "episodes_ingested": self.episodes_ingested,
          "transitions_ingested": self.transitions_ingested,
          "rejected": self.rejected,
          "unique_request_ids": len(self._request_ids),
          "scene_coverage_window": len(set(self._scene_window)),
          "last_staleness_lag": self.last_staleness_lag,
          "max_staleness_lag": self.max_staleness_lag,
      }


def flywheel_rules(staleness_ceiling: float,
                   coverage_floor: float = 4.0,
                   served_mix_floor: float = 0.05,
                   coverage_warmup: int = 8,
                   mix_warmup: int = 16) -> List[HealthRule]:
  """The ingested-stream interlock (wired into the ISSUE 12 sentinel).

  - staleness ceiling: ingested transitions were served by params more
    than ``staleness_ceiling`` learner steps behind — the promote path
    has stalled and the flywheel is feeding on its own stale output
    (warmup 0: the FIRST stale episode is already evidence);
  - per-scene coverage floor: distinct scenes over the recent episode
    window collapsed — a looping or poisoned data source;
  - served-vs-synthetic mix floor: the served share of everything
    enqueued since cutover (``FlywheelIngest.mark_cutover``) fell —
    some non-fleet source is still filling the ring after the
    synthetic collectors were supposedly retired.
  """
  return [
      HealthRule("flywheel_staleness_ceiling", "flywheel/staleness_lag",
                 kind="max", limit=float(staleness_ceiling), warmup=0),
      HealthRule("flywheel_scene_coverage_floor",
                 "flywheel/scene_coverage", kind="min",
                 limit=float(coverage_floor), warmup=coverage_warmup),
      HealthRule("flywheel_served_mix_floor", "flywheel/served_fraction",
                 kind="min", limit=float(served_mix_floor),
                 warmup=mix_warmup),
  ]

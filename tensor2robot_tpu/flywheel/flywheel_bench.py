"""Fleet data flywheel bench: served traffic becomes the stream — FLYWHEEL_r18.

The ISSUE 18 acceptance instrument. One CLOSED serve→collect→train→
redeploy loop runs live on the virtual mesh and its claims are
bar-checked AT GENERATION TIME. Three phases, ONE JSON line (the
repo's bench/driver contract):

1. **ingest_gate** — the spec-validated door in isolation: a
   well-formed served episode round-trips; a malformed one — shape
   drift, dtype drift, a missing outcome stream, a transition without
   its correlation id or serving version — is REFUSED with the
   offending field NAMED (``IngestRejected`` + a
   ``flywheel_ingest_rejected`` flight-recorder dump per refusal);
   nothing is silently dropped.
2. **closed_loop** — the full ``FlywheelLoop``: synthetic warm start,
   collectors retired PERMANENTLY at cutover, then policy improvement
   measured against the analytic Q* oracle while the ONLY incoming
   data is what the serving fleet answered — through ≥ 2 completed
   export→shadow→canary→promote cycles MID-RUN, every ingested
   transition carrying its originating request's correlation id,
   episode counts reconciling against the router's logical-request
   counter with no external bookkeeping, the ingest health rules
   (staleness / coverage / mix) green, and the whole run's executable
   ledger exactly-once (learner AOT, Bellman CEM, collector CEM, and
   every fleet replica bucket).
3. **stale_params_control** — the same loop with the export path
   SEVERED (no exports, no promotes): the fleet serves the warm-start
   params forever while the learner advances, and the staleness-
   ceiling rule MUST breach (with its ``health_breach`` dump) — the
   poisoning interlock's positive test. A flywheel guard that cannot
   detect its own promote path stalling is decoration.

HONESTY CAVEAT (carried as ``virtual_mesh``): chipless, the fleet is
XLA virtual CPU devices. What this artifact proves is LOOP STRUCTURE —
improvement with synthetic collection off, promote cycles changing the
serving params mid-run, per-transition traceability, the interlock
firing on the stalled control and staying silent on health — not
serving or ingest THROUGHPUT, which is the queued chip claim
(bench.py's flywheel block).
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Dict, List, Optional

import numpy as np

from tensor2robot_tpu.flywheel.capture import (FlywheelIngest,
                                               IngestRejected)
from tensor2robot_tpu.flywheel.loop import FlywheelConfig, FlywheelLoop

R18_MIN_PROMOTES = 2   # completed promote cycles mid-run, committed bar


def _find_dumps(logdir: str, reason: str) -> List[dict]:
  found = []
  for root, _, files in os.walk(logdir):
    for name in sorted(files):
      if name.startswith("flightrec-") and reason in name:
        try:
          with open(os.path.join(root, name)) as f:
            found.append(json.load(f))
        except (OSError, ValueError):
          pass
  return found


def _served_episode(image_size: int, action_size: int, steps: int,
                    seed: int) -> Dict[str, np.ndarray]:
  rng = np.random.default_rng(seed)
  return {
      "images": rng.integers(0, 255, (steps + 1, image_size,
                                      image_size, 3), dtype=np.uint8),
      "actions": rng.uniform(-1, 1, (steps, action_size)).astype(
          np.float32),
      "rewards": np.zeros((steps,), np.float32),
      "dones": np.zeros((steps,), np.float32),
  }


def _measure_ingest_gate(image_size: int, action_size: int,
                         seed: int) -> Dict:
  """Phase 1: the re-ingest door refuses malformed episodes BY NAME."""
  from tensor2robot_tpu.obs.flight_recorder import FlightRecorder
  from tensor2robot_tpu.obs.registry import MetricRegistry
  from tensor2robot_tpu.replay.ingest import TransitionQueue
  from tensor2robot_tpu.replay.loop import transition_spec

  logdir = tempfile.mkdtemp(prefix="flywheel_gate_")
  recorder = FlightRecorder(dump_dir=logdir, min_dump_interval_s=0.0)
  queue = TransitionQueue(64)
  ingest = FlywheelIngest(queue, transition_spec(image_size,
                                                 action_size),
                          learner_step_fn=lambda: 7,
                          registry=MetricRegistry(),
                          flight_recorder=recorder)
  steps = 3
  rids = [f"vm-gate-{i}" for i in range(steps)]
  versions = [5] * steps
  accepted = ingest.submit_episode(
      _served_episode(image_size, action_size, steps, seed),
      scene_seed=seed, request_ids=rids, params_versions=versions)

  # Each malformation must be refused with THIS field named.
  malformed = []
  episode = _served_episode(image_size, action_size, steps, seed + 1)
  episode["images"] = episode["images"][:, : image_size // 2]
  malformed.append(("image_shape_drift", episode, rids, versions,
                    "image"))
  # float64 actions are NOT drift — the spec door same-kind casts them
  # (the ISSUE 4 dtype normalization); complex payloads are not
  # same-kind castable and must be refused by name.
  episode = _served_episode(image_size, action_size, steps, seed + 2)
  episode["actions"] = episode["actions"].astype(np.complex64)
  malformed.append(("action_dtype_drift", episode, rids, versions,
                    "action"))
  episode = _served_episode(image_size, action_size, steps, seed + 3)
  episode["rewards"] = episode["rewards"][:-1]  # outcome never closed
  malformed.append(("missing_outcome", episode, rids, versions,
                    "episode_streams"))
  episode = _served_episode(image_size, action_size, steps, seed + 4)
  malformed.append(("missing_correlation_id", episode, rids[:-1],
                    versions, "request_ids"))
  episode = _served_episode(image_size, action_size, steps, seed + 5)
  malformed.append(("missing_params_version", episode, rids,
                    [5, None, 5], "params_versions"))

  cases = []
  for name, episode, case_rids, case_versions, want_field in malformed:
    try:
      ingest.submit_episode(episode, scene_seed=seed,
                            request_ids=case_rids,
                            params_versions=case_versions)
      cases.append({"case": name, "refused": False, "ok": False})
    except IngestRejected as e:
      cases.append({
          "case": name, "refused": True, "field": e.field,
          "detail": e.detail[:160],
          "ok": bool(e.field == want_field),
      })
  snapshot = ingest.snapshot()
  dumps = _find_dumps(logdir, "flywheel_ingest_rejected")
  # Refusals raise AND count AND dump — never a silent drop: the queue
  # holds exactly the accepted episode's transitions. Dump filenames
  # carry a monotonic per-process sequence since ISSUE 19, so N
  # refusals yield EXACTLY N files (the old ms-stamped names coalesced
  # back-to-back refusals and this bar was stuck at ">= 1").
  return {
      "accepted_transitions": accepted,
      "cases": cases,
      "rejected_count": snapshot["rejected"],
      "rejected_dumps": len(dumps),
      "queue_enqueued": queue.stats()["enqueued"],
      "ok": bool(accepted == steps
                 and all(case["ok"] for case in cases)
                 and snapshot["rejected"] == len(cases)
                 and len(dumps) == len(cases)
                 and queue.stats()["enqueued"] == steps),
  }


def _loop_evidence(result: Dict) -> Dict:
  """The compact per-run evidence block shared by both loop phases."""
  return {
      "config": result["config"],
      "evals": {k: v for k, v in result["evals"].items()
                if k != "history"},
      "eval_history": result["evals"]["history"],
      "promotes": {k: v for k, v in result["promotes"].items()
                   if k != "timeline"},
      "rollout_events": [entry["event"]
                         for entry in result["promotes"]["timeline"]],
      "capture": result["capture"],
      "ingest": result["ingest"],
      "client": result["client"],
      "synthetic_episodes": result["synthetic"]["episodes"],
      "provenance": result["provenance"],
      "reconcile": result["reconcile"],
      "health": result["health"],
      "ledger_exactly_once": result["ledger"]["exactly_once"],
      "ledger_learner": result["ledger"]["learner"],
      "queue": result["queue"],
  }


def _measure_closed_loop(config: FlywheelConfig) -> Dict:
  """Phase 2: the live flywheel; every committed claim checked."""
  result = FlywheelLoop(config).run()
  evidence = _loop_evidence(result)
  ingest = result["ingest"]
  capture = result["capture"]
  traceable = bool(
      ingest["transitions_ingested"] > 0
      and ingest["unique_request_ids"] == ingest["transitions_ingested"]
      and capture["unattributed"] == 0
      and result["client"]["rejected"] == 0)
  evidence["ok"] = bool(
      result["evals"]["fleet_phase_improvement"] > 0
      and result["promotes"]["completed"] >= R18_MIN_PROMOTES
      and traceable
      and result["reconcile"]["ok"]
      and result["health"]["ok"]
      and result["ledger"]["exactly_once"]
      and result["client"]["error"] is None)
  evidence["traceable"] = traceable
  return evidence


def _measure_stale_control(config: FlywheelConfig) -> Dict:
  """Phase 3: export path severed → the staleness rule MUST breach."""
  result = FlywheelLoop(config).run()
  evidence = _loop_evidence(result)
  breached = result["health"]["breaches_per_rule"]
  dumps = _find_dumps(os.path.join(result["workdir"], "flightrec"),
                      "health_breach")
  staleness_dump = any(
      dump.get("trigger", {}).get("rule") == "flywheel_staleness_ceiling"
      for dump in dumps)
  evidence["breach_dumps"] = len(dumps)
  evidence["staleness_dump_ok"] = bool(staleness_dump)
  evidence["ok"] = bool(
      "flywheel_staleness_ceiling" in breached
      and result["promotes"]["completed"] == 0
      and result["ingest"]["max_staleness_lag"]
      > result["config"]["staleness_ceiling"]
      and staleness_dump)
  return evidence


def measure_flywheel(
    warm_steps: int = 60,
    fleet_steps: int = 120,
    export_every: int = 30,
    control_fleet_steps: int = 90,
    seed: int = 0,
    enforce_bars: bool = True,
) -> Dict:
  """Runs the three-phase flywheel protocol; returns the FLYWHEEL_r18
  artifact dict. ``enforce_bars`` (the --smoke lane) raises if any
  committed acceptance bar fails AT GENERATION TIME — a committed
  flywheel artifact that does not meet its own bars must not exist."""
  import jax

  devices = jax.devices()
  device_kind = devices[0].device_kind
  base = FlywheelConfig(warm_steps=warm_steps, fleet_steps=fleet_steps,
                        export_every=export_every, seed=seed)

  gate = _measure_ingest_gate(base.image_size, base.action_size, seed)
  closed_loop = _measure_closed_loop(base)
  control_config = FlywheelConfig(
      warm_steps=warm_steps, fleet_steps=control_fleet_steps,
      export_every=export_every, promotes=False, seed=seed,
      # The healthy run's ceiling, resolved the same way — the control
      # and the healthy run disagree ONLY on whether exports flow.
      staleness_ceiling=base.resolved_staleness_ceiling())
  control = _measure_stale_control(control_config)

  flywheel_ok = bool(gate["ok"] and closed_loop["ok"])
  interlock_ok = bool(closed_loop["health"]["ok"] and control["ok"])
  result = {
      "round": 18,
      "metric": ("fleet data flywheel: served traffic captured, "
                 "spec-validated, re-ingested as the training stream "
                 "through live promote cycles"),
      "device_kind": device_kind,
      "virtual_mesh": device_kind.lower() == "cpu",
      "devices": len(devices),
      "ingest_gate": gate,
      "closed_loop": closed_loop,
      "stale_params_control": control,
      # Compact sentinels (bench.py round 18; null-safe): improvement
      # and cycle ORDERING are meaningful chipless; serving/ingest
      # throughput on real chips is the queued chip claim.
      "flywheel_policy_improvement": closed_loop["evals"][
          "fleet_phase_improvement"],
      "flywheel_ingest_health_ok": interlock_ok,
      "flywheel_ok": flywheel_ok,
      "note": (
          "One closed serve→collect→train→redeploy loop live on the "
          "virtual mesh: synthetic collectors retired at cutover, "
          "then the learner improves against the analytic Q* oracle "
          "while its ONLY incoming data is what the serving fleet "
          "answered — captured at the replica flush seam with its "
          "correlation id, CEM seed, and serving params version, "
          "closed against the env-dynamics oracle, and re-admitted "
          "through the same spec validation the synthetic path uses "
          "(malformed episodes refused with the field named, never "
          "dropped). Promote cycles complete mid-run so the deployed "
          "params change the data they later train on; ingested "
          "transitions reconcile 1:1 against the router's logical-"
          "request counter; the staleness/coverage/mix interlock is "
          "green — and breaches, with its dump, on the stale-params "
          "control whose export path is severed. Executable ledger "
          "exactly-once across learner, Bellman, collector, and every "
          "fleet replica bucket. virtual_mesh=true: structure/"
          "ordering claims only — serving and ingest throughput on "
          "real chips land via bench.py's flywheel block."),
  }

  if enforce_bars:
    failures = []
    if not gate["ok"]:
      failures.append(f"ingest gate failed: {gate}")
    if not closed_loop["ok"]:
      failures.append(
          "closed loop failed: improvement="
          f"{closed_loop['evals']['fleet_phase_improvement']}, "
          f"promotes={closed_loop['promotes']['completed']}, "
          f"traceable={closed_loop['traceable']}, "
          f"reconcile={closed_loop['reconcile']}, "
          f"health={closed_loop['health']}, "
          f"ledger={closed_loop['ledger_exactly_once']}, "
          f"client_error={closed_loop['client']['error']}")
    if not control["ok"]:
      failures.append(
          "stale-params control did not breach: "
          f"breaches={control['health']['breaches_per_rule']}, "
          f"max_lag={control['ingest']['max_staleness_lag']}, "
          f"dumps={control['breach_dumps']}")
    if failures:
      raise AssertionError(
          "FLYWHEEL_r18 acceptance bars failed: " + "; ".join(failures))
  return result


def main(argv=None) -> None:
  """CLI: ONE JSON line. --smoke bootstraps the 8-virtual-device CPU
  mesh (re-exec with the canonical env) and runs the committed
  FLYWHEEL_r18 protocol with generation-time bar enforcement; --ci is
  the reduced tier-1 lane (short phases, bars deferred to
  tests/test_flywheel.py behind the cpu_count gate)."""
  import argparse
  import sys

  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument("--smoke", action="store_true",
                      help="chipless committed-artifact lane: full "
                           "protocol, bars enforced at generation time")
  parser.add_argument("--ci", action="store_true",
                      help="reduced chipless lane for tier-1 tests")
  parser.add_argument("--seed", type=int, default=0)
  parser.add_argument("--out", default=None,
                      help="also write the JSON line to this file")
  args = parser.parse_args(argv)
  if args.smoke or args.ci:
    from tensor2robot_tpu.utils.cpu_mesh_env import (cpu_mesh_env,
                                                     is_cpu_mesh_env)
    n = 8 if args.smoke else 2
    if not is_cpu_mesh_env(n):
      if argv is not None:
        raise RuntimeError(
            "--smoke/--ci need the virtual CPU mesh configured before "
            "JAX initializes; call main() with argv=None (the CLI "
            "re-execs itself).")
      os.execve(sys.executable,
                [sys.executable, "-m",
                 "tensor2robot_tpu.flywheel.flywheel_bench",
                 *sys.argv[1:]],
                cpu_mesh_env(n))
  if args.ci:
    results = measure_flywheel(
        warm_steps=16, fleet_steps=30, export_every=15,
        control_fleet_steps=60, seed=args.seed, enforce_bars=False)
  else:
    results = measure_flywheel(seed=args.seed)
  line = json.dumps(results)
  if args.out:
    with open(args.out, "w") as f:
      f.write(line + "\n")
  print(line)


if __name__ == "__main__":
  main()

"""The closed serve→collect→train→redeploy cycle (ISSUE 18 tentpole).

``FlywheelLoop.run()`` drives one continuous flywheel on the virtual
mesh:

1. **Warm start** — synthetic ``CollectorWorker`` traffic (provenance
   "synthetic") fills the ring and the learner trains to mid-descent,
   exactly the PR 2 host loop. Then the collectors stop, PERMANENTLY.
2. **Cutover** — the warm-started params deploy to the serving fleet
   (``set_variables`` with the warm step as the version: the fleet
   serves what the learner just trained).
3. **Fleet phase** — a ``FleetClient`` drives grasp episodes through
   ``RolloutController.submit`` like any other client; the
   ``EpisodeRecorder`` at the replica flush seam captures what the
   fleet served; the client closes each served action against the env
   dynamics oracle (``GraspRetryEnv`` — per-request outcomes, the
   QT-Opt robot stand-in) and re-ingests the episode through the
   spec-validated ``FlywheelIngest`` gate (provenance "served"). The
   learner keeps training — now ONLY on fleet-served traffic arriving
   through the same TransitionQueue → replay ring path — and exports
   every ``export_every`` steps through ``ExportWatcher`` →
   shadow → canary → promote, so a promoted checkpoint immediately
   changes the data it will later train on.

The stale-params control (``promotes=False``) severs step 3's export
path: the fleet serves the warm-start params forever while the learner
advances, and the staleness-ceiling HealthRule must breach — the
poisoning interlock's positive test.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from tensor2robot_tpu.flywheel.capture import (EpisodeRecorder,
                                               FlywheelIngest,
                                               IngestRejected,
                                               flywheel_rules)
from tensor2robot_tpu.obs import context as context_lib
from tensor2robot_tpu.obs import flight_recorder as flight_lib
from tensor2robot_tpu.obs import registry as registry_lib
from tensor2robot_tpu.obs.health import HealthMonitor
from tensor2robot_tpu.replay.ingest import ReplayFeeder, TransitionQueue
from tensor2robot_tpu.replay.ring_buffer import ShardedReplayBuffer
from tensor2robot_tpu.serving.slo import SLOClass


@dataclasses.dataclass
class FlywheelConfig:
  """Knobs for one flywheel run (defaults: chipless CI smoke scale)."""

  image_size: int = 16
  action_size: int = 4
  batch_size: int = 32
  capacity: int = 1024
  min_fill: int = 96
  num_buffer_shards: int = 2
  prioritized: bool = True
  gamma: float = 0.8
  learning_rate: float = 3e-3
  cem_num_samples: int = 16
  cem_num_elites: int = 4
  cem_iterations: int = 2
  max_attempts: int = 3
  grasp_radius: float = 0.4
  queue_capacity: int = 1024
  # Phase lengths (learner optimizer steps).
  warm_steps: int = 60
  fleet_steps: int = 120
  refresh_every: int = 15
  eval_batches: int = 4
  export_every: int = 30
  # Warm-start synthetic collection (OFF after cutover, by design).
  warm_envs: int = 4
  exploration_epsilon: float = 0.25
  scripted_fraction: float = 0.25
  # Serving fleet.
  num_fleet_devices: Optional[int] = None  # None = every visible device
  ladder_sizes: Tuple[int, ...] = (1, 2)
  deadline_ms: float = 500.0               # client SLO budget
  record_timeout_s: float = 10.0
  client_pace_s: float = 0.0
  # Rollout gate (deliberately fast cycles: the flywheel bench proves
  # the LOOP closes, not the gate's sharpness — PR 7/10 own that).
  mirror_fraction: float = 1.0
  canary_fraction: float = 0.5
  min_shadow_samples: int = 12
  min_canary_samples: int = 6
  # The rollout q bar scores the CANDIDATE's actions under the LIVE
  # serving critic (rollout.py) — a parity bar, right for same-params
  # tier candidates. Between SUCCESSIVE learner checkpoints it reads
  # Bellman contraction as regression: the warm-start critic
  # overestimates Q, so a better-trained candidate's argmax actions
  # legitimately score ~0.3-0.45 LOWER under the stale oracle
  # (observed q_delta_mean over the smoke protocol). 0.75 clears that
  # drift band while still rolling back a candidate whose actions the
  # serving oracle scores as catastrophic.
  max_q_regression: float = 0.75
  promote_timeout_s: float = 120.0
  # Ingest health interlock.
  staleness_ceiling: Optional[float] = None  # None → 2*export_every + 15
  coverage_floor: float = 4.0
  served_mix_floor: float = 0.05
  coverage_window: int = 32
  # False = the injected stale-params control: no exports, no promotes;
  # the staleness rule must breach.
  promotes: bool = True
  seed: int = 0
  workdir: Optional[str] = None  # export root + flightrec dumps

  def resolved_staleness_ceiling(self) -> float:
    if self.staleness_ceiling is not None:
      return float(self.staleness_ceiling)
    # Healthy bound: the serving version trails the learner by at most
    # one export interval (the learner gates on the rollout verdict),
    # and the metric takes the episode's OLDEST version — an episode
    # whose first request was served just before a promote and which
    # closes late in the next export interval carries ~2 intervals of
    # lag. Two intervals plus margin separates "promote path alive"
    # from "flywheel feeding on stale output".
    return float(2 * self.export_every + 15)


class FleetClient:
  """Episode driver + outcome closer: the fleet's user AND its sensor.

  One thread playing grasp episodes against the serving fleet: per
  attempt it mints a correlation id, submits the scene through the
  controller (exactly one logical request), waits for the
  EpisodeRecorder's capture of what the fleet actually served, executes
  THAT action against the env dynamics (``GraspRetryEnv`` is the
  outcome oracle — per-request seeds, static scene per episode), and on
  episode close hands the assembled episode to the ingest gate with its
  request ids and serving params versions. The capture is the truth: a
  request whose record never arrives (shed, or its mirror lost) aborts
  the episode — counted, never fabricated.
  """

  def __init__(self, submit_fn, recorder: EpisodeRecorder,
               ingest: FlywheelIngest, *, image_size: int,
               max_attempts: int, grasp_radius: float, seed: int,
               slo: Optional[SLOClass] = None,
               record_timeout_s: float = 10.0, pace_s: float = 0.0,
               flight_recorder=None):
    from tensor2robot_tpu.research.qtopt.synthetic_grasping import (
        GraspRetryEnv)
    self._submit = submit_fn
    self._recorder = recorder
    self._ingest = ingest
    self._env = GraspRetryEnv(image_size=image_size,
                              max_attempts=max_attempts,
                              radius=grasp_radius)
    self._max_attempts = max_attempts
    self._seed = seed
    self._next_scene = 0
    self._slo = slo
    self._record_timeout_s = record_timeout_s
    self._pace_s = pace_s
    self._flight = flight_recorder or flight_lib.get_recorder()
    self.requests_submitted = 0
    self.episodes_closed = 0
    self.episodes_aborted = 0
    self.successes = 0
    self.sheds = 0
    self.unclosed = 0
    self.rejected = 0
    self.errors: List[BaseException] = []
    self._stop = threading.Event()
    self._thread = threading.Thread(target=self._run,
                                    name="flywheel-client", daemon=True)

  def start(self) -> "FleetClient":
    self._thread.start()
    return self

  def request_stop(self) -> None:
    self._stop.set()

  def stop(self, timeout: float = 30.0) -> None:
    self.request_stop()
    self._thread.join(timeout)
    if self.errors:
      raise RuntimeError("fleet client died") from self.errors[0]

  def _scene_seed(self) -> int:
    # The CollectorWorker's scene-seed convention, offset so client
    # scenes never collide with warm-start scenes.
    seed = (self._seed + 17) * 1_000_003 + self._next_scene
    self._next_scene += 1
    return seed

  def _run(self) -> None:
    try:
      while not self._stop.is_set():
        self.play_episode()
        if self._pace_s:
          time.sleep(self._pace_s)
    except BaseException as e:  # noqa: BLE001 — surfaced via stop()
      self.errors.append(e)
      self._flight.trigger("collector_thread_exception",
                           error=f"{type(e).__name__}: {e}",
                           site="flywheel_client")

  def play_episode(self) -> bool:
    """One full episode; True when it closed and ingested."""
    scene_seed = self._scene_seed()
    self._env.reset(scene_seed)
    scene = np.asarray(self._env.image)
    actions, rewards, dones = [], [], []
    request_ids, params_versions = [], []
    for _ in range(self._max_attempts):
      request_id = context_lib.new_request_id()
      self.requests_submitted += 1
      try:
        future = self._submit(scene, slo=self._slo,
                              request_id=request_id)
        future.result(timeout=self._record_timeout_s)
      except Exception:
        # Shed / timed out / torn down: the fleet never answered, so
        # there is no served action to execute. Abort the episode.
        self.sheds += 1
        self.episodes_aborted += 1
        return False
      record = self._recorder.wait_for(request_id,
                                       timeout=self._record_timeout_s)
      if record is None:
        # Answered but never captured (e.g. its canary-phase live
        # mirror was shed before flushing): without the seam's record
        # the transition is untraceable — abort, never fabricate.
        self.unclosed += 1
        self.episodes_aborted += 1
        return False
      action = np.asarray(record.action, np.float32)
      reward, done, truncated = self._env.step(action)
      actions.append(action)
      rewards.append(float(reward))
      # Bootstrap through truncation: only SUCCESS terminates value
      # (the CollectorWorker convention).
      dones.append(float(done))
      request_ids.append(request_id)
      params_versions.append(record.params_version)
      if done or truncated:
        break
    episode = {
        "images": np.stack([scene] * (len(actions) + 1)),
        "actions": np.stack(actions),
        "rewards": np.asarray(rewards, np.float32),
        "dones": np.asarray(dones, np.float32),
    }
    try:
      self._ingest.submit_episode(
          episode, scene_seed=scene_seed, request_ids=request_ids,
          params_versions=params_versions, provenance="served")
    except IngestRejected:
      self.rejected += 1
      self.episodes_aborted += 1
      return False
    self.episodes_closed += 1
    self.successes += int(dones[-1] > 0)
    return True

  def snapshot(self) -> Dict[str, int]:
    return {
        "requests_submitted": self.requests_submitted,
        "episodes_closed": self.episodes_closed,
        "episodes_aborted": self.episodes_aborted,
        "successes": self.successes,
        "sheds": self.sheds,
        "unclosed": self.unclosed,
        "rejected": self.rejected,
    }


class FlywheelLoop:
  """One flywheel run end to end; ``run()`` returns the evidence dict."""

  def __init__(self, config: Optional[FlywheelConfig] = None):
    self.config = config or FlywheelConfig()
    self._step = 0
    self._train_exec = None
    self.compile_counts: Dict[str, int] = {}

  # -- learner plumbing -----------------------------------------------------

  def _host_variables(self, state):
    from tensor2robot_tpu.export import export_utils
    return export_utils.fetch_variables_to_host(
        state.variables(use_ema=True))

  def _eval_set(self):
    """Held-out scenes + analytic Q* (the loop.py eval oracle: grasping
    at the object always succeeds, so Q*(s,a) = 1 if success else
    gamma; distance to THIS fixed point witnesses learning where the
    self-consistent Bellman residual cannot)."""
    from tensor2robot_tpu.research.qtopt import synthetic_grasping as sg
    c = self.config
    n = c.batch_size * c.eval_batches
    images, targets = sg.sample_scenes(
        n, image_size=c.image_size, seed=c.seed + 990_001,
        num_distractors=0, occlusion=False)
    rng = np.random.default_rng(c.seed + 990_002)
    actions = rng.uniform(-1.0, 1.0,
                          (n, c.action_size)).astype(np.float32)
    near = rng.random(n) < 0.5
    noise = rng.normal(0.0, 0.12, (n, 2)).astype(np.float32)
    actions[near, :2] = np.clip(targets[near] + noise[near], -1.0, 1.0)
    success = sg.grasp_success(targets, actions,
                               c.grasp_radius).astype(np.float32)
    q_star = np.where(success > 0, 1.0, c.gamma).astype(np.float32)
    batches, stars = [], []
    for i in range(c.eval_batches):
      part = slice(i * c.batch_size, (i + 1) * c.batch_size)
      batches.append({
          "image": images[part],
          "action": actions[part],
          "reward": success[part],
          "done": success[part],
          "next_image": images[part],
      })
      stars.append(q_star[part])
    return batches, stars

  def _eval(self, updater, variables, batches, stars) -> Dict[str, float]:
    tds = [updater.td_errors(variables, batch, star)
           for batch, star in zip(batches, stars)]
    td = np.concatenate(tds)
    return {"eval_td_error": float(np.mean(td)),
            "eval_q_loss": float(np.mean(np.square(td)))}

  def _train_tick(self, trainer, state, updater, feeder, buffer, model):
    feeder.drain()
    batch, info = buffer.sample()
    targets, q_next = updater.compute_targets(batch)
    features = {"image": np.asarray(batch["image"]),
                "action": np.asarray(batch["action"])}
    labels = {model.target_key: targets}
    sharded = trainer.shard_batch((features, labels))
    if self._train_exec is None:
      # AOT once at the ring's fixed batch shape: later drift raises in
      # XLA's executable check instead of recompiling — the flywheel
      # inherits the loop's exactly-once ledger claim unchanged.
      self._train_exec = trainer.aot_train_step(state, *sharded)
      self.compile_counts["train_step"] = (
          self.compile_counts.get("train_step", 0) + 1)
    state, metrics = self._train_exec(state, *sharded)
    online = state.variables(use_ema=True)
    td = updater.td_errors(online, batch, targets)
    buffer.update_priorities(info.indices, td)
    return state, online, {
        "loss": float(metrics["loss"]),
        "td_mean": float(np.mean(td)),
        "q_next_mean": float(np.mean(q_next)),
    }

  # -- export → watcher hand-off --------------------------------------------

  @staticmethod
  def _export_step(export_root: str, step: int, host_variables) -> str:
    """Publishes a STEP-named export dir (tmp → atomic rename).

    Deliberately not export_utils.versioned_export_dir: its unix-time
    versions would race the watcher's monotonic ``_seen`` against the
    step numbers the staleness metric needs — here dir name == pushed
    version == learner step, one number everywhere.
    """
    from tensor2robot_tpu.export import export_utils, variables_io
    from tensor2robot_tpu.export.native_export_generator import (
        VARIABLES_NPZ)
    tmp = os.path.join(export_root, f".tmp-{step}")
    final = os.path.join(export_root, str(step))
    os.makedirs(tmp, exist_ok=True)
    variables_io.save_variables(os.path.join(tmp, VARIABLES_NPZ),
                                host_variables)
    return export_utils.publish(tmp, final)

  @staticmethod
  def _await_verdict(controller, since: int, timeout_s: float):
    """Blocks until the controller records a terminal rollout event
    (promote | auto_rollback) past timeline index ``since``; returns
    (event or None, new timeline length). The learner gates its next
    export interval on the verdict so "≥ 2 promote cycles completed
    MID-RUN" is a structural property of the run, not a race."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
      events = controller.timeline()
      for index in range(since, len(events)):
        if events[index]["event"] in ("promote", "auto_rollback"):
          return events[index], len(events)
      time.sleep(0.05)
    return None, since

  # -- the run --------------------------------------------------------------

  def run(self) -> Dict:
    import jax
    import optax

    from tensor2robot_tpu.export import export_utils
    from tensor2robot_tpu.parallel import mesh as mesh_lib
    from tensor2robot_tpu.replay.bellman import BellmanUpdater
    from tensor2robot_tpu.replay.loop import (CollectorWorker,
                                              _HotReloadPredictor,
                                              transition_spec)
    from tensor2robot_tpu.replay.smoke import TinyQCriticModel
    from tensor2robot_tpu.research.qtopt import synthetic_grasping as sg
    from tensor2robot_tpu.serving.bucketing import BucketLadder
    from tensor2robot_tpu.serving.policy import CEMFleetPolicy
    from tensor2robot_tpu.serving.router import FleetRouter
    from tensor2robot_tpu.serving.rollout import (ExportWatcher,
                                                  RolloutConfig,
                                                  RolloutController)
    from tensor2robot_tpu.serving.stats import ServingStats
    from tensor2robot_tpu.train.trainer import Trainer

    c = self.config
    workdir = c.workdir or tempfile.mkdtemp(prefix="flywheel-")
    export_root = os.path.join(workdir, "exports")
    os.makedirs(export_root, exist_ok=True)
    registry = registry_lib.MetricRegistry()
    flight = flight_lib.FlightRecorder(
        dump_dir=os.path.join(workdir, "flightrec"))

    devices = list(jax.devices())
    fleet_devices = (devices if c.num_fleet_devices is None
                     else devices[:c.num_fleet_devices])

    # Learner: single-device mesh (the serving fleet owns the mesh
    # story here; the learner side stays shape-stable — pjit paper).
    model = TinyQCriticModel(
        image_size=c.image_size, action_size=c.action_size,
        optimizer_fn=lambda: optax.adam(c.learning_rate))
    mesh = mesh_lib.create_mesh({"data": 1, "model": 1},
                                devices=devices[:1])
    trainer = Trainer(model, mesh=mesh, seed=c.seed)
    state = trainer.create_train_state(batch_size=c.batch_size)
    host_variables = self._host_variables(state)

    spec = transition_spec(c.image_size, c.action_size)
    buffer = ShardedReplayBuffer(
        spec, c.capacity, c.batch_size, num_shards=c.num_buffer_shards,
        seed=c.seed + 3, prioritized=c.prioritized)
    queue = TransitionQueue(c.queue_capacity)
    feeder = ReplayFeeder(queue, buffer, c.min_fill)
    updater = BellmanUpdater(
        model, host_variables, action_size=c.action_size, gamma=c.gamma,
        num_samples=c.cem_num_samples, num_elites=c.cem_num_elites,
        iterations=c.cem_iterations, seed=c.seed + 13)

    # Warm-start collection policy over ITS OWN hot-reload predictor
    # (the learner refreshes it; the serving fleet's predictor changes
    # only via promote — that separation IS the staleness story).
    collector_predictor = _HotReloadPredictor(model, host_variables)
    collector_policy = CEMFleetPolicy(
        collector_predictor, action_size=c.action_size,
        num_samples=c.cem_num_samples, num_elites=c.cem_num_elites,
        iterations=c.cem_iterations, seed=c.seed + 7,
        ladder=BucketLadder((c.warm_envs,)))

    # Serving fleet with the capture seam installed.
    serving_predictor = _HotReloadPredictor(model, host_variables)
    stats = ServingStats(registry)
    episode_recorder = EpisodeRecorder()
    router = FleetRouter(
        serving_predictor, devices=fleet_devices,
        action_size=c.action_size, num_samples=c.cem_num_samples,
        num_elites=c.cem_num_elites, iterations=c.cem_iterations,
        seed=c.seed + 21, ladder_sizes=c.ladder_sizes, stats=stats,
        flight_recorder=flight, episode_recorder=episode_recorder)
    router.warmup(lambda s: sg.sample_scenes(
        1, image_size=c.image_size, seed=int(s))[0][0])
    router.start()

    watcher = (ExportWatcher(export_root, flight_recorder=flight)
               if c.promotes else None)
    controller = RolloutController(
        router, serving_predictor,
        config=RolloutConfig(
            mirror_fraction=c.mirror_fraction,
            canary_fraction=c.canary_fraction,
            min_shadow_samples=c.min_shadow_samples,
            min_canary_samples=c.min_canary_samples,
            max_q_regression=c.max_q_regression, seed=c.seed + 31),
        watcher=watcher, poll_s=0.05, flight_recorder=flight)

    staleness_ceiling = c.resolved_staleness_ceiling()
    monitor = HealthMonitor(
        flywheel_rules(staleness_ceiling,
                       coverage_floor=c.coverage_floor,
                       served_mix_floor=c.served_mix_floor),
        registry=registry, recorder=flight, halt_on_breach=False)
    ingest = FlywheelIngest(
        queue, spec, lambda: self._step, monitor=monitor,
        registry=registry, flight_recorder=flight,
        coverage_window=c.coverage_window)

    # ---- phase 1: synthetic warm start ------------------------------------
    collector = CollectorWorker(
        collector_policy, queue, c.image_size, num_envs=c.warm_envs,
        max_attempts=c.max_attempts, seed=c.seed,
        grasp_radius=c.grasp_radius,
        exploration_epsilon=c.exploration_epsilon,
        scripted_fraction=c.scripted_fraction, flight_recorder=flight)
    collector.start()
    fill_deadline = time.monotonic() + 120.0
    while not feeder.ready():
      feeder.drain()
      if time.monotonic() > fill_deadline:
        collector.stop()
        raise RuntimeError(
            f"replay min-fill {c.min_fill} not reached in 120s "
            f"(size={buffer.size})")
      time.sleep(0.01)

    eval_batches, eval_stars = self._eval_set()
    online = state.variables(use_ema=True)
    initial_eval = self._eval(updater, online, eval_batches, eval_stars)
    eval_history = [dict(step=0, phase="init", **initial_eval)]

    train_metrics: Dict[str, float] = {}
    for step in range(1, c.warm_steps + 1):
      self._step = step
      state, online, train_metrics = self._train_tick(
          trainer, state, updater, feeder, buffer, model)
      if step % c.refresh_every == 0:
        host_variables = self._host_variables(state)
        collector_predictor.update(host_variables)
        updater.refresh(host_variables, step)
    collector.stop()  # synthetic collection OFF — permanently
    synthetic_episodes = collector.episodes

    cutover_eval = self._eval(updater, online, eval_batches, eval_stars)
    eval_history.append(dict(step=c.warm_steps, phase="cutover",
                             **cutover_eval))

    # ---- phase 2: cutover — deploy the warm model to the fleet ------------
    warm_variables = self._host_variables(state)
    ingest.mark_cutover()
    serving_predictor.set_variables(warm_variables,
                                    version=c.warm_steps)
    updater.refresh(warm_variables, c.warm_steps)
    controller.start()
    client_slo = SLOClass(name="flywheel", priority=1,
                          deadline_ms=c.deadline_ms)
    client = FleetClient(
        controller.submit, episode_recorder, ingest,
        image_size=c.image_size, max_attempts=c.max_attempts,
        grasp_radius=c.grasp_radius, seed=c.seed, slo=client_slo,
        record_timeout_s=c.record_timeout_s, pace_s=c.client_pace_s,
        flight_recorder=flight)
    client.start()

    # ---- phase 3: the closed loop -----------------------------------------
    exports: List[int] = []
    verdicts: List[dict] = []
    timeline_cursor = len(controller.timeline())
    client_error: Optional[str] = None
    try:
      end = c.warm_steps + c.fleet_steps
      for step in range(c.warm_steps + 1, end + 1):
        self._step = step
        state, online, train_metrics = self._train_tick(
            trainer, state, updater, feeder, buffer, model)
        if step % c.refresh_every == 0:
          updater.refresh(self._host_variables(state), step)
        if c.promotes and (step - c.warm_steps) % c.export_every == 0:
          host_variables = self._host_variables(state)
          export_dir = self._export_step(export_root, step,
                                         host_variables)
          watcher.notify(export_dir, step)
          exports.append(step)
          verdict, timeline_cursor = self._await_verdict(
              controller, timeline_cursor, c.promote_timeout_s)
          verdicts.append({
              "export_step": step,
              "event": None if verdict is None else verdict["event"],
          })
          mid_eval = self._eval(updater, online, eval_batches,
                                eval_stars)
          eval_history.append(dict(
              step=step,
              phase=("post_" + verdict["event"]) if verdict else
              "post_export_timeout", **mid_eval))
      # Grace: hold the fleet open until at least one more episode
      # ingests AT the terminal learner step, so the staleness metric
      # is observed against the final step count. This is what makes
      # the stale-params control's breach structural — the learner
      # outruns the client, and without a terminal observation the
      # breach would hinge on episode timing.
      grace_deadline = time.monotonic() + 30.0
      ingested_before = ingest.snapshot()["episodes_ingested"]
      while (ingest.snapshot()["episodes_ingested"] == ingested_before
             and time.monotonic() < grace_deadline):
        time.sleep(0.05)
    finally:
      client.request_stop()
      try:
        client.stop()
      except RuntimeError as e:
        client_error = str(e.__cause__ or e)
      controller.stop()
      router.stop()

    final_eval = self._eval(updater, online, eval_batches, eval_stars)
    eval_history.append(dict(step=c.warm_steps + c.fleet_steps,
                             phase="final", **final_eval))

    # ---- evidence ---------------------------------------------------------
    ledger = dict(self.compile_counts)
    ledger.update({
        f"bellman_{k}" if not k.startswith("bellman") else k: v
        for k, v in updater.compile_counts.items()})
    ledger.update({f"cem_collector_bucket_{k}": v
                   for k, v in sorted(
                       collector_policy.compile_counts.items())})
    fleet_ledger = router.compile_ledger()
    ledger_exactly_once = (
        all(v == 1 for v in ledger.values())
        and all(count == 1 for per_device in fleet_ledger.values()
                for count in per_device.values()))

    snapshot = stats.snapshot()
    ingest_snap = ingest.snapshot()
    client_snap = client.snapshot()
    reconcile = {
        "client_submits": client_snap["requests_submitted"],
        "serving_logical_requests": snapshot["logical_requests"],
        "captured_unique": episode_recorder.captured,
        "ingested_transitions": ingest_snap["transitions_ingested"],
        "ingested_unique_request_ids": ingest_snap["unique_request_ids"],
        # The satellite-1 claim: episode accounting reconciles against
        # serving stats with NO client-side bookkeeping required —
        # logical requests count client submits 1:1 through every
        # rollout phase, and every ingested transition carries a
        # distinct captured request id.
        "ok": bool(
            client_snap["requests_submitted"]
            == snapshot["logical_requests"]
            and ingest_snap["unique_request_ids"]
            == ingest_snap["transitions_ingested"]
            and episode_recorder.captured
            <= snapshot["logical_requests"]),
    }

    promotes_completed = sum(
        1 for v in verdicts if v["event"] == "promote")
    monitor_snap = monitor.snapshot()
    improvement = (cutover_eval["eval_td_error"]
                   - final_eval["eval_td_error"])

    return {
        "config": {
            "warm_steps": c.warm_steps, "fleet_steps": c.fleet_steps,
            "export_every": c.export_every,
            "staleness_ceiling": staleness_ceiling,
            "promotes_enabled": c.promotes,
            "fleet_devices": len(fleet_devices),
            "seed": c.seed,
        },
        "evals": {
            "initial_td": initial_eval["eval_td_error"],
            "cutover_td": cutover_eval["eval_td_error"],
            "final_td": final_eval["eval_td_error"],
            "fleet_phase_improvement": improvement,
            "history": eval_history,
        },
        "train": train_metrics,
        "promotes": {
            "exports": exports,
            "verdicts": verdicts,
            "completed": promotes_completed,
            "rollbacks": sum(1 for v in verdicts
                             if v["event"] == "auto_rollback"),
            "timeline": controller.timeline(),
        },
        "capture": episode_recorder.snapshot(),
        "ingest": ingest_snap,
        "client": dict(client_snap, error=client_error),
        "synthetic": {"episodes": synthetic_episodes},
        "provenance": buffer.provenance_counts(),
        "reconcile": reconcile,
        "health": {
            "ok": monitor_snap["breach_count"] == 0,
            "breach_count": monitor_snap["breach_count"],
            "breaches_per_rule": monitor_snap["breaches_per_rule"],
            "last_summary": monitor_snap["last_summary"],
        },
        "ledger": {
            "learner": ledger,
            "fleet": fleet_ledger,
            "exactly_once": bool(ledger_exactly_once),
        },
        "queue": queue.stats(),
        "serving": {
            "logical_requests": snapshot["logical_requests"],
            "requests": snapshot["requests"],
            "shed_total": snapshot["shed_total"],
        },
        "workdir": workdir,
    }

"""Hooks: pluggable train-loop observers (exports, logging, custom).

Reference parity: hooks/ (SURVEY.md §2, §3.4) — HookBuilder interface for
gin-injected SessionRunHooks; async SavedModel export triggered by
checkpoint saves, with fleet-dir copy + version GC.
"""

from tensor2robot_tpu.hooks.hook_builder import Hook, HookBuilder
from tensor2robot_tpu.hooks.async_export_hook import (
    AsyncExportHook,
    AsyncExportHookBuilder,
)

__all__ = [
    "Hook",
    "HookBuilder",
    "AsyncExportHook",
    "AsyncExportHookBuilder",
]

"""Async export hook: serve-fresh-models-while-training.

Reference parity: hooks/async_export_hook_builder.py (SURVEY.md §3.4) —
TPU training can't export inline, so a checkpoint-triggered listener
exports in a worker thread and GCs old versions, keeping the robot
fleet's poll directory fresh during long runs. Same design here: the
device never stalls on export — the hook snapshots (host fetch) the EMA
variables at a checkpoint boundary and hands them to a single worker
thread; if an export is still running the new request replaces any
queued one (exporting every checkpoint is pointless if exports are
slower than checkpoints).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import List, Optional

from tensor2robot_tpu.export import export_utils
from tensor2robot_tpu.hooks.hook_builder import Hook, HookBuilder

_log = logging.getLogger(__name__)


class AsyncExportHook(Hook):
  """Exports on checkpoint saves via a worker thread."""

  def __init__(self, export_generator, keep: int = 5,
               shutdown_timeout_s: float = 180.0,
               on_export=None):
    """Args:
      export_generator: the artifact writer (set_specification + export).
      keep: versions retained after GC.
      shutdown_timeout_s: end-of-training drain bound.
      on_export: optional callable ``(export_dir, step)`` invoked on the
        worker thread after each successful publish — the push half of
        the learner→server rollout plumbing: a co-resident
        ``serving.rollout.RolloutController`` wires its watcher's
        ``notify`` here and starts the shadow evaluation the moment a
        checkpoint lands, instead of on the next poll tick. Exceptions
        are logged and never fail the export (serving trouble must not
        stall training).
    """
    self._generator = export_generator
    self._keep = keep
    self._shutdown_timeout_s = shutdown_timeout_s
    self._on_export = on_export
    # maxsize=1 + replace-on-full: at most one pending export.
    self._pending: "queue.Queue" = queue.Queue(maxsize=1)
    self._worker: Optional[threading.Thread] = None
    self._stop = object()
    self._last_submitted_step: Optional[int] = None

  def begin(self, trainer, state, model_dir: str) -> None:
    # Runs on EVERY host: after_checkpoint's variable snapshot is a
    # cross-process collective for sharded params, so all hosts must
    # keep making it together; the artifact writes are chief-gated
    # inside export_utils.export_and_gc (None on non-primary).
    export_utils.resolve_export_root(self._generator, model_dir)
    self._generator.set_specification_from_model(trainer.model)
    self._worker = threading.Thread(
        target=self._run, name="t2r-async-export", daemon=True)
    self._worker.start()

  def _submit(self, item) -> None:
    """Put, replacing any not-yet-started export (mid-train use only)."""
    while True:
      try:
        self._pending.put_nowait(item)
        return
      except queue.Full:
        try:
          self._pending.get_nowait()
        except queue.Empty:
          pass

  def after_checkpoint(self, step: int, state) -> None:
    if self._worker is None:  # begin not called
      return
    variables = state.variables(use_ema=True)
    if self._skip_fetch(variables):
      return
    # Snapshot on the host: the donated device buffers are reused by the
    # next step, so the worker must not touch them.
    variables = export_utils.fetch_variables_to_host(variables)
    self._submit((variables, int(state.step)))
    self._last_submitted_step = int(state.step)

  @staticmethod
  def _skip_fetch(variables) -> bool:
    """Non-primary hosts snapshot only when the fetch is a collective
    they must participate in (cross-process-sharded params); with
    fully-replicated params the primary fetches alone — the others
    would device_get the whole tree per checkpoint just to have
    export_and_gc discard it."""
    from tensor2robot_tpu.parallel import distributed
    return (not distributed.is_primary()
            and not export_utils.fetch_is_collective(variables))

  def _run(self) -> None:
    while True:
      item = self._pending.get()
      if item is self._stop:
        return
      variables, step = item
      try:
        export_dir = export_utils.export_and_gc(
            self._generator, variables, keep=self._keep, global_step=step)
        if export_dir is not None:
          _log.info("Async export published %s", export_dir)
          if self._on_export is not None:
            try:
              self._on_export(export_dir, step)
            except Exception:
              _log.exception("on_export callback failed; training "
                             "continues.")
      except Exception:
        _log.exception("Async export failed; training continues.")

  def end(self, state) -> None:
    # Drain, exporting the final state unless the final checkpoint already
    # submitted this exact step. Ordered, deadline-bounded puts (not
    # _submit): the stop signal must never displace a queued final
    # export, and a hung worker must never block shutdown past the
    # deadline (the worker is a daemon thread: abandoning it cannot
    # block interpreter exit).
    if self._worker is None:
      # begin() starts the worker on EVERY host (the snapshot can be a
      # cross-process collective — see _skip_fetch); None here means
      # begin was never called.
      _log.warning("AsyncExportHook.end called without begin; no export "
                   "worker exists, nothing to export.")
      return
    deadline = time.monotonic() + self._shutdown_timeout_s
    submitted = True
    if self._last_submitted_step != int(state.step):
      variables = state.variables(use_ema=True)
      if not self._skip_fetch(variables):
        variables = export_utils.fetch_variables_to_host(variables)
        submitted = self._put_with_deadline((variables, int(state.step)),
                                            deadline)
    if submitted and self._put_with_deadline(self._stop, deadline):
      self._worker.join(timeout=max(0.0, deadline - time.monotonic()))
      if not self._worker.is_alive():
        return
    _log.error("Async export worker did not finish within %.0fs; "
               "abandoning it (final export may be missing).",
               self._shutdown_timeout_s)

  def _put_with_deadline(self, item, deadline: float) -> bool:
    try:
      self._pending.put(item, timeout=max(0.0, deadline - time.monotonic()))
      return True
    except queue.Full:
      return False


class AsyncExportHookBuilder(HookBuilder):
  """Builds AsyncExportHook (config-injectable; reference
  §AsyncExportHookBuilder)."""

  def __init__(self, export_generator, keep: int = 5,
               shutdown_timeout_s: float = 180.0, on_export=None):
    self._export_generator = export_generator
    self._keep = keep
    self._shutdown_timeout_s = shutdown_timeout_s
    self._on_export = on_export

  def create_hooks(self, trainer, model_dir: str) -> List[Hook]:
    return [AsyncExportHook(self._export_generator, keep=self._keep,
                            shutdown_timeout_s=self._shutdown_timeout_s,
                            on_export=self._on_export)]

"""Hook + HookBuilder protocol.

Reference parity: hooks/hook_builder.py §HookBuilder (SURVEY.md §2). The
Estimator SessionRunHook lifecycle maps onto the host loop's sync points:
begin → (after_step at each metric sync) → after_checkpoint (the
CheckpointSaverListener.after_save analogue) → end.
"""

from __future__ import annotations

import abc
from typing import List


class Hook:
  """Train-loop observer; all methods optional overrides, host-side."""

  def begin(self, trainer, state, model_dir: str) -> None:
    """Called once before the first step."""

  def after_step(self, state, metrics: dict) -> None:
    """Called at metric sync points (not every step) with host scalars."""

  def after_checkpoint(self, step: int, state) -> None:
    """Called after a checkpoint save is scheduled for `step`."""

  def end(self, state) -> None:
    """Called once after the last step (and final checkpoint)."""


class HookBuilder(abc.ABC):
  """Factory of hooks, injectable via config (reference §HookBuilder)."""

  @abc.abstractmethod
  def create_hooks(self, trainer, model_dir: str) -> List[Hook]:
    """Builds hooks for this run."""

"""Reusable network layers (reference layers/ zoo, SURVEY.md §2)."""

from tensor2robot_tpu.layers.vision_layers import (
    ImagesToFeatures,
    ImageFeaturesToPose,
    spatial_softmax,
)
from tensor2robot_tpu.layers.resnet import ResNet, FilmResNet
from tensor2robot_tpu.layers import mdn
from tensor2robot_tpu.layers import snail

__all__ = [
    "ImagesToFeatures",
    "ImageFeaturesToPose",
    "spatial_softmax",
    "ResNet",
    "FilmResNet",
    "mdn",
    "snail",
]

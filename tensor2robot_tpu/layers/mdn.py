"""Mixture density network heads (multimodal action distributions).

Reference parity: layers/mdn.py §predict_mixture_params,
§get_mixture_distribution, §gaussian_mixture_approximate_mode
(SURVEY.md §2): diagonal-Gaussian mixtures over action vectors, used by
VRGripper's behavior-cloning heads. Implemented directly on jnp (no
distribution-library dependency): log-prob via logsumexp, which XLA fuses
into the surrounding loss.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class MixtureParams(NamedTuple):
  """Diagonal GMM parameters: shapes (..., K), (..., K, D), (..., K, D)."""
  log_alphas: jnp.ndarray
  mus: jnp.ndarray
  log_sigmas: jnp.ndarray


def predict_mixture_params(
    inputs: jnp.ndarray,
    num_components: int,
    sample_size: int,
    module: Any = None,
    name: str = "mdn",
) -> MixtureParams:
  """Projects features to GMM parameters (reference
  §predict_mixture_params).

  Args:
    inputs: (..., F) features.
    num_components: K mixture components.
    sample_size: D, dimensionality of the predicted variable.
    module: optional enclosing flax module scope (unused; Dense below is
      created in the caller's scope via nn.Dense when called inside
      @nn.compact).
  """
  del module
  k, d = num_components, sample_size
  raw = nn.Dense(k * (2 * d + 1), dtype=jnp.float32, name=name)(
      inputs.astype(jnp.float32))
  alphas = raw[..., :k]
  rest = raw[..., k:].reshape(raw.shape[:-1] + (k, 2 * d))
  mus = rest[..., :d]
  # Softplus-shifted sigma, clipped away from zero for stability.
  log_sigmas = jnp.log(nn.softplus(rest[..., d:]) + 1e-5)
  return MixtureParams(
      log_alphas=nn.log_softmax(alphas, axis=-1),
      mus=mus,
      log_sigmas=log_sigmas)


def log_prob(params: MixtureParams, x: jnp.ndarray) -> jnp.ndarray:
  """GMM log-likelihood of x: (..., D) → (...)."""
  x = x[..., None, :]  # broadcast over components
  inv_var = jnp.exp(-2.0 * params.log_sigmas)
  component_ll = -0.5 * jnp.sum(
      ((x - params.mus) ** 2) * inv_var
      + 2.0 * params.log_sigmas
      + jnp.log(2.0 * jnp.pi),
      axis=-1)
  return jax.scipy.special.logsumexp(
      params.log_alphas + component_ll, axis=-1)


def negative_log_likelihood(params: MixtureParams,
                            x: jnp.ndarray) -> jnp.ndarray:
  """Mean NLL — the reference's MDN training loss."""
  return -jnp.mean(log_prob(params, x))


def gaussian_mixture_approximate_mode(params: MixtureParams) -> jnp.ndarray:
  """Mean of the highest-weight component (reference
  §gaussian_mixture_approximate_mode) — the deterministic action at
  serving time."""
  best = jnp.argmax(params.log_alphas, axis=-1)
  return jnp.take_along_axis(
      params.mus, best[..., None, None], axis=-2).squeeze(-2)


def sample(params: MixtureParams, rng: jax.Array) -> jnp.ndarray:
  """Draws one sample per batch element."""
  rng_comp, rng_normal = jax.random.split(rng)
  component = jax.random.categorical(rng_comp, params.log_alphas, axis=-1)
  mu = jnp.take_along_axis(
      params.mus, component[..., None, None], axis=-2).squeeze(-2)
  sigma = jnp.exp(jnp.take_along_axis(
      params.log_sigmas, component[..., None, None], axis=-2)).squeeze(-2)
  return mu + sigma * jax.random.normal(rng_normal, mu.shape)

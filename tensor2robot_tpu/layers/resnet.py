"""ResNet v1 + FiLM-conditioned variant.

Reference parity: layers/resnet.py §resnet_model and
layers/film_resnet_model.py (SURVEY.md §2): ResNet feature towers
(grasp2vec uses ResNet-50) and the FiLM variant where a task/context
embedding modulates each residual block (VRGripper). TPU-first: NHWC,
bfloat16 activations with float32 batch-norm statistics, static shapes.

FiLM (feature-wise linear modulation): per-block (gamma, beta) projected
from a conditioning embedding scale/shift the post-BN activations —
`film_gamma * x + film_beta` — so one tower serves many tasks.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from tensor2robot_tpu.layers.vision_layers import make_norm, normalize_image
from tensor2robot_tpu.ops.strided_conv import FoldedStridedConv3x3

# depth -> (block sizes, bottleneck?)
_CONFIGS = {
    18: ((2, 2, 2, 2), False),
    34: ((3, 4, 6, 3), False),
    50: ((3, 4, 6, 3), True),
    101: ((3, 4, 23, 3), True),
}


class _Film(nn.Module):
  """Projects a context embedding to (gamma, beta) for `width` channels."""

  width: int
  dtype: Any

  @nn.compact
  def __call__(self, x: jnp.ndarray, context: jnp.ndarray) -> jnp.ndarray:
    gamma_beta = nn.Dense(2 * self.width, dtype=self.dtype,
                          name="film_proj")(context.astype(self.dtype))
    gamma, beta = jnp.split(gamma_beta[:, None, None, :], 2, axis=-1)
    # Residual formulation (1 + gamma): identity at init.
    return x * (1.0 + gamma) + beta


class _Block(nn.Module):
  """Basic (2-conv) or bottleneck (3-conv) residual block, optional FiLM."""

  width: int
  stride: int
  bottleneck: bool
  use_film: bool
  dtype: Any
  norm_kind: str = "batch"
  # "parity" = nn.Conv strided lowerings; "fast" = the 3×3 stride-2
  # convs go through ops/strided_conv.FoldedStridedConv3x3 — same
  # function, same param layout (checkpoints interchange), folded
  # backward shapes. Stride-1 and 1×1 convs are unaffected.
  impl: str = "parity"

  def _conv3x3_strided(self, features: int, name: str):
    if self.impl == "fast" and self.stride == 2:
      return FoldedStridedConv3x3(features, use_bias=False,
                                  dtype=self.dtype, name=name)
    return nn.Conv(features, (3, 3), strides=(self.stride,) * 2,
                   use_bias=False, dtype=self.dtype, name=name)

  @nn.compact
  def __call__(self, x, context, train: bool):
    norm = make_norm(self.norm_kind, train, self.dtype)
    out_width = self.width * (4 if self.bottleneck else 1)
    residual = x
    if residual.shape[-1] != out_width or self.stride != 1:
      residual = nn.Conv(out_width, (1, 1), strides=(self.stride,) * 2,
                         use_bias=False, dtype=self.dtype,
                         name="proj_conv")(x)
      residual = norm("proj_bn")(residual)

    if self.bottleneck:
      y = nn.Conv(self.width, (1, 1), use_bias=False, dtype=self.dtype,
                  name="conv1")(x)
      y = nn.relu(norm("bn1")(y))
      y = self._conv3x3_strided(self.width, "conv2")(y)
      y = nn.relu(norm("bn2")(y))
      y = nn.Conv(out_width, (1, 1), use_bias=False, dtype=self.dtype,
                  name="conv3")(y)
      y = norm("bn3")(y)
    else:
      y = self._conv3x3_strided(self.width, "conv1")(x)
      y = nn.relu(norm("bn1")(y))
      y = nn.Conv(out_width, (3, 3), use_bias=False, dtype=self.dtype,
                  name="conv2")(y)
      y = norm("bn2")(y)

    if self.use_film:
      y = _Film(out_width, self.dtype, name="film")(y, context)
    return nn.relu(y + residual)


class ResNet(nn.Module):
  """ResNet v1 feature tower; num_classes=0 → pooled features.

  Reference §resnet_model. `film=True` turns every block into a
  FiLM-conditioned block (call with `context`).
  """

  depth: int = 50
  width: int = 64
  num_classes: int = 0
  film: bool = False
  return_spatial: bool = False  # also return the pre-pool feature map
  remat: bool = False  # rematerialize each block on the backward pass
  norm: str = "batch"  # 'batch' (reference) or 'group' (vision_layers.make_norm)
  impl: str = "parity"  # 'fast' folds the stride-2 3×3 convs (see _Block)
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, images, context: Optional[jnp.ndarray] = None,
               train: bool = False):
    if self.depth not in _CONFIGS:
      raise ValueError(f"Unsupported depth {self.depth}; "
                       f"have {sorted(_CONFIGS)}")
    if self.film and context is None:
      raise ValueError("FiLM ResNet requires a context embedding.")
    block_sizes, bottleneck = _CONFIGS[self.depth]

    x = normalize_image(images, self.dtype)  # uint8 wire → [0,1] on-chip
    x = nn.Conv(self.width, (7, 7), strides=(2, 2), use_bias=False,
                dtype=self.dtype, name="stem_conv")(x)
    x = make_norm(self.norm, train, self.dtype)("stem_bn")(x)
    x = nn.relu(x)
    x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

    # remat=True drops each block's activations after the forward pass and
    # recomputes them during backprop (jax.checkpoint): activation memory
    # goes from O(depth) to O(1) blocks — the HBM-for-FLOPs trade that
    # lets deep towers train at large batch/resolution on one chip.
    # (self, x, context, train) → train is static arg index 3.
    block_cls = (nn.remat(_Block, static_argnums=(3,)) if self.remat
                 else _Block)
    for stage, num_blocks in enumerate(block_sizes):
      for block in range(num_blocks):
        x = block_cls(
            width=self.width * (2 ** stage),
            stride=2 if (block == 0 and stage > 0) else 1,
            bottleneck=bottleneck,
            use_film=self.film,
            dtype=self.dtype,
            norm_kind=self.norm,
            impl=self.impl,
            name=f"stage{stage}_block{block}")(x, context, train)

    features = jnp.mean(x, axis=(1, 2))  # global average pool
    if self.num_classes:
      features = nn.Dense(self.num_classes, dtype=jnp.float32,
                          name="classifier")(features)
    if self.return_spatial:
      return features, x
    return features


def FilmResNet(depth: int = 18, **kwargs) -> ResNet:
  """The reference's film_resnet_model: ResNet with FiLM conditioning."""
  return ResNet(depth=depth, film=True, **kwargs)

"""SNAIL building blocks: temporal convs + causal attention.

Reference parity: layers/snail.py §CausalConv, §TCBlock, §AttentionBlock
(SURVEY.md §2) — Mishra et al.'s Simple Neural AttentIve meta-Learner
blocks used for meta-learning over episode sequences. Sequences here are
short robot episodes (SURVEY.md §5.7), so attention is materialized
directly; long-context variants belong to the parallel/ ring-attention
path, not here.

TPU notes: causal conv is a static pad + valid conv (no dynamic shapes);
everything operates on (B, T, D) with T static under jit.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


class CausalConv(nn.Module):
  """1D dilated causal convolution over (B, T, D)."""

  features: int
  kernel_size: int = 2
  dilation: int = 1
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
    pad = self.dilation * (self.kernel_size - 1)
    x = jnp.pad(x.astype(self.dtype), ((0, 0), (pad, 0), (0, 0)))
    return nn.Conv(
        self.features, (self.kernel_size,),
        kernel_dilation=(self.dilation,),
        padding="VALID", dtype=self.dtype)(x)


class DenseBlock(nn.Module):
  """Gated causal conv whose output is concatenated to its input
  (WaveNet-style gating: tanh ⊙ sigmoid)."""

  filters: int
  dilation: int
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
    xf = CausalConv(self.filters, dilation=self.dilation,
                    dtype=self.dtype, name="filter")(x)
    xg = CausalConv(self.filters, dilation=self.dilation,
                    dtype=self.dtype, name="gate")(x)
    activations = jnp.tanh(xf) * nn.sigmoid(xg)
    return jnp.concatenate([x.astype(self.dtype), activations], axis=-1)


class TCBlock(nn.Module):
  """Stack of DenseBlocks with dilations 1, 2, 4, … covering seq_len."""

  seq_len: int
  filters: int
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
    if x.shape[1] > self.seq_len:
      raise ValueError(
          f"TCBlock(seq_len={self.seq_len}) got length-{x.shape[1]} "
          "input; the dilation schedule would not cover it.")
    for i in range(int(math.ceil(math.log2(max(self.seq_len, 2))))):
      x = DenseBlock(self.filters, dilation=2 ** i,
                     dtype=self.dtype, name=f"dense{i}")(x)
    return x


class AttentionBlock(nn.Module):
  """Single-head causal attention; output concatenated to input.

  `seq_mesh` switches the attention core to sequence-parallel ring
  attention over that mesh's `seq_axis` — episodes longer than one
  device's memory shard across the ring (parallel/ring_attention.py);
  the dense core stays the default for the short episodes robot tasks
  actually have (SURVEY.md §5.7).

  `use_flash` switches the in-device core to the Pallas blockwise
  kernel (ops/flash_attention.py): O(T) HBM traffic instead of the
  materialized (B, T, T) score tensor. Off by default — at reference
  episode lengths (T ≲ a few hundred) the dense core is faster to
  compile and within noise at runtime; flip it on for long in-device
  sequences. Requires key_size == value_size (one head dim) and is
  first-order only (custom_vjp) — keep it off under MAML inner loops.
  """

  key_size: int
  value_size: int
  dtype: Any = jnp.bfloat16
  seq_mesh: Any = None
  seq_axis: str = "seq"
  # On dp×sp meshes, name the batch mesh axis so each data row computes
  # only its batch shard (unset, the ring path would all-gather the
  # batch and redo identical work per row).
  batch_axis: Any = None
  use_flash: bool = False
  # Passed through to ops.flash_attention: "auto" (Pallas on TPU, XLA
  # reference elsewhere), "pallas" (always the kernel — interpreted
  # off-TPU; what CPU tests use to actually exercise it), or "xla".
  flash_implementation: str = "auto"

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
    t = x.shape[1]
    keys = nn.Dense(self.key_size, dtype=self.dtype, name="key")(
        x.astype(self.dtype))
    queries = nn.Dense(self.key_size, dtype=self.dtype, name="query")(
        x.astype(self.dtype))
    values = nn.Dense(self.value_size, dtype=self.dtype, name="value")(
        x.astype(self.dtype))
    if self.use_flash:
      if self.seq_mesh is not None:
        raise ValueError(
            "use_flash is the in-device core; for sequence-parallel "
            "attention seq_mesh alone selects ring_attention.")
      if self.key_size != self.value_size:
        raise ValueError(
            "use_flash requires key_size == value_size (one head dim); "
            f"got {self.key_size} vs {self.value_size}.")
      from tensor2robot_tpu.ops import flash_attention
      read = flash_attention(
          queries[:, :, None, :], keys[:, :, None, :],
          values[:, :, None, :], causal=True,
          implementation=self.flash_implementation)[:, :, 0, :]
      return jnp.concatenate([x.astype(self.dtype), read], axis=-1)
    if self.seq_mesh is not None:
      from tensor2robot_tpu.parallel.ring_attention import ring_attention
      read = ring_attention(
          queries[:, :, None, :], keys[:, :, None, :],
          values[:, :, None, :],
          mesh=self.seq_mesh, axis=self.seq_axis, causal=True,
          batch_axis=self.batch_axis)[:, :, 0, :]
      return jnp.concatenate([x.astype(self.dtype), read], axis=-1)
    # float32 logits/softmax: attention normalization is precision-
    # sensitive even at short T.
    logits = jnp.einsum("btk,bsk->bts", queries, keys).astype(jnp.float32)
    logits = logits / np.sqrt(self.key_size)
    mask = jnp.tril(jnp.ones((t, t), bool))
    logits = jnp.where(mask[None], logits, -1e30)
    weights = nn.softmax(logits, axis=-1).astype(self.dtype)
    read = jnp.einsum("bts,bsv->btv", weights, values)
    return jnp.concatenate([x.astype(self.dtype), read], axis=-1)

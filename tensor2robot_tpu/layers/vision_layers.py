"""Vision layers: conv towers + spatial softmax for robot cameras.

Reference parity: layers/vision_layers.py §BuildImagesToFeaturesModel,
§BuildImageFeaturesToPoseModel, §spatial_softmax (SURVEY.md §2 layers
row). TPU notes: NHWC layout (XLA:TPU native), bfloat16 activations, all
convs stride/kernel static so XLA tiles them onto the MXU.
"""

from __future__ import annotations

import math
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


class GroupNormAuto(nn.Module):
  """GroupNorm with num_groups = gcd(32, channels): divides every
  channel count while defaulting to the standard 32 groups for the
  usual 64·2^k widths."""

  dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x):
    return nn.GroupNorm(num_groups=math.gcd(32, x.shape[-1]),
                        dtype=self.dtype)(x)


def make_norm(kind: str, train: bool, dtype: Any):
  """Returns name -> norm layer for `kind` ∈ {'batch', 'group', 'none'}.

  'batch' is the reference's choice. 'group' (GroupNorm, Wu & He 2018)
  is batch-independent: no running statistics, no train/eval asymmetry,
  and no per-core-batch stats problem under data parallelism. Required
  for two situations measured in this repo: difference-of-embeddings
  metric learning (grasp2vec: train-mode BN's within-batch coupling
  does not survive into eval) and MAML-wrapped bases (the inner loop
  never collects running statistics, so eval-mode BN normalizes with
  init stats — see meta_learning.maml_model). 'none' disables
  normalization entirely.
  """
  if kind == "batch":
    return lambda name: nn.BatchNorm(
        use_running_average=not train, dtype=dtype, name=name)
  if kind == "group":
    return lambda name: GroupNormAuto(dtype=dtype, name=name)
  if kind == "none":
    return lambda name: (lambda x: x)
  raise ValueError(
      f"Unknown norm kind {kind!r}; have 'batch', 'group', 'none'.")


def normalize_image(image: jnp.ndarray, dtype: Any) -> jnp.ndarray:
  """Camera image → model-ready [0, 1] activations in `dtype`.

  Accepts the two wire formats the image pipeline produces: already-
  scaled float (host converted, the default) or raw uint8 (the
  bandwidth-saving path — uint8 crosses host→device at 1/4 the float32
  bytes and this cast+rescale fuses into the first conv under XLA).
  """
  if jnp.issubdtype(image.dtype, jnp.integer):
    return image.astype(dtype) * (1.0 / 255.0)
  return image.astype(dtype)


def spatial_softmax(features: jnp.ndarray,
                    temperature: float = 1.0) -> jnp.ndarray:
  """Expected (x, y) image-coordinates per channel ("feature points").

  Delegates to the fused Pallas kernel (ops/spatial_softmax.py) when the
  shape fits VMEM, falling back to its XLA reference otherwise — same
  contract either way.

  Args:
    features: (B, H, W, C) activations.
    temperature: softmax temperature.

  Returns:
    (B, 2*C): per-channel expected coordinates in [-1, 1] (x then y),
    the keypoint pooling the reference used between conv tower and pose
    head.
  """
  from tensor2robot_tpu.ops.spatial_softmax import (
      spatial_softmax as fused_spatial_softmax,
  )
  return fused_spatial_softmax(features, temperature)


class ImagesToFeatures(nn.Module):
  """Conv tower: camera image → spatial feature map.

  Reference §BuildImagesToFeaturesModel: a VGG-ish stack of 3x3 convs
  with occasional stride-2 downsamples, batch norm, relu.
  """

  filters: Sequence[int] = (32, 64, 64, 128)
  strides: Sequence[int] = (2, 2, 2, 1)
  norm: str = "batch"  # 'batch', 'group', or 'none' (see make_norm)
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, images: jnp.ndarray, train: bool = False):
    if len(self.filters) != len(self.strides):
      raise ValueError(
          f"filters ({len(self.filters)}) and strides "
          f"({len(self.strides)}) must have equal length.")
    x = normalize_image(images, self.dtype)  # uint8 wire → [0,1] on-chip
    norm = make_norm(self.norm, train, self.dtype)
    for i, (width, stride) in enumerate(zip(self.filters, self.strides)):
      x = nn.Conv(width, (3, 3), strides=(stride, stride),
                  dtype=self.dtype, name=f"conv{i}")(x)
      x = norm(f"bn{i}")(x)
      x = nn.relu(x)
    return x


class ImageFeaturesToPose(nn.Module):
  """Spatial-softmax keypoints → MLP → pose vector.

  Reference §BuildImageFeaturesToPoseModel.
  """

  pose_dim: int = 2
  hidden_sizes: Sequence[int] = (64, 64)
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, feature_map: jnp.ndarray, train: bool = False):
    x = spatial_softmax(feature_map)
    for i, width in enumerate(self.hidden_sizes):
      x = nn.Dense(width, dtype=self.dtype, name=f"fc{i}")(x)
      x = nn.relu(x)
    # Head in float32: small, and keeps regression targets full-precision.
    return nn.Dense(self.pose_dim, dtype=jnp.float32, name="pose")(x)

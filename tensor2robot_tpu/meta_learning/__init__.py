"""Meta-learning: MAML as a model transformer (SURVEY.md §2, §3.5)."""

from tensor2robot_tpu.meta_learning.maml_model import MAMLModel
from tensor2robot_tpu.meta_learning.meta_data import (
    meta_batch_from_arrays,
    multi_batch_apply,
)

__all__ = ["MAMLModel", "meta_batch_from_arrays", "multi_batch_apply"]

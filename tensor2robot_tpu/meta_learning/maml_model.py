"""MAMLModel — model-agnostic meta-learning as a model transformer.

Reference parity: meta_learning/maml_model.py §MAMLModel +
meta_learning/maml_inner_loop.py §MAMLInnerLoopGradientDescent
(SURVEY.md §2, §3.5). The reference unrolled K functional gradient steps
in-graph with tf.gradients and manual weight substitution; in JAX the
same contraption is `jax.grad` over a functional inner loop, vmapped
over the task batch — second-order gradients come for free from the
outer differentiation (SURVEY.md §3.5 rebuild note).

Input layout (flat TensorSpecStruct keys, batch dim = tasks):
    condition/features/*  (B, N_c, ...)   support inputs
    condition/labels/*    (B, N_c, ...)   support targets
    inference/features/*  (B, N_q, ...)   query inputs
    inference/labels/*    (B, N_q, ...)   query targets
built by meta_data.meta_batch_from_arrays (reference §MetaExample).

Notes:
  - Batch-norm statistics are NOT adapted in the inner loop (running
    state is read-only during adaptation, updates discarded) — matching
    the reference, whose inner loop only substituted weights.
    CONSEQUENCE (measured): they are never collected during
    meta-training either, so a BatchNorm base model evaluates/serves
    with its INIT running statistics — meta-training can look perfect
    (outer loss ~3e-4 on the two-object meta-reaching task) while
    eval-mode predictions collapse to the unadapted baseline. Wrap
    bases built with batch-independent norms instead (e.g.
    `norm='group'` on the bundled models; layers.vision_layers
    §make_norm) — the bundled maml factories default to that.
  - PREDICT performs the same adapt-then-forward: meta-serving requires
    condition data in the request, as in the reference's meta predictors.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensor2robot_tpu import modes
from tensor2robot_tpu.config import configurable
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel, Metrics
from tensor2robot_tpu.specs import tensorspec_utils as ts


def _subtree(struct, prefix: str) -> ts.TensorSpecStruct:
  flat = ts.flatten_spec_structure(struct)
  out = ts.TensorSpecStruct()
  for key, value in flat.items():
    if key.startswith(prefix + "/"):
      out[key[len(prefix) + 1:]] = value
  return out


@configurable
class MAMLModel(AbstractT2RModel):
  """Wraps any AbstractT2RModel with a MAML inner/outer loop."""

  def __init__(
      self,
      base_model: AbstractT2RModel,
      num_inner_steps: int = 1,
      inner_lr: float = 0.01,
      learn_inner_lr: bool = False,
      first_order: bool = False,
      num_condition_samples: int = 4,
      num_inference_samples: int = 4,
      **kwargs,
  ):
    """Args (reference §MAMLModel / §MAMLInnerLoopGradientDescent):
      base_model: the task model being meta-learned.
      num_inner_steps: K unrolled adaptation steps.
      inner_lr: initial (or fixed) inner-loop step size.
      learn_inner_lr: meta-learn one step size per parameter leaf
        (the reference's learned per-layer inner LRs).
      first_order: stop gradients through the inner-loop gradients
        (FOMAML) — cheaper, usually nearly as good.
      num_condition_samples / num_inference_samples: per-task split
        sizes declared in the feature specs.
    """
    kwargs.setdefault("compute_dtype", base_model.compute_dtype)
    super().__init__(**kwargs)
    self.base_model = base_model
    self.num_inner_steps = num_inner_steps
    self.inner_lr = inner_lr
    self.learn_inner_lr = learn_inner_lr
    self.first_order = first_order
    self.num_condition_samples = num_condition_samples
    self.num_inference_samples = num_inference_samples

  # --- specs ---------------------------------------------------------------

  def get_feature_specification(self, mode: str) -> ts.TensorSpecStruct:
    base_f = ts.flatten_spec_structure(
        self.base_model.preprocessor.get_out_feature_specification(mode))
    base_l = ts.flatten_spec_structure(
        self.base_model.preprocessor.get_out_label_specification(mode))
    out = ts.TensorSpecStruct()
    for name, count in (("condition", self.num_condition_samples),
                        ("inference", self.num_inference_samples)):
      for key, spec in base_f.items():
        out[f"{name}/features/{key}"] = ts.ExtendedTensorSpec.from_spec(
            spec, shape=(count,) + spec.shape)
      for key, spec in base_l.items():
        out[f"{name}/labels/{key}"] = ts.ExtendedTensorSpec.from_spec(
            spec, shape=(count,) + spec.shape)
    return out

  def get_label_specification(self, mode: str) -> ts.TensorSpecStruct:
    del mode
    return ts.TensorSpecStruct()  # query labels travel inside features

  # --- variables -----------------------------------------------------------

  def build_module(self) -> nn.Module:
    return self.base_model.module

  def init_variables(self, rng: jax.Array, batch_size: int = 1,
                     mode: str = modes.TRAIN) -> Dict[str, Any]:
    del batch_size
    variables = dict(self.base_model.init_variables(
        rng, batch_size=self.num_condition_samples, mode=mode))
    if self.learn_inner_lr:
      base_params = variables.pop("params")
      inner_lrs = jax.tree_util.tree_map(
          lambda _: jnp.asarray(self.inner_lr, jnp.float32), base_params)
      variables["params"] = {"base": base_params, "inner_lrs": inner_lrs}
    return variables

  def _split_params(self, params):
    if self.learn_inner_lr:
      return params["base"], params["inner_lrs"]
    return params, None

  # --- the MAML computation ------------------------------------------------

  def inference_network_fn(
      self,
      variables,
      features: ts.TensorSpecStruct,
      mode: str,
      rngs: Optional[Dict[str, jax.Array]] = None,
  ) -> Tuple[Any, Dict[str, Any]]:
    base = self.base_model
    params = variables["params"]
    model_state = {k: v for k, v in variables.items() if k != "params"}
    base_params, inner_lrs = self._split_params(params)

    cond_f = _subtree(features, "condition/features")
    cond_l = _subtree(features, "condition/labels")
    query_f = _subtree(features, "inference/features")

    dropout_rng = (rngs or {}).get("dropout")

    mutable = (list(base.mutable_collections())
               if mode == modes.TRAIN else [])

    def apply_base(p, f, step_rng):
      variables_b = {"params": p, **model_state}
      base_rngs = {"dropout": step_rng} if step_rng is not None else None
      if mutable:
        # Batch-norm etc. may write during the train-mode forward, but
        # the inner loop never adapts state: updates are discarded.
        outputs, _ = base.module.apply(
            variables_b, f, mode, rngs=base_rngs, mutable=mutable)
        return outputs
      return base.module.apply(variables_b, f, mode, rngs=base_rngs)

    def support_loss(p, f, l, step_rng):
      outputs = apply_base(p, f, step_rng)
      loss, _ = base.loss_fn(outputs, f, l)
      return loss

    lr_tree = (inner_lrs if inner_lrs is not None else
               jax.tree_util.tree_map(lambda _: self.inner_lr, base_params))

    def single_task(cf, cl, qf, task_rng):
      p = base_params
      final_support_loss = jnp.float32(0)
      for k in range(self.num_inner_steps):  # unrolled, like the reference
        step_rng = (jax.random.fold_in(task_rng, k)
                    if task_rng is not None else None)
        loss_k, grads = jax.value_and_grad(support_loss)(p, cf, cl,
                                                         step_rng)
        if self.first_order:
          grads = jax.lax.stop_gradient(grads)
        p = jax.tree_util.tree_map(
            lambda pp, g, lr: pp - lr * g, p, grads, lr_tree)
        final_support_loss = loss_k
      query_rng = (jax.random.fold_in(task_rng, self.num_inner_steps)
                   if task_rng is not None else None)
      query_outputs = apply_base(p, qf, query_rng)
      return query_outputs, final_support_loss

    num_tasks = jax.tree_util.tree_leaves(cond_f)[0].shape[0]
    task_rngs = (jax.random.split(dropout_rng, num_tasks)
                 if dropout_rng is not None else None)
    if task_rngs is not None:
      query_outputs, support_losses = jax.vmap(single_task)(
          cond_f, cond_l, query_f, task_rngs)
    else:
      query_outputs, support_losses = jax.vmap(
          lambda cf, cl, qf: single_task(cf, cl, qf, None))(
              cond_f, cond_l, query_f)
    outputs = ts.TensorSpecStruct(query_outputs)
    outputs["condition_loss"] = support_losses
    # Pass model_state through unchanged (never adapted, never dropped —
    # returning {} here would wipe batch_stats out of the TrainState).
    return outputs, model_state

  def mutable_collections(self) -> Tuple[str, ...]:
    return ()  # inner loop is stateless; BN state is read-only here

  def loss_fn(self, outputs, features, labels) -> Tuple[jnp.ndarray, Metrics]:
    del labels
    query_labels = _subtree(features, "inference/labels")
    base_outputs = ts.TensorSpecStruct(
        (k, v) for k, v in outputs.items() if k != "condition_loss")
    query_features = _subtree(features, "inference/features")
    loss, metrics = self.base_model.loss_fn(
        base_outputs, query_features, query_labels)
    metrics = dict(metrics)
    metrics["outer_loss"] = loss
    metrics["inner_loss_final"] = jnp.mean(outputs["condition_loss"])
    return loss, metrics

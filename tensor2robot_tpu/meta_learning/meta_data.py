"""Meta-batched data utilities.

Reference parity: meta_learning/meta_tfdata.py §multi_batch_apply and
meta_learning/meta_example.py §MetaExample (SURVEY.md §2): handling
(task_batch, samples_per_task, ...) nested batches and converting
per-task example pools into condition/inference meta-batches.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from tensor2robot_tpu.specs import tensorspec_utils as ts


def multi_batch_apply(fn: Callable, num_batch_dims: int, *arrays: Any,
                      **kwargs) -> Any:
  """Applies `fn` with the leading `num_batch_dims` dims merged into one.

  The reference used this to push (task, sample, ...) tensors through
  ops expecting a single batch dim; in JAX it remains useful for host
  pipelines and non-vmapped transforms.
  """
  import jax

  leaves = jax.tree_util.tree_leaves(arrays)
  if not leaves:
    return fn(*arrays, **kwargs)
  lead = leaves[0].shape[:num_batch_dims]

  def merge(x):
    return x.reshape((-1,) + tuple(x.shape[num_batch_dims:]))

  def split(x):
    return x.reshape(lead + tuple(x.shape[1:]))

  merged = jax.tree_util.tree_map(merge, arrays)
  out = fn(*merged, **kwargs)
  return jax.tree_util.tree_map(split, out)


def meta_batch_from_arrays(
    features_per_task: ts.TensorSpecStruct,
    labels_per_task: ts.TensorSpecStruct,
    num_condition_samples: int,
    num_inference_samples: int,
    rng: Optional[np.random.Generator] = None,
) -> ts.TensorSpecStruct:
  """Builds one MAML meta-feature struct from per-task sample pools.

  Args:
    features_per_task / labels_per_task: flat structs of arrays shaped
      (num_tasks, pool_size, ...).
    num_condition_samples / num_inference_samples: split sizes (pool must
      hold at least their sum).
    rng: optional shuffler of the per-task pool before splitting.

  Returns:
    Flat struct with condition/features/*, condition/labels/*,
    inference/features/*, inference/labels/* — the MAMLModel input
    layout (reference §MetaExample).
  """
  flat_features = ts.flatten_spec_structure(features_per_task)
  flat_labels = ts.flatten_spec_structure(labels_per_task)
  any_leaf = next(iter(flat_features.values()))
  num_tasks, pool = any_leaf.shape[:2]
  need = num_condition_samples + num_inference_samples
  if pool < need:
    raise ValueError(
        f"Per-task pool of {pool} samples cannot supply "
        f"{num_condition_samples}+{num_inference_samples}.")
  if rng is not None:
    order = np.stack([rng.permutation(pool) for _ in range(num_tasks)])
  else:
    order = np.broadcast_to(np.arange(pool), (num_tasks, pool))
  cond_idx = order[:, :num_condition_samples]
  inf_idx = order[:, num_condition_samples:need]

  def gather(array, idx):
    return np.stack([array[t][idx[t]] for t in range(num_tasks)])

  out = ts.TensorSpecStruct()
  for key, value in flat_features.items():
    out[f"condition/features/{key}"] = gather(value, cond_idx)
    out[f"inference/features/{key}"] = gather(value, inf_idx)
  for key, value in flat_labels.items():
    out[f"condition/labels/{key}"] = gather(value, cond_idx)
    out[f"inference/labels/{key}"] = gather(value, inf_idx)
  return out

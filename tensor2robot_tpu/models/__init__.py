"""Model layer: the portable T2R model abstraction and canonical task heads.

Reference parity: models/ (SURVEY.md §2 "Model interface", "Model base
classes").
"""

from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.models.classification_model import ClassificationModel
from tensor2robot_tpu.models.critic_model import CriticModel
from tensor2robot_tpu.models.regression_model import RegressionModel

__all__ = [
    "AbstractT2RModel",
    "ClassificationModel",
    "CriticModel",
    "RegressionModel",
]

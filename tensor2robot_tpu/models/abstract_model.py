"""AbstractT2RModel — the portable model abstraction, rebuilt functional-first.

Reference parity: models/model_interface.py §ModelInterface,
models/abstract_model.py §AbstractT2RModel (SURVEY.md §2, §3.1). The
reference model owned: spec declaration, network fn, loss fn, metrics fn,
optimizer factory, and the Estimator model_fn glue. The rebuild keeps the
first five and deletes the glue — a JAX train step is just

    grads = jax.grad(model.model_train_fn)(params, features, labels, rng)

pjit-sharded by the trainer (train/train_eval.py), so there is no
device_type branching (same XLA program serves CPU/GPU/TPU), no
TPUEstimatorSpec, and no host_call: metrics are returned as arrays and the
host loop writes them. EMA ("use_avg_model_params") and warm-start
("init_from_checkpoint") are declared here and executed by the trainer.

Model contract:
  - ``build_module()`` returns a Flax module whose ``__call__(features,
    mode)`` maps a TensorSpecStruct of arrays → TensorSpecStruct/dict of
    outputs. Modules run in ``compute_dtype`` (bfloat16 by default — MXU
    native) with parameters kept in ``param_dtype`` (float32 master copy).
  - ``loss_fn(outputs, features, labels)`` → (scalar loss, metrics dict).
  - Everything is pure: RNGs are passed explicitly, mutable collections
    (batch_stats) are threaded functionally.
"""

from __future__ import annotations

import abc
from typing import Any, Callable, Dict, Mapping, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from tensor2robot_tpu import modes
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
    ModelNoOpPreprocessor,
)
from tensor2robot_tpu.specs import tensorspec_utils as ts

# variables = {"params": ..., **model_state}; model_state holds non-param
# collections (batch_stats, ...).
Variables = Mapping[str, Any]
Metrics = Dict[str, jnp.ndarray]


class AbstractT2RModel(abc.ABC):
  """Spec-declaring, loss-defining, optimizer-providing model base."""

  def __init__(
      self,
      optimizer_fn: Optional[Callable[[], optax.GradientTransformation]] = None,
      use_avg_model_params: bool = False,
      avg_model_params_decay: float = 0.9999,
      init_from_checkpoint: Optional[str] = None,
      init_from_checkpoint_assignment_map: Optional[Dict[str, str]] = None,
      compute_dtype: Any = jnp.bfloat16,
      param_dtype: Any = jnp.float32,
  ):
    """See class docstring.

    Args:
      optimizer_fn: factory returning an optax transformation; None →
        ``create_optimizer``'s default (Adam 1e-4, the reference default).
      use_avg_model_params: maintain a Polyak/EMA copy of params, used for
        eval and export (reference §use_avg_model_params).
      avg_model_params_decay: EMA decay.
      init_from_checkpoint: checkpoint path to warm-start from (reference
        §init_from_checkpoint); applied by the trainer before step 0.
      init_from_checkpoint_assignment_map: optional {source_prefix:
        target_prefix} param renaming for the warm-start, in
        tf.train.init_from_checkpoint's direction — checkpoint name on
        the left (see train.checkpoints.merge_params).
      compute_dtype: activation dtype inside the network (bfloat16 keeps
        matmuls on the MXU's native path).
      param_dtype: master parameter dtype.
    """
    self._optimizer_fn = optimizer_fn
    self.use_avg_model_params = use_avg_model_params
    self.avg_model_params_decay = avg_model_params_decay
    self.init_from_checkpoint = init_from_checkpoint
    self.init_from_checkpoint_assignment_map = (
        init_from_checkpoint_assignment_map)
    self.compute_dtype = compute_dtype
    self.param_dtype = param_dtype
    self._module: Optional[nn.Module] = None
    self._preprocessor: Optional[AbstractPreprocessor] = None

  # --- specs (reference §get_feature_specification et al.) ----------------

  @abc.abstractmethod
  def get_feature_specification(self, mode: str) -> ts.SpecStructure:
    """Model-consumed feature specs for `mode`."""

  def get_label_specification(self, mode: str) -> ts.SpecStructure:
    """Model-consumed label specs for `mode` (default: none)."""
    del mode
    return ts.TensorSpecStruct()

  @property
  def preprocessor(self) -> AbstractPreprocessor:
    """The preprocessor pairing this model with the input pipeline.

    Default: identity, resolving the model's own specs per mode. Models
    with image pipelines override with e.g. preprocessors.ImagePreprocessor.
    """
    if self._preprocessor is None:
      self._preprocessor = self.create_preprocessor()
    return self._preprocessor

  def create_preprocessor(self) -> AbstractPreprocessor:
    return ModelNoOpPreprocessor(self)

  # --- network ------------------------------------------------------------

  @abc.abstractmethod
  def build_module(self) -> nn.Module:
    """Builds the Flax module; called once and cached."""

  @property
  def module(self) -> nn.Module:
    if self._module is None:
      self._module = self.build_module()
    return self._module

  def init_variables(
      self,
      rng: jax.Array,
      batch_size: int = 1,
      mode: str = modes.TRAIN,
  ) -> Variables:
    """Initializes variables from the declared specs (no data needed)."""
    spec = self.preprocessor.get_out_feature_specification(mode)
    features = jax.tree_util.tree_map(
        lambda s: jnp.zeros((batch_size,) + s.shape, s.dtype),
        ts.flatten_spec_structure(spec),
        is_leaf=lambda x: isinstance(x, ts.ExtendedTensorSpec))
    param_rng, dropout_rng = jax.random.split(rng)
    return self.module.init(
        {"params": param_rng, "dropout": dropout_rng}, features, mode)

  def inference_network_fn(
      self,
      variables: Variables,
      features: ts.TensorSpecStruct,
      mode: str,
      rngs: Optional[Dict[str, jax.Array]] = None,
  ) -> Tuple[Any, Dict[str, Any]]:
    """Functional forward pass (reference §inference_network_fn).

    Returns:
      (outputs, new_model_state): new_model_state carries updated mutable
      collections (batch_stats) in train mode; empty otherwise.
    """
    mutable = self.mutable_collections() if mode == modes.TRAIN else []
    if mutable:
      outputs, new_state = self.module.apply(
          variables, features, mode, rngs=rngs, mutable=mutable)
      return outputs, dict(new_state)
    outputs = self.module.apply(variables, features, mode, rngs=rngs)
    return outputs, {}

  def mutable_collections(self) -> Tuple[str, ...]:
    """Non-param variable collections updated during training."""
    return ("batch_stats",)

  # --- loss / metrics -----------------------------------------------------

  @abc.abstractmethod
  def loss_fn(
      self,
      outputs: Any,
      features: ts.TensorSpecStruct,
      labels: Optional[ts.TensorSpecStruct],
  ) -> Tuple[jnp.ndarray, Metrics]:
    """Scalar training loss + metrics (reference §model_train_fn core)."""

  def model_train_fn(
      self,
      variables: Variables,
      features: ts.TensorSpecStruct,
      labels: Optional[ts.TensorSpecStruct],
      rngs: Optional[Dict[str, jax.Array]] = None,
  ) -> Tuple[jnp.ndarray, Tuple[Metrics, Dict[str, Any]]]:
    """loss + (metrics, updated model state); differentiate w.r.t. params.

    The trainer wraps this in jax.value_and_grad(..., has_aux=True) inside
    the pjit'd step (SURVEY.md §3.1 device-side path).
    """
    outputs, new_state = self.inference_network_fn(
        variables, features, modes.TRAIN, rngs=rngs)
    loss, metrics = self.loss_fn(outputs, features, labels)
    metrics = dict(metrics)
    metrics.setdefault("loss", loss)
    return loss, (metrics, new_state)

  def model_eval_fn(
      self,
      variables: Variables,
      features: ts.TensorSpecStruct,
      labels: Optional[ts.TensorSpecStruct],
  ) -> Metrics:
    """Eval metrics (reference §model_eval_fn). EMA params are swapped in
    by the trainer before this runs when use_avg_model_params is set."""
    outputs, _ = self.inference_network_fn(variables, features, modes.EVAL)
    loss, metrics = self.loss_fn(outputs, features, labels)
    metrics = dict(metrics)
    metrics.setdefault("loss", loss)
    return metrics

  def model_image_summaries_fn(
      self,
      variables: Variables,
      features: ts.TensorSpecStruct,
  ) -> Optional[Dict[str, Any]]:
    """Optional eval-time image summaries: {tag: HWC/HW uint8 or [0,1]
    float image} rendered from one eval batch (reference: tf.summary
    image summaries through host_call — e.g. grasp2vec localization
    heatmaps). Default None = no images. Called by the eval loop with
    the (EMA) eval variables and the last eval batch; written via
    MetricWriter.write_images."""
    del variables, features
    return None

  # --- optimizer (reference §create_optimizer / §create_train_op) ---------

  def create_optimizer(self) -> optax.GradientTransformation:
    """The gradient transformation for training.

    Cross-replica gradient averaging is NOT here (the reference wrapped
    CrossShardOptimizer at this point): under pjit, gradients of a
    data-sharded batch are reduced by XLA automatically — the mesh is the
    all-reduce.
    """
    if self._optimizer_fn is not None:
      return self._optimizer_fn()
    return optax.adam(1e-4)

  # --- serving ------------------------------------------------------------

  def predict_fn(
      self,
      variables: Variables,
      features: ts.TensorSpecStruct,
  ) -> Any:
    """Pure inference entry used by export/predictors (PREDICT mode)."""
    outputs, _ = self.inference_network_fn(variables, features,
                                           modes.PREDICT)
    return outputs

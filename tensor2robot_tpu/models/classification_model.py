"""ClassificationModel — softmax cross-entropy task head base class.

Reference parity: models/classification_model.py §ClassificationModel
(SURVEY.md §2 "Model base classes"). The module's outputs must contain
``logits`` of shape (batch, num_classes).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import optax

from tensor2robot_tpu.models.abstract_model import AbstractT2RModel, Metrics
from tensor2robot_tpu.specs import tensorspec_utils as ts


class ClassificationModel(AbstractT2RModel):
  """Softmax classification against integer class labels.

  Args:
    label_key: flat key of the int class-id tensor in the label spec.
    output_key: key of the logits in the module outputs.
  """

  def __init__(self, label_key: str = "label", output_key: str = "logits",
               **kwargs):
    super().__init__(**kwargs)
    self.label_key = label_key
    self.output_key = output_key

  def loss_fn(
      self,
      outputs,
      features: ts.TensorSpecStruct,
      labels: Optional[ts.TensorSpecStruct],
  ) -> Tuple[jnp.ndarray, Metrics]:
    if labels is None:
      raise ValueError("ClassificationModel.loss_fn requires labels")
    logits = outputs[self.output_key].astype(jnp.float32)
    class_ids = labels[self.label_key]
    # Dispatch on dtype, not ndim: integer labels of shape (B,) or (B, 1)
    # are class ids; float labels must be one-hot/soft distributions. An
    # ndim heuristic would silently broadcast (B,1) int labels into the
    # one-hot path and optimize garbage.
    if jnp.issubdtype(class_ids.dtype, jnp.integer):
      class_ids = class_ids.reshape(logits.shape[:-1])
      xent = optax.softmax_cross_entropy_with_integer_labels(
          logits, class_ids).mean()
      accuracy = jnp.mean(
          (jnp.argmax(logits, -1) == class_ids).astype(jnp.float32))
    else:
      if class_ids.shape != logits.shape:
        raise ValueError(
            f"Float labels must be one-hot with shape {logits.shape}, got "
            f"{class_ids.shape}; integer class ids must use an int dtype.")
      xent = optax.softmax_cross_entropy(logits, class_ids).mean()
      accuracy = jnp.mean(
          (jnp.argmax(logits, -1) == jnp.argmax(class_ids, -1)
           ).astype(jnp.float32))
    return xent, {"cross_entropy": xent, "accuracy": accuracy}

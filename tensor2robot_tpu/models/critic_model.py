"""CriticModel — (state, action) → scalar Q-value base class.

Reference parity: models/critic_model.py §CriticModel (SURVEY.md §2) — the
base of the QT-Opt grasping Q-function (research/qtopt). Bellman targets
arrive as labels (the reference's off-repo Bellman-updater fleet produced
them; here any replay/conversion pipeline can): the model itself is a pure
supervised critic.

Loss options mirror the QT-Opt setup: ``cross_entropy`` treats the target as
a probability-of-success in [0, 1] against a sigmoid Q head (the published
grasping formulation); ``mse`` is the generic regression critic.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from tensor2robot_tpu.models.abstract_model import AbstractT2RModel, Metrics
from tensor2robot_tpu.specs import tensorspec_utils as ts


class CriticModel(AbstractT2RModel):
  """Q(s, a) critic. Module outputs must contain ``q_predicted`` — the
  pre-sigmoid logit when loss_type='cross_entropy', the raw value for 'mse'.

  Args:
    target_key: flat key of the Bellman/For-success target in labels.
    loss_type: 'cross_entropy' (QT-Opt grasping) or 'mse'.
  """

  def __init__(self, target_key: str = "target_q",
               loss_type: str = "cross_entropy", **kwargs):
    if loss_type not in ("cross_entropy", "mse"):
      raise ValueError(f"Unknown loss_type {loss_type!r}")
    super().__init__(**kwargs)
    self.target_key = target_key
    self.loss_type = loss_type

  def q_value(self, outputs) -> jnp.ndarray:
    """Q in value space (sigmoid applied for the cross-entropy head)."""
    q = outputs["q_predicted"]
    if self.loss_type == "cross_entropy":
      return jax.nn.sigmoid(q.astype(jnp.float32))
    return q

  def loss_fn(
      self,
      outputs,
      features: ts.TensorSpecStruct,
      labels: Optional[ts.TensorSpecStruct],
  ) -> Tuple[jnp.ndarray, Metrics]:
    if labels is None:
      raise ValueError("CriticModel.loss_fn requires labels")
    q_logit = outputs["q_predicted"].astype(jnp.float32)
    target = labels[self.target_key].astype(jnp.float32)
    q_logit = q_logit.reshape(target.shape)
    if self.loss_type == "cross_entropy":
      loss = optax.sigmoid_binary_cross_entropy(q_logit, target).mean()
      q_prob = jax.nn.sigmoid(q_logit)
      metrics = {
          "bce": loss,
          "q_mean": q_prob.mean(),
          # Grasp-success style accuracy at the 0.5 threshold.
          "accuracy": jnp.mean(
              ((q_prob > 0.5) == (target > 0.5)).astype(jnp.float32)),
      }
      return loss, metrics
    loss = jnp.mean(jnp.square(q_logit - target))
    return loss, {"mse": loss, "q_mean": q_logit.mean()}

"""CriticModel — (state, action) → scalar Q-value base class.

Reference parity: models/critic_model.py §CriticModel (SURVEY.md §2) — the
base of the QT-Opt grasping Q-function (research/qtopt). Bellman targets
arrive as labels (the reference's off-repo Bellman-updater fleet produced
them; here any replay/conversion pipeline can): the model itself is a pure
supervised critic.

Loss options mirror the QT-Opt setup: ``cross_entropy`` treats the target as
a probability-of-success in [0, 1] against a sigmoid Q head (the published
grasping formulation); ``mse`` is the generic regression critic.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from tensor2robot_tpu.models.abstract_model import AbstractT2RModel, Metrics
from tensor2robot_tpu.specs import tensorspec_utils as ts


class CriticModel(AbstractT2RModel):
  """Q(s, a) critic. Module outputs must contain ``q_predicted`` — the
  pre-sigmoid logit when loss_type='cross_entropy', the raw value for 'mse'.

  Args:
    target_key: flat key of the Bellman/For-success target in labels.
    loss_type: 'cross_entropy' (QT-Opt grasping) or 'mse'.
  """

  def __init__(self, target_key: str = "target_q",
               loss_type: str = "cross_entropy", **kwargs):
    if loss_type not in ("cross_entropy", "mse"):
      raise ValueError(f"Unknown loss_type {loss_type!r}")
    super().__init__(**kwargs)
    self.target_key = target_key
    self.loss_type = loss_type

  def q_value(self, outputs) -> jnp.ndarray:
    """Q in value space (sigmoid applied for the cross-entropy head)."""
    q = outputs["q_predicted"]
    if self.loss_type == "cross_entropy":
      return jax.nn.sigmoid(q.astype(jnp.float32))
    return q

  def factored_cem_fns(self):
    """Optional factored scoring pair for fused CEM consumers.

    CEM scores ONE state against many candidate actions, but the tiled
    score contract (cem.make_tiled_q_score_fn) re-runs the whole
    (image + action) forward per candidate — for image-tower-heavy
    critics, num_samples copies of identical image work per state per
    CEM iteration. A module that can split the action-independent
    prefix exposes `encode(features) -> code` and
    `q_from_code({"image": code, "action": actions})`; consumers then
    encode each state once and run CEM over the (cheap-to-tile) code —
    the same Q function, the image tower hoisted out of the search
    loop (replay/anakin.py measures the win; the generic tiled path
    stays the default everywhere else).

    Returns (encode_fn, q_from_code_fn) with predict_fn-shaped
    signatures (variables first), or None when the module has no
    factored form — callers must fall back to the tiled score.
    """
    module = self.module
    if not (hasattr(module, "encode") and hasattr(module, "q_from_code")):
      return None

    def encode_fn(variables, features):
      return module.apply(variables, features, method=module.encode)

    def q_from_code_fn(variables, features):
      return module.apply(variables, features, method=module.q_from_code)

    return encode_fn, q_from_code_fn

  def loss_fn(
      self,
      outputs,
      features: ts.TensorSpecStruct,
      labels: Optional[ts.TensorSpecStruct],
  ) -> Tuple[jnp.ndarray, Metrics]:
    if labels is None:
      raise ValueError("CriticModel.loss_fn requires labels")
    q_logit = outputs["q_predicted"].astype(jnp.float32)
    target = labels[self.target_key].astype(jnp.float32)
    q_logit = q_logit.reshape(target.shape)
    if self.loss_type == "cross_entropy":
      loss = optax.sigmoid_binary_cross_entropy(q_logit, target).mean()
      q_prob = jax.nn.sigmoid(q_logit)
      metrics = {
          "bce": loss,
          "q_mean": q_prob.mean(),
          # Grasp-success style accuracy at the 0.5 threshold.
          "accuracy": jnp.mean(
              ((q_prob > 0.5) == (target > 0.5)).astype(jnp.float32)),
      }
      return loss, metrics
    loss = jnp.mean(jnp.square(q_logit - target))
    return loss, {"mse": loss, "q_mean": q_logit.mean()}

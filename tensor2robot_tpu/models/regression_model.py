"""RegressionModel — MSE task head base class.

Reference parity: models/regression_model.py §RegressionModel (SURVEY.md §2
"Model base classes"). Subclasses declare specs + build_module; the module's
outputs must contain ``inference_output``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from tensor2robot_tpu.models.abstract_model import AbstractT2RModel, Metrics
from tensor2robot_tpu.specs import tensorspec_utils as ts


class RegressionModel(AbstractT2RModel):
  """MSE regression against a single label tensor.

  Args:
    label_key: flat key of the regression target in the label spec.
    output_key: key of the prediction in the module outputs.
  """

  def __init__(self, label_key: str = "target",
               output_key: str = "inference_output", **kwargs):
    super().__init__(**kwargs)
    self.label_key = label_key
    self.output_key = output_key

  def loss_fn(
      self,
      outputs,
      features: ts.TensorSpecStruct,
      labels: Optional[ts.TensorSpecStruct],
  ) -> Tuple[jnp.ndarray, Metrics]:
    if labels is None:
      raise ValueError("RegressionModel.loss_fn requires labels")
    predictions = outputs[self.output_key]
    targets = labels[self.label_key].astype(predictions.dtype)
    error = (predictions - targets).astype(jnp.float32)
    mse = jnp.mean(jnp.square(error))
    mae = jnp.mean(jnp.abs(error))
    return mse, {"mse": mse, "mae": mae}

"""Canonical mode names, shared by data, preprocessors, models, and train.

The analogue of tf.estimator.ModeKeys in the reference's
mode-parameterized APIs (get_feature_specification(mode), preprocess_fn
mode-awareness — SURVEY.md §2).
"""

TRAIN = "train"
EVAL = "eval"
PREDICT = "predict"

ALL_MODES = (TRAIN, EVAL, PREDICT)


def validate_mode(mode: str) -> str:
  if mode not in ALL_MODES:
    raise ValueError(f"Unknown mode {mode!r}; expected one of {ALL_MODES}")
  return mode

"""One observability spine for the production loop (ISSUE 11).

Four layers, each usable alone, designed to compose:

- ``trace``: host-side structured spans (thread-safe, nestable) that
  double as ``jax.profiler.TraceAnnotation``s while a device trace is
  active, exportable as one Chrome-trace/Perfetto JSON per run.
- ``registry``: a process-wide typed metric registry (counters, gauges,
  bounded histograms with p50/p99 snapshots) with one bridge flushing
  snapshots through the existing ``utils.metric_writer.MetricWriter``
  (JSONL + TensorBoard stay the dashboards).
- ``ledger``: the compile-count dicts scattered through replay/ and
  serving/ promoted to a first-class ``ExecutableLedger`` that joins
  ``compiled.cost_analysis()`` FLOPs/bytes with dispatch counts and
  measured wall time into per-executable device-time attribution.
- ``flight_recorder``: a bounded in-memory ring of recent spans/events,
  dumped atomically to ``<logdir>/flightrec-*.json`` on SLO breach,
  rollout auto-rollback, watchdog stall, or an unhandled loop-thread
  exception.

Round 13 (ISSUE 12) extends the spine ACROSS processes:

- ``context``: contextvar-carried ``request_id``/``step_id``
  correlation ids minted at serving ingress, auto-attached to every
  span, exported as Perfetto flows — one clickable per-request
  timeline across threads and (via the aggregator) processes.
- ``aggregate``: the fleet merge — N processes' host/pid-stamped
  ``metrics.jsonl`` streams, registry snapshots, Chrome traces, and
  flightrec dumps from one shared logdir into one ``FLEETOBS`` view
  (reservoir-union percentiles, per-host step rates, SLO rollup,
  host-prefixed merged trace).
- ``watchdog``: named heartbeats for every loop thread; a monitor
  flags stalls (no progress within deadline) with counter → flightrec
  dump → callback escalation, and ``find_stragglers`` flags fleet
  members below a fraction of the median step rate.

Round 15 (ISSUE 14) adds ``faults`` — deterministic seeded fault
injection through explicit seams; round 16 (ISSUE 15) adds ``health``
— the silent-failure sentinel: in-program training-health summaries
computed inside the fused learn executables, a ``HealthMonitor`` of
declarative rules (hard nonfinite, EWMA drift, bound floors)
escalating through the rails above, and the fleet Q-drift guard over
per-replica served-Q sketches.

The Podracer analysis (PAPERS.md, arXiv:2104.06272) and the pjit/TPUv4
scaling study (arXiv:2204.06514) both justify their architectures with
exactly this per-executable utilization accounting; the multi-host and
bf16-CEM directions in ROADMAP.md will be measured through this layer.
"""

from tensor2robot_tpu.obs.aggregate import aggregate_logdir
from tensor2robot_tpu.obs.context import (bind, current_request_id,
                                          new_request_id)
from tensor2robot_tpu.obs.flight_recorder import (FlightRecorder,
                                                  get_recorder)
from tensor2robot_tpu.obs.health import (HealthHalt, HealthMonitor,
                                         HealthRule, default_rules,
                                         q_drift_report)
from tensor2robot_tpu.obs.ledger import (ExecutableLedger,
                                         check_compile_ledger,
                                         peak_flops_for)
from tensor2robot_tpu.obs.registry import MetricRegistry, get_registry
from tensor2robot_tpu.obs.trace import (Tracer, get_tracer,
                                        set_device_annotations, span)
from tensor2robot_tpu.obs.watchdog import (Watchdog, find_stragglers,
                                           get_watchdog)

__all__ = [
    "ExecutableLedger",
    "FlightRecorder",
    "HealthHalt",
    "HealthMonitor",
    "HealthRule",
    "MetricRegistry",
    "Tracer",
    "Watchdog",
    "aggregate_logdir",
    "bind",
    "check_compile_ledger",
    "current_request_id",
    "default_rules",
    "find_stragglers",
    "get_recorder",
    "get_registry",
    "get_tracer",
    "get_watchdog",
    "new_request_id",
    "peak_flops_for",
    "q_drift_report",
    "set_device_annotations",
    "span",
]

"""One observability spine for the production loop (ISSUE 11).

Four layers, each usable alone, designed to compose:

- ``trace``: host-side structured spans (thread-safe, nestable) that
  double as ``jax.profiler.TraceAnnotation``s while a device trace is
  active, exportable as one Chrome-trace/Perfetto JSON per run.
- ``registry``: a process-wide typed metric registry (counters, gauges,
  bounded histograms with p50/p99 snapshots) with one bridge flushing
  snapshots through the existing ``utils.metric_writer.MetricWriter``
  (JSONL + TensorBoard stay the dashboards).
- ``ledger``: the compile-count dicts scattered through replay/ and
  serving/ promoted to a first-class ``ExecutableLedger`` that joins
  ``compiled.cost_analysis()`` FLOPs/bytes with dispatch counts and
  measured wall time into per-executable device-time attribution.
- ``flight_recorder``: a bounded in-memory ring of recent spans/events,
  dumped atomically to ``<logdir>/flightrec-*.json`` on SLO breach,
  rollout auto-rollback, or an unhandled loop-thread exception.

The Podracer analysis (PAPERS.md, arXiv:2104.06272) and the pjit/TPUv4
scaling study (arXiv:2204.06514) both justify their architectures with
exactly this per-executable utilization accounting; the multi-host and
bf16-CEM directions in ROADMAP.md will be measured through this layer.
"""

from tensor2robot_tpu.obs.flight_recorder import (FlightRecorder,
                                                  get_recorder)
from tensor2robot_tpu.obs.ledger import (ExecutableLedger,
                                         check_compile_ledger,
                                         peak_flops_for)
from tensor2robot_tpu.obs.registry import MetricRegistry, get_registry
from tensor2robot_tpu.obs.trace import (Tracer, get_tracer,
                                        set_device_annotations, span)

__all__ = [
    "ExecutableLedger",
    "FlightRecorder",
    "MetricRegistry",
    "Tracer",
    "check_compile_ledger",
    "get_recorder",
    "get_registry",
    "get_tracer",
    "peak_flops_for",
    "set_device_annotations",
    "span",
]

"""Fleet observability merge: N processes' obs streams → ONE view.

PR 8 stamped every MetricWriter JSONL record with ``host``/``pid`` "for
the coming multi-host tier"; this module is that tier's read side. A
fleet logdir holds per-process streams — ``metrics.jsonl`` files,
``registry*.json`` snapshots (obs/registry.py ``export_snapshot``:
counters, gauges, RAW histogram reservoirs), Chrome traces with
correlation-id'd spans (obs/trace.py), and ``flightrec-*.json``
post-mortems — and ``aggregate_logdir`` merges them into one
schema-versioned fleet view:

- **metrics**: counters summed across processes; histograms merged by
  *reservoir union* — samples from every process pooled, then ONE
  nearest-rank pass (obs/registry.py's convention — the repo's single
  percentile source) produces the fleet p50/p99. Averaging per-process
  percentiles has no statistical meaning and is exactly the mistake
  this module exists to prevent.
- **per-process view**: each ``host:pid`` gets its record count, step
  span, measured step rate (the straggler detector's input), last
  gauge values, and a bounded step series.
- **SLO rollup**: per-class requests / shed_expired / shed_capacity
  summed across every router in the fleet, class latency p50/p99 from
  the unioned reservoirs, plus a consistency check — the global shed
  counters must equal the per-class sums across all sources.
- **merged trace**: every process's Chrome trace concatenated into
  ``fleet_trace.json`` with host-prefixed process lanes (pids remapped
  to stable synthetic ids so two hosts' pid 1234 cannot collide) and
  request flows RE-LINKED globally — a request id appearing in two
  processes' spans becomes one arrow chain across both lanes.
- **watchdog**: ``watchdog_stall`` dumps schema-validated and
  summarized; per-process step rates run through
  ``obs.watchdog.find_stragglers`` against the fleet median.

The CLI (``bin/obs_aggregate``) aggregates any logdir; ``--smoke`` is
the committed FLEETOBS_r13 protocol — >= 2 REAL subprocess serve loops
(the ``cpu_mesh_env`` re-exec idiom, 8 virtual devices each) against
one shared logdir, plus an injected watchdog stall and a healthy
negative control, all merged and self-checked here.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Tuple

from tensor2robot_tpu.obs.registry import _nearest_rank
from tensor2robot_tpu.obs import watchdog as watchdog_lib

SCHEMA = "t2r-fleetobs-1"

_MAX_SERIES_POINTS = 200


def _is_own_output(name: str) -> bool:
  # Outputs this module itself writes — never inputs on a re-run.
  return name == "fleet_trace.json" or name.startswith("FLEETOBS")


def discover_inputs(logdir: str) -> Dict[str, List[str]]:
  """Walks the fleet logdir for the four per-process stream kinds."""
  found: Dict[str, List[str]] = {
      "metrics": [], "registry": [], "trace": [], "flightrec": []}
  for root, _, files in os.walk(logdir):
    for name in sorted(files):
      if _is_own_output(name):
        continue
      path = os.path.join(root, name)
      if name == "metrics.jsonl":
        found["metrics"].append(path)
      elif name.startswith("registry") and name.endswith(".json"):
        found["registry"].append(path)
      elif name.startswith("trace") and name.endswith(".json"):
        found["trace"].append(path)
      elif name.startswith("flightrec-") and name.endswith(".json"):
        found["flightrec"].append(path)
  return found


def _load_json(path: str) -> Optional[dict]:
  try:
    with open(path) as f:
      return json.load(f)
  except (OSError, ValueError):
    return None


# -- metrics.jsonl ----------------------------------------------------------


def _merge_metrics(paths: List[str]) -> Tuple[Dict[str, dict], List[str]]:
  """Per-(host:pid) summary from the stamped JSONL streams."""
  per_process: Dict[str, dict] = {}
  problems: List[str] = []
  for path in paths:
    try:
      with open(path) as f:
        lines = f.readlines()
    except OSError as e:
      problems.append(f"{path}: {e}")
      continue
    for line in lines:
      line = line.strip()
      if not line:
        continue
      try:
        record = json.loads(line)
      except ValueError:
        problems.append(f"{path}: unparseable line")
        continue
      host = record.get("host", "unknown")
      pid = record.get("pid", 0)
      key = f"{host}:{pid}"
      entry = per_process.setdefault(key, {
          "host": host, "pid": pid, "records": 0,
          "step_min": None, "step_max": None,
          "wall_min": None, "wall_max": None,
          "gauges": {}, "step_series": [],
      })
      entry["records"] += 1
      step = record.get("step")
      wall = record.get("wall_time")
      if step is not None:
        entry["step_min"] = (step if entry["step_min"] is None
                             else min(entry["step_min"], step))
        entry["step_max"] = (step if entry["step_max"] is None
                             else max(entry["step_max"], step))
      if wall is not None:
        entry["wall_min"] = (wall if entry["wall_min"] is None
                             else min(entry["wall_min"], wall))
        entry["wall_max"] = (wall if entry["wall_max"] is None
                             else max(entry["wall_max"], wall))
      if step is not None and wall is not None:
        entry["step_series"].append([round(wall, 3), step])
      for field, value in record.items():
        if field in ("step", "wall_time", "host", "pid"):
          continue
        if isinstance(value, (int, float)):
          entry["gauges"][field] = value  # last-write-wins per stream
  for entry in per_process.values():
    series = entry.pop("step_series")
    wall0 = entry["wall_min"] or 0.0
    series = [[round(wall - wall0, 3), step] for wall, step in series]
    if len(series) > _MAX_SERIES_POINTS:
      stride = -(-len(series) // _MAX_SERIES_POINTS)
      series = series[::stride] + [series[-1]]
    entry["step_series"] = series
    span = ((entry["wall_max"] - entry["wall_min"])
            if entry["wall_min"] is not None else None)
    entry["wall_span_s"] = round(span, 3) if span is not None else None
    steps = ((entry["step_max"] - entry["step_min"])
             if entry["step_min"] is not None else None)
    # steps == 0 over a real observed span is rate 0.0, NOT None: a
    # host wedged at step N that keeps emitting health records is the
    # worst straggler there is, and None would exclude it from the
    # fleet-median comparison entirely (span > 0 needs >= 2 records,
    # so a single-record stream still reads None — no interval was
    # observed).
    entry["step_rate"] = (round(steps / span, 4)
                          if steps is not None and span and span > 0
                          else None)
    del entry["wall_min"], entry["wall_max"]
  return per_process, problems


# -- registry snapshots -----------------------------------------------------


def _merge_registries(paths: List[str]) -> dict:
  """Counters summed, histogram reservoirs unioned, gauges per-host."""
  counters: Dict[str, int] = {}
  gauges_per_host: Dict[str, dict] = {}
  samples: Dict[str, list] = {}
  counts: Dict[str, int] = {}
  sources = 0
  per_source: List[dict] = []
  for path in paths:
    snapshot = _load_json(path)
    if not snapshot or snapshot.get("schema") != "t2r-registry-1":
      continue
    sources += 1
    key = f"{snapshot.get('host', '?')}:{snapshot.get('pid', 0)}"
    for name, value in snapshot.get("counters", {}).items():
      counters[name] = counters.get(name, 0) + int(value)
    gauges_per_host.setdefault(key, {}).update(
        snapshot.get("gauges", {}))
    q_sketches = {}
    for name, hist in snapshot.get("histograms", {}).items():
      samples.setdefault(name, []).extend(hist.get("samples", []))
      counts[name] = counts.get(name, 0) + int(hist.get("count", 0))
      # Per-replica served-Q reservoirs (ISSUE 15): summarized PER
      # SOURCE — two hosts' replicas share device names, so pooling
      # them by name would hide exactly the divergence the fleet
      # Q-drift guard exists to see.
      if (name.startswith("serving/replica/")
          and name.endswith("/q_value")):
        replica = name[len("serving/replica/"):-len("/q_value")]
        reservoir = sorted(hist.get("samples", []))
        if reservoir:
          q_sketches[replica] = {
              "count": int(hist.get("count", 0)),
              "mean": round(sum(reservoir) / len(reservoir), 6),
              "p50": round(_nearest_rank(reservoir, 50), 6),
              "p90": round(_nearest_rank(reservoir, 90), 6),
          }
    per_source.append({
        "process": key,
        "counters": snapshot.get("counters", {}),
        "q_sketches": q_sketches,
    })
  histograms = {}
  for name, pooled in sorted(samples.items()):
    if not pooled:
      histograms[name] = {"count": counts.get(name, 0)}
      continue
    ordered = sorted(pooled)
    histograms[name] = {
        "count": counts.get(name, 0),
        "merged_samples": len(pooled),
        "p50": round(_nearest_rank(ordered, 50), 4),
        "p99": round(_nearest_rank(ordered, 99), 4),
        "max": round(ordered[-1], 4),
        "mean": round(sum(pooled) / len(pooled), 4),
    }
  return {
      "sources": sources,
      "counters": counters,
      "gauges_per_host": gauges_per_host,
      "histograms": histograms,
      "per_source": per_source,
  }


def _slo_rollup(registries: dict) -> dict:
  """Cross-host per-class rollup + the shed-consistency self-check."""
  counters = registries["counters"]
  histograms = registries["histograms"]
  classes: Dict[str, dict] = {}
  prefix = "serving/class/"
  for name, value in counters.items():
    if not name.startswith(prefix):
      continue
    class_name, _, field = name[len(prefix):].partition("/")
    entry = classes.setdefault(class_name, {
        "requests": 0, "shed_expired": 0, "shed_capacity": 0,
        "shed_fault": 0})
    if field in entry:
      entry[field] += int(value)
  for class_name, entry in classes.items():
    entry["shed"] = (entry["shed_expired"] + entry["shed_capacity"]
                     + entry["shed_fault"])
    latency = histograms.get(f"{prefix}{class_name}/latency_ms")
    if latency and latency.get("merged_samples"):
      entry["latency_p50_ms"] = latency["p50"]
      entry["latency_p99_ms"] = latency["p99"]
  shed_total = sum(entry["shed"] for entry in classes.values())
  global_shed = (counters.get("serving/shed_expired", 0)
                 + counters.get("serving/shed_capacity", 0)
                 + counters.get("serving/shed_fault", 0))
  # Consistency across SOURCES too: the global counters from every
  # registry snapshot must sum to the per-class sums — a process whose
  # sheds bypassed class accounting (or a double-merged snapshot)
  # breaks this, which is exactly what the obs_bench self-check exists
  # to catch.
  per_source_ok = True
  for source in registries["per_source"]:
    source_counters = source["counters"]
    source_global = (source_counters.get("serving/shed_expired", 0)
                     + source_counters.get("serving/shed_capacity", 0)
                     + source_counters.get("serving/shed_fault", 0))
    source_classes = sum(
        int(value) for name, value in source_counters.items()
        if name.startswith(prefix)
        and name.rsplit("/", 1)[-1] in ("shed_expired", "shed_capacity",
                                        "shed_fault"))
    if source_global != source_classes:
      per_source_ok = False
  return {
      "per_class": {name: classes[name] for name in sorted(classes)},
      "shed_total": shed_total,
      "requests_total": counters.get("serving/requests", 0),
      "consistent": bool(shed_total == global_shed and per_source_ok),
  }


def _health_rollup(registries: dict, flightrec: dict) -> dict:
  """Fleet health verdict (ISSUE 15): breach counters summed across
  processes, health_breach dumps schema-summarized, and the fleet
  Q-DRIFT check run over EVERY process's per-replica served-Q sketches
  (keys ``host:pid/replica``, so two hosts' same-named devices stay
  distinct) — the cross-host form of the router's own
  ``check_q_drift``. Verdict: "divergent" when any replica's served-Q
  stream disagrees with the fleet, else "breaching" when any health
  rule fired anywhere, else "ok" ("insufficient" q-data keeps the
  breach-based verdict)."""
  from tensor2robot_tpu.obs import health as health_lib

  counters = {
      name[len("health/"):]: int(value)
      for name, value in registries["counters"].items()
      if name.startswith("health/")}
  fleet_sketches = {}
  for source in registries["per_source"]:
    for replica, summary in source.get("q_sketches", {}).items():
      fleet_sketches[f"{source['process']}/{replica}"] = summary
  q_drift = health_lib.q_drift_report(fleet_sketches)
  breach_total = counters.get("breaches", 0)
  divergent = q_drift["verdict"] == "divergent"
  return {
      "verdict": ("divergent" if divergent
                  else "breaching" if breach_total else "ok"),
      "breach_counters": counters,
      "breach_total": breach_total,
      "breach_dumps": len(flightrec.get("health_breaches", [])),
      "q_drift": q_drift,
  }


# -- traces -----------------------------------------------------------------


def _merge_traces(paths: List[str], out_path: Optional[str]) -> dict:
  """Concatenates per-process Chrome traces into one fleet timeline.

  Each source file gets a stable synthetic pid lane (host-prefixed
  process_name metadata preserved/added), and request flows are
  re-linked GLOBALLY: spans in different processes carrying the same
  request id join one arrow chain — the cross-process request timeline
  the tentpole promises.

  Timestamp alignment: each Tracer's ts is relative to its OWN
  construction-time perf_counter epoch, so raw concatenation would
  stack every lane at ts 0. The exporter stamps ``epoch_wall_s`` (the
  epoch on the shared wall clock) into the process_name metadata;
  every source with the anchor is offset onto one timeline relative to
  the earliest epoch. Anchor-less sources (older traces) keep offset 0
  — comparable within their own lane, as before.
  """
  from tensor2robot_tpu.obs import context as context_lib
  from tensor2robot_tpu.obs import trace as trace_lib

  loaded = []
  epochs = []
  for path in sorted(paths):
    payload = _load_json(path)
    if not payload or "traceEvents" not in payload:
      continue
    label = None
    epoch_wall = None
    for event in payload["traceEvents"]:
      if event.get("ph") == "M" and event.get("name") == "process_name":
        label = event.get("args", {}).get("name")
        epoch_wall = event.get("args", {}).get("epoch_wall_s")
        break
    loaded.append((path, payload, label, epoch_wall))
    if epoch_wall is not None:
      epochs.append(epoch_wall)
  base_epoch = min(epochs) if epochs else None

  events: List[dict] = []
  by_request: Dict[str, list] = {}
  sources = []
  for index, (path, payload, label, epoch_wall) in enumerate(loaded):
    new_pid = index + 1
    offset_us = (round((epoch_wall - base_epoch) * 1e6, 3)
                 if epoch_wall is not None and base_epoch is not None
                 else 0.0)
    label = label or os.path.basename(os.path.dirname(path)) or path
    sources.append({"file": os.path.relpath(path,
                                            os.path.dirname(out_path))
                    if out_path else path,
                    "process": label, "pid": new_pid,
                    "offset_us": offset_us})
    events.append({
        "name": "process_name", "ph": "M", "pid": new_pid, "tid": 0,
        "args": {"name": label, "epoch_wall_s": epoch_wall},
    })
    for event in payload["traceEvents"]:
      if event.get("ph") == "M":
        continue
      if event.get("cat") == "request":
        continue  # re-linked globally below
      remapped = dict(event)
      remapped["pid"] = new_pid
      if "ts" in remapped:
        remapped["ts"] = round(remapped["ts"] + offset_us, 3)
      events.append(remapped)
      if event.get("ph") != "X":
        continue
      args = event.get("args", {})
      record = {
          "name": event.get("name"),
          "ts_s": remapped.get("ts", 0.0) / 1e6,
          "dur_s": event.get("dur", 0.0) / 1e6,
          "tid": event.get("tid", 0),
          "pid": new_pid,
          "request_id": args.get("request_id"),
          "request_ids": args.get("request_ids"),
      }
      for request_id in context_lib.span_request_ids(record):
        by_request.setdefault(request_id, []).append(record)
  flow_ids: Dict[str, int] = {}
  events.extend(trace_lib.request_flow_events(by_request, 0,
                                              flow_ids=flow_ids))
  # Correlation readout: which requests link a full serve timeline
  # (enqueue -> flush -> dispatch), and does any flow cross processes?
  linked = []
  cross_process = 0
  for request_id, records in sorted(by_request.items()):
    names = {record["name"] for record in records}
    if ("serve/enqueue" in names and "serve/flush" in names
        and "serve/dispatch" in names):
      linked.append(request_id)
    if len({record["pid"] for record in records}) > 1:
      cross_process += 1
  if out_path is not None:
    payload = {"traceEvents": events, "displayTimeUnit": "ms"}
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
      json.dump(payload, f)
    os.replace(tmp, out_path)
  example = None
  if linked:
    example_records = sorted(by_request[linked[0]],
                             key=lambda r: r["ts_s"])
    example = {"request_id": linked[0],
               "spans": [record["name"] for record in example_records]}
  return {
      "file": os.path.basename(out_path) if out_path else None,
      "sources": sources,
      "events": len(events),
      "request_ids_seen": len(by_request),
      "flows_linked": len(flow_ids),
      "linked_serve_timelines": len(linked),
      "cross_process_flows": cross_process,
      "example_timeline": example,
  }


# -- flight-recorder dumps --------------------------------------------------


def _merge_flightrecs(paths: List[str]) -> dict:
  """Summarizes every post-mortem dump; validates watchdog_stall and
  health_breach ones against their trigger schemas."""
  from tensor2robot_tpu.obs import health as health_lib

  reasons: Dict[str, int] = {}
  by_process: Dict[str, int] = {}
  watchdog_stalls = []
  health_breaches = []
  request_ids = []
  invalid = []
  for path in sorted(paths):
    payload = _load_json(path)
    if not payload or payload.get("schema") != "t2r-flightrec-1":
      invalid.append(os.path.basename(path))
      continue
    reason = payload.get("reason", "unknown")
    reasons[reason] = reasons.get(reason, 0) + 1
    key = f"{payload.get('host', '?')}:{payload.get('pid', 0)}"
    by_process[key] = by_process.get(key, 0) + 1
    if payload.get("request_id"):
      request_ids.append(payload["request_id"])
    if reason == "watchdog_stall":
      trigger = payload.get("trigger", {})
      missing = [field for field in watchdog_lib.STALL_FIELDS
                 if field not in trigger]
      watchdog_stalls.append({
          "file": os.path.basename(path),
          "process": key,
          "component": trigger.get("component"),
          "stalled_for_s": trigger.get("stalled_for_s"),
          "events": len(payload.get("events", [])),
          "schema_ok": not missing,
          "missing_fields": missing,
      })
    elif reason == "health_breach":
      trigger = payload.get("trigger", {})
      missing = [field for field in health_lib.BREACH_FIELDS
                 if field not in trigger]
      health_breaches.append({
          "file": os.path.basename(path),
          "process": key,
          "rule": trigger.get("rule"),
          "metric": trigger.get("metric"),
          "step": trigger.get("step"),
          "schema_ok": not missing,
          "missing_fields": missing,
      })
  return {
      "dumps": sum(reasons.values()),
      "reasons": reasons,
      "by_process": by_process,
      "request_ids": request_ids[:16],
      "watchdog_stalls": watchdog_stalls,
      "health_breaches": health_breaches,
      "invalid": invalid,
  }


# -- the one entry point ----------------------------------------------------


def aggregate_logdir(logdir: str,
                     merged_trace: bool = True,
                     straggler_fraction: float = 0.5) -> dict:
  """Merges every per-process stream under ``logdir`` into one view."""
  inputs = discover_inputs(logdir)
  per_process, problems = _merge_metrics(inputs["metrics"])
  registries = _merge_registries(inputs["registry"])
  slo = _slo_rollup(registries)
  trace_out = (os.path.join(logdir, "fleet_trace.json")
               if merged_trace and inputs["trace"] else None)
  trace = _merge_traces(inputs["trace"], trace_out)
  flightrec = _merge_flightrecs(inputs["flightrec"])
  health = _health_rollup(registries, flightrec)
  rates = {key: entry["step_rate"]
           for key, entry in per_process.items()
           if entry["step_rate"] is not None}
  stragglers = watchdog_lib.find_stragglers(
      rates, fraction=straggler_fraction)
  hosts = sorted({entry["host"] for entry in per_process.values()})
  return {
      "schema": SCHEMA,
      "logdir": logdir,
      "inputs": {kind: len(paths) for kind, paths in inputs.items()},
      "hosts": hosts,
      "hosts_merged": len(per_process),
      "per_host": {key: per_process[key]
                   for key in sorted(per_process)},
      "registry": {
          "sources": registries["sources"],
          "counters": registries["counters"],
          "histograms": registries["histograms"],
          "gauges_per_host": registries["gauges_per_host"],
      },
      "slo": slo,
      "health": health,
      "trace": trace,
      "flightrec": flightrec,
      "stragglers": stragglers,
      "problems": problems,
      "note": (
          "hosts_merged counts distinct host:pid streams (one per "
          "process; on one machine these are pids). Histogram "
          "percentiles come from ONE nearest-rank pass over the "
          "unioned reservoirs — never from averaging per-process "
          "percentiles. step_rate is steps per wall second over each "
          "stream's observed span; stragglers compares those rates "
          "against the fleet median (needs >= 2 streams)."),
  }


# -- the FLEETOBS_r13 protocol ---------------------------------------------


def _run_worker(index: int, logdir: str, seed: int,
                duration_s: float, ladder_sizes,
                slow_factor: float = 1.0) -> None:
  """One REAL fleet process: a routed serve window against the shared
  logdir. Runs under the 8-virtual-device CPU mesh env its parent
  spawned it with; everything it leaves behind — metrics.jsonl,
  registry snapshot, Chrome trace, breach dump — is aggregator input.
  """
  import jax

  from tensor2robot_tpu.obs import flight_recorder as flight_lib
  from tensor2robot_tpu.obs import registry as registry_lib
  from tensor2robot_tpu.obs import trace as trace_lib
  from tensor2robot_tpu.serving.router import FleetRouter
  from tensor2robot_tpu.serving.slo import SLOClass
  from tensor2robot_tpu.serving.smoke import TinyQPredictor
  from tensor2robot_tpu.serving.stats import ServingStats
  from tensor2robot_tpu.utils.metric_writer import MetricWriter

  worker_dir = os.path.join(logdir, f"worker{index}")
  os.makedirs(worker_dir, exist_ok=True)
  recorder = flight_lib.get_recorder()
  recorder.configure(dump_dir=worker_dir, min_dump_interval_s=0.5)
  registry = registry_lib.get_registry()

  devices = jax.devices()
  predictor = TinyQPredictor(seed=seed)
  stats = ServingStats()
  max_queue = 4
  router = FleetRouter(
      predictor, devices=devices, num_samples=16, num_elites=4,
      iterations=2, ladder_sizes=ladder_sizes, max_queue=max_queue,
      dispatch_margin_ms=20.0, stats=stats, seed=seed)
  router.warmup(predictor.make_image)
  images = [predictor.make_image(seed + i) for i in range(8)]

  # Two paced classes with SHORT budgets (a lone partial batch waits
  # out its class deadline before flushing, so the pace loop's step
  # time is bounded by the slowest class budget — sub-second keeps the
  # per-step JSONL series dense enough for a measured step rate); the
  # long-budget batch class exists only for the deterministic breach
  # burst below.
  interactive = SLOClass("interactive", priority=2, deadline_ms=150.0)
  standard = SLOClass("standard", priority=1, deadline_ms=300.0)
  batch_class = SLOClass("batch", priority=0, deadline_ms=2000.0)
  completed = 0
  submitted = 0
  with MetricWriter(worker_dir) as writer, router:
    stop_at = time.perf_counter() + duration_s
    step = 0
    while time.perf_counter() < stop_at:
      futures = []
      for i in range(4):
        slo = interactive if (submitted + i) % 3 else standard
        futures.append(router.submit(images[i % len(images)], slo=slo))
      submitted += len(futures)
      for future in futures:
        try:
          future.result(timeout=30)
          completed += 1
        except Exception:
          pass
      step += 1
      stats.write_to(writer, step)
      registry.set_gauges({"fleetobs/worker_completed": completed})
      registry.flush_to(writer, step,
                        names=["fleetobs/worker_completed"])
      # slow_factor > 1 makes this worker a deliberate straggler for
      # the fleet-median comparison (reported, not asserted — two
      # processes have a fragile median).
      time.sleep(0.02 * slow_factor)

    # Injected SLO breach (the FLEET burst idiom): deterministic
    # capacity sheds under held flushes; the first shed's dump carries
    # its request id into the fleet flightrec rollup.
    import contextlib as _contextlib
    breach_futures = []
    with _contextlib.ExitStack() as stack:
      for replica in router.replicas:
        stack.enter_context(replica.batcher.hold_flushes())
      for j in range(2 * max_queue * len(router.replicas)):
        breach_futures.append(
            router.submit(images[j % len(images)], slo=batch_class))
    shed = 0
    for future in breach_futures:
      try:
        future.result(timeout=60)
      except Exception:
        shed += 1
    # Final JSONL record AFTER the breach: the per-process stream must
    # carry the shed totals the registry snapshot carries, or the
    # aggregator's "rollup consistent with the per-process JSONL"
    # claim would be vacuously about a pre-breach window.
    stats.write_to(writer, step + 1)

  registry.export_snapshot(os.path.join(worker_dir, "registry.json"))
  trace_lib.get_tracer().export_chrome_trace(
      os.path.join(worker_dir, "trace.json"))
  print(json.dumps({
      "worker": index,
      "host": os.uname().nodename,
      "pid": os.getpid(),
      "devices": len(devices),
      "submitted": submitted,
      "completed": completed,
      "shed": shed,
  }))


def watchdog_controls(logdir: str, ci: bool = False) -> dict:
  """Injected stall + healthy negative control, chiplessly in-process.

  Deadlines follow the cpu_count >= 4 gating convention via
  ``scaled_deadline`` so slow-CI scheduling noise cannot flip either
  verdict (the false-positive guard the satellite demands).
  """
  import threading

  from tensor2robot_tpu.obs.flight_recorder import FlightRecorder
  from tensor2robot_tpu.obs.registry import MetricRegistry

  dump_dir = os.path.join(logdir, "watchdog")
  registry = MetricRegistry()
  recorder = FlightRecorder(dump_dir=dump_dir, min_dump_interval_s=0.0)

  # Healthy control FIRST (a clean monitor): a beating component plus
  # an idle one; the monitor must record ZERO events.
  healthy = watchdog_lib.Watchdog(
      poll_s=0.05, recorder=recorder, registry=registry,
      default_deadline_s=watchdog_lib.scaled_deadline(2.0))
  beating = healthy.register("replay/learner")
  idle = healthy.register("serve/batcher")
  del idle  # registered, never beats — idle components cannot stall
  stop = threading.Event()

  def _beat():
    while not stop.is_set():
      beating.beat()
      time.sleep(0.02)

  thread = threading.Thread(target=_beat, daemon=True)
  with healthy:
    thread.start()
    time.sleep(0.4 if ci else 1.0)
  stop.set()
  thread.join(5.0)
  healthy_events = list(healthy.events)

  # Injected stall: a component that declares work pending (busy) and
  # then never progresses. The deadline is tiny ON PURPOSE — this is
  # the positive control, so it must fire fast and deterministically.
  injected = watchdog_lib.Watchdog(
      poll_s=0.05, recorder=recorder, registry=registry,
      default_deadline_s=0.2)
  stalled = injected.register("replay/learner")
  stalled.busy()
  with injected:
    deadline = time.monotonic() + 30.0
    while injected.stall_count == 0 and time.monotonic() < deadline:
      time.sleep(0.05)
  stall_events = [event for event in injected.events
                  if event["event"] == "watchdog_stall"]
  dumps = [name for name in sorted(os.listdir(dump_dir))
           if "watchdog_stall" in name] if os.path.isdir(dump_dir) else []
  dump_payload = (_load_json(os.path.join(dump_dir, dumps[0]))
                  if dumps else None)
  return {
      "healthy_control": {
          "duration_s": 0.4 if ci else 1.0,
          "beats": beating.beats,
          "events": len(healthy_events),
          "ok": not healthy_events,
      },
      "injected_stall": {
          "events": len(stall_events),
          "component": (stall_events[0]["component"]
                        if stall_events else None),
          "dump": dumps[0] if dumps else None,
          "dump_schema": (dump_payload or {}).get("schema"),
          "dump_trigger": (dump_payload or {}).get("trigger"),
          "ok": bool(stall_events and dumps
                     and (dump_payload or {}).get("schema")
                     == "t2r-flightrec-1"),
      },
      "registry_stalls": registry.counter("watchdog/stalls").value,
  }


def measure_fleetobs(num_workers: int = 2,
                     duration_s: float = 3.0,
                     ladder_sizes=(1, 2, 4),
                     seed: int = 0,
                     logdir: Optional[str] = None,
                     ci: bool = False) -> dict:
  """The FLEETOBS_r13 protocol: real subprocess loops + the merge.

  Spawns ``num_workers`` REAL processes (each re-exec'd under the
  8-virtual-device CPU mesh env — the conftest idiom) running routed
  serve windows against ONE shared logdir, runs the watchdog positive/
  negative controls chiplessly in this process, then aggregates the
  logdir and self-checks the merged view:

  - a per-host stream present for every worker pid;
  - the merged per-class shed rollup consistent with the per-process
    registry counters (the obs_bench satellite's bar, cross-process);
  - >= 1 correlation-linked request timeline (enqueue → flush →
    dispatch) in the merged trace;
  - the injected stall produced a schema-valid ``watchdog_stall`` dump
    and the healthy control produced zero watchdog events.
  """
  import subprocess
  import sys
  import tempfile

  from tensor2robot_tpu.utils.cpu_mesh_env import cpu_mesh_env

  logdir = logdir or tempfile.mkdtemp(prefix="fleetobs_")
  os.makedirs(logdir, exist_ok=True)
  worker_env = cpu_mesh_env(8)
  processes = []
  start = time.perf_counter()
  for index in range(num_workers):
    args = [sys.executable, "-m", "tensor2robot_tpu.obs.aggregate",
            "--worker", str(index), "--logdir", logdir,
            "--seed", str(seed + 17 * index),
            "--duration", str(duration_s)]
    if ci:
      args.append("--ci")
    processes.append(subprocess.Popen(
        args, env=worker_env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True))
  workers = []
  failures = []
  for index, process in enumerate(processes):
    try:
      stdout, stderr = process.communicate(timeout=900)
    except subprocess.TimeoutExpired:
      process.kill()
      stdout, stderr = process.communicate()
      failures.append(f"worker {index}: timeout")
      continue
    if process.returncode != 0:
      failures.append(
          f"worker {index}: rc={process.returncode}: {stderr[-800:]}")
      continue
    lines = [line for line in stdout.strip().splitlines() if line.strip()]
    try:
      workers.append(json.loads(lines[-1]))
    except (IndexError, ValueError):
      failures.append(f"worker {index}: no summary line")
  if failures:
    raise RuntimeError("fleetobs workers failed: " + "; ".join(failures))
  workers_wall = time.perf_counter() - start

  watchdog = watchdog_controls(logdir, ci=ci)
  fleet = aggregate_logdir(logdir)

  # Self-checks — the committed artifact's acceptance bars, enforced
  # at generation time so a regression cannot produce a green-looking
  # artifact.
  worker_pids = {worker["pid"] for worker in workers}
  stream_pids = {entry["pid"] for entry in fleet["per_host"].values()}
  assert worker_pids <= stream_pids, (
      f"metrics streams missing for workers: {worker_pids - stream_pids}")
  assert fleet["hosts_merged"] >= num_workers, fleet["hosts_merged"]
  worker_entries = [entry for entry in fleet["per_host"].values()
                    if entry["pid"] in worker_pids]
  for entry in worker_entries:
    assert entry["step_series"], entry  # a per-host series per pid
  assert fleet["slo"]["consistent"], fleet["slo"]
  shed_from_workers = sum(worker["shed"] for worker in workers)
  assert fleet["slo"]["shed_total"] >= shed_from_workers, (
      fleet["slo"]["shed_total"], shed_from_workers)
  # The rollup must agree with the per-process JSONL streams too: each
  # worker's final shed_total gauge (written after its breach) sums to
  # the merged per-class shed rollup.
  jsonl_shed = sum(entry["gauges"].get("serving/shed_total", 0)
                   for entry in worker_entries)
  assert int(jsonl_shed) == fleet["slo"]["shed_total"], (
      jsonl_shed, fleet["slo"]["shed_total"])
  assert fleet["trace"]["linked_serve_timelines"] >= 1, fleet["trace"]
  assert watchdog["injected_stall"]["ok"], watchdog
  assert watchdog["healthy_control"]["ok"], watchdog
  # The watchdog dumps land under the logdir, so the flightrec rollup
  # must see them alongside the workers' breach dumps.
  assert fleet["flightrec"]["reasons"].get("watchdog_stall", 0) >= 1
  assert fleet["flightrec"]["reasons"].get("slo_breach", 0) >= 1

  return {
      "round": 13,
      "schema": SCHEMA,
      "metric": ("fleet observability: cross-process metric/trace "
                 "merge, correlation-linked request timelines, "
                 "stall/straggler watchdog"),
      "protocol": (f"{num_workers} subprocess serve loops "
                   "(8-virtual-device CPU mesh each, cpu_mesh_env "
                   "re-exec) against one shared logdir + in-process "
                   "watchdog controls + aggregate_logdir merge"),
      "virtual_mesh": True,
      "workers": workers,
      "workers_wall_s": round(workers_wall, 2),
      "watchdog": watchdog,
      "fleet": fleet,
      "note": (
          "Chipless honesty (the MULTICHIP caveat applied to the "
          "fleet merge): every worker's 8 'devices' are virtual CPU "
          "devices sharing this host's cores, so latency percentiles "
          "and step rates are host numbers — the structural claims "
          "(per-process streams merge, one percentile source, "
          "correlation flows link across threads/processes, the "
          "watchdog catches an injected stall and stays silent on a "
          "healthy loop) are what this artifact commits. step_rate "
          "stragglers are reported against the fleet median but not "
          "asserted at N=2."),
  }


def main(argv=None) -> None:
  """CLI: aggregate a fleet logdir, or run the FLEETOBS protocol.

      # merge an existing fleet logdir into one view
      python -m tensor2robot_tpu.bin.obs_aggregate --logdir DIR --out F.json

      # the committed FLEETOBS_r13 protocol (chipless)
      python -m tensor2robot_tpu.bin.obs_aggregate --smoke --out FLEETOBS_r13.json
  """
  import argparse
  import sys

  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument("--logdir", default=None,
                      help="fleet logdir to aggregate (or the shared "
                           "dir for --smoke/--worker)")
  parser.add_argument("--out", default=None,
                      help="also write the JSON line to this file")
  parser.add_argument("--smoke", action="store_true",
                      help="run the committed FLEETOBS protocol: >= 2 "
                           "subprocess loops + watchdog controls + merge")
  parser.add_argument("--ci", action="store_true",
                      help="reduced tier-1 lane of the same protocol")
  parser.add_argument("--worker", type=int, default=None,
                      help="internal: run one fleet worker process")
  parser.add_argument("--seed", type=int, default=0)
  parser.add_argument("--duration", type=float, default=None,
                      help="worker serve-window seconds")
  args = parser.parse_args(argv)

  if args.worker is not None:
    if args.logdir is None:
      parser.error("--worker needs --logdir")
    ladder = (1, 2) if args.ci else (1, 2, 4)
    _run_worker(args.worker, args.logdir, seed=args.seed,
                duration_s=args.duration or 2.0, ladder_sizes=ladder,
                slow_factor=3.0 if args.worker else 1.0)
    return

  if args.smoke or args.ci:
    results = measure_fleetobs(
        num_workers=2,
        duration_s=args.duration or (1.0 if args.ci else 3.0),
        ladder_sizes=(1, 2) if args.ci else (1, 2, 4),
        seed=args.seed, logdir=args.logdir, ci=args.ci)
  else:
    if args.logdir is None:
      parser.error("--logdir is required without --smoke/--ci")
    results = aggregate_logdir(args.logdir)
  line = json.dumps(results)
  if args.out:
    with open(args.out, "w") as f:
      f.write(line + "\n")
  print(line)


if __name__ == "__main__":
  main()

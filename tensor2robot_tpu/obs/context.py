"""Request-scoped correlation ids for cross-process causality (ISSUE 12).

A fleet request's life crosses threads and tiers: the client thread
that enqueued it, the EDF heap it waited in, the dispatcher that
flushed it, the device the replica dispatched to, sometimes a
``rollout_mirror`` twin riding the shadow path. Before this module
those hops produced disconnected spans — "where did request X spend
its 40ms" required manual timestamp archaeology.

The fix is one process-local identity layer:

- ``new_request_id()`` mints a globally unique id at *ingress* —
  ``FleetServer.submit`` / ``FleetRouter.submit`` / a bare
  ``MicroBatcher.submit`` — stamped with host + pid so ids stay
  distinct across the processes a fleet logdir merges.
- ``bind(request_id=..., step_id=...)`` carries the identity in a
  ``contextvars.ContextVar``; every ``obs.trace.span`` completed while
  bound automatically carries the bound ids as span attrs (explicit
  span attrs win on collision).
- The batcher threads the id onto its pending-request record, so the
  dispatcher side (a DIFFERENT thread — contextvars do not cross) can
  re-bind it around the flush: ``serve/flush`` spans carry the whole
  batch's ids as a comma-joined ``request_ids`` attr, and anything the
  flush calls into (the replica's device dispatch) inherits them.

``Tracer.export_chrome_trace`` turns the ids into Perfetto *flow
events*: every request id seen on >= 2 spans becomes one clickable
arrow chain linking enqueue → flush → dispatch across thread lanes.
The flight recorder's dumps carry the triggering request's id, so a
shed's post-mortem names the exact request that breached.

Everything here is host-side and allocation-light: a bind is one
ContextVar.set, an id is a counter increment — safe on the serving
hot path.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import socket
from typing import Dict, Iterable, Optional

# One ContextVar per correlation field. `request_id` identifies one
# client request end to end; `step_id` identifies one loop step (the
# replay loop binds its optimizer step so learner-side spans join the
# same timeline view); `request_ids` is the batch-side form a flush
# binds for the spans that serve MANY requests at once.
_REQUEST_ID: contextvars.ContextVar = contextvars.ContextVar(
    "t2r_request_id", default=None)
_REQUEST_IDS: contextvars.ContextVar = contextvars.ContextVar(
    "t2r_request_ids", default=None)
_STEP_ID: contextvars.ContextVar = contextvars.ContextVar(
    "t2r_step_id", default=None)

_SEQ = itertools.count()
# Short stable host tag; pid is read per-mint so a fork cannot reuse
# the parent's id space.
_HOST = socket.gethostname().split(".", 1)[0]


def new_request_id() -> str:
  """Mints one fleet-unique request id: ``<host>-<pid>-<seq>``."""
  return f"{_HOST}-{os.getpid()}-{next(_SEQ)}"


def current_request_id() -> Optional[str]:
  return _REQUEST_ID.get()


def current_step_id() -> Optional[int]:
  return _STEP_ID.get()


def context_attrs() -> Dict[str, object]:
  """The currently bound correlation attrs (empty dict when unbound).

  This is the tracer's per-span read: two ContextVar.get calls on the
  hot path, dict construction only when something is actually bound.
  """
  request_id = _REQUEST_ID.get()
  request_ids = _REQUEST_IDS.get()
  step_id = _STEP_ID.get()
  if request_id is None and request_ids is None and step_id is None:
    return {}
  attrs: Dict[str, object] = {}
  if request_id is not None:
    attrs["request_id"] = request_id
  if request_ids is not None:
    attrs["request_ids"] = request_ids
  if step_id is not None:
    attrs["step_id"] = step_id
  return attrs


@contextlib.contextmanager
def bind(request_id: Optional[str] = None,
         request_ids: Optional[str] = None,
         step_id: Optional[int] = None):
  """Binds correlation ids for the duration of the ``with`` block.

  Only the fields given are (re)bound; the rest keep their current
  values, so a nested bind of ``step_id`` does not drop an enclosing
  ``request_id``. ``request_ids`` is the comma-joined batch form the
  dispatcher binds around a flush.
  """
  tokens = []
  try:
    if request_id is not None:
      tokens.append((_REQUEST_ID, _REQUEST_ID.set(request_id)))
    if request_ids is not None:
      tokens.append((_REQUEST_IDS, _REQUEST_IDS.set(request_ids)))
    if step_id is not None:
      tokens.append((_STEP_ID, _STEP_ID.set(int(step_id))))
    yield
  finally:
    for var, token in reversed(tokens):
      var.reset(token)


def join_ids(ids: Iterable[Optional[str]]) -> str:
  """The canonical batch encoding: comma-joined, Nones dropped (span
  attrs must stay JSON scalars; the trace exporter splits on ",")."""
  return ",".join(i for i in ids if i)


def span_request_ids(record: dict) -> Iterable[str]:
  """Every request id a completed span record carries — the single
  decoder for the ``request_id`` / ``request_ids`` attr convention
  (used by the Chrome-trace flow linker and the fleet aggregator)."""
  single = record.get("request_id")
  if single:
    yield single
  many = record.get("request_ids")
  if many:
    for part in str(many).split(","):
      if part and part != single:
        yield part

"""Deterministic fault injection: every failure mode as a test input.

The fleet's observability spine (PR 8/9) can SEE failures; proving the
fleet *recovers* from them needs failures on demand — reproducibly, so
a chaos artifact's bars are re-runnable, and through explicit seams, so
the injection points are the same code paths real faults travel (no
monkeypatching: a patched method proves nothing about the unpatched
fleet).

A ``FaultPlan`` is a seeded schedule of ``FaultSpec`` entries. Each
spec names:

- a **kind** (what goes wrong): ``dispatch_error`` (a replica's device
  call raises), ``latency_spike`` (a dispatch stalls for latency_s),
  ``hung_flush`` (a batcher flush wedges), ``thread_kill`` (a
  dispatcher thread dies mid-flush — raised as a ``BaseException`` so
  it models the deaths ordinary ``except Exception`` recovery cannot
  catch), ``export_corrupt`` / ``export_partial_write`` (an export
  artifact lands damaged on disk), ``crash`` (the learner process dies
  at an optimizer step);
- a **point** (which seam checks it): components with a plan installed
  call ``plan.perturb(point, site=...)`` at exactly one place each —
  ``PolicyReplica`` at ``replica_dispatch``, ``MicroBatcher`` at
  ``batcher_flush``, ``ExportWatcher`` at ``export_load`` (via
  ``check`` + ``damage_export``), ``ReplayTrainLoop`` at
  ``learner_step``;
- a **schedule**: ``at=N`` fires on the N-th check of that
  (point, site) — or, when the seam passes an explicit ``index``
  (the learner's optimizer step), on index == N — with ``every``/
  ``count`` for repetition, or ``probability`` for a seeded Bernoulli
  per check. Same plan + same call sequence ⇒ the same faults fire at
  the same places, every run.

Every fired fault is recorded on the plan (``plan.fired``) AND triggers
a flight-recorder dump (reason ``fault_injected``) stamped with the
ACTIVE correlation id (obs/context.py) — so a chaos run's post-mortems
name the exact request each injected fault hit, exactly like a real
incident's would.

The no-plan case is the oracle: every seam's check is
``if plan is None: return`` — components without a plan installed
execute the identical instruction stream they did before this module
existed.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tensor2robot_tpu.obs import context as context_lib
from tensor2robot_tpu.obs import flight_recorder as flight_lib

# The closed set of failure modes a plan can schedule. Adding one means
# adding the seam that honors it — an unknown kind is a typo, not a
# silently inert spec.
FAULT_KINDS = (
    "dispatch_error",         # replica device call raises InjectedFault
    "latency_spike",          # dispatch sleeps latency_s, then proceeds
    "hung_flush",             # batcher flush wedges latency_s
    "thread_kill",            # dispatcher thread dies (InjectedKill)
    "export_corrupt",         # export npz overwritten with garbage
    "export_partial_write",   # export npz truncated mid-file
    "crash",                  # learner raises InjectedCrash at a step
    "nan_grads",              # learner data poisoned non-finite (ISSUE 15)
    "value_scale",            # learner values scaled finite-but-wrong
    "corrupt_served_variables",  # replica serves a corrupted param tree
)

# The SILENT kinds (ISSUE 15): they never raise or stall — they corrupt
# DATA and keep running, which is exactly the failure mode the health
# sentinel (obs/health.py) exists to catch. ``perturb`` returns the
# fired numeric specs so the owning seam can apply the corruption to
# its own state: the learner seams poison targets / params
# (`apply_numeric_to_targets` / `corrupt_train_state`), the replica
# dispatch seam installs a corrupted served-variables tree
# (`corrupt_variables`) that still returns plausible finite numbers —
# the botched-hot-swap model the fleet Q-drift guard detects.
NUMERIC_KINDS = frozenset(
    {"nan_grads", "value_scale", "corrupt_served_variables"})


class InjectedFault(RuntimeError):
  """A scheduled, retryable fault (a replica dispatch error): ordinary
  ``Exception`` machinery — retries, circuit breakers — must absorb it
  exactly as it would a real device error."""

  def __init__(self, kind: str, point: str, site: str):
    self.kind = kind
    self.point = point
    self.site = site
    super().__init__(f"injected {kind} at {point}[{site}]")


class InjectedKill(BaseException):
  """A scheduled thread death. Deliberately NOT an ``Exception``: the
  dispatcher's per-flush ``except Exception`` recovery must not absorb
  it — it models the class of deaths (KeyboardInterrupt on the wrong
  thread, MemoryError, a C-extension abort) only the thread-level
  death handler can account for."""

  def __init__(self, point: str, site: str):
    self.point = point
    self.site = site
    super().__init__(f"injected thread kill at {point}[{site}]")


class InjectedCrash(RuntimeError):
  """A scheduled learner crash at a named optimizer step — the
  preemption/OOM stand-in the checkpoint-resume path recovers from."""

  def __init__(self, step: int):
    self.step = step
    super().__init__(f"injected learner crash at optimizer step {step}")


@dataclasses.dataclass(frozen=True)
class FaultSpec:
  """One scheduled fault (see module docstring for the field grammar).

  Attributes:
    kind: one of FAULT_KINDS.
    point: the seam that checks this spec ("replica_dispatch",
      "batcher_flush", "export_load", "learner_step").
    site: exact site match within the point ("" matches every site) —
      a device string for replicas, a batcher name, an export version.
    at: fire when the (point, site) check counter — or the seam's
      explicit ``index``, when it passes one — equals this value.
      None with probability=0 never fires (a disabled spec).
    every: after ``at``, also fire every `every` further checks
      (0 = fire at `at` only).
    count: total fire budget for this spec.
    probability: seeded Bernoulli per check (alternative to `at`;
      deterministic given the plan seed and the call sequence).
    latency_s: stall duration for latency_spike / hung_flush.
    scale: corruption factor for the numeric kinds — value_scale
      multiplies the learner's Bellman targets by it,
      corrupt_served_variables scales a replica's served float params
      by it (finite, plausible, wrong). Ignored by the other kinds.
  """

  kind: str
  point: str
  site: str = ""
  at: Optional[int] = None
  every: int = 0
  count: int = 1
  probability: float = 0.0
  latency_s: float = 0.0
  scale: float = 8.0

  def __post_init__(self):
    if self.kind not in FAULT_KINDS:
      raise ValueError(
          f"unknown fault kind {self.kind!r}; known: {FAULT_KINDS}")
    if self.at is None and self.probability <= 0.0:
      raise ValueError(
          f"spec {self.kind}@{self.point} has no schedule: set `at` "
          "or a positive `probability`")
    if self.probability < 0.0 or self.probability > 1.0:
      raise ValueError(f"probability must be in [0, 1], got "
                       f"{self.probability}")
    if self.count < 1:
      raise ValueError(f"count must be >= 1, got {self.count}")


class FaultPlan:
  """A seeded, deterministic schedule of faults over explicit seams.

  Thread-safe: seams fire from dispatcher threads, collector threads,
  and the learner concurrently; counters and fire budgets are guarded
  by one lock (held only for the bookkeeping — sleeps and raises
  happen outside it).
  """

  def __init__(self, specs: Sequence[FaultSpec], seed: int = 0,
               recorder: Optional[flight_lib.FlightRecorder] = None):
    self.specs = tuple(specs)
    self.seed = seed
    self._recorder = recorder
    self._lock = threading.Lock()
    self._counters: Dict[Tuple[str, str], int] = {}
    # Per-spec state: independent seeded rng (probability draws stay
    # deterministic per spec regardless of the other specs' traffic)
    # and the remaining fire budget.
    self._rngs = [np.random.default_rng(seed * 1_000_003 + i)
                  for i in range(len(self.specs))]
    self._remaining = [spec.count for spec in self.specs]
    self.fired: List[dict] = []

  def _matches(self, spec: FaultSpec, spec_index: int, point: str,
               site: str, tick: int) -> bool:
    """Caller holds the lock. `tick` is the schedule position: the
    seam's explicit index when given, else the (point, site) counter."""
    if spec.point != point:
      return False
    if spec.site and spec.site != site:
      return False
    if self._remaining[spec_index] <= 0:
      return False
    if spec.at is not None:
      if tick < spec.at:
        return False
      offset = tick - spec.at
      return offset == 0 or (spec.every > 0 and offset % spec.every == 0)
    return float(self._rngs[spec_index].random()) < spec.probability

  def check(self, point: str, site: str = "",
            index: Optional[int] = None) -> List[FaultSpec]:
    """Advances the (point, site) schedule one tick; returns the specs
    that fire on it (fire records + flightrec dumps included). Seams
    with bespoke fault actions (the export watcher) call this and act
    on the result; everything else uses ``perturb``."""
    fired: List[FaultSpec] = []
    with self._lock:
      key = (point, site)
      counter = self._counters.get(key, 0)
      self._counters[key] = counter + 1
      tick = counter if index is None else int(index)
      for i, spec in enumerate(self.specs):
        if self._matches(spec, i, point, site, tick):
          self._remaining[i] -= 1
          fired.append(spec)
    for spec in fired:
      self._record_fire(spec, point, site, tick)
    return fired

  def _record_fire(self, spec: FaultSpec, point: str, site: str,
                   tick: int) -> None:
    # The active correlation id rides the dump (ISSUE 14 contract):
    # a fault fired inside a replica flush carries the batch's
    # request_ids; one fired at the router front door carries the
    # single request_id; the learner's carries neither (step-scoped).
    attrs = context_lib.context_attrs()
    record = {
        "kind": spec.kind, "point": point, "site": site, "tick": tick,
        "wall_time": time.time(),
    }
    record.update({k: attrs[k] for k in ("request_id", "request_ids")
                   if k in attrs})
    with self._lock:
      self.fired.append(record)
    recorder = self._recorder or flight_lib.get_recorder()
    try:
      recorder.trigger("fault_injected", fault=spec.kind, point=point,
                       site=site, tick=tick,
                       **{k: v for k, v in record.items()
                          if k in ("request_id", "request_ids")})
    except Exception:
      pass  # diagnostics never break the injection (listener contract)

  def perturb(self, point: str, site: str = "",
              index: Optional[int] = None) -> List[FaultSpec]:
    """The one-line seam: check the schedule and ACT on what fires —
    sleep for latency faults, raise for error/kill/crash faults. When
    several specs fire on one tick, stalls apply first (a fault that
    both delays and then fails models a timing-out dispatch).

    Returns the fired NUMERIC specs (NUMERIC_KINDS): those never raise
    or stall here — the seam owns the corruption (targets, params, a
    served-variables tree) and applies it with the helpers below. A
    numeric spec co-scheduled with a raising kind on the same tick is
    lost to the raise; schedule silent and loud faults on distinct
    ticks."""
    fired = self.check(point, site, index=index)
    if not fired:
      return []
    for spec in fired:
      if spec.kind in ("latency_spike", "hung_flush") and spec.latency_s:
        time.sleep(spec.latency_s)
    for spec in fired:
      if spec.kind == "dispatch_error":
        raise InjectedFault(spec.kind, point, site)
      if spec.kind == "thread_kill":
        raise InjectedKill(point, site)
      if spec.kind == "crash":
        raise InjectedCrash(index if index is not None else -1)
    return [spec for spec in fired if spec.kind in NUMERIC_KINDS]

  def fired_counts(self) -> Dict[str, int]:
    """{kind: times fired} — the chaos artifact's injection ledger."""
    with self._lock:
      counts: Dict[str, int] = {}
      for record in self.fired:
        counts[record["kind"]] = counts.get(record["kind"], 0) + 1
      return counts

  def snapshot(self) -> dict:
    with self._lock:
      return {
          "seed": self.seed,
          "specs": [dataclasses.asdict(spec) for spec in self.specs],
          "fired": [dict(record) for record in self.fired],
      }


def apply_numeric_to_targets(targets, specs: Sequence[FaultSpec]):
  """Applies fired numeric specs to a host Bellman-target batch (the
  host learner seam's corruption point): ``nan_grads`` poisons one
  label with NaN — the loss mean goes NaN, so the REAL backward pass
  produces genuinely non-finite gradients, not a simulated flag;
  ``value_scale`` multiplies every target by spec.scale (a finite
  value explosion the drift rules must catch). Returns a fresh array;
  the input is never mutated."""
  out = np.asarray(targets, np.float32).copy()
  for spec in specs:
    if spec.kind == "nan_grads":
      out.reshape(-1)[0] = np.nan
    elif spec.kind == "value_scale":
      out = out * np.float32(spec.scale)
  return out


def corrupt_train_state(state, specs: Sequence[FaultSpec]):
  """Applies fired numeric specs to a fused learner's TrainState (the
  anakin/megastep seam, between dispatches — donated device state has
  no in-program seam, so corruption lands where a preemption-era
  memory fault would: on the carried params). ``nan_grads`` NaNs the
  first param leaf (the next learn iteration's forward, loss, and
  gradients all go genuinely non-finite); ``value_scale`` scales every
  float param leaf by spec.scale (finite Q explosion). Returns a new
  TrainState; shardings ride along with the elementwise ops."""
  import jax
  import jax.numpy as jnp

  params = state.params
  for spec in specs:
    if spec.kind == "nan_grads":
      leaves, treedef = jax.tree_util.tree_flatten(params)
      # Leaf-dtype NaN: a strongly-typed f32 NaN would silently
      # promote a bf16/f64 leaf and the next dispatch's AOT executable
      # would reject the drifted aval instead of detecting the NaN.
      leaves = [leaves[0] * jnp.asarray(jnp.nan, leaves[0].dtype)
                ] + leaves[1:]
      params = jax.tree_util.tree_unflatten(treedef, leaves)
    elif spec.kind == "value_scale":
      params = jax.tree_util.tree_map(
          lambda leaf: leaf * jnp.asarray(spec.scale, leaf.dtype)
          if jnp.issubdtype(leaf.dtype, jnp.floating) else leaf,
          params)
  return state.replace(params=params)


def corrupt_variables(variables, scale: float):
  """A finite-but-wrong copy of a served variables pytree: every float
  leaf scaled by ``scale`` — the ``corrupt_served_variables`` model of
  a botched ``set_variables`` hot-swap. The replica keeps answering
  with plausible numbers; only the fleet Q-drift guard (cross-replica
  served-Q divergence) can see it."""
  import jax
  import jax.numpy as jnp

  return jax.tree_util.tree_map(
      lambda leaf: leaf * jnp.asarray(scale, leaf.dtype)
      if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating) else leaf,
      variables)


def damage_export(export_dir: str, kind: str,
                  filename: Optional[str] = None) -> str:
  """Applies an export_corrupt / export_partial_write fault to a
  published export dir, deterministically: corrupt = the variables npz
  overwritten with non-npz bytes (a bitrotted artifact), partial_write
  = truncated to half length (a writer killed mid-copy — the failure
  async export's tmp→mv normally prevents, modeled here for consumers
  that must still survive a broken publisher). Returns the damaged
  path. The watcher-side validation (serving/rollout.ExportWatcher)
  must reject either damage with a flight-recorder record and never
  swap it in."""
  if filename is None:
    from tensor2robot_tpu.export.native_export_generator import (
        VARIABLES_NPZ)
    filename = VARIABLES_NPZ
  path = os.path.join(export_dir, filename)
  if kind == "export_corrupt":
    with open(path, "wb") as f:
      f.write(b"not-an-npz\x00" * 16)
  elif kind == "export_partial_write":
    size = os.path.getsize(path)
    with open(path, "rb+") as f:
      f.truncate(max(1, size // 2))
  else:
    raise ValueError(f"damage_export got non-export kind {kind!r}")
  return path

"""Flight recorder: the last N spans/events, dumped on failure.

A production fleet's worst bugs are the ones whose evidence scrolled
away: the SLO breach that shed a burst of interactive traffic, the
canary that auto-rolled-back, the collector thread that died at 3am.
The recorder keeps a BOUNDED in-memory ring of recent events (completed
spans via a tracer listener, plus explicit ``record`` calls from the
serving/replay/rollout layers) and dumps it ATOMICALLY to
``<dump_dir>/flightrec-*.json`` when a trigger fires:

- SLO breach: any shed in ``serving.batcher.MicroBatcher`` (expired at
  enqueue or capacity eviction);
- rollout auto-rollback (``serving.rollout.RolloutController``);
- an unhandled exception in any loop thread (batcher dispatcher,
  rollout worker, collector threads, the replay train loop).

Dumps are rate-limited (``min_dump_interval_s``) so an overload burst
produces one post-mortem, not a dump per shed — every trigger is still
RECORDED in the ring either way. Without a configured ``dump_dir`` the
recorder runs ring-only (record everything, write nothing): safe to
wire into every component by default.

Dump schema (``docs/ARTIFACTS.md`` round-12 section)::

    {"schema": "t2r-flightrec-1", "host": ..., "pid": ...,
     "reason": ..., "dumped_at": <unix s>, "events_total": N,
     "trigger": {<the triggering event's fields>},   # when triggered
     "request_id": ...,   # when the trigger named one (ISSUE 12)
     "events": [{"t_s": ..., "wall_time": ..., "kind":
                 "span"|"event"|"trigger", "name": ..., ...}, ...]}
"""

from __future__ import annotations

import json
import logging
import os
import re
import socket
import threading
import time
from collections import deque
from typing import Optional

_log = logging.getLogger(__name__)

SCHEMA = "t2r-flightrec-1"

# Per-process monotonic dump sequence, shared across ALL recorder
# instances (two recorders pointed at one dir must not coalesce
# either). See dump() — ISSUE 19.
import itertools

_DUMP_SEQ = itertools.count()
_SEQ_LOCK = threading.Lock()


class FlightRecorder:
  """Bounded event ring with rate-limited atomic post-mortem dumps."""

  def __init__(self, capacity: int = 4096,
               dump_dir: Optional[str] = None,
               min_dump_interval_s: float = 5.0):
    self._events: deque = deque(maxlen=capacity)
    self._lock = threading.Lock()
    self._epoch = time.perf_counter()
    self.dump_dir = dump_dir
    self.min_dump_interval_s = min_dump_interval_s
    self._last_dump_at = -float("inf")
    self.events_total = 0
    self.dumps_written = 0
    self.dumps_suppressed = 0
    self.last_dump_path: Optional[str] = None

  def configure(self, dump_dir: Optional[str] = None,
                min_dump_interval_s: Optional[float] = None) -> None:
    """Late wiring for the process-default recorder: components record
    from construction; dumps start once someone (the owning loop/bench)
    names a directory.

    Repointing an already-configured recorder at a DIFFERENT directory
    logs a warning: on the shared process recorder that is
    last-configured-wins — the previous owner's triggers now dump into
    the new owner's logdir. Two loops in one process should each own a
    ``FlightRecorder`` instance instead (ReplayTrainLoop does since
    round 13) and leave the process recorder to the serving tier.
    """
    if dump_dir is not None:
      if self.dump_dir is not None and self.dump_dir != dump_dir:
        _log.warning(
            "flight recorder dump_dir repointed %r -> %r "
            "(last-configured-wins on a shared recorder; use "
            "per-component FlightRecorder instances to keep dumps "
            "apart)", self.dump_dir, dump_dir)
      self.dump_dir = dump_dir
    if min_dump_interval_s is not None:
      self.min_dump_interval_s = min_dump_interval_s

  # -- recording -----------------------------------------------------------

  def record(self, kind: str, name: str, **fields) -> None:
    event = {
        "t_s": round(time.perf_counter() - self._epoch, 6),
        "wall_time": time.time(),
        "kind": kind,
        "name": name,
    }
    for key, value in fields.items():
      event[key] = value if isinstance(
          value, (int, float, str, bool, type(None))) else repr(value)
    with self._lock:
      self._events.append(event)
      self.events_total += 1

  def record_span(self, span: dict) -> None:
    """Tracer-listener entry: completed spans join the ring. Attr
    values are sanitized like record()'s — a numpy scalar riding a
    span attr must not make a later dump's json.dump raise."""
    event = {}
    for key, value in span.items():
      event[key] = value if isinstance(
          value, (int, float, str, bool, type(None))) else repr(value)
    event["kind"] = "span"
    event["wall_time"] = time.time()
    with self._lock:
      self._events.append(event)
      self.events_total += 1

  def attach(self, tracer) -> None:
    tracer.add_listener(self.record_span)

  def detach(self, tracer) -> None:
    """Unsubscribes from the tracer (idempotent). Per-loop recorder
    instances attach for their run and MUST detach after it, or every
    later span in the process pays a listener call per dead loop."""
    tracer.remove_listener(self.record_span)

  def events(self) -> list:
    with self._lock:
      return list(self._events)

  # -- dumping -------------------------------------------------------------

  def dump(self, reason: str, dump_dir: Optional[str] = None,
           context: Optional[dict] = None) -> Optional[str]:
    """Writes the ring atomically (tmp → rename); returns the path, or
    None when no dump directory is configured. ``context`` (the
    triggering event's fields) lands top-level as ``trigger`` — a
    breach dump names its ``request_id`` without the reader fishing
    through the ring."""
    directory = dump_dir or self.dump_dir
    if directory is None:
      return None
    os.makedirs(directory, exist_ok=True)
    with self._lock:
      events = list(self._events)
      events_total = self.events_total
    slug = re.sub(r"[^A-Za-z0-9_-]+", "_", reason)[:48] or "unknown"
    # Monotonic per-process sequence (ISSUE 19): ms-stamped names alone
    # coalesce back-to-back dumps — two triggers inside one millisecond
    # (or two recorders sharing a dir) silently overwrote each other,
    # which is why the flywheel/health bars were stuck at "dumps >= 1".
    # N triggers now yield N files.
    with _SEQ_LOCK:
      seq = next(_DUMP_SEQ)
    path = os.path.join(
        directory,
        f"flightrec-{int(time.time() * 1e3)}-{seq:04d}-{slug}.json")
    payload = {
        "schema": SCHEMA,
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "reason": reason,
        "dumped_at": time.time(),
        "events_total": events_total,
        "events": events,
    }
    if context:
      payload["trigger"] = {
          key: value if isinstance(
              value, (int, float, str, bool, type(None))) else repr(value)
          for key, value in context.items()}
      if "request_id" in context:
        payload["request_id"] = payload["trigger"]["request_id"]
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
      # default=repr as a belt: a post-mortem writer must not itself
      # crash on an exotic value that slipped past sanitization.
      json.dump(payload, f, default=repr)
    os.replace(tmp, path)
    with self._lock:
      self.dumps_written += 1
      self.last_dump_path = path
    return path

  def trigger(self, reason: str, **fields) -> Optional[str]:
    """Records the trigger event, then dumps (rate-limited).

    Returns the dump path, or None when suppressed by the rate limit
    or when no dump_dir is configured — the trigger EVENT lands in the
    ring regardless, so the next written dump still carries it.
    """
    self.record("trigger", reason, **fields)
    now = time.perf_counter()
    with self._lock:
      if now - self._last_dump_at < self.min_dump_interval_s:
        self.dumps_suppressed += 1
        return None
      self._last_dump_at = now
    return self.dump(reason, context=fields)


_DEFAULT: Optional[FlightRecorder] = None
_DEFAULT_LOCK = threading.Lock()


def get_recorder() -> FlightRecorder:
  """The process-wide recorder; subscribed to the process tracer on
  first access so recent spans are always part of a post-mortem."""
  global _DEFAULT
  with _DEFAULT_LOCK:
    if _DEFAULT is None:
      _DEFAULT = FlightRecorder()
      from tensor2robot_tpu.obs import trace
      _DEFAULT.attach(trace.get_tracer())
    return _DEFAULT

"""Silent-failure sentinel: training/serving health as first-class data.

PR 11 made LOUD failures (crashes, dead dispatchers, corrupt exports) a
handled regime; the classic large-scale-training failure mode is
SILENT — non-finite grads, Q-value explosion, replay-priority collapse,
a replica serving plausible-but-wrong values after a bad hot-swap.
Nothing crashes; the loop trains on garbage for hours. This module is
the sentinel that pages instead, in three layers:

- **In-program health summaries**: a small FIXED-SHAPE pytree of scalar
  reductions per learn iteration — non-finite counts over grads /
  params / targets (``jnp.isfinite`` sums), global grad/param norms, TD
  and Q mean/max, replay priority entropy, and sample age — computed
  INSIDE the already-compiled learn bodies (the fused ``anakin_step`` /
  ``megastep`` scan carries them; the host loop assembles the same
  keys per optimizer step). Cost is a handful of reductions riding the
  existing metrics D2H: zero new executables in the fused ledgers,
  host-blocked unchanged.
- **``HealthMonitor`` + declarative ``HealthRule``s**: a hard
  nonfinite==0 rule, EWMA/z-score drift rules for grad norm / TD / Q,
  and staleness / priority-entropy bound rules, escalating through the
  existing rails — registry counters (``health/...``) → a
  schema-validated ``health_breach`` flight-recorder dump carrying the
  step and any bound correlation ids → an optional callback → an
  optional auto-action that snapshots a checkpoint (the PR 11
  machinery) and, configurably, HALTS (``HealthHalt``) rather than
  training on garbage.
- **Fleet Q-drift guard**: per-replica streaming quantile sketches of
  served Q-values (``serving.stats.ServingStats``) compared against
  the fleet median (``q_drift_report``) — the check that catches a
  corrupted replica or a botched ``set_variables`` that still returns
  finite numbers. The router rolls the verdict into
  ``health_snapshot()`` and fires ``replica_divergent``;
  ``obs/aggregate.py`` runs the same report fleet-wide across
  processes.

The Podracer and pjit/TPUv4 scaling papers (PAPERS.md) both treat
cheap in-program health reductions as the precondition for running
fused/multi-host loops unattended — this module is that precondition
for ROADMAP item 1's operating mode.
"""

from __future__ import annotations

import dataclasses
import math
import statistics
import threading
from typing import Callable, Dict, List, Mapping, Optional, Sequence

from tensor2robot_tpu.obs import context as context_lib
from tensor2robot_tpu.obs import flight_recorder as flight_lib
from tensor2robot_tpu.obs import registry as registry_lib

# The fixed health-summary schema every learn path emits (fused bodies
# compute these in-program; the host loop assembles the same keys from
# its per-step host data). One schema — a rule written against a key
# holds on every loop path.
SUMMARY_KEYS = (
    "health/nonfinite_grads",
    "health/nonfinite_params",
    "health/nonfinite_targets",
    "health/grad_norm",
    "health/param_norm",
    "health/td_mean",
    "health/td_max",
    "health/q_mean",
    "health/q_max",
    "health/priority_entropy",
    "health/sample_age",
)

# Keys aggregated by RUNNING MAX across a fused scan's inner
# iterations (a transient mid-scan NaN or spike must survive to the
# dispatch boundary); the rest report the last trained iteration.
SCAN_MAX_KEYS = frozenset({
    "health/nonfinite_grads",
    "health/nonfinite_params",
    "health/nonfinite_targets",
    "health/grad_norm",
    "health/td_max",
    "health/q_max",
})

# Event schema for health_breach flight-recorder triggers — the
# aggregator validates dumps against these fields (the watchdog's
# STALL_FIELDS convention).
BREACH_FIELDS = ("rule", "metric", "value", "step")


class HealthHalt(RuntimeError):
  """Raised by a halting HealthMonitor breach: the loop stops INSTEAD
  of training on garbage. Carries the breaches that tripped it."""

  def __init__(self, step: int, breaches: List[dict]):
    self.step = step
    self.breaches = breaches
    names = ", ".join(sorted({b["rule"] for b in breaches}))
    super().__init__(
        f"health halt at step {step}: breached [{names}] — halting "
        "rather than training on garbage (see the health_breach "
        "flight-recorder dump)")


# -- pure jittable reductions (the in-program summary pieces) ---------------


def tree_nonfinite_count(tree):
  """Total non-finite elements across a pytree's float leaves, as one
  f32 scalar (jittable — the hard-rule input, computed in-program)."""
  import jax
  import jax.numpy as jnp

  total = jnp.zeros((), jnp.float32)
  for leaf in jax.tree_util.tree_leaves(tree):
    leaf = jnp.asarray(leaf)
    if jnp.issubdtype(leaf.dtype, jnp.floating):
      total = total + jnp.sum(~jnp.isfinite(leaf)).astype(jnp.float32)
  return total


def tree_global_norm(tree):
  """Global L2 norm over a pytree's float leaves (f32, jittable)."""
  import jax
  import jax.numpy as jnp

  total = jnp.zeros((), jnp.float32)
  for leaf in jax.tree_util.tree_leaves(tree):
    leaf = jnp.asarray(leaf)
    if jnp.issubdtype(leaf.dtype, jnp.floating):
      total = total + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
  return jnp.sqrt(total)


def merge_scan_metrics(new: Dict, old: Dict, gate):
  """Per-key scan-carry merge for the fused loops: when ``gate`` is
  true the SCAN_MAX_KEYS keep their running max (a spike inside the
  scan survives to the dispatch readout) and every other key takes the
  new value; when false the old carry rides through unchanged."""
  import jax.numpy as jnp

  out = {}
  for key, new_value in new.items():
    old_value = old[key]
    if key in SCAN_MAX_KEYS:
      out[key] = jnp.where(gate, jnp.maximum(new_value, old_value),
                           old_value)
    else:
      out[key] = jnp.where(gate, new_value, old_value)
  return out


def reduce_scanned_metrics(stacked: Dict):
  """The megastep form of the same aggregation: metrics stacked along
  the scan axis reduce per key — max for SCAN_MAX_KEYS, last
  otherwise (the host-loop last-step convention)."""
  return {key: (value.max(axis=0) if key in SCAN_MAX_KEYS
                else value[-1])
          for key, value in stacked.items()}


def zero_summary():
  """The fixed-shape all-zeros summary (the fused loops' scan-carry
  init; also the 'never trained yet' placeholder)."""
  import jax.numpy as jnp

  return {key: jnp.zeros((), jnp.float32) for key in SUMMARY_KEYS}


# -- declarative rules ------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class HealthRule:
  """One declarative check over one summary metric.

  Attributes:
    name: rule id (registry counter ``health/<name>``, breach field).
    metric: the summary key it watches.
    kind: "max" (hard ceiling: value > limit breaches — the
      nonfinite==0 rule is ``max`` with limit 0), "min" (floor:
      value < limit breaches — the priority-entropy collapse rule), or
      "drift" (EWMA z-score: |value - ewma_mean| / ewma_std >
      z_threshold after ``warmup`` observations).
    limit: the bound for max/min rules.
    z_threshold / ewma_alpha / min_std / min_rel_std: drift-rule
      statistics. The z denominator is floored at
      ``max(min_std, min_rel_std * |ewma_mean|)`` — a healthy series
      that settles to near-constant values must not turn ordinary
      fluctuation into a breach just because its variance collapsed
      (the false-positive mode of a raw z-score). The EWMA state
      FREEZES on a breaching observation, so persistent corruption
      keeps breaching instead of teaching the baseline to accept it.
    warmup: observations before min/drift rules arm (a cold loop's
      first steps are legitimately wild; hard max rules with warmup 0
      are always armed — a NaN is never a warm-up artifact).
    halt: a breach of this rule escalates to HealthHalt when the
      monitor runs with halt_on_breach.
  """

  name: str
  metric: str
  kind: str = "max"
  limit: float = 0.0
  z_threshold: float = 6.0
  ewma_alpha: float = 0.1
  min_std: float = 1e-3
  min_rel_std: float = 0.25
  warmup: int = 10
  halt: bool = False

  def __post_init__(self):
    if self.kind not in ("max", "min", "drift"):
      raise ValueError(f"unknown rule kind {self.kind!r}; "
                       "known: max, min, drift")


def default_rules(capacity: Optional[int] = None) -> tuple:
  """The sentinel's default rule set (ISSUE 15): hard nonfinite==0
  everywhere numbers can go non-finite, drift rules on grad norm / TD /
  Q (the value-explosion detectors), a priority-entropy floor (replay
  priority collapse: one transition dominating the sampling
  distribution), and — when the ring capacity is known — a sample-age
  ceiling (replay gone stale: the learner replaying ancient data while
  ingest silently died)."""
  rules = [
      HealthRule("nonfinite_grads", "health/nonfinite_grads",
                 kind="max", limit=0.0, warmup=0, halt=True),
      HealthRule("nonfinite_params", "health/nonfinite_params",
                 kind="max", limit=0.0, warmup=0, halt=True),
      HealthRule("nonfinite_targets", "health/nonfinite_targets",
                 kind="max", limit=0.0, warmup=0, halt=True),
      HealthRule("grad_norm_drift", "health/grad_norm", kind="drift",
                 z_threshold=8.0, warmup=10),
      HealthRule("td_drift", "health/td_mean", kind="drift",
                 z_threshold=8.0, warmup=10),
      HealthRule("q_drift", "health/q_max", kind="drift",
                 z_threshold=8.0, warmup=10),
      HealthRule("priority_entropy_floor", "health/priority_entropy",
                 kind="min", limit=0.05, warmup=10),
  ]
  if capacity is not None:
    rules.append(HealthRule("sample_age_ceiling", "health/sample_age",
                            kind="max", limit=float(8 * capacity),
                            warmup=5))
  return tuple(rules)


class _DriftState:
  """EWMA mean/variance for one drift rule (exponentially weighted
  moments, Welford-style update)."""

  __slots__ = ("n", "mean", "var")

  def __init__(self):
    self.n = 0
    self.mean = 0.0
    self.var = 0.0

  def update(self, value: float, alpha: float) -> None:
    if self.n == 0:
      self.mean = value
    else:
      delta = value - self.mean
      self.mean += alpha * delta
      self.var = (1.0 - alpha) * (self.var + alpha * delta * delta)
    self.n += 1

  def std(self, min_std: float, min_rel_std: float = 0.0) -> float:
    return max(math.sqrt(max(self.var, 0.0)), min_std,
               min_rel_std * abs(self.mean))


class HealthMonitor:
  """Evaluates HealthRules over per-step summaries; escalates breaches.

  Escalation per breach, every hop exception-isolated (the PR 8
  listener contract — diagnostics never crash the observed loop):

    registry counters (``health/breaches`` + ``health/<rule>``)
    → rate-limited ``health_breach`` flight-recorder dump (BREACH_FIELDS
      schema, stamped with the step and any bound correlation ids)
    → optional ``on_breach`` callback
    → optional ``snapshot_fn`` (the loop's checkpoint machinery: freeze
      the last-known state beside the post-mortem)
    → with ``halt_on_breach``, a breach of a ``halt`` rule raises
      ``HealthHalt`` AFTER the escalation above — the one hop that is
      deliberately NOT isolated, because stopping is the action.

  Thread-safety: observe() is called from one loop thread; the lock
  guards snapshot() readers.
  """

  def __init__(self, rules: Optional[Sequence[HealthRule]] = None,
               registry: Optional[registry_lib.MetricRegistry] = None,
               recorder: Optional[flight_lib.FlightRecorder] = None,
               on_breach: Optional[Callable[[dict], None]] = None,
               halt_on_breach: bool = False,
               max_breach_history: int = 256):
    self.rules = tuple(default_rules() if rules is None else rules)
    names = [rule.name for rule in self.rules]
    if len(set(names)) != len(names):
      raise ValueError(f"duplicate rule names: {sorted(names)}")
    self._registry = registry
    self._recorder = recorder
    self._on_breach = on_breach
    self.halt_on_breach = halt_on_breach
    self._lock = threading.Lock()
    self._drift: Dict[str, _DriftState] = {
        rule.name: _DriftState() for rule in self.rules
        if rule.kind == "drift"}
    self._seen: Dict[str, int] = {rule.name: 0 for rule in self.rules}
    self.observations = 0
    self.breaches: List[dict] = []
    self._max_breaches = max_breach_history
    self.breach_count = 0
    self.last_summary: Dict[str, float] = {}

  def _check_rule(self, rule: HealthRule, value: float,
                  step: int) -> Optional[dict]:
    """One rule against one value; updates rule state. Returns the
    breach record or None."""
    seen = self._seen[rule.name]
    self._seen[rule.name] = seen + 1
    breach: Optional[dict] = None
    if rule.kind == "max":
      if seen >= rule.warmup and value > rule.limit:
        breach = {"threshold": rule.limit}
    elif rule.kind == "min":
      if seen >= rule.warmup and value < rule.limit:
        breach = {"threshold": rule.limit}
    else:  # drift
      state = self._drift[rule.name]
      if state.n >= rule.warmup:
        std = state.std(rule.min_std, rule.min_rel_std)
        z = abs(value - state.mean) / std
        if z > rule.z_threshold:
          breach = {"z": round(z, 3), "ewma_mean": round(state.mean, 6),
                    "ewma_std": round(std, 6),
                    "threshold": rule.z_threshold}
      if breach is None:
        # Freeze the baseline on breach: persistent corruption must
        # keep breaching, not teach the EWMA its new normal.
        state.update(value, rule.ewma_alpha)
    if breach is None:
      return None
    breach.update({
        "rule": rule.name, "metric": rule.metric,
        "value": float(value), "step": int(step), "kind": rule.kind,
        "halt": rule.halt,
    })
    return breach

  def observe(self, step: int, summary: Mapping[str, float]
              ) -> List[dict]:
    """One per-step summary through every rule. Returns the breaches
    (already escalated); raises HealthHalt when a halting rule
    breached under halt_on_breach."""
    return self.observe_with_snapshot(step, summary, snapshot_fn=None)

  def observe_with_snapshot(
      self, step: int, summary: Mapping[str, float],
      snapshot_fn: Optional[Callable[[], None]] = None) -> List[dict]:
    """observe() + the auto-action: ``snapshot_fn`` (the loop's
    checkpoint closure) runs once when any rule breached, BEFORE a
    halt — the post-mortem gets the state that breached."""
    breaches: List[dict] = []
    with self._lock:
      self.observations += 1
      self.last_summary = {key: float(value)
                           for key, value in summary.items()}
      for rule in self.rules:
        value = summary.get(rule.metric)
        if value is None:
          continue
        value = float(value)
        if math.isnan(value) and rule.kind == "drift":
          # A NaN metric is the hard rules' jurisdiction; feeding it
          # to an EWMA would poison the baseline forever.
          continue
        breach = self._check_rule(rule, value, step)
        if breach is not None:
          breaches.append(breach)
      self.breach_count += len(breaches)
      self.breaches.extend(breaches)
      if len(self.breaches) > self._max_breaches:
        del self.breaches[:len(self.breaches) - self._max_breaches]
    for breach in breaches:
      self._escalate(breach)
    if breaches and snapshot_fn is not None:
      try:
        snapshot_fn()
      except Exception:
        pass  # the snapshot is best-effort; the breach record stands
    if self.halt_on_breach:
      halting = [b for b in breaches if b.get("halt")]
      if halting:
        raise HealthHalt(step, halting)
    return breaches

  def _escalate(self, breach: dict) -> None:
    """counters → rate-limited dump (step + correlation ids) →
    callback; each hop exception-isolated."""
    try:
      registry = self._registry or registry_lib.get_registry()
      registry.counter("health/breaches").inc()
      registry.counter(f"health/{breach['rule']}").inc()
    except Exception:
      pass
    try:
      recorder = self._recorder or flight_lib.get_recorder()
      fields = {key: breach[key] for key in BREACH_FIELDS}
      fields.update({key: breach[key] for key in ("z", "threshold")
                     if key in breach})
      # Bound correlation/step ids ride the dump exactly like an
      # injected fault's (obs/faults.py contract).
      attrs = context_lib.context_attrs()
      fields.update({key: attrs[key]
                     for key in ("request_id", "request_ids", "step_id")
                     if key in attrs})
      recorder.trigger("health_breach", **fields)
    except Exception:
      pass
    if self._on_breach is not None:
      try:
        self._on_breach(breach)
      except Exception:
        pass

  def state_dict(self) -> dict:
    """JSON-able resume state (ISSUE 16 satellite): the EWMA drift
    baselines plus the per-rule seen counts — exactly the state whose
    loss makes a resumed loop drift-blind for ``warmup`` steps. Breach
    history/last_summary stay run-local (the flight recorder owns the
    post-mortem record); hard rules carry no state at all."""
    with self._lock:
      return {
          "drift": {name: [state.n, state.mean, state.var]
                    for name, state in self._drift.items()},
          "seen": dict(self._seen),
          "observations": self.observations,
      }

  def load_state_dict(self, state: Mapping) -> None:
    """Re-seats state_dict() baselines. Rule names the current monitor
    doesn't know are ignored (a resume across a rule-set change keeps
    what still applies); unknown-to-the-checkpoint rules keep their
    fresh zero state and re-warm normally."""
    with self._lock:
      for name, entry in dict(state.get("drift", {})).items():
        drift = self._drift.get(name)
        if drift is None:
          continue
        drift.n, drift.mean, drift.var = (
            int(entry[0]), float(entry[1]), float(entry[2]))
      for name, count in dict(state.get("seen", {})).items():
        if name in self._seen:
          self._seen[name] = int(count)
      self.observations = int(state.get("observations",
                                        self.observations))

  def snapshot(self) -> dict:
    """Artifact-ready monitor state: rule table, breach history,
    per-rule counts, the last summary observed."""
    with self._lock:
      per_rule: Dict[str, int] = {}
      for breach in self.breaches:
        per_rule[breach["rule"]] = per_rule.get(breach["rule"], 0) + 1
      return {
          "rules": [{
              "name": rule.name, "metric": rule.metric,
              "kind": rule.kind, "halt": rule.halt,
          } for rule in self.rules],
          "observations": self.observations,
          "breach_count": self.breach_count,
          "breaches_per_rule": per_rule,
          "breaches": [dict(breach) for breach in self.breaches],
          "last_summary": dict(self.last_summary),
      }


# -- fleet Q-drift guard ----------------------------------------------------


def q_drift_report(replica_summaries: Mapping[str, Mapping],
                   z_threshold: float = 8.0,
                   min_samples: int = 16,
                   min_scale: float = 1e-4) -> dict:
  """Cross-replica served-Q divergence vs the fleet (leave-one-out).

  ``replica_summaries`` maps a replica label to its served-Q sketch
  summary ({"count", "mean", "p50", "p90", ...} — ServingStats'
  ``q_sketch_summaries`` shape, or the aggregator's per-process form).
  Every replica serves the same request distribution through the same
  params, so their served-Q MEANS must agree up to sampling noise; one
  that doesn't is serving a different function (a corrupted replica,
  a botched ``set_variables`` that still returns finite numbers).

  The check is scale-free — Q heads range from ~1e-3 logits (the CI
  critics) to order-1 values, so no absolute threshold can be a
  default. For each qualifying replica (>= ``min_samples`` served
  values): the FLEET CENTER is the median of the OTHER replicas'
  means (leave-one-out, so the candidate cannot pull its own
  yardstick), and the SCALE is the larger of (a) the other replicas'
  median absolute deviation around that center and (b) half their
  median within-replica p90-p50 spread — MAD is zero at fleet size 2,
  where the within-replica dispersion is the honest noise floor —
  floored at ``min_scale``. A replica whose |mean - center| exceeds
  ``z_threshold`` x scale is DIVERGENT. (At fleet size 2 the guard
  cannot name the culprit — both sides of a wide gap flag — but the
  alarm still fires; >= 3 replicas isolate the corrupted one.)

  Verdicts: "ok", "divergent" (names in ``divergent``), or
  "insufficient" (< 2 qualifying replicas: no fleet to diverge from).
  """
  qualifying = {
      name: summary for name, summary in replica_summaries.items()
      if summary.get("count", 0) >= min_samples
      and summary.get("mean") is not None}
  report = {
      "z_threshold": z_threshold,
      "min_samples": min_samples,
      "min_scale": min_scale,
      "replicas": {},
      "divergent": [],
      "fleet_median": None,
  }
  for name, summary in sorted(replica_summaries.items()):
    report["replicas"][name] = {
        "count": int(summary.get("count", 0)),
        "mean": summary.get("mean"),
        "median": summary.get("p50"),
        "qualifying": name in qualifying,
    }
  if len(qualifying) < 2:
    report["verdict"] = "insufficient"
    return report
  means = {name: float(summary["mean"])
           for name, summary in qualifying.items()}
  spreads = {
      name: max(float(summary.get("p90") or 0.0)
                - float(summary.get("p50") or 0.0), 0.0)
      for name, summary in qualifying.items()}
  report["fleet_median"] = round(statistics.median(means.values()), 6)
  for name in qualifying:
    others = [means[other] for other in qualifying if other != name]
    center = statistics.median(others)
    mad = statistics.median(
        abs(value - center) for value in others)
    spread_floor = 0.5 * statistics.median(
        spreads[other] for other in qualifying if other != name)
    scale = max(mad, spread_floor, min_scale)
    z = abs(means[name] - center) / scale
    entry = report["replicas"][name]
    entry["delta"] = round(abs(means[name] - center), 6)
    entry["z"] = round(z, 3)
    if z > z_threshold:
      entry["divergent"] = True
      report["divergent"].append(name)
  report["divergent"].sort()
  report["verdict"] = "divergent" if report["divergent"] else "ok"
  return report

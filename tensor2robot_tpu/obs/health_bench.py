"""Health-sentinel bench: injected silent corruption vs detection — HEALTH_r16.

The ISSUE 15 acceptance instrument. Every SILENT corruption kind the
sentinel claims to catch is INJECTED deterministically (obs/faults.py
numeric kinds — the same seeded FaultPlan seams as the PR 11 chaos
bench, no monkeypatching) against live machinery, and detection is
measured and bar-checked AT GENERATION TIME. Four phases, ONE JSON
line (the repo's bench/driver contract):

1. **ledger_stability** — the fused anakin loop run twice on the
   dp mesh, health summaries OFF then ON: the executable ledger must
   be BIT-IDENTICAL (the summaries are reductions inside the one
   already-compiled ``anakin_step`` — zero new executables), and the
   instrumented run's host-blocked fraction must hold the r09 level
   (the summaries ride the existing metrics D2H).
2. **detection** — each corruption kind against the loop/fleet it
   targets, detection REQUIRED within its rule's window:
   ``nan_grads`` through the FUSED anakin loop (params poisoned at the
   between-dispatch seam → the next dispatch's in-program summary
   reads non-finite grads/params → hard rule → ``health_breach`` dump
   → HealthHalt); ``value_scale`` through the host loop (targets
   scaled 50x → TD/grad-norm drift rules trip on the very next step);
   ``corrupt_served_variables`` against a live FleetRouter (one
   replica's served params scaled — every answer stays finite and
   plausible — caught only by the fleet Q-drift guard:
   ``health_snapshot()`` verdict divergent, the culprit named, a
   ``replica_divergent`` dump fired, and the injected fault's own dump
   carrying the request ids it hit).
3. **fleet_aggregate** — the corrupted fleet's registry snapshot
   through ``obs/aggregate.py``: the cross-process health rollup must
   reach the same divergent verdict from the exported per-replica
   served-Q reservoirs alone.
4. **healthy_control** — the same three rigs with NO plan: zero
   health breaches, Q-drift verdict ok, aggregate verdict ok. A
   sentinel that pages on healthy runs is worse than none.

HONESTY CAVEAT (carried as ``virtual_mesh``): chipless, the mesh is
XLA virtual CPU devices. What this artifact proves is DETECTION
STRUCTURE — the right rule fires at the right step with the right
correlation, and stays silent on health — not detection latency in
wall-clock terms on real chips (bench.py's ``health`` block on a pool
window).
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Dict, List, Optional

import numpy as np

from tensor2robot_tpu.obs import faults as faults_lib
from tensor2robot_tpu.obs import health as health_lib

R16_HOST_BLOCKED_BAR = 0.05   # the r09 "zero host work" level, with slack
R16_DETECTION_WINDOW = 2      # dispatches within which a fused corruption
                              # must surface (hard rules: the NEXT summary)


def _anakin_rig(num_envs: int, mesh_axis: int, seed: int,
                health: bool):
  """A direct AnakinLoop (TinyQ, dp mesh) — the ledger-stability rig.
  Returns (loop, trainer_state, ledger_fn)."""
  import jax
  import optax

  from tensor2robot_tpu.export import export_utils
  from tensor2robot_tpu.parallel import mesh as mesh_lib
  from tensor2robot_tpu.replay.anakin import AnakinLoop
  from tensor2robot_tpu.replay.device_buffer import DeviceReplayBuffer
  from tensor2robot_tpu.replay.loop import transition_spec
  from tensor2robot_tpu.replay.smoke import TinyQCriticModel
  from tensor2robot_tpu.research.qtopt.jax_grasping import (
      JaxGraspEnv, make_scene_bank)
  from tensor2robot_tpu.train.trainer import Trainer

  image_size, action_size = 16, 4
  devices = jax.devices()[:mesh_axis]
  mesh = mesh_lib.create_mesh({"data": len(devices), "model": 1},
                              devices=devices)
  model = TinyQCriticModel(image_size=image_size,
                           action_size=action_size,
                           optimizer_fn=lambda: optax.adam(3e-3))
  trainer = Trainer(model, mesh=mesh, seed=seed,
                    shard_optimizer_state=len(devices) > 1)
  state = trainer.create_train_state(batch_size=32)
  buffer = DeviceReplayBuffer(
      transition_spec(image_size, action_size), 512, 32, seed=seed,
      prioritized=True, ingest_chunk=num_envs, mesh=mesh)
  bank = make_scene_bank(128, image_size=image_size, base_seed=seed)
  env = JaxGraspEnv(num_envs, image_size=image_size, max_attempts=3,
                    radius=0.4, bank=bank)
  loop = AnakinLoop(model, trainer, buffer, env,
                    action_size=action_size, gamma=0.8,
                    num_samples=16, num_elites=4, iterations=2,
                    inner_steps=40, train_every=8, min_fill=32,
                    seed=seed + 13, health=health)
  host_variables = export_utils.fetch_variables_to_host(
      state.variables(use_ema=True))
  loop.refresh(host_variables, step=0)

  def ledger():
    return {**dict(loop.compile_counts), **dict(buffer.compile_counts)}

  return loop, state, ledger


def _measure_ledger_stability(mesh_axis: int, dispatches: int,
                              seed: int) -> Dict:
  """Phase 1: health off vs on — identical ledger, r09 host-blocked."""
  num_envs = 32
  ledgers = {}
  host_blocked = None
  summary_keys_seen: List[str] = []
  for label, health in (("pre_health", False), ("health", True)):
    loop, state, ledger = _anakin_rig(num_envs, mesh_axis, seed, health)
    state, metrics = loop.step(state)  # compile + warm, untimed
    exec0 = loop.exec_seconds
    start = time.perf_counter()
    for _ in range(dispatches):
      state, metrics = loop.step(state)
    elapsed = time.perf_counter() - start
    ledgers[label] = ledger()
    if health:
      host_blocked = max(
          0.0, 1.0 - (loop.exec_seconds - exec0) / elapsed)
      summary_keys_seen = sorted(
          key for key in metrics if key.startswith("health/"))
  identical = ledgers["pre_health"] == ledgers["health"]
  return {
      "mesh_axis": mesh_axis,
      "dispatches": dispatches,
      "ledger_pre_health": ledgers["pre_health"],
      "ledger_health": ledgers["health"],
      "ledger_identical": bool(identical),
      "summary_keys": summary_keys_seen,
      "summary_schema_ok": summary_keys_seen == sorted(
          health_lib.SUMMARY_KEYS),
      "host_blocked_fraction": (round(host_blocked, 4)
                                if host_blocked is not None else None),
      "host_blocked_bar": R16_HOST_BLOCKED_BAR,
      "ok": bool(identical and host_blocked is not None
                 and host_blocked <= R16_HOST_BLOCKED_BAR
                 and summary_keys_seen == sorted(health_lib.SUMMARY_KEYS)),
  }


def _find_dumps(logdir: str, reason: str) -> List[dict]:
  found = []
  for root, _, files in os.walk(logdir):
    for name in sorted(files):
      if name.startswith("flightrec-") and reason in name:
        try:
          with open(os.path.join(root, name)) as f:
            found.append(json.load(f))
        except (OSError, ValueError):
          pass
  return found


def _make_loop(logdir: str, seed: int, anakin: bool, halt: bool,
               plan: Optional[faults_lib.FaultPlan],
               eval_every: int = 15):
  import optax

  from tensor2robot_tpu.replay.loop import (ReplayLoopConfig,
                                            ReplayTrainLoop)
  from tensor2robot_tpu.replay.smoke import TinyQCriticModel

  config = ReplayLoopConfig(
      seed=seed, eval_every=eval_every, mesh_dp=1, mesh_tp=1,
      health=True, health_halt=halt, anakin=anakin,
      anakin_inner=20, anakin_train_every=4,
      min_fill=64 if anakin else 96)
  model = TinyQCriticModel(
      image_size=config.image_size, action_size=config.action_size,
      optimizer_fn=lambda: optax.adam(config.learning_rate))
  loop = ReplayTrainLoop(config, logdir, model=model, fault_plan=plan)
  return loop, config


def _measure_nan_grads_anakin(steps: int, inject_at: int,
                              seed: int) -> Dict:
  """Phase 2a: nan_grads through the FUSED loop → hard rule → halt."""
  logdir = tempfile.mkdtemp(prefix="health_nan_")
  plan = faults_lib.FaultPlan([
      faults_lib.FaultSpec(kind="nan_grads", point="learner_step",
                           site="anakin", at=inject_at, every=1,
                           count=1)])
  loop, config = _make_loop(logdir, seed, anakin=True, halt=True,
                            plan=plan)
  halted = None
  try:
    loop.run(steps)
  except health_lib.HealthHalt as e:
    halted = {"step": e.step,
              "rules": sorted({b["rule"] for b in e.breaches})}
  snapshot = loop.health_monitor.snapshot()
  injected_tick = (plan.snapshot()["fired"][0]["tick"]
                   if plan.fired_counts() else None)
  detected_step = (snapshot["breaches"][0]["step"]
                   if snapshot["breaches"] else None)
  steps_per_dispatch = config.anakin_inner // config.anakin_train_every
  window = R16_DETECTION_WINDOW * steps_per_dispatch
  dumps = _find_dumps(logdir, "health_breach")
  dump_step_ok = any(
      dump.get("trigger", {}).get("step") == detected_step
      and not [field for field in health_lib.BREACH_FIELDS
               if field not in dump.get("trigger", {})]
      for dump in dumps)
  return {
      "steps": steps,
      "inject_at": inject_at,
      "injected_tick": injected_tick,
      "detected_step": detected_step,
      "detection_window_steps": window,
      "halted": halted,
      "breached_rules": snapshot["breaches_per_rule"],
      "breach_dumps": len(dumps),
      "dump_step_and_schema_ok": bool(dump_step_ok),
      "ok": bool(
          halted is not None and injected_tick is not None
          and detected_step is not None
          and injected_tick <= detected_step <= injected_tick + window
          and "nonfinite_grads" in snapshot["breaches_per_rule"]
          and dump_step_ok),
  }


def _measure_value_scale_host(steps: int, inject_at: int, scale: float,
                              seed: int) -> Dict:
  """Phase 2b: value_scale through the HOST loop → drift rules."""
  logdir = tempfile.mkdtemp(prefix="health_scale_")
  plan = faults_lib.FaultPlan([
      faults_lib.FaultSpec(kind="value_scale", point="learner_step",
                           site="learner", at=inject_at, scale=scale)])
  loop, _ = _make_loop(logdir, seed, anakin=False, halt=False,
                       plan=plan)
  result = loop.run(steps)
  snapshot = result["health"]
  # The fault fires at the END of step inject_at and corrupts step
  # inject_at + 1's targets — the drift rules' window is that step.
  detected_steps = sorted({b["step"] for b in snapshot["breaches"]})
  window_ok = bool(detected_steps
                   and inject_at + 1 <= detected_steps[0] <= inject_at + 3)
  dumps = _find_dumps(logdir, "health_breach")
  dump_ok = any(
      dump.get("trigger", {}).get("step") in detected_steps
      and not [field for field in health_lib.BREACH_FIELDS
               if field not in dump.get("trigger", {})]
      for dump in dumps)
  drift_rules = {rule for rule in snapshot["breaches_per_rule"]
                 if rule in ("td_drift", "q_drift", "grad_norm_drift")}
  return {
      "steps": steps,
      "inject_at": inject_at,
      "scale": scale,
      "detected_steps": detected_steps[:8],
      "breached_rules": snapshot["breaches_per_rule"],
      "breach_dumps": len(dumps),
      "dump_step_and_schema_ok": bool(dump_ok),
      "ok": bool(window_ok and drift_rules and dump_ok),
  }


def _run_fleet_window(devices, seed: int, corrupt_index: Optional[int],
                      requests: int, logdir: str) -> Dict:
  """One routed serve window; corrupt_index selects the replica whose
  served variables a fired fault scales (None = healthy control).
  Exports the isolated registry snapshot into ``logdir`` for the
  aggregate phase."""
  from tensor2robot_tpu.obs.flight_recorder import FlightRecorder
  from tensor2robot_tpu.obs.registry import MetricRegistry
  from tensor2robot_tpu.serving.router import FleetRouter
  from tensor2robot_tpu.serving.smoke import TinyQPredictor
  from tensor2robot_tpu.serving.stats import ServingStats

  os.makedirs(logdir, exist_ok=True)
  recorder = FlightRecorder(dump_dir=logdir, min_dump_interval_s=0.0)
  registry = MetricRegistry()
  plan = None
  if corrupt_index is not None:
    plan = faults_lib.FaultPlan([
        faults_lib.FaultSpec(kind="corrupt_served_variables",
                             point="replica_dispatch",
                             site=str(devices[corrupt_index]), at=0,
                             scale=16.0)],
        seed=seed, recorder=recorder)
  predictor = TinyQPredictor(seed=seed)
  stats = ServingStats(registry=registry)
  router = FleetRouter(predictor, devices=devices,
                       ladder_sizes=(1, 2), seed=seed, stats=stats,
                       fault_plan=plan, flight_recorder=recorder)
  router.warmup(predictor.make_image)
  images = [predictor.make_image(seed + i) for i in range(8)]
  with router:
    futures = [router.submit(images[i % len(images)])
               for i in range(requests)]
    for future in futures:
      future.result(60)
    snapshot = router.health_snapshot()
  registry.export_snapshot(os.path.join(logdir, "registry.json"))
  fault_records = plan.snapshot()["fired"] if plan is not None else []
  return {
      "requests": requests,
      "devices": len(devices),
      "verdict": snapshot["q_drift"]["verdict"],
      "divergent": snapshot["q_drift"]["divergent"],
      "health": snapshot["health"],
      "replica_z": {name: entry.get("z")
                    for name, entry in
                    snapshot["q_drift"]["replicas"].items()},
      "fault_records": fault_records,
      "divergent_dumps": len(_find_dumps(logdir, "replica_divergent")),
      "timeline_events": [entry["event"]
                          for entry in snapshot["timeline"]],
  }


def _measure_corrupt_served(devices, requests: int, seed: int) -> Dict:
  """Phase 2c + 3: the corrupted fleet window, then the aggregate
  rollup over its exported registry snapshot."""
  from tensor2robot_tpu.obs import aggregate as aggregate_lib

  corrupt_index = min(1, len(devices) - 1)
  logdir = tempfile.mkdtemp(prefix="health_fleet_")
  window = _run_fleet_window(devices, seed, corrupt_index, requests,
                             logdir)
  corrupt_device = str(devices[corrupt_index])
  correlated = sum(1 for record in window["fault_records"]
                   if record.get("request_id")
                   or record.get("request_ids"))
  fleet = aggregate_lib.aggregate_logdir(logdir, merged_trace=False)
  aggregate_health = fleet["health"]
  aggregate_divergent_ok = (
      aggregate_health["verdict"] == "divergent"
      and any(name.endswith("/" + corrupt_device)
              for name in aggregate_health["q_drift"]["divergent"]))
  detected = (window["verdict"] == "divergent"
              and corrupt_device in window["divergent"])
  return {
      "corrupt_replica": corrupt_device,
      "window": window,
      "correlated_fault_dumps": correlated,
      "aggregate_verdict": aggregate_health["verdict"],
      "aggregate_divergent": aggregate_health["q_drift"]["divergent"],
      # EXACT dump count (ISSUE 19 de-coalesced filenames): one
      # divergent TRANSITION fires one replica_divergent dump — the
      # snapshot's single check_q_drift pass — no more, no less.
      "ok": bool(detected and window["divergent_dumps"] == 1
                 and correlated >= 1
                 and "replica_divergent" in window["timeline_events"]
                 and aggregate_divergent_ok),
  }


def _measure_healthy_controls(devices, steps: int, requests: int,
                              seed: int) -> Dict:
  """Phase 4: the same rigs, no plan — ZERO breaches everywhere."""
  from tensor2robot_tpu.obs import aggregate as aggregate_lib

  logdir = tempfile.mkdtemp(prefix="health_ctrl_")
  loop, _ = _make_loop(logdir, seed, anakin=True, halt=True, plan=None)
  result = loop.run(steps)
  anakin_health = result["health"]
  fleet_dir = tempfile.mkdtemp(prefix="health_ctrl_fleet_")
  window = _run_fleet_window(devices, seed, None, requests, fleet_dir)
  fleet = aggregate_lib.aggregate_logdir(fleet_dir, merged_trace=False)
  return {
      "anakin": {
          "steps": steps,
          "observations": anakin_health["observations"],
          "breach_count": anakin_health["breach_count"],
          "eval_td_reduction": result["eval_td_reduction"],
      },
      "fleet": {
          "requests": requests,
          "verdict": window["verdict"],
          "divergent": window["divergent"],
          "replica_z": window["replica_z"],
      },
      "aggregate_verdict": fleet["health"]["verdict"],
      "ok": bool(anakin_health["breach_count"] == 0
                 and anakin_health["observations"] > 0
                 and window["verdict"] == "ok"
                 and fleet["health"]["verdict"] == "ok"),
  }


def measure_health(
    n_devices: Optional[int] = None,
    ledger_mesh_axis: int = 8,
    ledger_dispatches: int = 3,
    nan_steps: int = 60,
    nan_inject_at: int = 20,
    scale_steps: int = 40,
    scale_inject_at: int = 20,
    fleet_requests: int = 240,
    control_steps: int = 30,
    seed: int = 0,
    enforce_bars: bool = True,
) -> Dict:
  """Runs the four-phase health protocol; returns the HEALTH_r16
  artifact dict. ``enforce_bars`` (the --smoke lane) raises if any
  committed acceptance bar fails AT GENERATION TIME — a committed
  sentinel artifact that does not meet its own bars must not exist."""
  import jax

  devices = jax.devices()
  if n_devices is not None:
    if n_devices > len(devices):
      raise ValueError(
          f"asked for {n_devices} devices, have {len(devices)}; on a "
          "chipless host run the CLI --smoke lane (it bootstraps an "
          "8-virtual-device CPU mesh).")
    devices = devices[:n_devices]
  device_kind = devices[0].device_kind
  mesh_axis = min(ledger_mesh_axis, len(devices))

  ledger_stability = _measure_ledger_stability(mesh_axis,
                                               ledger_dispatches, seed)
  nan_grads = _measure_nan_grads_anakin(nan_steps, nan_inject_at, seed)
  value_scale = _measure_value_scale_host(scale_steps, scale_inject_at,
                                          50.0, seed)
  corrupt_served = _measure_corrupt_served(devices, fleet_requests,
                                           seed)
  healthy = _measure_healthy_controls(devices, control_steps,
                                      fleet_requests, seed)

  detection_ok = bool(nan_grads["ok"] and value_scale["ok"]
                      and corrupt_served["ok"])
  q_drift_ok = bool(corrupt_served["ok"] and healthy["ok"])
  result = {
      "round": 16,
      "metric": ("silent-failure sentinel: in-program health "
                 "summaries, numeric anomaly rules, fleet Q-drift "
                 "guard"),
      "device_kind": device_kind,
      "virtual_mesh": device_kind.lower() == "cpu",
      "devices": len(devices),
      "rules": [rule.name for rule in health_lib.default_rules(512)],
      "ledger_stability": ledger_stability,
      "detection": {
          "nan_grads": nan_grads,
          "value_scale": value_scale,
          "corrupt_served_variables": corrupt_served,
      },
      "healthy_control": healthy,
      # Compact sentinels (bench.py round 16; null-safe): detection is
      # meaningful chipless as STRUCTURE (the right rule at the right
      # step with the right correlation, silence on health); detection
      # LATENCY on real chips is the queued chip claim.
      "health_breach_detection_ok": detection_ok,
      "fleet_q_drift_ok": q_drift_ok,
      "note": (
          "Deterministic numeric corruption (obs/faults.py NUMERIC_"
          "KINDS) against live machinery on the virtual mesh: "
          "nan_grads through the fused anakin loop caught by the "
          "in-program nonfinite hard rule (health_breach dump + "
          "HealthHalt), value_scale through the host loop caught by "
          "the EWMA drift rules on the very next step, and a "
          "corrupt_served_variables replica — finite, plausible, "
          "wrong — caught only by the fleet Q-drift guard (divergent "
          "verdict naming the replica, replica_divergent dump, and "
          "the same verdict re-derived cross-process by obs/"
          "aggregate from exported served-Q reservoirs). Healthy "
          "controls: zero breaches, ok verdicts. The instrumented "
          "fused loop's executable ledger is bit-identical to the "
          "uninstrumented run and host-blocked holds the r09 level. "
          "virtual_mesh=true: structure/ordering claims only — "
          "detection latency on real chips lands via bench.py's "
          "health block."),
  }

  if enforce_bars:
    failures = []
    if not ledger_stability["ok"]:
      failures.append(
          f"ledger stability failed: identical="
          f"{ledger_stability['ledger_identical']}, host_blocked="
          f"{ledger_stability['host_blocked_fraction']}, schema_ok="
          f"{ledger_stability['summary_schema_ok']}")
    for kind, phase in result["detection"].items():
      if not phase["ok"]:
        failures.append(f"{kind} not detected: {phase}")
    if not healthy["ok"]:
      failures.append(f"healthy control breached: {healthy}")
    if failures:
      raise AssertionError(
          "HEALTH_r16 acceptance bars failed: " + "; ".join(failures))
  return result


def main(argv=None) -> None:
  """CLI: ONE JSON line. --smoke bootstraps the 8-virtual-device CPU
  mesh (re-exec with the canonical env) and runs the committed
  HEALTH_r16 protocol with generation-time bar enforcement; --ci is
  the reduced tier-1 lane (2 devices, short windows, bars deferred to
  tests/test_health.py behind the cpu_count gate)."""
  import argparse
  import sys

  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument("--smoke", action="store_true",
                      help="chipless committed-artifact lane: full "
                           "protocol, bars enforced at generation time")
  parser.add_argument("--ci", action="store_true",
                      help="reduced chipless lane for tier-1 tests")
  parser.add_argument("--seed", type=int, default=0)
  parser.add_argument("--out", default=None,
                      help="also write the JSON line to this file")
  args = parser.parse_args(argv)
  if args.smoke or args.ci:
    from tensor2robot_tpu.utils.cpu_mesh_env import (cpu_mesh_env,
                                                     is_cpu_mesh_env)
    n = 8 if args.smoke else 2
    if not is_cpu_mesh_env(n):
      if argv is not None:
        raise RuntimeError(
            "--smoke/--ci need the virtual CPU mesh configured before "
            "JAX initializes; call main() with argv=None (the CLI "
            "re-execs itself).")
      os.execve(sys.executable,
                [sys.executable, "-m",
                 "tensor2robot_tpu.obs.health_bench",
                 *sys.argv[1:]],
                cpu_mesh_env(n))
  if args.ci:
    results = measure_health(
        n_devices=2, ledger_mesh_axis=1, ledger_dispatches=2,
        nan_steps=40, nan_inject_at=10, scale_steps=30,
        scale_inject_at=15, fleet_requests=120, control_steps=15,
        seed=args.seed, enforce_bars=False)
  else:
    results = measure_health(seed=args.seed)
  line = json.dumps(results)
  if args.out:
    with open(args.out, "w") as f:
      f.write(line + "\n")
  print(line)


if __name__ == "__main__":
  main()

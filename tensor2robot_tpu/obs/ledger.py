"""ExecutableLedger: compile counts + device-time attribution, first class.

Every compiled program in the production loop already kept an ad-hoc
``compile_counts`` dict (replay buffers, the megastep, the fused
anakin_step, the CEM bucket ladders, the Bellman updater) whose values
tier-1 asserts stay exactly 1 — the fixed-shape "compiles once, never
recompiles" discipline. This module promotes those dicts into one
ledger that ALSO answers the question the Podracer and pjit/TPUv4
papers (PAPERS.md) build their whole analyses on: *where does device
time go, per executable?*

Each AOT executable registers with name/device/shapes; the ledger joins
``compiled.cost_analysis()`` FLOPs/bytes with dispatch counts and
measured wall seconds into per-executable device-time share and an
estimated MFU. Chipless (virtual CPU mesh) the MFU is honestly null —
there is no peak-FLOPs model for this host — and the share numbers
measure host wall-clock attribution, the MULTICHIP virtual-mesh caveat
applied to time instead of throughput.

Timing honesty: ``record_dispatch`` seconds are measured host-side
around the dispatch. Call sites that synchronize on the result (the
anakin/megastep D2H metric reads) record true device+D2H time; staging
calls that fire and forget (the device ring's host extend) record
dispatch time only — attribution shares are therefore lower bounds for
async call sites, and on scanned executables ``cost_analysis`` reports
the scan body ONCE (bench.py convention), so FLOPs-derived fields are
per-body, not per-dispatch-of-K.

``check_compile_ledger`` is the ONE shared assertion helper the replay,
anakin, and fleet smokes use in place of their per-test ``all(v == 1)``
copies.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional

# Chip peak FLOP/s keyed by substrings of jax device_kind (the bench.py
# table, now owned here so every MFU estimate in the repo shares one
# source). v5e ("TPU v5 lite"): public spec bf16 peak.
CHIP_PEAKS = {
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6": 918e12,
}


def peak_flops_for(device_kind: Optional[str]) -> Optional[float]:
  """Peak FLOP/s for a device kind; None when unknown (e.g. cpu)."""
  if not device_kind:
    return None
  kind = device_kind.lower()
  for key, peak in CHIP_PEAKS.items():
    if key in kind:
      return peak
  return None


class ExecutableEntry:
  """One executable's ledger row (guarded by the owning ledger's lock)."""

  __slots__ = ("name", "device", "shapes", "dtype", "compiles",
               "dispatches", "seconds", "flops_per_dispatch",
               "bytes_per_dispatch")

  def __init__(self, name: str):
    self.name = name
    self.device: Optional[str] = None
    self.shapes: Optional[dict] = None
    self.dtype: Optional[str] = None
    self.compiles = 0
    self.dispatches = 0
    self.seconds = 0.0
    self.flops_per_dispatch: Optional[float] = None
    self.bytes_per_dispatch: Optional[float] = None


def _cost_analysis(compiled):
  """(flops, bytes_accessed) from an AOT executable; (None, None) when
  the backend doesn't report them."""
  try:
    analysis = compiled.cost_analysis()
    if isinstance(analysis, (list, tuple)):
      analysis = analysis[0]
    flops = float(analysis.get("flops", 0.0)) or None
    nbytes = float(analysis.get("bytes accessed", 0.0)) or None
    return flops, nbytes
  except Exception:
    return None, None


class ExecutableLedger:
  """Thread-safe name → ExecutableEntry map with attribution readout."""

  def __init__(self):
    self._entries: Dict[str, ExecutableEntry] = {}
    self._lock = threading.Lock()
    self._window_start = time.perf_counter()

  # -- recording -----------------------------------------------------------

  def register(self, name: str, compiled=None, device=None,
               shapes: Optional[dict] = None,
               dtype: Optional[str] = None) -> str:
    """One compilation of ``name``; repeat registrations bump the
    compile count (the recompile regression the smokes assert against).
    ``compiled`` (an AOT executable) contributes cost_analysis
    FLOPs/bytes; ``device`` is any str()-able placement label.
    ``dtype`` tags the executable's SCORING precision tier ("f32" /
    "bf16", ISSUE 13) so ``attribution()`` can split device-time and
    MFU per tier — an untagged row groups under "untagged" (host
    bookkeeping executables that have no scoring tier)."""
    with self._lock:
      entry = self._entries.get(name)
      if entry is None:
        entry = self._entries[name] = ExecutableEntry(name)
      entry.compiles += 1
      if device is not None:
        entry.device = str(device)
      if shapes is not None:
        entry.shapes = dict(shapes)
      if dtype is not None:
        entry.dtype = str(dtype)
    if compiled is not None:
      flops, nbytes = _cost_analysis(compiled)
      with self._lock:
        if flops is not None:
          entry.flops_per_dispatch = flops
        if nbytes is not None:
          entry.bytes_per_dispatch = nbytes
    return name

  def record_dispatch(self, name: str, seconds: float,
                      count: int = 1) -> None:
    """Accumulates one (or ``count``) dispatches and their measured wall
    seconds. An unregistered name is created with compiles=0 so a
    dispatch-before-register wiring bug surfaces in the attribution
    instead of crashing the loop."""
    with self._lock:
      entry = self._entries.get(name)
      if entry is None:
        entry = self._entries[name] = ExecutableEntry(name)
      entry.dispatches += count
      entry.seconds += float(seconds)

  # -- readout -------------------------------------------------------------

  @property
  def compile_counts(self) -> Dict[str, int]:
    """The classic ledger dict view ({name: compiles})."""
    with self._lock:
      return {name: entry.compiles
              for name, entry in sorted(self._entries.items())}

  def names(self) -> List[str]:
    with self._lock:
      return sorted(self._entries)

  def attribution(self, wall_seconds: Optional[float] = None,
                  device_kind: Optional[str] = None) -> dict:
    """Per-executable device-time share + estimated MFU.

    With ``wall_seconds`` (the measured run window) shares are
    seconds/wall — they sum to <= 1.0 because the instrumented call
    sites are sequential host calls; the remainder is host work outside
    any executable. Without it shares are normalized over attributed
    seconds (sum == 1.0 when anything was dispatched).
    """
    with self._lock:
      entries = sorted(self._entries.values(),
                       key=lambda e: -e.seconds)
      rows = []
      attributed = sum(entry.seconds for entry in entries)
      denominator = wall_seconds if wall_seconds else attributed
      peak = peak_flops_for(device_kind)
      for entry in entries:
        mfu = None
        if peak and entry.flops_per_dispatch and entry.seconds > 0:
          mfu = round(entry.flops_per_dispatch * entry.dispatches
                      / entry.seconds / peak, 4)
        rows.append({
            "name": entry.name,
            "device": entry.device,
            "shapes": entry.shapes,
            "dtype": entry.dtype,
            "compiles": entry.compiles,
            "dispatches": entry.dispatches,
            "seconds_total": round(entry.seconds, 4),
            "device_time_share": round(
                entry.seconds / denominator, 4) if denominator else 0.0,
            "flops_per_dispatch": entry.flops_per_dispatch,
            "bytes_per_dispatch": entry.bytes_per_dispatch,
            "estimated_mfu": mfu,
        })
    shares = sum(row["device_time_share"] for row in rows)
    # Per-tier rollup (ISSUE 13): device-time split by scoring dtype, so
    # a mixed f32/bf16 fleet's attribution answers "where does time go,
    # per precision" — the Gemma-style serving-tier accounting.
    tiers: Dict[str, dict] = {}
    for row in rows:
      tier = tiers.setdefault(row["dtype"] or "untagged", {
          "executables": 0, "dispatches": 0, "seconds_total": 0.0,
          "device_time_share": 0.0})
      tier["executables"] += 1
      tier["dispatches"] += row["dispatches"]
      tier["seconds_total"] += row["seconds_total"]
      tier["device_time_share"] += row["device_time_share"]
    for tier in tiers.values():  # one rounding step, after the sums
      tier["seconds_total"] = round(tier["seconds_total"], 4)
      tier["device_time_share"] = round(tier["device_time_share"], 4)
    return {
        "wall_seconds": round(wall_seconds, 4) if wall_seconds else None,
        "attributed_seconds": round(attributed, 4),
        "attributed_share": round(shares, 4),
        "device_kind": device_kind,
        "peak_flops": peak,
        "tier_shares": tiers,
        "executables": rows,
        "note": (
            "device_time_share = measured dispatch seconds / "
            "wall_seconds (host-clock attribution; lower bound for "
            "async call sites). estimated_mfu is null without a known "
            "chip peak — on the virtual CPU mesh this mirrors the "
            "MULTICHIP caveat: shares are structural evidence, not "
            "chip rates. cost_analysis counts a scan body once, so "
            "flops_per_dispatch on scanned executables is per-body."),
    }


def _flatten_counts(counts: dict, prefix: str = "") -> Dict[str, int]:
  """Flattens the fleet's nested {device: {bucket: n}} ledgers."""
  flat: Dict[str, int] = {}
  for key, value in counts.items():
    label = f"{prefix}{key}"
    if isinstance(value, dict):
      flat.update(_flatten_counts(value, prefix=f"{label}/"))
    else:
      flat[label] = value
  return flat


def check_compile_ledger(counts: dict, require: Iterable[str] = (),
                         forbid: Iterable[str] = ()) -> Dict[str, int]:
  """THE shared smoke assertion: every executable compiled exactly once.

  Args:
    counts: a compile-count mapping — flat ({name: n}) or nested (the
      fleet router's {device: {bucket: n}}).
    require: names (or name prefixes ending in "*") that must be
      present.
    forbid: names that must be absent (executables a fused path
      subsumes).

  Returns the flattened counts for any further assertions; raises
  AssertionError naming the offending entries otherwise.
  """
  flat = _flatten_counts(dict(counts))
  assert flat, "empty compile ledger: nothing registered a compile"
  wrong = {name: n for name, n in flat.items() if n != 1}
  assert not wrong, f"executables not compiled exactly once: {wrong}"
  for name in require:
    if name.endswith("*"):
      prefix = name[:-1]
      assert any(key.startswith(prefix) for key in flat), (
          f"no executable matching {name!r} in ledger: {sorted(flat)}")
    else:
      assert name in flat, (
          f"required executable {name!r} missing from ledger: "
          f"{sorted(flat)}")
  for name in forbid:
    assert name not in flat, (
        f"forbidden executable {name!r} present in ledger "
        f"(a fused path should have subsumed it): {sorted(flat)}")
  return flat

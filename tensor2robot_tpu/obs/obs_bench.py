"""Obs bench: the observability spine exercised end to end — OBS_r13.

The ISSUE 11 acceptance instrument, extended for round 13 (ISSUE 12):
after the three original phases it exports the process registry
snapshot, runs the watchdog positive/negative controls, then runs
``obs/aggregate.py`` over its OWN phase artifacts and asserts the
merged view is self-consistent — so the committed artifact validates
the aggregator, not just the spine. One run, ONE JSON line:

1. **replay** — the replay-smoke protocol (the r10 shape:
   ``run_qtopt_replay --smoke --anakin --mesh DP,1`` built via the
   CLI's own ``build_config``) with the loop's ``ExecutableLedger``
   collecting per-executable dispatch counts + wall seconds joined with
   ``cost_analysis`` FLOPs/bytes → the per-executable device-time-share
   / estimated-MFU attribution block. Shares sum to <= 1.0 (sequential
   host dispatch windows over the run's wall clock) and every
   executable the smoke dispatched appears exactly once.
2. **host_loop** — a short host-path loop (threaded collectors +
   per-step sample/label/train): the configuration whose act / extend /
   learn stages are distinct host phases, so the exported Chrome trace
   carries >= 1 span per loop stage (the fused anakin path folds
   act/step/extend/learn into ONE ``learn/anakin_step`` span by
   construction — that is the point of fusing).
3. **serve** — a FleetRouter window over every device (per-device
   ledger rows via the policies' ``@device`` keys), live traffic for
   ``serve/flush`` spans, then an INJECTED SLO breach under
   ``hold_flushes()``: a capacity burst whose sheds trigger the flight
   recorder — the dump is schema-validated here and by tier-1.
4. **trace / registry / flightrec** — the Chrome-trace export (valid
   JSON, per-stage span counts), the process registry snapshot, and
   the breach dump's path + schema.
5. **watchdog** (round 13) — an injected stall (a busy component that
   never progresses) must produce a schema-valid ``watchdog_stall``
   flight-recorder dump, and a healthy beating component must produce
   ZERO events (the false-positive negative control; deadlines scale
   with the cpu_count >= 4 gating convention).
6. **fleetobs** (round 13) — ``aggregate_logdir`` over this run's own
   logdir: the merged view's shed rollup must be consistent (global
   counters == per-class sums across sources), the breach request's
   correlation timeline must link enqueue → flush → dispatch in the
   merged trace, and the hosts_merged / stall counts land in bench.py's
   compact keys. The MULTI-process version of this merge is the
   separate committed FLEETOBS artifact (bin/obs_aggregate --smoke).

HONESTY CAVEAT (mirrors MULTICHIP/FLEET): chipless, the mesh is 8
virtual CPU devices sharing this host's cores — `estimated_mfu` is
null (no CPU peak-FLOPs model) and shares are host wall-clock
attribution, structural evidence rather than chip rates. Real-chip
attribution lands via bench.py's `obs` block (same schema) on a pool
window.
"""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
import time
from typing import Dict, Optional

from tensor2robot_tpu.serving.slo import SLOClass


def _largest_pow2_dp(n_devices: int, cap: int = 8) -> int:
  dp = 1
  while dp * 2 <= min(n_devices, cap):
    dp *= 2
  return dp


def _run_replay_phase(anakin: bool, steps: int, mesh_dp: int,
                      logdir: str, seed: int) -> Dict:
  """One ReplayTrainLoop run (the smoke protocol) + its attribution."""
  import jax
  import optax

  from tensor2robot_tpu.bin.run_qtopt_replay import build_config
  from tensor2robot_tpu.replay.loop import ReplayTrainLoop
  from tensor2robot_tpu.replay.smoke import TinyQCriticModel

  config = build_config(
      smoke=True, seed=seed, device_resident=anakin, anakin=anakin,
      mesh=(mesh_dp, 1) if anakin else (0, 1))
  if not anakin:
    # Host-path phase: short, stage-diverse, still off-policy end to
    # end — sized for span coverage, not for the learning bar (the
    # replay phase and tier-1's smokes carry that).
    from dataclasses import replace
    config = replace(config, capacity=256, min_fill=64,
                     eval_every=max(8, steps // 2),
                     log_every=max(4, steps // 4))
  model = TinyQCriticModel(
      image_size=config.image_size, action_size=config.action_size,
      optimizer_fn=lambda: optax.adam(config.learning_rate))
  loop = ReplayTrainLoop(config, logdir, model=model)
  start = time.perf_counter()
  results = loop.run(steps)
  wall = time.perf_counter() - start
  attribution = loop.obs_ledger.attribution(
      wall_seconds=wall, device_kind=jax.devices()[0].device_kind)
  return {
      "protocol": ("run_qtopt_replay --smoke --anakin "
                   f"--mesh {mesh_dp},1" if anakin
                   else "run_qtopt_replay --smoke (host path, reduced)"),
      "steps": results["steps"],
      "eval_td_reduction": results["eval_td_reduction"],
      "compile_counts": results["compile_counts"],
      "mesh_shape": results.get("mesh_shape"),
      "wall_seconds": round(wall, 3),
      "attribution": attribution,
  }


def _run_serve_phase(duration_s: float, ladder_sizes, max_queue: int,
                     dump_dir: str, seed: int) -> Dict:
  """Router traffic + the injected hold_flushes SLO breach."""
  import jax
  import numpy as np

  from tensor2robot_tpu.obs import flight_recorder as flight_lib
  from tensor2robot_tpu.obs import ledger as ledger_lib
  from tensor2robot_tpu.serving.router import FleetRouter
  from tensor2robot_tpu.serving.smoke import TinyQPredictor
  from tensor2robot_tpu.serving.stats import ServingStats

  recorder = flight_lib.get_recorder()
  recorder.configure(dump_dir=dump_dir, min_dump_interval_s=1.0)

  devices = jax.devices()
  predictor = TinyQPredictor(seed=seed)
  stats = ServingStats()
  ledger = ledger_lib.ExecutableLedger()
  router = FleetRouter(
      predictor, devices=devices, num_samples=16, num_elites=4,
      iterations=2, ladder_sizes=ladder_sizes, max_queue=max_queue,
      dispatch_margin_ms=20.0, stats=stats, seed=seed, ledger=ledger)
  images = [predictor.make_image(seed + i) for i in range(16)]
  compile_start = time.perf_counter()
  router.warmup(predictor.make_image)
  warmup_s = time.perf_counter() - compile_start

  interactive = SLOClass("interactive", priority=1, deadline_ms=250.0)
  batch_class = SLOClass("batch", priority=0, deadline_ms=2000.0)
  serve_start = time.perf_counter()
  with router:
    # Live window: steady paced traffic through the routed fleet. A
    # contended host may shed some of it (counted, not fatal — that is
    # the serving layer's contract).
    futures = []
    i = 0
    stop_at = time.perf_counter() + duration_s
    while time.perf_counter() < stop_at:
      futures.append(router.submit(images[i % len(images)],
                                   slo=interactive))
      i += 1
      time.sleep(0.01)
    completed = 0
    for future in futures:
      try:
        future.result(timeout=30)
        completed += 1
      except Exception:
        pass

    # INJECTED SLO BREACH under held flushes (the FLEET overload-burst
    # idiom): admission/shedding become a pure function of arrivals +
    # the queue bound, the lowest-priority burst sheds, and the first
    # shed triggers the flight-recorder dump being validated.
    burst = 2 * max_queue * len(router.replicas)
    breach_futures = []
    with contextlib.ExitStack() as stack:
      for replica in router.replicas:
        stack.enter_context(replica.batcher.hold_flushes())
      for j in range(burst):
        breach_futures.append(
            router.submit(images[j % len(images)], slo=batch_class))
    shed = 0
    for future in breach_futures:
      try:
        future.result(timeout=60)
      except Exception:
        shed += 1
  serve_wall = time.perf_counter() - serve_start

  snapshot = stats.snapshot()
  counts = ledger.compile_counts
  expected = len(devices) * len(tuple(ladder_sizes))
  ledger_ok = (len(counts) == expected
               and all(value == 1 for value in counts.values()))
  dump_path = recorder.last_dump_path
  dump = None
  if dump_path and os.path.exists(dump_path):
    with open(dump_path) as f:
      payload = json.load(f)
    dump = {
        "path": os.path.basename(dump_path),
        "schema": payload.get("schema"),
        "reason": payload.get("reason"),
        "events": len(payload.get("events", [])),
    }
  return {
      "devices": len(devices),
      "bucket_ladder": [int(size) for size in ladder_sizes],
      "warmup_compile_s": round(warmup_s, 2),
      "requests_completed": completed,
      "breach": {
          "burst": burst,
          "shed": shed,
          "shed_total": snapshot.get("shed_total", 0),
          "flightrec": dump,
      },
      "attribution": ledger.attribution(
          wall_seconds=serve_wall,
          device_kind=devices[0].device_kind),
      "compile_counts": counts,
      "ledger_ok": bool(ledger_ok),
  }


def measure_obs(
    replay_steps: int = 300,
    host_steps: int = 40,
    serve_duration_s: float = 2.0,
    mesh_dp: Optional[int] = None,
    ladder_sizes=(1, 2, 4),
    max_queue: int = 8,
    seed: int = 0,
    logdir: Optional[str] = None,
) -> Dict:
  """Runs the full protocol (replay/host/serve phases + the registry
  export, watchdog controls, and aggregator self-check); returns the
  OBS_r13 artifact dict."""
  import jax

  from tensor2robot_tpu.obs import trace as trace_lib

  logdir = logdir or tempfile.mkdtemp(prefix="obs_bench_")
  devices = jax.devices()
  device_kind = devices[0].device_kind
  dp = mesh_dp or _largest_pow2_dp(len(devices))

  replay = _run_replay_phase(
      anakin=True, steps=replay_steps, mesh_dp=dp,
      logdir=os.path.join(logdir, "replay"), seed=seed)
  host_loop = _run_replay_phase(
      anakin=False, steps=host_steps, mesh_dp=1,
      logdir=os.path.join(logdir, "host"), seed=seed + 1)
  serve = _run_serve_phase(
      serve_duration_s, ladder_sizes, max_queue,
      dump_dir=os.path.join(logdir, "serve"), seed=seed + 2)

  tracer = trace_lib.get_tracer()
  trace_path = os.path.join(logdir, "trace.json")
  tracer.export_chrome_trace(trace_path)
  stage_counts = tracer.stage_counts()

  from tensor2robot_tpu.obs import registry as registry_lib
  registry_snapshot = {
      key: value
      for key, value in registry_lib.get_registry().snapshot().items()
      if not key.endswith(("/p90", "/max", "/mean"))}

  # Round 13: watchdog controls + the aggregator run over THIS run's
  # own artifacts (metrics.jsonl from the replay/host phases, the
  # registry snapshot exported here, the Chrome trace, the breach +
  # watchdog flightrec dumps) — so the committed artifact proves the
  # MERGE, not just the spine. The multi-process form of the same
  # merge is the separate FLEETOBS artifact (bin/obs_aggregate).
  from tensor2robot_tpu.obs import aggregate as aggregate_lib
  registry_lib.get_registry().export_snapshot(
      os.path.join(logdir, "registry.json"))
  watchdog = aggregate_lib.watchdog_controls(logdir, ci=True)
  fleet = aggregate_lib.aggregate_logdir(logdir)
  assert fleet["slo"]["consistent"], fleet["slo"]
  assert fleet["slo"]["shed_total"] >= serve["breach"]["shed"], (
      fleet["slo"], serve["breach"])
  assert fleet["trace"]["linked_serve_timelines"] >= 1, fleet["trace"]
  assert watchdog["injected_stall"]["ok"], watchdog
  assert watchdog["healthy_control"]["ok"], watchdog
  fleetobs = {
      "hosts_merged": fleet["hosts_merged"],
      "inputs": fleet["inputs"],
      "slo": fleet["slo"],
      "trace": {key: fleet["trace"][key]
                for key in ("file", "events", "request_ids_seen",
                            "flows_linked", "linked_serve_timelines",
                            "example_timeline")},
      "flightrec_reasons": fleet["flightrec"]["reasons"],
      "stragglers": fleet["stragglers"],
      "consistent": fleet["slo"]["consistent"],
  }

  return {
      "round": 13,
      "metric": ("observability spine: per-executable device-time "
                 "attribution + spans + metric registry + flight "
                 "recorder across the production loop, plus (r13) "
                 "correlation-linked request timelines, the fleet "
                 "aggregator self-check, and the stall watchdog "
                 "controls"),
      "device_kind": device_kind,
      "virtual_mesh": device_kind.lower() == "cpu",
      "devices": len(devices),
      "mesh_dp": dp,
      "replay": replay,
      "host_loop": host_loop,
      "serve": serve,
      "trace": {
          "file": os.path.basename(trace_path),
          "logdir": logdir,
          "spans_total": tracer.total_spans,
          "stage_counts": stage_counts,
      },
      "registry": registry_snapshot,
      "watchdog": watchdog,
      "fleetobs": fleetobs,
      "flightrec_schema": "t2r-flightrec-1",
      "note": (
          "Attribution shares are host wall-clock dispatch windows "
          "over each phase's run window (sum <= 1.0; the remainder is "
          "host work outside any executable). estimated_mfu is null "
          "with virtual_mesh=true — no peak-FLOPs model for this host "
          "(the MULTICHIP caveat applied to utilization); real-chip "
          "attribution lands via bench.py's obs block, same schema. "
          "The Chrome trace and flight-recorder dump live in the "
          "run's logdir (paths are run-local, basenames recorded "
          "here); the fused anakin path reports act/step/extend/learn "
          "as ONE learn/anakin_step span by construction — the "
          "host_loop phase is where the act/extend/learn stages are "
          "separate host spans."),
  }


def main(argv=None) -> None:
  """CLI: ONE JSON line (the bench contract). --smoke bootstraps the
  8-virtual-device CPU mesh (re-exec with the canonical env) and runs
  the committed OBS_r13 protocol; --ci is the reduced tier-1 lane."""
  import argparse
  import sys

  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument("--smoke", action="store_true",
                      help="chipless committed-artifact lane (OBS_r13): "
                           "8 virtual CPU devices, full protocol")
  parser.add_argument("--ci", action="store_true",
                      help="reduced chipless lane for tier-1 tests")
  parser.add_argument("--seed", type=int, default=0)
  parser.add_argument("--logdir", default=None,
                      help="trace/flightrec output dir (default: a "
                           "tempdir; printed in the artifact)")
  parser.add_argument("--out", default=None,
                      help="also write the JSON line to this file")
  args = parser.parse_args(argv)
  if args.smoke or args.ci:
    from tensor2robot_tpu.utils.cpu_mesh_env import (cpu_mesh_env,
                                                     is_cpu_mesh_env)
    if not is_cpu_mesh_env(8):
      if argv is not None:
        raise RuntimeError(
            "--smoke/--ci need the 8-virtual-device CPU mesh "
            "configured before JAX initializes; call main() with "
            "argv=None (the CLI re-execs itself).")
      os.execve(sys.executable,
                [sys.executable, "-m", "tensor2robot_tpu.obs.obs_bench",
                 *sys.argv[1:]],
                cpu_mesh_env(8))
  kwargs = dict(seed=args.seed, logdir=args.logdir)
  if args.ci:
    kwargs.update(replay_steps=40, host_steps=12, serve_duration_s=1.0)
  results = measure_obs(**kwargs)
  line = json.dumps(results)
  if args.out:
    with open(args.out, "w") as f:
      f.write(line + "\n")
  print(line)


if __name__ == "__main__":
  main()

"""Process-wide typed metric registry: counters, gauges, histograms.

Before this module every subsystem kept its own scalar plumbing:
``ServingStats`` counters, the replay loop's ad-hoc health dicts, the
trainer's per-sync metric maps. The registry is the one namespace they
all emit through; the EXISTING ``utils.metric_writer.MetricWriter``
(JSONL + TensorBoard) stays the dashboard — ``flush_to`` is the single
bridge, so a metric registered anywhere reaches both sinks with no new
plumbing, and the JSONL records carry host/pid for the coming
multi-host tier (stamped by MetricWriter itself).

Types are enforced: asking for ``counter("x")`` after ``gauge("x")``
raises instead of silently aliasing two semantics onto one name.
Histograms are bounded reservoirs (newest ``max_samples`` kept) with
nearest-rank p50/p99 snapshots — the same percentile convention
``serving.stats.LatencyHistogram`` established.
"""

from __future__ import annotations

import collections
import json
import math
import os
import socket
import threading
from typing import Dict, Iterable, Mapping, Optional

# Schema tag for on-disk registry snapshots (the fleet aggregator's
# input format — obs/aggregate.py merges one per process).
SNAPSHOT_SCHEMA = "t2r-registry-1"


def _nearest_rank(ordered, pct: float) -> float:
  rank = min(len(ordered) - 1,
             max(0, math.ceil(pct / 100.0 * len(ordered)) - 1))
  return ordered[rank]


class Counter:
  """Monotonic process-lifetime count."""

  __slots__ = ("name", "_value", "_lock")

  def __init__(self, name: str):
    self.name = name
    self._value = 0
    self._lock = threading.Lock()

  def inc(self, n: int = 1) -> int:
    with self._lock:
      self._value += n
      return self._value

  @property
  def value(self) -> int:
    with self._lock:
      return self._value


class Gauge:
  """Last-write-wins scalar."""

  __slots__ = ("name", "_value", "_lock")

  def __init__(self, name: str):
    self.name = name
    self._value: Optional[float] = None
    self._lock = threading.Lock()

  def set(self, value: float) -> None:
    with self._lock:
      self._value = float(value)

  @property
  def value(self) -> Optional[float]:
    with self._lock:
      return self._value


class Histogram:
  """Bounded reservoir (newest max_samples) with percentile snapshots."""

  __slots__ = ("name", "_samples", "_count", "_lock")

  def __init__(self, name: str, max_samples: int = 16384):
    self.name = name
    self._samples: collections.deque = collections.deque(maxlen=max_samples)
    self._count = 0
    self._lock = threading.Lock()

  def record(self, value: float) -> None:
    with self._lock:
      self._samples.append(float(value))
      self._count += 1

  def samples(self) -> list:
    """The retained reservoir (newest max_samples). This is what the
    fleet aggregator unions across processes so the merged p99 comes
    from ONE nearest-rank pass over real samples instead of averaging
    per-process percentiles (which has no statistical meaning)."""
    with self._lock:
      return list(self._samples)

  @property
  def count(self) -> int:
    """Samples ever recorded (the reservoir may have dropped oldest)."""
    with self._lock:
      return self._count

  def snapshot(self, digits: int = 4) -> Dict[str, float]:
    with self._lock:
      samples = list(self._samples)
      count = self._count
    if not samples:
      return {"count": 0}
    ordered = sorted(samples)
    return {
        "count": count,
        "p50": round(_nearest_rank(ordered, 50), digits),
        "p90": round(_nearest_rank(ordered, 90), digits),
        "p99": round(_nearest_rank(ordered, 99), digits),
        "max": round(ordered[-1], digits),
        "mean": round(sum(samples) / len(samples), digits),
    }


class MetricRegistry:
  """Typed name → metric map with one MetricWriter bridge."""

  def __init__(self):
    self._metrics: Dict[str, object] = {}
    self._lock = threading.Lock()

  def _get(self, name: str, kind):
    with self._lock:
      metric = self._metrics.get(name)
      if metric is None:
        metric = self._metrics[name] = kind(name)
      elif not isinstance(metric, kind):
        raise TypeError(
            f"metric {name!r} is a {type(metric).__name__}, not a "
            f"{kind.__name__} — one name, one type")
      return metric

  def counter(self, name: str) -> Counter:
    return self._get(name, Counter)

  def gauge(self, name: str) -> Gauge:
    return self._get(name, Gauge)

  def histogram(self, name: str) -> Histogram:
    return self._get(name, Histogram)

  def set_gauges(self, scalars: Mapping[str, float]) -> None:
    """Batch gauge update (the loops' per-sync health blocks)."""
    for name, value in scalars.items():
      if value is None:
        continue
      self.gauge(name).set(value)

  def names(self) -> Iterable[str]:
    with self._lock:
      return sorted(self._metrics)

  def snapshot(self, names: Optional[Iterable[str]] = None
               ) -> Dict[str, float]:
    """Flat scalar view: counters/gauges by name, histograms flattened
    to ``name/p50`` ``name/p99`` ``name/mean`` ``name/count``.
    ``names`` restricts to those metric names BEFORE any histogram
    reservoir is sorted — flushing a handful of gauges must not pay
    for every 16k-sample latency reservoir in the process."""
    with self._lock:
      metrics = dict(self._metrics)
    if names is not None:
      wanted = set(names)
      metrics = {name: metric for name, metric in metrics.items()
                 if name in wanted}
    out: Dict[str, float] = {}
    for name, metric in sorted(metrics.items()):
      if isinstance(metric, Histogram):
        for key, value in metric.snapshot().items():
          out[f"{name}/{key}"] = value
      else:
        value = metric.value
        if value is not None:
          out[name] = value
    return out

  def export_snapshot(self, path: str,
                      host: Optional[str] = None) -> str:
    """Writes this process's full registry state for the fleet merge.

    Atomic (tmp → mv), host/pid-stamped, schema-versioned. Counters
    and gauges export their values; histograms export their RAW
    reservoir (plus the true count), because cross-process percentile
    merging needs samples, not percentiles — obs/aggregate.py unions
    the reservoirs and runs the one nearest-rank pass.

    ``host`` overrides the hostname stamp — the multi-host emulation
    seam (ISSUE 19): per-emulated-host registries written from one
    machine keep distinct ``host:pid`` merge keys, so the aggregator's
    per-source Q-drift attribution names the emulated host exactly as
    a real pod's would.
    """
    with self._lock:
      metrics = dict(self._metrics)
    counters: Dict[str, int] = {}
    gauges: Dict[str, float] = {}
    histograms: Dict[str, dict] = {}
    for name, metric in sorted(metrics.items()):
      if isinstance(metric, Counter):
        counters[name] = metric.value
      elif isinstance(metric, Gauge):
        if metric.value is not None:
          gauges[name] = metric.value
      elif isinstance(metric, Histogram):
        histograms[name] = {"count": metric.count,
                            "samples": metric.samples()}
    payload = {
        "schema": SNAPSHOT_SCHEMA,
        "host": host or socket.gethostname(),
        "pid": os.getpid(),
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
      json.dump(payload, f)
    os.replace(tmp, path)
    return path

  def flush_to(self, metric_writer, step: int,
               names: Optional[Iterable[str]] = None,
               prefix: str = "") -> None:
    """THE bridge: one ``write_scalars`` call per flush.

    ``names`` restricts the flush to those metric names (the loops pass
    exactly the block they just updated, so their JSONL records keep
    the pre-registry schema byte-for-byte); None flushes everything.
    """
    snap = self.snapshot(names=names)
    scalars = {prefix + key: value for key, value in snap.items()
               if isinstance(value, (int, float))}
    if scalars:
      metric_writer.write_scalars(step, scalars)


_DEFAULT: Optional[MetricRegistry] = None
_DEFAULT_LOCK = threading.Lock()


def get_registry() -> MetricRegistry:
  """The process-wide registry every wired component emits through."""
  global _DEFAULT
  with _DEFAULT_LOCK:
    if _DEFAULT is None:
      _DEFAULT = MetricRegistry()
    return _DEFAULT

"""Host-side structured spans for the whole production loop.

``span("replay/learn", **attrs)`` is a thread-safe, nestable context
manager. Completed spans land in a bounded in-memory ring and are
exportable as ONE Chrome-trace/Perfetto JSON file per run
(``Tracer.export_chrome_trace``), so "where did the wall-clock go"
is answerable for any run without a debugger attached.

Span names are ``stage/detail`` — the first path segment is the loop
stage (``act``, ``extend``, ``learn``, ``serve``, ``replay``), which
``stage_counts()`` aggregates and the obs bench asserts coverage over.

While a device trace is active (the guarded window in
``utils.profiling``), every span ALSO enters a
``jax.profiler.TraceAnnotation`` with the same name, so host spans line
up against XLA device lanes in the same Perfetto view. Outside a trace
window the annotation is skipped entirely — the hot-path cost of a span
is two ``perf_counter`` reads and one deque append.

Listeners (``add_listener``) receive every completed span dict — the
flight recorder subscribes so the last N spans are always available for
a post-mortem dump.

Correlation (ISSUE 12): spans completed while ``obs.context`` has a
bound ``request_id``/``step_id`` carry those ids as attrs
automatically, and ``export_chrome_trace`` links every request id seen
on >= 2 spans into one Perfetto *flow* (arrow chain across thread
lanes) — the per-request timeline the fleet aggregator merges across
processes.
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional

from tensor2robot_tpu.obs import context as context_lib

_log = logging.getLogger(__name__)


class Tracer:
  """Bounded ring of completed spans + per-thread nesting state."""

  def __init__(self, max_spans: int = 65536):
    self._epoch = time.perf_counter()
    self._spans: collections.deque = collections.deque(maxlen=max_spans)
    self._total = 0
    self._lock = threading.Lock()
    self._local = threading.local()
    self._listeners: List[Callable[[dict], None]] = []
    # Toggled by utils.profiling's guarded start/stop_trace: spans only
    # pay the TraceAnnotation cost while a device trace can see them.
    self.annotate_devices = False

  # -- recording -----------------------------------------------------------

  def _stack(self) -> list:
    stack = getattr(self._local, "stack", None)
    if stack is None:
      stack = self._local.stack = []
    return stack

  @contextlib.contextmanager
  def span(self, name: str, **attrs):
    """One nestable span; attrs must be JSON-serializable scalars."""
    stack = self._stack()
    parent = stack[-1] if stack else None
    depth = len(stack)
    stack.append(name)
    annotation = None
    if self.annotate_devices:
      import jax
      annotation = jax.profiler.TraceAnnotation(name)
      annotation.__enter__()
    start = time.perf_counter()
    try:
      yield
    finally:
      duration = time.perf_counter() - start
      if annotation is not None:
        annotation.__exit__(None, None, None)
      stack.pop()
      record = {
          "name": name,
          "ts_s": round(start - self._epoch, 6),
          "dur_s": round(duration, 6),
          "tid": threading.get_ident(),
          "depth": depth,
      }
      if parent is not None:
        record["parent"] = parent
      context_attrs = context_lib.context_attrs()
      if context_attrs:
        record.update(context_attrs)
      if attrs:  # explicit attrs win over inherited context attrs
        record.update(attrs)
      with self._lock:
        self._spans.append(record)
        self._total += 1
      for listener in list(self._listeners):
        try:
          listener(record)
        except Exception:  # diagnostics must never crash the path
          _log.warning("span listener %r failed", listener,
                       exc_info=True)

  def add_listener(self, listener: Callable[[dict], None]) -> None:
    """Registers a completed-span callback (e.g. the flight recorder)."""
    with self._lock:
      if listener not in self._listeners:
        self._listeners.append(listener)

  def remove_listener(self, listener: Callable[[dict], None]) -> None:
    """Unsubscribes a listener; unknown listeners are a no-op (a
    recorder detaching twice must not raise in a finally block)."""
    with self._lock:
      if listener in self._listeners:
        self._listeners.remove(listener)

  # -- readout -------------------------------------------------------------

  def spans(self) -> List[dict]:
    with self._lock:
      return list(self._spans)

  @property
  def total_spans(self) -> int:
    """Spans ever recorded (the ring may have dropped the oldest)."""
    with self._lock:
      return self._total

  def stage_counts(self) -> Dict[str, int]:
    """{first path segment of span name: count} over the retained ring."""
    counts: Dict[str, int] = {}
    for record in self.spans():
      stage = record["name"].split("/", 1)[0]
      counts[stage] = counts.get(stage, 0) + 1
    return counts

  def clear(self) -> None:
    with self._lock:
      self._spans.clear()
      self._total = 0

  def export_chrome_trace(self, path: str,
                          label: Optional[str] = None) -> str:
    """Writes the retained spans as Chrome-trace JSON (atomic tmp→mv).

    Loads directly in Perfetto / chrome://tracing; complete events
    ("ph": "X") with microsecond timestamps relative to this tracer's
    epoch, one row per Python thread. Every request id carried by
    >= 2 spans (the ``request_id``/``request_ids`` attr convention,
    obs/context.py) additionally becomes one flow — "s"/"t"/"f"
    arrow events with a shared id — so a request's enqueue → flush →
    dispatch hops across threads read as one clickable timeline.

    ``label`` overrides the ``host:pid`` process_name metadata — the
    front door (serving/frontdoor.py) exports its OWN tracer under its
    own label so the fleet merge gives the ingress hop its own lane
    and cross-lane request flows (ISSUE 19).
    """
    retained = self.spans()
    pid = os.getpid()
    # Wall-clock anchor for the fleet merge: span timestamps are
    # relative to THIS tracer's construction-time perf_counter epoch,
    # which is meaningless across processes — epoch_wall_s is that
    # epoch on the shared wall clock, so obs/aggregate.py can offset
    # each process's lane onto one comparable timeline.
    epoch_wall_s = time.time() - (time.perf_counter() - self._epoch)
    events = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": label or f"{socket.gethostname()}:{pid}",
                 "epoch_wall_s": round(epoch_wall_s, 6)},
    }]
    by_request: Dict[str, list] = {}
    for record in retained:
      args = {key: value for key, value in record.items()
              if key not in ("name", "ts_s", "dur_s", "tid")}
      events.append({
          "name": record["name"],
          "ph": "X",
          "ts": round(record["ts_s"] * 1e6, 3),
          "dur": round(record["dur_s"] * 1e6, 3),
          "pid": pid,
          "tid": record["tid"],
          "args": args,
      })
      for request_id in context_lib.span_request_ids(record):
        by_request.setdefault(request_id, []).append(record)
    events.extend(request_flow_events(by_request, pid))
    payload = {"traceEvents": events, "displayTimeUnit": "ms"}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
      json.dump(payload, f)
    os.replace(tmp, path)
    return path


def request_flow_events(by_request: Dict[str, list], pid: int,
                        flow_ids: Optional[Dict[str, int]] = None) -> list:
  """Perfetto flow events linking each request's spans in time order.

  ``by_request`` maps request id → span records (the tracer's dict
  shape); ids with fewer than two spans emit nothing (an arrow needs
  two ends). ``flow_ids`` lets the fleet aggregator keep flow ids
  stable while merging several processes' traces — same request id in
  two files, one arrow chain across both. A record carrying its own
  ``pid`` (the aggregator's remapped per-process lanes) overrides the
  default ``pid``.
  """
  flow_ids = {} if flow_ids is None else flow_ids
  events = []
  for request_id, records in sorted(by_request.items()):
    if len(records) < 2:
      continue
    flow_id = flow_ids.setdefault(request_id, len(flow_ids) + 1)
    ordered = sorted(records, key=lambda r: r["ts_s"])
    for index, record in enumerate(ordered):
      if index == 0:
        phase = "s"
      elif index == len(ordered) - 1:
        phase = "f"
      else:
        phase = "t"
      event = {
          "name": f"request {request_id}",
          "cat": "request",
          "ph": phase,
          "id": flow_id,
          # Bind the arrow end INSIDE its slice (not at the edge) so
          # Perfetto attaches it to the enclosing span unambiguously.
          "ts": round((record["ts_s"] + record["dur_s"] / 2) * 1e6, 3),
          "pid": record.get("pid", pid),
          "tid": record["tid"],
      }
      if phase == "f":
        event["bp"] = "e"
      events.append(event)
  return events


_DEFAULT: Optional[Tracer] = None
_DEFAULT_LOCK = threading.Lock()


def get_tracer() -> Tracer:
  """The process-wide tracer every wired component records into."""
  global _DEFAULT
  with _DEFAULT_LOCK:
    if _DEFAULT is None:
      _DEFAULT = Tracer()
    return _DEFAULT


def span(name: str, **attrs):
  """``with obs.trace.span("learn/megastep", k=10): ...``"""
  return get_tracer().span(name, **attrs)


def set_device_annotations(enabled: bool) -> None:
  """Flip TraceAnnotation emission on the process tracer (the guarded
  profiler window in utils.profiling owns this flag)."""
  get_tracer().annotate_devices = bool(enabled)

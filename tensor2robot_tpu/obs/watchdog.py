"""Stall / straggler watchdog for the loop's long-lived threads.

A decoupled multi-process loop (the Podracer shape, PAPERS.md) fails
quietly: a learner blocked on a dead feeder still *looks* alive from
the outside, and a straggling host drags the fleet's step rate down
without any single process erroring. The watchdog makes both failure
modes first-class observability events:

- **Heartbeats**: every loop thread (ReplayTrainLoop learner/feeder,
  collector/actor threads, batcher dispatchers, the rollout worker)
  registers a named heartbeat and calls ``beat()`` whenever it makes
  real progress. A thread that is *intentionally* waiting (an idle
  dispatcher with an empty queue) calls ``idle()`` — idleness is not a
  stall, and the distinction is what keeps the healthy-run negative
  control at zero events.
- **Stalls**: the monitor thread flags a component whose progress
  counter has not advanced within its per-component deadline.
  Escalation mirrors the PR 8 listener contract — exception-isolated
  at every hop so diagnostics never crash the observed path:
  registry counters (``watchdog/stalls`` + per-component), a
  rate-limited flight-recorder dump (reason ``watchdog_stall``,
  carrying the stalled component plus the ring's recent spans — the
  component's own last spans are in there via the tracer listener),
  then the optional ``on_stall`` callback. A component that beats
  again after a stall records a ``watchdog_recovered`` ring event and
  re-arms.
- **Stragglers**: cross-process by construction — one process cannot
  know the fleet median. ``find_stragglers`` takes the per-host step
  rates the aggregator (obs/aggregate.py) computes from the merged
  ``metrics.jsonl`` streams and flags any host/component below
  ``fraction`` of the fleet median; the FLEETOBS artifact carries the
  result.

Deadlines are wall-clock and must absorb CI noise: tests follow the
repo's ``os.cpu_count() >= 4`` gating convention by scaling deadlines
up on small hosts (see ``scaled_deadline``) instead of asserting tight
timing everywhere.
"""

from __future__ import annotations

import logging
import os
import statistics
import threading
import time
from typing import Callable, Dict, List, Mapping, Optional

from tensor2robot_tpu.obs import flight_recorder as flight_lib
from tensor2robot_tpu.obs import registry as registry_lib

_log = logging.getLogger(__name__)

# Event schema version for watchdog_stall flight-recorder triggers —
# the aggregator validates dumps against these fields.
STALL_FIELDS = ("component", "stalled_for_s", "deadline_s", "beats")


def scaled_deadline(deadline_s: float, min_cores: int = 4,
                    factor: float = 4.0) -> float:
  """The timing-bar gating convention applied to deadlines: on hosts
  below ``min_cores`` a stall deadline is scaled UP by ``factor`` so
  slow-CI scheduling noise cannot masquerade as a stall (the false
  positive the negative-control test guards against)."""
  if (os.cpu_count() or 1) < min_cores:
    return deadline_s * factor
  return deadline_s


class Heartbeat:
  """One component's liveness record (name + monotonic progress)."""

  __slots__ = ("name", "deadline_s", "_beats", "_last_beat", "_idle",
               "registered_at")

  def __init__(self, name: str, deadline_s: float):
    self.name = name
    self.deadline_s = deadline_s
    self._beats = 0
    now = time.monotonic()
    self._last_beat = now
    self.registered_at = now
    # Born idle: a registered component has not promised progress yet
    # (a batcher may start with an empty queue). The first beat or an
    # explicit busy() arms stall detection.
    self._idle = True

  def beat(self, n: int = 1) -> None:
    """Progress happened. Single attribute stores (GIL-atomic) — no
    lock on the hot path; the monitor reads a consistent-enough pair."""
    self._beats += n
    self._last_beat = time.monotonic()
    self._idle = False

  def idle(self) -> None:
    """About to wait for work on purpose: not a stall."""
    self._idle = True

  def busy(self) -> None:
    """Work is pending but no progress yet — arms stall detection
    without counting a beat (e.g. a dispatcher that woke to a held
    queue). Coming out of idle resets the clock: the stall deadline
    runs from when work ARRIVED, not from the last beat before a long
    legitimate idle stretch."""
    if self._idle:
      self._last_beat = time.monotonic()
      self._idle = False

  @property
  def beats(self) -> int:
    return self._beats

  @property
  def is_idle(self) -> bool:
    return self._idle

  def age_s(self, now: Optional[float] = None) -> float:
    """Seconds since the last beat (or since registration)."""
    return (time.monotonic() if now is None else now) - self._last_beat


class Watchdog:
  """Monitors registered heartbeats; escalates stalls, never crashes.

  Args:
    poll_s: monitor thread check cadence.
    default_deadline_s: per-component deadline when register() doesn't
      name one.
    recorder: flight recorder for ``watchdog_stall`` dumps (default:
      the process recorder — ring-only until a dump_dir is
      configured, same contract as every other trigger source).
    registry: metric registry for the stall counters (default: the
      process registry).
    on_stall: optional callback receiving the stall event dict;
      exceptions are logged and swallowed (listener contract).
  """

  def __init__(self, poll_s: float = 0.5,
               default_deadline_s: float = 30.0,
               recorder: Optional[flight_lib.FlightRecorder] = None,
               registry: Optional[registry_lib.MetricRegistry] = None,
               on_stall: Optional[Callable[[dict], None]] = None):
    self.poll_s = poll_s
    self.default_deadline_s = default_deadline_s
    self._recorder = recorder
    self._registry = registry
    self._on_stall = on_stall
    self._lock = threading.Lock()
    self._heartbeats: Dict[str, Heartbeat] = {}
    self._stalled: Dict[str, bool] = {}
    self.events: List[dict] = []  # stall/recovery history (bounded)
    self._max_events = 256
    self._thread: Optional[threading.Thread] = None
    self._stop = threading.Event()

  # -- registration --------------------------------------------------------

  def register(self, name: str,
               deadline_s: Optional[float] = None) -> Heartbeat:
    """Registers a component; a taken name gets a ``#<n>`` suffix so
    two loops in one process cannot silently share (and reset) one
    heartbeat — the per-recorder-instance lesson applied here."""
    deadline = (self.default_deadline_s if deadline_s is None
                else float(deadline_s))
    with self._lock:
      unique = name
      n = 2
      while unique in self._heartbeats:
        unique = f"{name}#{n}"
        n += 1
      heartbeat = Heartbeat(unique, deadline)
      self._heartbeats[unique] = heartbeat
      self._stalled[unique] = False
    return heartbeat

  def unregister(self, heartbeat: Heartbeat) -> None:
    """Removes a component (loop shutdown); unknown entries are a
    no-op so a finally-block unregister can never raise."""
    with self._lock:
      current = self._heartbeats.get(heartbeat.name)
      if current is heartbeat:
        del self._heartbeats[heartbeat.name]
        self._stalled.pop(heartbeat.name, None)

  # -- monitoring ----------------------------------------------------------

  def check_once(self, now: Optional[float] = None) -> List[dict]:
    """One monitor pass; returns the NEW stall events it raised.

    Separated from the thread loop so tests (and the aggregator's
    offline view) can drive detection deterministically.
    """
    now = time.monotonic() if now is None else now
    new_events: List[dict] = []
    with self._lock:
      snapshot = list(self._heartbeats.values())
    for heartbeat in snapshot:
      # Read is_idle BEFORE age: busy()/beat() store _last_beat first
      # and flip _idle second, so an idle=False read here guarantees
      # the _last_beat we read next is at least as fresh — the reverse
      # read order could pair a stale idle-era timestamp with the
      # just-armed busy flag and flag a healthy component the instant
      # it comes out of a long legitimate idle.
      if heartbeat.is_idle:
        stalled_now = False
      else:
        stalled_now = heartbeat.age_s(now) > heartbeat.deadline_s
      age = heartbeat.age_s(now)
      with self._lock:
        if self._heartbeats.get(heartbeat.name) is not heartbeat:
          # Unregistered (or replaced) between the snapshot and this
          # check: a finished component must never be escalated, and
          # writing _stalled for it would leak the key forever.
          continue
        was_stalled = self._stalled.get(heartbeat.name, False)
        if stalled_now == was_stalled:
          continue
        self._stalled[heartbeat.name] = stalled_now
        event = {
            "event": "watchdog_stall" if stalled_now
                     else "watchdog_recovered",
            "component": heartbeat.name,
            "stalled_for_s": round(age, 3),
            "deadline_s": heartbeat.deadline_s,
            "beats": heartbeat.beats,
            "t_monotonic": round(now, 3),
        }
        self.events.append(event)
        if len(self.events) > self._max_events:
          del self.events[:len(self.events) - self._max_events]
      if stalled_now:
        new_events.append(event)
        self._escalate(event)
      else:
        self._record_recovery(event)
    return new_events

  def _escalate(self, event: dict) -> None:
    """counter → rate-limited dump → callback; each hop isolated."""
    try:
      registry = self._registry or registry_lib.get_registry()
      registry.counter("watchdog/stalls").inc()
      registry.counter(
          f"watchdog/stall/{event['component']}").inc()
    except Exception:
      _log.warning("watchdog registry escalation failed", exc_info=True)
    try:
      recorder = self._recorder or flight_lib.get_recorder()
      recorder.trigger(
          "watchdog_stall",
          component=event["component"],
          stalled_for_s=event["stalled_for_s"],
          deadline_s=event["deadline_s"],
          beats=event["beats"])
    except Exception:
      _log.warning("watchdog recorder escalation failed", exc_info=True)
    if self._on_stall is not None:
      try:
        self._on_stall(event)
      except Exception:  # listener contract: diagnostics never crash
        _log.warning("watchdog on_stall callback failed", exc_info=True)

  def _record_recovery(self, event: dict) -> None:
    try:
      recorder = self._recorder or flight_lib.get_recorder()
      recorder.record("event", "watchdog_recovered",
                      component=event["component"],
                      beats=event["beats"])
    except Exception:
      _log.warning("watchdog recovery record failed", exc_info=True)

  def _run(self) -> None:
    while not self._stop.wait(self.poll_s):
      try:
        self.check_once()
      except Exception:  # the monitor must outlive any check failure
        _log.warning("watchdog check failed", exc_info=True)

  def start(self) -> "Watchdog":
    with self._lock:
      if self._thread is not None:
        return self
      self._stop.clear()
      self._thread = threading.Thread(
          target=self._run, name="obs-watchdog", daemon=True)
    self._thread.start()
    return self

  def stop(self) -> None:
    with self._lock:
      thread, self._thread = self._thread, None
    if thread is not None:
      self._stop.set()
      thread.join(10.0)

  def __enter__(self) -> "Watchdog":
    return self.start()

  def __exit__(self, *exc_info) -> None:
    self.stop()

  # -- readout -------------------------------------------------------------

  @property
  def stall_count(self) -> int:
    with self._lock:
      return sum(1 for event in self.events
                 if event["event"] == "watchdog_stall")

  def snapshot(self) -> dict:
    """Current component table + event history (artifact-ready)."""
    now = time.monotonic()
    with self._lock:
      components = {
          name: {
              "beats": heartbeat.beats,
              "age_s": round(heartbeat.age_s(now), 3),
              "deadline_s": heartbeat.deadline_s,
              "idle": heartbeat.is_idle,
              "stalled": self._stalled.get(name, False),
          }
          for name, heartbeat in sorted(self._heartbeats.items())}
      events = [dict(event) for event in self.events]
    return {
        "components": components,
        "stalls": sum(1 for event in events
                      if event["event"] == "watchdog_stall"),
        "events": events,
    }


def find_stragglers(rates: Mapping[str, float],
                    fraction: float = 0.5) -> dict:
  """Flags fleet members whose rate falls below ``fraction`` x median.

  ``rates`` maps a member key (the aggregator uses ``host:pid``) to
  its step rate. Needs >= 2 members — a fleet of one has no median to
  straggle against. None/zero-rate members are compared like any
  other (a stopped host IS the worst straggler).
  """
  cleaned = {name: float(rate or 0.0) for name, rate in rates.items()}
  if len(cleaned) < 2:
    return {"fleet_median": None, "threshold": None, "stragglers": []}
  median = statistics.median(cleaned.values())
  threshold = fraction * median
  stragglers = [
      {"name": name, "rate": round(rate, 4),
       "fleet_median": round(median, 4)}
      for name, rate in sorted(cleaned.items())
      if rate < threshold]
  return {
      "fleet_median": round(median, 4),
      "threshold": round(threshold, 4),
      "stragglers": stragglers,
  }


_DEFAULT: Optional[Watchdog] = None
_DEFAULT_LOCK = threading.Lock()


def get_watchdog() -> Watchdog:
  """The process-wide watchdog components register into by default.

  NOT started automatically: registration + beats are cheap counter
  stores, and the monitor thread only runs once an owner (a loop, a
  bench, a deployment main) calls ``start()`` — zero behavior change
  for code that never opts in.
  """
  global _DEFAULT
  with _DEFAULT_LOCK:
    if _DEFAULT is None:
      _DEFAULT = Watchdog()
    return _DEFAULT

"""Pallas TPU kernels for the framework's hot ops.

XLA fuses most of the elementwise work into the surrounding matmuls
already; these kernels cover the reductions XLA schedules poorly. Every
op has an XLA reference implementation, an `implementation="auto"`
switch, and runs the Pallas path in interpreter mode off-TPU so CPU CI
tests the same kernel code.
"""

from tensor2robot_tpu.ops.spatial_softmax import (
    spatial_softmax,
    spatial_softmax_reference,
)
from tensor2robot_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_reference,
)

"""Trace-time dispatch control for the Pallas ops.

Multi-platform export (jax.export / jax2tf with platforms=("cpu",
"tpu")) lowers every branch of the computation for every target
platform — including branches guarded by jax.lax.platform_dependent —
and a compiled pallas_call cannot lower for CPU. Exporters therefore
wrap their tracing in `xla_only()`, which makes every op's "auto" path
pick its XLA reference at trace time. Thread-local, so an async export
worker forcing XLA does not affect the training step being traced on
the main thread.
"""

from __future__ import annotations

import contextlib
import threading

_STATE = threading.local()


def use_xla_only() -> bool:
  return getattr(_STATE, "xla_only", False)


@contextlib.contextmanager
def xla_only():
  """Within this context, ops' "auto" paths trace the XLA reference."""
  previous = use_xla_only()
  _STATE.xla_only = True
  try:
    yield
  finally:
    _STATE.xla_only = previous

"""Blockwise (flash) multi-head attention — Pallas TPU kernel.

Single-device counterpart of parallel/ring_attention.py: the same
running-max/denominator accumulation, but blocked over VMEM tiles inside
one chip instead of over ring hops. O(T) HBM traffic for the forward
pass instead of materializing the (B, H, T, T) score tensor (which is
what the XLA reference below does). Used for long in-device sequences;
ring_attention composes it across chips for sequences that exceed one
device.

Gradient: custom_vjp with Pallas backward kernels (the standard flash
backward — residuals are q, k, v, the output, and the per-row
logsumexp; dq and dk/dv are recomputed blockwise in two passes), so
training memory stays O(T) end to end. First-order only — custom_vjp
does not compose with forward-over-reverse, so models differentiated
twice (MAML inner loops) must pass implementation="xla".
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tensor2robot_tpu.ops import dispatch

_BLOCK = 128
_MAX_SINGLE_BLOCK_T = 1024
# K and V are staged whole per (b·h) row, and Pallas double-buffers
# pipelined inputs — so the resident K/V footprint is 2× their size.
# Bound that under the ~16 MB scoped-VMEM budget with headroom for the
# Q/O/lse tiles and f32 working set (measured on v5e: T=8192, D=128
# bf16 fits; T=16384 overflows the 16 MB limit by the double buffer).
# Longer sequences belong to ring_attention.
_MAX_KV_VMEM_BYTES = 14 * 1024 * 1024
_PIPELINE_BUFFERS = 2


def flash_attention_reference(q, k, v, causal: bool = False,
                              scale: Optional[float] = None):
  """XLA reference: materializes (B, H, T, T) scores. (B, T, H, D) in/out."""
  if scale is None:
    scale = 1.0 / math.sqrt(q.shape[-1])
  scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                      k.astype(jnp.float32)) * scale
  if causal:
    t = q.shape[1]
    mask = jnp.tril(jnp.ones((t, t), bool))
    scores = jnp.where(mask[None, None], scores, -jnp.inf)
  weights = jax.nn.softmax(scores, axis=-1)
  out = jnp.einsum("bhqk,bkhd->bqhd", weights, v.astype(jnp.float32))
  return out.astype(q.dtype)


def _causal_mask(s, qi, kj, block_q: int, block_k: int):
  """Mask the (BQ, BK) score tile to the causal triangle with -inf."""
  rows = qi * block_q + jax.lax.broadcasted_iota(
      jnp.int32, (block_q, block_k), 0)
  cols = kj * block_k + jax.lax.broadcasted_iota(
      jnp.int32, (block_q, block_k), 1)
  return jnp.where(rows >= cols, s, -jnp.inf)


def _kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale: float,
            causal: bool, block_q: int, block_k: int, seq_len: int):
  """One (block_q, D) query tile vs all K/V tiles of this (b·h) row.

  Also emits the per-row logsumexp (the flash-backward residual)."""
  q = q_ref[0].astype(jnp.float32) * scale                 # (BQ, D)
  qi = pl.program_id(1)
  head_dim = q.shape[-1]

  def body(kj, carry):
    m, l, acc = carry
    k_blk = k_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
    v_blk = v_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                # (BQ, BK)
    if causal:
      s = _causal_mask(s, qi, kj, block_q, block_k)
    m_blk = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_blk)
    safe_max = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    correction = jnp.exp(m - safe_max)
    p = jnp.exp(s - safe_max)
    l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * correction + jnp.dot(
        p, v_blk, preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new

  if causal:
    # Only K blocks that intersect the causal triangle of this Q tile.
    num_k = (qi * block_q + block_q + block_k - 1) // block_k
  else:
    num_k = seq_len // block_k
  init = (jnp.full((block_q, 1), -jnp.inf, jnp.float32),
          jnp.zeros((block_q, 1), jnp.float32),
          jnp.zeros((block_q, head_dim), jnp.float32))
  m, l, acc = jax.lax.fori_loop(0, num_k, body, init)
  safe_m = jnp.where(jnp.isneginf(m), 0.0, m)
  # Fully-masked rows (l == 0, only possible non-causally with explicit
  # masks) get a large-negative finite lse via the 1e-37 clamp; the
  # backward's exp(s - lse) is still 0 there because s is -inf. Shape
  # (BQ, 1): the lse array carries a trailing unit dim so its blocks
  # satisfy the TPU (8, 128) block-shape rule.
  lse_ref[0] = safe_m + jnp.log(jnp.maximum(l, 1e-37))
  l = jnp.where(l == 0.0, 1.0, l)
  o_ref[0] = (acc / l).astype(o_ref.dtype)


def _block_sizes(t: int):
  if t % _BLOCK == 0:
    return _BLOCK, _BLOCK
  if t <= _MAX_SINGLE_BLOCK_T:
    return t, t
  return None


def _supported(q, k) -> Optional[str]:
  """None if the Pallas path can run, else the reason it cannot."""
  t, d = q.shape[1], q.shape[3]
  if _block_sizes(t) is None:
    return (f"T must be divisible by {_BLOCK} or <= "
            f"{_MAX_SINGLE_BLOCK_T}; got T={t}")
  kv_bytes = _PIPELINE_BUFFERS * 2 * t * d * k.dtype.itemsize
  if kv_bytes > _MAX_KV_VMEM_BYTES:
    return (f"double-buffered K+V row ({kv_bytes} bytes at T={t}, D={d})"
            f" exceeds the {_MAX_KV_VMEM_BYTES}-byte VMEM budget; use "
            "ring_attention for sequences this long")
  return None


def _to_rows(x):
  b, t, h, d = x.shape
  return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _from_rows(x, b, h):
  bh, t, d = x.shape
  return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _pallas_forward(q, k, v, causal: bool, scale: float,
                    with_residuals: bool = False):
  b, t, h, d = q.shape
  block_q, block_k = _block_sizes(t)
  # (B, T, H, D) → (B·H, T, D): heads become independent grid rows.
  qr, kr, vr = _to_rows(q), _to_rows(k), _to_rows(v)
  grid = (b * h, t // block_q)
  tile = lambda i, qi: (i, qi, 0)
  full = lambda i, qi: (i, 0, 0)
  out, lse = pl.pallas_call(
      functools.partial(_kernel, scale=scale, causal=causal,
                        block_q=block_q, block_k=block_k, seq_len=t),
      out_shape=(jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
                 jax.ShapeDtypeStruct((b * h, t, 1), jnp.float32)),
      grid=grid,
      in_specs=[
          pl.BlockSpec((1, block_q, d), tile, memory_space=pltpu.VMEM),
          pl.BlockSpec((1, t, d), full, memory_space=pltpu.VMEM),
          pl.BlockSpec((1, t, d), full, memory_space=pltpu.VMEM),
      ],
      out_specs=(
          pl.BlockSpec((1, block_q, d), tile, memory_space=pltpu.VMEM),
          pl.BlockSpec((1, block_q, 1), tile, memory_space=pltpu.VMEM),
      ),
      interpret=jax.default_backend() != "tpu",
  )(qr, kr, vr)
  out4 = _from_rows(out, b, h)
  if with_residuals:
    return out4, lse
  return out4


def _kernel_dq(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               *, scale: float, causal: bool, block_q: int, block_k: int,
               seq_len: int):
  """dq for one query tile: dq_i = Σ_j (P_ij ⊙ (dO_i V_jᵀ − Δ_i)) K_j."""
  q = q_ref[0].astype(jnp.float32)                         # (BQ, D)
  do = do_ref[0].astype(jnp.float32)                       # (BQ, D)
  lse = lse_ref[0]                                         # (BQ, 1)
  delta = delta_ref[0]                                     # (BQ, 1)
  qi = pl.program_id(1)

  def body(kj, dq_acc):
    k_blk = k_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
    v_blk = v_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale        # (BQ, BK)
    if causal:
      s = _causal_mask(s, qi, kj, block_q, block_k)
    p = jnp.exp(s - lse)
    dpv = jax.lax.dot_general(
        do, v_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                # (BQ, BK)
    ds = p * (dpv - delta)
    return dq_acc + jnp.dot(ds, k_blk,
                            preferred_element_type=jnp.float32) * scale

  if causal:
    num_k = (qi * block_q + block_q + block_k - 1) // block_k
  else:
    num_k = seq_len // block_k
  dq = jax.lax.fori_loop(
      0, num_k, body, jnp.zeros((block_q, q.shape[-1]), jnp.float32))
  dq_ref[0] = dq.astype(dq_ref.dtype)


def _kernel_dkv(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale: float, causal: bool,
                block_q: int, block_k: int, seq_len: int):
  """dk/dv for one key tile: dV_j = Σ_i P_ijᵀ dO_i;
  dK_j = Σ_i (P_ij ⊙ (dO_i V_jᵀ − Δ_i))ᵀ Q_i · scale."""
  k_tile = k_ref[0].astype(jnp.float32)                    # (BK, D)
  v_tile = v_ref[0].astype(jnp.float32)                    # (BK, D)
  kj = pl.program_id(1)
  head_dim = k_tile.shape[-1]

  def body(qi, carry):
    dk_acc, dv_acc = carry
    q_blk = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
    do_blk = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(
        jnp.float32)
    lse_blk = lse_ref[0, pl.ds(qi * block_q, block_q), :]   # (BQ, 1)
    delta_blk = delta_ref[0, pl.ds(qi * block_q, block_q), :]
    s = jax.lax.dot_general(
        q_blk, k_tile, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale        # (BQ, BK)
    if causal:
      s = _causal_mask(s, qi, kj, block_q, block_k)
    p = jnp.exp(s - lse_blk)                               # (BQ, BK)
    dv_acc = dv_acc + jax.lax.dot_general(
        p, do_blk, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                # (BK, D)
    dpv = jax.lax.dot_general(
        do_blk, v_tile, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                # (BQ, BK)
    ds = p * (dpv - delta_blk)
    dk_acc = dk_acc + jax.lax.dot_general(
        ds, q_blk, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale        # (BK, D)
    return dk_acc, dv_acc

  num_q = seq_len // block_q
  # Causal: only Q tiles whose last row reaches this K tile contribute.
  start = (kj * block_k) // block_q if causal else 0
  init = (jnp.zeros((block_k, head_dim), jnp.float32),
          jnp.zeros((block_k, head_dim), jnp.float32))
  dk, dv = jax.lax.fori_loop(start, num_q, body, init)
  dk_ref[0] = dk.astype(dk_ref.dtype)
  dv_ref[0] = dv.astype(dv_ref.dtype)


def _pallas_backward(q, k, v, out, lse, do, causal: bool,
                     scale: float):
  """Two-pass flash backward over the row layout; returns (dq, dk, dv)
  in the original (B, T, H, D) layout.

  `out` is the forward output in its original (B, T, H, D) layout — the
  same array the caller's graph already keeps alive as the next layer's
  activation, so saving it as a residual costs no extra memory.
  """
  b, t, h, d = q.shape
  block_q, block_k = _block_sizes(t)
  qr, kr, vr, dor = _to_rows(q), _to_rows(k), _to_rows(v), _to_rows(do)
  # Δ_i = Σ_d dO_id · O_id — cheap elementwise reduction, XLA fuses it.
  # Trailing unit dim: see the lse shape note in _kernel.
  delta = _to_rows(jnp.sum(do.astype(jnp.float32)
                           * out.astype(jnp.float32), axis=-1,
                           keepdims=True))                  # (BH, T, 1)
  interpret = jax.default_backend() != "tpu"
  tile_q = lambda i, qi: (i, qi, 0)
  tile_k = lambda i, kj: (i, kj, 0)
  full = lambda i, _: (i, 0, 0)
  kwargs = dict(scale=scale, causal=causal, block_q=block_q,
                block_k=block_k, seq_len=t)
  dq = pl.pallas_call(
      functools.partial(_kernel_dq, **kwargs),
      out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
      grid=(b * h, t // block_q),
      in_specs=[
          pl.BlockSpec((1, block_q, d), tile_q, memory_space=pltpu.VMEM),
          pl.BlockSpec((1, t, d), full, memory_space=pltpu.VMEM),
          pl.BlockSpec((1, t, d), full, memory_space=pltpu.VMEM),
          pl.BlockSpec((1, block_q, d), tile_q, memory_space=pltpu.VMEM),
          pl.BlockSpec((1, block_q, 1), tile_q, memory_space=pltpu.VMEM),
          pl.BlockSpec((1, block_q, 1), tile_q, memory_space=pltpu.VMEM),
      ],
      out_specs=pl.BlockSpec((1, block_q, d), tile_q,
                             memory_space=pltpu.VMEM),
      interpret=interpret,
  )(qr, kr, vr, dor, lse, delta)
  dk, dv = pl.pallas_call(
      functools.partial(_kernel_dkv, **kwargs),
      out_shape=(jax.ShapeDtypeStruct((b * h, t, d), k.dtype),
                 jax.ShapeDtypeStruct((b * h, t, d), v.dtype)),
      grid=(b * h, t // block_k),
      in_specs=[
          pl.BlockSpec((1, t, d), full, memory_space=pltpu.VMEM),
          pl.BlockSpec((1, block_k, d), tile_k, memory_space=pltpu.VMEM),
          pl.BlockSpec((1, block_k, d), tile_k, memory_space=pltpu.VMEM),
          pl.BlockSpec((1, t, d), full, memory_space=pltpu.VMEM),
          pl.BlockSpec((1, t, 1), full, memory_space=pltpu.VMEM),
          pl.BlockSpec((1, t, 1), full, memory_space=pltpu.VMEM),
      ],
      out_specs=(
          pl.BlockSpec((1, block_k, d), tile_k, memory_space=pltpu.VMEM),
          pl.BlockSpec((1, block_k, d), tile_k, memory_space=pltpu.VMEM),
      ),
      interpret=interpret,
  )(qr, kr, vr, dor, lse, delta)
  return (_from_rows(dq, b, h), _from_rows(dk, b, h),
          _from_rows(dv, b, h))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_pallas(q, k, v, causal: bool, scale: float):
  return _pallas_forward(q, k, v, causal, scale)


def _fwd(q, k, v, causal, scale):
  out, lse = _pallas_forward(q, k, v, causal, scale, with_residuals=True)
  return out, (q, k, v, out, lse)


def _bwd(causal, scale, residuals, grad):
  q, k, v, out, lse = residuals
  return _pallas_backward(q, k, v, out, lse, grad, causal, scale)


_flash_attention_pallas.defvjp(_fwd, _bwd)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    implementation: str = "auto"):
  """Multi-head attention over (B, T, H, D) without the (T, T) tensor.

  Args:
    q, k, v: (B, T, H, D) arrays (same layout as ring_attention).
    causal: apply a causal mask.
    scale: attention scale; default 1/sqrt(D).
    implementation: "pallas", "xla", or "auto" (pallas when T is
      blockable: divisible by 128 or ≤ 1024 as one block).

  Returns:
    (B, T, H, D) attention output in q's dtype.
  """
  if implementation not in ("auto", "pallas", "xla"):
    raise ValueError(
        f"implementation must be 'auto', 'pallas', or 'xla'; got "
        f"{implementation!r}")
  if scale is None:
    scale = 1.0 / math.sqrt(q.shape[-1])
  unsupported = _supported(q, k)
  if implementation == "xla" or (implementation == "auto"
                                 and (unsupported is not None
                                      or dispatch.use_xla_only()
                                      or jax.default_backend() != "tpu")):
    return flash_attention_reference(q, k, v, causal, scale)
  if unsupported is not None:
    raise ValueError(f"flash_attention pallas path: {unsupported}")
  return _flash_attention_pallas(q, k, v, causal, scale)

"""Blockwise (flash) multi-head attention — Pallas TPU kernel.

Single-device counterpart of parallel/ring_attention.py: the same
running-max/denominator accumulation, but blocked over VMEM tiles inside
one chip instead of over ring hops. O(T) HBM traffic for the forward
pass instead of materializing the (B, H, T, T) score tensor (which is
what the XLA reference below does). Used for long in-device sequences;
ring_attention composes it across chips for sequences that exceed one
device.

Gradient: custom_vjp recomputing through the XLA reference, so training
at long T should prefer ring_attention (whose accumulation is
differentiated directly); this kernel's primary consumers are
inference-time attention (serving, CEM sweeps) and moderate-T training.
First-order only — custom_vjp does not compose with forward-over-
reverse, so models differentiated twice (MAML inner loops) must pass
implementation="xla".
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tensor2robot_tpu.ops import dispatch

_BLOCK = 128
_MAX_SINGLE_BLOCK_T = 1024
# K and V are staged whole per (b·h) row; bound their combined VMEM
# footprint well under the ~16 MB budget (Q/O tiles + f32 working set
# take the rest). Longer sequences belong to ring_attention.
_MAX_KV_VMEM_BYTES = 8 * 1024 * 1024


def flash_attention_reference(q, k, v, causal: bool = False,
                              scale: Optional[float] = None):
  """XLA reference: materializes (B, H, T, T) scores. (B, T, H, D) in/out."""
  if scale is None:
    scale = 1.0 / math.sqrt(q.shape[-1])
  scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                      k.astype(jnp.float32)) * scale
  if causal:
    t = q.shape[1]
    mask = jnp.tril(jnp.ones((t, t), bool))
    scores = jnp.where(mask[None, None], scores, -jnp.inf)
  weights = jax.nn.softmax(scores, axis=-1)
  out = jnp.einsum("bhqk,bkhd->bqhd", weights, v.astype(jnp.float32))
  return out.astype(q.dtype)


def _kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, causal: bool,
            block_q: int, block_k: int, seq_len: int):
  """One (block_q, D) query tile vs all K/V tiles of this (b·h) row."""
  q = q_ref[0].astype(jnp.float32) * scale                 # (BQ, D)
  qi = pl.program_id(1)
  head_dim = q.shape[-1]

  def body(kj, carry):
    m, l, acc = carry
    k_blk = k_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
    v_blk = v_ref[0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                # (BQ, BK)
    if causal:
      rows = qi * block_q + jax.lax.broadcasted_iota(
          jnp.int32, (block_q, block_k), 0)
      cols = kj * block_k + jax.lax.broadcasted_iota(
          jnp.int32, (block_q, block_k), 1)
      s = jnp.where(rows >= cols, s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_blk)
    safe_max = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    correction = jnp.exp(m - safe_max)
    p = jnp.exp(s - safe_max)
    l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * correction + jnp.dot(
        p, v_blk, preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new

  if causal:
    # Only K blocks that intersect the causal triangle of this Q tile.
    num_k = (qi * block_q + block_q + block_k - 1) // block_k
  else:
    num_k = seq_len // block_k
  init = (jnp.full((block_q, 1), -jnp.inf, jnp.float32),
          jnp.zeros((block_q, 1), jnp.float32),
          jnp.zeros((block_q, head_dim), jnp.float32))
  _, l, acc = jax.lax.fori_loop(0, num_k, body, init)
  l = jnp.where(l == 0.0, 1.0, l)
  o_ref[0] = (acc / l).astype(o_ref.dtype)


def _block_sizes(t: int):
  if t % _BLOCK == 0:
    return _BLOCK, _BLOCK
  if t <= _MAX_SINGLE_BLOCK_T:
    return t, t
  return None


def _supported(q, k) -> Optional[str]:
  """None if the Pallas path can run, else the reason it cannot."""
  t, d = q.shape[1], q.shape[3]
  if _block_sizes(t) is None:
    return (f"T must be divisible by {_BLOCK} or <= "
            f"{_MAX_SINGLE_BLOCK_T}; got T={t}")
  kv_bytes = 2 * t * d * k.dtype.itemsize
  if kv_bytes > _MAX_KV_VMEM_BYTES:
    return (f"K+V row ({kv_bytes} bytes at T={t}, D={d}) exceeds the "
            f"{_MAX_KV_VMEM_BYTES}-byte VMEM budget; use "
            "ring_attention for sequences this long")
  return None


def _pallas_forward(q, k, v, causal: bool, scale: float):
  b, t, h, d = q.shape
  block_q, block_k = _block_sizes(t)
  # (B, T, H, D) → (B·H, T, D): heads become independent grid rows.
  to_rows = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
  qr, kr, vr = to_rows(q), to_rows(k), to_rows(v)
  grid = (b * h, t // block_q)
  out = pl.pallas_call(
      functools.partial(_kernel, scale=scale, causal=causal,
                        block_q=block_q, block_k=block_k, seq_len=t),
      out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
      grid=grid,
      in_specs=[
          pl.BlockSpec((1, block_q, d), lambda i, qi: (i, qi, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((1, t, d), lambda i, qi: (i, 0, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((1, t, d), lambda i, qi: (i, 0, 0),
                       memory_space=pltpu.VMEM),
      ],
      out_specs=pl.BlockSpec((1, block_q, d), lambda i, qi: (i, qi, 0),
                             memory_space=pltpu.VMEM),
      interpret=jax.default_backend() != "tpu",
  )(qr, kr, vr)
  return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_pallas(q, k, v, causal: bool, scale: float):
  return _pallas_forward(q, k, v, causal, scale)


def _fwd(q, k, v, causal, scale):
  return _pallas_forward(q, k, v, causal, scale), (q, k, v)


def _bwd(causal, scale, residuals, grad):
  q, k, v = residuals
  _, vjp = jax.vjp(
      lambda q, k, v: flash_attention_reference(q, k, v, causal, scale),
      q, k, v)
  return vjp(grad)


_flash_attention_pallas.defvjp(_fwd, _bwd)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    implementation: str = "auto"):
  """Multi-head attention over (B, T, H, D) without the (T, T) tensor.

  Args:
    q, k, v: (B, T, H, D) arrays (same layout as ring_attention).
    causal: apply a causal mask.
    scale: attention scale; default 1/sqrt(D).
    implementation: "pallas", "xla", or "auto" (pallas when T is
      blockable: divisible by 128 or ≤ 1024 as one block).

  Returns:
    (B, T, H, D) attention output in q's dtype.
  """
  if implementation not in ("auto", "pallas", "xla"):
    raise ValueError(
        f"implementation must be 'auto', 'pallas', or 'xla'; got "
        f"{implementation!r}")
  if scale is None:
    scale = 1.0 / math.sqrt(q.shape[-1])
  unsupported = _supported(q, k)
  if implementation == "xla" or (implementation == "auto"
                                 and (unsupported is not None
                                      or dispatch.use_xla_only()
                                      or jax.default_backend() != "tpu")):
    return flash_attention_reference(q, k, v, causal, scale)
  if unsupported is not None:
    raise ValueError(f"flash_attention pallas path: {unsupported}")
  return _flash_attention_pallas(q, k, v, causal, scale)

"""Reshape-formulation max pooling for non-overlapping windows.

`nn.max_pool` lowers to XLA reduce-window, whose gradient is a
SelectAndScatter op — historically one of the slowest TPU lowerings
(it re-scans every window serially to find the argmax). For the
NON-OVERLAPPING case (window == strides), the same function is
expressible as a reshape + max over the split axes: the backward then
compiles to a compare/mask/multiply fusion with no SelectAndScatter.

Forward parity with `nn.max_pool(x, (2, 2), strides=(2, 2))` is exact
(same elements, same max). The BACKWARD differs only on exact ties
within a window: reduce-max's gradient splits evenly among tied maxima
where SelectAndScatter routes everything to the first — both are valid
subgradients of the same function (ties are common after relu, where
whole windows can be 0). tests/test_ops.py pins both contracts.

Measured use: a candidate swap for the QT-Opt stem's 118²→59² pool —
adopted only if the step budget shows a real win (bench.py
§step_budget_parity_b32 measures the stem piece both ways).
"""

from __future__ import annotations

import jax.numpy as jnp


def max_pool_reshape(x: jnp.ndarray, window: int = 2) -> jnp.ndarray:
  """Non-overlapping `window`×`window` max pool over NHWC, stride ==
  window (the `nn.max_pool(x, (w, w), strides=(w, w))` case).

  H and W must be divisible by `window` (the flagship's 118² is; callers
  with ragged sizes should crop first — VALID padding drops the ragged
  edge anyway, but silently reproducing that here would hide a
  mismatch).
  """
  b, h, w, c = x.shape
  if h % window or w % window:
    raise ValueError(
        f"max_pool_reshape needs H, W divisible by {window}, got "
        f"{(h, w)}; crop first (VALID-pool semantics drop the edge).")
  x = x.reshape(b, h // window, window, w // window, window, c)
  return x.max(axis=(2, 4))

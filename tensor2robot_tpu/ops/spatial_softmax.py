"""Fused spatial-softmax expectation (Pallas TPU kernel).

The keypoint pooling between every conv tower and pose head
(layers/vision_layers.py §spatial_softmax; reference
§BuildImageFeaturesToPoseModel's spatial softmax): per-channel softmax
over the H×W grid followed by expected-(x, y) coordinates. The XLA form
materializes the (B, C, H, W) attention tensor in HBM between the
softmax and the two weighted reductions; this kernel keeps one
(H·W, C-tile) block resident in VMEM and does max → exp → three
reductions in a single pass, so HBM traffic drops from ~4 passes over
the activation to one read + one (B, 2, C) write.

Gradient: custom_jvp whose rule routes through the XLA reference, so
reverse-mode — including the higher-order reverse MAML's second-order
outer gradient needs — derives from plain jnp ops; the kernel serves
every non-differentiated forward (serving, eval, CEM sweeps).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tensor2robot_tpu.ops import dispatch

# One (H·W, C_TILE) fp32 block must fit comfortably in VMEM (~16 MB).
_MAX_VMEM_BLOCK_ELEMS = 1 << 21  # 2M fp32 elems = 8 MB
_LANES = 128


def spatial_softmax_reference(features: jnp.ndarray,
                              temperature: float = 1.0) -> jnp.ndarray:
  """XLA reference: identical math, O(B·H·W·C) intermediate in HBM."""
  b, h, w, c = features.shape
  dtype = features.dtype
  logits = features.astype(jnp.float32).transpose(0, 3, 1, 2)
  logits = logits.reshape(b, c, h * w) / temperature
  attention = jax.nn.softmax(logits, axis=-1).reshape(b, c, h, w)
  xs = jnp.linspace(-1.0, 1.0, w)
  ys = jnp.linspace(-1.0, 1.0, h)
  expected_x = jnp.sum(attention * xs[None, None, None, :], axis=(2, 3))
  expected_y = jnp.sum(attention * ys[None, None, :, None], axis=(2, 3))
  return jnp.concatenate([expected_x, expected_y], axis=-1).astype(dtype)


def _kernel(x_ref, out_ref, *, height: int, width: int,
            inv_temperature: float):
  """One (1, H·W, C_TILE) block: softmax + expected coords, fused."""
  logits = x_ref[0].astype(jnp.float32) * inv_temperature  # (HW, CT)
  hw = height * width
  row = jax.lax.broadcasted_iota(jnp.int32, (hw, 1), 0)
  col_in_image = (row % width).astype(jnp.float32)
  row_in_image = (row // width).astype(jnp.float32)
  # linspace(-1, 1, n)[i] == -1 + 2*i/(n-1); n==1 degenerates to [-1],
  # which the same formula yields with the max() guard (i is then 0).
  x_coord = -1.0 + 2.0 * col_in_image / max(width - 1, 1)
  y_coord = -1.0 + 2.0 * row_in_image / max(height - 1, 1)

  maxes = jnp.max(logits, axis=0, keepdims=True)          # (1, CT)
  weights = jnp.exp(logits - maxes)                       # (HW, CT)
  denom = jnp.sum(weights, axis=0, keepdims=True)         # (1, CT)
  inv_denom = 1.0 / denom
  out_ref[0, 0, :] = jnp.sum(weights * x_coord, axis=0) * inv_denom[0]
  out_ref[0, 1, :] = jnp.sum(weights * y_coord, axis=0) * inv_denom[0]


def _pallas_forward(features: jnp.ndarray,
                    temperature: float) -> jnp.ndarray:
  interpret = jax.default_backend() != "tpu"
  b, h, w, c = features.shape
  hw = h * w
  c_tile = min(c, _LANES)
  x = features.reshape(b, hw, c)
  grid = (b, pl.cdiv(c, c_tile))
  out = pl.pallas_call(
      functools.partial(_kernel, height=h, width=w,
                        inv_temperature=1.0 / temperature),
      out_shape=jax.ShapeDtypeStruct((b, 2, c), jnp.float32),
      grid=grid,
      in_specs=[pl.BlockSpec((1, hw, c_tile), lambda i, j: (i, 0, j),
                             memory_space=pltpu.VMEM)],
      out_specs=pl.BlockSpec((1, 2, c_tile), lambda i, j: (i, 0, j),
                             memory_space=pltpu.VMEM),
      interpret=interpret,
  )(x)
  return jnp.concatenate([out[:, 0, :], out[:, 1, :]],
                         axis=-1).astype(features.dtype)


@functools.partial(jax.custom_jvp, nondiff_argnums=(1,))
def _spatial_softmax_pallas(features: jnp.ndarray,
                            temperature: float) -> jnp.ndarray:
  return _pallas_forward(features, temperature)


@_spatial_softmax_pallas.defjvp
def _jvp(temperature, primals, tangents):
  # Differentiation routes through the XLA reference (the fused forward
  # never materializes the attention weights the chain rule needs).
  # custom_jvp rather than custom_vjp: the rule below is plain jnp, so
  # reverse-mode — and higher-order reverse, which MAML's second-order
  # outer gradient needs — both derive from it. The Pallas kernel then
  # serves every non-differentiated forward (serving, eval, CEM sweeps).
  (features,), (features_dot,) = primals, tangents
  return jax.jvp(lambda f: spatial_softmax_reference(f, temperature),
                 (features,), (features_dot,))


def _supported(features: jnp.ndarray) -> bool:
  b, h, w, c = features.shape
  return h * w * min(c, _LANES) <= _MAX_VMEM_BLOCK_ELEMS


def spatial_softmax(features: jnp.ndarray, temperature: float = 1.0,
                    implementation: str = "auto") -> jnp.ndarray:
  """Expected (x, y) image-coordinates per channel ("feature points").

  Args:
    features: (B, H, W, C) activations.
    temperature: softmax temperature.
    implementation: "pallas", "xla", or "auto" (pallas whenever the
      block fits VMEM; the kernel runs interpreted off-TPU).

  Returns:
    (B, 2*C): per-channel expected coordinates in [-1, 1], x block
    then y block — same contract as the reference's spatial softmax.
  """
  if implementation not in ("auto", "pallas", "xla"):
    raise ValueError(
        f"implementation must be 'auto', 'pallas', or 'xla'; got "
        f"{implementation!r}")
  if implementation == "xla":
    return spatial_softmax_reference(features, temperature)
  if implementation == "pallas":
    # Explicit request: kernel on every platform (interpreted off-TPU) —
    # the path CPU CI uses to exercise the kernel body.
    return _spatial_softmax_pallas(features, temperature)
  if (dispatch.use_xla_only() or jax.default_backend() != "tpu"
      or not _supported(features)):
    # xla_only: multi-platform export tracing (see ops/dispatch.py) — a
    # compiled pallas_call cannot lower for the artifact's CPU target.
    # Off-TPU, auto means XLA: an interpreted kernel is strictly slower
    # there (explicit implementation="pallas" remains the CI coverage
    # path).
    return spatial_softmax_reference(features, temperature)
  return _spatial_softmax_pallas(features, temperature)

"""Folded space-to-depth stem convolution (TPU-first stem formulation).

The reference's grasping nets open with a big-spatial, 3-channel stem
conv (reference research/qtopt/t2r_models.py §LegacyGraspingModelQ via
SURVEY.md §2: Conv 64×(6,6)/4 on a 472² camera image). On TPU that
layer is badly lane-starved — only 3 of the MXU's input lanes carry
data per tap — and XLA's direct conv lowering measures ~3% MFU on v5e,
making the stem ~40% of the whole train step (2026-07-31 microbench,
docs/DESIGN.md §8).

The space-to-depth stem (the model's documented
`stem_kind="space_to_depth"` option: 8×8 receptive field, stride 4,
function class strictly containing the parity stem's 6×6) fixes the
lane starvation, but the naive 6D block-transpose costs more than it
saves (BENCH_r02: 159 vs 189 steps/s). This module implements the same
function WITHOUT any transpose, as ONE standard convolution over a
reshaped (free) view of the image:

  rows = zero-pad x to (B, H+4, W·C + 4C), viewed as
         (B, (H+4)·…, W/4 + 1, 4C) — reshapes only, no data movement.
  y[b, jo, wo, o] = Σ_{r<8, s<2, m<4C}
      rows[b, 4·jo + r, wo + s, m] · w[r, s, m, o]

i.e. an (8, 2)-kernel, stride-(4, 1), Cin=4C convolution: the
W-direction phase extraction is folded into the contraction ordering,
so XLA sees a well-shaped conv (contraction 16·C = 48 for RGB) instead
of either a 3-channel conv or a 6D transpose. Measured on v5e
(2026-07-31, batch 32, 472²): stem fwd+grad_w 1269 µs vs 1701 µs for
the parity 6×6 conv and 2670 µs (fwd alone) for the naive
space-to-depth — with bit-identical results to the naive formulation
under the `fold_s2d_weights` weight-layout permutation.

A fully-fused Pallas patches-in-VMEM kernel was attempted and is
recorded as a negative result: the im2col lane regroup ((J, WO·m) →
(J, WO, m)) is a lane→sublane redistribution that Mosaic's
infer-vector-layout rejects ("unsupported shape cast", tested m = 12
and 16), and every transpose-based workaround either pays per-tile
relayouts comparable to the XLA folded conv or exceeds the ~16 MB VMEM
budget at the required tile sizes (J = 59 forced by JO = 118 = 2·59).
The folded-conv formulation keeps the win inside XLA instead.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_R = 8  # kernel rows (2 stride-4 row blocks)
_S = 2  # kernel col-blocks


def _geometry(x_shape, w_shape):
  b, h, w, c = x_shape
  if h % 4 or w % 4:
    raise ValueError(f"H and W must be multiples of 4, got {x_shape}")
  if w_shape[:3] != (_R, _S, 4 * c):
    raise ValueError(
        f"weights must be ({_R}, {_S}, {4 * c}, O) for C={c}, got "
        f"{w_shape}")
  return b, h // 4, w // 4, c, w_shape[-1]


def fold_s2d_weights(w_blocks: jax.Array) -> jax.Array:
  """(2, 2, 16C, O) block-transpose layout → (8, 2, 4C, O) folded layout.

  The naive space-to-depth formulation reshapes 4×4 blocks to channels
  (ordering (row_phase p, col_phase q, c)) and applies a (2, 2) conv;
  its contraction index is (K, L, p, q, c). The folded kernel's is
  (r = 4K + p, s = L, m = qC + c)."""
  kh, kw, c16, o = w_blocks.shape
  if (kh, kw) != (2, 2) or c16 % 16:
    raise ValueError(f"expected (2, 2, 16C, O), got {w_blocks.shape}")
  c = c16 // 16
  wr = w_blocks.reshape(2, 2, 4, 4, c, o)      # K, L, p, q, c, o
  return wr.transpose(0, 2, 1, 3, 4, 5).reshape(_R, _S, 4 * c, o)


def folded_s2d_stem(x: jax.Array, w: jax.Array) -> jax.Array:
  """Space-to-depth stem conv: (B, H, W, C) → (B, ⌈H/4⌉, ⌈W/4⌉, O).

  Non-multiple-of-4 sizes are zero-padded up first (matching the naive
  space-to-depth formulation's edge behavior class — the model option
  predates this op and accepted any size).

  w: (8, 2, 4C, O) folded layout (see module docstring /
  fold_s2d_weights)."""
  _, h, wd, _ = x.shape
  pad_h, pad_w = (-h) % 4, (-wd) % 4
  if pad_h or pad_w:
    x = jnp.pad(x, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)))
  b, jo, wo, c, _ = _geometry(x.shape, w.shape)
  lanes = wo * 4 * c
  rows = jnp.pad(x.reshape(b, 4 * jo, lanes),
                 ((0, 0), (0, 4), (0, 4 * c)))
  folded = rows.reshape(b, 4 * (jo + 1), wo + 1, 4 * c)
  y = jax.lax.conv_general_dilated(
      folded, w, window_strides=(4, 1), padding="VALID",
      dimension_numbers=("NHWC", "HWIO", "NHWC"))
  assert y.shape == (b, jo, wo, w.shape[-1]), y.shape
  return y


def init_folded_stem_weights(key, c: int, o: int,
                             dtype=jnp.float32) -> jax.Array:
  """Lecun-normal init over the (8, 2, 4C, O) folded layout."""
  fan_in = _R * _S * 4 * c
  return (jax.random.normal(key, (_R, _S, 4 * c, o)) /
          np.sqrt(fan_in)).astype(dtype)

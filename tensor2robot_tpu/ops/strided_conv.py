"""Folded formulation of 3×3 stride-2 SAME convolution.

Same idea as the stem fold (`ops/stem_conv.py`), applied to the
flagship's post-merge tower (reference grasping net via SURVEY.md §2:
three Conv 64×(3,3)/2 layers, 59²→30²→15²→8²): express the strided
conv as a stride-(2, 1) conv over a lanes-folded VIEW of the input —
the W-direction stride phases live in the channel dimension, so both
the forward and (the actual motivation) the BACKWARD see
larger-contraction, stride-1-in-minor-dim shapes instead of XLA's
strided/dilated grad convolutions.

Construction, for x (B, H, W, C) → y (B, ⌈H/2⌉, ⌈W/2⌉, O):

  pad x with SAME-exact lo/hi zeros to (B, 2·HO+2, 2·WO+2, C);
  view rows as (B, H_p, W_p/2, 2C)       # reshape only, free
  y = conv(view, w_folded, strides=(2, 1), VALID)

  w_folded (4, 2, 2C, O): w_folded[r, s, qC+c, o] = w[r, 2s+q, c, o]
  for r < 3 and 2s+q < 3, zero elsewhere (the r=3 row and the (s,q)
  combination addressing kernel column 3 are structurally zero taps).

The function is EXACTLY the parity convolution — same taps, same
SAME-padding offsets (including the even-size case where SAME pads
only on the high side) — up to float reassociation of the contraction.
Weights stay in the parity (3, 3, C, O) layout; the fold runs inside
jit on the tiny kernel tensor, so checkpoints and the model's param
tree are untouched and autodiff transposes the fold for free.

Adopted only where the step budget shows a measured win (bench.py
§step_budget_parity_b32 measures the post tower both ways);
correctness is pinned CPU-side in tests/test_ops.py either way.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


def fold_strided3x3_weights(w: jax.Array) -> jax.Array:
  """(3, 3, C, O) parity layout → (4, 2, 2C, O) folded layout."""
  kh, kw, c, o = w.shape
  if (kh, kw) != (3, 3):
    raise ValueError(f"expected a (3, 3, C, O) kernel, got {w.shape}")
  # (r, s, q, c, o) with kernel column = 2s + q; column 3 and row 3
  # are structural zeros.
  wf = jnp.zeros((4, 2, 2, c, o), w.dtype)
  wf = wf.at[0:3, 0, 0].set(w[:, 0])   # s=0, q=0 → col 0
  wf = wf.at[0:3, 0, 1].set(w[:, 1])   # s=0, q=1 → col 1
  wf = wf.at[0:3, 1, 0].set(w[:, 2])   # s=1, q=0 → col 2
  return wf.reshape(4, 2, 2 * c, o)


def strided3x3_same(x: jax.Array, w: jax.Array) -> jax.Array:
  """conv2d(x, w, strides=(2, 2), padding='SAME') via the folded view.

  x: (B, H, W, C) NHWC; w: (3, 3, C, O) — the PARITY weight layout.
  Bit-compatible function with `lax.conv_general_dilated(..., (2, 2),
  'SAME')` up to float reassociation.
  """
  b, h, wd, c = x.shape
  out_h, out_w = -(-h // 2), -(-wd // 2)   # ceil: SAME output sizes
  # SAME pad_lo is pad_total // 2; pad hi is topped up so the folded
  # view is rectangular: H_p = 2·out_h + 2 covers the last window's
  # r<3 taps (the r=3 tap row is structurally zero), W_p likewise and
  # even by construction (the 2C fold needs even W_p).
  pad_total_h = max((out_h - 1) * 2 + 3 - h, 0)
  pad_total_w = max((out_w - 1) * 2 + 3 - wd, 0)
  lo_h, lo_w = pad_total_h // 2, pad_total_w // 2
  hp, wp = 2 * out_h + 2, 2 * out_w + 2
  x = jnp.pad(x, ((0, 0), (lo_h, hp - lo_h - h), (lo_w, wp - lo_w - wd),
                  (0, 0)))
  view = x.reshape(b, hp, wp // 2, 2 * c)
  y = jax.lax.conv_general_dilated(
      view, fold_strided3x3_weights(w), window_strides=(2, 1),
      padding="VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"))
  assert y.shape == (b, out_h, out_w, w.shape[-1]), y.shape
  return y


class FoldedStridedConv3x3(nn.Module):
  """Flax wrapper with nn.Conv-IDENTICAL param layout (`kernel`
  (3, 3, C, O), optional `bias` (O,)) — parity and folded checkpoints
  interchange with no conversion. Drop-in for
  `nn.Conv(features, (3, 3), strides=(2, 2))` (SAME padding)."""

  features: int
  use_bias: bool = True
  dtype: Any = jnp.bfloat16

  @nn.compact
  def __call__(self, x: jax.Array) -> jax.Array:
    kernel = self.param(
        "kernel", nn.initializers.lecun_normal(),
        (3, 3, x.shape[-1], self.features))
    y = strided3x3_same(x.astype(self.dtype), kernel.astype(self.dtype))
    if self.use_bias:
      bias = self.param("bias", nn.initializers.zeros, (self.features,))
      y = y + bias.astype(self.dtype)
    return y

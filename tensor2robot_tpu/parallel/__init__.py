"""Device-mesh parallelism: the framework's single distribution abstraction.

Reference parity: SURVEY.md §2 "DP / comms backend" rows and §5.8. The
reference had two sibling backends (TPU CrossShardOptimizer over ICI;
fork-side NCCL MirroredStrategy). The rebuild has exactly one: a
`jax.sharding.Mesh` plus NamedSharding annotations — XLA inserts the
collectives (psum over ICI within a slice, DCN across slices).
"""

from tensor2robot_tpu.parallel.mesh import (
    create_mesh,
    batch_sharding,
    replicated_sharding,
    shard_batch,
    local_batch_slice,
)
from tensor2robot_tpu.parallel.ring_attention import (
    dense_attention_reference,
    ring_attention,
)
from tensor2robot_tpu.parallel.ulysses_attention import (
    ulysses_attention,
)
from tensor2robot_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
)
from tensor2robot_tpu.parallel.expert_parallel import (
    MoEParams,
    expert_parallel_moe,
    init_moe_params,
    switch_moe,
)
from tensor2robot_tpu.parallel.tp_rules import (
    infer_dense_tp_specs,
    infer_dense_tp_specs_from_model,
    infer_fsdp_specs,
    infer_fsdp_specs_from_model,
    specs_to_shardings,
)

__all__ = [
    "create_mesh",
    "batch_sharding",
    "replicated_sharding",
    "shard_batch",
    "local_batch_slice",
    "ring_attention",
    "ulysses_attention",
    "dense_attention_reference",
    "pipeline_apply",
    "stack_stage_params",
    "MoEParams",
    "expert_parallel_moe",
    "init_moe_params",
    "switch_moe",
    "infer_dense_tp_specs",
    "infer_dense_tp_specs_from_model",
    "infer_fsdp_specs",
    "infer_fsdp_specs_from_model",
    "specs_to_shardings",
]

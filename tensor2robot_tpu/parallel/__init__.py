"""Device-mesh parallelism: the framework's single distribution abstraction.

Reference parity: SURVEY.md §2 "DP / comms backend" rows and §5.8. The
reference had two sibling backends (TPU CrossShardOptimizer over ICI;
fork-side NCCL MirroredStrategy). The rebuild has exactly one: a
`jax.sharding.Mesh` plus NamedSharding annotations — XLA inserts the
collectives (psum over ICI within a slice, DCN across slices).
"""

from tensor2robot_tpu.parallel.mesh import (
    create_mesh,
    batch_sharding,
    replicated_sharding,
    shard_batch,
    local_batch_slice,
)

__all__ = [
    "create_mesh",
    "batch_sharding",
    "replicated_sharding",
    "shard_batch",
    "local_batch_slice",
]

"""Multi-host runtime: process bootstrap + hybrid ICI/DCN meshes.

Reference parity: the reference's multi-device story was TPUEstimator's
master RPC + per-host infeed (upstream) and NCCL MirroredStrategy (the
fork) — SURVEY.md §5.8. The JAX-native equivalent has two halves:

1. Process bootstrap: every host calls `initialize()` once, then the
   normal single-program code sees the GLOBAL device set
   (`jax.devices()`), and the existing mesh/pjit path scales to
   multi-host unchanged — XLA routes collectives over ICI within a
   slice and DCN across slices.
2. Mesh layout: `create_hybrid_mesh` keeps bandwidth-hungry axes
   (model/tensor parallel) inside a slice (ICI) and puts the
   gradient-all-reduce data axis across slices (DCN), the standard
   layout from the scaling playbook.

Nothing here opens sockets itself; `jax.distributed.initialize` speaks
the JAX coordination service (or the TPU metadata autodetect path), so
there is no NCCL/MPI dependency to replace.
"""

from __future__ import annotations

import logging
import os
from typing import Mapping, Optional

import jax
import numpy as np
from jax.sharding import Mesh

from tensor2robot_tpu.parallel import mesh as mesh_lib

_log = logging.getLogger(__name__)
_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
  """Connects this process to the multi-host runtime (idempotent).

  MUST run before any other JAX API touches the backend (device
  queries included) — backend initialization is one-shot, and an
  uncoordinated backend sees only local devices. With no arguments,
  relies on `jax.distributed.initialize`'s cluster autodetection (TPU
  pod metadata / cluster env vars); when no cluster environment is
  detectable this degrades to a logged single-process no-op, so
  single-process runs may call it unconditionally.
  """
  global _initialized
  if _initialized:
    return
  explicit = (coordinator_address is not None or num_processes is not None
              or process_id is not None)
  if explicit and (num_processes or 0) > 1 and (
      os.environ.get("JAX_PLATFORMS", "").startswith("cpu")):
    # Chipless multi-controller bring-up (ISSUE 19): the CPU backend's
    # default cross-process collectives tier is "none", which makes
    # every computation spanning processes fail to compile
    # ("Multiprocess computations aren't implemented"). jaxlib ships a
    # gloo TCP tier that rides the same coordination service — select
    # it here, while the backend is still uninitialized (this function
    # is documented as the process's first JAX call, so this is the
    # one place the flag can still take effect). Real TPU/GPU pods
    # never enter this branch: their collectives are ICI/NCCL-native.
    try:
      jax.config.update("jax_cpu_collectives_implementation", "gloo")
      # Gloo pairs assume one in-flight collective per context; the CPU
      # client's async dispatch can issue two differently-sized
      # collectives back-to-back and cross their wire frames
      # ("op.preamble.length <= op.nbytes" aborts). Synchronous
      # dispatch serializes issue order — correctness over overlap on
      # this emulation tier.
      jax.config.update("jax_cpu_enable_async_dispatch", False)
    except Exception:  # older jaxlib without the gloo tier
      _log.warning("CPU gloo collectives unavailable; cross-process "
                   "programs will not compile on this backend.",
                   exc_info=True)
  try:
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
  except (RuntimeError, ValueError) as e:
    if explicit:
      raise
    # No detectable cluster environment (bare single-process run) — or
    # the backend was already initialized, in which case multi-host
    # setup either already happened (fine) or is impossible now (the
    # caller violated the call-order contract; surface that loudly).
    if "already" in str(e).lower():
      _log.warning(
          "jax.distributed.initialize skipped: backend already "
          "initialized (%s). If this is a multi-host run, initialize() "
          "must be the first JAX call in the process.", e)
    else:
      _log.info("No cluster environment detected (%s); single-process.",
                e)
  _initialized = True
  _log.info("Distributed runtime: process %d/%d, %d local of %d "
            "global devices.", jax.process_index(), jax.process_count(),
            jax.local_device_count(), jax.device_count())


def is_primary() -> bool:
  """True on the process that owns logging/checkpoint/export side
  effects (reference: the chief worker)."""
  return jax.process_index() == 0


def create_hybrid_mesh(
    ici_axes: Mapping[str, int],
    dcn_axes: Optional[Mapping[str, int]] = None,
) -> Mesh:
  """Mesh whose `ici_axes` stay within a slice and `dcn_axes` span slices.

  Args:
    ici_axes: ordered {axis: size} laid out over in-slice ICI links —
      put model/tensor/sequence axes here.
    dcn_axes: ordered {axis: size} laid out across slices over DCN —
      typically just the gradient-all-reduce `data` axis. One size may
      be -1 (fill). None/empty or single-slice topologies degrade to a
      plain `create_mesh` over everything (DCN layout is irrelevant
      when there is nothing to cross).

  Returns:
    jax.sharding.Mesh with dcn axes outermost, ici axes innermost.
  """
  dcn_axes = dict(dcn_axes or {})
  axes = {**dcn_axes, **{k: v for k, v in ici_axes.items()}}
  if len(set(axes)) != len(dcn_axes) + len(ici_axes):
    raise ValueError(
        f"Axis names repeat across ici {list(ici_axes)} and dcn "
        f"{list(dcn_axes)}.")
  if dcn_axes and any(v == -1 for v in ici_axes.values()):
    # A -1 ici axis would fill across slices, defeating the layout.
    raise ValueError(
        f"-1 (fill) is only allowed on dcn axes when dcn_axes is set; "
        f"got ici_axes={dict(ici_axes)}.")
  devices = jax.devices()
  # The DCN granule is the TPU slice when the backend reports one;
  # otherwise (CPU/GPU multi-process) the process is the granule —
  # cross-process links are the slow tier there, which is exactly the
  # boundary the dcn axes should straddle. This also lets multi-process
  # CPU CI exercise the real hybrid layout.
  process_is_granule = not hasattr(devices[0], "slice_index")
  granule = (lambda d: d.process_index) if process_is_granule else (
      lambda d: d.slice_index)
  num_slices = len({granule(d) for d in devices})
  if not dcn_axes or num_slices == 1:
    return mesh_lib.create_mesh(axes)

  from jax.experimental import mesh_utils
  ici_sizes = list(ici_axes.values())
  dcn_sizes = [v for v in dcn_axes.values()]
  fill = [i for i, v in enumerate(dcn_sizes) if v == -1]
  if len(fill) > 1:
    raise ValueError("At most one dcn axis may be -1.")
  if fill:
    fixed = int(np.prod([v for v in dcn_sizes if v != -1]))
    per_slice = int(np.prod(ici_sizes)) * fixed
    if len(devices) % per_slice != 0:
      raise ValueError(
          f"{len(devices)} devices not divisible by {per_slice} "
          f"(ici {ici_axes} × fixed dcn axes).")
    dcn_sizes[fill[0]] = len(devices) // per_slice
  # DCN axes lead: the granule index is the slowest-varying coordinate.
  device_array = mesh_utils.create_hybrid_device_mesh(
      mesh_shape=[1] * len(dcn_sizes) + ici_sizes,
      dcn_mesh_shape=dcn_sizes + [1] * len(ici_sizes),
      devices=devices,
      process_is_granule=process_is_granule)
  return Mesh(device_array, tuple(dcn_axes) + tuple(ici_axes))


def sync_global_devices(name: str) -> None:
  """Cross-host barrier (reference: implicit session-run sync points)."""
  from jax.experimental import multihost_utils
  multihost_utils.sync_global_devices(name)


def global_put(tree, shardings):
  """Places a host-local pytree onto (possibly cross-process) shardings.

  Single-process this IS `jax.device_put` — byte-for-byte the r17
  oracle path. Multi-process, `device_put` refuses shardings whose
  device set spans processes, so each leaf is assembled with
  `jax.make_array_from_callback` against the full local value: every
  process holds the identical full array (true for everything this
  repo places at bring-up — seeded env/ring init, replicated target
  variables, dispatch counters) and contributes exactly the index
  slices its local devices own. Correct for BOTH replicated and
  axis-split shardings, which is why this is the one placement
  primitive (`make_array_from_process_local_data` would need the
  per-process slice pre-cut for the split case).

  Args:
    tree: pytree of host/np/jnp arrays, identical on every process.
    shardings: one `jax.sharding.Sharding` applied to every leaf, or a
      pytree of shardings matching `tree`'s structure.
  """
  if jax.process_count() == 1:
    return jax.device_put(tree, shardings)

  def place(leaf, sharding):
    arr = np.asarray(leaf)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])

  if isinstance(shardings, jax.sharding.Sharding):
    return jax.tree_util.tree_map(lambda leaf: place(leaf, shardings), tree)
  return jax.tree_util.tree_map(place, tree, shardings)


def global_scalar(value, mesh, dtype=None):
  """A replicated GLOBAL scalar on `mesh` (multi-process jit operands
  must be global arrays even when every shard holds the same value —
  the dispatch-counter seam of the fused loops). Single-process this
  is a plain `jnp.asarray`, the unchanged oracle path."""
  import jax.numpy as jnp
  arr = jnp.asarray(value, dtype)
  if jax.process_count() == 1:
    return arr
  return global_put(arr, mesh_lib.replicated_sharding(mesh))

"""Expert parallelism: Switch-style mixture-of-experts with all-to-all
token dispatch over an `expert` mesh axis.

Beyond the reference (pure data parallelism — SURVEY.md §2 "Parallelism
strategies"): the fifth axis of the dp/tp/sp/pp/ep family. Experts are
feed-forward blocks whose weights are sharded one-group-per-device over
the `expert` mesh axis; tokens are routed top-1 (Switch) with a capacity
limit, exchanged device↔expert with a pair of `all_to_all`s (the
canonical MoE mesh transpose: (E, C, D) split over E in, concat over C),
processed by the local expert group, and combined back gate-weighted.

The dense path (`switch_moe`) is the single-device reference — identical
math, no collectives — used for tests and small models; both paths are
differentiable and share the routing implementation, so they cannot
drift.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

try:  # promoted to jax.shard_map in newer releases
  from jax import shard_map
except ImportError:
  from jax.experimental.shard_map import shard_map


class MoEParams(NamedTuple):
  """Router + stacked expert FFN weights.

  router: (D, E). w1/b1: (E, D, H). w2/b2: (E, H, D) — leading expert
  axis is what the `expert` mesh axis shards.
  """
  router: jnp.ndarray
  w1: jnp.ndarray
  b1: jnp.ndarray
  w2: jnp.ndarray
  b2: jnp.ndarray


def init_moe_params(rng: jax.Array, num_experts: int, d_model: int,
                    d_hidden: int, dtype=jnp.float32) -> MoEParams:
  k1, k2, k3 = jax.random.split(rng, 3)
  scale1 = 1.0 / jnp.sqrt(d_model).astype(dtype)
  scale2 = 1.0 / jnp.sqrt(d_hidden).astype(dtype)
  return MoEParams(
      router=jax.random.normal(k1, (d_model, num_experts), dtype) * scale1,
      w1=jax.random.normal(k2, (num_experts, d_model, d_hidden),
                           dtype) * scale1,
      b1=jnp.zeros((num_experts, d_hidden), dtype),
      w2=jax.random.normal(k3, (num_experts, d_hidden, d_model),
                           dtype) * scale2,
      b2=jnp.zeros((num_experts, d_model), dtype),
  )


class _Routing(NamedTuple):
  combine: jnp.ndarray    # (N, E, C) — one-hot dispatch/combine tensor
  gate: jnp.ndarray       # (N,) — top-1 router probability
  fraction: jnp.ndarray   # (E,) — fraction of tokens routed per expert
  mean_prob: jnp.ndarray  # (E,) — mean router probability per expert


def _route(tokens: jnp.ndarray, router: jnp.ndarray,
           capacity: int) -> _Routing:
  """Top-1 routing with per-expert capacity; overflow tokens drop (the
  residual connection around the MoE block carries them unchanged)."""
  n, _ = tokens.shape
  num_experts = router.shape[-1]
  logits = tokens.astype(jnp.float32) @ router.astype(jnp.float32)
  probs = jax.nn.softmax(logits, axis=-1)                  # (N, E)
  expert_index = jnp.argmax(probs, axis=-1)                # (N,)
  gate = jnp.take_along_axis(probs, expert_index[:, None], axis=-1)[:, 0]
  onehot = jax.nn.one_hot(expert_index, num_experts,
                          dtype=jnp.float32)               # (N, E)
  # Position of each token within its expert's queue (first-come).
  position = jnp.cumsum(onehot, axis=0) * onehot           # 1-based
  keep = (position > 0) & (position <= capacity)
  pos_onehot = jax.nn.one_hot(
      ((position - 1.0) * onehot).astype(jnp.int32), capacity,
      dtype=jnp.float32)
  combine = jnp.where(keep[..., None], onehot[..., None] * pos_onehot,
                      0.0)                                 # (N, E, C)
  return _Routing(combine=combine, gate=gate,
                  fraction=jnp.mean(onehot, axis=0),
                  mean_prob=jnp.mean(probs, axis=0))


def _aux_loss(fraction: jnp.ndarray, mean_prob: jnp.ndarray) -> jnp.ndarray:
  """Switch aux loss: E · Σ_e fraction_tokens_e · mean_router_prob_e."""
  return fraction.shape[-1] * jnp.sum(fraction * mean_prob)


def _expert_ffn(buf: jnp.ndarray, params: MoEParams) -> jnp.ndarray:
  """Applies expert e's FFN to buffer row e: (E, C, D) → (E, C, D)."""
  h = jax.nn.relu(
      jnp.einsum("ecd,edh->ech", buf, params.w1.astype(buf.dtype))
      + params.b1[:, None].astype(buf.dtype))
  return (jnp.einsum("ech,ehd->ecd", h, params.w2.astype(buf.dtype))
          + params.b2[:, None].astype(buf.dtype))


def default_capacity(num_tokens: int, num_experts: int,
                     capacity_factor: float = 1.25) -> int:
  return max(1, int(num_tokens * capacity_factor / num_experts))


def switch_moe(tokens: jnp.ndarray, params: MoEParams,
               capacity: Optional[int] = None,
               capacity_factor: float = 1.25):
  """Dense single-device Switch MoE: (N, D) tokens → ((N, D), aux_loss)."""
  n, d = tokens.shape
  num_experts = params.router.shape[-1]
  if capacity is None:
    capacity = default_capacity(n, num_experts, capacity_factor)
  routing = _route(tokens, params.router, capacity)
  f32 = tokens.astype(jnp.float32)
  buf = jnp.einsum("nec,nd->ecd", routing.combine, f32)    # (E, C, D)
  out = _expert_ffn(buf, params)
  y = jnp.einsum("nec,ecd->nd", routing.combine, out)
  y = y * routing.gate[:, None]
  return (y.astype(tokens.dtype),
          _aux_loss(routing.fraction, routing.mean_prob))


def _ep_local(tokens, params: MoEParams, *, axis_name: str, capacity: int):
  """Per-device body: tokens (N_local, D); expert weights (E/P, ...)."""
  routing = _route(tokens, params.router, capacity)
  f32 = tokens.astype(jnp.float32)
  buf = jnp.einsum("nec,nd->ecd", routing.combine, f32)    # (E, C, D)
  # Mesh transpose: every device sends expert-shard e its (C, D) queue →
  # local buffer (E/P, P·C, D) holding ALL devices' tokens for the
  # local expert group.
  buf = jax.lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=1,
                           tiled=True)
  out = _expert_ffn(buf, params)
  # Inverse transpose: results return to their source device.
  out = jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=0,
                           tiled=True)                     # (E, C, D)
  y = jnp.einsum("nec,ecd->nd", routing.combine, out)
  y = y * routing.gate[:, None]
  # Global aux statistics FIRST (token shards are equal-size, so pmean of
  # per-shard means is the global mean), then the nonlinear product —
  # this keeps the EP aux loss bit-identical to the dense path's.
  fraction = jax.lax.pmean(routing.fraction, axis_name)
  mean_prob = jax.lax.pmean(routing.mean_prob, axis_name)
  return y.astype(tokens.dtype), _aux_loss(fraction, mean_prob)


def expert_parallel_moe(
    tokens: jnp.ndarray,
    params: MoEParams,
    mesh: Mesh,
    axis: str = "expert",
    capacity: Optional[int] = None,
    capacity_factor: float = 1.25,
):
  """Switch MoE with experts sharded over the `axis` mesh axis.

  Args:
    tokens: (N, D); N must divide evenly over the axis (tokens are
      data-sharded over the same axis the experts live on — each device
      routes its token shard to all expert shards via all_to_all).
    params: MoEParams; the leading expert axis (size E) must divide
      evenly over the axis and is sharded one-group-per-device.
    mesh: device mesh containing `axis`.
    capacity: per-expert, per-source-device token queue length; default
      `default_capacity(N/P, E, capacity_factor)`.

  Returns:
    ((N, D) output, scalar load-balancing aux loss) — numerically equal
    to `switch_moe` with capacity=P·(per-device capacity) modulo
    first-come ordering of the token shards.
  """
  num_devices = mesh.shape[axis]
  n, _ = tokens.shape
  num_experts = params.router.shape[-1]
  if n % num_devices != 0:
    raise ValueError(f"Token count {n} not divisible by {axis!r} axis "
                     f"size {num_devices}.")
  if num_experts % num_devices != 0:
    raise ValueError(f"Expert count {num_experts} not divisible by "
                     f"{axis!r} axis size {num_devices}.")
  if capacity is None:
    capacity = default_capacity(n // num_devices, num_experts,
                                capacity_factor)
  token_spec = PartitionSpec(axis)
  param_specs = MoEParams(
      router=PartitionSpec(),           # replicated — every device routes
      w1=PartitionSpec(axis), b1=PartitionSpec(axis),
      w2=PartitionSpec(axis), b2=PartitionSpec(axis),
  )
  fn = shard_map(
      functools.partial(_ep_local, axis_name=axis, capacity=capacity),
      mesh=mesh,
      in_specs=(token_spec, param_specs),
      out_specs=(token_spec, PartitionSpec()),
  )
  return fn(tokens, params)

"""Mesh construction and sharding helpers.

Reference parity: replaces utils/train_eval.py §TPUConfig(num_shards) +
models/abstract_model.py §CrossShardOptimizer (SURVEY.md §2 "DP / comms
backend (upstream)") and the fork's NCCL MirroredStrategy. One mesh, any
number of named axes; data parallelism is the `data` axis, and tensor/
sequence parallelism slot in as extra axes without touching the trainer
(SURVEY.md §5.8 rebuild stance).

Design notes (TPU-first):
  - The mesh is created over `jax.devices()` in row-major order; on a real
    pod slice this matches ICI topology well enough for DP (all-reduce is
    topology-agnostic under XLA's collective scheduler). Model axes should
    be innermost (fastest-varying) so TP collectives ride the shortest ICI
    links — `create_mesh` therefore puts `data` outermost by convention.
  - Multi-host: `shard_batch` uses
    `jax.make_array_from_process_local_data`, so each host feeds only its
    local shard (per-host input pipelines, reference's per-host input_fn).
"""

from __future__ import annotations

import collections
import math
from typing import Any, Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def create_mesh(
    axes: Optional[Mapping[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
  """Creates a named device mesh.

  Args:
    axes: ordered {axis_name: size}; at most one size may be -1 (fill with
      all remaining devices). Default: {"data": -1} — pure DP, the
      reference's only strategy.
    devices: devices to mesh over; default jax.devices().

  Returns:
    jax.sharding.Mesh with the requested axes.
  """
  if devices is None:
    devices = jax.devices()
  devices = list(devices)
  if axes is None:
    axes = {"data": -1}
  axes = collections.OrderedDict(axes)
  fill_axes = [name for name, size in axes.items() if size == -1]
  if len(fill_axes) > 1:
    raise ValueError(f"At most one axis may be -1, got {fill_axes}.")
  fixed = math.prod(size for size in axes.values() if size != -1)
  if len(devices) % fixed != 0:
    raise ValueError(
        f"Device count {len(devices)} not divisible by fixed axes {axes}.")
  if fill_axes:
    axes[fill_axes[0]] = len(devices) // fixed
  total = math.prod(axes.values())
  if total != len(devices):
    raise ValueError(
        f"Mesh axes {dict(axes)} require {total} devices, have"
        f" {len(devices)}.")
  mesh_devices = np.asarray(devices).reshape(tuple(axes.values()))
  return Mesh(mesh_devices, tuple(axes.keys()))


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
  """Sharding for batched arrays: leading dim split over `axis`."""
  return NamedSharding(mesh, PartitionSpec(axis))


def mesh_devices(mesh: Mesh) -> list:
  """The mesh's devices as a flat row-major list — the serving fleet's
  replica enumeration (serving/router.py places one bucket-ladder
  replica per entry). Row-major matches create_mesh's layout, so
  replica i of a dp×tp mesh is the same physical chip the training
  side addresses at flat index i — one device numbering for both
  halves of the learner→server loop."""
  return list(mesh.devices.flat)


def nearest_multiples(value: int, divisor: int) -> str:
  """'8 or 16'-style fix suggestion for a size that must divide a mesh
  axis — ONE phrasing for every divisibility-refusal message (ring
  capacity, env fleet width, learn batch), so the actionable-error
  contract cannot drift per call site."""
  lower = (value // divisor) * divisor
  return f"{lower} or {lower + divisor}" if lower else f"{divisor}"


def env_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
  """Sharding for per-shard env fleets: the fleet-width leading dim of
  every episode-state leaf (images, targets, attempts) splits over
  `axis`, so each device steps num_envs / axis_size envs of the fused
  Anakin loop's fleet in place (Podracer's per-core environment slices,
  arXiv:2104.06272). Same rule as `batch_sharding` — a fleet IS a batch
  of envs — but named at the call site so the env-state placement reads
  as intent and can diverge (e.g. a 2D env grid) without touching batch
  consumers. Fleet width must divide the axis; `replay/anakin.AnakinLoop`
  validates and names the fix."""
  return NamedSharding(mesh, PartitionSpec(axis))


def ring_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
  """Sharding for device-resident replay rings: the capacity-leading
  storage/bookkeeping leaves split over `axis`, so each device holds
  capacity / axis_size slots of the ring in its own HBM (the
  weight-update-sharding discipline of arXiv:2004.13336 applied to
  replay state). Capacity must divide the axis;
  `replay/device_buffer.DeviceReplayBuffer` enforces this with an
  actionable error instead of silently replicating."""
  return NamedSharding(mesh, PartitionSpec(axis))


def stacked_batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
  """Sharding for K-stacked batches (loop axis, batch, ...): the leading
  scan axis is replicated, the batch dim behind it splits over `axis`
  (consumed by Trainer.train_steps, the iterations_per_loop path)."""
  return NamedSharding(mesh, PartitionSpec(None, axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
  """Fully-replicated sharding (params, opt state under pure DP)."""
  return NamedSharding(mesh, PartitionSpec())


def local_batch_slice(global_batch_size: int) -> int:
  """Per-process batch size for a per-host input pipeline.

  Reference parity: TPUEstimator's per-host input_fn sharding
  (params['batch_size'] = global / num_hosts), SURVEY.md §3.1.
  """
  if global_batch_size % jax.process_count() != 0:
    raise ValueError(
        f"Global batch {global_batch_size} not divisible by process count"
        f" {jax.process_count()}.")
  return global_batch_size // jax.process_count()


def shard_batch(mesh: Mesh, batch: Any, axis: str = "data") -> Any:
  """Places a host-local batch pytree onto the mesh, sharded over `axis`.

  Single-process: a plain sharded device_put. Multi-process: each host
  contributes its local slice of the global batch
  (jax.make_array_from_process_local_data assembles the global logical
  array) — the host→device boundary of SURVEY.md §3.1 without infeed
  queues.
  """
  axis_size = mesh.shape[axis]
  batched_leaves = [leaf for leaf in jax.tree_util.tree_leaves(batch)
                    if np.ndim(leaf) >= 1]
  for leaf in batched_leaves:
    global_size = np.shape(leaf)[0] * jax.process_count()
    if global_size % axis_size != 0:
      raise ValueError(
          f"Global batch size {global_size} (local "
          f"{np.shape(leaf)[0]} × {jax.process_count()} processes) is "
          f"not divisible by the {axis!r} mesh axis ({axis_size} devices); "
          "choose a batch size that is a multiple of the data-parallel "
          "degree.")
  sharding = batch_sharding(mesh, axis)
  replicated = replicated_sharding(mesh)

  def leaf_sharding(leaf):
    # Scalar leaves (loss masks, step counters riding in a batch pytree)
    # have no batch dim to split: replicate them instead of erroring.
    return sharding if np.ndim(leaf) >= 1 else replicated

  if jax.process_count() == 1:
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, leaf_sharding(x)), batch)
  return jax.tree_util.tree_map(
      lambda x: jax.make_array_from_process_local_data(
          leaf_sharding(x), np.asarray(x)),
      batch)

"""Pod-scale bring-up bench (ISSUE 19): MULTIHOST_r19's generator.

Three claims, each proven against live machinery on the chipless
virtual mesh (2 emulated hosts x 4 virtual CPU devices each — REAL
separate processes speaking the JAX coordination service, not threads):

1. **Multi-controller mesh bring-up** — ONE ``anakin_step`` lowers over
   a cross-process Mesh: 2 processes x cpu_mesh_env(4) = 8 global
   devices, mesh {data: 4, model: 2}, composing the ISSUE 16 tp rules
   and ZeRO-1 with the ISSUE 19 placement seam
   (``distributed.global_put``/``global_scalar``). Bars: every process
   sees 8 global devices, compiles ``anakin_step`` exactly ONCE
   (per-process exactly-once ledgers), reaches the same trained-step
   count, and emits a bit-identical replicated metric stream — two
   controllers, one program.
2. **Oracle parity** — at process_count == 1 the placement seam IS the
   pre-ISSUE-19 code (``global_put`` == ``jax.device_put``,
   ``global_scalar`` == ``jnp.asarray``). Proven by running the same
   single-process tp=1 config twice — seam live vs seam literally
   monkeypatched back to the r17 calls — and requiring bit-identical
   metric streams, final evals, and compile ledgers.
3. **Fused kill-and-resume** — the between-dispatch barrier checkpoint
   (loop._save_fused_checkpoint) survives losing a process: a 2-process
   run is killed (os._exit, non-primary rank) immediately after its
   first fused save; the relaunched 2-process run restores the
   composite shard-for-shard and its post-resume metric stream is
   bit-identical to an uninterrupted control run's entries past the
   checkpoint step.
4. **Router-of-routers front door** — 2 emulated-host FleetRouters
   (each with its OWN MetricRegistry/ServingStats, exported under its
   own host label) behind one FrontDoor: ingress-stamped deadlines and
   correlation ids survive the hop (cross_process_flows covers every
   request), per-host logical_requests reconcile 1:1 with the front
   door's submit count, and a genuinely corrupted host replica
   (faults.corrupt_served_variables — finite, plausible, wrong) is
   named divergent by the obs/aggregate Q-drift rollup and quarantined
   BY NAME via ``FrontDoor.apply_drift_rollup``, after which ingress
   lands only on the healthy host.

Honesty rule (virtual mesh): throughput and scaling-efficiency keys are
null — 8 virtual devices on a small CPU host measure XLA partitioning
overhead, not chips; structure/ordering/parity claims are what this
artifact carries. Latency-budget bars (front-door per-class p99) are
enforced only when ``os.cpu_count() >= 4``; below that they are
reported null with the gate named.

CLI (ONE JSON line; bars enforced at generation on --smoke):

    python -m tensor2robot_tpu.parallel.multihost_bench --smoke \\
        --out MULTIHOST_r19.json

    # Reduced tier-1 lane (front-door phase only, bars deferred):
    python -m tensor2robot_tpu.parallel.multihost_bench --ci
"""

from __future__ import annotations

import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

SCHEMA = "t2r-multihost-1"

# Metric keys compared bit-for-bit across processes / runs (full float64
# precision through JSON round-trip — equality here IS bit-identity).
STREAM_KEYS = ("replay/train_loss", "replay/train_td_error",
               "replay/train_q_next", "replay/sample_staleness")

_WORKER_FLAG = "--worker"


def _repo_root() -> str:
  return os.path.dirname(os.path.dirname(
      os.path.dirname(os.path.abspath(__file__))))


def _free_port() -> int:
  with socket.socket() as s:
    s.bind(("localhost", 0))
    return s.getsockname()[1]


def _read_stream(logdir: str) -> List[dict]:
  """The worker's training metric stream, full precision, in step order."""
  path = os.path.join(logdir, "metrics.jsonl")
  stream = []
  if not os.path.exists(path):
    return stream
  with open(path) as f:
    for line in f:
      record = json.loads(line)
      if "replay/train_loss" in record:
        stream.append({"step": record["step"],
                       **{key: record[key] for key in STREAM_KEYS
                          if key in record}})
  return stream


# --- worker (runs in a fresh interpreter under cpu_mesh_env) ---------------


def _run_worker(spec: Dict) -> None:
  """One emulated host: ``distributed.initialize`` FIRST (the one-shot
  backend contract), then the stock ReplayTrainLoop anakin config —
  nothing in here is bench-special except the kill hook."""
  from tensor2robot_tpu.parallel import distributed as dist_lib
  if spec["num_processes"] > 1:
    dist_lib.initialize(spec["coordinator"], spec["num_processes"],
                        spec["process_id"])
  import jax
  import optax
  from tensor2robot_tpu.replay.loop import ReplayLoopConfig, ReplayTrainLoop
  from tensor2robot_tpu.replay.smoke import TinyQCriticModel

  if spec.get("oracle_seam"):
    # The r17 oracle: un-patch the ISSUE 19 placement seam back to the
    # literal pre-PR calls. Single-process lowering must not notice.
    import jax.numpy as jnp
    dist_lib.global_put = jax.device_put
    dist_lib.global_scalar = (
        lambda value, mesh, dtype=None: jnp.asarray(value, dtype))

  config = ReplayLoopConfig(
      seed=spec["seed"], anakin=True, image_size=8, action_size=4,
      mesh_dp=spec["mesh_dp"], mesh_tp=spec["mesh_tp"],
      envs_per_collector=spec.get("envs_per_collector", 4),
      log_every=1, eval_every=10**6,
      checkpoint_every=spec.get("checkpoint_every", 0),
      checkpoint_dir=spec.get("checkpoint_dir"),
      resume=spec.get("resume", False))
  model = TinyQCriticModel(
      image_size=config.image_size, action_size=config.action_size,
      optimizer_fn=lambda: optax.adam(config.learning_rate))
  loop = ReplayTrainLoop(config, spec["logdir"], model=model)

  if spec.get("kill_after_save"):
    # Crash protocol: die IMMEDIATELY after the first fused save
    # completes (past its done-barrier, so the checkpoint is whole).
    # Only the designated rank exits; the survivor demonstrates the
    # pod-level failure mode (stuck in the next dispatch's collective)
    # until the parent reaps it.
    original = loop._save_fused_checkpoint
    kill_rank = spec["kill_after_save"]["rank"]

    def _save_then_die(step, state, learner, initial_eval, eval_history):
      original(step, state, learner, initial_eval, eval_history)
      if spec["process_id"] == kill_rank:
        print(f"WORKER{spec['process_id']}_KILLED step={step}",
              flush=True)
        os._exit(3)

    loop._save_fused_checkpoint = _save_then_die

  result = loop.run(spec["num_steps"])
  summary = {
      "process_id": spec["process_id"],
      "process_count": jax.process_count(),
      "global_devices": jax.device_count(),
      "local_devices": jax.local_device_count(),
      "steps": result["steps"],
      "mesh_shape": result["mesh_shape"],
      "zero1": result["zero1"],
      "compile_counts": result["compile_counts"],
      "env_steps": result["env_steps_collected"],
      "final_eval": result["final_eval"],
      "stream": _read_stream(spec["logdir"]),
  }
  print(f"WORKER{spec['process_id']}_RESULT " + json.dumps(summary),
        flush=True)
  print(f"WORKER{spec['process_id']}_OK", flush=True)


# --- parent-side orchestration ---------------------------------------------


def _learner_round(workdir: str, num_processes: int, num_steps: int,
                   mesh_dp: int, mesh_tp: int, seed: int,
                   local_devices: int = 4,
                   envs_per_collector: int = 4,
                   checkpoint_every: int = 0,
                   checkpoint_dir: Optional[str] = None,
                   resume: bool = False,
                   kill_rank: Optional[int] = None,
                   oracle_seam: bool = False,
                   timeout_s: float = 900.0) -> Dict:
  """Spawns ``num_processes`` real workers against one coordination
  service and returns their parsed summaries. ``kill_rank`` arms the
  crash protocol: that rank os._exits(3) after the first fused save and
  the survivors are reaped (their output is not a result)."""
  from tensor2robot_tpu.utils.cpu_mesh_env import cpu_mesh_env
  port = _free_port()
  env = cpu_mesh_env(local_devices)
  env["PYTHONPATH"] = (_repo_root() + os.pathsep
                       + env.get("PYTHONPATH", ""))
  procs = []
  for process_id in range(num_processes):
    logdir = os.path.join(workdir, f"proc{process_id}")
    os.makedirs(logdir, exist_ok=True)
    spec = {
        "process_id": process_id,
        "num_processes": num_processes,
        "coordinator": f"localhost:{port}",
        "logdir": logdir,
        "num_steps": num_steps,
        "mesh_dp": mesh_dp,
        "mesh_tp": mesh_tp,
        "envs_per_collector": envs_per_collector,
        "seed": seed,
        "checkpoint_every": checkpoint_every,
        "checkpoint_dir": checkpoint_dir,
        "resume": resume,
        "oracle_seam": oracle_seam,
    }
    if kill_rank is not None:
      spec["kill_after_save"] = {"rank": kill_rank}
    procs.append(subprocess.Popen(
        [sys.executable, "-m",
         "tensor2robot_tpu.parallel.multihost_bench", _WORKER_FLAG,
         json.dumps(spec)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True))
  outputs: List[Optional[str]] = [None] * num_processes
  try:
    if kill_rank is not None:
      # Wait for the dying rank; the survivors are then stuck in the
      # next dispatch's cross-process collective — reap them.
      out, _ = procs[kill_rank].communicate(timeout=timeout_s)
      outputs[kill_rank] = out
      for i, proc in enumerate(procs):
        if i != kill_rank and proc.poll() is None:
          proc.kill()
          outputs[i], _ = proc.communicate()
      return {"killed_rank": kill_rank,
              "killed_rc": procs[kill_rank].returncode,
              "killed_output": outputs[kill_rank]}
    for i, proc in enumerate(procs):
      out, _ = proc.communicate(timeout=timeout_s)
      outputs[i] = out
      if proc.returncode != 0:
        raise RuntimeError(
            f"multihost worker {i} failed rc={proc.returncode}:\n{out}")
  finally:
    for proc in procs:
      if proc.poll() is None:
        proc.kill()
        proc.communicate()
  workers = []
  for i, out in enumerate(outputs):
    marker = f"WORKER{i}_RESULT "
    lines = [ln for ln in (out or "").splitlines()
             if ln.startswith(marker)]
    if not lines or f"WORKER{i}_OK" not in (out or ""):
      raise RuntimeError(f"worker {i} produced no result:\n{out}")
    workers.append(json.loads(lines[0][len(marker):]))
  return {"workers": workers}


def _ledger_subset(compile_counts: Dict) -> Dict:
  """The executables whose exactly-once property the bars assert."""
  return {key: value for key, value in sorted(compile_counts.items())
          if key.startswith(("anakin", "ring_"))}


def _bar(enforce: bool, ok: bool, message: str) -> bool:
  if enforce and not ok:
    raise AssertionError(message)
  return bool(ok)


def measure_mesh_bringup(workdir: str, seed: int, num_steps: int,
                         checkpoint_dir: str, enforce_bars: bool) -> Dict:
  """Phase 1: one anakin_step over 2 real processes x 4 virtual devices
  (this run, with checkpoint_every=5, doubles as the uninterrupted
  control for the resume-parity phase)."""
  round_ = _learner_round(
      workdir, num_processes=2, num_steps=num_steps, mesh_dp=4,
      mesh_tp=2, seed=seed, checkpoint_every=5,
      checkpoint_dir=checkpoint_dir)
  workers = round_["workers"]
  ledgers = [_ledger_subset(w["compile_counts"]) for w in workers]
  bars = {
      "two_processes": _bar(
          enforce_bars,
          all(w["process_count"] == 2 for w in workers),
          f"expected process_count 2: {workers}"),
      "eight_global_devices": _bar(
          enforce_bars,
          all(w["global_devices"] == 8 and w["local_devices"] == 4
              for w in workers),
          f"expected 2x4=8 global devices: {workers}"),
      "anakin_step_compiled_once_per_process": _bar(
          enforce_bars,
          all(w["compile_counts"].get("anakin_step") == 1
              for w in workers),
          f"anakin_step must compile exactly once per process: {ledgers}"),
      "tp_zero1_composed": _bar(
          enforce_bars,
          all(w["mesh_shape"] == {"data": 4, "model": 2} and w["zero1"]
              for w in workers),
          f"expected dp=4 tp=2 zero1 mesh: {workers}"),
      "same_final_step": _bar(
          enforce_bars,
          len({w["steps"] for w in workers}) == 1,
          f"processes disagree on trained steps: {workers}"),
      "replicated_stream_identical": _bar(
          enforce_bars,
          workers[0]["stream"] == workers[1]["stream"]
          and len(workers[0]["stream"]) > 0,
          "replicated metric streams differ across processes"),
  }
  return {
      "processes": 2,
      "local_devices_per_process": 4,
      "global_devices": workers[0]["global_devices"],
      "mesh_shape": workers[0]["mesh_shape"],
      "zero1": workers[0]["zero1"],
      "steps": workers[0]["steps"],
      "env_steps": workers[0]["env_steps"],
      "per_process_ledgers": ledgers,
      "stream_steps": [entry["step"] for entry in workers[0]["stream"]],
      "bars": bars,
      "control_workers": workers,  # consumed by the resume phase
  }


def measure_oracle_parity(workdir: str, seed: int, num_steps: int,
                          enforce_bars: bool) -> Dict:
  """Phase 2: seam-live vs seam-reverted single-process runs (tp=1, the
  r17 oracle config) must be bit-identical everywhere that matters."""
  live = _learner_round(
      os.path.join(workdir, "live"), num_processes=1,
      num_steps=num_steps, mesh_dp=8, mesh_tp=1, seed=seed,
      local_devices=8, envs_per_collector=8)["workers"][0]
  oracle = _learner_round(
      os.path.join(workdir, "oracle"), num_processes=1,
      num_steps=num_steps, mesh_dp=8, mesh_tp=1, seed=seed,
      local_devices=8, envs_per_collector=8,
      oracle_seam=True)["workers"][0]
  bars = {
      "stream_bit_identical": _bar(
          enforce_bars,
          live["stream"] == oracle["stream"] and len(live["stream"]) > 0,
          f"seam changed 1-process lowering: {live['stream']} vs "
          f"{oracle['stream']}"),
      "final_eval_bit_identical": _bar(
          enforce_bars, live["final_eval"] == oracle["final_eval"],
          f"final evals differ: {live['final_eval']} vs "
          f"{oracle['final_eval']}"),
      "ledger_identical": _bar(
          enforce_bars,
          live["compile_counts"] == oracle["compile_counts"],
          f"compile ledgers differ: {live['compile_counts']} vs "
          f"{oracle['compile_counts']}"),
  }
  return {
      "config": {"mesh_dp": 8, "mesh_tp": 1, "processes": 1},
      "steps": live["steps"],
      "stream_steps": [entry["step"] for entry in live["stream"]],
      "bars": bars,
  }


def measure_fused_resume(workdir: str, seed: int, num_steps: int,
                         control_workers: List[dict],
                         enforce_bars: bool) -> Dict:
  """Phase 3: kill rank 1 right after the first fused save, relaunch
  both ranks with resume=True, and require the post-resume streams to
  match the uninterrupted control bit-for-bit."""
  checkpoint_dir = os.path.join(workdir, "ckpt")
  killed = _learner_round(
      os.path.join(workdir, "killed"), num_processes=2,
      num_steps=num_steps, mesh_dp=4, mesh_tp=2, seed=seed,
      checkpoint_every=5, checkpoint_dir=checkpoint_dir, kill_rank=1)
  saved_steps = sorted(int(name) for name in os.listdir(checkpoint_dir)
                       if name.isdigit())
  resume_step = saved_steps[0] if saved_steps else None
  resumed = _learner_round(
      os.path.join(workdir, "resumed"), num_processes=2,
      num_steps=num_steps, mesh_dp=4, mesh_tp=2, seed=seed,
      checkpoint_every=5, checkpoint_dir=checkpoint_dir, resume=True)
  workers = resumed["workers"]
  parity = []
  for rank, worker in enumerate(workers):
    control_tail = [entry for entry in control_workers[rank]["stream"]
                    if resume_step is not None
                    and entry["step"] > resume_step]
    parity.append(worker["stream"] == control_tail
                  and len(control_tail) > 0)
  bars = {
      "killed_rank_exited_3": _bar(
          enforce_bars, killed["killed_rc"] == 3,
          f"kill hook did not fire: rc={killed['killed_rc']}\n"
          f"{killed['killed_output']}"),
      "checkpoint_landed_before_kill": _bar(
          enforce_bars, resume_step is not None,
          f"no fused checkpoint on disk under {checkpoint_dir}"),
      "resumed_to_control_step": _bar(
          enforce_bars,
          all(w["steps"] == control_workers[0]["steps"]
              for w in workers),
          f"resumed final steps diverge from control: {workers}"),
      "post_resume_stream_bit_identical": _bar(
          enforce_bars, all(parity),
          f"post-resume streams diverge from control tail: {parity}"),
      "final_eval_matches_control": _bar(
          enforce_bars,
          workers[0]["final_eval"] == control_workers[0]["final_eval"],
          f"resumed final eval differs: {workers[0]['final_eval']} vs "
          f"{control_workers[0]['final_eval']}"),
  }
  return {
      "resume_step": resume_step,
      "killed_rank": 1,
      "killed_rc": killed["killed_rc"],
      "post_resume_stream_steps": [entry["step"]
                                   for entry in workers[0]["stream"]],
      "fused_resume_parity_ok": all(bars.values()),
      "bars": bars,
  }


def measure_frontdoor(seed: int, requests: int, enforce_bars: bool,
                      with_drift: bool = True) -> Dict:
  """Phase 4: the router-of-routers over two emulated hosts sharing
  device NAMES (the distinctness claim: hostA's replica on the same
  device stays healthy while hostB's is corrupted and named)."""
  import jax
  import numpy as np
  from tensor2robot_tpu.obs import aggregate as aggregate_lib
  from tensor2robot_tpu.obs import faults as faults_lib
  from tensor2robot_tpu.obs import registry as registry_lib
  from tensor2robot_tpu.obs import trace as trace_lib
  from tensor2robot_tpu.serving import slo as slo_lib
  from tensor2robot_tpu.serving.frontdoor import FrontDoor
  from tensor2robot_tpu.serving.router import FleetRouter
  from tensor2robot_tpu.serving.smoke import TinyQPredictor
  from tensor2robot_tpu.serving.stats import ServingStats

  quantitative = (os.cpu_count() or 1) >= 4
  logdir = tempfile.mkdtemp(prefix="multihost_frontdoor_")
  devices = jax.devices()[:2]
  predictor = TinyQPredictor(seed=seed)
  registries: Dict[str, registry_lib.MetricRegistry] = {}
  hosts: Dict[str, FleetRouter] = {}
  corrupt_site = str(devices[0])
  for name in ("hostA", "hostB"):
    registry = registries[name] = registry_lib.MetricRegistry()
    plan = None
    if with_drift and name == "hostB":
      # Finite, plausible, wrong: only the fleet Q-drift rollup can
      # catch this — hostA's replica on the SAME-NAMED device is the
      # healthy twin the attribution must not confuse.
      plan = faults_lib.FaultPlan([
          faults_lib.FaultSpec(kind="corrupt_served_variables",
                               point="replica_dispatch",
                               site=corrupt_site, at=0, scale=16.0)],
          seed=seed)
    hosts[name] = FleetRouter(
        predictor, devices=devices, ladder_sizes=(1, 2), seed=seed,
        stats=ServingStats(registry=registry), fault_plan=plan)
  door = FrontDoor(hosts)
  door.warmup(predictor.make_image)
  classes = list(slo_lib.DEFAULT_CLASSES)
  latencies: Dict[str, List[float]] = {cls.name: [] for cls in classes}
  pid = os.getpid()
  with door:
    for i in range(requests):
      cls = classes[i % len(classes)]
      begin = time.perf_counter()
      action = door.act(predictor.make_image(seed + i), slo=cls)
      latencies[cls.name].append(
          (time.perf_counter() - begin) * 1e3)
      assert np.asarray(action).shape == (4,)
      time.sleep(0.002)
    pre_drift = door.snapshot()

    # The fleet merge: per-emulated-host registries + both trace lanes.
    for name, registry in registries.items():
      host_dir = os.path.join(logdir, name)
      os.makedirs(host_dir, exist_ok=True)
      registry.export_snapshot(os.path.join(host_dir, "registry.json"),
                               host=name)
    trace_lib.get_tracer().export_chrome_trace(
        os.path.join(logdir, "trace-hostpool.json"))
    door.export_trace(os.path.join(logdir, "trace-frontdoor.json"))
    fleet = aggregate_lib.aggregate_logdir(logdir)

    named = []
    if with_drift:
      named = door.apply_drift_rollup(
          fleet["health"],
          {f"hostA:{pid}": "hostA", f"hostB:{pid}": "hostB"})
    before = door.snapshot()["hosts"]
    post_quarantine = 12
    for i in range(post_quarantine):
      door.act(predictor.make_image(seed + requests + i),
               slo=classes[i % len(classes)])
    after = door.snapshot()
  drift = fleet["health"]["q_drift"]
  divergent = list(drift.get("divergent", []))
  p99_by_class = {
      name: (sorted(values)[max(0, int(len(values) * 0.99) - 1)]
             if values else None)
      for name, values in latencies.items()}
  budgets = {cls.name: cls.deadline_ms for cls in classes}
  headroom = None
  if quantitative:
    headroom = min(
        (budgets[name] - p99_by_class[name]) / budgets[name]
        for name in budgets)
  bars = {
      "reconciled_exact": _bar(
          enforce_bars,
          pre_drift["reconciled"] and after["reconciled"],
          f"front-door/host logical_requests mismatch: {after}"),
      "flows_cross_the_hop": _bar(
          enforce_bars,
          fleet["trace"]["cross_process_flows"] >= requests,
          f"expected >= {requests} cross-lane request flows, got "
          f"{fleet['trace']['cross_process_flows']}"),
      "all_replica_sketches_qualify": _bar(
          enforce_bars,
          with_drift and all(
              entry.get("qualifying")
              for entry in drift.get("replicas", {}).values())
          or not with_drift,
          f"replica served-Q sketches too thin for drift: {drift}"),
      "corrupted_host_named": _bar(
          enforce_bars,
          not with_drift
          or (f"hostB:{pid}/{corrupt_site}" in divergent
              and not any(key.startswith("hostA:")
                          for key in divergent)
              and named == [f"hostB:{corrupt_site}"]),
          f"drift rollup misattributed the corrupted host: "
          f"divergent={divergent} named={named}"),
      "quarantine_diverts_ingress": _bar(
          enforce_bars,
          not with_drift
          or (after["hosts"]["hostB"]["submitted"]
              == before["hostB"]["submitted"]
              and after["hosts"]["hostA"]["submitted"]
              == before["hostA"]["submitted"] + post_quarantine),
          f"post-quarantine ingress still reached hostB: "
          f"{before} -> {after['hosts']}"),
      "p99_inside_every_budget": _bar(
          enforce_bars and quantitative,
          (not quantitative) or headroom is None or headroom > 0,
          f"front-door p99 breached a class budget: {p99_by_class} vs "
          f"{budgets}"),
  }
  shutil.rmtree(logdir, ignore_errors=True)
  return {
      "requests": requests + post_quarantine,
      "hosts": 2,
      "replicas_per_host": 2,
      "submitted": after["submitted"],
      "hosts_logical_requests_total": after[
          "hosts_logical_requests_total"],
      "per_class": after["per_class"],
      "p99_ms_by_class": ({name: round(value, 3)
                           for name, value in p99_by_class.items()
                           if value is not None}
                          if quantitative else None),
      "class_budgets_ms": budgets,
      "frontdoor_p99_headroom": (round(headroom, 4)
                                 if headroom is not None else None),
      "cross_process_flows": fleet["trace"]["cross_process_flows"],
      "divergent": divergent,
      "quarantined": named,
      "timeline_events": [entry["event"]
                          for entry in after["timeline"]],
      "quantitative": quantitative,
      "bars": bars,
  }


def measure_multihost(seed: int = 0, num_steps: int = 15,
                      frontdoor_requests: int = 240,
                      enforce_bars: bool = True) -> Dict:
  """The committed MULTIHOST_r19 protocol (see module docstring)."""
  workdir = tempfile.mkdtemp(prefix="multihost_r19_")
  try:
    bringup = measure_mesh_bringup(
        os.path.join(workdir, "bringup"), seed, num_steps,
        checkpoint_dir=os.path.join(workdir, "bringup", "ckpt"),
        enforce_bars=enforce_bars)
    control_workers = bringup.pop("control_workers")
    oracle = measure_oracle_parity(
        os.path.join(workdir, "oracle"), seed, num_steps=10,
        enforce_bars=enforce_bars)
    resume = measure_fused_resume(
        os.path.join(workdir, "resume"), seed, num_steps,
        control_workers=control_workers, enforce_bars=enforce_bars)
    frontdoor = measure_frontdoor(
        seed, requests=frontdoor_requests, enforce_bars=enforce_bars)
  finally:
    shutil.rmtree(workdir, ignore_errors=True)
  return {
      "schema": SCHEMA,
      "virtual_mesh": True,
      "mesh_bringup": bringup,
      "oracle_parity": oracle,
      "fused_resume": resume,
      "frontdoor": frontdoor,
      # Compact sentinels (bench.py round 19; null-safe): structure/
      # parity claims are meaningful chipless; rates are not.
      "multihost_processes": bringup["processes"],
      "oracle_bit_identical": all(oracle["bars"].values()),
      "fused_resume_parity_ok": resume["fused_resume_parity_ok"],
      "frontdoor_p99_headroom": frontdoor["frontdoor_p99_headroom"],
      "frontdoor_reconciled": frontdoor["bars"]["reconciled_exact"],
      # Honesty rule: a 2-process mesh emulated on one small CPU host
      # measures coordination-service and XLA partitioning overhead,
      # not interconnect — rate and scaling keys are null until the
      # real-chip pod slice (ROADMAP item 1).
      "env_steps_per_sec": None,
      "scaling_efficiency": None,
      "note": (
          "Pod-scale bring-up on the VIRTUAL mesh: 2 real processes x "
          "4 virtual CPU devices through the JAX coordination service. "
          "One anakin_step lowers over the cross-process dp=4 x tp=2 "
          "mesh (ZeRO-1 on) with exactly-once per-process compile "
          "ledgers and bit-identical replicated metric streams; the "
          "1-process placement seam is bit-identical to the r17 tp=1 "
          "oracle (live vs monkeypatched-back runs); kill-one-process "
          "after the first fused save resumes shard-for-shard with "
          "post-resume streams bit-identical to the uninterrupted "
          "control; the front door reconciles ingress 1:1 against "
          "per-host routers, links every request flow across the hop, "
          "and quarantines the drift-rollup-named corrupted host by "
          "name. virtual_mesh=true: throughput/scaling keys null by "
          "rule; front-door p99 bars gated on cpu_count >= 4."),
  }


def main(argv=None) -> None:
  """CLI: ONE JSON line. --smoke bootstraps the 8-virtual-device CPU
  mesh for the parent (workers get their own 4-device envs) and runs
  the committed MULTIHOST_r19 protocol with generation-time bar
  enforcement; --ci is the reduced tier-1 lane (front-door phase only,
  bars deferred to tests/)."""
  import argparse

  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument(_WORKER_FLAG, dest="worker", default=None,
                      help=argparse.SUPPRESS)
  parser.add_argument("--smoke", action="store_true",
                      help="chipless committed-artifact lane: full "
                           "protocol, bars enforced at generation time")
  parser.add_argument("--ci", action="store_true",
                      help="reduced chipless lane (front door only)")
  parser.add_argument("--seed", type=int, default=0)
  parser.add_argument("--out", default=None,
                      help="also write the JSON line to this file")
  args = parser.parse_args(argv)
  if args.worker is not None:
    _run_worker(json.loads(args.worker))
    return
  if args.smoke or args.ci:
    from tensor2robot_tpu.utils.cpu_mesh_env import (cpu_mesh_env,
                                                     is_cpu_mesh_env)
    n = 8 if args.smoke else 2
    if not is_cpu_mesh_env(n):
      if argv is not None:
        raise RuntimeError(
            "--smoke/--ci need the virtual CPU mesh configured before "
            "JAX initializes; call main() with argv=None (the CLI "
            "re-execs itself).")
      os.execve(sys.executable,
                [sys.executable, "-m",
                 "tensor2robot_tpu.parallel.multihost_bench",
                 *sys.argv[1:]],
                cpu_mesh_env(n))
  if args.ci:
    results = {
        "schema": SCHEMA,
        "virtual_mesh": True,
        "frontdoor": measure_frontdoor(
            args.seed, requests=60, enforce_bars=False),
    }
  else:
    results = measure_multihost(seed=args.seed)
  line = json.dumps(results)
  if args.out:
    with open(args.out, "w") as f:
      f.write(line + "\n")
  print(line)


if __name__ == "__main__":
  main()

"""Pipeline parallelism: microbatched stage execution over a mesh axis.

Beyond the reference (whose only strategy was data parallelism —
SURVEY.md §2 "Parallelism strategies"): a GPipe-style pipeline expressed
the TPU-native way. Stages are homogeneous (same pytree structure per
stage, the usual repeated-block case); their params are stacked with a
leading stage axis sharded over the `stage` mesh axis, so each device
holds exactly one stage's weights. Under `shard_map`, activations flow
stage→stage via `jax.lax.ppermute` (one ICI hop per tick) while
microbatches stream in, filling the pipeline; the loop runs
M + P - 1 ticks (bubble fraction (P-1)/(M+P-1), amortized by more
microbatches).

Differentiating through the schedule gives the backward pipeline for
free: ppermute's transpose is the reverse-direction ppermute, so
`jax.grad` of a pipelined loss runs the textbook reverse schedule
without any hand-written backward pass.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

try:  # promoted to jax.shard_map in newer releases
  from jax import shard_map
except ImportError:
  from jax.experimental.shard_map import shard_map


def stack_stage_params(params_per_stage: Sequence[Any]) -> Any:
  """Stacks per-stage param pytrees (identical structure) along a new
  leading stage axis — the layout pipeline_apply shards over `stage`."""
  return jax.tree_util.tree_map(
      lambda *leaves: jnp.stack(leaves), *params_per_stage)


def _pipeline_local(stacked_params, microbatches, *, stage_fn,
                    axis_name: str):
  """Per-device body. stacked_params leaves are (1, ...) local slices;
  microbatches leaves are (M, mb, ...) (replicated over the axis)."""
  index = jax.lax.axis_index(axis_name)
  num_stages = jax.lax.psum(1, axis_name)
  params = jax.tree_util.tree_map(lambda p: p[0], stacked_params)
  num_microbatches = jax.tree_util.tree_leaves(microbatches)[0].shape[0]

  first = jax.tree_util.tree_map(lambda x: x[0], microbatches)
  out_struct = jax.eval_shape(stage_fn, params, first)
  zeros_like_out = jax.tree_util.tree_map(
      lambda s: jnp.zeros(s.shape, s.dtype), out_struct)
  # Activations keep the stage-output structure from tick to tick; the
  # input microbatch structure must match it (homogeneous stages).
  outputs = jax.tree_util.tree_map(
      lambda s: jnp.zeros((num_microbatches,) + s.shape, s.dtype),
      out_struct)
  forward = [(i, i + 1) for i in range(num_stages - 1)]

  def tick(t, carry):
    incoming, outputs = carry
    # Stage 0 consumes microbatch t while t < M, then recirculates its
    # last input (those trailing ticks only drain later stages; the
    # results computed from the stale input never reach `outputs`).
    feed_index = jnp.minimum(t, num_microbatches - 1)
    feed = jax.tree_util.tree_map(
        lambda x: jax.lax.dynamic_index_in_dim(x, feed_index, 0,
                                               keepdims=False),
        microbatches)
    x = jax.tree_util.tree_map(
        lambda a, b: jnp.where(index == 0, a, b), feed, incoming)
    y = stage_fn(params, x)
    # The last stage finished microbatch t - (P - 1) at this tick.
    done = t - (num_stages - 1)
    write = jnp.logical_and(index == num_stages - 1, done >= 0)
    slot = jnp.maximum(done, 0)
    outputs = jax.tree_util.tree_map(
        lambda buf, val: jax.lax.dynamic_update_index_in_dim(
            buf,
            jnp.where(write, val,
                      jax.lax.dynamic_index_in_dim(buf, slot, 0, False)),
            slot, 0),
        outputs, y)
    # Hand activations to the next stage (stage 0 receives zeros).
    incoming = jax.tree_util.tree_map(
        lambda a: jax.lax.ppermute(a, axis_name, forward), y)
    return incoming, outputs

  # Mark the carried buffers device-varying up front (they depend on
  # axis_index from the first tick) for shard_map's VMA type check.
  _pcast = getattr(jax.lax, "pcast",
                   lambda x, axes, to: x)  # pre-VMA jax: no-op
  varying = lambda tree: jax.tree_util.tree_map(
      lambda x: _pcast(x, (axis_name,), to="varying"), tree)
  init = (varying(zeros_like_out), varying(outputs))
  _, outputs = jax.lax.fori_loop(
      0, num_microbatches + num_stages - 1, tick, init)
  # Only the last stage holds real outputs; psum over the (zero
  # elsewhere) buffers replicates them to every stage.
  return jax.lax.psum(outputs, axis_name)


def pipeline_apply(
    stacked_params: Any,
    batch: Any,
    stage_fn: Callable[[Any, Any], Any],
    mesh: Mesh,
    axis: str = "stage",
    num_microbatches: Optional[int] = None,
) -> Any:
  """Runs `batch` through P pipelined stages of `stage_fn`.

  Args:
    stacked_params: pytree whose leaves carry a leading stage axis of
      size P (see stack_stage_params); sharded over `axis`.
    batch: pytree of (B, ...) arrays; num_microbatches must divide B.
      The batch structure must equal the stage output structure
      (homogeneous stages — x and stage_fn(params, x) match).
    stage_fn: (stage_params, x) -> y for ONE stage.
    mesh: device mesh containing `axis`.
    num_microbatches: default P (one in flight per stage); more
      microbatches shrink the pipeline bubble.

  Returns:
    (B, ...) pytree: stage_fn applied P times in sequence.
  """
  num_stages = mesh.shape[axis]
  for path, leaf in jax.tree_util.tree_leaves_with_path(stacked_params):
    if leaf.shape[:1] != (num_stages,):
      raise ValueError(
          f"stacked_params leaf {jax.tree_util.keystr(path)} has leading "
          f"dim {leaf.shape[:1]}, but the {axis!r} mesh axis has "
          f"{num_stages} stages — shard_map would silently keep only "
          "the first stage of each local slice.")
  m = num_microbatches or num_stages
  leaves = jax.tree_util.tree_leaves(batch)
  b = leaves[0].shape[0]
  if b % m != 0:
    raise ValueError(f"Batch size {b} not divisible by "
                     f"num_microbatches={m}.")
  microbatched = jax.tree_util.tree_map(
      lambda x: x.reshape((m, b // m) + x.shape[1:]), batch)

  params_spec = PartitionSpec(axis)
  fn = shard_map(
      functools.partial(_pipeline_local, stage_fn=stage_fn,
                        axis_name=axis),
      mesh=mesh,
      in_specs=(params_spec, PartitionSpec()),
      out_specs=PartitionSpec(),
  )
  out = fn(stacked_params, microbatched)
  return jax.tree_util.tree_map(
      lambda x: x.reshape((b,) + x.shape[2:]), out)

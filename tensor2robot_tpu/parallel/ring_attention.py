"""Ring attention: exact attention over sequence-sharded inputs.

Long-context support beyond the reference (which capped sequences at
short robot episodes — SURVEY.md §5.7): the sequence axis is sharded
over a mesh axis, each device keeps its Q shard resident and K/V shards
rotate around the ring via `jax.lax.ppermute` (one ICI hop per step),
while softmax is accumulated blockwise with the running-max trick — so
attention memory is O(T_local²-ish per block) instead of O(T²) and the
sequence length scales with the ring size.

The public entry runs under `shard_map` over the caller's mesh; K/V
rotation overlaps with the current block's compute under XLA's async
collective scheduling.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

try:  # promoted to jax.shard_map in newer releases
  from jax import shard_map
except ImportError:
  from jax.experimental.shard_map import shard_map


def _ring_attention_local(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    causal: bool,
    scale: float,
    batch_axis: Optional[str] = None,
):
  """Per-device body: q, k, v are local shards (B, T_local, H, D)."""
  num_devices = jax.lax.psum(1, axis_name)
  my_index = jax.lax.axis_index(axis_name)
  b, t_local, h, d = q.shape

  q_f32 = q.astype(jnp.float32)
  q_positions = my_index * t_local + jnp.arange(t_local)

  def block(scores_max, denom, acc, k_blk, v_blk, source_index):
    """One flash-attention accumulation step against a K/V block."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q_f32,
                        k_blk.astype(jnp.float32)) * scale
    if causal:
      k_positions = source_index * t_local + jnp.arange(t_local)
      mask = q_positions[:, None] >= k_positions[None, :]
      scores = jnp.where(mask[None, None], scores, -jnp.inf)
    block_max = jnp.max(scores, axis=-1)
    new_max = jnp.maximum(scores_max, block_max)
    # Renormalize both the old accumulator and the new block. Guard
    # against all--inf rows (fully-masked): safe_new_max is finite, so
    # exp(scores_max - safe_new_max) is 0 (not nan) when scores_max is
    # still -inf.
    safe_new_max = jnp.where(jnp.isneginf(new_max), 0.0, new_max)
    correction = jnp.exp(scores_max - safe_new_max)
    weights = jnp.exp(scores - safe_new_max[..., None])
    new_denom = denom * correction + jnp.sum(weights, axis=-1)
    block_acc = jnp.einsum("bhqk,bkhd->bqhd", weights,
                           v_blk.astype(jnp.float32))
    new_acc = acc * correction.transpose(0, 2, 1)[..., None] + block_acc
    return new_max, new_denom, new_acc

  perm = [(i, (i + 1) % num_devices) for i in range(num_devices)]

  def body(step, carry):
    k_blk, v_blk, scores_max, denom, acc = carry
    # After `step` rotations this device holds the block that started
    # at ring position (my_index - step) mod n.
    source_index = (my_index - step) % num_devices
    scores_max, denom, acc = block(
        scores_max, denom, acc, k_blk, v_blk, source_index)
    k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
    v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    return k_blk, v_blk, scores_max, denom, acc

  # Mark the accumulators device-varying up front (they depend on
  # axis_index — and on the batch shard when batch-sharded — from the
  # first iteration) for shard_map's VMA type check.
  vary_axes = (axis_name,) + ((batch_axis,) if batch_axis else ())
  _pcast = getattr(jax.lax, "pcast",
                   lambda x, axes, to: x)  # pre-VMA jax: no-op
  varying = lambda x: _pcast(x, vary_axes, to="varying")
  init = (
      k, v,
      varying(jnp.full((b, h, t_local), -jnp.inf, jnp.float32)),
      varying(jnp.zeros((b, h, t_local), jnp.float32)),
      varying(jnp.zeros((b, t_local, h, d), jnp.float32)),
  )
  # n-1 rotated steps; the final block is accumulated outside the loop
  # so no dead K/V ring hop is issued on the last iteration.
  k_last, v_last, scores_max, denom, acc = jax.lax.fori_loop(
      0, num_devices - 1, body, init)
  _, denom, acc = block(
      scores_max, denom, acc, k_last, v_last,
      (my_index - (num_devices - 1)) % num_devices)
  denom = jnp.where(denom == 0.0, 1.0, denom)  # fully-masked rows → 0
  out = acc / denom.transpose(0, 2, 1)[..., None]
  return out.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis: str = "seq",
    causal: bool = False,
    scale: Optional[float] = None,
    batch_axis: Optional[str] = None,
) -> jnp.ndarray:
  """Exact multi-head attention with the sequence sharded over `axis`.

  Args:
    q, k, v: (B, T, H, D) arrays; T must divide evenly over the mesh
      axis. Inputs may be replicated or already sequence-sharded — the
      shard_map in_specs lay them out over `axis`.
    mesh: the device mesh (e.g. create_mesh({"data": 1, "seq": 8})).
    axis: mesh axis name carrying the sequence dimension.
    causal: apply a causal mask over GLOBAL positions.
    scale: attention scale; default 1/sqrt(D).
    batch_axis: mesh axis carrying the batch dim — set this on dp×sp
      meshes so each data-row only computes its batch shard (omitting it
      there would all-gather the batch and redo it per row).

  Returns:
    (B, T, H, D) attention output, sharded like the inputs.
  """
  if scale is None:
    scale = 1.0 / math.sqrt(q.shape[-1])
  spec = PartitionSpec(batch_axis, axis, None, None)
  fn = shard_map(
      functools.partial(_ring_attention_local, axis_name=axis,
                        causal=causal, scale=scale,
                        batch_axis=batch_axis),
      mesh=mesh,
      in_specs=(spec, spec, spec),
      out_specs=spec,
  )
  return fn(q, k, v)


def dense_attention_reference(q, k, v, causal=False, scale=None):
  """Unsharded O(T²) reference used by tests and small models."""
  if scale is None:
    scale = 1.0 / math.sqrt(q.shape[-1])
  scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                      k.astype(jnp.float32)) * scale
  if causal:
    t = q.shape[1]
    mask = jnp.tril(jnp.ones((t, t), bool))
    scores = jnp.where(mask[None, None], scores, -jnp.inf)
  weights = jax.nn.softmax(scores, axis=-1)
  out = jnp.einsum("bhqk,bkhd->bqhd", weights, v.astype(jnp.float32))
  return out.astype(q.dtype)

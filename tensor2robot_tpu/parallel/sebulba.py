"""Sebulba tier (ISSUE 20): decoupled actor PROCESSES feeding the
sharded learner with overlapped device ingest.

The Podracer paper (PAPERS.md, arXiv:2104.06272) names two TPU-native
architectures. Anakin — acting fused INTO the learn executable — landed
in PR 5/6 and scaled to the multi-controller mesh in PR 19, but it only
serves envs that compile. This module is the complement: Sebulba's
decoupled split, where N actor PROCESSES (each owning its own JAX
runtime and ONE acting executable pinned to its device slice) stream
fixed-shape transition chunks to a separate learner process whose
device ring and megastep stay the PR 3/16 sharded executables. Any env
that can step under numpy — pose_env, vrgripper, a real robot bridge —
can live in an actor process without ever entering XLA.

The wire is a filesystem chunk spool, deliberately dumb and inspectable:

  workdir/spool/actor<i>/chunk-<seq>.npz   fixed-shape transition chunks
                                           (atomic tmp -> rename, dense
                                           seq numbers — a gap means
                                           "not landed yet", never loss)
  workdir/spool/actor<i>/heartbeat.json    liveness ticks (advances on
                                           every chunk AND while the
                                           actor is backpressure-stalled,
                                           so "slow" never reads as
                                           "dead")
  workdir/spool/acks.json                  learner's consumed seq per
                                           actor — the bounded-backlog
                                           backpressure signal actors
                                           poll (the TransitionQueue
                                           drop-oldest policy's
                                           cross-process face)
  workdir/params/params-<v>.npz            learner-published variables;
                                           actors hot-reload through the
                                           `_HotReloadPredictor` contract
                                           (never recompiles acting)

Learner-side dataflow (all inside the learner process):

  SpoolReader.poll -> TransitionQueue.put_batch      (ingest thread)
  queue.drain_batch -> prefetch_to_device            (learner thread —
      the data/prefetch double-buffer: `depth` async device_put
      transfers in flight, so H2D DMA of chunk k+1..k+depth overlaps
      the megastep crunching chunk k's batch)
  -> DeviceReplayBuffer.extend_device_chunk          (ONE fixed-shape
      extend executable; chunks are already device-resident)
  -> MegastepLearner.step every `chunks_per_megastep` chunks.

Determinism contract (the SEBULBA_r20 bit-identity bar): the learner
consumes chunks in QUEUE order and runs one megastep per fixed chunk
count, so its param evolution is a pure function of the arrival
manifest — the recorded `(actor, seq)` ingestion order. Replaying the
manifest against the spooled chunk files in ONE serial process (the
oracle, `_run_oracle`) reproduces the live learner's params bit for
bit; all the asynchrony lives in PRODUCTION, never in consumption.

Actor death is a handled regime, not an error path: the learner-side
watchdog (PR 9) holds one heartbeat per actor (armed on the actor's
first signal, beaten on every chunk/tick), and `ActorSupervisor` maps
stalls onto the PR 11 CircuitBreaker state machine — stall ->
record_failure -> open (QUARANTINE, the dead process is reaped) ->
quarantine window elapses -> allows() claims the half-open PROBE (the
actor is respawned continuing its seq numbering) -> first fresh chunk
-> record_success -> closed (REINSTATE). The learner keeps training on
the surviving stream throughout: every shape is fixed, so the megastep
ledger stays exactly-once across the whole outage.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

SCHEMA = "t2r-sebulba-1"
_WORKER_FLAG = "--worker"

# The loop's canonical transition keys (replay.ingest.TRANSITION_KEYS,
# restated locally so synthetic actor processes never import the jax
# chain before their backend env is settled).
CHUNK_KEYS = ("image", "action", "reward", "done", "next_image")

STOP_FILE = "STOP"
ACKS_FILE = "acks.json"
DONE_FILE = "DONE.json"
HEARTBEAT_FILE = "heartbeat.json"


def _repo_root() -> str:
  return os.path.dirname(os.path.dirname(
      os.path.dirname(os.path.abspath(__file__))))


def _atomic_write_json(path: str, payload: dict) -> None:
  tmp = path + ".tmp"
  with open(tmp, "w") as f:
    json.dump(payload, f)
  os.replace(tmp, path)


def _read_json(path: str) -> Optional[dict]:
  """Best-effort read: a missing file returns None (json files here are
  written atomically, so partial reads cannot happen)."""
  try:
    with open(path) as f:
      return json.load(f)
  except (FileNotFoundError, json.JSONDecodeError):
    return None


def actor_dir(spool_dir: str, actor_id: int) -> str:
  return os.path.join(spool_dir, f"actor{actor_id}")


def chunk_path(spool_dir: str, actor_id: int, seq: int) -> str:
  return os.path.join(actor_dir(spool_dir, actor_id),
                      f"chunk-{seq:08d}.npz")


# --- transport: actor-side producer ----------------------------------------


class ChunkWriter:
  """Actor-side spool producer: one fixed-shape chunk file per call.

  Duck-types ``TransitionQueue.put_batch`` so a stock ``VectorActor``
  drives the cross-process wire unchanged — its one put per lockstep
  control step becomes one atomically-landed npz file with a dense
  sequence number. Ownership semantics are STRICTER than the in-memory
  queue's zero-copy hand-through (the arrays are serialized on the
  spot), so the queue's "fresh arrays per put" producer rule is
  automatically satisfied.
  """

  def __init__(self, spool_dir: str, actor_id: int, start_seq: int = 0):
    self.spool_dir = spool_dir
    self.actor_id = actor_id
    self.seq = int(start_seq)
    self._tick = 0
    self.dir = actor_dir(spool_dir, actor_id)
    os.makedirs(self.dir, exist_ok=True)

  def put_batch(self, batch, provenance: str = "actor") -> int:
    del provenance  # the reader derives provenance from the directory
    chunk = {key: np.asarray(value) for key, value in batch.items()}
    sizes = {value.shape[0] for value in chunk.values()}
    if len(sizes) != 1:
      raise ValueError(f"inconsistent chunk leading dims: {sizes}")
    n = sizes.pop()
    path = chunk_path(self.spool_dir, self.actor_id, self.seq)
    tmp = os.path.join(self.dir, f".tmp-{self.seq:08d}.npz")
    with open(tmp, "wb") as f:
      np.savez(f, **chunk)
    os.replace(tmp, path)
    self.seq += 1
    self.write_heartbeat()
    return n

  def write_heartbeat(self) -> None:
    """Liveness tick: advances on every chunk AND during backpressure
    stalls, so the learner's watchdog can tell slow from dead."""
    self._tick += 1
    _atomic_write_json(os.path.join(self.dir, HEARTBEAT_FILE), {
        "seq": self.seq,
        "tick": self._tick,
        "wall_time": time.time(),
        "pid": os.getpid(),
    })

  def finish(self) -> None:
    _atomic_write_json(os.path.join(self.dir, DONE_FILE),
                       {"final_seq": self.seq})


# --- transport: learner-side tail ------------------------------------------


class SpoolReader:
  """Learner-side tail over the per-actor chunk streams.

  ``poll()`` returns every newly-landed chunk in dense per-actor seq
  order (a gap means "still being written", so the reader waits — the
  atomic rename guarantees a visible file is whole). ``write_acks``
  publishes the consumed frontier, which is the actors' backpressure
  signal.
  """

  def __init__(self, spool_dir: str, num_actors: int):
    self.spool_dir = spool_dir
    self.num_actors = num_actors
    self.next_seq: Dict[int, int] = {i: 0 for i in range(num_actors)}
    for i in range(num_actors):
      os.makedirs(actor_dir(spool_dir, i), exist_ok=True)

  def poll(self, max_per_actor: int = 32
           ) -> List[Tuple[int, int, Dict[str, np.ndarray]]]:
    out: List[Tuple[int, int, Dict[str, np.ndarray]]] = []
    for actor in range(self.num_actors):
      for _ in range(max_per_actor):
        seq = self.next_seq[actor]
        path = chunk_path(self.spool_dir, actor, seq)
        if not os.path.exists(path):
          break
        with np.load(path) as archive:
          chunk = {key: archive[key] for key in archive.files}
        out.append((actor, seq, chunk))
        self.next_seq[actor] = seq + 1
    return out

  def heartbeat(self, actor: int) -> Optional[dict]:
    return _read_json(os.path.join(actor_dir(self.spool_dir, actor),
                                   HEARTBEAT_FILE))

  def last_landed_seq(self, actor: int) -> int:
    """Highest chunk seq on disk + 1 (where a respawned actor must
    continue so the probe never overwrites landed experience)."""
    directory = actor_dir(self.spool_dir, actor)
    seqs = [int(name[len("chunk-"):-len(".npz")])
            for name in os.listdir(directory)
            if name.startswith("chunk-") and name.endswith(".npz")]
    return (max(seqs) + 1) if seqs else 0

  def write_acks(self) -> None:
    _atomic_write_json(
        os.path.join(self.spool_dir, ACKS_FILE),
        {str(actor): seq for actor, seq in self.next_seq.items()})


def load_chunk(spool_dir: str, actor_id: int, seq: int
               ) -> Dict[str, np.ndarray]:
  with np.load(chunk_path(spool_dir, actor_id, seq)) as archive:
    return {key: archive[key] for key in archive.files}


# --- params export/hot-reload (learner -> actors) --------------------------


def _params_path(params_dir: str, version: int) -> str:
  return os.path.join(params_dir, f"params-{version:06d}.npz")


def publish_params(params_dir: str, version: int, variables) -> str:
  """Atomically lands one versioned variables snapshot (tmp -> rename,
  the export_utils.publish discipline at npz granularity)."""
  from tensor2robot_tpu.export import variables_io
  os.makedirs(params_dir, exist_ok=True)
  path = _params_path(params_dir, version)
  tmp = os.path.join(params_dir, f".tmp-{version:06d}.npz")
  variables_io.save_variables(tmp, variables)
  os.replace(tmp, path)
  return path


def latest_params_version(params_dir: str) -> Optional[int]:
  try:
    names = os.listdir(params_dir)
  except FileNotFoundError:
    return None
  versions = [int(name[len("params-"):-len(".npz")]) for name in names
              if name.startswith("params-") and name.endswith(".npz")]
  return max(versions) if versions else None


def _wait_for_params(params_dir: str, timeout_s: float = 180.0):
  from tensor2robot_tpu.export import variables_io
  deadline = time.monotonic() + timeout_s
  while time.monotonic() < deadline:
    version = latest_params_version(params_dir)
    if version is not None:
      return version, variables_io.load_variables(
          _params_path(params_dir, version))
    time.sleep(0.05)
  raise TimeoutError(
      f"no params landed under {params_dir} within {timeout_s}s")


# --- the actor process worker ----------------------------------------------


def _synthetic_actor(spec: Dict, writer: ChunkWriter):
  """Chunk producer with NO jax dependency: random fixed-shape
  transitions at a configurable cadence. The supervisor/watchdog/crash
  tests use this mode so the quarantine protocol is provable in
  seconds (process startup is a numpy import, not a JAX runtime)."""
  rng = np.random.default_rng(spec["seed"] + 11 * spec["actor_id"])
  n = spec["num_envs"]
  size = spec["image_size"]
  action_size = spec["action_size"]
  sleep_s = spec.get("step_sleep_s", 0.01)
  busy = {"s": 0.0}

  def step() -> None:
    begin = time.perf_counter()
    image = rng.integers(0, 256, (n, size, size, 3), dtype=np.uint8)
    writer.put_batch({
        "image": image,
        "action": rng.uniform(-1.0, 1.0,
                              (n, action_size)).astype(np.float32),
        "reward": (rng.random(n) < 0.3).astype(np.float32),
        "done": (rng.random(n) < 0.2).astype(np.float32),
        "next_image": image,
    })
    # The sleep counts as busy on purpose: it stands in for env/policy
    # latency, which is exactly what the overlap instrument measures.
    if sleep_s:
      time.sleep(sleep_s)
    busy["s"] += time.perf_counter() - begin

  return step, lambda: {"mode": "synthetic",
                        "busy_seconds": round(busy["s"], 3)}


def _cem_actor(spec: Dict, writer: ChunkWriter):
  """The real acting half: ONE CEM bucket executable pinned to this
  process's device, a stock VectorActor driven thread-free (the
  PROCESS is the actor loop), params hot-reloaded from the learner's
  export dir through the never-recompile predictor contract."""
  import optax

  from tensor2robot_tpu.export import variables_io
  from tensor2robot_tpu.replay.actor import VectorActor
  from tensor2robot_tpu.replay.loop import _HotReloadPredictor
  from tensor2robot_tpu.replay.smoke import TinyQCriticModel
  from tensor2robot_tpu.serving.bucketing import BucketLadder
  from tensor2robot_tpu.serving.policy import CEMFleetPolicy

  model = TinyQCriticModel(
      image_size=spec["image_size"], action_size=spec["action_size"],
      optimizer_fn=lambda: optax.adam(1e-3))
  version, variables = _wait_for_params(
      spec["params_dir"], timeout_s=spec.get("params_timeout_s", 180.0))
  predictor = _HotReloadPredictor(model, variables)
  policy = CEMFleetPolicy(
      predictor, action_size=spec["action_size"],
      num_samples=spec["cem_num_samples"],
      num_elites=spec["cem_num_elites"],
      iterations=spec["cem_iterations"], seed=spec["seed"] + 7,
      ladder=BucketLadder((spec["num_envs"],)))
  actor = VectorActor(
      policy, writer, spec["image_size"], num_envs=spec["num_envs"],
      max_attempts=spec.get("max_attempts", 3), seed=spec["seed"],
      grasp_radius=spec.get("grasp_radius", 0.4))
  # Thread-free drive: replicate start()'s reset, then call step_once
  # directly from the process main loop (the VectorActor thread stays
  # unstarted; step_once owns the busy accounting since ISSUE 20).
  actor._env.reset([actor._scene_seed()
                    for _ in range(actor.num_envs)])
  state = {"version": version, "reloads": 0, "steps": 0}
  reload_every = spec.get("reload_every", 4)

  def step() -> None:
    actor.step_once()
    state["steps"] += 1
    if reload_every and state["steps"] % reload_every == 0:
      latest = latest_params_version(spec["params_dir"])
      if latest is not None and latest > state["version"]:
        predictor.update(variables_io.load_variables(
            _params_path(spec["params_dir"], latest)))
        state["version"] = latest
        state["reloads"] += 1

  def summary() -> Dict:
    return {
        "mode": "cem",
        "env_steps": actor.env_steps,
        "episodes": actor.episodes,
        "successes": actor.successes,
        "busy_seconds": round(actor.busy_seconds, 3),
        "params_version": state["version"],
        "param_reloads": state["reloads"],
        "compile_counts": {f"cem_bucket_{k}": v for k, v in
                           sorted(policy.compile_counts.items())},
    }

  return step, summary


def _run_actor(spec: Dict) -> None:
  """Actor process main: produce chunks under bounded backpressure
  until STOP (or the chunk cap, or the armed crash protocol fires)."""
  actor_id = spec["actor_id"]
  writer = ChunkWriter(spec["spool_dir"], actor_id,
                       start_seq=spec.get("start_seq", 0))
  stop_path = os.path.join(spec["spool_dir"], STOP_FILE)
  acks_path = os.path.join(spec["spool_dir"], ACKS_FILE)
  max_backlog = spec.get("max_backlog", 8)
  die_after = spec.get("die_after_chunks")
  max_chunks = spec.get("max_chunks", 10 ** 6)
  if spec.get("synthetic"):
    step_fn, summary_fn = _synthetic_actor(spec, writer)
  else:
    step_fn, summary_fn = _cem_actor(spec, writer)
  written = 0
  stall_s = 0.0
  while written < max_chunks and not os.path.exists(stop_path):
    # Bounded backpressure: never run more than max_backlog chunks
    # ahead of the learner's ack frontier. Heartbeats keep ticking
    # through the stall — slow consumption must not read as death.
    while not os.path.exists(stop_path):
      acks = _read_json(acks_path) or {}
      if writer.seq - int(acks.get(str(actor_id), 0)) < max_backlog:
        break
      writer.write_heartbeat()
      time.sleep(0.02)
      stall_s += 0.02
    if os.path.exists(stop_path):
      break
    step_fn()
    written += 1
    if die_after is not None and written >= die_after:
      # Crash protocol (the kill-one-actor phase): die silently with a
      # distinctive rc — no DONE marker, no result line, exactly what
      # a preempted/OOM-killed actor looks like to the learner.
      print(f"ACTOR{actor_id}_KILLED seq={writer.seq}", flush=True)
      os._exit(3)
  writer.finish()
  summary = {
      "actor_id": actor_id,
      "pid": os.getpid(),
      "chunks": written,
      "start_seq": spec.get("start_seq", 0),
      "final_seq": writer.seq,
      "backpressure_stall_s": round(stall_s, 3),
      **summary_fn(),
  }
  obs_logdir = spec.get("obs_logdir")
  if obs_logdir:
    # The PR 19 fleet-observability transport: each actor process
    # exports its registry snapshot under its own host label, and the
    # learner-side aggregate merges them into ONE fleet view (same
    # read side the multi-controller mesh uses).
    from tensor2robot_tpu.obs.registry import get_registry
    registry = get_registry()
    registry.gauge("sebulba_actor/chunks").set(written)
    registry.gauge("sebulba_actor/busy_s").set(
        summary.get("busy_seconds", 0.0))
    registry.gauge("sebulba_actor/backpressure_stall_s").set(
        round(stall_s, 3))
    registry.export_snapshot(
        os.path.join(obs_logdir,
                     f"registry-actor{actor_id}-{os.getpid()}.json"),
        host=f"actor{actor_id}")
  print(f"ACTOR{actor_id}_RESULT " + json.dumps(summary), flush=True)
  print(f"ACTOR{actor_id}_OK", flush=True)


# --- supervisor: quarantine -> probe -> reinstate over processes -----------


class ActorSupervisor:
  """Actor-process lifecycle + the PR 11 breaker regime for actors.

  One learner-side Heartbeat per actor (armed busy on the actor's
  FIRST observed signal so a slow JAX bring-up is idle, not stalled;
  beaten on every chunk arrival and heartbeat tick) and one
  CircuitBreaker per actor (failure_threshold=1 — a watchdog stall IS
  the failure evidence). ``check()`` drives watchdog detection and the
  breaker transitions; the owner calls ``observe()`` from its ingest
  loop with each poll's arrivals.
  """

  def __init__(self, spool_dir: str, specs: List[Dict],
               env: Optional[Dict[str, str]] = None,
               watchdog=None, recorder=None, registry=None,
               deadline_s: float = 1.0, quarantine_s: float = 0.75,
               max_respawns: int = 2):
    from tensor2robot_tpu.obs import flight_recorder as flight_lib
    from tensor2robot_tpu.obs import registry as registry_lib
    from tensor2robot_tpu.obs import watchdog as watchdog_lib
    from tensor2robot_tpu.serving.slo import CircuitBreaker
    self.spool_dir = spool_dir
    self._specs = {spec["actor_id"]: dict(spec) for spec in specs}
    self._env = env
    self._recorder = recorder or flight_lib.get_recorder()
    self._registry = registry or registry_lib.get_registry()
    self._watchdog = watchdog or watchdog_lib.Watchdog(
        poll_s=0.2, recorder=self._recorder, registry=self._registry)
    self._deadline_s = watchdog_lib.scaled_deadline(deadline_s)
    self._quarantine_s = quarantine_s
    self._max_respawns = max_respawns
    self._breakers = {actor_id: CircuitBreaker(
        failure_threshold=1, quarantine_s=quarantine_s)
        for actor_id in self._specs}
    self._heartbeats: Dict[int, object] = {}
    self._armed: Dict[int, bool] = {}
    self._last_tick: Dict[int, int] = {}
    self._procs: Dict[int, subprocess.Popen] = {}
    self._outputs: Dict[int, List[str]] = {
        actor_id: [] for actor_id in self._specs}
    self.respawns: Dict[int, int] = {
        actor_id: 0 for actor_id in self._specs}
    self.timeline: List[dict] = []
    self.watchdog_events: List[dict] = []
    self._epoch = time.monotonic()

  # -- lifecycle -----------------------------------------------------------

  def _event(self, event: str, actor_id: int, **fields) -> None:
    entry = {"event": event, "actor": actor_id,
             "t_s": round(time.monotonic() - self._epoch, 3), **fields}
    self.timeline.append(entry)
    self._recorder.record("sebulba", event, actor=actor_id, **fields)

  def _spawn(self, actor_id: int, start_seq: int) -> None:
    spec = dict(self._specs[actor_id], start_seq=start_seq)
    self._procs[actor_id] = subprocess.Popen(
        [sys.executable, "-m", "tensor2robot_tpu.parallel.sebulba",
         _WORKER_FLAG, json.dumps(spec)],
        env=self._env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)

  def start(self) -> None:
    for actor_id in sorted(self._specs):
      heartbeat = self._watchdog.register(
          f"sebulba/actor{actor_id}", deadline_s=self._deadline_s)
      self._heartbeats[actor_id] = heartbeat
      self._armed[actor_id] = False
      self._last_tick[actor_id] = -1
      self._spawn(actor_id, start_seq=0)
      self._event("spawn", actor_id, pid=self._procs[actor_id].pid)

  def _reap(self, actor_id: int) -> Optional[int]:
    """Collects a finished process's output; returns its rc (None if
    still running — a stalled-but-alive actor is killed first: a
    quarantined actor must not keep producing)."""
    proc = self._procs.get(actor_id)
    if proc is None:
      return None
    if proc.poll() is None:
      proc.kill()
    out, _ = proc.communicate()
    if out:
      self._outputs[actor_id].append(out)
    del self._procs[actor_id]
    return proc.returncode

  # -- detection + state machine -------------------------------------------

  def observe(self, arrivals, reader: SpoolReader) -> None:
    """Feeds liveness evidence from one ingest poll: chunk arrivals
    and heartbeat-file ticks each beat the actor's heartbeat; a chunk
    from a non-closed breaker is the probe verdict (reinstate)."""
    fresh = {actor for actor, _, _ in arrivals}
    for actor_id, heartbeat in self._heartbeats.items():
      signal = actor_id in fresh
      record = reader.heartbeat(actor_id)
      if record is not None:
        tick = int(record.get("tick", 0))
        if tick != self._last_tick[actor_id]:
          self._last_tick[actor_id] = tick
          signal = True
      if not signal:
        continue
      if not self._armed[actor_id]:
        heartbeat.busy()
        self._armed[actor_id] = True
      heartbeat.beat()
      breaker = self._breakers[actor_id]
      if actor_id in fresh and breaker.state != "closed":
        # Fresh experience from the probed actor: conclusive health
        # evidence — the breaker closes and the actor is reinstated.
        breaker.record_success()
        if breaker.state == "closed":
          self._event("reinstate", actor_id,
                      respawns=self.respawns[actor_id])

  def check(self, reader: SpoolReader) -> List[dict]:
    """One supervision pass: watchdog stalls -> quarantine; elapsed
    quarantine windows -> claim the half-open probe and respawn."""
    new_events = self._watchdog.check_once()
    self.watchdog_events.extend(new_events)
    for event in new_events:
      name = event["component"]
      if (event["event"] != "watchdog_stall"
          or not name.startswith("sebulba/actor")):
        continue
      actor_id = int(name[len("sebulba/actor"):].split("#")[0])
      breaker = self._breakers[actor_id]
      breaker.record_failure()
      if breaker.state == "open":
        rc = self._reap(actor_id)
        self._event("quarantine", actor_id, rc=rc,
                    stalled_for_s=event["stalled_for_s"])
        self._recorder.trigger("sebulba_actor_quarantined",
                               actor=actor_id, rc=rc)
    for actor_id, breaker in self._breakers.items():
      if breaker.state != "open":
        continue
      if self.respawns[actor_id] >= self._max_respawns:
        continue
      if breaker.allows():  # claims the single half-open probe slot
        # The injected crash (die_after_chunks) is one-shot: the probe
        # incarnation must be healthy or reinstatement is unprovable.
        self._specs[actor_id].pop("die_after_chunks", None)
        start_seq = reader.last_landed_seq(actor_id)
        # Fresh heartbeat for the probe incarnation: the stalled entry
        # must not carry its stale clock into the new process.
        self._watchdog.unregister(self._heartbeats[actor_id])
        self._heartbeats[actor_id] = self._watchdog.register(
            f"sebulba/actor{actor_id}", deadline_s=self._deadline_s)
        self._armed[actor_id] = False
        # The dead incarnation's heartbeat file survives on disk; seed
        # the tick cursor with it so only the PROBE's own signal (a new
        # tick or a fresh chunk) arms stall detection — the probe gets
        # the same unbounded bring-up window as the initial spawn
        # instead of inheriting a deadline armed off stale evidence.
        stale = reader.heartbeat(actor_id)
        self._last_tick[actor_id] = (
            int(stale.get("tick", 0)) if stale else -1)
        self.respawns[actor_id] += 1
        self._spawn(actor_id, start_seq=start_seq)
        self._event("probe", actor_id, start_seq=start_seq,
                    pid=self._procs[actor_id].pid)
    return new_events

  # -- shutdown + results --------------------------------------------------

  def stop(self, timeout_s: float = 60.0) -> None:
    _atomic_write_json(os.path.join(self.spool_dir, STOP_FILE),
                       {"stopped_at": time.time()})
    deadline = time.monotonic() + timeout_s
    for actor_id, proc in list(self._procs.items()):
      remaining = max(0.1, deadline - time.monotonic())
      try:
        out, _ = proc.communicate(timeout=remaining)
      except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
      if out:
        self._outputs[actor_id].append(out)
      del self._procs[actor_id]
    for heartbeat in self._heartbeats.values():
      self._watchdog.unregister(heartbeat)

  def breaker_events(self) -> Dict[int, List[dict]]:
    return {actor_id: list(breaker.events)
            for actor_id, breaker in self._breakers.items()}

  def results(self) -> Dict[int, Optional[dict]]:
    """Each actor's LAST incarnation's parsed result line (None when
    that incarnation died resultless — the killed-actor case)."""
    parsed: Dict[int, Optional[dict]] = {}
    for actor_id, outputs in self._outputs.items():
      marker = f"ACTOR{actor_id}_RESULT "
      result = None
      for out in outputs:
        for line in out.splitlines():
          if line.startswith(marker):
            result = json.loads(line[len(marker):])
      parsed[actor_id] = result
    return parsed

  def raw_output(self, actor_id: int) -> str:
    return "\n".join(self._outputs.get(actor_id, []))


# --- the learner half ------------------------------------------------------


@dataclass
class SebulbaConfig:
  """One config drives the live run AND the serial oracle replay (the
  bit-identity bar depends on both halves building identical learner
  stacks — same seeds, same shapes, same megastep cadence)."""
  image_size: int = 8
  action_size: int = 4
  seed: int = 0
  num_actors: int = 2
  envs_per_actor: int = 16  # chunk rows == the device ring's ingest quantum
  capacity: int = 512
  batch_size: int = 32
  inner_steps: int = 4  # K optimizer steps per megastep dispatch
  chunks_per_megastep: int = 4
  num_megasteps: int = 6
  mesh_devices: int = 2  # the sharded learner's capacity/data axis
  gamma: float = 0.8
  learning_rate: float = 3e-3
  cem_num_samples: int = 16
  cem_num_elites: int = 4
  cem_iterations: int = 2
  queue_capacity: int = 1024
  prefetch_depth: int = 2
  publish_every: int = 2  # megasteps between param exports to actors
  target_refresh_every: int = 2
  actor_deadline_s: float = 1.0
  quarantine_s: float = 0.75
  max_backlog: int = 8
  actor_max_chunks: int = 4096
  synthetic_actors: bool = False
  actor_step_sleep_s: float = 0.0

  def to_json(self) -> Dict:
    return dataclasses.asdict(self)

  @classmethod
  def from_json(cls, payload: Dict) -> "SebulbaConfig":
    return cls(**payload)


class SebulbaLearner:
  """The learner process's device half: sharded ring + megastep,
  fed device-resident chunks through the prefetch seam."""

  def __init__(self, config: SebulbaConfig, workdir: str,
               registry=None, recorder=None):
    import jax
    import optax

    from tensor2robot_tpu.export import export_utils
    from tensor2robot_tpu.obs import flight_recorder as flight_lib
    from tensor2robot_tpu.obs import ledger as obs_ledger
    from tensor2robot_tpu.obs import registry as registry_lib
    from tensor2robot_tpu.parallel import mesh as mesh_lib
    from tensor2robot_tpu.replay.device_buffer import (DeviceReplayBuffer,
                                                       MegastepLearner)
    from tensor2robot_tpu.replay.ingest import TransitionQueue
    from tensor2robot_tpu.replay.loop import transition_spec
    from tensor2robot_tpu.replay.smoke import TinyQCriticModel
    from tensor2robot_tpu.train.trainer import Trainer

    self.config = config
    self.workdir = workdir
    os.makedirs(workdir, exist_ok=True)
    devices = jax.devices()
    if len(devices) < config.mesh_devices:
      raise RuntimeError(
          f"sharded Sebulba learner needs {config.mesh_devices} "
          f"devices, found {len(devices)} — run under cpu_mesh_env "
          "(the bench CLI re-execs itself)")
    self.registry = registry or registry_lib.MetricRegistry()
    self.recorder = recorder or flight_lib.FlightRecorder(
        dump_dir=os.path.join(workdir, "flightrec"))
    self.ledger = obs_ledger.ExecutableLedger()
    self.mesh = mesh_lib.create_mesh(
        {"data": config.mesh_devices},
        devices=devices[:config.mesh_devices])
    self.replicated = mesh_lib.replicated_sharding(self.mesh)
    self.model = TinyQCriticModel(
        image_size=config.image_size, action_size=config.action_size,
        optimizer_fn=lambda: optax.adam(config.learning_rate))
    self.trainer = Trainer(self.model, mesh=self.mesh,
                           seed=config.seed)
    self.state = self.trainer.create_train_state(
        batch_size=config.batch_size)
    self.buffer = DeviceReplayBuffer(
        transition_spec(config.image_size, config.action_size),
        config.capacity, config.batch_size, seed=config.seed,
        prioritized=True, ingest_chunk=config.envs_per_actor,
        mesh=self.mesh, ledger=self.ledger)
    self.learner = MegastepLearner(
        self.model, self.trainer, self.buffer,
        action_size=config.action_size, gamma=config.gamma,
        num_samples=config.cem_num_samples,
        num_elites=config.cem_num_elites,
        iterations=config.cem_iterations,
        inner_steps=config.inner_steps, seed=config.seed + 13,
        ledger=self.ledger)
    self._export = export_utils.fetch_variables_to_host
    self.learner.refresh(self.host_variables(), step=0)
    self.queue = TransitionQueue(
        config.queue_capacity, registry=self.registry,
        flight_recorder=self.recorder)
    self.params_dir = os.path.join(workdir, "params")
    self.params_version = 0
    publish_params(self.params_dir, 0, self.host_variables())

  def host_variables(self):
    return self._export(self.state.variables(use_ema=True))

  def compile_counts(self) -> Dict[str, int]:
    return {**self.buffer.compile_counts,
            **self.learner.compile_counts}

  def drive(self, host_chunks: Iterator[Dict[str, np.ndarray]],
            publish: bool = True) -> Dict:
    """Consumes the chunk stream through the prefetch seam and runs
    the megastep cadence. THE shared consumption body: the live run
    and the serial oracle replay both land here, which is what makes
    the bit-identity bar a statement about transport/overlap and not
    about two subtly different learner loops.

    Per chunk: one async device_put is already in flight (the
    prefetch double-buffer), one ``extend_device_chunk`` dispatch
    lands it in the sharded ring; every ``chunks_per_megastep``-th
    chunk triggers one megastep dispatch. Param publish (actors'
    hot-reload feed) and target refresh run on their megastep
    cadences; publish is side-effect-only and the refresh schedule is
    a pure function of the megastep index, so determinism holds.
    """
    from tensor2robot_tpu.data.prefetch import (PrefetchExhausted,
                                                prefetch_to_device)
    from tensor2robot_tpu.obs import trace as trace_lib
    config = self.config
    stream: List[dict] = []
    megasteps = 0
    chunks = 0
    extend_busy_s = 0.0
    learn_busy_s = 0.0
    prefetched = prefetch_to_device(
        host_chunks, sharding=self.replicated,
        depth=config.prefetch_depth, registry=self.registry,
        name="sebulba_prefetch", exhaust_error=True)
    wall0 = time.perf_counter()
    while megasteps < config.num_megasteps:
      try:
        device_chunk = next(prefetched)
      except PrefetchExhausted:
        break  # the typed end-of-stream, not a bare StopIteration
      begin = time.perf_counter()
      with trace_lib.span("sebulba/extend",
                          rows=config.envs_per_actor):
        self.buffer.extend_device_chunk(device_chunk)
      extend_busy_s += time.perf_counter() - begin
      chunks += 1
      if chunks % config.chunks_per_megastep:
        continue
      begin = time.perf_counter()
      self.state, metrics = self.learner.step(self.state)
      learn_busy_s += time.perf_counter() - begin
      megasteps += 1
      # Full float64 precision through the JSON round-trip: equality
      # on these entries IS bit-identity (multihost_bench contract).
      stream.append({"megastep": megasteps, **metrics})
      if (config.target_refresh_every
          and megasteps % config.target_refresh_every == 0):
        self.learner.refresh(self.host_variables(), step=megasteps)
      if (publish and config.publish_every
          and megasteps % config.publish_every == 0):
        self.params_version += 1
        publish_params(self.params_dir, self.params_version,
                       self.host_variables())
    wall_s = time.perf_counter() - wall0
    self.registry.gauge("sebulba/learner_busy_fraction").set(
        learn_busy_s / wall_s if wall_s > 0 else 0.0)
    self.registry.gauge("sebulba/ingest_busy_fraction").set(
        extend_busy_s / wall_s if wall_s > 0 else 0.0)
    return {
        "megasteps": megasteps,
        "chunks_consumed": chunks,
        "optimizer_steps": megasteps * config.inner_steps,
        "stream": stream,
        "learn_busy_s": round(learn_busy_s, 4),
        "extend_busy_s": round(extend_busy_s, 4),
        "wall_s": round(wall_s, 4),
    }

  def save_final_params(self, path: str) -> str:
    from tensor2robot_tpu.export import variables_io
    tmp = path + ".tmp"
    variables_io.save_variables(tmp, self.host_variables())
    os.replace(tmp, path)
    return path


def _actor_specs(config: SebulbaConfig, spool_dir: str,
                 params_dir: str,
                 die_after: Optional[Dict[int, int]] = None,
                 obs_logdir: Optional[str] = None) -> List[Dict]:
  specs = []
  for actor_id in range(config.num_actors):
    spec = {
        "role": "actor",
        "actor_id": actor_id,
        "spool_dir": spool_dir,
        "params_dir": params_dir,
        "obs_logdir": obs_logdir,
        "seed": config.seed + actor_id,
        "image_size": config.image_size,
        "action_size": config.action_size,
        "num_envs": config.envs_per_actor,
        "cem_num_samples": config.cem_num_samples,
        "cem_num_elites": config.cem_num_elites,
        "cem_iterations": config.cem_iterations,
        "max_backlog": config.max_backlog,
        "max_chunks": config.actor_max_chunks,
        "synthetic": config.synthetic_actors,
        "step_sleep_s": config.actor_step_sleep_s,
    }
    if die_after and actor_id in die_after:
      spec["die_after_chunks"] = die_after[actor_id]
    specs.append(spec)
  return specs


def run_live(config: SebulbaConfig, workdir: str,
             die_after: Optional[Dict[int, int]] = None,
             actor_env: Optional[Dict[str, str]] = None,
             timeout_s: float = 600.0) -> Dict:
  """The live Sebulba window: THIS process is the learner; N actor
  processes stream chunks through the spool. Returns the result block
  (manifest, overlap instruments, supervisor timeline, actor results,
  compile ledger) plus the final params path for the parity check."""
  from tensor2robot_tpu.utils.cpu_mesh_env import cpu_mesh_env
  os.makedirs(workdir, exist_ok=True)
  spool_dir = os.path.join(workdir, "spool")
  os.makedirs(spool_dir, exist_ok=True)
  obs_logdir = os.path.join(workdir, "obslog")
  os.makedirs(obs_logdir, exist_ok=True)
  learner = SebulbaLearner(config, workdir)
  specs = _actor_specs(config, spool_dir, learner.params_dir,
                       die_after=die_after, obs_logdir=obs_logdir)
  if actor_env is None:
    # Each actor owns its own single-device CPU runtime — its acting
    # executable is pinned to ITS device slice, not the learner mesh.
    actor_env = cpu_mesh_env(1)
    actor_env["PYTHONPATH"] = (_repo_root() + os.pathsep
                               + actor_env.get("PYTHONPATH", ""))
  reader = SpoolReader(spool_dir, config.num_actors)
  supervisor = ActorSupervisor(
      spool_dir, specs, env=actor_env, recorder=learner.recorder,
      registry=learner.registry, deadline_s=config.actor_deadline_s,
      quarantine_s=config.quarantine_s)
  arrivals: List[dict] = []
  needed = config.num_megasteps * config.chunks_per_megastep
  stop = threading.Event()
  occupancy = learner.registry.histogram("sebulba/queue_occupancy")
  occupancy_gauge = learner.registry.gauge(
      "sebulba/queue_occupancy_last")

  def ingest() -> None:
    # The ingest thread: disk tail -> bounded queue, plus all actor
    # supervision. Nothing here touches device state — the learner
    # thread owns every dispatch, so megastep/extend never race.
    # Admission control: only tail as many chunks as the queue has
    # room for, so the queue NEVER sheds during the parity window and
    # the ack frontier (what actors' backpressure watches) means
    # "admitted to the learner", not merely "seen on disk". Drops
    # remain a real regime at saturation — proven by the ingest unit
    # tests — but a dropped row would fork the live stream from the
    # recorded manifest.
    chunk_rows = config.envs_per_actor
    while not stop.is_set():
      room = learner.queue.capacity - len(learner.queue)
      per_actor = room // max(1, chunk_rows * config.num_actors)
      events = (reader.poll(max_per_actor=min(per_actor, 8))
                if per_actor > 0 else [])
      for actor, seq, chunk in events:
        learner.queue.put_batch(chunk, provenance=f"actor{actor}")
        arrivals.append({"actor": actor, "seq": seq})
      supervisor.observe(events, reader)
      supervisor.check(reader)
      reader.write_acks()
      fill = len(learner.queue) / learner.queue.capacity
      occupancy.record(fill)
      occupancy_gauge.set(fill)
      if not events:
        time.sleep(0.01)

  starved = {"s": 0.0}

  def host_chunks() -> Iterator[Dict[str, np.ndarray]]:
    yielded = 0
    deadline = time.monotonic() + timeout_s
    while yielded < needed:
      if time.monotonic() > deadline:
        raise TimeoutError(
            f"learner starved: {yielded}/{needed} chunks after "
            f"{timeout_s}s (actors dead without reinstatement?)")
      batch = learner.queue.drain_batch(config.envs_per_actor)
      if batch is None:
        begin = time.perf_counter()
        time.sleep(0.002)
        starved["s"] += time.perf_counter() - begin
        continue
      yield batch
      yielded += 1

  supervisor.start()
  thread = threading.Thread(target=ingest, daemon=True)
  thread.start()
  try:
    drive = learner.drive(host_chunks(), publish=True)
  finally:
    stop.set()
    thread.join(10.0)
    supervisor.stop()
  learner.registry.gauge("sebulba/learner_stall_s").set(starved["s"])
  actor_results = supervisor.results()
  actor_busy_s = sum(
      (result or {}).get("busy_seconds", 0.0)
      for result in actor_results.values())
  actor_stall_s = sum(
      (result or {}).get("backpressure_stall_s", 0.0)
      for result in actor_results.values())
  wall = max(drive["wall_s"], 1e-9)
  learner.registry.export_snapshot(
      os.path.join(obs_logdir, f"registry-learner-{os.getpid()}.json"),
      host="learner")
  params_path = learner.save_final_params(
      os.path.join(workdir, "final_params.npz"))
  queue_stats = learner.queue.stats()
  occ = occupancy.snapshot()
  return {
      "config": config.to_json(),
      "learner_pid": os.getpid(),
      "mesh_shape": {"data": config.mesh_devices},
      "drive": drive,
      "manifest": arrivals[:needed],
      "arrivals_total": len(arrivals),
      "queue": queue_stats,
      "overlap": {
          "learner_wall_s": drive["wall_s"],
          "learn_busy_s": drive["learn_busy_s"],
          "extend_busy_s": drive["extend_busy_s"],
          "learner_stall_s": round(starved["s"], 4),
          "actor_busy_s": round(actor_busy_s, 4),
          "actor_backpressure_stall_s": round(actor_stall_s, 4),
          # Acting/learning overlap: actor-process busy seconds per
          # learner wall second (the ActorFleet.busy_seconds instrument
          # lifted across the process boundary), capped at 1.
          "overlap_fraction": round(
              min(1.0, actor_busy_s / wall), 4),
          "learner_busy_fraction": round(
              drive["learn_busy_s"] / wall, 4),
          "queue_occupancy": {
              "max": occ.get("max"), "p50": occ.get("p50"),
              "samples": occ.get("count"),
          },
      },
      "actors": {str(actor_id): result
                 for actor_id, result in actor_results.items()},
      "watchdog_events": supervisor.watchdog_events,
      "supervisor": {
          "timeline": supervisor.timeline,
          "respawns": dict(supervisor.respawns),
          "breaker_events": {
              str(actor_id): events for actor_id, events in
              supervisor.breaker_events().items()},
      },
      "compile_counts": learner.compile_counts(),
      "final_params_path": params_path,
      "obs_logdir": obs_logdir,
  }


# --- the serial single-process oracle --------------------------------------


def _manifest_chunks(spool_dir: str, manifest: List[dict]
                     ) -> Iterator[Dict[str, np.ndarray]]:
  for entry in manifest:
    yield load_chunk(spool_dir, entry["actor"], entry["seq"])


def _run_oracle(spec: Dict) -> None:
  """Oracle worker: ONE serial process replays the recorded stream —
  the manifest's (actor, seq) order against the spooled chunk files —
  through the identical learner stack and consumption body. No queue,
  no threads, no actor processes: if the live learner's params match
  this bitwise, the decoupling added overlap and nothing else."""
  config = SebulbaConfig.from_json(spec["config"])
  manifest = _read_json(spec["manifest_path"])["manifest"]
  learner = SebulbaLearner(config, spec["workdir"])
  drive = learner.drive(
      _manifest_chunks(spec["spool_dir"], manifest), publish=False)
  params_path = learner.save_final_params(spec["params_out"])
  summary = {
      "drive": drive,
      "compile_counts": learner.compile_counts(),
      "params_path": params_path,
  }
  print("ORACLE_RESULT " + json.dumps(summary), flush=True)
  print("ORACLE_OK", flush=True)


def run_oracle_subprocess(config: SebulbaConfig, spool_dir: str,
                          manifest: List[dict], workdir: str,
                          timeout_s: float = 900.0) -> Dict:
  """Runs the oracle replay in a FRESH interpreter (no shared jit
  cache, no shared process state with the live learner) under the same
  virtual-device env, and returns its parsed summary."""
  from tensor2robot_tpu.utils.cpu_mesh_env import cpu_mesh_env
  import jax
  os.makedirs(workdir, exist_ok=True)
  manifest_path = os.path.join(workdir, "manifest.json")
  _atomic_write_json(manifest_path, {"manifest": manifest})
  spec = {
      "role": "oracle",
      "config": config.to_json(),
      "spool_dir": spool_dir,
      "manifest_path": manifest_path,
      "workdir": os.path.join(workdir, "oracle_learner"),
      "params_out": os.path.join(workdir, "oracle_params.npz"),
  }
  env = cpu_mesh_env(max(len(jax.devices()), config.mesh_devices))
  env["PYTHONPATH"] = (_repo_root() + os.pathsep
                       + env.get("PYTHONPATH", ""))
  proc = subprocess.Popen(
      [sys.executable, "-m", "tensor2robot_tpu.parallel.sebulba",
       _WORKER_FLAG, json.dumps(spec)],
      env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
      text=True)
  out, _ = proc.communicate(timeout=timeout_s)
  if proc.returncode != 0 or "ORACLE_OK" not in out:
    raise RuntimeError(
        f"sebulba oracle failed rc={proc.returncode}:\n{out}")
  marker = "ORACLE_RESULT "
  line = next(ln for ln in out.splitlines() if ln.startswith(marker))
  return json.loads(line[len(marker):])


def compare_params(path_a: str, path_b: str) -> Dict:
  """Leaf-for-leaf bitwise comparison of two saved variables npz."""
  import hashlib
  with np.load(path_a) as a, np.load(path_b) as b:
    keys_a, keys_b = sorted(a.files), sorted(b.files)
    mismatched = []
    digest = hashlib.sha256()
    if keys_a != keys_b:
      return {"bit_identical": False, "keys_a": len(keys_a),
              "keys_b": len(keys_b), "mismatched_keys": True}
    for key in keys_a:
      left, right = a[key], b[key]
      digest.update(left.tobytes())
      # equal_nan only exists for inexact dtypes (the manifest leaf is
      # uint8); bitwise identity is the claim either way.
      same = (left.dtype == right.dtype and left.shape == right.shape
              and left.tobytes() == right.tobytes())
      if not same:
        mismatched.append(key)
  return {
      "bit_identical": not mismatched,
      "leaves": len(keys_a),
      "mismatched": mismatched[:8],
      "sha256": digest.hexdigest()[:16],
  }


def main(argv=None) -> None:
  import argparse
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument(_WORKER_FLAG, dest="worker", default=None,
                      help=argparse.SUPPRESS)
  args = parser.parse_args(argv)
  if args.worker is None:
    parser.error("this module's CLI is the worker entry point; the "
                 "user-facing protocol lives in "
                 "tensor2robot_tpu.bin.bench_sebulba")
  spec = json.loads(args.worker)
  if spec.get("role") == "oracle":
    _run_oracle(spec)
  else:
    _run_actor(spec)


if __name__ == "__main__":
  main()

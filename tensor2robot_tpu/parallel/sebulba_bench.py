"""Sebulba decoupled-tier bench (ISSUE 20): SEBULBA_r20's generator.

Two claims, each proven against live machinery on the chipless box
(2 REAL actor processes + 1 learner process over CPU devices — separate
interpreters with their own JAX runtimes, not threads):

1. **Decoupled overlap with oracle parity** — 2 CEM actor processes
   (each a stock ``VectorActor`` pinning ONE acting executable to its
   own single-device runtime, hot-reloading learner-published params
   through the never-recompile predictor contract) stream fixed-shape
   chunks through the spool + bounded ``TransitionQueue`` into the
   2-device sharded learner, whose ingest seam is
   ``data/prefetch.py``'s double-buffered async ``device_put`` feeding
   ``extend_device_chunk``. Bars: two real actor pids, zero queue drops
   in the parity window, device_extend/megastep compiled exactly ONCE,
   overlap/stall/occupancy instruments present and sane, and —
   the tentpole — learner params BIT-identical to a serialized
   single-process oracle (fresh interpreter, no queue, no threads)
   replaying the recorded arrival manifest against the spooled chunks.
   The PR 19 fleet-observability transport carries the evidence: every
   actor exports its registry snapshot under its own host label and
   ``obs/aggregate`` merges actor0/actor1/learner into one view.
2. **Actor death is a handled regime** — actor0 is killed mid-stream
   (``os._exit(3)`` after N chunks, the preemption shape). Bars: the
   learner-side watchdog flags the silent actor, the PR 11 breaker
   walks quarantine (open) → probe (half_open respawn continuing the
   seq numbering) → reinstate (closed on the probe's first fresh
   chunk), the learner finishes every megastep on the surviving stream,
   post-death chunks from the reinstated actor are ingested, and the
   exactly-once ledger shows ZERO new learner compiles across the whole
   outage.

Honesty rule (virtual devices): env_steps_per_sec / transitions_per_sec
are null — actor processes emulated on a small CPU host measure process
scheduling, not acting throughput. Overlap-fraction MAGNITUDE bars are
enforced only when ``os.cpu_count() >= 4`` (below that, a 2-core box
cannot genuinely run actors and learner concurrently); the structural
bars (instrument present, 0 < fraction <= 1, stalls accounted) hold
everywhere.

CLI (ONE JSON line; bars enforced at generation on --smoke):

    python -m tensor2robot_tpu.parallel.sebulba_bench --smoke \\
        --out SEBULBA_r20.json

    # Reduced tier-1 lane (synthetic actors, bars deferred):
    python -m tensor2robot_tpu.parallel.sebulba_bench --ci
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
from typing import Dict

from tensor2robot_tpu.parallel.sebulba import (SCHEMA, SebulbaConfig,
                                               compare_params, run_live,
                                               run_oracle_subprocess)


def _bar(enforce: bool, ok: bool, message: str) -> bool:
  if enforce and not ok:
    raise AssertionError(message)
  return bool(ok)


def _quantitative() -> bool:
  """Magnitude bars need enough cores for actors and learner to truly
  run concurrently; a 2-core box proves structure only."""
  return (os.cpu_count() or 1) >= 4


def measure_decoupled_overlap(workdir: str, seed: int,
                              enforce_bars: bool,
                              synthetic: bool = False,
                              num_megasteps: int = 4) -> Dict:
  """Phase 1: live 2-actor decoupled run, then the serial oracle."""
  from tensor2robot_tpu.obs.aggregate import aggregate_logdir
  config = SebulbaConfig(
      seed=seed, num_actors=2, envs_per_actor=16, capacity=512,
      batch_size=32, inner_steps=2, chunks_per_megastep=2,
      num_megasteps=num_megasteps, mesh_devices=2, queue_capacity=512,
      cem_num_samples=16, cem_num_elites=4, cem_iterations=2,
      publish_every=2, target_refresh_every=2,
      actor_deadline_s=8.0, quarantine_s=2.0,
      synthetic_actors=synthetic, actor_max_chunks=512)
  live = run_live(config, os.path.join(workdir, "live"),
                  timeout_s=420.0)
  oracle = run_oracle_subprocess(
      config, os.path.join(workdir, "live", "spool"),
      live["manifest"], os.path.join(workdir, "oracle"))
  parity = compare_params(live["final_params_path"],
                          oracle["params_path"])
  fleet = aggregate_logdir(live["obs_logdir"], merged_trace=False)
  # Registry snapshots merge under host:pid keys (the aggregate's
  # "hosts" list tracks metrics.jsonl streams, which this tier does
  # not write) — the host labels prove which processes reported.
  fleet_hosts = sorted({key.split(":")[0] for key
                        in fleet["registry"]["gauges_per_host"]})
  overlap = live["overlap"]
  actors = live["actors"]
  pids = {(result or {}).get("pid") for result in actors.values()}
  quantitative = _quantitative()
  bars = {
      "two_actor_processes": _bar(
          enforce_bars,
          len(actors) == 2 and None not in pids
          and len(pids) == 2 and live["learner_pid"] not in pids,
          f"expected 2 live actor pids distinct from the learner, got "
          f"{pids} vs learner {live['learner_pid']}"),
      "learner_sharded_two_devices": _bar(
          enforce_bars, live["mesh_shape"] == {"data": 2},
          f"learner mesh {live['mesh_shape']} is not the 2-device "
          "data-sharded layout"),
      "executables_exactly_once": _bar(
          enforce_bars,
          live["compile_counts"] == {"device_extend": 1, "megastep": 1},
          f"learner compile ledger {live['compile_counts']} is not "
          "exactly-once"),
      "no_drops_in_parity_window": _bar(
          enforce_bars, live["queue"]["dropped"] == 0,
          f"queue shed {live['queue']['dropped']} rows during the "
          "parity window — the recorded manifest no longer equals the "
          "consumed stream"),
      "params_bit_identical_to_oracle": _bar(
          enforce_bars, parity["bit_identical"],
          f"live learner params diverge from the serial oracle: "
          f"{parity['mismatched']}"),
      "metric_stream_bit_identical": _bar(
          enforce_bars,
          live["drive"]["stream"] == oracle["drive"]["stream"],
          "live megastep metric stream != oracle stream"),
      "oracle_ledger_matches": _bar(
          enforce_bars,
          oracle["compile_counts"] == live["compile_counts"],
          f"oracle compiles {oracle['compile_counts']} != live "
          f"{live['compile_counts']}"),
      "overlap_instrumented": _bar(
          enforce_bars,
          0.0 < overlap["overlap_fraction"] <= 1.0
          and overlap["learner_stall_s"] >= 0.0
          and overlap["queue_occupancy"]["samples"] > 0
          and overlap["learn_busy_s"] > 0.0,
          f"overlap instruments incomplete: {overlap}"),
      "fleet_view_merged_all_hosts": _bar(
          enforce_bars,
          {"actor0", "actor1", "learner"} <= set(fleet_hosts),
          f"obs/aggregate merged hosts {fleet_hosts}, expected "
          "actor0+actor1+learner"),
      # Magnitude claim, quantitative-gated: with real concurrency the
      # actors should keep acting for at least half the learner wall.
      "overlap_fraction_majority": _bar(
          enforce_bars and quantitative,
          overlap["overlap_fraction"] >= 0.5,
          f"overlap fraction {overlap['overlap_fraction']} < 0.5"
      ) if quantitative else None,
  }
  return {
      "config": live["config"],
      "actor_mode": ("synthetic" if synthetic else "cem"),
      "actors": {
          key: {field: (result or {}).get(field)
                for field in ("pid", "chunks", "busy_seconds",
                              "env_steps", "param_reloads",
                              "params_version", "compile_counts",
                              "backpressure_stall_s")}
          for key, result in actors.items()},
      "overlap": overlap,
      "queue": live["queue"],
      "compile_counts": live["compile_counts"],
      "oracle": {
          "compile_counts": oracle["compile_counts"],
          "megasteps": oracle["drive"]["megasteps"],
      },
      "params_parity": parity,
      "fleet_obs": {
          "hosts": fleet_hosts,
          "registry_sources": fleet["registry"]["sources"],
      },
      "quantitative_bars_enforced": quantitative,
      "bars": bars,
  }


def measure_actor_outage(workdir: str, seed: int,
                         enforce_bars: bool) -> Dict:
  """Phase 2: kill actor0 mid-stream; prove quarantine → probe →
  reinstate while the learner trains through on the survivor."""
  die_after = 4
  config = SebulbaConfig(
      seed=seed + 1, num_actors=2, envs_per_actor=8, capacity=64,
      batch_size=8, inner_steps=2, chunks_per_megastep=2,
      num_megasteps=10, mesh_devices=2, queue_capacity=96,
      synthetic_actors=True, actor_max_chunks=512,
      actor_deadline_s=0.25, quarantine_s=0.5,
      actor_step_sleep_s=0.05)
  live = run_live(config, os.path.join(workdir, "outage"),
                  die_after={0: die_after}, timeout_s=300.0)
  timeline = live["supervisor"]["timeline"]
  events0 = [entry["event"] for entry in timeline
             if entry["actor"] == 0]
  quarantine = next((entry for entry in timeline
                     if entry["event"] == "quarantine"
                     and entry["actor"] == 0), None)
  breaker0 = [entry["state"] for entry
              in live["supervisor"]["breaker_events"]["0"]]
  consumed0 = [entry["seq"] for entry in live["manifest"]
               if entry["actor"] == 0]
  consumed1 = [entry["seq"] for entry in live["manifest"]
               if entry["actor"] == 1]
  bars = {
      "actor_killed_rc3": _bar(
          enforce_bars,
          quarantine is not None and quarantine.get("rc") == 3,
          f"expected the quarantined actor0 reaped with rc=3, got "
          f"{quarantine}"),
      "watchdog_flagged_silent_actor": _bar(
          enforce_bars,
          any(event["event"] == "watchdog_stall"
              and event["component"].startswith("sebulba/actor0")
              for event in live["watchdog_events"]),
          f"no watchdog_stall for actor0 in {live['watchdog_events']}"),
      "quarantine_probe_reinstate_in_order": _bar(
          enforce_bars,
          [event for event in events0
           if event != "spawn"] == ["quarantine", "probe", "reinstate"],
          f"actor0 lifecycle {events0} is not spawn->quarantine->"
          "probe->reinstate"),
      "breaker_walked_the_states": _bar(
          enforce_bars, breaker0 == ["open", "half_open", "closed"],
          f"breaker transitions {breaker0} != open->half_open->closed"),
      "probe_resumed_seq_numbering": _bar(
          enforce_bars,
          any(entry["event"] == "probe" and entry["actor"] == 0
              and entry["start_seq"] >= die_after for entry in timeline),
          f"probe did not continue actor0's sequence: {timeline}"),
      "reinstated_chunks_ingested": _bar(
          enforce_bars, any(seq >= die_after for seq in consumed0),
          f"no post-death actor0 chunk consumed (seqs {consumed0})"),
      "survivor_fed_learner": _bar(
          enforce_bars, len(consumed1) > 0,
          "actor1 (the survivor) fed the learner no chunks"),
      "all_megasteps_completed": _bar(
          enforce_bars,
          live["drive"]["megasteps"] == config.num_megasteps,
          f"learner stopped at {live['drive']['megasteps']}/"
          f"{config.num_megasteps} megasteps"),
      "zero_learner_recompiles": _bar(
          enforce_bars,
          live["compile_counts"] == {"device_extend": 1, "megastep": 1},
          f"outage caused learner recompiles: {live['compile_counts']}"),
  }
  return {
      "config": live["config"],
      "die_after_chunks": die_after,
      "timeline": timeline,
      "breaker_events": live["supervisor"]["breaker_events"],
      "respawns": live["supervisor"]["respawns"],
      "watchdog_events": live["watchdog_events"],
      "consumed_seqs": {"actor0": consumed0, "actor1": consumed1},
      "compile_counts": live["compile_counts"],
      "megasteps": live["drive"]["megasteps"],
      "bars": bars,
  }


def measure_sebulba(seed: int = 0, enforce_bars: bool = True) -> Dict:
  """The committed SEBULBA_r20 protocol (see module docstring)."""
  workdir = tempfile.mkdtemp(prefix="sebulba_r20_")
  try:
    overlap = measure_decoupled_overlap(
        os.path.join(workdir, "overlap"), seed,
        enforce_bars=enforce_bars, synthetic=False)
    outage = measure_actor_outage(
        os.path.join(workdir, "outage"), seed,
        enforce_bars=enforce_bars)
  finally:
    shutil.rmtree(workdir, ignore_errors=True)
  return {
      "schema": SCHEMA,
      "virtual_mesh": True,
      "decoupled_overlap": overlap,
      "actor_outage": outage,
      # Compact sentinels (bench.py round 20; null-safe): structure/
      # parity claims are meaningful chipless; rates are not.
      "sebulba_actor_processes": len(overlap["actors"]),
      "oracle_bit_identical": overlap["bars"][
          "params_bit_identical_to_oracle"],
      "outage_reinstated": outage["bars"][
          "quarantine_probe_reinstate_in_order"],
      "zero_recompiles_through_outage": outage["bars"][
          "zero_learner_recompiles"],
      "overlap_fraction": overlap["overlap"]["overlap_fraction"],
      # Honesty rule: actor processes time-sliced on a small CPU host
      # measure the scheduler, not acting throughput — rate keys are
      # null until the real-chip tier (ROADMAP item 1).
      "env_steps_per_sec": None,
      "transitions_per_sec": None,
      "note": (
          "Sebulba decoupled tier on VIRTUAL devices: 2 real CEM actor "
          "processes (one acting executable each, params hot-reloaded "
          "through the never-recompile predictor) stream fixed-shape "
          "chunks through the bounded TransitionQueue into the "
          "2-device sharded learner behind the double-buffered "
          "device_put prefetch seam. Learner params and megastep "
          "metric stream are bit-identical to a serialized one-process "
          "oracle replaying the recorded arrival manifest; "
          "device_extend/megastep compile exactly once, including "
          "across kill-actor0 -> watchdog flag -> breaker quarantine "
          "-> probe respawn (seq numbering continued) -> reinstate. "
          "obs/aggregate merges actor0/actor1/learner registry "
          "snapshots into one fleet view. virtual_mesh=true: "
          "throughput keys null by rule; overlap-magnitude bars gated "
          "on cpu_count >= 4."),
  }


def main(argv=None) -> None:
  """CLI: ONE JSON line. --smoke bootstraps the 8-virtual-device CPU
  mesh (actor workers get their own 1-device envs) and runs the
  committed SEBULBA_r20 protocol with generation-time bar enforcement;
  --ci is the reduced tier-1 lane (synthetic actors, bars deferred to
  tests/)."""
  import argparse

  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument("--smoke", action="store_true",
                      help="chipless committed-artifact lane: full "
                           "protocol, bars enforced at generation time")
  parser.add_argument("--ci", action="store_true",
                      help="reduced chipless lane (synthetic actors)")
  parser.add_argument("--seed", type=int, default=0)
  parser.add_argument("--out", default=None,
                      help="also write the JSON line to this file")
  args = parser.parse_args(argv)
  if args.smoke or args.ci:
    from tensor2robot_tpu.utils.cpu_mesh_env import (cpu_mesh_env,
                                                     is_cpu_mesh_env)
    n = 8 if args.smoke else 4
    if not is_cpu_mesh_env(n):
      if argv is not None:
        raise RuntimeError(
            "--smoke/--ci need the virtual CPU mesh configured before "
            "JAX initializes; call main() with argv=None (the CLI "
            "re-execs itself).")
      os.execve(sys.executable,
                [sys.executable, "-m",
                 "tensor2robot_tpu.parallel.sebulba_bench",
                 *sys.argv[1:]],
                cpu_mesh_env(n))
  if args.ci:
    workdir = tempfile.mkdtemp(prefix="sebulba_ci_")
    try:
      results = {
          "schema": SCHEMA,
          "virtual_mesh": True,
          "decoupled_overlap": measure_decoupled_overlap(
              workdir, args.seed, enforce_bars=False, synthetic=True,
              num_megasteps=3),
      }
    finally:
      shutil.rmtree(workdir, ignore_errors=True)
  else:
    results = measure_sebulba(seed=args.seed)
  line = json.dumps(results)
  if args.out:
    with open(args.out, "w") as f:
      f.write(line + "\n")
  print(line)


if __name__ == "__main__":
  main()

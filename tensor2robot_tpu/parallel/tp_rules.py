"""Tensor-parallel sharding rules for model parameters.

The mesh abstraction (SURVEY.md §5.8) reserves extra axes for model
parallelism; these helpers derive `PartitionSpec`s for parameter trees so
the Trainer can lay large matmul weights across a `model` axis — XLA
then inserts the all-gathers/reduce-scatters over ICI. Parity note: the
reference had no TP at all; this is capability beyond it.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def infer_dense_tp_specs(
    params: Any,
    mesh: Mesh,
    axis: str = "model",
    min_width: int = 64,
) -> Any:
  """PartitionSpec tree: shard wide matmul kernels' output dim over `axis`.

  Heuristic column parallelism: any parameter with ndim ≥ 2 whose last
  dimension is ≥ min_width and divisible by the axis size gets
  P(..., axis); everything else (biases, norm scales, small heads) is
  replicated. Returns all-replicated specs when the mesh lacks `axis`
  or it has size 1, so callers can apply unconditionally.
  """
  axis_size = mesh.shape.get(axis, 1)

  def rule(leaf):
    shape = np.shape(leaf)
    if (axis_size > 1 and len(shape) >= 2
        and shape[-1] >= min_width and shape[-1] % axis_size == 0):
      return PartitionSpec(*([None] * (len(shape) - 1)), axis)
    return PartitionSpec()

  return jax.tree_util.tree_map(rule, params)


def infer_dense_tp_specs_from_model(
    model,
    mesh: Mesh,
    axis: str = "model",
    min_width: int = 64,
) -> Any:
  """Derives TP specs from a T2R model without materializing weights."""
  shapes = jax.eval_shape(
      lambda rng: model.init_variables(rng), jax.random.key(0))
  return infer_dense_tp_specs(shapes["params"], mesh, axis=axis,
                              min_width=min_width)


def specs_to_shardings(specs: Any, mesh: Mesh) -> Any:
  """PartitionSpec tree → NamedSharding tree."""
  return jax.tree_util.tree_map(
      lambda spec: NamedSharding(mesh, spec), specs,
      is_leaf=lambda x: isinstance(x, PartitionSpec))

"""Tensor-parallel sharding rules for model parameters.

The mesh abstraction (SURVEY.md §5.8) reserves extra axes for model
parallelism; these helpers derive `PartitionSpec`s for parameter trees so
the Trainer can lay large matmul weights across a `model` axis — XLA
then inserts the all-gathers/reduce-scatters over ICI. Parity note: the
reference had no TP at all; this is capability beyond it.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def infer_dense_tp_specs(
    params: Any,
    mesh: Mesh,
    axis: str = "model",
    min_width: int = 64,
) -> Any:
  """PartitionSpec tree: shard wide matmul kernels' output dim over `axis`.

  Heuristic column parallelism: any parameter with ndim ≥ 2 whose last
  dimension is ≥ min_width and divisible by the axis size gets
  P(..., axis); everything else (biases, norm scales, small heads) is
  replicated. Returns all-replicated specs when the mesh lacks `axis`
  or it has size 1, so callers can apply unconditionally.
  """
  axis_size = mesh.shape.get(axis, 1)

  def rule(leaf):
    shape = np.shape(leaf)
    if (axis_size > 1 and len(shape) >= 2
        and shape[-1] >= min_width and shape[-1] % axis_size == 0):
      return PartitionSpec(*([None] * (len(shape) - 1)), axis)
    return PartitionSpec()

  return jax.tree_util.tree_map(rule, params)


def path_key(path, sep: str = "/") -> str:
  """Slash-joined name of a pytree key path (flax param naming):
  ``(DictKey('pre_conv0'), DictKey('kernel'))`` → ``pre_conv0/kernel``."""
  parts = []
  for entry in path:
    if hasattr(entry, "key"):
      parts.append(str(entry.key))
    elif hasattr(entry, "idx"):
      parts.append(str(entry.idx))
    elif hasattr(entry, "name"):
      parts.append(str(entry.name))
    else:
      parts.append(str(entry))
  return sep.join(parts)


def match_partition_rules(
    rules: Sequence[Tuple[str, PartitionSpec]],
    params: Any,
    sep: str = "/",
) -> Any:
  """Regex partition rules over a named param tree → PartitionSpec tree.

  Each leaf's slash-joined path (``pre_conv0/kernel``) is matched
  against `rules` in order via ``re.search``; the FIRST matching rule's
  spec wins. Scalar and size-1 leaves are always replicated before any
  rule runs (there is nothing to split), so rule sets only need to name
  real tensors. A leaf no rule matches raises — a model growing a new
  param must extend its rules, not silently replicate — so rule sets
  conventionally end with a ``(".*", P())`` catch-all when replication
  is the intended default. Works on concrete arrays and on
  ``jax.eval_shape`` structs alike (only ``.shape`` is read).
  """
  def match(path, leaf):
    name = path_key(path, sep)
    shape = np.shape(leaf)
    if len(shape) == 0 or int(np.prod(shape, dtype=np.int64)) == 1:
      return PartitionSpec()
    for pattern, spec in rules:
      if re.search(pattern, name) is not None:
        return spec
    raise ValueError(f"Partition rule not found for param: {name}")

  return jax.tree_util.tree_map_with_path(match, params)


def partition_specs_for_model(model, mesh: Mesh, axis: str = "model") -> Any:
  """The model's own TP layout as a PartitionSpec tree, mesh-validated.

  Asks `model` for ``partition_rules(axis=...)`` — the regex → spec
  pairs a model declares about its OWN param names (the pjit/TPUv4
  scaling recipe: layouts live with the model, the trainer just applies
  them) — and matches them over the eval_shape param tree. Falls back
  to all-replicated specs when the mesh lacks `axis`, the axis has size
  1, or the model declares no rules, so callers apply the result
  unconditionally and tp=1 lowers bit-identically to an unsharded run.
  Every sharded dim is checked divisible by the axis size; a rule
  splitting a 64-wide channel dim 8 ways is fine, 48 ways is a refusal
  naming the param, not a silent wrong layout.
  """
  shapes = _eval_param_shapes(model)
  axis_size = mesh.shape.get(axis, 1)
  rules_fn = getattr(model, "partition_rules", None)
  if axis_size <= 1 or rules_fn is None:
    return jax.tree_util.tree_map(lambda leaf: PartitionSpec(), shapes)
  specs = match_partition_rules(rules_fn(axis=axis), shapes)

  def validate(path, leaf, spec):
    shape = np.shape(leaf)
    entries = tuple(spec)
    for dim, entry in enumerate(entries):
      names = entry if isinstance(entry, tuple) else (entry,)
      if axis in [n for n in names if n is not None]:
        if shape[dim] % axis_size != 0:
          raise ValueError(
              f"partition rule for {path_key(path)!r} shards dim {dim} "
              f"(size {shape[dim]}) over {axis!r} of size {axis_size}, "
              f"which does not divide it; fix the rule or the mesh")
    return spec

  return jax.tree_util.tree_map_with_path(
      validate, shapes, specs,
      is_leaf=lambda x: isinstance(x, PartitionSpec))


def compose_data_axis_spec(shape, base_spec: PartitionSpec, axis: str,
                           axis_size: int) -> PartitionSpec:
  """ZeRO-1's data-axis shard composed ONTO an existing (TP) spec.

  Shards the largest `axis_size`-divisible dim that `base_spec` leaves
  unclaimed over `axis`, preserving the base spec's model-axis entries —
  the TP×ZeRO composition: an opt-state leaf keeps its param's model
  split and additionally scatters over the data axis. With
  ``base_spec=P()`` this reduces EXACTLY to
  ``largest_divisible_dim_spec`` (the pure-DP ZeRO-1 rule, unchanged).
  """
  base = list(tuple(base_spec)) + [None] * (len(shape) - len(tuple(base_spec)))
  divisible = [i for i, s in enumerate(shape)
               if base[i] is None and s >= axis_size
               and s % axis_size == 0]
  if not divisible:
    if any(entry is not None for entry in base):
      return PartitionSpec(*base)
    return PartitionSpec()
  dim = max(divisible, key=lambda i: shape[i])
  base[dim] = axis
  return PartitionSpec(*base)


def _eval_param_shapes(model) -> Any:
  """Parameter shape tree of a T2R model without materializing weights."""
  shapes = jax.eval_shape(
      lambda rng: model.init_variables(rng), jax.random.key(0))
  return shapes["params"]


def largest_divisible_dim_spec(shape, axis: str, axis_size: int
                               ) -> PartitionSpec:
  """PartitionSpec sharding `shape`'s largest axis_size-divisible dim over
  `axis`; replicated when no dim qualifies. The shared rule behind both
  FSDP param sharding and ZeRO-1 opt-state sharding."""
  divisible = [i for i, s in enumerate(shape)
               if s >= axis_size and s % axis_size == 0]
  if not divisible:
    return PartitionSpec()
  dim = max(divisible, key=lambda i: shape[i])
  spec = [None] * len(shape)
  spec[dim] = axis
  return PartitionSpec(*spec)


def infer_dense_tp_specs_from_model(
    model,
    mesh: Mesh,
    axis: str = "model",
    min_width: int = 64,
) -> Any:
  """Derives TP specs from a T2R model without materializing weights."""
  return infer_dense_tp_specs(_eval_param_shapes(model), mesh, axis=axis,
                              min_width=min_width)


def infer_fsdp_specs(
    params: Any,
    mesh: Mesh,
    axis: str = "data",
    min_size: int = 4096,
) -> Any:
  """PartitionSpec tree: fully-sharded parameters over the DATA axis
  (FSDP / ZeRO-3, Rajbhandari et al. 2019, arXiv:1910.02054).

  Each parameter with ≥ min_size elements shards its largest
  axis-divisible dimension over `axis`; per-chip param + grad + opt-state
  memory drops by the DP degree, and XLA turns the constraint into
  just-in-time all-gathers for the forward/backward plus reduce-scatter
  of the gradients — the same schedule hand-written FSDP runtimes
  implement, derived here entirely from shardings. Small leaves stay
  replicated (gathering them costs more latency than they save).

  Feed the result to ``Trainer(param_specs=...)``: since the batch is
  sharded over the same axis this composes as standard FSDP+DP. Returns
  all-replicated specs when the mesh lacks `axis` or it has size 1.
  """
  axis_size = mesh.shape.get(axis, 1)

  def rule(leaf):
    shape = np.shape(leaf)
    if axis_size <= 1 or int(np.prod(shape, dtype=np.int64)) < min_size:
      return PartitionSpec()
    return largest_divisible_dim_spec(shape, axis, axis_size)

  return jax.tree_util.tree_map(rule, params)


def infer_fsdp_specs_from_model(
    model,
    mesh: Mesh,
    axis: str = "data",
    min_size: int = 4096,
) -> Any:
  """Derives FSDP specs from a T2R model without materializing weights."""
  return infer_fsdp_specs(_eval_param_shapes(model), mesh, axis=axis,
                          min_size=min_size)


def specs_to_shardings(specs: Any, mesh: Mesh) -> Any:
  """PartitionSpec tree → NamedSharding tree."""
  return jax.tree_util.tree_map(
      lambda spec: NamedSharding(mesh, spec), specs,
      is_leaf=lambda x: isinstance(x, PartitionSpec))

"""Tensor-parallel sharding rules for model parameters.

The mesh abstraction (SURVEY.md §5.8) reserves extra axes for model
parallelism; these helpers derive `PartitionSpec`s for parameter trees so
the Trainer can lay large matmul weights across a `model` axis — XLA
then inserts the all-gathers/reduce-scatters over ICI. Parity note: the
reference had no TP at all; this is capability beyond it.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def infer_dense_tp_specs(
    params: Any,
    mesh: Mesh,
    axis: str = "model",
    min_width: int = 64,
) -> Any:
  """PartitionSpec tree: shard wide matmul kernels' output dim over `axis`.

  Heuristic column parallelism: any parameter with ndim ≥ 2 whose last
  dimension is ≥ min_width and divisible by the axis size gets
  P(..., axis); everything else (biases, norm scales, small heads) is
  replicated. Returns all-replicated specs when the mesh lacks `axis`
  or it has size 1, so callers can apply unconditionally.
  """
  axis_size = mesh.shape.get(axis, 1)

  def rule(leaf):
    shape = np.shape(leaf)
    if (axis_size > 1 and len(shape) >= 2
        and shape[-1] >= min_width and shape[-1] % axis_size == 0):
      return PartitionSpec(*([None] * (len(shape) - 1)), axis)
    return PartitionSpec()

  return jax.tree_util.tree_map(rule, params)


def _eval_param_shapes(model) -> Any:
  """Parameter shape tree of a T2R model without materializing weights."""
  shapes = jax.eval_shape(
      lambda rng: model.init_variables(rng), jax.random.key(0))
  return shapes["params"]


def largest_divisible_dim_spec(shape, axis: str, axis_size: int
                               ) -> PartitionSpec:
  """PartitionSpec sharding `shape`'s largest axis_size-divisible dim over
  `axis`; replicated when no dim qualifies. The shared rule behind both
  FSDP param sharding and ZeRO-1 opt-state sharding."""
  divisible = [i for i, s in enumerate(shape)
               if s >= axis_size and s % axis_size == 0]
  if not divisible:
    return PartitionSpec()
  dim = max(divisible, key=lambda i: shape[i])
  spec = [None] * len(shape)
  spec[dim] = axis
  return PartitionSpec(*spec)


def infer_dense_tp_specs_from_model(
    model,
    mesh: Mesh,
    axis: str = "model",
    min_width: int = 64,
) -> Any:
  """Derives TP specs from a T2R model without materializing weights."""
  return infer_dense_tp_specs(_eval_param_shapes(model), mesh, axis=axis,
                              min_width=min_width)


def infer_fsdp_specs(
    params: Any,
    mesh: Mesh,
    axis: str = "data",
    min_size: int = 4096,
) -> Any:
  """PartitionSpec tree: fully-sharded parameters over the DATA axis
  (FSDP / ZeRO-3, Rajbhandari et al. 2019, arXiv:1910.02054).

  Each parameter with ≥ min_size elements shards its largest
  axis-divisible dimension over `axis`; per-chip param + grad + opt-state
  memory drops by the DP degree, and XLA turns the constraint into
  just-in-time all-gathers for the forward/backward plus reduce-scatter
  of the gradients — the same schedule hand-written FSDP runtimes
  implement, derived here entirely from shardings. Small leaves stay
  replicated (gathering them costs more latency than they save).

  Feed the result to ``Trainer(param_specs=...)``: since the batch is
  sharded over the same axis this composes as standard FSDP+DP. Returns
  all-replicated specs when the mesh lacks `axis` or it has size 1.
  """
  axis_size = mesh.shape.get(axis, 1)

  def rule(leaf):
    shape = np.shape(leaf)
    if axis_size <= 1 or int(np.prod(shape, dtype=np.int64)) < min_size:
      return PartitionSpec()
    return largest_divisible_dim_spec(shape, axis, axis_size)

  return jax.tree_util.tree_map(rule, params)


def infer_fsdp_specs_from_model(
    model,
    mesh: Mesh,
    axis: str = "data",
    min_size: int = 4096,
) -> Any:
  """Derives FSDP specs from a T2R model without materializing weights."""
  return infer_fsdp_specs(_eval_param_shapes(model), mesh, axis=axis,
                          min_size=min_size)


def specs_to_shardings(specs: Any, mesh: Mesh) -> Any:
  """PartitionSpec tree → NamedSharding tree."""
  return jax.tree_util.tree_map(
      lambda spec: NamedSharding(mesh, spec), specs,
      is_leaf=lambda x: isinstance(x, PartitionSpec))

"""All-to-all (Ulysses-style) sequence parallelism for attention.

The complement to `ring_attention` for long in-context sequences
(long-context support beyond the reference, which capped sequences at
short robot episodes — SURVEY.md §5.7): instead of rotating K/V shards
around a ring (P-1 ppermute hops), one `all_to_all` re-shards the
inputs from sequence-sharded (B, T/P, H, D) to head-sharded
(B, T, H/P, D), each device runs ordinary full-sequence attention over
its head subset, and a second `all_to_all` restores sequence sharding.

Trade-off vs ring attention (pick per workload):
  - Ulysses: two all-to-all rounds total — one (q,k,v fused) in, one
    out (O(1) collective rounds, bandwidth O(B·T·H·D/P) per device) —
    but every device holds the FULL sequence
    for H/P heads — T is bounded by per-device memory unless the local
    attention is itself blockwise (use attn_impl="pallas" to keep the
    local working set O(T)).
  - Ring: P-1 ppermute rounds overlapped with compute; K/V memory stays
    at the shard size, so T scales with the ring — better for extreme T,
    more latency-sensitive on slow interconnects.
  - Head-count constraint: Ulysses needs H % P == 0; ring does not.

Fully differentiable through `jax.grad` (the collectives are plain XLA
ops); with attn_impl="pallas" the same first-order-only caveat as
ops.flash_attention applies.
"""

from __future__ import annotations

import functools
import inspect
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

try:  # promoted to jax.shard_map in newer releases
  from jax import shard_map
except ImportError:
  from jax.experimental.shard_map import shard_map


def _local_attention(q, k, v, causal: bool, scale: float, attn_impl: str):
  if attn_impl == "pallas":
    from tensor2robot_tpu.ops.flash_attention import flash_attention
    return flash_attention(q, k, v, causal=causal, scale=scale,
                           implementation="pallas")
  from tensor2robot_tpu.parallel.ring_attention import (
      dense_attention_reference)
  return dense_attention_reference(q, k, v, causal=causal, scale=scale)


def _ulysses_local(q, k, v, axis_name: str, causal: bool, scale: float,
                   attn_impl: str):
  """Per-device body: shards are (B, T_local, H, D)."""
  # Sequence-sharded → head-sharded: split the head axis P ways, gather
  # the sequence axis. q/k/v are stacked so the in-direction re-shard is
  # one collective launch instead of three.
  qkv = jnp.stack((q, k, v))                           # (3, B, T_loc, H, D)
  qkv = jax.lax.all_to_all(
      qkv, axis_name, split_axis=3, concat_axis=2, tiled=True)
  qh, kh, vh = qkv[0], qkv[1], qkv[2]                  # (B, T, H/P, D)
  out = _local_attention(qh, kh, vh, causal, scale, attn_impl)
  # Head-sharded → sequence-sharded: the inverse all-to-all.
  return jax.lax.all_to_all(
      out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis: str = "seq",
    causal: bool = False,
    scale: Optional[float] = None,
    batch_axis: Optional[str] = None,
    attn_impl: str = "xla",
) -> jnp.ndarray:
  """Exact multi-head attention with the sequence sharded over `axis`,
  computed via head-scatter/sequence-gather all-to-alls.

  Args:
    q, k, v: (B, T, H, D) arrays; T and H must divide evenly over the
      mesh axis. Inputs may be replicated or already sequence-sharded —
      the shard_map in_specs lay them out over `axis`.
    mesh: the device mesh (e.g. create_mesh({"data": 1, "seq": 8})).
    axis: mesh axis name carrying the sequence dimension.
    causal: apply a causal mask over GLOBAL positions.
    scale: attention scale; default 1/sqrt(D).
    batch_axis: mesh axis carrying the batch dim on dp×sp meshes.
    attn_impl: "xla" (dense local attention) or "pallas" (blockwise
      flash kernel locally — keeps per-device memory O(T), TPU only).

  Returns:
    (B, T, H, D) attention output, sharded like the inputs.
  """
  if attn_impl not in ("xla", "pallas"):
    raise ValueError(
        f"attn_impl must be 'xla' or 'pallas', got {attn_impl!r} — a "
        "typo here would silently fall back to the dense O(T²) path.")
  num_shards = mesh.shape[axis]
  if q.shape[2] % num_shards != 0:
    raise ValueError(
        f"Ulysses needs heads ({q.shape[2]}) divisible by the {axis!r} "
        f"axis size ({num_shards}); use ring_attention otherwise.")
  if scale is None:
    scale = 1.0 / math.sqrt(q.shape[-1])
  spec = PartitionSpec(batch_axis, axis, None, None)
  # pallas_call's out_shape carries no varying-mesh-axes annotation,
  # which the replication/VMA type check rejects inside shard_map; the
  # explicit in/out_specs above already pin the layout, so the check
  # adds nothing here. The kwarg was renamed check_rep -> check_vma.
  check_kw = ("check_vma" if "check_vma"
              in inspect.signature(shard_map).parameters else "check_rep")
  fn = shard_map(
      functools.partial(_ulysses_local, axis_name=axis, causal=causal,
                        scale=scale, attn_impl=attn_impl),
      mesh=mesh,
      in_specs=(spec, spec, spec),
      out_specs=spec,
      **{check_kw: attn_impl != "pallas"},
  )
  return fn(q, k, v)

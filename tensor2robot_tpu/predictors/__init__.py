"""Predictors — the on-robot inference API.

Reference parity: predictors/ (SURVEY.md §2, §3.3): restore-with-timeout
(robots start before the first export exists), predict(np dict)→np dict
validated against spec assets, hot-reload on new versions.
"""

from tensor2robot_tpu.predictors.abstract_predictor import AbstractPredictor
from tensor2robot_tpu.predictors.checkpoint_predictor import (
    CheckpointPredictor,
)
from tensor2robot_tpu.predictors.exported_model_predictor import (
    ExportedModelPredictor,
)

__all__ = [
    "AbstractPredictor",
    "CheckpointPredictor",
    "ExportedModelPredictor",
]

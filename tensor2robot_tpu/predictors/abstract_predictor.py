"""AbstractPredictor: the robot-facing inference contract.

Reference parity: predictors/abstract_predictor.py §AbstractPredictor
(SURVEY.md §2): predict/restore/init_randomly/model_version/
get_feature_specification/close, with restore-with-timeout semantics.
"""

from __future__ import annotations

import abc
from typing import Dict, Optional

import numpy as np

from tensor2robot_tpu.specs import tensorspec_utils as ts


class AbstractPredictor(abc.ABC):
  """Loads a trained artifact and serves predict() on the robot."""

  @abc.abstractmethod
  def restore(self, timeout_s: float = 0.0,
              raise_on_timeout: bool = False) -> bool:
    """Loads (or hot-reloads) the newest available model.

    Blocks up to timeout_s waiting for a first model to appear (robots
    start before the trainer's first export — SURVEY.md §2 predictors
    row), polling with jittered exponential backoff
    (utils/backoff.py: a robot fleet restarting together must not
    hammer the export filesystem in lockstep). Returns True when a
    model is loaded. With ``raise_on_timeout``, a timeout that leaves
    NO model loaded raises ``utils.backoff.PollTimeout`` naming the
    path that was being waited on instead of returning False — the
    loud form for deployments where silently proceeding without a
    model is worse than crashing with the path in the message.
    """

  @abc.abstractmethod
  def predict(
      self, features: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Runs inference on a batched numpy feature dict."""

  def predict_batched(
      self, features: Dict[str, np.ndarray],
      ladder=None) -> Dict[str, np.ndarray]:
    """predict() with the batch dim padded to a bounded size ladder.

    Fleet serving flushes batches of whatever size the deadline caught;
    calling predict() raw would compile one executable per distinct
    size (and per CEM sample multiple on the host path). This pads the
    leading dim up to a fixed rung — a `serving.BucketLadder` when
    given, else the next power of two — runs predict(), and slices the
    outputs back, so the executable count stays bounded no matter what
    request sizes arrive. Padding repeats the last row (numerically
    benign through normalization layers); padded outputs are dropped.
    """
    from tensor2robot_tpu.serving.bucketing import pad_to
    sizes = {np.asarray(v).shape[0] for v in dict(features).values()}
    if len(sizes) != 1:
      raise ValueError(f"inconsistent leading batch dims: {sizes}")
    n = sizes.pop()
    bucket = ladder.bucket_for(n) if ladder is not None else (
        1 << max(0, (n - 1).bit_length()))
    if bucket == n:
      return self.predict(features)
    padded = {k: pad_to(np.asarray(v), bucket)
              for k, v in dict(features).items()}
    return {k: v[:n] for k, v in self.predict(padded).items()}

  @abc.abstractmethod
  def get_feature_specification(self) -> ts.TensorSpecStruct:
    """The (flat) feature spec predict() expects."""

  @property
  @abc.abstractmethod
  def model_version(self) -> int:
    """Monotonic version of the loaded model; -1 before restore."""

  def init_randomly(self) -> None:
    """Initializes with random weights (debug/bring-up; reference
    §init_randomly). Optional: default raises."""
    raise NotImplementedError(
        f"{type(self).__name__} does not support init_randomly.")

  def set_variables(self, variables,
                    version: Optional[int] = None,
                    cast: bool = False) -> None:
    """Hot-swaps the served params in place (same tree structure/shapes).

    `cast` is the explicit precision-cast seam (ISSUE 13): a candidate
    whose leaves arrive at a different floating dtype than the served
    tree (e.g. a bf16-exported checkpoint promoted onto an f32-serving
    predictor, or vice versa) is REJECTED by default — the fleet's AOT
    executables were compiled against the live avals, and a silent
    dtype change would fail every replica's next flush. Passing
    cast=True declares the drift intentional: implementations cast the
    candidate onto the LIVE tree's dtypes before installing it, so the
    served avals (and therefore every compiled consumer) are untouched
    while the candidate's VALUES land. Note the scoring-precision tier
    itself never needs this — bf16 scoring quantizes inside the tier's
    executables (cem.cast_scoring_variables) and the master params stay
    f32; the seam exists for params that were ALREADY cast on disk.

    The rollout controller's promotion path (serving/rollout.py): a
    canary-validated candidate cuts over by swapping the variables the
    predictor hands out — an atomic pointer swap under the GIL.
    `version` is the candidate's step in the SAME namespace
    model_version lives in (checkpoint/export global step): passing it
    keeps restore()'s newest-wins staleness check honest — without it,
    a promotion from export step 250 onto a predictor at checkpoint
    step 100 would leave model_version at 101, and a later restore()
    poll finding checkpoint 150 would silently overwrite the promoted
    params with OLDER ones. When None, the version bumps by one
    (in-memory predictors with counter versions). Implementations
    clamp to stay monotonic. Compiled consumers (the fleet policies'
    bucket executables, AOT CEM programs) take variables as an
    ARGUMENT, so a swap is never a recompile; the hot-reload ledger
    test pins that. Optional: predictors whose params live inside an
    opaque artifact (e.g. a TF SavedModel) raise, and rollout for them
    goes through restore() on a new artifact instead.
    """
    raise NotImplementedError(
        f"{type(self).__name__} does not support in-place variable "
        "hot-swap; publish a new export and call restore().")

  def _next_swap_version(self, version: Optional[int]) -> int:
    """Monotonic model_version for a set_variables swap (shared rule)."""
    bumped = self.model_version + 1
    return bumped if version is None else max(bumped, int(version))

  def device_fn(self):
    """Device-resident serving entry for jit-composed policies.

    Returns (fn, variables) where ``fn(variables, flat_features) ->
    outputs dict`` is traceable under jax.jit — so wrappers like the
    QT-Opt CEM loop can fuse sampling + scoring + refitting into ONE
    compiled program per control step instead of shipping sample
    batches across the host boundary every predict() (the host path
    moves the tiled image H2D per CEM iteration; this path moves it
    once). Optional: predictors without a JAX-native computation
    (e.g. the TF SavedModel predictor) raise, and callers fall back
    to predict().
    """
    raise NotImplementedError(
        f"{type(self).__name__} has no device-resident serving path.")

  def close(self) -> None:
    """Releases resources."""

  def assert_is_loaded(self) -> None:
    if self.model_version < 0:
      raise ValueError("Predictor has no model loaded; call restore().")

  def _validate_features(
      self, features: Dict[str, np.ndarray]) -> ts.TensorSpecStruct:
    """Validates a batched feature dict against the spec (batch dim free)."""
    spec = self.get_feature_specification()
    flat = ts.TensorSpecStruct(
        (k, np.asarray(v)) for k, v in dict(features).items())
    return ts.validate_and_flatten(spec, flat, batched=True)

  def _poll_newer_version(self, export_root: str,
                          timeout_s: float) -> Optional[int]:
    """Waits for an export version newer than model_version; None if the
    timeout expires first (shared by the export-dir predictors)."""
    from tensor2robot_tpu.export import export_utils

    def newest():
      versions = export_utils.list_export_versions(export_root)
      candidate = versions[-1] if versions else None
      if candidate is not None and candidate > self.model_version:
        return candidate
      return None

    return self._wait_for(newest, timeout_s,
                          description=f"an export under {export_root}")

  @staticmethod
  def _wait_for(predicate, timeout_s: float,
                description: Optional[str] = None):
    """Polls predicate() until truthy or timeout; returns its value.

    Jittered exponential backoff (utils/backoff.py) instead of the old
    fixed 0.5s cadence: a restarting robot fleet decorrelates instead
    of stampeding the export filesystem, and a long wait backs off to
    ~2s polls. `description` names the awaited path for the loud
    restore(raise_on_timeout=True) form.
    """
    from tensor2robot_tpu.utils import backoff
    return backoff.poll_with_backoff(
        predicate, timeout_s, initial_s=0.1, max_s=2.0,
        description=description)

  def _timeout_unloaded(self, description: str, timeout_s: float,
                        raise_on_timeout: bool) -> bool:
    """Shared restore() timeout exit: False when a model is already
    serving (a hot-reload poll that found nothing new is healthy), a
    PollTimeout naming `description` when raise_on_timeout and NOTHING
    was ever loaded (the robot would otherwise start serving thin
    air)."""
    if self.model_version >= 0:
      return True
    if raise_on_timeout:
      from tensor2robot_tpu.utils import backoff
      raise backoff.PollTimeout(description, timeout_s, 0)
    return False

"""CheckpointPredictor: rebuild the model in-process, restore a checkpoint.

Reference parity: predictors/checkpoint_predictor.py §CheckpointPredictor
(SURVEY.md §2): no export needed — the predictor owns the model's Python
code, restores the latest checkpoint from a training run dir, and serves
predict(). Uses EMA params when the run trained with use_avg_model_params
(the reference's eval/export swap).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from tensor2robot_tpu import modes
from tensor2robot_tpu.predictors.abstract_predictor import AbstractPredictor
from tensor2robot_tpu.specs import tensorspec_utils as ts


class CheckpointPredictor(AbstractPredictor):
  """Serves a T2R model directly from its checkpoint directory."""

  def __init__(self, model, checkpoint_dir: Optional[str] = None):
    """Args:
      model: an AbstractT2RModel instance (provides module + specs).
      checkpoint_dir: the training run's checkpoint dir; None allows only
        init_randomly.
    """
    self._model = model
    self._checkpoint_dir = checkpoint_dir
    self._variables = None
    self._version = -1
    self._predict = None
    self._manager = None

  def _build_predict(self):
    from tensor2robot_tpu.export import export_utils
    model = self._model

    def predict(variables, features):
      return export_utils.normalize_serving_outputs(
          model.predict_fn(variables, features))

    return jax.jit(predict)

  def restore(self, timeout_s: float = 0.0,
              raise_on_timeout: bool = False) -> bool:
    if self._checkpoint_dir is None:
      raise ValueError("No checkpoint_dir given; use init_randomly().")
    import os
    directory = os.path.abspath(self._checkpoint_dir)

    def _latest():
      if self._manager is None:
        if not os.path.isdir(directory):
          # Trainer hasn't created the run dir yet; keep polling without
          # creating it (create=True would defeat typo detection).
          return None
        self._manager = ocp.CheckpointManager(
            directory, options=ocp.CheckpointManagerOptions(create=False))
      self._manager.reload()  # pick up steps written since construction
      step = self._manager.latest_step()
      if step is None or step <= self._version:
        return None
      return step, self._manager.restore(
          step, args=ocp.args.StandardRestore())

    result = self._wait_for(
        _latest, timeout_s,
        description=f"a checkpoint under {directory}")
    if not result:
      return self._timeout_unloaded(
          f"a checkpoint under {directory}", timeout_s, raise_on_timeout)
    step, restored = result
    ema = restored.get("ema_params")
    params = ema if ema is not None else restored["params"]
    model_state = restored.get("model_state")
    # Device-resident: orbax restores host arrays, and keeping numpy here
    # would re-upload the whole weight pytree on every predict()/fused
    # control step (cf. ExportedModelPredictor.restore).
    self._variables = jax.tree_util.tree_map(jax.numpy.asarray, {
        "params": params,
        **(model_state if model_state is not None else {}),
    })
    self._version = int(step)
    if self._predict is None:
      self._predict = self._build_predict()
    return True

  def init_randomly(self) -> None:
    variables = self._model.init_variables(jax.random.key(0))
    self._variables = jax.tree_util.tree_map(jax.numpy.asarray, variables)
    self._version = 0
    if self._predict is None:
      self._predict = self._build_predict()

  def set_variables(self, variables,
                    version: Optional[int] = None,
                    cast: bool = False) -> None:
    """See AbstractPredictor.set_variables: the rollout promotion path.
    Structure must match the loaded tree — a mismatched candidate must
    fail HERE (actionable), not as a shape error inside some replica's
    next flush. Pass the candidate's export step as `version` so a
    later restore() poll cannot mistake an older on-disk checkpoint
    for news.

    cast=True is the intentional precision-cast seam (ISSUE 13): a
    dtype-drifted candidate (e.g. bf16-exported params promoted onto
    this f32-serving predictor) is cast leaf-by-leaf onto the LIVE
    tree's dtypes before installing, so the served avals — and every
    replica's compiled bucket executable — are untouched while the
    candidate's values land. Without it, dtype drift rejects exactly
    as before (an unintentional cast is a fleet-wide aval mismatch
    waiting to happen)."""
    self.assert_is_loaded()

    def check(old, new):
      if np.shape(old) != np.shape(new):
        raise ValueError(
            f"hot-swap shape mismatch: {np.shape(old)} -> "
            f"{np.shape(new)} (a reshaped candidate would recompile "
            "every bucket executable; promote via a new export "
            "instead).")
      old_dtype = np.asarray(old).dtype
      new_dtype = np.asarray(new).dtype
      if old_dtype != new_dtype:
        # jnp.issubdtype, not np: bfloat16 is an ml_dtypes extension
        # numpy's floating hierarchy does not recognize.
        floating = (jax.numpy.issubdtype(old_dtype, jax.numpy.floating)
                    and jax.numpy.issubdtype(new_dtype,
                                             jax.numpy.floating))
        if cast and floating:
          # The explicit seam: candidate values at the live avals.
          # Scoped to floating->floating — the documented precision
          # drift. A non-float mismatch (an int counter arriving as
          # float, a uint8 table as f32) is STRUCTURAL drift; casting
          # it would silently truncate/wrap values fleet-wide, so it
          # rejects below regardless of `cast`.
          return jax.numpy.asarray(new).astype(old_dtype)
        raise ValueError(
            f"hot-swap dtype mismatch: {old_dtype} -> {new_dtype} "
            "(the fleet's AOT executables were compiled against the "
            "old avals; a dtype change would fail every replica's "
            "next flush — promote via a new export"
            + (", or pass cast=True for an intentional precision "
               "cast onto the served dtypes" if floating else
               "; a non-floating mismatch is structural drift the "
               "cast seam refuses") + ").")
      return new

    checked = jax.tree_util.tree_map(check, self._variables, variables)
    self._variables = jax.tree_util.tree_map(jax.numpy.asarray, checked)
    self._version = self._next_swap_version(version)

  def predict(
      self, features: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    self.assert_is_loaded()
    flat = self._validate_features(features)
    outputs = self._predict(self._variables, flat)
    return {k: np.asarray(v) for k, v in outputs.items()}

  def device_fn(self):
    """See AbstractPredictor.device_fn: the model's predict_fn is plain
    traced JAX, directly composable under an outer jit."""
    self.assert_is_loaded()
    from tensor2robot_tpu.export import export_utils
    model = self._model

    def fn(variables, features):
      return export_utils.normalize_serving_outputs(
          model.predict_fn(variables, ts.TensorSpecStruct(features)))

    return fn, self._variables

  def get_feature_specification(self) -> ts.TensorSpecStruct:
    return ts.flatten_spec_structure(
        self._model.preprocessor.get_out_feature_specification(
            modes.PREDICT))

  @property
  def model_version(self) -> int:
    return self._version

  def close(self) -> None:
    self._variables = None
    self._predict = None
    self._version = -1  # assert_is_loaded fails cleanly after close()
    if self._manager is not None:
      self._manager.close()
      self._manager = None

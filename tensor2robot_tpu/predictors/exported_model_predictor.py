"""ExportedModelPredictor: serve a native (jax.export) artifact.

Reference parity: predictors/exported_savedmodel_predictor.py
§ExportedSavedModelPredictor (SURVEY.md §3.3): poll an export root for the
newest version, block-with-timeout until the first export exists, predict
on numpy dicts, hot-reload on newer versions. The artifact carries the
whole computation (StableHLO) + weights + specs, so no model Python code
is needed on the robot.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import numpy as np

from tensor2robot_tpu.export import export_utils, variables_io
from tensor2robot_tpu.export.native_export_generator import (
    SERVING_FN_NAME,
    VARIABLES_DIR,
    VARIABLES_NPZ,
)
from tensor2robot_tpu.predictors.abstract_predictor import AbstractPredictor
from tensor2robot_tpu.specs import tensorspec_utils as ts


class ExportedModelPredictor(AbstractPredictor):
  """Polls export_root and serves the newest native artifact."""

  def __init__(self, export_root: str):
    self._export_root = export_root
    self._version = -1
    self._call = None
    self._exported_call = None
    self._variables = None
    self._feature_spec: Optional[ts.TensorSpecStruct] = None
    self._feature_keys = None
    self._example_parser = None

  # --- loading -------------------------------------------------------------

  def restore(self, timeout_s: float = 0.0,
              raise_on_timeout: bool = False) -> bool:
    newest = self._poll_newer_version(self._export_root, timeout_s)
    if newest is None:
      return self._timeout_unloaded(
          f"a native export under {self._export_root}", timeout_s,
          raise_on_timeout)
    export_dir = os.path.join(self._export_root, str(newest))
    with open(os.path.join(export_dir, SERVING_FN_NAME), "rb") as f:
      exported = jax.export.deserialize(bytearray(f.read()))
    npz_path = os.path.join(export_dir, VARIABLES_NPZ)
    if os.path.exists(npz_path):
      variables = variables_io.load_variables(npz_path)
    else:  # legacy orbax-layout artifact
      import orbax.checkpoint as ocp
      variables = ocp.StandardCheckpointer().restore(
          os.path.abspath(os.path.join(export_dir, VARIABLES_DIR)))
    feature_spec, _, extra = export_utils.read_spec_assets(export_dir)
    self._exported_call = exported.call
    self._call = jax.jit(exported.call)
    self._variables = jax.tree_util.tree_map(jax.numpy.asarray, variables)
    self._feature_spec = feature_spec
    self._feature_keys = extra["feature_keys"]
    self._example_parser = None  # rebuilt on demand for the new spec
    self._version = newest
    return True

  # --- serving -------------------------------------------------------------

  def predict(
      self, features: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    self.assert_is_loaded()
    flat = self._validate_features(features)
    missing = [key for key in self._feature_keys if key not in flat]
    if missing:
      raise ValueError(
          f"Features {missing} are required by this export (all exported "
          "keys are positional inputs of the serialized computation, "
          "including specs marked optional at training time).")
    args = [np.asarray(flat[key]) for key in self._feature_keys]
    outputs = self._call(self._variables, *args)
    return {k: np.asarray(v) for k, v in outputs.items()}

  def predict_examples(self, serialized) -> Dict[str, np.ndarray]:
    """Serves a batch of SERIALIZED tf.Example records — TF-free.

    The SavedModel path parses records inside the loaded graph
    (`ExportedSavedModelPredictor.predict_examples`); the native
    artifact carries only the computation, so parsing happens here
    through the packaged feature spec and the repo's dependency-free
    tf.Example codec (with the C++ whole-batch fast path when the
    library is available) — a robot without TF can still consume the
    exact wire format the data-collection fleet logs (raw uint8 bytes,
    encoded jpeg/png, dense numerics alike, per the spec's
    data_format).
    """
    from tensor2robot_tpu.data.parser import ExampleParser
    self.assert_is_loaded()
    if getattr(self, "_example_parser", None) is None:
      self._example_parser = ExampleParser(self._feature_spec)
    features, _ = self._example_parser.parse_batch(list(serialized))
    return self.predict(features)

  def device_fn(self):
    """See AbstractPredictor.device_fn: the deserialized StableHLO call
    is traceable under an outer jit (it inlines as a call op)."""
    self.assert_is_loaded()
    call = self._exported_call
    keys = tuple(self._feature_keys)

    def fn(variables, features):
      return dict(call(variables, *[features[key] for key in keys]))

    return fn, self._variables

  def get_feature_specification(self) -> ts.TensorSpecStruct:
    self.assert_is_loaded()
    return self._feature_spec

  @property
  def model_version(self) -> int:
    return self._version

  def close(self) -> None:
    self._call = None
    self._exported_call = None
    self._variables = None
    self._example_parser = None
    self._version = -1  # assert_is_loaded fails cleanly after close()

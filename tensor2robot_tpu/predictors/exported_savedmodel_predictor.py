"""ExportedSavedModelPredictor — load jax2tf SavedModels like the reference.

Reference parity: predictors/exported_savedmodel_predictor.py (SURVEY.md
§3.3): poll export root, load newest SavedModel with the TF C++ loader,
predict via the serving_default signature, hot-reload on new versions.
Kept for robot stacks that still link TF; pure-JAX consumers should use
ExportedModelPredictor.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np

from tensor2robot_tpu.export import export_utils
from tensor2robot_tpu.predictors.abstract_predictor import AbstractPredictor
from tensor2robot_tpu.specs import tensorspec_utils as ts


class ExportedSavedModelPredictor(AbstractPredictor):
  """Polls export_root and serves the newest SavedModel."""

  def __init__(self, export_root: str):
    self._export_root = export_root
    self._version = -1
    self._fn = None
    self._loaded = None
    self._feature_spec: Optional[ts.TensorSpecStruct] = None

  def restore(self, timeout_s: float = 0.0,
              raise_on_timeout: bool = False) -> bool:
    import tensorflow as tf
    newest = self._poll_newer_version(self._export_root, timeout_s)
    if newest is None:
      return self._timeout_unloaded(
          f"a SavedModel export under {self._export_root}", timeout_s,
          raise_on_timeout)
    export_dir = os.path.join(self._export_root, str(newest))
    loaded = tf.saved_model.load(export_dir)
    self._loaded = loaded  # keep a reference: signatures hold weak refs
    self._fn = loaded.signatures["serving_default"]
    self._feature_spec, _, _ = export_utils.read_spec_assets(export_dir)
    self._version = newest
    return True

  def predict(
      self, features: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    import tensorflow as tf
    self.assert_is_loaded()
    flat = self._validate_features(features)
    missing = [k for k in self._feature_spec.keys() if k not in flat]
    if missing:
      raise ValueError(
          f"Features {missing} are required by this SavedModel signature "
          "(specs marked optional at training time are still baked into "
          "the export's input signature).")
    outputs = self._fn(**{k: tf.constant(np.asarray(v))
                          for k, v in flat.items()})
    return {k: v.numpy() for k, v in outputs.items()}

  def predict_examples(self, serialized) -> Dict[str, np.ndarray]:
    """Serves a batch of SERIALIZED tf.Example records via the export's
    `tf_example` signature — the robot wire path (reference
    §ExportedSavedModelPredictor served the same signature): parsing,
    decode_raw of uint8 image bytes, and the model run all happen
    inside the loaded SavedModel, so the caller ships exactly what the
    data-collection fleet logs.

    Args:
      serialized: sequence of `tf.train.Example.SerializeToString()`
        byte strings.
    """
    import tensorflow as tf
    self.assert_is_loaded()
    if "tf_example" not in self._loaded.signatures:
      raise ValueError(
          "This SavedModel was exported without the tf_example "
          "signature (SavedModelExportGenerator("
          "with_tf_example_signature=False)); use predict() with "
          "numpy feeds instead.")
    fn = self._loaded.signatures["tf_example"]
    outputs = fn(tf.constant(list(serialized), dtype=tf.string))
    return {k: v.numpy() for k, v in outputs.items()}

  def get_feature_specification(self) -> ts.TensorSpecStruct:
    self.assert_is_loaded()
    return self._feature_spec

  @property
  def model_version(self) -> int:
    return self._version

  def close(self) -> None:
    self._fn = None
    self._loaded = None

"""Preprocessors: declared-spec-in → declared-spec-out transformations.

Reference parity: preprocessors/ (SURVEY.md §2 "Preprocessors"). Host-side
by design: they run in the input-pipeline threads, so only dense numeric
statically-shaped arrays ever cross the host→device boundary — the invariant
the reference enforced with TPUPreprocessorWrapper, which the rebuild gets
for free (no strings can reach device_put). Device-side augmentation (inside
the jitted step) lives in tensor2robot_tpu.ops instead.
"""

from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
    ModelNoOpPreprocessor,
    NoOpPreprocessor,
)
from tensor2robot_tpu.preprocessors.image_preprocessors import (
    ImagePreprocessor,
    apply_photometric_distortions,
    center_crop,
    random_crop,
)

__all__ = [
    "AbstractPreprocessor",
    "ImagePreprocessor",
    "ModelNoOpPreprocessor",
    "NoOpPreprocessor",
    "apply_photometric_distortions",
    "center_crop",
    "random_crop",
]

"""Abstract preprocessor protocol: spec-in/spec-out, mode-aware, host-side.

Reference parity: preprocessors/abstract.py §AbstractPreprocessor,
preprocessors/noop_preprocessor.py §NoOpPreprocessor (SURVEY.md §2). The
in-specs describe what the input pipeline must parse; the out-specs describe
what the model consumes. The train loop and input generators glue the two
(SURVEY.md §3.1): parse per in-spec → preprocess → validate per out-spec →
device.
"""

from __future__ import annotations

import abc
from typing import Optional, Tuple

from tensor2robot_tpu import modes
from tensor2robot_tpu.specs import tensorspec_utils as ts


class AbstractPreprocessor(abc.ABC):
  """Transforms parsed batches into model-ready batches, per mode."""

  @abc.abstractmethod
  def get_in_feature_specification(self, mode: str) -> ts.TensorSpecStruct:
    """What the input pipeline must produce for this preprocessor."""

  @abc.abstractmethod
  def get_in_label_specification(self, mode: str) -> ts.TensorSpecStruct:
    """Label specs the input pipeline must produce."""

  @abc.abstractmethod
  def get_out_feature_specification(self, mode: str) -> ts.TensorSpecStruct:
    """What this preprocessor hands to the model."""

  @abc.abstractmethod
  def get_out_label_specification(self, mode: str) -> ts.TensorSpecStruct:
    """Label specs handed to the model."""

  @abc.abstractmethod
  def _preprocess_fn(
      self,
      features: ts.TensorSpecStruct,
      labels: Optional[ts.TensorSpecStruct],
      mode: str,
  ) -> Tuple[ts.TensorSpecStruct, Optional[ts.TensorSpecStruct]]:
    """The transformation itself (batched numpy in, batched numpy out)."""

  def preprocess(
      self,
      features: ts.TensorSpecStruct,
      labels: Optional[ts.TensorSpecStruct],
      mode: str,
  ) -> Tuple[ts.TensorSpecStruct, Optional[ts.TensorSpecStruct]]:
    """Validated preprocess: checks inputs and outputs against the specs."""
    modes.validate_mode(mode)
    features = ts.validate_and_pack(
        self.get_in_feature_specification(mode), features)
    if labels is not None and len(labels):
      labels = ts.validate_and_pack(
          self.get_in_label_specification(mode), labels)
    out_features, out_labels = self._preprocess_fn(features, labels, mode)
    out_features = ts.validate_and_pack(
        self.get_out_feature_specification(mode), out_features)
    if out_labels is not None and len(out_labels):
      out_labels = ts.validate_and_pack(
          self.get_out_label_specification(mode), out_labels)
    return out_features, out_labels


class NoOpPreprocessor(AbstractPreprocessor):
  """Identity preprocessor: in-specs == out-specs == the model's specs.

  Reference: preprocessors/noop_preprocessor.py §NoOpPreprocessor.
  """

  def __init__(
      self,
      feature_spec: ts.SpecStructure,
      label_spec: Optional[ts.SpecStructure] = None,
  ):
    ts.assert_valid_spec_structure(feature_spec)
    self._feature_spec = ts.flatten_spec_structure(feature_spec)
    if label_spec is not None:
      ts.assert_valid_spec_structure(label_spec)
      self._label_spec = ts.flatten_spec_structure(label_spec)
    else:
      self._label_spec = ts.TensorSpecStruct()

  def get_in_feature_specification(self, mode: str) -> ts.TensorSpecStruct:
    return self._feature_spec

  def get_in_label_specification(self, mode: str) -> ts.TensorSpecStruct:
    return self._label_spec

  def get_out_feature_specification(self, mode: str) -> ts.TensorSpecStruct:
    return self._feature_spec

  def get_out_label_specification(self, mode: str) -> ts.TensorSpecStruct:
    return self._label_spec

  def _preprocess_fn(self, features, labels, mode):
    return features, labels


class ModelNoOpPreprocessor(AbstractPreprocessor):
  """Identity preprocessor resolving specs from a model *per mode*.

  The default for models without an explicit preprocessor: unlike
  NoOpPreprocessor's static specs, this respects mode-dependent spec
  declarations (a PREDICT spec may legitimately omit train-only keys).
  `model` is any object with get_feature_specification(mode) /
  get_label_specification(mode).
  """

  def __init__(self, model):
    self._model = model

  def get_in_feature_specification(self, mode: str) -> ts.TensorSpecStruct:
    return ts.flatten_spec_structure(
        self._model.get_feature_specification(mode))

  def get_in_label_specification(self, mode: str) -> ts.TensorSpecStruct:
    return ts.flatten_spec_structure(
        self._model.get_label_specification(mode))

  def get_out_feature_specification(self, mode: str) -> ts.TensorSpecStruct:
    return self.get_in_feature_specification(mode)

  def get_out_label_specification(self, mode: str) -> ts.TensorSpecStruct:
    return self.get_in_label_specification(mode)

  def _preprocess_fn(self, features, labels, mode):
    return features, labels

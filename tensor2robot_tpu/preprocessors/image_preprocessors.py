"""Image preprocessing: crops, photometric distortion, dtype conversion.

Reference parity: preprocessors/image_transformations.py
§ApplyPhotometricImageDistortions, §CreateRandomCrop and the
uint8→float conversion half of §TPUPreprocessorWrapper (SURVEY.md §2).

Host-side numpy, batched, vectorized — runs in the input-pipeline threads so
the device step stays pure compute. The distortion math matches the
reference's TF ops: brightness/contrast/saturation jitter in float space,
applied per-example with an independent host RNG (training only).
"""

from __future__ import annotations

import itertools
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

from tensor2robot_tpu import modes
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.specs import tensorspec_utils as ts


def random_crop(
    images: np.ndarray,
    target_height: int,
    target_width: int,
    rng: np.random.Generator,
) -> np.ndarray:
  """Per-example random spatial crop of a BHWC batch."""
  b, h, w, _ = images.shape
  if target_height > h or target_width > w:
    raise ValueError(
        f"Crop {target_height}x{target_width} larger than image {h}x{w}")
  tops = rng.integers(0, h - target_height + 1, size=b)
  lefts = rng.integers(0, w - target_width + 1, size=b)
  out = np.empty((b, target_height, target_width, images.shape[3]),
                 dtype=images.dtype)
  for i in range(b):
    out[i] = images[i, tops[i]:tops[i] + target_height,
                    lefts[i]:lefts[i] + target_width]
  return out


def center_crop(images: np.ndarray, target_height: int,
                target_width: int) -> np.ndarray:
  """Deterministic center crop of a BHWC batch (eval counterpart)."""
  _, h, w, _ = images.shape
  if target_height > h or target_width > w:
    raise ValueError(
        f"Crop {target_height}x{target_width} larger than image {h}x{w}")
  top = (h - target_height) // 2
  left = (w - target_width) // 2
  return images[:, top:top + target_height, left:left + target_width]


def adjust_saturation(images: np.ndarray, factors: np.ndarray) -> np.ndarray:
  """Exact HSV saturation scaling on RGB, vectorized (no HSV round-trip).

  For fixed hue/value each channel is c_i = v·(1 − s·q_i), so scaling
  s→k·s is c_i' = v − k·(v − c_i), with k capped per-pixel where k·s would
  exceed 1 — identical to tf.image.adjust_saturation's convert→scale→clip.
  """
  v = images.max(axis=-1, keepdims=True)
  diff = v - images
  max_diff = diff.max(axis=-1, keepdims=True)
  with np.errstate(divide="ignore", invalid="ignore"):
    cap = np.where(max_diff > 0, v / max_diff, np.inf)
  k = np.minimum(factors, cap)
  return v - k * diff


def apply_photometric_distortions(
    images: np.ndarray,
    rng: np.random.Generator,
    max_brightness_delta: float = 0.125,
    contrast_range: Tuple[float, float] = (0.5, 1.5),
    saturation_range: Tuple[float, float] = (0.5, 1.5),
    noise_stddev: float = 0.0,
    copy: bool = True,
) -> np.ndarray:
  """Per-example brightness/contrast/saturation jitter on float images.

  Reference: §ApplyPhotometricImageDistortions. Input must be float in
  [0, 1]; output is clipped back to [0, 1]. Contrast scales around the
  per-channel mean and saturation scales HSV S — matching
  tf.image.adjust_contrast / adjust_saturation (verified against TF in
  tests). `copy=False` mutates `images` in place (input-pipeline hot path).
  """
  if not np.issubdtype(images.dtype, np.floating):
    raise ValueError(
        f"Photometric distortions expect float images in [0,1], got "
        f"{images.dtype}; convert first.")
  b = images.shape[0]
  out = images.astype(np.float32, copy=copy)
  # Saturation first (on the undistorted colors), as HSV math assumes
  # in-gamut RGB.
  if out.shape[-1] == 3:
    sat = rng.uniform(*saturation_range, size=(b, 1, 1, 1)).astype(np.float32)
    out = adjust_saturation(out, sat)
  # Brightness: additive delta per example.
  deltas = rng.uniform(-max_brightness_delta, max_brightness_delta,
                       size=(b, 1, 1, 1)).astype(np.float32)
  out += deltas
  # Contrast: scale around the per-example, per-channel mean.
  factors = rng.uniform(*contrast_range, size=(b, 1, 1, 1)).astype(np.float32)
  means = out.mean(axis=(1, 2), keepdims=True)
  out -= means
  out *= factors
  out += means
  if noise_stddev > 0.0:
    out += rng.normal(0.0, noise_stddev, size=out.shape).astype(np.float32)
  return np.clip(out, 0.0, 1.0, out=out)


class ImagePreprocessor(AbstractPreprocessor):
  """Standard camera-image path: decode-sized uint8 in → float model-size out.

  Train: random crop + photometric distortion. Eval/predict: center crop
  only. Non-image keys pass through unchanged. The uint8→float32 [0,1]
  conversion is the reference's TPUPreprocessorWrapper dtype rule.

  Args:
    feature_spec: model-facing (out) feature specs; the image key must be a
      float spec with shape (H, W, C).
    label_spec: passthrough label specs.
    image_key: flat key of the image feature.
    in_image_shape: the parsed (pre-crop) image shape; defaults to the out
      shape (no crop).
    distort: enable photometric distortion in train mode.
    seed: augmentation seed. Pass a per-host-distinct value (e.g.
      seed + shard_index) in multi-host training so hosts don't apply
      identical crop sequences.
  """

  def __init__(
      self,
      feature_spec: ts.SpecStructure,
      label_spec: Optional[ts.SpecStructure] = None,
      image_key: str = "image",
      in_image_shape: Optional[Sequence[int]] = None,
      data_format: str = "jpeg",
      distort: bool = True,
      seed: int = 0,
  ):
    self._out_feature_spec = ts.flatten_spec_structure(feature_spec)
    if image_key not in self._out_feature_spec:
      raise ValueError(
          f"image_key {image_key!r} not in feature spec: "
          f"{list(self._out_feature_spec)}")
    self._image_key = image_key
    out_image = self._out_feature_spec[image_key]
    if not (np.issubdtype(out_image.dtype, np.floating)
            or out_image.dtype == np.uint8):
      raise ValueError(
          f"Out image spec must be float or uint8 (model-ready), got "
          f"{out_image.dtype}")
    in_shape = tuple(in_image_shape) if in_image_shape else out_image.shape
    # In-spec: parsed as encoded uint8 image at the pre-crop size.
    self._in_feature_spec = ts.TensorSpecStruct(self._out_feature_spec)
    self._in_feature_spec[image_key] = ts.ExtendedTensorSpec(
        in_shape, np.uint8, name=out_image.name or image_key,
        data_format=data_format)
    self._label_spec = (
        ts.flatten_spec_structure(label_spec) if label_spec is not None
        else ts.TensorSpecStruct())
    self._distort = distort
    # Preprocessors run on the input pipeline's thread pool;
    # np.random.Generator is not thread-safe, so each thread gets its own
    # stream: (seed, stream-index) with the index handed out atomically.
    self._seed = seed
    self._stream_counter = itertools.count()
    self._local = threading.local()

  @property
  def _rng(self) -> np.random.Generator:
    rng = getattr(self._local, "rng", None)
    if rng is None:
      rng = np.random.default_rng([self._seed, next(self._stream_counter)])
      self._local.rng = rng
    return rng

  def get_in_feature_specification(self, mode: str) -> ts.TensorSpecStruct:
    return self._in_feature_spec

  def get_in_label_specification(self, mode: str) -> ts.TensorSpecStruct:
    return self._label_spec

  def get_out_feature_specification(self, mode: str) -> ts.TensorSpecStruct:
    return self._out_feature_spec

  def get_out_label_specification(self, mode: str) -> ts.TensorSpecStruct:
    return self._label_spec

  def _preprocess_fn(self, features, labels, mode):
    out = ts.TensorSpecStruct(features)
    images = np.asarray(features[self._image_key])
    out_spec = self._out_feature_spec[self._image_key]
    target_h, target_w = out_spec.shape[:2]
    uint8_out = out_spec.dtype == np.uint8
    # Crop on uint8 first: converting the full pre-crop batch to float32
    # would waste host bandwidth in the pipeline threads.
    if mode == modes.TRAIN:
      if images.shape[1:3] != (target_h, target_w):
        images = random_crop(images, target_h, target_w, self._rng)
      if self._distort:
        images = apply_photometric_distortions(
            images.astype(np.float32) / 255.0, self._rng, copy=False)
      elif not uint8_out:
        images = images.astype(np.float32) / 255.0
    else:
      if images.shape[1:3] != (target_h, target_w):
        images = center_crop(images, target_h, target_w)
      if not uint8_out:
        images = images.astype(np.float32) / 255.0
    if uint8_out and images.dtype != np.uint8:
      # Distorted floats round back to the uint8 wire format; the model
      # rescales on device (layers.normalize_image) — uint8 crosses
      # host→device at a quarter of the float32 bytes.
      from tensor2robot_tpu.utils.image import to_uint8
      images = to_uint8(images)
    out[self._image_key] = images.astype(out_spec.dtype, copy=False)
    return out, labels

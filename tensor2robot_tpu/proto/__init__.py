"""Protobuf schema for specs + serving metadata (reference: proto/t2r.proto)."""

from tensor2robot_tpu.proto import t2r_pb2

"""Spec structure ↔ protobuf conversion.

Reference parity: the reference serializes its spec system through
proto/t2r.proto (SURVEY.md §2 "Proto") so exported artifacts carry their
input signature in a language-neutral form. These converters are the
binary twin of `tensorspec_utils.to_serialized`/`from_serialized` (JSON):
both round-trip `TensorSpecStruct`s exactly; the proto form additionally
carries the global step and exporter metadata (`T2RAssets`).
"""

from __future__ import annotations

import json
from typing import Any, Mapping, Optional, Tuple

from tensor2robot_tpu.proto import t2r_pb2
from tensor2robot_tpu.specs import tensorspec_utils as ts


def spec_to_proto(
    spec: ts.ExtendedTensorSpec,
    out: Optional[t2r_pb2.ExtendedTensorSpecProto] = None,
) -> t2r_pb2.ExtendedTensorSpecProto:
  """ExtendedTensorSpec → ExtendedTensorSpecProto."""
  proto = out if out is not None else t2r_pb2.ExtendedTensorSpecProto()
  proto.shape.extend(int(d) for d in spec.shape)
  proto.dtype = spec.dtype.name
  proto.name = spec.name or ""
  proto.is_optional = spec.is_optional
  proto.is_sequence = spec.is_sequence
  proto.data_format = spec.data_format or ""
  proto.dataset_key = spec.dataset_key
  if spec.varlen_default_value is not None:
    proto.varlen_default_value.value = float(spec.varlen_default_value)
  return proto


def proto_to_spec(
    proto: t2r_pb2.ExtendedTensorSpecProto) -> ts.ExtendedTensorSpec:
  """ExtendedTensorSpecProto → ExtendedTensorSpec."""
  varlen = None
  if proto.HasField("varlen_default_value"):
    varlen = proto.varlen_default_value.value
  return ts.ExtendedTensorSpec(
      shape=tuple(proto.shape),
      dtype=proto.dtype,
      name=proto.name or None,
      is_optional=proto.is_optional,
      is_sequence=proto.is_sequence,
      data_format=proto.data_format or None,
      dataset_key=proto.dataset_key,
      varlen_default_value=varlen,
  )


def struct_to_proto(
    spec_structure: ts.SpecStructure,
    out: Optional[t2r_pb2.TensorSpecStructProto] = None,
) -> t2r_pb2.TensorSpecStructProto:
  """Any spec structure → flattened, order-preserving proto."""
  proto = out if out is not None else t2r_pb2.TensorSpecStructProto()
  flat = ts.flatten_spec_structure(spec_structure)
  for key, spec in flat.items():
    entry = proto.entries.add()
    entry.key = key
    spec_to_proto(spec, out=entry.spec)
  return proto


def proto_to_struct(
    proto: t2r_pb2.TensorSpecStructProto) -> ts.TensorSpecStruct:
  """Inverse of `struct_to_proto` (always returns the flattened view)."""
  struct = ts.TensorSpecStruct()
  for entry in proto.entries:
    struct[entry.key] = proto_to_spec(entry.spec)
  return struct


def make_t2r_assets(
    feature_spec: ts.SpecStructure,
    label_spec: Optional[ts.SpecStructure] = None,
    extra: Optional[Mapping[str, Any]] = None,
    global_step: int = 0,
) -> t2r_pb2.T2RAssets:
  """Builds the serving-metadata proto written next to every export.

  `extra` values are JSON-encoded so arbitrary exporter metadata
  (lists, dicts) survives the string-map wire type.
  """
  assets = t2r_pb2.T2RAssets(global_step=int(global_step))
  struct_to_proto(feature_spec, out=assets.feature_spec)
  if label_spec is not None:
    struct_to_proto(label_spec, out=assets.label_spec)
  for key, value in (extra or {}).items():
    assets.extra[str(key)] = json.dumps(value)
  return assets


def parse_t2r_assets(
    assets: t2r_pb2.T2RAssets,
) -> Tuple[ts.TensorSpecStruct, Optional[ts.TensorSpecStruct], dict]:
  """T2RAssets → (feature_spec, label_spec, extra dict)."""
  feature_spec = proto_to_struct(assets.feature_spec)
  label_spec = (proto_to_struct(assets.label_spec)
                if assets.HasField("label_spec") else None)
  extra = {key: json.loads(value) for key, value in assets.extra.items()}
  return feature_spec, label_spec, extra

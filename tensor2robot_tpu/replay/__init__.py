"""Distributed replay + Bellman updater: the closed QT-Opt learning loop.

The reference repo shipped only the Q-function; its collectors, replay
log buffer, and Bellman updater fleet ran off-repo (SURVEY.md §2). This
package reconstructs that loop in the Podracer shape (PAPERS.md,
arXiv:2104.06272) — fixed-shape batches, a bounded compiled-program
set, host-RAM replay:

- ``ReplayBuffer`` / ``ShardedReplayBuffer`` (ring_buffer.py):
  preallocated spec-validated ring storage, O(1) wraparound append,
  seeded uniform or TD-proportional (sum-tree) sampling at ONE fixed
  batch shape;
- ``SumTree`` (sum_tree.py): O(log n) proportional sampling;
- ``episode_to_transitions`` / ``TransitionQueue`` / ``ReplayFeeder``
  (ingest.py): episode flattening, bounded drop-oldest backpressure
  with counted sheds, min-fill gating;
- ``BellmanUpdater`` (bellman.py): lagged/polyak target network,
  CEM-maximized Q-targets (reward + gamma * max_a' Q_target), AOT at
  the fixed batch shape with a compile-count ledger;
- ``DeviceReplayBuffer`` / ``MegastepLearner`` (device_buffer.py,
  ISSUE 4): the same ring as a device-resident pytree with pure
  jittable extend/sample/reprioritize, plus the fused Anakin-style
  megastep — K sample -> CEM-label -> train -> reprioritize iterations
  in ONE donated AOT executable (``ReplayLoopConfig.device_resident``);
- ``VectorActor`` / ``ActorFleet`` (actor.py, ISSUE 5): the batched
  actor side — every env stepped in lockstep through ONE fused CEM
  bucket executable (`synthetic_grasping.VectorGraspEnv` underneath),
  feeding the queue in fixed fleet-size chunks, double-buffered
  against the megastep learner (``ReplayLoopConfig.vector_actors``;
  the threaded CollectorWorker path is the fallback);
- ``AnakinLoop`` (anakin.py, ISSUE 6): the whole production loop —
  JAX-native env (`research/qtopt/jax_grasping.JaxGraspEnv`), CEM
  acting, fixed-chunk replay extend, and the learner inner body —
  fused into ONE donated executable scanning K control steps with
  zero host work in the steady state (``ReplayLoopConfig.anakin``;
  the vector-actor and threaded paths are the measured fallbacks);
- ``ReplayTrainLoop`` (loop.py): async collect -> replay -> train
  driver wiring serving's CEMFleetPolicy collectors, the buffer, the
  updater, and train/trainer.py together, with replay-health metrics
  through utils/metric_writer.

Entry point: ``python -m tensor2robot_tpu.bin.run_qtopt_replay``.
"""

from tensor2robot_tpu.replay.actor import ActorFleet, VectorActor
from tensor2robot_tpu.replay.anakin import AnakinLoop
from tensor2robot_tpu.replay.bellman import BellmanUpdater
from tensor2robot_tpu.replay.device_buffer import (DeviceReplayBuffer,
                                                   DeviceReplayState,
                                                   MegastepLearner)
from tensor2robot_tpu.replay.ingest import (ReplayFeeder, TransitionQueue,
                                            episode_to_transitions)
from tensor2robot_tpu.replay.loop import (CollectorWorker, ReplayLoopConfig,
                                          ReplayTrainLoop, transition_spec)
from tensor2robot_tpu.replay.ring_buffer import (ReplayBuffer, SampleInfo,
                                                 ShardedReplayBuffer)
from tensor2robot_tpu.replay.sum_tree import SumTree

__all__ = [
    "ActorFleet",
    "AnakinLoop",
    "BellmanUpdater",
    "CollectorWorker",
    "DeviceReplayBuffer",
    "DeviceReplayState",
    "MegastepLearner",
    "ReplayBuffer",
    "ReplayFeeder",
    "ReplayLoopConfig",
    "ReplayTrainLoop",
    "SampleInfo",
    "ShardedReplayBuffer",
    "SumTree",
    "TransitionQueue",
    "VectorActor",
    "episode_to_transitions",
    "transition_spec",
]

"""Vectorized actor fleet: batched env stepping through one fused
CEM executable per actor-batch bucket (ISSUE 5 tentpole).

PR 3 fused the learner into a single device-resident megastep, which
moved the QT-Opt loop's bottleneck to the actor side: the PR 2
collectors are Python threads, each stepping a small `GraspRetryEnv`
fleet through its own `CEMFleetPolicy` bucket call — per-step
host↔device round-trips and GIL contention scale with the THREAD
count, not the env count. Podracer (PAPERS.md, arXiv:2104.06272) makes
the counter-argument this module implements: Sebulba/Anakin throughput
comes from *batched acting* — many environments stepped in lockstep
through one compiled control step — co-scheduled with learning, and
the pjit/TPUv4 scaling study (arXiv:2204.06514) adds the shape
discipline: both phases stay a small fixed set of XLA executables.

The pieces:

- ``VectorActor``: one thread driving a ``VectorGraspEnv`` (all N
  scenes as stacked arrays, one numpy call per control step) through
  ONE `CEMFleetPolicy` bucket executable per step — the policy's
  ladder is pinned to the actor batch, so acting compiles exactly one
  executable for the life of the fleet, and param refresh rides the
  hot-reload contract (variables are executable ARGUMENTS — the same
  never-recompile discipline the megastep holds). Each step feeds the
  whole fleet batch to ``TransitionQueue.put_batch`` as one fixed-size
  chunk, which the device ring's jittable fixed-chunk extend consumes
  without ever seeing a new shape.
- ``ActorFleet``: the driver — owns the actors, starts/stops their
  threads, and aggregates episode/step/busy-time accounting. Acting
  runs on its own thread(s) double-buffered against the learner: while
  the train thread blocks inside a megastep dispatch (the GIL is
  released during XLA execution), the fleet is producing the next
  transitions, so collection and training OVERLAP instead of
  interleaving. ``busy_seconds()`` is the instrument: the actor bench
  reads it across a learner window to report the acting/learning
  overlap fraction as a measurement, not a diagram.

Collection semantics are UNCHANGED from the scalar collectors (PARITY
note): same retry budget (`max_attempts`), same epsilon-uniform +
scripted near-object exploration mix drawn in the same per-step order,
same scene-seed formula, same static-scene transition layout
(next_image == scene image; truncation bootstraps with done=0). Scope
of the parity claim: one VectorActor is bit-identical to ITS env count
worth of scalar envs sharing one seed stream (the property
tests/test_actor.py pins); a threaded MULTI-collector loop runs one
independent stream per worker, so against it the parity is
formula-level, not stream-level — that path's scene assignment is
thread-timing-dependent anyway. The scalar `CollectorWorker` path
stays in replay/loop.py as the measured fallback.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from tensor2robot_tpu.obs import flight_recorder as flight_lib
from tensor2robot_tpu.obs import trace as trace_lib
from tensor2robot_tpu.obs import watchdog as watchdog_lib
from tensor2robot_tpu.replay.ingest import TransitionQueue


class VectorActor:
  """One thread stepping N envs in lockstep through a batched policy.

  The vectorized counterpart of `loop.CollectorWorker`: one
  `policy(images)` call covers the WHOLE actor batch (one bucket
  executable), one `VectorGraspEnv.step` computes every outcome, and
  one `TransitionQueue.put_batch` hands the fleet's transitions over
  as a single fixed-size chunk.
  """

  def __init__(self, policy, queue: TransitionQueue, image_size: int,
               num_envs: int = 32, max_attempts: int = 4,
               seed: int = 0, grasp_radius: float = 0.35,
               exploration_epsilon: float = 0.2,
               scripted_fraction: float = 0.25,
               flight_recorder=None, watchdog=None):
    from tensor2robot_tpu.research.qtopt.synthetic_grasping import (
        VectorGraspEnv)
    self._policy = policy
    self._queue = queue
    # Owner-injectable observability (CollectorWorker contract): the
    # loop passes ITS recorder so an actor-death dump lands beside the
    # run's metrics, and ITS watchdog so the owner's monitor covers
    # acting liveness — defaults are the process singletons.
    self._recorder = flight_recorder or flight_lib.get_recorder()
    self._watchdog = watchdog or watchdog_lib.get_watchdog()
    # Exploration mix, QT-Opt parity — the same recipe, draw order, and
    # rng stream seeding as CollectorWorker (see its inline rationale:
    # scripted successes are what keep a cold critic off the base
    # rate); only the fleet width differs.
    self._epsilon = exploration_epsilon
    self._scripted = scripted_fraction
    self._explore_rng = np.random.default_rng(seed + 555)
    self._env = VectorGraspEnv(
        num_envs, image_size=image_size, max_attempts=max_attempts,
        radius=grasp_radius)
    self._seed = seed
    self._next_scene = 0
    self.env_steps = 0
    self.busy_seconds = 0.0
    self.errors: List[BaseException] = []
    self._stop = threading.Event()
    self._thread = threading.Thread(target=self._run, daemon=True)

  @property
  def num_envs(self) -> int:
    return self._env.num_envs

  @property
  def episodes(self) -> int:
    return self._env.episodes

  @property
  def successes(self) -> int:
    return self._env.successes

  def start(self) -> None:
    self._env.reset([self._scene_seed()
                     for _ in range(self._env.num_envs)])
    self._thread.start()

  def request_stop(self) -> None:
    """Signals the thread; returns immediately (never raises)."""
    self._stop.set()

  def stop(self, timeout: float = 30.0) -> None:
    """Signal + join + surface any recorded error (CollectorWorker
    contract: a multi-actor owner should request_stop() on every actor
    first, then join)."""
    self.request_stop()
    self._thread.join(timeout)
    if self.errors:
      raise RuntimeError("actor died") from self.errors[0]

  def _scene_seed(self) -> int:
    # CollectorWorker._scene_seed, verbatim: one monotonic counter over
    # the whole fleet, so scene assignment matches the scalar path's.
    seed = self._seed * 1_000_003 + self._next_scene
    self._next_scene += 1
    return seed

  def _run(self) -> None:
    # Liveness heartbeat (ISSUE 12): one beat per lockstep control
    # step; unregistered when the thread exits so a finished actor
    # never reads as a stalled one.
    heartbeat = self._watchdog.register("act/vector_actor")
    try:
      while not self._stop.is_set():
        self.step_once()
        heartbeat.beat()
    except BaseException as e:  # noqa: BLE001 — surfaced via stop()
      self.errors.append(e)
      self._recorder.trigger(
          "actor_thread_exception", error=f"{type(e).__name__}: {e}")
    finally:
      self._watchdog.unregister(heartbeat)

  def step_once(self) -> None:
    """One batched control step: act → step → enqueue, all fleet-wide.

    The scene snapshot is taken BEFORE the env steps: auto-reset
    overwrites terminated envs' rows in place, and a terminal
    transition's observation/next_image must be the OLD scene (static
    scene, no bootstrap leak across the reset — the scalar path's
    `[scene] * (t + 1)` episode stack holds the same invariant).

    Owns its busy accounting (moved here from `_run` for ISSUE 20):
    the Sebulba actor process drives step_once directly without ever
    starting the thread, and the overlap instrument must not care
    which driver is calling.
    """
    begin = time.perf_counter()
    env = self._env
    n = env.num_envs
    scenes = env.images.copy()
    targets = env.targets.copy()
    with trace_lib.span("act/cem_policy", envs=n):
      actions = np.asarray(self._policy(scenes))
    draw = self._explore_rng.random(n)
    uniform = self._explore_rng.uniform(
        -1.0, 1.0, actions.shape).astype(np.float32)
    scripted = uniform.copy()
    noise = self._explore_rng.normal(0.0, 0.12, (n, 2)).astype(np.float32)
    scripted[:, :2] = np.clip(targets + noise, -1.0, 1.0)
    actions = np.where((draw < self._epsilon)[:, None], uniform, actions)
    actions = np.where(
        (draw >= 1.0 - self._scripted)[:, None], scripted, actions)
    rewards, dones, _ = env.step(actions, seed_fn=self._scene_seed)
    self.env_steps += n
    # ONE fixed-size chunk per step (n never changes): image and
    # next_image alias the same snapshot on purpose — the scene is
    # static, and the buffer copies at its door anyway.
    self._queue.put_batch({
        "image": scenes,
        "action": actions.astype(np.float32, copy=False),
        "reward": rewards,
        "done": dones,
        "next_image": scenes,
    })
    self.busy_seconds += time.perf_counter() - begin


class ActorFleet:
  """Driver for the vectorized actors: lifecycle + fleet accounting.

  Owns `num_actors` `VectorActor`s (total_envs split evenly across
  them; one actor — one bucket executable — is the default and the
  measured configuration). The surface mirrors a CollectorWorker list
  so `ReplayTrainLoop`'s shared shutdown path drives either kind:
  `actors` is that list.
  """

  def __init__(self, policy, queue: TransitionQueue, image_size: int,
               total_envs: int, max_attempts: int = 4, seed: int = 0,
               grasp_radius: float = 0.35,
               exploration_epsilon: float = 0.2,
               scripted_fraction: float = 0.25,
               num_actors: int = 1,
               flight_recorder=None, watchdog=None):
    if num_actors < 1 or total_envs % num_actors:
      raise ValueError(
          f"total_envs {total_envs} must split evenly over "
          f"num_actors {num_actors}")
    self.actors = [
        VectorActor(policy, queue, image_size,
                    num_envs=total_envs // num_actors,
                    max_attempts=max_attempts, seed=seed + i,
                    grasp_radius=grasp_radius,
                    exploration_epsilon=exploration_epsilon,
                    scripted_fraction=scripted_fraction,
                    flight_recorder=flight_recorder, watchdog=watchdog)
        for i in range(num_actors)
    ]

  def start(self) -> None:
    for actor in self.actors:
      actor.start()

  def request_stop(self) -> None:
    for actor in self.actors:
      actor.request_stop()

  def stop(self, timeout: float = 30.0) -> None:
    """Signal every actor before joining any (one dead actor must not
    leave siblings running); surfaces the first recorded error."""
    self.request_stop()
    errors: List[BaseException] = []
    for actor in self.actors:
      actor._thread.join(timeout)
      errors.extend(actor.errors)
    if errors:
      raise RuntimeError(
          f"{len(errors)} actor error(s); first shown") from errors[0]

  # --- fleet accounting (the bench's instruments) -------------------------

  @property
  def env_steps(self) -> int:
    return sum(actor.env_steps for actor in self.actors)

  @property
  def episodes(self) -> int:
    return sum(actor.episodes for actor in self.actors)

  @property
  def successes(self) -> int:
    return sum(actor.successes for actor in self.actors)

  def busy_seconds(self) -> float:
    """Total wall seconds the actor threads spent inside acting steps
    (policy call + env step + enqueue). Read against a concurrent
    learner window, busy/wall is the acting/learning overlap fraction:
    ~1.0 means collection never paused while the learner trained."""
    return sum(actor.busy_seconds for actor in self.actors)

"""Actor-throughput bench: threaded scalar collectors vs vectorized fleet.

The ISSUE 5 acceptance instrument: at the SAME policy (one shared
hot-reload predictor, identical CEM hyperparameters) and the SAME total
env count, time the PR 2 actor side (num_collectors Python threads, each
stepping envs_per_collector scalar `GraspRetryEnv`s through its own
small CEM bucket call) against the vectorized fleet (ONE `VectorActor`
stepping every env in lockstep through one actor-batch bucket
executable). Learners are out of the picture for the throughput ratio —
both paths only collect — so the numbers isolate acting, mirroring how
replay/learner_bench isolates the learner; a third phase then runs the
fused megastep learner WHILE the vectorized fleet collects and reports
the acting/learning overlap fraction (busy-time under a concurrent
learner window), the Podracer co-scheduling claim as a measurement.

Emitted block (every citable field carries the repo's
{median,min,max,trials} spread shape):

  scalar_threads / vector_actor:
    env_steps_per_sec      fleet env transitions ATTEMPTED per second
    transitions_per_sec    transitions actually ENQUEUED per second
                           (scalar lags attempts by in-flight episodes;
                           the vector path enqueues every step)
  speedup                  per-trial vector/scalar env-steps ratio
                           (the >= 3x acceptance bar).
  overlap:
    acting_learning_overlap_fraction   actor busy seconds / wall
                           seconds of a concurrent megastep-learner
                           window (~1.0: collection never paused while
                           the learner trained).
    learner_steps_per_sec_while_acting the optimizer rate sustained
                           under that concurrent collection.
  compile_counts           both policies' per-bucket ledgers (exactly
                           one acting executable per bucket; the hot
                           param refresh path shares the executables).
"""

from __future__ import annotations

import time
from typing import Dict

import numpy as np

from tensor2robot_tpu.replay.learner_bench import (_spread,
                                                   _synthetic_transitions)


def measure_actor_throughput(
    num_envs: int = 32,
    scalar_collectors: int = 8,
    image_size: int = 16,
    action_size: int = 4,
    max_attempts: int = 3,
    grasp_radius: float = 0.4,
    exploration_epsilon: float = 0.25,
    scripted_fraction: float = 0.25,
    cem_num_samples: int = 16,
    cem_num_elites: int = 4,
    cem_iterations: int = 2,
    window_s: float = 1.0,
    trials: int = 3,
    batch_size: int = 32,
    learner_capacity: int = 256,
    learner_inner_steps: int = 5,
    gamma: float = 0.8,
    learning_rate: float = 3e-3,
    seed: int = 0,
) -> Dict:
  """Times both actor paths, then the overlap phase; returns the block.

  All compiles (both CEM buckets, the megastep) happen before any
  timing. Like learner_bench, timings run on a single-device mesh and
  are only citable from a quiet process (the CLI subprocess protocol);
  the spread over repeated windows is what makes the ratio citable on
  a contended host.
  """
  import jax
  import optax

  from tensor2robot_tpu.export import export_utils
  from tensor2robot_tpu.parallel import mesh as mesh_lib
  from tensor2robot_tpu.replay.actor import ActorFleet
  from tensor2robot_tpu.replay.device_buffer import (DeviceReplayBuffer,
                                                     MegastepLearner)
  from tensor2robot_tpu.replay.ingest import TransitionQueue
  from tensor2robot_tpu.replay.loop import (CollectorWorker,
                                            _HotReloadPredictor,
                                            transition_spec)
  from tensor2robot_tpu.replay.smoke import TinyQCriticModel
  from tensor2robot_tpu.serving.bucketing import BucketLadder
  from tensor2robot_tpu.serving.policy import CEMFleetPolicy
  from tensor2robot_tpu.train.trainer import Trainer

  if num_envs % scalar_collectors:
    raise ValueError(
        f"num_envs {num_envs} must split evenly over "
        f"scalar_collectors {scalar_collectors}")
  envs_per_collector = num_envs // scalar_collectors
  mesh = mesh_lib.create_mesh(devices=jax.devices()[:1])
  model = TinyQCriticModel(
      image_size=image_size, action_size=action_size,
      optimizer_fn=lambda: optax.adam(learning_rate))
  trainer = Trainer(model, mesh=mesh, seed=seed)
  state = trainer.create_train_state(batch_size=batch_size)
  host_variables = export_utils.fetch_variables_to_host(
      state.variables(use_ema=True))
  predictor = _HotReloadPredictor(model, host_variables)
  cem_kwargs = dict(action_size=action_size,
                    num_samples=cem_num_samples,
                    num_elites=cem_num_elites,
                    iterations=cem_iterations, seed=seed + 7)
  # One bucket per path: the scalar threads flush envs_per_collector
  # requests a call, the vector fleet num_envs — each path compiles
  # exactly its one acting executable (the per-bucket ledger below).
  scalar_policy = CEMFleetPolicy(
      predictor, ladder=BucketLadder((envs_per_collector,)), **cem_kwargs)
  vector_policy = CEMFleetPolicy(
      predictor, ladder=BucketLadder((num_envs,)), **cem_kwargs)
  warm_image = np.zeros((image_size, image_size, 3), np.uint8)

  def timed_windows(steps_of, enqueued_of):
    """(env_steps/s, transitions/s) per trial window over live threads."""
    sps, tps = [], []
    for _ in range(trials):
      steps0, enq0 = steps_of(), enqueued_of()
      start = time.perf_counter()
      time.sleep(window_s)
      elapsed = time.perf_counter() - start
      sps.append((steps_of() - steps0) / elapsed)
      tps.append((enqueued_of() - enq0) / elapsed)
    return sps, tps

  # --- scalar path: the PR 2 threaded collectors ------------------------
  scalar_queue = TransitionQueue(max(4096, 4 * num_envs))
  collectors = [
      CollectorWorker(scalar_policy, scalar_queue, image_size,
                      num_envs=envs_per_collector,
                      max_attempts=max_attempts, seed=seed + i,
                      grasp_radius=grasp_radius,
                      exploration_epsilon=exploration_epsilon,
                      scripted_fraction=scripted_fraction)
      for i in range(scalar_collectors)
  ]
  scalar_policy([warm_image] * envs_per_collector)  # compile, untimed
  for collector in collectors:
    collector.start()
  scalar_sps, scalar_tps = timed_windows(
      lambda: sum(c.env_steps for c in collectors),
      lambda: scalar_queue.enqueued)
  for collector in collectors:
    collector.request_stop()
  for collector in collectors:
    collector.stop()

  # --- vector path: one fused bucket over the whole fleet ---------------
  vector_queue = TransitionQueue(max(4096, 4 * num_envs))
  fleet = ActorFleet(vector_policy, vector_queue, image_size,
                     total_envs=num_envs, max_attempts=max_attempts,
                     seed=seed, grasp_radius=grasp_radius,
                     exploration_epsilon=exploration_epsilon,
                     scripted_fraction=scripted_fraction)
  vector_policy([warm_image] * num_envs)  # compile, untimed
  fleet.start()
  vector_sps, vector_tps = timed_windows(
      lambda: fleet.env_steps, lambda: vector_queue.enqueued)
  fleet.stop()

  # --- overlap phase: megastep learner under concurrent collection ------
  spec = transition_spec(image_size, action_size)
  buffer = DeviceReplayBuffer(
      spec, learner_capacity, batch_size, seed=seed, prioritized=True,
      ingest_chunk=min(64, learner_capacity), mesh=mesh)
  buffer.extend(_synthetic_transitions(learner_capacity, image_size,
                                       action_size, seed + 17))
  learner = MegastepLearner(
      model, trainer, buffer, action_size=action_size, gamma=gamma,
      num_samples=cem_num_samples, num_elites=cem_num_elites,
      iterations=cem_iterations, inner_steps=learner_inner_steps,
      seed=seed + 13)
  learner.refresh(host_variables, step=0)
  state, _ = learner.step(state)  # compile + warm, untimed
  overlap_queue = TransitionQueue(max(4096, 4 * num_envs))
  overlap_fleet = ActorFleet(vector_policy, overlap_queue, image_size,
                             total_envs=num_envs,
                             max_attempts=max_attempts, seed=seed + 99,
                             grasp_radius=grasp_radius,
                             exploration_epsilon=exploration_epsilon,
                             scripted_fraction=scripted_fraction)
  overlap_fleet.start()
  overlap_fracs, learner_sps = [], []
  for _ in range(trials):
    busy0 = overlap_fleet.busy_seconds()
    steps = 0
    start = time.perf_counter()
    while time.perf_counter() - start < window_s:
      state, _ = learner.step(state)
      steps += learner_inner_steps
    elapsed = time.perf_counter() - start
    overlap_fracs.append(
        min(1.0, (overlap_fleet.busy_seconds() - busy0) / elapsed))
    learner_sps.append(steps / elapsed)
  overlap_fleet.stop()

  return {
      "num_envs": num_envs,
      "scalar_collectors": scalar_collectors,
      "envs_per_collector": envs_per_collector,
      "window_s": window_s,
      "trials": trials,
      "scalar_threads": {
          "env_steps_per_sec": _spread(scalar_sps, 1),
          "transitions_per_sec": _spread(scalar_tps, 1),
      },
      "vector_actor": {
          "env_steps_per_sec": _spread(vector_sps, 1),
          "transitions_per_sec": _spread(vector_tps, 1),
      },
      "speedup": _spread(
          [v / max(s, 1e-9) for v, s in zip(vector_sps, scalar_sps)], 2),
      "overlap": {
          "acting_learning_overlap_fraction": _spread(overlap_fracs, 3),
          "learner_steps_per_sec_while_acting": _spread(learner_sps, 2),
      },
      "compile_counts": {
          **{f"scalar_cem_bucket_{k}": v
             for k, v in sorted(scalar_policy.compile_counts.items())},
          **{f"vector_cem_bucket_{k}": v
             for k, v in sorted(vector_policy.compile_counts.items())},
          **learner.compile_counts,
      },
      "note": (
          "same shared hot-reload predictor, same CEM hyperparameters, "
          "same total env count: scalar path = "
          f"{scalar_collectors} Python threads x {envs_per_collector} "
          "GraspRetryEnvs each (one small CEM bucket call per thread "
          "step); vector path = one VectorActor stepping all "
          f"{num_envs} envs through one fused bucket executable and "
          "one put_batch chunk per step. The overlap phase runs the "
          "fused megastep learner while a fresh fleet collects: "
          "overlap fraction = actor busy seconds / learner wall "
          "seconds. Single-device mesh; citable numbers come from the "
          "CLI subprocess protocol (quiet process), spreads over "
          "repeated windows."),
  }

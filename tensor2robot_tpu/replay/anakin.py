"""AnakinLoop: act→env-step→extend→learn fused into ONE executable.

ISSUE 6 tentpole, second half. PR 3 fused the learner (MegastepLearner)
and PR 4 batched the actors (VectorActor), but the two halves still
meet on the HOST: the actor dispatches a CEM executable per control
step, steps numpy, enqueues, and the feeder re-stages the same bytes
back to the device — at ~3.7k env steps/s the loop is bounded by that
host choreography, not by any compiled program. This module is the
full Anakin architecture from Podracer (PAPERS.md, arXiv:2104.06272):
environment, action selection, replay extend, AND the optimizer step
all live inside one donated AOT executable that lax.scans K control
steps per dispatch. In the steady state the host's only work is
reading back a handful of scalar metrics and promoting checkpoints —
and because the whole loop is one jitted program, it later shards over
the dp×tp mesh like any other step (arXiv:2204.06514), which is what
unblocks ROADMAP open item 1.

Per scanned control step:

  obs       = env_state.images                (uint8, pre-step snapshot)
  act       : CEM through the SAME fleet_cem_optimize /
              make_tiled_q_score_fn contract serving uses, on the LIVE
              online params (strictly fresher than the actors' hot
              reload); models exposing `factored_cem_fns` encode each
              scene once and search over the code (identical Q, the
              image tower hoisted out of the sample loop), plus the
              collectors' epsilon-uniform + scripted-near-object
              exploration mix — same fractions and per-step draw
              order, drawn from JAX RNG instead of the numpy stream.
  env step  : jax_grasping.JaxGraspEnv.step_fn (pure; lax.select
              auto-reset; property-tested bit-identical to the numpy
              oracle).
  extend    : DeviceReplayBuffer.extend_fn at ONE fixed chunk — the
              fleet width — so the ring ingests in place with no
              recompile and no host staging (next_image == image: the
              scene is static within an episode, the numpy collectors'
              transition recipe).
  learn     : every `train_every`-th step, gated on min-fill via
              lax.cond, the EXACT megastep inner body
              (device_buffer.make_learn_iteration_fn): sample →
              CEM-Bellman label vs the target net → Trainer
              grad/apply → TD → in-place reprioritize.

The target network stays an executable ARGUMENT (refresh never
recompiles) and ``compile_counts['anakin_step']`` extends the replay
ledger: exactly one fused executable for the life of the loop. The
min-fill gate lives INSIDE the program (buffer size test), so there is
no host-side warm-up phase either — dispatch 0 already runs the final
steady-state code path.

Pod scale (ISSUE 7): the SAME single executable is mesh-native. On a
dp×tp mesh (the trainer's), the env fleet shards over the data axis
(`parallel.mesh.env_sharding` via `JaxGraspEnv.state_shardings`: each
device steps num_envs / dp envs in its own HBM — Podracer's per-core
environment slices), the replay ring capacity-shards per device
(`DeviceReplayBuffer`'s `ring_sharding`, which REFUSES indivisible
capacities), the sampled learn batch is pinned back onto the data axis
so the label→grad→apply chain runs data-parallel with XLA inserting
the gradient all-reduce against replicated params, and — when the
Trainer is built with `shard_optimizer_state=True` — the ZeRO-1
cross-replica weight-update sharding (arXiv:2004.13336) applies INSIDE
the scanned train body, exactly as in the supervised path. Still ONE
`anakin_step` in the ledger; the host work is unchanged (zero in the
steady state). Per-shard PRNG streams need no extra machinery: acting,
exploration, and label keys are already derived per-env/per-sample via
`fold_in` over a global index, so each device materializes only its
slice of the key array — the GLOBAL stream is identical on every mesh
shape, which is what makes the 1-device run the semantics oracle for
the sharded one (tests/test_anakin.py pins this).

Determinism: acting, exploration, env-reset, sampling, and label
randomness are all pure functions of (seed, outer, inner[, position])
via fold_in — one dispatch stream is replayable and independent of
wall-clock or host state.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from tensor2robot_tpu.obs import ledger as obs_ledger
from tensor2robot_tpu.obs import trace as trace_lib
from tensor2robot_tpu.parallel import distributed as dist_lib
from tensor2robot_tpu.parallel import mesh as mesh_lib
from tensor2robot_tpu.replay.bellman import (TargetNetwork,
                                             make_bellman_targets_fn,
                                             make_cem_states_and_score)
from tensor2robot_tpu.replay.device_buffer import (DeviceReplayBuffer,
                                                   make_learn_iteration_fn)
from tensor2robot_tpu.research.qtopt import cem
from tensor2robot_tpu.research.qtopt.jax_grasping import JaxGraspEnv


class AnakinLoop(TargetNetwork):
  """The fused act→step→extend→learn loop around a JaxGraspEnv.

  Args:
    model/trainer/buffer: the MegastepLearner trio; the buffer's
      `ingest_chunk` MUST equal the env fleet width (one extend shape).
    env: a JaxGraspEnv (bank or procedural scene source).
    inner_steps: env control steps per dispatch (the scan length K).
    train_every: optimizer steps fire every `train_every`-th control
      step (must divide inner_steps). The numpy loop trained on its own
      thread at whatever cadence the box allowed; fused, the replay
      ratio is an explicit, reproducible knob.
    min_fill: optimizer steps are lax.cond-gated until the ring holds
      this many transitions — the ReplayFeeder.ready() gate, moved
      inside the program.
    exploration_epsilon / scripted_fraction: the collectors' mix.
  """

  def __init__(
      self,
      model,
      trainer,
      buffer: DeviceReplayBuffer,
      env: JaxGraspEnv,
      action_size: int = 4,
      gamma: float = 0.9,
      num_samples: int = 32,
      num_elites: int = 4,
      iterations: int = 2,
      inner_steps: int = 40,
      train_every: int = 8,
      min_fill: int = 0,
      exploration_epsilon: float = 0.2,
      scripted_fraction: float = 0.25,
      seed: int = 0,
      polyak_tau: Optional[float] = None,
      ledger: Optional[obs_ledger.ExecutableLedger] = None,
      precision: str = "f32",
      health: bool = False,
  ):
    """`precision` (ISSUE 13, cem.SCORING_PRECISIONS) is the CEM
    Q-scoring tier INSIDE the fused executable: acting's score calls
    and the label stage's target-net max run at the tier; the env step,
    replay extend, gradients, optimizer state, and the TD-priority
    arithmetic (the learn body's fresh-params forward) stay f32 — the
    low-precision-matmuls / f32-updates convention. "f32" (default)
    lowers the program bit-identically to r10.

    `health` (ISSUE 15): the scanned learn body additionally computes
    the fixed health-summary pytree (obs/health.SUMMARY_KEYS) —
    non-finite counts over grads/params/targets, grad/param norms,
    TD/Q mean/max, priority entropy, sample age — accumulated in the
    scan carry (running max for the spike-sensitive keys) and returned
    with the metrics. Still ONE `anakin_step` in the ledger: the cost
    is a few scalar reductions riding the existing metrics D2H, so
    host-blocked stays at its r09 level."""
    if inner_steps < 1 or train_every < 1 or inner_steps % train_every:
      raise ValueError(
          f"inner_steps {inner_steps} must be a positive multiple of "
          f"train_every {train_every}")
    if buffer.ingest_chunk != env.num_envs:
      raise ValueError(
          f"buffer ingest_chunk {buffer.ingest_chunk} must equal the "
          f"env fleet width {env.num_envs}: the fused extend runs at "
          "ONE fixed chunk shape — the fleet's")
    # Mesh-native placement (ISSUE 7): the trainer's mesh is THE mesh —
    # env fleet and learn batch shard over its data axis, so both must
    # divide it (an indivisible fleet/batch would silently replicate,
    # the exact trap the ring sharding refuses).
    self.mesh = trainer.mesh
    self._data_axis = trainer.data_axis
    axis_size = self.mesh.shape[self._data_axis]
    if env.num_envs % axis_size:
      raise ValueError(
          f"env fleet width {env.num_envs} is not divisible by the "
          f"{self._data_axis!r} mesh axis size ({axis_size} devices), so "
          f"the per-shard env fleets cannot form. Use a fleet of "
          f"{mesh_lib.nearest_multiples(env.num_envs, axis_size)} envs, or a "
          f"data axis that divides {env.num_envs}.")
    if buffer.sample_batch_size % axis_size:
      raise ValueError(
          f"sample batch {buffer.sample_batch_size} is not divisible by "
          f"the {self._data_axis!r} mesh axis size ({axis_size} devices), "
          f"so the fused learn body cannot run data-parallel. Use a batch "
          f"of "
          f"{mesh_lib.nearest_multiples(buffer.sample_batch_size, axis_size)}.")
    # Mesh placement is gated on the WHOLE mesh, not the data axis: a
    # dp=1/tp>1 mesh (the rule-partitioned flagship) still needs env
    # state and targets placed on the mesh — params shard over the
    # model axis, and un-placed host trees next to sharded params would
    # mix devices inside the fused jit. The 1-device mesh keeps the
    # r09 plain-copy path — the unchanged semantics oracle.
    self._sharded = self.mesh.size > 1
    # Target variables live replicated ON THE MESH when sharded (the
    # AOT executable is lowered against this placement; a host-numpy
    # refresh landing on device 0 only would make every shard read CEM
    # labels across the mesh).
    super().__init__(
        polyak_tau=polyak_tau,
        sharding=(mesh_lib.replicated_sharding(self.mesh)
                  if self._sharded else None))
    self._model = model
    self._trainer = trainer
    self._buffer = buffer
    self._env = env
    self._action_size = action_size
    self._gamma = gamma
    self._num_samples = num_samples
    self._num_elites = num_elites
    self._iterations = iterations
    self.inner_steps = inner_steps
    self.train_every = train_every
    self.min_fill = min_fill
    self._epsilon = exploration_epsilon
    self._scripted = scripted_fraction
    self._seed = seed
    self._clip_targets = getattr(model, "loss_type",
                                 "cross_entropy") == "cross_entropy"
    # CEM scoring precision (ISSUE 13, the ROADMAP item 3 bf16 tier):
    # `precision` is the policy knob, `dtype` the jnp name surfaced in
    # detail["anakin"]["dtype"] / the smoke artifact.
    self.precision = cem.validate_precision(precision)
    self.dtype = jnp.dtype(cem.scoring_dtype(precision)).name
    self.health = bool(health)
    self.compile_counts: Dict[str, int] = {}
    self._ledger = ledger
    self._exec = None
    self._outer = 0
    # Per-shard env fleets: the fleet-width leaves split over the data
    # axis at PLACEMENT time, so the executable is lowered (and its
    # donation paired) against the sharded layout from dispatch 0.
    self._env_shardings = env.state_shardings(self.mesh, self._data_axis)
    env_state = env.init_state(jax.random.key(seed + 21))
    if self._sharded:
      # global_put IS device_put single-process; multi-process (ISSUE
      # 19) it assembles each leaf as a global array from the identical
      # seeded init every process computes.
      env_state = dist_lib.global_put(env_state, self._env_shardings)
    self._env_state = env_state
    # Device counters snapshot (dispatch granularity, no mid-scan D2H).
    self.env_steps = 0
    self.trained_steps = 0
    # Cumulative wall time inside the fused executable (dispatch through
    # the metrics D2H) — the bench's host_blocked_fraction denominator;
    # host bookkeeping in step() deliberately falls OUTSIDE this clock.
    self.exec_seconds = 0.0

  # --- fleet bookkeeping (ActorFleet-shaped instruments) -------------------

  @property
  def mesh_shape(self) -> Dict[str, int]:
    """{axis: size} of the mesh the fused executable spans (the smoke
    artifact's record of HOW the loop was sharded)."""
    return dict(self.mesh.shape)

  @property
  def episodes(self) -> int:
    return int(jax.device_get(self._env_state.episodes))

  @property
  def successes(self) -> int:
    return int(jax.device_get(self._env_state.successes))

  # --- fused crash-resume (ISSUE 19: the donated state's only seam) --------

  def checkpoint_state(self):
    """The carried device state as one pytree for the checkpoint
    manager — env fleet, replay ring, target net, exactly the arrays
    the donated executable threads between dispatches. Taken BETWEEN
    dispatches (the only moment the donated buffers are live on the
    host side of the seam). TrainState stays with the caller (the loop
    owns it), completing the composite."""
    return {
        "env": self._env_state,
        "buffer": self._buffer.state,
        "target": self._target_variables,
    }

  def checkpoint_meta(self):
    """Host counters the device pytree does not carry (episodes and
    successes DO live in the env state and restore with it)."""
    return {
        "outer": self._outer,
        "env_steps": self.env_steps,
        "trained_steps": self.trained_steps,
        "refresh_count": self._refresh_count,
        "last_refresh_step": self.last_refresh_step,
    }

  def restore_checkpoint_state(self, composite, meta) -> None:
    """Installs a restored composite (arrays already placed on THIS
    loop's shardings by the checkpoint manager's template restore) and
    replays the host counters, so the next dispatch continues the
    (seed, outer, inner) RNG streams exactly where the crash cut them."""
    self._env_state = composite["env"]
    self._buffer.set_state(composite["buffer"])
    self._target_variables = composite["target"]
    self._outer = int(meta["outer"])
    self.env_steps = int(meta["env_steps"])
    self.trained_steps = int(meta["trained_steps"])
    self._refresh_count = int(meta["refresh_count"])
    self.last_refresh_step = int(meta["last_refresh_step"])

  # --- the fused program ---------------------------------------------------

  def _build_anakin_fn(self):
    model = self._model
    env_step = self._env.step_fn()
    extend = self._buffer.extend_fn()
    sample = self._buffer.sample_fn()
    update_priorities = self._buffer.update_priorities_fn()
    factored = getattr(model, "factored_cem_fns", lambda: None)()
    # The label stage's CEM max runs at the scoring tier; the learn
    # body's grads/optimizer/TD-priority forward stay f32 (the targets
    # come back f32 from q_value_from_logits — see
    # make_bellman_targets_fn's precision contract).
    targets_fn = make_bellman_targets_fn(
        model, self._action_size, self._gamma, self._num_samples,
        self._num_elites, self._iterations, self._clip_targets,
        factored=factored is not None, precision=self.precision)
    # Data-parallel pins for the multi-device mesh. All three are None/
    # identity on the 1-device mesh, so the single-device program — the
    # semantics oracle and measured fallback — lowers exactly as in r09.
    if self._sharded:
      batch_rule = mesh_lib.batch_sharding(self.mesh, self._data_axis)
      fleet_rule = mesh_lib.env_sharding(self.mesh, self._data_axis)
      env_shardings = self._env_shardings
      buffer_shardings = self._buffer.state_shardings()
      # The sampled gather out of the capacity-sharded ring re-lands
      # batch-split over the data axis, so label→grad→apply runs
      # data-parallel (XLA inserts the gradient all-reduce; with the
      # trainer's shard_optimizer_state the ZeRO-1 update sharding
      # applies inside this same scanned body).
      constrain_batch = (
          lambda batch: jax.lax.with_sharding_constraint(batch, batch_rule))
      constrain_carry = (
          lambda e, b: (jax.lax.with_sharding_constraint(e, env_shardings),
                        jax.lax.with_sharding_constraint(b, buffer_shardings)))
      constrain_actions = (
          lambda a: jax.lax.with_sharding_constraint(a, fleet_rule))
    else:
      constrain_batch = None
      constrain_carry = lambda e, b: (e, b)
      constrain_actions = lambda a: a
    learn = make_learn_iteration_fn(
        model, self._trainer.train_step_fn(with_health=self.health),
        sample, update_priorities,
        targets_fn, getattr(model, "target_key", "target_q"),
        self._clip_targets, constrain_batch=constrain_batch,
        health_entropy_fn=(self._buffer.priority_entropy_fn()
                           if self.health else None))
    n = self._env.num_envs
    batch_size = self._buffer.sample_batch_size
    k = self.inner_steps
    train_every = self.train_every
    min_fill = self.min_fill
    epsilon = self._epsilon
    scripted_fraction = self._scripted
    cem_kwargs = dict(num_samples=self._num_samples,
                      num_elites=self._num_elites,
                      iterations=self._iterations)
    action_size = self._action_size
    precision = self.precision
    act_base = jax.random.key(self._seed + 7)
    explore_base = jax.random.key(self._seed + 555)
    env_base = jax.random.key(self._seed + 31)
    sample_base = jax.random.key(self._seed)
    label_base = jax.random.key(self._seed + 1)

    def act(online_variables, obs, targets, tick):
      """CEM + exploration mix for the whole fleet, on device."""
      keys = jax.vmap(
          lambda j: jax.random.fold_in(
              jax.random.fold_in(act_base, tick), j))(
                  jnp.arange(n, dtype=jnp.uint32))
      states, score = make_cem_states_and_score(model, factored,
                                                online_variables, obs,
                                                precision=precision)
      best, _ = cem.fleet_cem_optimize(score, states, keys, action_size,
                                       precision=precision, **cem_kwargs)
      # The collectors' exploration recipe (actor.py VectorActor
      # step_once): one epsilon draw per env, uniform actions, scripted
      # near-object grasps from the oracle pose — same fractions and
      # per-step draw order, from folded JAX keys instead of the shared
      # numpy stream (formula-level parity; the ENV is the bit-exact
      # contract, exploration is policy, not environment).
      ekey = jax.random.fold_in(explore_base, tick)
      dkey, ukey, nkey = jax.random.split(ekey, 3)
      draw = jax.random.uniform(dkey, (n,))
      uniform = jax.random.uniform(ukey, (n, action_size), jnp.float32,
                                   -1.0, 1.0)
      noise = jax.random.normal(nkey, (n, 2), jnp.float32) * 0.12
      scripted = uniform.at[:, :2].set(
          jnp.clip(targets + noise, -1.0, 1.0))
      actions = jnp.where((draw < epsilon)[:, None], uniform, best)
      # In-shard acting: pin the fleet's actions back onto the env
      # slices (per-env fold_in keys already shard with the arange).
      return constrain_actions(
          jnp.where((draw >= 1.0 - scripted_fraction)[:, None],
                    scripted, actions))

    zero_metrics = {
        "loss": jnp.zeros((), jnp.float32),
        "td_error": jnp.zeros((), jnp.float32),
        "q_next": jnp.zeros((), jnp.float32),
        "staleness": jnp.zeros((), jnp.float32),
    }
    if self.health:
      from tensor2robot_tpu.obs import health as health_lib
      zero_metrics.update(health_lib.zero_summary())

    def anakin_step(train_state, env_state, buffer_state,
                    target_variables, outer_step):

      def body(carry, inner):
        train_state, env_state, buffer_state, last_metrics = carry
        tick = outer_step * jnp.int32(k) + inner
        obs = env_state.images  # PRE-step snapshot: the observation
        actions = act(train_state.variables(use_ema=True), obs,
                      env_state.targets, tick)
        env_state, (rewards, dones, _) = env_step(
            env_state, actions, jax.random.fold_in(env_base, tick))
        # Static scene: next_image == image; truncation already
        # bootstraps through done=0 (the env's contract).
        buffer_state = extend(buffer_state, {
            "image": obs,
            "action": actions.astype(jnp.float32),
            "reward": rewards,
            "done": dones,
            "next_image": obs,
        })
        do_train = jnp.logical_and(
            buffer_state.size >= min_fill,
            (inner + 1) % train_every == 0)

        def run_learn(train_state, buffer_state):
          skey = jax.random.fold_in(sample_base, tick)
          label_keys = jax.vmap(
              lambda j: jax.random.fold_in(
                  jax.random.fold_in(label_base, tick), j))(
                      jnp.arange(batch_size, dtype=jnp.uint32))
          return learn(train_state, buffer_state, target_variables,
                       skey, label_keys)

        def skip_learn(train_state, buffer_state):
          return train_state, buffer_state, zero_metrics

        train_state, buffer_state, metrics = jax.lax.cond(
            do_train, run_learn, skip_learn, train_state, buffer_state)
        # Hold the carried env/ring layouts shard-stable through every
        # scan iteration (and therefore across dispatches: the donated
        # outputs re-enter at the same shardings the AOT lowering saw).
        env_state, buffer_state = constrain_carry(env_state, buffer_state)
        # Keep the LAST TRAINED metrics (skipped steps report zeros);
        # the spike-sensitive health keys instead accumulate a RUNNING
        # MAX in the carry so a transient mid-scan NaN or norm spike
        # survives to the dispatch readout (obs/health.SCAN_MAX_KEYS;
        # without health keys this reduces to the plain last-trained
        # merge).
        from tensor2robot_tpu.obs import health as health_lib
        last_metrics = health_lib.merge_scan_metrics(
            metrics, last_metrics, do_train)
        trained = do_train.astype(jnp.int32)
        return (train_state, env_state, buffer_state,
                last_metrics), trained

      (train_state, env_state, buffer_state, metrics), trained = (
          jax.lax.scan(
              body,
              (train_state, env_state, buffer_state, zero_metrics),
              jnp.arange(k, dtype=jnp.int32)))
      metrics = dict(metrics)
      metrics["trained_steps"] = jnp.sum(trained)
      return train_state, env_state, buffer_state, metrics

    return anakin_step

  def compiled(self, train_state):
    """The fused executable, AOT-compiled once (ledger: exactly 1).

    Donates (train_state, env_state, buffer_state): params, opt state,
    the episode state, the replay storage, and the sum tree all update
    in place in device memory — the donation + fixed-shape discipline
    of arXiv:2204.06514 applied to the WHOLE production loop.
    """
    if self._exec is None:
      fn = self._build_anakin_fn()
      if self._sharded:
        # Donated AOT boundary stability: every dispatch's OUTPUT state
        # must carry the same layout as its input, or the second
        # dispatch rejects its own carried state. Warm-up dispatches
        # route params through the skip branch of the min-fill cond
        # (no in-body constraint lands), so XLA propagation is free to
        # pick a different output layout for TP-catch-all leaves —
        # pin the whole TrainState to the caller's concrete shardings.
        state_shardings = jax.tree_util.tree_map(
            lambda leaf: leaf.sharding, train_state)
        inner_fn = fn

        def fn(ts, env_state, buffer_state, target_variables, outer):
          ts, env_state, buffer_state, metrics = inner_fn(
              ts, env_state, buffer_state, target_variables, outer)
          ts = jax.lax.with_sharding_constraint(ts, state_shardings)
          return ts, env_state, buffer_state, metrics

      args = (train_state, self._env_state, self._buffer.state,
              self._target_variables,
              dist_lib.global_scalar(0, self.mesh, jnp.int32))
      self._exec = jax.jit(
          fn, donate_argnums=(0, 1, 2)).lower(*args).compile()
      self.compile_counts["anakin_step"] = (
          self.compile_counts.get("anakin_step", 0) + 1)
      if self._ledger is not None:
        self._ledger.register(
            "anakin_step", compiled=self._exec,
            device=f"mesh{dict(self.mesh.shape)}",
            dtype=self.precision,
            shapes={"inner_steps": self.inner_steps,
                    "fleet": self._env.num_envs,
                    "batch": self._buffer.sample_batch_size})
    return self._exec

  def step(self, train_state):
    """One dispatch = `inner_steps` control steps (and up to
    inner_steps / train_every optimizer steps, min-fill permitting).
    Returns (train_state', metrics) with metrics as host floats — the
    only D2H of the steady state.
    """
    if self._target_variables is None:
      raise ValueError("call refresh(variables, step=0) before step()")
    exec_ = self.compiled(train_state)
    with trace_lib.span("learn/anakin_step", inner=self.inner_steps,
                        fused="act,step,extend,learn"):
      t0 = time.perf_counter()
      train_state, env_state, buffer_state, metrics = exec_(
          train_state, self._env_state, self._buffer.state,
          self._target_variables,
          dist_lib.global_scalar(self._outer, self.mesh, jnp.int32))
      # device_get blocks until the fused program finishes: the clock
      # stops exactly at the end of device work + the scalar D2H, so the
      # bookkeeping below is measurable host time, not hidden inside the
      # "in executable" bucket.
      metrics = jax.device_get(metrics)
      dispatch_seconds = time.perf_counter() - t0
    self.exec_seconds += dispatch_seconds
    if self._ledger is not None:
      self._ledger.record_dispatch("anakin_step", dispatch_seconds)
    self._env_state = env_state
    self._buffer.set_state(buffer_state)
    self._outer += 1
    self.env_steps += self.inner_steps * self._env.num_envs
    host_metrics = {key: float(value) for key, value in metrics.items()}
    host_metrics["trained_steps"] = int(host_metrics["trained_steps"])
    self.trained_steps += host_metrics["trained_steps"]
    return train_state, host_metrics

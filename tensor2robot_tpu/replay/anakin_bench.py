"""Anakin-throughput bench: numpy vector fleet vs the fused loop.

The ISSUE 6 acceptance instrument: at the SAME env count and the SAME
policy (identical CEM hyperparameters over the same TinyQ critic), time
the r08 actor side — one `VectorActor` stepping every env through one
CEM bucket executable, numpy env + queue on the host — against the
fused `AnakinLoop`, where acting, env stepping, replay extend, AND the
optimizer step all run inside one donated executable.

Both sides run their FULL production shape for the headline ratio: the
vector fleet is co-scheduled with the megastep learner on the same
host (exactly the r08 production loop — acting and learning timeshare
the cores; the r08 overlap instrument showed collection never pauses,
but it still shares the machine), and the anakin loop trains every
`train_every`-th control step inside the fused program. The fleet's
collect-only rate (its unrealistic best case: a machine with nothing
else to do) is ALSO measured and reported, with the conservative
anakin-vs-collect-only ratio beside the headline — both definitions
are in the artifact, neither is hidden.

Emitted block (every citable field carries the repo's
{median,min,max,trials} spread shape):

  vector_fleet:
    env_steps_per_sec            co-scheduled with the megastep
                                 learner (the r08 production shape)
    collect_only_env_steps_per_sec   nothing else on the machine
    learner_steps_per_sec        the megastep rate sustained under
                                 the co-scheduled measurement
  anakin:
    env_steps_per_sec            the fused loop, training as it goes
    train_steps_per_sec          optimizer steps inside that number
    host_blocked_fraction        1 - time-in-executable / wall: the
                                 zero-host-work claim as a measurement
    dtype                        CEM scoring precision (ROADMAP item 5
                                 bf16 tier lands against this field)
  speedup                        per-trial anakin/co-scheduled ratio
                                 (the >= 5x acceptance bar)
  speedup_vs_collect_only        the conservative secondary ratio
  compile_counts                 one acting bucket + one megastep for
                                 the vector side, exactly one
                                 `anakin_step` for the fused loop.
"""

from __future__ import annotations

import time
from typing import Dict

import numpy as np

from tensor2robot_tpu.replay.learner_bench import _spread


def measure_anakin_throughput(
    num_envs: int = 32,
    image_size: int = 16,
    action_size: int = 4,
    max_attempts: int = 3,
    grasp_radius: float = 0.4,
    exploration_epsilon: float = 0.25,
    scripted_fraction: float = 0.25,
    cem_num_samples: int = 16,
    cem_num_elites: int = 4,
    cem_iterations: int = 2,
    inner_steps: int = 128,
    train_every: int = 8,
    bank_scenes: int = 512,
    window_s: float = 1.0,
    trials: int = 3,
    batch_size: int = 32,
    capacity: int = 512,
    gamma: float = 0.8,
    learning_rate: float = 3e-3,
    seed: int = 0,
) -> Dict:
  """Times both loop shapes; returns the `anakin_throughput` block.

  All compiles (the vector CEM bucket, the fused anakin executable)
  happen before any timing. Single-device mesh, citable only from a
  quiet process (the CLI subprocess protocol) — the learner_bench
  rules, unchanged.
  """
  import jax
  import optax

  from tensor2robot_tpu.export import export_utils
  from tensor2robot_tpu.parallel import mesh as mesh_lib
  from tensor2robot_tpu.replay.actor import ActorFleet
  from tensor2robot_tpu.replay.anakin import AnakinLoop
  from tensor2robot_tpu.replay.device_buffer import (DeviceReplayBuffer,
                                                     MegastepLearner)
  from tensor2robot_tpu.replay.ingest import TransitionQueue
  from tensor2robot_tpu.replay.learner_bench import _synthetic_transitions
  from tensor2robot_tpu.replay.loop import (_HotReloadPredictor,
                                            transition_spec)
  from tensor2robot_tpu.replay.smoke import TinyQCriticModel
  from tensor2robot_tpu.research.qtopt.jax_grasping import (JaxGraspEnv,
                                                            make_scene_bank)
  from tensor2robot_tpu.serving.bucketing import BucketLadder
  from tensor2robot_tpu.serving.policy import CEMFleetPolicy
  from tensor2robot_tpu.train.trainer import Trainer

  mesh = mesh_lib.create_mesh(devices=jax.devices()[:1])
  model = TinyQCriticModel(
      image_size=image_size, action_size=action_size,
      optimizer_fn=lambda: optax.adam(learning_rate))
  trainer = Trainer(model, mesh=mesh, seed=seed)
  state = trainer.create_train_state(batch_size=batch_size)
  host_variables = export_utils.fetch_variables_to_host(
      state.variables(use_ema=True))
  spec = transition_spec(image_size, action_size)

  # --- vector path: the r08 numpy fleet ---------------------------------
  predictor = _HotReloadPredictor(model, host_variables)
  vector_policy = CEMFleetPolicy(
      predictor, action_size=action_size, num_samples=cem_num_samples,
      num_elites=cem_num_elites, iterations=cem_iterations,
      seed=seed + 7, ladder=BucketLadder((num_envs,)))
  queue = TransitionQueue(max(4096, 4 * num_envs))
  fleet = ActorFleet(vector_policy, queue, image_size,
                     total_envs=num_envs, max_attempts=max_attempts,
                     seed=seed, grasp_radius=grasp_radius,
                     exploration_epsilon=exploration_epsilon,
                     scripted_fraction=scripted_fraction)
  warm_image = np.zeros((image_size, image_size, 3), np.uint8)
  vector_policy([warm_image] * num_envs)  # compile, untimed
  # The co-scheduled learner: the r08 production loop's other half,
  # driven exactly as actor_bench's overlap phase drives it (megastep
  # over a pre-filled device ring; same model/trainer/CEM settings).
  vbuffer = DeviceReplayBuffer(
      spec, capacity, batch_size, seed=seed, prioritized=True,
      ingest_chunk=min(64, capacity), mesh=mesh)
  vbuffer.extend(_synthetic_transitions(capacity, image_size,
                                        action_size, seed + 17))
  vlearner = MegastepLearner(
      model, trainer, vbuffer, action_size=action_size, gamma=gamma,
      num_samples=cem_num_samples, num_elites=cem_num_elites,
      iterations=cem_iterations, inner_steps=5, seed=seed + 13)
  vlearner.refresh(host_variables, step=0)
  state, _ = vlearner.step(state)  # compile + warm, untimed
  fleet.start()
  # Phase 1 (headline): acting rate while the learner trains on the
  # same host — the r08 production co-schedule.
  vector_sps, vector_learner_sps = [], []
  for _ in range(trials):
    steps0 = fleet.env_steps
    learner_steps = 0
    start = time.perf_counter()
    while time.perf_counter() - start < window_s:
      state, _ = vlearner.step(state)
      learner_steps += vlearner.inner_steps
    elapsed = time.perf_counter() - start
    vector_sps.append((fleet.env_steps - steps0) / elapsed)
    vector_learner_sps.append(learner_steps / elapsed)
  # Phase 2 (secondary): collect-only — the fleet's best case.
  collect_sps = []
  for _ in range(trials):
    steps0 = fleet.env_steps
    start = time.perf_counter()
    time.sleep(window_s)
    collect_sps.append(
        (fleet.env_steps - steps0) / (time.perf_counter() - start))
  fleet.stop()

  # --- anakin path: the full fused loop, training as it goes -----------
  buffer = DeviceReplayBuffer(
      spec, capacity, batch_size, seed=seed, prioritized=True,
      ingest_chunk=num_envs, mesh=mesh)
  bank = make_scene_bank(bank_scenes, image_size=image_size,
                         base_seed=seed)
  env = JaxGraspEnv(num_envs, image_size=image_size,
                    max_attempts=max_attempts, radius=grasp_radius,
                    bank=bank)
  loop = AnakinLoop(
      model, trainer, buffer, env, action_size=action_size, gamma=gamma,
      num_samples=cem_num_samples, num_elites=cem_num_elites,
      iterations=cem_iterations, inner_steps=inner_steps,
      train_every=train_every, min_fill=min(batch_size, capacity),
      exploration_epsilon=exploration_epsilon,
      scripted_fraction=scripted_fraction, seed=seed + 13)
  loop.refresh(host_variables, step=0)
  state, _ = loop.step(state)  # compile + warm + fill past min-fill
  anakin_sps, anakin_tps, anakin_blocked = [], [], []
  for _ in range(trials):
    steps = trained = 0
    # In-executable time comes from the loop's OWN clock (dispatch
    # through the metrics D2H, see AnakinLoop.step): host bookkeeping
    # inside step() counts as blocked here, exactly like learner_bench
    # times only the compiled-executable calls — wrapping the whole
    # step() call would make this fraction ~0 by construction.
    exec0 = loop.exec_seconds
    start = time.perf_counter()
    while time.perf_counter() - start < window_s:
      state, metrics = loop.step(state)
      steps += inner_steps * num_envs
      trained += metrics["trained_steps"]
    elapsed = time.perf_counter() - start
    anakin_sps.append(steps / elapsed)
    anakin_tps.append(trained / elapsed)
    anakin_blocked.append(
        max(0.0, 1.0 - (loop.exec_seconds - exec0) / elapsed))

  return {
      "num_envs": num_envs,
      "train_every": train_every,
      "inner_steps": inner_steps,
      "window_s": window_s,
      "trials": trials,
      "dtype": loop.dtype,
      "vector_fleet": {
          "env_steps_per_sec": _spread(vector_sps, 1),
          "collect_only_env_steps_per_sec": _spread(collect_sps, 1),
          "learner_steps_per_sec": _spread(vector_learner_sps, 2),
      },
      "anakin": {
          "env_steps_per_sec": _spread(anakin_sps, 1),
          "train_steps_per_sec": _spread(anakin_tps, 2),
          "host_blocked_fraction": _spread(anakin_blocked, 3),
          "dtype": loop.dtype,
      },
      "speedup": _spread(
          [a / max(v, 1e-9) for a, v in zip(anakin_sps, vector_sps)], 2),
      "speedup_vs_collect_only": _spread(
          [a / max(v, 1e-9) for a, v in zip(anakin_sps, collect_sps)],
          2),
      "compile_counts": {
          **{f"vector_cem_bucket_{k}": v
             for k, v in sorted(vector_policy.compile_counts.items())},
          **vlearner.compile_counts,
          **loop.compile_counts,
      },
      "note": (
          "same env count, same CEM hyperparameters, same TinyQ "
          "critic. Headline `speedup` compares full production loops: "
          f"vector path = one VectorActor stepping all {num_envs} "
          "numpy envs through one CEM bucket executable WHILE the "
          "megastep learner trains on the same host (the r08 "
          "co-schedule); anakin path = the fused "
          "act->step->extend->learn executable scanning "
          f"{inner_steps} control steps per dispatch, training every "
          f"{train_every}th step inside the measured number. "
          "collect_only_env_steps_per_sec gives the fleet the whole "
          "machine (its best case, unreachable in production); "
          "speedup_vs_collect_only is the conservative ratio against "
          "it. host_blocked_fraction counts wall time OUTSIDE the "
          "fused executable. Single-device mesh; citable numbers come "
          "from the CLI subprocess protocol (quiet process), spreads "
          "over repeated windows."),
  }

"""Pod-scale Anakin scaling bench: ONE fused executable, 1→N devices.

The ISSUE 7 acceptance instrument (the MULTICHIP_r06 artifact): hold
the GLOBAL workload fixed — same env fleet width, same sample batch,
same CEM policy and critic — and run the fused act→step→extend→learn
executable over data-parallel meshes of 1, 2, 4, and 8 devices,
measuring transitions/s and env steps/s at each scale. Per Podracer
(PAPERS.md, arXiv:2104.06272) the fused loop is exactly the program
that scales across a pod: each device steps num_envs / d envs, holds
capacity / d replay slots, and trains on batch / d transitions with
the gradient all-reduced — so on real chips the per-dispatch work
drops ~linearly with d and transitions/s rises near-linearly at fixed
global batch.

HONESTY CAVEAT (the artifact carries it as `virtual_mesh`): on a
chipless host the "devices" are XLA's virtual CPU devices — slices of
the same cores. Virtual-mesh scaling measures partitioning OVERHEAD,
not pod speedup: efficiency well below 1 is expected and is NOT a
regression (the 2-core CI box typically sits far below it). What this
bench proves chiplessly is structural: the SAME one-executable ledger
(`anakin_step` == 1 at every scale), host-blocked ~0, per-shard env
fleets, capacity-sharded ring, and a learn body whose metrics match
the 1-device oracle (the parity suite's claim) — the scaling NUMBERS
become meaningful when the TPU pool returns and the driver re-runs
this on real chips.

Emitted block (every citable field carries the repo's
{median,min,max,trials} spread shape):

  scales[i]:
    devices                      mesh size d (data axis; tp = 1)
    env_steps_per_sec            global fused-loop rate at this d
    transitions_per_sec          == env steps/s (one transition per
                                 env step enters the sharded ring)
    per_device_transitions_per_sec   transitions/s / d — the per-chip
                                 ingest rate the ring actually holds
    train_steps_per_sec          optimizer steps inside the number
    host_blocked_fraction        1 - in-executable / wall (per scale)
    speedup_vs_1dev              median ratio vs the d=1 run
    scaling_efficiency_vs_1dev   speedup / d (1.0 = linear)
    zero1                        ZeRO-1 weight-update sharding active
    compile_counts               exactly one anakin_step per scale
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence

from tensor2robot_tpu.replay.learner_bench import _spread


def default_device_counts(available: int) -> list:
  """Powers of two up to min(available, 8) — the 1/2/4/8 ladder where
  the hardware (or virtual mesh) permits, honest about fewer."""
  counts = []
  d = 1
  while d <= min(available, 8):
    counts.append(d)
    d *= 2
  return counts


def measure_anakin_multichip(
    device_counts: Optional[Sequence[int]] = None,
    num_envs: int = 32,
    image_size: int = 16,
    action_size: int = 4,
    max_attempts: int = 3,
    grasp_radius: float = 0.4,
    exploration_epsilon: float = 0.25,
    scripted_fraction: float = 0.25,
    cem_num_samples: int = 16,
    cem_num_elites: int = 4,
    cem_iterations: int = 2,
    inner_steps: int = 64,
    train_every: int = 8,
    bank_scenes: int = 256,
    window_s: float = 0.8,
    trials: int = 3,
    batch_size: int = 32,
    capacity: int = 512,
    gamma: float = 0.8,
    learning_rate: float = 3e-3,
    seed: int = 0,
) -> Dict:
  """Times the fused loop at each mesh size; returns the
  `anakin_multichip` block.

  All compiles happen before any timing (one fused executable per
  scale — the ledger proves it stays one). The workload is globally
  fixed: every entry of `device_counts` must divide `num_envs`,
  `batch_size`, and `capacity` (the loop refuses otherwise, naming the
  fix). Citable numbers come from a quiet process (the CLI subprocess
  protocol), same rule as every replay bench.
  """
  import jax
  import optax

  from tensor2robot_tpu.export import export_utils
  from tensor2robot_tpu.parallel import mesh as mesh_lib
  from tensor2robot_tpu.replay.anakin import AnakinLoop
  from tensor2robot_tpu.replay.device_buffer import DeviceReplayBuffer
  from tensor2robot_tpu.replay.loop import transition_spec
  from tensor2robot_tpu.replay.smoke import TinyQCriticModel
  from tensor2robot_tpu.research.qtopt.jax_grasping import (JaxGraspEnv,
                                                            make_scene_bank)
  from tensor2robot_tpu.train.trainer import Trainer

  devices = jax.devices()
  if device_counts is None:
    device_counts = default_device_counts(len(devices))
  # The vs-1dev fields need their actual baseline: always measure the
  # 1-device run (prepended if the caller's ladder skipped it), and
  # ascend so `base_median` is bound before any larger scale reads it.
  device_counts = sorted(set(int(d) for d in device_counts))
  if device_counts and device_counts[0] < 1:
    raise ValueError(
        f"device_counts must be positive mesh sizes, got {device_counts}")
  if not device_counts or device_counts[0] != 1:
    device_counts.insert(0, 1)
  if max(device_counts) > len(devices):
    raise ValueError(
        f"device_counts {device_counts} exceed the {len(devices)} "
        "visible device(s); on a chipless host run the CLI --smoke "
        "lane (it bootstraps an 8-virtual-device CPU mesh).")
  device_kind = devices[0].device_kind
  spec = transition_spec(image_size, action_size)
  # ONE bank render for every scale: scene content identical across
  # mesh sizes (the equalized global stream of the parity suite).
  bank = make_scene_bank(bank_scenes, image_size=image_size,
                         base_seed=seed)

  scales = []
  base_median = None
  for d in device_counts:
    mesh = mesh_lib.create_mesh({"data": d, "model": 1},
                                devices=devices[:d])
    zero1 = d > 1
    model = TinyQCriticModel(
        image_size=image_size, action_size=action_size,
        optimizer_fn=lambda: optax.adam(learning_rate))
    trainer = Trainer(model, mesh=mesh, seed=seed,
                      shard_optimizer_state=zero1)
    state = trainer.create_train_state(batch_size=batch_size)
    host_variables = export_utils.fetch_variables_to_host(
        state.variables(use_ema=True))
    buffer = DeviceReplayBuffer(
        spec, capacity, batch_size, seed=seed, prioritized=True,
        ingest_chunk=num_envs, mesh=mesh)
    env = JaxGraspEnv(num_envs, image_size=image_size,
                      max_attempts=max_attempts, radius=grasp_radius,
                      bank=bank)
    loop = AnakinLoop(
        model, trainer, buffer, env, action_size=action_size,
        gamma=gamma, num_samples=cem_num_samples,
        num_elites=cem_num_elites, iterations=cem_iterations,
        inner_steps=inner_steps, train_every=train_every,
        min_fill=min(batch_size, capacity),
        exploration_epsilon=exploration_epsilon,
        scripted_fraction=scripted_fraction, seed=seed + 13)
    loop.refresh(host_variables, step=0)
    state, _ = loop.step(state)  # compile + warm + fill past min-fill

    sps, tps, blocked = [], [], []
    for _ in range(trials):
      steps = trained = 0
      exec0 = loop.exec_seconds
      start = time.perf_counter()
      while time.perf_counter() - start < window_s:
        state, metrics = loop.step(state)
        steps += inner_steps * num_envs
        trained += metrics["trained_steps"]
      elapsed = time.perf_counter() - start
      sps.append(steps / elapsed)
      tps.append(trained / elapsed)
      blocked.append(
          max(0.0, 1.0 - (loop.exec_seconds - exec0) / elapsed))

    median = _spread(sps, 1)["median"]
    if base_median is None:
      base_median = median
    speedup = median / max(base_median, 1e-9)
    scales.append({
        "devices": d,
        "env_steps_per_sec": _spread(sps, 1),
        "transitions_per_sec": _spread(sps, 1),
        "per_device_transitions_per_sec": _spread(
            [s / d for s in sps], 1),
        "train_steps_per_sec": _spread(tps, 2),
        "host_blocked_fraction": _spread(blocked, 3),
        "speedup_vs_1dev": round(speedup, 3),
        "scaling_efficiency_vs_1dev": round(speedup / d, 3),
        "zero1": zero1,
        "compile_counts": dict(loop.compile_counts),
    })
    # Free this scale's device state before the next mesh allocates.
    del loop, buffer, env, state, trainer, model

  return {
      "num_envs": num_envs,
      "batch_size": batch_size,
      "capacity": capacity,
      "inner_steps": inner_steps,
      "train_every": train_every,
      "window_s": window_s,
      "trials": trials,
      "probed_device_kind": device_kind,
      "virtual_mesh": device_kind.lower() == "cpu",
      "device_counts": device_counts,
      "scales": scales,
      "note": (
          "Fixed GLOBAL workload at every mesh size: same env fleet "
          f"({num_envs} envs), same sample batch ({batch_size}), same "
          f"ring capacity ({capacity}), same CEM policy over the same "
          "TinyQ critic and the same prerendered scene bank. Each "
          "scale compiles ONE fused anakin_step executable over a "
          "{'data': d} mesh with per-shard env fleets, the ring "
          "capacity-sharded per device, data-parallel learn with "
          "gradient all-reduce, and ZeRO-1 weight-update sharding for "
          "d > 1. scaling_efficiency_vs_1dev = (env_steps/s at d) / "
          "(d * env_steps/s at 1): 1.0 is linear. With "
          "virtual_mesh=true the devices are slices of the same host "
          "cores, so efficiency measures XLA partitioning overhead, "
          "not pod speedup — the structural claims (one executable, "
          "host_blocked ~0, sharded state) are the chipless evidence; "
          "re-run on real chips for citable scaling."),
  }


def main(argv=None) -> None:
  """CLI: ONE JSON line (the bench contract); --smoke bootstraps an
  8-virtual-device CPU mesh (re-exec with the canonical env)."""
  import argparse
  import json
  import os
  import sys

  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument("--smoke", action="store_true",
                      help="chipless lane: 8 virtual CPU devices, "
                           "reduced windows")
  parser.add_argument("--devices", default=None,
                      help="comma-separated mesh sizes "
                           "(default: 1,2,4,8 where available)")
  parser.add_argument("--seed", type=int, default=0)
  parser.add_argument("--out", default=None,
                      help="also write the JSON line to this file")
  args = parser.parse_args(argv)
  if args.smoke:
    from tensor2robot_tpu.utils.cpu_mesh_env import (cpu_mesh_env,
                                                     is_cpu_mesh_env)
    if not is_cpu_mesh_env(8):
      if argv is not None:
        raise RuntimeError(
            "--smoke needs the 8-virtual-device CPU mesh configured "
            "before JAX initializes; call main() with argv=None (the "
            "CLI re-execs itself).")
      os.execve(sys.executable,
                [sys.executable, "-m",
                 "tensor2robot_tpu.replay.anakin_multichip_bench",
                 *sys.argv[1:]],
                cpu_mesh_env(8))
  device_counts = ([int(x) for x in args.devices.split(",")]
                   if args.devices else None)
  kwargs = dict(device_counts=device_counts, seed=args.seed)
  if args.smoke:
    # CI scale: smaller windows/fleet, same structure (the committed
    # artifact uses the defaults via a quiet full run).
    kwargs.update(num_envs=16, inner_steps=32, window_s=0.5, trials=2,
                  bank_scenes=128)
  results = measure_anakin_multichip(**kwargs)
  line = json.dumps(results)
  if args.out:
    with open(args.out, "w") as f:
      f.write(line + "\n")
  print(line)


if __name__ == "__main__":
  main()
